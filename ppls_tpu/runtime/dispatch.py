"""Heterogeneous-shape dispatcher (round 21): a pool of StreamEngines
behind one serving surface, so shape-heterogeneous traffic is a
zero-recompile workload.

The stream engine compiles ONCE per (eps, rule, theta_block, ...)
static configuration — that is the whole point of the compile-once
guard — which means a mixed-shape request stream historically had two
bad options: retrace the single engine per shape (the exact failure
``ppls_recompiles_total`` exists to police) or hand-partition traffic
into one serve process per shape. The :class:`EngineDispatcher` is the
third option: requests carry per-request ``eps``/``rule``/``theta``
routing keys, a deterministic canonicalizer quantizes them onto a
BOUNDED key lattice, and each lattice point gets its own StreamEngine
with its own compile-once guard. No engine ever sees more than one
static shape, so the pool-wide recompile count is pinned at zero.

Canonicalization (the key lattice)
    * ``eps`` quantizes to its tuning-table eps BAND
      (``tune.eps_band``: the nearest power of ten) — the engine runs
      at the band edge ``10**band``, which is always at least as tight
      as any eps in the band's upper half and within one decade
      otherwise. Bands outside ``[1e-12, 1e-1]`` are rejected.
    * ``rule`` must name a member of :class:`~ppls_tpu.config.Rule`.
    * theta batches pad up to the next power-of-two ``theta_block``
      bucket (1, 2, 4, ... ``MAX_THETA_BUCKET``); batches keep their
      true length inside the engine (the pad is the BUCKET, not fake
      thetas). Batches >1 require TRAPEZOID (union refinement).

Work-conserving schedule
    Each dispatcher ``step()`` is one TURN: route the shared backlog,
    then run ONE phase on every live engine that has work, in
    round-robin order rotated by the turn index — drained engines are
    skipped, so a busy shape never idles behind an empty one, and no
    shape can starve another (one phase per engine per turn, full
    stop). Admission control, token buckets, the shed policy, and the
    SLO evaluator all lift from per-engine to POOL scope: one shared
    backlog with the per-engine slot occupancy as the routing gate.

Park / unpark (the pool stays bounded)
    At most ``max_engines`` engines are live. When a new key needs a
    slot, the LRU victim (idle engines first) checkpoints through
    ``runtime/checkpoint`` and is PARKED; when its shape returns, the
    engine resumes from that snapshot bit-identically — same phase
    rows, same pending queue, same per-request areas. Park files are
    sequence-numbered and immutable, so a crash mid-park never damages
    an older generation.

Coordinated snapshot cut
    ``snapshot()`` writes one immutable per-engine snapshot per live
    engine under a CUT number, then the pool manifest (routing ledger,
    grid maps, backlog, accounting) LAST via the checkpoint module's
    atomic rename — a crash between the two leaves the previous cut's
    manifest pointing at the previous cut's files (superseded files
    are GC'd only after the new manifest lands). Every engine file
    carries the pool id in its ``client_state``; resume refuses a
    manifest whose configuration or engine-key set differs, and an
    engine file from a different pool, with the checkpoint module's
    refusing-to-blend contract.

Compile accounting across the pool
    ``run_stream_cycle``'s pjit cache is MODULE-global: engine B's
    first trace grows the same cache engine A already published, so
    naively forwarding cache sizes would count every spin-up as a
    recompile of every other engine. The per-engine telemetry wrapper
    therefore attributes global cache GROWTH to the engine that was
    stepping when it happened and forwards only its own attributed
    count — each engine's pool-visible series is flat at its own entry
    count, and ``ppls_recompiles_total`` stays 0 unless an engine
    re-traces its OWN program (a real compile-once violation).
"""

from __future__ import annotations

import dataclasses
import math
import os
import re
import tempfile
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ppls_tpu.config import Rule
from ppls_tpu.obs.registry import MetricsRegistry
from ppls_tpu.obs.telemetry import Telemetry
from ppls_tpu.runtime.stream import (_COUNTER_STATS, STREAM_STAT_FIELDS,
                                     CompletedRequest, ShedRecord,
                                     StreamEngine, StreamResult)
from ppls_tpu.runtime.tune import eps_band

# the canonical eps lattice: tuning-table bands, one engine per band.
# Outside this range a request is malformed (the tables stop there and
# an engine at 1e-13 would never retire within any sane deadline).
EPS_BAND_MIN = -12
EPS_BAND_MAX = -1

# theta batches bucket to powers of two up to this cap — the bucket is
# a compile static (``theta_block``), so the cap bounds the lattice;
# it also has to divide the engine's lane count, which every pow2 up
# to 64 does for the default lanes=256.
MAX_THETA_BUCKET = 64

DISPATCH_CKPT_VERSION = 1

_FS_SAFE = re.compile(r"[^A-Za-z0-9_.-]")


def _theta_bucket(n: int) -> int:
    """Next power-of-two bucket for a theta batch of length ``n``."""
    return 1 << max(0, int(math.ceil(math.log2(max(1, int(n))))))


@dataclasses.dataclass(frozen=True, order=True)
class EngineKey:
    """One point on the canonical key lattice = one pooled engine.

    The string form ``e{band}:{rule}:t{block}`` is the pool's stable
    engine label — it keys the manifest, the metric labels, and the
    park files, so it must stay deterministic and parseable."""

    eps_band: int
    rule: str
    theta_block: int

    @property
    def eps(self) -> float:
        return 10.0 ** self.eps_band

    def __str__(self) -> str:
        return f"e{self.eps_band}:{self.rule}:t{self.theta_block}"

    @classmethod
    def parse(cls, s: str) -> "EngineKey":
        m = re.fullmatch(r"e(-?\d+):([a-z_]+):t(\d+)", s)
        if m is None:
            raise ValueError(f"malformed engine key {s!r}")
        return cls(int(m.group(1)), m.group(2), int(m.group(3)))


def canonical_key(eps, rule, theta) -> EngineKey:
    """Quantize a request's routing keys onto the engine-key lattice.

    Raises ``ValueError`` on anything malformed or out of band —
    BEFORE any pool state is consumed, so the caller owns the
    rejection record exactly like a malformed ``StreamEngine.submit``.
    """
    try:
        eps = float(eps)
    except (TypeError, ValueError):
        raise ValueError(f"eps must be a number, got {eps!r}")
    if not math.isfinite(eps) or eps <= 0.0:
        raise ValueError(f"eps must be finite and > 0, got {eps!r}")
    band = eps_band(eps)
    if not EPS_BAND_MIN <= band <= EPS_BAND_MAX:
        raise ValueError(
            f"eps {eps!r} quantizes to band 1e{band}, outside the "
            f"dispatchable range [1e{EPS_BAND_MIN}, 1e{EPS_BAND_MAX}]")
    if isinstance(rule, Rule):
        r = rule
    else:
        try:
            r = Rule(str(rule).strip().lower())
        except ValueError:
            raise ValueError(
                f"unknown rule {rule!r} (want one of "
                f"{[m.value for m in Rule]})")
    if isinstance(theta, (tuple, list, np.ndarray)):
        n = int(np.asarray(theta).reshape(-1).shape[0])
        if n == 0:
            raise ValueError("empty theta batch")
    else:
        n = 1
    bucket = _theta_bucket(n)
    if bucket > MAX_THETA_BUCKET:
        raise ValueError(
            f"theta batch of {n} exceeds the dispatcher's bucket cap "
            f"({MAX_THETA_BUCKET})")
    if bucket > 1 and r is not Rule.TRAPEZOID:
        raise ValueError(
            "theta batches run union-refinement, which is TRAPEZOID "
            f"only; got rule={r.value!r} with a batch of {n}")
    return EngineKey(band, r.value, bucket)


@dataclasses.dataclass
class PoolRequest:
    """One request in POOL time: rids, phases, and deadlines here are
    all pool-scoped (``grid`` = global rid, turns = dispatcher
    phases); the engine-local twins live behind the routing maps."""

    grid: int
    key: str
    theta: object
    bounds: Tuple[float, float]
    submit_turn: int
    submit_t: float
    tenant: str = "default"
    priority: int = 1
    deadline_turns: Optional[int] = None
    routed_turn: Optional[int] = None

    @property
    def deadline_turn(self) -> Optional[int]:
        if self.deadline_turns is None:
            return None
        return self.submit_turn + self.deadline_turns


class _EngineTelemetry(Telemetry):
    """The per-engine telemetry handle the dispatcher threads into
    each pooled StreamEngine.

    * **Registry:** PRIVATE per engine. ``StreamEngine.resume``
      replays its whole deterministic record into its registry — on a
      shared registry every unpark would double-count the pool totals.
      The pool reads engine totals from these private registries and
      publishes pool-scope accounting on its own registry.
    * **Tracer:** SHARED with the pool — one timeline. Every span and
      event gains an ``engine`` label, and request-scoped ``rid``
      attrs translate from engine-local rids to pool grids so the
      rid-linkage contract holds on the single events file.
    * **Compile attribution:** see the module docstring — global
      cache growth is attributed to this engine only while it is the
      one stepping, and only the attributed count is forwarded to the
      pool telemetry (first forward = that engine's baseline)."""

    def __init__(self, pool: "EngineDispatcher", label: str):
        super().__init__(registry=MetricsRegistry())
        self._pool = pool
        self._label = label
        self._rid_map: Dict[int, int] = {}   # engine rid -> pool grid
        self._local_entries = 0              # attributed cache entries
        # round 22: set by the turn loop around a LEASED phase launch
        # so the phase span records it ran on a donated credit (the
        # occupancy tool reconciles these against the lease grants)
        self._lease_phase = False
        # one timeline: the pool's tracer replaces the private one the
        # base constructor made (which is disabled and writes nowhere)
        self.tracer = pool.telemetry.tracer

    def fresh_registry(self) -> None:
        """Swap in an empty registry before an unpark resume — the
        resumed engine re-registers and replays everything it needs;
        keeping the old registry would double every replayed value."""
        self.registry = MetricsRegistry()
        self._compile_seen = {}

    # -- tracer face: engine label + rid translation --------------------

    def span(self, name: str, **attrs):
        attrs.setdefault("engine", self._label)
        if name == "phase" and self._lease_phase:
            attrs.setdefault("leased", True)
        return self.tracer.span(name, **attrs)

    def event(self, name: str, **attrs) -> None:
        rid = attrs.get("rid")
        if rid is not None:
            attrs["rid"] = self._rid_map.get(int(rid), int(rid))
        attrs.setdefault("engine", self._label)
        self.tracer.event(name, **attrs)

    def request_span(self, rid: int, **attrs):
        """The engine's request span IS the pool's grid span: submit
        (and resume replay) return the already-open pool span so the
        rid's causal trace stays one unbroken timeline across routing,
        park/unpark, and retirement."""
        grid = self._rid_map.get(int(rid), int(rid))
        span = self._pool._grid_spans.get(grid)
        if span is None:
            span = self._pool.telemetry.request_span(
                grid, engine=self._label, **attrs)
            self._pool._grid_spans[grid] = span
        return span

    # -- compile attribution --------------------------------------------

    def publish_compile(self, engine: str, entries: int,
                        wall_s: float = 0.0) -> None:
        entries = int(entries)
        pool = self._pool
        prev = pool._cache_entries_seen
        if prev is None:
            pool._cache_entries_seen = entries
            grew = 0
        else:
            grew = max(0, entries - prev)
            pool._cache_entries_seen = max(prev, entries)
        if grew:
            self._local_entries += grew
        # the private gauge keeps the raw global count (debuggability);
        # the POOL series gets the attributed per-engine count, whose
        # growth — and only whose growth — is a real recompile
        self.publish_compile_cache(engine, entries)
        if self._local_entries:
            pool.telemetry.publish_compile(
                f"{engine}[{self._label}]", self._local_entries,
                wall_s=wall_s if grew else 0.0)


class EngineDispatcher:
    """A pool of StreamEngines keyed by canonicalized compile statics,
    one serving surface (see module docstring).

    The public face deliberately mirrors :class:`StreamEngine` —
    ``submit`` / ``step`` / ``drain`` / ``run`` / ``result`` /
    ``snapshot`` / ``resume`` / ``idle`` / ``slo_health`` — so the
    serve CLI, the benches, and the artifact tooling treat a pool and
    a single engine interchangeably. ``submit`` additionally takes the
    per-request ``eps``/``rule`` routing keys."""

    def __init__(self, family: str, *,
                 slots: int = 64,
                 max_engines: int = 4,
                 default_eps: float = 1e-6,
                 default_rule: Rule = Rule.TRAPEZOID,
                 queue_limit: Optional[int] = None,
                 tenant_quotas: Optional[dict] = None,
                 default_deadline_phases: Optional[int] = None,
                 park_patience: int = 2,
                 checkpoint_path: Optional[str] = None,
                 checkpoint_every: int = 8,
                 telemetry: Optional[Telemetry] = None,
                 slo_config=None,
                 fault_injector=None,
                 quarantine: bool = False,
                 on_shed=None,
                 interpret: Optional[bool] = None,
                 lease: bool = False,
                 lease_cap: int = 3,
                 lease_patience: int = 1,
                 overlap_boundaries: bool = False,
                 checkpoint_background: Optional[bool] = None,
                 engine_kw: Optional[dict] = None):
        from ppls_tpu.models.integrands import get_family_ds
        self.family = family
        self.slots = int(slots)
        self.max_engines = max(1, int(max_engines))
        self.default_eps = float(default_eps)
        self.default_rule = (default_rule if isinstance(default_rule,
                                                       Rule)
                             else Rule(str(default_rule)))
        self.queue_limit = queue_limit
        self.tenant_quotas = tenant_quotas
        self.default_deadline_phases = default_deadline_phases
        self.park_patience = max(1, int(park_patience))
        self.checkpoint_path = checkpoint_path
        self.checkpoint_every = max(int(checkpoint_every), 1)
        # round 22 (tentpole): slot-credit leasing + overlapped phase
        # boundaries. Both are pure host-side BOUNDARY policy — they
        # never touch a compile static, so compile-once (and the
        # zero-recompile invariant) holds by construction. Neither is
        # manifest identity: like queue_limit/quotas, a resume must be
        # driven with the same flags for the schedule to replay.
        self.lease = bool(lease)
        self.lease_cap = max(1, int(lease_cap))
        self.lease_patience = max(1, int(lease_patience))
        self.overlap_boundaries = bool(overlap_boundaries)
        # background checkpoint serialization rides the overlap flag
        # by default (it IS the boundary-overlap story for the cut),
        # but stays independently controllable
        self.checkpoint_background = bool(
            overlap_boundaries if checkpoint_background is None
            else checkpoint_background)
        self.telemetry = telemetry if telemetry is not None \
            else Telemetry()
        self.fault_injector = fault_injector
        self.quarantine = bool(quarantine)
        self.on_shed = on_shed
        # None = per-engine auto-detect (interpret off-TPU), the
        # StreamEngine default
        self.interpret = (None if interpret is None
                          else bool(interpret))
        self.engine_kw = dict(engine_kw or {})
        self._f_ds = get_family_ds(family)

        # pool identity: minted once, stamped into every engine
        # snapshot's client_state — the cross-pool blend refusal
        self.pool_id = os.urandom(8).hex()

        # engine pool state
        self._engines: Dict[str, StreamEngine] = {}
        self._wrappers: Dict[str, _EngineTelemetry] = {}
        self._parked: Dict[str, dict] = {}
        self._order: List[str] = []          # live round-robin order
        self._last_used: Dict[str, int] = {}
        self._park_seq = 0
        self._pool_dir: Optional[str] = None

        # routing state (pool time)
        self.turn = 0
        self._next_grid = 0
        self._backlog: List[PoolRequest] = []
        self._inflight: Dict[int, PoolRequest] = {}
        self._gmap: Dict[int, Tuple[str, int]] = {}  # grid->(key,lrid)
        self._taken: Dict[str, List[int]] = {}   # key->[ncomp, nshed]
        self._grid_spans: dict = {}
        self._tokens: Dict[str, float] = {}
        self._token_waits: Dict[int, int] = {}
        self.completed: List[CompletedRequest] = []
        self.shed: List[ShedRecord] = []
        self.client_state: dict = {}

        # round 22: the lease ledger — per-engine idle streaks (the
        # donor hysteresis), cumulative donated/received credits, and
        # the boundary/overlap tallies. All of it rides the
        # coordinated snapshot so a resumed pool replays the identical
        # lease decisions.
        self._idle_streak: Dict[str, int] = {}
        self._lease_given: Dict[str, int] = {}
        self._lease_recv: Dict[str, int] = {}
        self._boundaries = 0
        self._overlapped = 0
        self._boundary_wall = 0.0
        self._overlap_wall = 0.0

        # compile attribution (module-global pjit cache; see wrapper)
        self._cache_entries_seen: Optional[int] = None

        # coordinated snapshot cut bookkeeping
        self._cut = 0
        self._cut_files: set = set()

        # pool-scope accounting: the same metric names the single
        # engine publishes, so the serve summary, /metrics, and the
        # SLO evaluator read one surface regardless of tier — plus the
        # dispatch-specific families (engine-labeled)
        tel = self.telemetry
        reg = tel.registry
        self._c_retired = tel.stream_counter("retired")
        self._c_tenant_retired = reg.counter(
            "ppls_stream_tenant_retired_total",
            "requests retired, by tenant", ("tenant",))
        self._c_deadline = reg.counter(
            "ppls_stream_deadline_exceeded_total",
            "in-flight requests retired failed at their phase "
            "deadline", ("tenant",))
        self._c_quarantined = reg.counter(
            "ppls_stream_quarantined_total",
            "requests retired as failed through the NaN quarantine")
        self._c_shed = tel.shed_counter()
        self._h_lat_phases = tel.latency_phases_histogram()
        self._h_lat_seconds = tel.latency_seconds_histogram()
        self._h_class_lat = tel.class_latency_histogram()
        self._h_tenant_lat = tel.tenant_latency_histogram()
        self._h_engine_lat = tel.dispatch_latency_histogram()
        self._g_engines = tel.dispatch_engines_gauge()
        self._c_phases = tel.dispatch_phase_counter()
        self._c_routed = tel.dispatch_routed_counter()
        self._c_spinup = reg.counter(
            "ppls_dispatch_engine_spinups_total",
            "engine spin-ups (cold or unpark), by engine key",
            ("engine",))
        self._c_park = reg.counter(
            "ppls_dispatch_engine_parks_total",
            "LRU engine parks (checkpoint + evict), by engine key",
            ("engine",))
        self._c_lease_donated = reg.counter(
            "ppls_dispatch_lease_donated_total",
            "phase credits donated to the lease pool, by donor "
            "engine key", ("engine",))
        self._c_lease_recv = reg.counter(
            "ppls_dispatch_lease_received_total",
            "leased phase credits received, by borrower engine key",
            ("engine",))
        self._c_boundary = reg.counter(
            "ppls_dispatch_boundaries_total",
            "engine phase boundaries the turn loop ran (host "
            "fetch + retire bookkeeping)")
        self._c_boundary_overlap = reg.counter(
            "ppls_dispatch_boundaries_overlapped_total",
            "phase boundaries whose host work ran while another "
            "engine's launched cycle was still in flight")
        self._g_backlog = reg.gauge(
            "ppls_dispatch_backlog",
            "pool-scope shared backlog depth (unrouted requests)")
        self._g_inflight = reg.gauge(
            "ppls_dispatch_inflight",
            "requests routed to an engine and not yet terminal")
        self._g_occ = reg.gauge(
            "ppls_dispatch_slot_occupancy",
            "per-engine resident slots / total slots", ("engine",))
        self._g_turn = reg.gauge(
            "ppls_dispatch_turn", "dispatcher turn counter")
        # registered here with the exact telemetry-module text so
        # recompiles() can sum the family without re-registering a
        # conflicting twin
        self._c_recompiles = reg.counter(
            "ppls_recompiles_total",
            "pjit cache growth events after the engine's first "
            "observation (compile-once invariant violations)",
            ("engine",))
        self._slo = None
        if slo_config is not None:
            from ppls_tpu.obs.slo import SloEvaluator
            self._slo = SloEvaluator(slo_config, tel, scope="pool")
        self._g_engines.labels(state="live").set(0.0)
        self._g_engines.labels(state="parked").set(0.0)

    # ------------------------------------------------------------------
    # request intake (pool scope)
    # ------------------------------------------------------------------

    def submit(self, theta, bounds, tenant: str = "default",
               priority: int = 1,
               deadline_phases: Optional[int] = None,
               eps: Optional[float] = None,
               rule=None) -> int:
        """Queue one request with its routing keys; returns the pool
        grid (the pool-scope rid). A malformed submission — bad
        eps/rule/theta shape, bad domain, bad tenancy fields — raises
        ``ValueError`` BEFORE a grid is consumed (the caller owns the
        rejection record, same contract as ``StreamEngine.submit``).
        A well-formed submission always consumes a grid; under a full
        ``queue_limit`` the engine's deterministic shed policy applies
        at POOL scope (lowest-priority-oldest vs the arrival)."""
        from ppls_tpu.models.integrands import check_ds_domain
        key = canonical_key(self.default_eps if eps is None else eps,
                            self.default_rule if rule is None
                            else rule, theta)
        bounds = (float(bounds[0]), float(bounds[1]))
        if isinstance(theta, (tuple, list, np.ndarray)):
            thetas = tuple(float(t)
                           for t in np.asarray(theta).reshape(-1))
            theta_store = thetas if key.theta_block > 1 else thetas[0]
        else:
            thetas = (float(theta),)
            theta_store = float(theta)
        check_ds_domain(self._f_ds,
                        np.tile(np.array([bounds]),
                                (len(thetas), 1)),
                        np.array(thetas))
        tenant = str(tenant)
        if not tenant or len(tenant) > 128:
            raise ValueError(
                f"tenant must be a non-empty string of <= 128 chars, "
                f"got {tenant!r}")
        priority = int(priority)
        if deadline_phases is None:
            deadline_phases = self.default_deadline_phases
        if deadline_phases is not None:
            deadline_phases = int(deadline_phases)
            if deadline_phases < 1:
                raise ValueError(
                    f"deadline_phases must be >= 1, got "
                    f"{deadline_phases}")
        grid = self._next_grid
        self._next_grid += 1
        preq = PoolRequest(
            grid=grid, key=str(key), theta=theta_store,
            bounds=bounds, submit_turn=self.turn,
            submit_t=time.perf_counter(), tenant=tenant,
            priority=priority, deadline_turns=deadline_phases)
        self._grid_spans[grid] = self.telemetry.request_span(
            grid, tenant=tenant, priority=priority,
            submit_phase=self.turn, engine=preq.key)
        if self.queue_limit is not None \
                and len(self._backlog) >= self.queue_limit:
            victim = min(self._backlog,
                         key=lambda r: (r.priority, r.grid))
            if victim.priority < preq.priority:
                self._backlog.remove(victim)
                self._shed_pool(victim, "queue_full")
            else:
                self._shed_pool(preq, "queue_full")
                return grid
        self._backlog.append(preq)
        return grid

    def _shed_pool(self, preq: PoolRequest, reason: str) -> ShedRecord:
        rec = ShedRecord(
            rid=preq.grid, theta=preq.theta, bounds=preq.bounds,
            tenant=preq.tenant, priority=preq.priority, reason=reason,
            phase=self.turn, submit_phase=preq.submit_turn)
        self.shed.append(rec)
        self._c_shed.labels(tenant=preq.tenant, reason=reason).inc()
        self._token_waits.pop(preq.grid, None)
        span = self._grid_spans.pop(preq.grid, None)
        self.telemetry.request_event(
            span, "request_shed", rid=preq.grid, tenant=preq.tenant,
            priority=preq.priority, reason=reason, phase=self.turn,
            submit_phase=preq.submit_turn, engine=preq.key)
        if span is not None:
            span.close(disposition="shed", reason=reason,
                       phase=self.turn)
        if self.on_shed is not None:
            self.on_shed(rec)
        return rec

    def _quota_for(self, tenant: str) -> Optional[dict]:
        if self.tenant_quotas is None:
            return None
        return self.tenant_quotas.get(tenant,
                                      self.tenant_quotas.get("*"))

    def _refill_tokens(self) -> None:
        if self.tenant_quotas is None:
            return
        for tenant in self._tokens:
            q = self._quota_for(tenant)
            if q is not None:
                self._tokens[tenant] = min(
                    q["burst"], self._tokens[tenant] + q["rate"])

    def _shed_unmeetable(self) -> None:
        victims = [r for r in self._backlog
                   if r.deadline_turn is not None
                   and r.deadline_turn < self.turn]
        for preq in victims:
            self._backlog.remove(preq)
            self._shed_pool(preq, "deadline_exceeded")

    # ------------------------------------------------------------------
    # engine pool: spin-up / park / unpark
    # ------------------------------------------------------------------

    def _pool_path(self) -> str:
        """Directory for park files: the checkpoint dir when one is
        configured, else a lazily created temp dir (parking must work
        on an un-checkpointed pool — it is an eviction, not a durable
        cut)."""
        if self._pool_dir is None:
            if self.checkpoint_path:
                self._pool_dir = (os.path.dirname(
                    os.path.abspath(self.checkpoint_path)) or ".")
                os.makedirs(self._pool_dir, exist_ok=True)
            else:
                self._pool_dir = tempfile.mkdtemp(
                    prefix="ppls-dispatch-")
        return self._pool_dir

    @staticmethod
    def _fs_key(keystr: str) -> str:
        return _FS_SAFE.sub("-", keystr)

    def _engine_kwargs(self, key: EngineKey) -> dict:
        kw = dict(self.engine_kw)
        kw.update(slots=self.slots, rule=Rule(key.rule),
                  theta_block=key.theta_block,
                  interpret=self.interpret,
                  quarantine=self.quarantine,
                  checkpoint_background=self.checkpoint_background)
        return kw

    def _register_live(self, keystr: str, eng: StreamEngine) -> None:
        self._engines[keystr] = eng
        self._order.append(keystr)
        self._last_used[keystr] = self.turn
        self._taken.setdefault(keystr, [0, 0])

    def _spinup(self, keystr: str) -> StreamEngine:
        key = EngineKey.parse(keystr)
        wrapper = self._wrappers.get(keystr)
        if wrapper is None:
            wrapper = _EngineTelemetry(self, keystr)
            self._wrappers[keystr] = wrapper
        # each engine resolves its own tuned cadence signature and
        # owns its own compile-once guard from here on
        eng = StreamEngine(self.family, key.eps, telemetry=wrapper,
                           **self._engine_kwargs(key))
        self._register_live(keystr, eng)
        self._c_spinup.labels(engine=keystr).inc()
        self.telemetry.event(
            "engine_spinup", engine=keystr, turn=self.turn,
            resumed=False, live=len(self._engines),
            parked=len(self._parked))
        return eng

    def _park(self, keystr: str) -> None:
        """Checkpoint + evict one live engine. The park file is a new
        immutable sequence-numbered snapshot (re-parks never overwrite
        an older generation), stamped with the pool id."""
        eng = self._engines.pop(keystr)
        self._order.remove(keystr)
        self._park_seq += 1
        path = os.path.join(
            self._pool_path(),
            f"park.{self._park_seq:05d}.{self._fs_key(keystr)}.ckpt")
        eng.client_state["pool_id"] = self.pool_id
        eng.client_state["engine_key"] = keystr
        eng.checkpoint_path = path
        eng.snapshot()
        eng.checkpoint_path = None
        self._parked[keystr] = {
            "path": path, "seq": self._park_seq, "idle": eng.idle,
            "phase": eng.phase, "pending": eng.pending,
            "resident": eng.resident,
            "totals": self._wrapper_totals(self._wrappers[keystr]),
        }
        self._c_park.labels(engine=keystr).inc()
        self._g_occ.labels(engine=keystr).set(0.0)
        self.telemetry.event(
            "engine_park", engine=keystr, turn=self.turn,
            phase=eng.phase, idle=eng.idle, pending=eng.pending,
            resident=eng.resident, live=len(self._engines),
            parked=len(self._parked))

    def _unpark(self, keystr: str) -> StreamEngine:
        info = self._parked.pop(keystr)
        key = EngineKey.parse(keystr)
        wrapper = self._wrappers[keystr]
        # fresh registry: the resume replay below rebuilds the
        # engine's whole deterministic record into it (the old one
        # already holds those values — keeping it would double-count)
        wrapper.fresh_registry()
        eng = StreamEngine.resume(info["path"], self.family, key.eps,
                                  telemetry=wrapper,
                                  **self._engine_kwargs(key))
        if eng.client_state.get("pool_id") != self.pool_id:
            raise ValueError(
                f"park file {info['path']!r} belongs to a different "
                f"pool (stored {eng.client_state.get('pool_id')!r}, "
                f"this pool {self.pool_id!r}); refusing to blend")
        # resume() armed auto-snapshots onto the park file — the pool
        # owns the snapshot cadence, and park files are immutable
        eng.checkpoint_path = None
        self._register_live(keystr, eng)
        self._c_spinup.labels(engine=keystr).inc()
        self.telemetry.event(
            "engine_spinup", engine=keystr, turn=self.turn,
            resumed=True, phase=eng.phase, live=len(self._engines),
            parked=len(self._parked))
        return eng

    def _pick_victim(self, exclude: str) -> Optional[str]:
        """LRU park victim: idle engines first; a busy engine only
        when it has not been routed to for ``park_patience`` turns
        (anti-thrash — under key pressure a busy shape holds its
        engine for at least that long)."""
        cands = [k for k in self._order if k != exclude]
        if not cands:
            return None
        idle = [k for k in cands if self._engines[k].idle]
        if idle:
            return min(idle,
                       key=lambda k: (self._last_used.get(k, -1), k))
        stale = [k for k in cands
                 if self._last_used.get(k, -1)
                 <= self.turn - self.park_patience]
        if stale:
            return min(stale,
                       key=lambda k: (self._last_used.get(k, -1), k))
        return None

    def _ensure_engine(self, keystr: str) -> Optional[StreamEngine]:
        """Live engine for ``keystr``, spinning up / unparking (and
        LRU-evicting) as needed; ``None`` when the cap is reached and
        no victim is eligible yet (the request stays in the backlog).
        """
        eng = self._engines.get(keystr)
        if eng is not None:
            return eng
        if len(self._engines) >= self.max_engines:
            victim = self._pick_victim(keystr)
            if victim is None:
                return None
            self._park(victim)
        if keystr in self._parked:
            return self._unpark(keystr)
        return self._spinup(keystr)

    # ------------------------------------------------------------------
    # routing + the work-conserving turn
    # ------------------------------------------------------------------

    def _route(self) -> None:
        """Deal backlog requests to their engines: order is
        (-priority, grid) — higher classes first, FIFO within a class
        — gated by the pool token buckets and each engine's free
        capacity (slots not already spoken for), so admission control
        stays pool-scope and an engine's pending queue never grows
        beyond what it can seat."""
        if not self._backlog:
            return
        routed: set = set()
        for preq in sorted(self._backlog,
                           key=lambda r: (-r.priority, r.grid)):
            dt = preq.deadline_turn
            remaining = None if dt is None else dt - self.turn
            if remaining is not None and remaining < 1:
                continue    # next turn's unmeetable shed takes it
            q = self._quota_for(preq.tenant)
            if q is not None:
                if preq.tenant not in self._tokens:
                    self._tokens[preq.tenant] = q["burst"]
                if self._tokens[preq.tenant] < 1.0:
                    self._token_waits[preq.grid] = \
                        self._token_waits.get(preq.grid, 0) + 1
                    self.telemetry.request_event(
                        self._grid_spans.get(preq.grid),
                        "token_wait", rid=preq.grid,
                        tenant=preq.tenant, phase=self.turn)
                    continue
            eng = self._engines.get(preq.key)
            if eng is None:
                eng = self._ensure_engine(preq.key)
                if eng is None:
                    continue            # pool at cap, victims fresh
            if eng.free_capacity <= 0:
                continue
            wrapper = self._wrappers[preq.key]
            lrid = eng.next_rid
            # the map entry must exist BEFORE submit: the engine opens
            # its request span during submit and the wrapper resolves
            # it to the pool grid span through this map
            wrapper._rid_map[lrid] = preq.grid
            eng.submit(preq.theta, preq.bounds, tenant=preq.tenant,
                       priority=preq.priority,
                       deadline_phases=remaining)
            if q is not None:
                self._tokens[preq.tenant] -= 1.0
            preq.routed_turn = self.turn
            self._gmap[preq.grid] = (preq.key, lrid)
            self._inflight[preq.grid] = preq
            self._last_used[preq.key] = self.turn
            self._c_routed.labels(engine=preq.key).inc()
            self.telemetry.request_event(
                self._grid_spans.get(preq.grid), "request_dealt",
                rid=preq.grid, engine=preq.key, phase=self.turn,
                engine_rid=lrid, engine_phase=eng.phase)
            routed.add(preq.grid)
        if routed:
            self._backlog = [r for r in self._backlog
                             if r.grid not in routed]

    def _unpark_stranded(self) -> None:
        """Progress guarantee: when every live engine is drained but
        parked work exists, unpark it (deterministically: smallest
        key) — otherwise the pool would idle forever on turns."""
        if not self._inflight and not self._backlog:
            return
        if any(not e.idle for e in self._engines.values()):
            return
        cands = sorted(k for k, i in self._parked.items()
                       if not i["idle"])
        if cands:
            self._ensure_engine(cands[0])

    def _update_idle_streaks(self) -> None:
        """Donor hysteresis state: consecutive turns each LIVE engine
        has been drained (routing for this turn already ran, so a
        just-fed engine resets here). Parked engines carry no streak —
        they donate unconditionally."""
        for keystr in self._order:
            if self._engines[keystr].idle:
                self._idle_streak[keystr] = \
                    self._idle_streak.get(keystr, 0) + 1
            else:
                self._idle_streak[keystr] = 0

    def _lease_schedule(self) -> Dict[str, int]:
        """Deal this turn's phase credits. Base schedule: one credit
        per live engine with work (the round-21 work-conserving turn).
        With leasing on, engines with idle slots DONATE their turn
        budget to the deepest-backlog engines:

        * donors — every parked engine (infinitely idle, so they rank
          first; their whole budget is the one phase they would run if
          live), then live drained engines whose idle streak has
          reached ``lease_patience`` (hysteresis: a one-turn gap never
          thrashes credits), deepest streak first, key order breaking
          ties;
        * borrowers — live busy engines ranked by backlog depth
          (pending + resident), key order breaking ties; credits deal
          one at a time round-robin down that ranking, capped at
          ``lease_cap`` extra credits per borrower per turn;
        * undealt credits lapse (they are phase slots, not tokens).

        Every input is host state the boundary already owns — the
        policy is deterministic, and the grants it emits replay
        bit-identically from the snapshot's lease ledger."""
        credits = {k: (0 if self._engines[k].idle else 1)
                   for k in self._order}
        if not self.lease:
            return credits
        borrowers = sorted(
            (k for k in self._order if not self._engines[k].idle),
            key=lambda k: (-(self._engines[k].pending
                             + self._engines[k].resident), k))
        if not borrowers:
            return credits
        donors = sorted(self._parked) + sorted(
            (k for k in self._order
             if self._engines[k].idle
             and self._idle_streak.get(k, 0) >= self.lease_patience),
            key=lambda k: (-self._idle_streak.get(k, 0), k))
        extra = {k: 0 for k in borrowers}
        grants: Dict[Tuple[str, str], int] = {}
        bi = 0
        for donor in donors:
            placed = False
            for _ in range(len(borrowers)):
                b = borrowers[bi % len(borrowers)]
                bi += 1
                if extra[b] < self.lease_cap:
                    extra[b] += 1
                    credits[b] += 1
                    grants[(donor, b)] = grants.get((donor, b), 0) + 1
                    placed = True
                    break
            if not placed:
                break           # every borrower at cap: the rest lapse
        for (donor, b), n in sorted(grants.items()):
            self._lease_given[donor] = \
                self._lease_given.get(donor, 0) + n
            self._lease_recv[b] = self._lease_recv.get(b, 0) + n
            self._c_lease_donated.labels(engine=donor).inc(n)
            self._c_lease_recv.labels(engine=b).inc(n)
            self.telemetry.event(
                "lease_grant", turn=self.turn, donor=donor,
                borrower=b, credits=n,
                donor_parked=donor in self._parked)
        return credits

    def _note_phase(self, keystr: str) -> None:
        self._last_used[keystr] = self.turn
        self._c_phases.labels(engine=keystr).inc()

    def _finish_phase(self, eng, keystr: str, token,
                      in_flight: int) -> None:
        """Run one engine's boundary (the PULL half) and tally it:
        every finish is a boundary; a finish with other launched
        cycles still in flight is an OVERLAPPED boundary — its host
        work ran concurrently with device compute it did not wait on.
        """
        t0 = time.perf_counter()
        eng.step_finish(token)
        dt = time.perf_counter() - t0
        self._boundaries += 1
        self._c_boundary.inc()
        self._boundary_wall += dt
        if in_flight > 0:
            self._overlapped += 1
            self._c_boundary_overlap.inc()
            self._overlap_wall += dt
        self._note_phase(keystr)

    def _run_turn_phases(self, credits: Dict[str, int]) -> int:
        """Run this turn's phases per the credit schedule. Credits run
        in ROUNDS: round r steps every engine holding more than r
        credits, rotated by the turn index over the ELIGIBLE engines
        only (round 22 fix: a drained/parked engine never occupies a
        rotation slot, so it cannot burn a turn credit that a busy
        engine would have used). An engine that drains mid-turn
        forfeits its remaining credits — they are phase slots, not
        carryover tokens.

        With ``overlap_boundaries`` each round launches every
        eligible engine's compiled cycle back-to-back (JAX async
        dispatch returns before the device finishes), then runs the
        boundaries LIFO — innermost launch first, so the tracer's
        span nesting stays clean — with each boundary's host work
        overlapping the still-in-flight peers' device compute."""
        eligible = [k for k in self._order
                    if credits.get(k, 0) > 0]
        if not eligible:
            return 0
        start = self.turn % len(eligible)
        rotated = eligible[start:] + eligible[:start]
        stepped = 0
        max_c = max(credits.values())
        for r in range(max_c):
            batch = []
            for keystr in rotated:
                if credits.get(keystr, 0) <= r:
                    continue
                eng = self._engines.get(keystr)
                if eng is None or eng.idle:
                    continue    # drained mid-turn: credits lapse
                batch.append((keystr, eng))
            if not batch:
                break
            if self.overlap_boundaries:
                launched = []
                for keystr, eng in batch:
                    wrapper = self._wrappers[keystr]
                    wrapper._lease_phase = r > 0
                    try:
                        token = eng.step_begin()
                    finally:
                        wrapper._lease_phase = False
                    launched.append((keystr, eng, token))
                for i in range(len(launched) - 1, -1, -1):
                    keystr, eng, token = launched[i]
                    self._finish_phase(eng, keystr, token,
                                       in_flight=i)
                stepped += len(launched)
            else:
                for keystr, eng in batch:
                    wrapper = self._wrappers[keystr]
                    wrapper._lease_phase = r > 0
                    try:
                        token = eng.step_begin()
                    finally:
                        wrapper._lease_phase = False
                    self._finish_phase(eng, keystr, token,
                                       in_flight=0)
                    stepped += 1
        return stepped

    def step(self) -> List[CompletedRequest]:
        """One pool TURN: route, then run the credit schedule — one
        phase per live engine with work, plus any leased credits
        (round-robin rotated by the turn index over the eligible
        engines), then collect retirements into the pool ledger."""
        t0 = time.perf_counter()
        n_dev = max(1, len(self._engines))
        if self.fault_injector is not None:
            self.fault_injector.on_phase_open(self.turn, n_dev=n_dev)
        span = self.telemetry.span(
            "turn", turn=self.turn, live=len(self._engines),
            parked=len(self._parked), backlog=len(self._backlog))
        self._refill_tokens()
        self._shed_unmeetable()
        self._route()
        self._unpark_stranded()
        self._update_idle_streaks()
        credits = self._lease_schedule()
        stepped = self._run_turn_phases(credits)
        retired = self._collect()
        self.turn += 1
        self._publish_gauges(step_wall_s=time.perf_counter() - t0)
        if self._slo is not None:
            self._slo.evaluate_slo(self.turn)
        span.close(stepped=stepped,
                   leased=sum(max(0, c - 1)
                              for c in credits.values()),
                   retired=len(retired), backlog=len(self._backlog))
        if self.checkpoint_path and \
                self.turn % self.checkpoint_every == 0:
            self.snapshot()
        if self.fault_injector is not None:
            self.fault_injector.on_phase_close(self.turn - 1,
                                               n_dev=n_dev)
        return retired

    def _collect(self) -> List[CompletedRequest]:
        out: List[CompletedRequest] = []
        for keystr in list(self._order):
            eng = self._engines[keystr]
            taken = self._taken[keystr]
            for c in eng.completed[taken[0]:]:
                out.append(self._pool_complete(keystr, c))
            taken[0] = len(eng.completed)
            for s in eng.shed[taken[1]:]:
                self._pool_shed_from_engine(keystr, s)
            taken[1] = len(eng.shed)
        return out

    def _pool_complete(self, keystr: str,
                       c: CompletedRequest) -> CompletedRequest:
        """Translate one engine retirement into the pool ledger:
        pool grid, pool turns, pool latency — the engine already
        emitted the retire event and closed the (shared) request span
        through its telemetry wrapper."""
        wrapper = self._wrappers[keystr]
        grid = wrapper._rid_map.get(c.rid, c.rid)
        preq = self._inflight.pop(grid, None)
        now = time.perf_counter()
        g = dataclasses.replace(
            c, rid=grid,
            submit_phase=(preq.submit_turn if preq is not None
                          else c.submit_phase),
            admit_phase=(preq.routed_turn if preq is not None
                         and preq.routed_turn is not None
                         else c.admit_phase),
            retire_phase=self.turn,
            latency_s=(now - preq.submit_t if preq is not None
                       else c.latency_s))
        self._grid_spans.pop(grid, None)
        self._token_waits.pop(grid, None)
        self._account_pool_retirement(g, keystr)
        self.completed.append(g)
        return g

    def _account_pool_retirement(self, g: CompletedRequest,
                                 keystr: Optional[str]) -> None:
        self._c_retired.inc()
        self._c_tenant_retired.labels(tenant=g.tenant).inc()
        lat = g.latency_phases
        self._h_lat_phases.observe(lat)
        self._h_lat_seconds.observe(g.latency_s)
        self._h_class_lat.labels(priority=str(g.priority)) \
            .observe(lat)
        self._h_tenant_lat.labels(tenant=g.tenant).observe(lat)
        if keystr is not None:
            self._h_engine_lat.labels(engine=keystr).observe(lat)
        if g.failed:
            if g.failure == "deadline_exceeded":
                self._c_deadline.labels(tenant=g.tenant).inc()
            else:
                self._c_quarantined.inc()

    def _pool_shed_from_engine(self, keystr: str,
                               s: ShedRecord) -> None:
        wrapper = self._wrappers[keystr]
        grid = wrapper._rid_map.get(s.rid, s.rid)
        preq = self._inflight.pop(grid, None)
        rec = ShedRecord(
            rid=grid, theta=s.theta, bounds=s.bounds, tenant=s.tenant,
            priority=s.priority, reason=s.reason, phase=self.turn,
            submit_phase=(preq.submit_turn if preq is not None
                          else s.submit_phase))
        self.shed.append(rec)
        self._c_shed.labels(tenant=s.tenant, reason=s.reason).inc()
        # the engine already emitted request_shed and closed the
        # shared span through its wrapper — only the ledger + pool
        # counters live here
        self._grid_spans.pop(grid, None)
        self._token_waits.pop(grid, None)
        if self.on_shed is not None:
            self.on_shed(rec)

    def _publish_gauges(self, step_wall_s: float = 0.0) -> None:
        self._g_engines.labels(state="live") \
            .set(float(len(self._engines)))
        self._g_engines.labels(state="parked") \
            .set(float(len(self._parked)))
        self._g_backlog.set(float(len(self._backlog)))
        self._g_inflight.set(float(len(self._inflight)))
        self._g_turn.set(float(self.turn))
        for keystr, eng in self._engines.items():
            self._g_occ.labels(engine=keystr).set(
                eng.resident / max(1, eng.slots))

    # ------------------------------------------------------------------
    # drive surface (mirrors StreamEngine)
    # ------------------------------------------------------------------

    @property
    def idle(self) -> bool:
        """Nothing backlogged, nothing in flight (live OR parked),
        every live engine drained."""
        return (not self._backlog and not self._inflight
                and all(e.idle for e in self._engines.values()))

    # serve-CLI compatibility face: the single-engine names, in pool
    # units, so the serve loop / ingest stats / summary path drives a
    # pool and an engine through one code path
    @property
    def phase(self) -> int:
        return self.turn

    @property
    def next_rid(self) -> int:
        return self._next_grid

    @property
    def pending(self) -> int:
        """Everything admitted and not yet seated: the shared backlog
        plus every engine's own pending queue (parked included)."""
        n = len(self._backlog)
        n += sum(e.pending for e in self._engines.values())
        n += sum(int(i["pending"]) for i in self._parked.values())
        return n

    @property
    def resident(self) -> int:
        n = sum(e.resident for e in self._engines.values())
        n += sum(int(i["resident"]) for i in self._parked.values())
        return n

    @property
    def lanes(self) -> int:
        """Per-engine lane count (uniform across the pool — lanes ride
        ``engine_kw``), for the occupancy summary's normalization."""
        for eng in self._engines.values():
            return eng.lanes
        from ppls_tpu.runtime.stream import DEFAULT_LANES
        return int(self.engine_kw.get("lanes", DEFAULT_LANES))

    def spillover_summary(self) -> dict:
        """Engine-shape spillover block from the pool ledger (pooled
        engines run without a spillover executor, so tasks is the sum
        of whatever the completed records carried)."""
        done = [c for c in self.completed
                if getattr(c, "spillover", False)]
        total = len(self.completed)
        return {
            "spillover_completed": len(done),
            "spillover_fraction": (len(done) / total if total
                                   else 0.0),
            "spillover_tasks": 0,
        }

    def clear_snapshot(self) -> None:
        """Drop the whole coordinated cut: manifest first (no resume
        can see a half-deleted cut), then the per-engine files."""
        if self.checkpoint_background:
            from ppls_tpu.runtime.checkpoint import \
                flush_background_writer
            flush_background_writer()
        if self.checkpoint_path \
                and os.path.exists(self.checkpoint_path):
            os.unlink(self.checkpoint_path)
        for p in self._cut_files:
            try:
                os.unlink(p)
            except OSError:
                pass
        self._cut_files = set()

    def drain(self, max_turns: int = 1 << 14,
              _crash_after_turns: Optional[int] = None
              ) -> List[CompletedRequest]:
        done: List[CompletedRequest] = []
        turns = 0
        while not self.idle:
            done.extend(self.step())
            turns += 1
            if _crash_after_turns is not None \
                    and turns >= _crash_after_turns:
                raise RuntimeError(
                    f"simulated crash after {turns} turns (test hook)")
            if turns >= max_turns:
                raise RuntimeError(
                    f"dispatcher did not drain in {max_turns} turns "
                    f"({len(self._backlog)} backlogged, "
                    f"{len(self._inflight)} in flight)")
        return done

    def run(self, requests: Sequence[tuple],
            arrival_phase: Optional[Sequence[int]] = None,
            _crash_after_turns: Optional[int] = None) -> StreamResult:
        """Convenience driver, the engine-run twin: ``requests`` are
        (theta, bounds) pairs or (theta, bounds, kwargs) triples —
        kwargs may carry the routing keys (``eps``/``rule``) plus the
        tenancy fields — submitted up front or on the open-loop
        ``arrival_phase`` schedule (pool turns)."""
        t0 = time.perf_counter()
        sched = ([0] * len(requests) if arrival_phase is None
                 else [int(p) for p in arrival_phase])
        if len(sched) != len(requests):
            raise ValueError("arrival_phase length != requests length")
        order = sorted(range(len(requests)), key=lambda i: sched[i])
        queue = [(sched[i], requests[i]) for i in order]
        turn0 = self.turn
        run_span = self.telemetry.span(
            "run", engine="dispatch-pool", requests=len(queue))
        k = 0
        turns = 0
        while k < len(queue) or not self.idle:
            while k < len(queue) and queue[k][0] <= self.turn - turn0:
                r = queue[k][1]
                kw2 = r[2] if len(r) > 2 else {}
                self.submit(r[0], r[1], **kw2)
                k += 1
            self.step()
            turns += 1
            if _crash_after_turns is not None \
                    and turns >= _crash_after_turns:
                raise RuntimeError(
                    f"simulated crash after {turns} turns (test hook)")
            if turns > (1 << 14):
                raise RuntimeError("dispatcher did not converge")
        run_span.close(turns=turns, completed=len(self.completed))
        return self.result(wall_s=time.perf_counter() - t0)

    def result(self, wall_s: float = 0.0) -> StreamResult:
        """Pool-scope result on the StreamResult shape: the completed
        ledger in pool rids/turns, totals summed across the pool's
        per-engine registries (parked engines contribute their
        park-time capture), the pool latency histograms. Per-phase
        stats rows stay per-engine (they interleave meaninglessly
        across shapes) — timeline consumers read the events file."""
        from ppls_tpu.utils.metrics import round_stats_from_rows
        rows = np.zeros((0, len(STREAM_STAT_FIELDS)), np.int64)
        return StreamResult(
            completed=list(self.completed), phases=self.turn,
            wall_s=wall_s, totals=self.pool_totals(),
            phase_stats=rows,
            fam_done=np.zeros(0, dtype=bool),
            fam_first_phase=np.zeros(0, dtype=np.int32),
            fam_last_phase=np.zeros(0, dtype=np.int32),
            latency_hist_phases=self._h_lat_phases.solo(),
            latency_hist_seconds=self._h_lat_seconds.solo(),
            per_round=round_stats_from_rows(rows, STREAM_STAT_FIELDS),
            shed=list(self.shed))

    def _wrapper_totals(self, wrapper: _EngineTelemetry) -> dict:
        reg = wrapper.registry
        vals = {k: int(reg.value(f"ppls_stream_{k}_total"))
                for k in _COUNTER_STATS}
        vals["maxd"] = int(reg.value("ppls_stream_max_depth"))
        return vals

    def pool_totals(self) -> dict:
        """Device-counter totals summed across the pool: live engines
        from their private registries, parked engines from the totals
        captured at park time (their registries are replayed fresh at
        unpark, so the capture is the only live copy meanwhile)."""
        vals = {k: 0 for k in _COUNTER_STATS}
        maxd = 0
        for keystr in self._engines:
            t = self._wrapper_totals(self._wrappers[keystr])
            for k in _COUNTER_STATS:
                vals[k] += t[k]
            maxd = max(maxd, t["maxd"])
        for info in self._parked.values():
            t = info.get("totals") or {}
            for k in _COUNTER_STATS:
                vals[k] += int(t.get(k, 0))
            maxd = max(maxd, int(t.get("maxd", 0)))
        vals["maxd"] = maxd
        return vals

    def recompiles(self) -> int:
        """Pool-wide ``ppls_recompiles_total`` — THE invariant this
        tier exists to hold at zero on mixed-shape traffic."""
        return int(sum(child.value
                       for _, child in self._c_recompiles.items()))

    def engines_summary(self) -> dict:
        """Per-engine decomposition for the serve summary / bench
        record: state, phases, occupancy, routed/completed counts,
        and the pool-latency p99 of requests that retired there."""
        reg = self.telemetry.registry
        out: dict = {}
        for keystr in self._order:
            eng = self._engines[keystr]
            p99 = self._h_engine_lat.labels(engine=keystr) \
                .quantile(0.99)
            out[keystr] = {
                "state": "live", "phases": int(eng.phase),
                "pending": int(eng.pending),
                "resident": int(eng.resident),
                "completed": len(eng.completed),
                "shed": len(eng.shed),
                "routed": int(reg.value("ppls_dispatch_routed_total",
                                        engine=keystr)),
                "p99_latency_turns": p99,
                "lease_donated": int(
                    self._lease_given.get(keystr, 0)),
                "lease_received": int(
                    self._lease_recv.get(keystr, 0)),
            }
        for keystr, info in sorted(self._parked.items()):
            p99 = self._h_engine_lat.labels(engine=keystr) \
                .quantile(0.99)
            out[keystr] = {
                "state": "parked", "phases": int(info["phase"]),
                "pending": int(info["pending"]),
                "resident": int(info["resident"]),
                "completed": self._taken.get(keystr, [0, 0])[0],
                "shed": self._taken.get(keystr, [0, 0])[1],
                "routed": int(reg.value("ppls_dispatch_routed_total",
                                        engine=keystr)),
                "p99_latency_turns": p99,
                "lease_donated": int(
                    self._lease_given.get(keystr, 0)),
                "lease_received": int(
                    self._lease_recv.get(keystr, 0)),
            }
        return out

    def lease_summary(self) -> dict:
        """The lease/overlap block for the serve summary and the
        bench record: cumulative donated/received credits (which must
        balance — every grant is one donor credit landing on one
        borrower), the boundary tallies, and the overlap fractions
        (count-weighted and wall-weighted)."""
        donated = sum(self._lease_given.values())
        received = sum(self._lease_recv.values())
        return {
            "enabled": bool(self.lease),
            "overlap_boundaries": bool(self.overlap_boundaries),
            "donated": int(donated),
            "received": int(received),
            "balanced": donated == received,
            "by_donor": {k: int(v) for k, v in
                         sorted(self._lease_given.items())},
            "by_borrower": {k: int(v) for k, v in
                            sorted(self._lease_recv.items())},
            "boundaries": int(self._boundaries),
            "overlapped": int(self._overlapped),
            "overlap_fraction": (self._overlapped / self._boundaries
                                 if self._boundaries else 0.0),
            "boundary_wall_s": float(self._boundary_wall),
            "overlap_wall_s": float(self._overlap_wall),
            "overlap_wall_frac": (
                self._overlap_wall / self._boundary_wall
                if self._boundary_wall > 0 else 0.0),
        }

    def slo_health(self) -> dict:
        if self._slo is None:
            return {"ok": True, "burning": [], "phase": self.turn}
        return self._slo.health()

    # ------------------------------------------------------------------
    # coordinated snapshot cut / resume
    # ------------------------------------------------------------------

    def _manifest_identity_base(self) -> dict:
        return {
            "engine": "dispatch-pool",
            "version": DISPATCH_CKPT_VERSION,
            "family": self.family,
            "slots": self.slots,
            "max_engines": self.max_engines,
        }

    def _manifest_identity(self, keys) -> dict:
        ident = self._manifest_identity_base()
        ident["keys"] = ",".join(sorted(keys))
        return ident

    def snapshot(self) -> None:
        """One coordinated cut: every live engine snapshots to an
        immutable cut-numbered file, then the manifest (identity =
        pool config + the engine-key set) lands LAST via the atomic
        rename — see the module docstring for the crash story.
        Superseded cut files are GC'd only after the new manifest is
        durable."""
        if not self.checkpoint_path:
            raise ValueError("no checkpoint_path configured")
        from ppls_tpu.runtime.checkpoint import save_family_checkpoint
        self._cut += 1
        cut = self._cut
        d = self._pool_path()
        base = os.path.basename(self.checkpoint_path)
        new_files: set = set()
        engines_meta: dict = {}
        for keystr in list(self._order):
            eng = self._engines[keystr]
            path = os.path.join(
                d, f"{base}.c{cut:05d}.{self._fs_key(keystr)}")
            eng.client_state["pool_id"] = self.pool_id
            eng.client_state["engine_key"] = keystr
            eng.checkpoint_path = path
            eng.snapshot()
            eng.checkpoint_path = None
            new_files.add(path)
            engines_meta[keystr] = {
                "state": "live", "path": os.path.basename(path),
                "phase": int(eng.phase), "idle": eng.idle,
                "pending": int(eng.pending),
                "resident": int(eng.resident),
                "totals": self._wrapper_totals(
                    self._wrappers[keystr]),
            }
        for keystr, info in self._parked.items():
            engines_meta[keystr] = {
                "state": "parked",
                "path": os.path.basename(info["path"]),
                "phase": int(info["phase"]), "idle": info["idle"],
                "pending": int(info["pending"]),
                "resident": int(info["resident"]),
                "totals": info["totals"], "seq": info["seq"],
            }
            new_files.add(info["path"])
        totals = {
            "turn": self.turn,
            "next_grid": self._next_grid,
            "cut": cut,
            "pool_id": self.pool_id,
            "park_seq": self._park_seq,
            "order": list(self._order),
            "last_used": {k: int(v)
                          for k, v in self._last_used.items()},
            "engines": engines_meta,
            "rid_maps": {k: {str(l): int(g)
                             for l, g in w._rid_map.items()}
                         for k, w in self._wrappers.items()},
            "local_entries": {k: int(w._local_entries)
                              for k, w in self._wrappers.items()},
            "taken": {k: [int(v[0]), int(v[1])]
                      for k, v in self._taken.items()},
            "backlog": [dataclasses.asdict(r) for r in self._backlog],
            "inflight": {str(g): dataclasses.asdict(r)
                         for g, r in self._inflight.items()},
            "gmap": {str(g): [k, int(l)]
                     for g, (k, l) in self._gmap.items()},
            "completed": [dataclasses.asdict(c)
                          for c in self.completed],
            "shed": [dataclasses.asdict(s) for s in self.shed],
            "tokens": dict(self._tokens),
            "token_waits": {str(k): int(v)
                            for k, v in self._token_waits.items()},
            "client_state": dict(self.client_state),
            # round 22: the lease ledger rides the cut — a resumed
            # pool replays the identical lease decisions (streaks are
            # the hysteresis state; given/recv replay the counters)
            "lease": {
                "idle_streak": {k: int(v) for k, v in
                                self._idle_streak.items()},
                "given": {k: int(v) for k, v in
                          self._lease_given.items()},
                "recv": {k: int(v) for k, v in
                         self._lease_recv.items()},
                "boundaries": int(self._boundaries),
                "overlapped": int(self._overlapped),
                "boundary_wall": float(self._boundary_wall),
                "overlap_wall": float(self._overlap_wall),
            },
        }
        writer = None
        if self.checkpoint_background:
            from ppls_tpu.runtime.checkpoint import background_writer
            writer = background_writer()
        # manifest-LAST discipline in background mode: the per-engine
        # cut files above were submitted to the same single-thread
        # FIFO writer (each engine was built with
        # checkpoint_background), so the manifest job below cannot
        # land before them — and the GC job after it cannot run
        # before the manifest is durable
        save_family_checkpoint(
            self.checkpoint_path,
            identity=self._manifest_identity(engines_meta),
            bag_cols={}, count=0, acc=np.zeros((2, 1)),
            totals=totals, writer=writer)
        stale = self._cut_files - new_files

        def _gc(paths=frozenset(stale)):
            for p in paths:
                try:
                    os.unlink(p)
                except OSError:
                    pass

        if writer is not None:
            writer.submit(_gc)
        else:
            _gc()
        self._cut_files = new_files
        self.telemetry.event(
            "dispatch_checkpoint", turn=self.turn, cut=cut,
            engines=len(engines_meta), backlog=len(self._backlog),
            inflight=len(self._inflight),
            completed=len(self.completed))
        if self.fault_injector is not None:
            # the injector mutates the manifest FILE — a background
            # cut must be fully durable before the hook fires
            if writer is not None:
                writer.flush()
            self.fault_injector.on_checkpoint_write(
                self.checkpoint_path)

    @classmethod
    def resume(cls, checkpoint_path: str, family: str,
               **kwargs) -> "EngineDispatcher":
        """Rebuild the whole pool from its last coordinated cut: the
        manifest's engine-key set must match the per-engine files
        (each checked against its own checkpoint identity AND the
        stamped pool id), the routing ledger and grid maps restore,
        live engines resume in their stored round-robin order, and
        the continued mixed stream replays bit-identically. A
        manifest from a different pool configuration — or one whose
        engine-key set differs from its per-engine snapshots —
        refuses with the checkpoint module's refusing-to-blend
        contract."""
        from ppls_tpu.runtime.checkpoint import (
            load_family_checkpoint, peek_checkpoint_identity)
        disp = cls(family, checkpoint_path=checkpoint_path, **kwargs)
        stored = peek_checkpoint_identity(checkpoint_path)
        want = disp._manifest_identity_base()
        got_base = {k: v for k, v in stored.items() if k != "keys"}
        if got_base != want:
            diff = {k: (got_base.get(k), want.get(k))
                    for k in set(got_base) | set(want)
                    if got_base.get(k) != want.get(k)}
            raise ValueError(
                f"dispatch manifest {checkpoint_path!r} belongs to a "
                f"different pool configuration; refusing to blend "
                f"(stored vs requested): {diff}")
        ident = dict(want, keys=stored.get("keys", ""))
        _, _, _, totals = load_family_checkpoint(checkpoint_path,
                                                 ident)
        engines_meta = totals["engines"]
        listed = ",".join(sorted(engines_meta))
        if listed != ident["keys"]:
            raise ValueError(
                f"dispatch manifest {checkpoint_path!r} engine-key "
                f"set differs from its per-engine snapshot list "
                f"({ident['keys']!r} vs {listed!r}); refusing to "
                f"blend")
        disp.pool_id = totals["pool_id"]
        disp.turn = int(totals["turn"])
        disp._next_grid = int(totals["next_grid"])
        disp._cut = int(totals["cut"])
        disp._park_seq = int(totals["park_seq"])
        disp._last_used = {k: int(v)
                           for k, v in totals["last_used"].items()}
        disp._taken = {k: [int(v[0]), int(v[1])]
                       for k, v in totals["taken"].items()}
        disp._gmap = {int(g): (v[0], int(v[1]))
                      for g, v in totals["gmap"].items()}
        disp._tokens = {str(k): float(v)
                        for k, v in totals["tokens"].items()}
        disp._token_waits = {int(k): int(v)
                             for k, v in totals["token_waits"]
                             .items()}
        disp.client_state = dict(totals.get("client_state", {}))
        # round 22: lease ledger (absent in round-21 manifests — an
        # empty ledger is exactly the pre-lease state). Cumulative
        # counters replay like the retirement ledger below.
        lease = totals.get("lease") or {}
        disp._idle_streak = {k: int(v) for k, v in
                             lease.get("idle_streak", {}).items()}
        disp._lease_given = {k: int(v) for k, v in
                             lease.get("given", {}).items()}
        disp._lease_recv = {k: int(v) for k, v in
                            lease.get("recv", {}).items()}
        disp._boundaries = int(lease.get("boundaries", 0))
        disp._overlapped = int(lease.get("overlapped", 0))
        disp._boundary_wall = float(lease.get("boundary_wall", 0.0))
        disp._overlap_wall = float(lease.get("overlap_wall", 0.0))
        for k, v in disp._lease_given.items():
            disp._c_lease_donated.labels(engine=k).inc(v)
        for k, v in disp._lease_recv.items():
            disp._c_lease_recv.labels(engine=k).inc(v)
        disp._c_boundary.inc(disp._boundaries)
        disp._c_boundary_overlap.inc(disp._overlapped)

        def _theta_in(v):
            return tuple(v) if isinstance(v, list) else v

        def _preq_in(d):
            return PoolRequest(
                grid=int(d["grid"]), key=d["key"],
                theta=_theta_in(d["theta"]),
                bounds=tuple(d["bounds"]),
                submit_turn=int(d["submit_turn"]),
                submit_t=time.perf_counter(),
                tenant=d.get("tenant", "default"),
                priority=int(d.get("priority", 1)),
                deadline_turns=d.get("deadline_turns"),
                routed_turn=d.get("routed_turn"))

        disp._backlog = [_preq_in(d) for d in totals["backlog"]]
        disp._inflight = {int(g): _preq_in(d)
                          for g, d in totals["inflight"].items()}
        disp.completed = [CompletedRequest(
            **{k: (tuple(v) if k == "bounds"
                   else _theta_in(v) if k == "theta" else v)
               for k, v in d.items()}) for d in totals["completed"]]
        disp.shed = [ShedRecord(
            **{k: (tuple(v) if k == "bounds"
                   else _theta_in(v) if k == "theta" else v)
               for k, v in d.items()}) for d in totals["shed"]]
        # pool registry replay: the deterministic ledger rebuilds the
        # pool-scope counters/histograms exactly (same discipline as
        # the engine's _replay_registry)
        for g in disp.completed:
            keystr = disp._gmap.get(g.rid, (None,))[0]
            disp._account_pool_retirement(g, keystr)
        for s in disp.shed:
            disp._c_shed.labels(tenant=s.tenant,
                                reason=s.reason).inc()
        # wrappers + rid maps BEFORE engine resumes (the engines
        # re-open their request spans through the maps)
        for keystr, m in totals["rid_maps"].items():
            wrapper = _EngineTelemetry(disp, keystr)
            wrapper._rid_map = {int(l): int(g) for l, g in m.items()}
            wrapper._local_entries = int(
                totals.get("local_entries", {}).get(keystr, 0))
            disp._wrappers[keystr] = wrapper
        # live rids re-open their pool grid spans in the appended
        # segment — backlog here, inflight through the engine resumes
        # below (the wrapper routes them to the same grid spans)
        for preq in (disp._backlog + sorted(
                disp._inflight.values(), key=lambda r: r.grid)):
            disp._grid_spans[preq.grid] = \
                disp.telemetry.request_span(
                    preq.grid, tenant=preq.tenant,
                    priority=preq.priority,
                    submit_phase=preq.submit_turn, engine=preq.key)
        d = disp._pool_path()
        for keystr in totals["order"]:
            info = engines_meta[keystr]
            key = EngineKey.parse(keystr)
            wrapper = disp._wrappers[keystr]
            eng = StreamEngine.resume(
                os.path.join(d, info["path"]), family, key.eps,
                telemetry=wrapper, **disp._engine_kwargs(key))
            if eng.client_state.get("pool_id") != disp.pool_id:
                raise ValueError(
                    f"engine snapshot {info['path']!r} belongs to a "
                    f"different pool (stored "
                    f"{eng.client_state.get('pool_id')!r}, manifest "
                    f"{disp.pool_id!r}); refusing to blend")
            eng.checkpoint_path = None
            disp._engines[keystr] = eng
            disp._order.append(keystr)
            disp._taken.setdefault(keystr, [0, 0])
        for keystr, info in engines_meta.items():
            if info["state"] != "parked":
                continue
            disp._parked[keystr] = {
                "path": os.path.join(d, info["path"]),
                "seq": int(info.get("seq", 0)),
                "idle": bool(info["idle"]),
                "phase": int(info["phase"]),
                "pending": int(info["pending"]),
                "resident": int(info["resident"]),
                "totals": info["totals"],
            }
        disp._cut_files = {
            os.path.join(d, info["path"])
            for info in engines_meta.values()}
        if disp._slo is not None:
            disp._slo.seed_base(disp.turn)
        disp._publish_gauges()
        disp.telemetry.event(
            "dispatch_resume", turn=disp.turn,
            live=len(disp._engines), parked=len(disp._parked),
            backlog=len(disp._backlog),
            inflight=len(disp._inflight),
            completed=len(disp.completed))
        return disp
