"""Seeded fault injection: deterministic chaos for the recovery stack.

The reference program has exactly one failure story — any rank dying
hangs the farmer's blocking recv forever (``aquadPartA.c:145``) — and
until round 14 this reproduction's recovery paths (watchdog resume,
checkpoint resume, and now resize-resume + quarantine) were proved
only by hand-written hang tests. This module makes failure a FIRST-
CLASS, REPRODUCIBLE input: a :class:`FaultPlan` is a seeded schedule
of fault events, and a :class:`FaultInjector` fires them at the
boundaries the engines already own — phase open/close, checkpoint
write, stream admission — so every recovery path can be exercised
end-to-end, deterministically, in CI.

Fault taxonomy (``FAULT_KINDS``):

* ``chip_loss``     — raise :class:`guard.ChipLossError` at a phase
  boundary: the supervisor resize-resumes the latest snapshot onto the
  surviving mesh (the elastic ``mesh_resize`` checkpoint rule);
* ``crash``         — raise :class:`guard.InjectedCrash` at a phase
  boundary: classified transient, recovered by backoff + resume;
* ``hang``          — block the engine thread at a phase boundary (a
  wedged device): the watchdog deadline fires and the supervisor
  resumes. Default ``seconds`` is effectively forever — the hung
  attempt's daemonized thread must NOT wake up mid-recovery and race
  the resumed run (guard.py's deadline-sizing contract);
* ``straggler``     — sleep ``seconds`` at a phase boundary and
  continue: a slow chip/host, visible as wall time without any state
  damage (the flight recorder's per-chip work-share detector covers
  the on-mesh form);
* ``nan_poison``    — corrupt one admitted request's theta payload to
  NaN AFTER submit-time validation (poison that slipped past the
  gate): the engine genuinely computes with it, the slot's area goes
  non-finite, and the quarantine retire path must contain it while
  healthy co-resident requests retire normally;
* ``sigterm``       — deliver SIGTERM to this process at a phase
  boundary (round 16): the deterministic spelling of the orchestrator
  kill the zero-downtime-restart contract is tested against — the
  serve loop's GracefulShutdown must final-checkpoint, close the span
  timeline balanced, and exit 0, and the ``serve --checkpoint``
  restart must resume with zero lost acknowledged requests;
* ``host_loss``     — SIGKILL a chosen WORKER PROCESS at a phase
  boundary (round 18): the cluster coordinator installs
  ``host_kill_fn`` so the event kills a real process (the loss then
  surfaces at the next RPC, like a real dead host); without the hook
  (single-process engines) it raises :class:`guard.HostLossError`
  directly. Opt-in like ``sigterm`` — deliberately excluded from the
  seeded-schedule pool so existing seeds keep their schedules;
* ``ckpt_truncate`` — truncate the snapshot file just written (a
  crash mid-upload / out-of-disk shape);
* ``ckpt_corrupt``  — flip one byte in the middle of the snapshot
  just written (bit rot): both must surface as
  :class:`runtime.checkpoint.CheckpointCorruptError` at resume, never
  as unpickled garbage.

Every injected fault emits a ``fault_injected`` telemetry event and
counts into ``ppls_faults_injected_total{kind}``, so a chaos run's
recovery timeline is attribution-backed: each recovery in the events
file pairs with the fault that caused it.

Arming: ``ppls-tpu serve --fault-plan SPEC`` or ``PPLS_FAULT_PLAN``
(CLI wins). SPEC is inline JSON (a list of event objects), ``@file``
holding the same, or ``seed:<n>[:<k>]`` for a generated schedule of
``k`` events drawn deterministically from seed ``n``
(:meth:`FaultPlan.seeded`).
"""

from __future__ import annotations

import dataclasses
import json
import os
import threading
import time
from typing import List, Optional

import numpy as np

from ppls_tpu.runtime.guard import (ChipLossError, HostLossError,
                                    InjectedCrash)

FAULT_KINDS = ("chip_loss", "crash", "hang", "straggler", "nan_poison",
               "ckpt_truncate", "ckpt_corrupt", "sigterm",
               "host_loss")

# kinds keyed on the PHASE index (fire at a phase boundary); the
# others key on the request rid (nan_poison) or the checkpoint-write
# index (ckpt_*). NOTE: sigterm and host_loss (round 18) are
# phase-keyed too but deliberately NOT in PHASE_KINDS — seeded
# schedule generation draws from PHASE_KINDS, and appending there
# would silently change every existing seed's schedule (the
# same-seed-same-schedule contract, regression-pinned in
# tests/test_faults.py).
PHASE_KINDS = ("chip_loss", "crash", "hang", "straggler")
_EDGE_KINDS = PHASE_KINDS + ("sigterm", "host_loss")

# an injected hang must outlive any plausible watchdog deadline: the
# wedged thread is daemonized and must sleep until process exit, never
# wake mid-recovery and race the resumed run on the snapshot path
HANG_FOREVER_S = 1 << 20

ENV_FAULT_PLAN = "PPLS_FAULT_PLAN"


@dataclasses.dataclass
class FaultEvent:
    """One scheduled fault. ``at`` is the phase index for
    :data:`PHASE_KINDS`, the request rid for ``nan_poison``, and the
    checkpoint-write ordinal for ``ckpt_truncate``/``ckpt_corrupt``.
    ``edge`` picks the phase-open or phase-close boundary for
    phase-keyed kinds. Each event fires exactly once."""

    kind: str
    at: int
    chip: Optional[int] = None        # chip_loss: which chip dies
    #                                   (default: the highest index)
    seconds: float = 0.0              # hang/straggler duration
    edge: str = "open"                # "open" | "close"
    fired: bool = False

    def __post_init__(self):
        if self.kind not in FAULT_KINDS:
            raise ValueError(
                f"unknown fault kind {self.kind!r}; expected one of "
                f"{FAULT_KINDS}")
        if self.edge not in ("open", "close"):
            raise ValueError(
                f"fault edge must be 'open' or 'close', got "
                f"{self.edge!r}")
        self.at = int(self.at)
        if self.kind == "hang" and not self.seconds:
            self.seconds = float(HANG_FOREVER_S)

    def describe(self) -> dict:
        d = {"kind": self.kind, "at": self.at}
        if self.chip is not None:
            d["chip"] = int(self.chip)
        if self.seconds:
            d["seconds"] = float(self.seconds)
        if self.edge != "open":
            d["edge"] = self.edge
        return d


class FaultPlan:
    """An ordered, seeded schedule of :class:`FaultEvent`."""

    def __init__(self, events: List[FaultEvent], seed: Optional[int] = None):
        self.events = list(events)
        self.seed = seed

    def __len__(self) -> int:
        return len(self.events)

    def to_json(self) -> str:
        return json.dumps([e.describe() for e in self.events])

    @classmethod
    def from_events(cls, specs, seed: Optional[int] = None
                    ) -> "FaultPlan":
        return cls([FaultEvent(**d) for d in specs], seed=seed)

    @classmethod
    def seeded(cls, seed: int, n_events: int = 4, horizon: int = 12,
               kinds=PHASE_KINDS + ("nan_poison",)) -> "FaultPlan":
        """Deterministic schedule generation: ``n_events`` faults drawn
        from ``kinds`` with phases/rids in ``[1, horizon)``. The same
        seed always yields the same schedule (``np.random.default_rng``
        is sequence-stable), which is the whole point: a chaos failure
        reproduces from its seed."""
        rng = np.random.default_rng(int(seed))
        events = []
        for _ in range(int(n_events)):
            kind = str(rng.choice(list(kinds)))
            at = int(rng.integers(1, max(int(horizon), 2)))
            ev = FaultEvent(kind=kind, at=at)
            if kind == "straggler":
                ev.seconds = float(rng.integers(1, 4)) * 0.05
            events.append(ev)
        events.sort(key=lambda e: (e.at, e.kind))
        return cls(events, seed=int(seed))

    @classmethod
    def from_spec(cls, spec: Optional[str]) -> Optional["FaultPlan"]:
        """Parse a ``--fault-plan`` / ``PPLS_FAULT_PLAN`` spec: inline
        JSON list, ``@file.json``, or ``seed:<n>[:<k>]``. None/empty
        disarms (returns None)."""
        if not spec:
            return None
        spec = spec.strip()
        if spec.startswith("seed:"):
            parts = spec.split(":")
            seed = int(parts[1])
            n = int(parts[2]) if len(parts) > 2 else 4
            return cls.seeded(seed, n_events=n)
        if spec.startswith("@"):
            with open(spec[1:], encoding="utf-8") as fh:
                data = json.load(fh)
        else:
            data = json.loads(spec)
        if isinstance(data, dict):
            data = data.get("events", [])
        return cls.from_events(data)

    @classmethod
    def from_env(cls) -> Optional["FaultPlan"]:
        return cls.from_spec(os.environ.get(ENV_FAULT_PLAN))


class FaultInjector:
    """Consults a :class:`FaultPlan` at the engine boundaries and fires
    matching events (once each), emitting the attribution trail.

    The injector OUTLIVES engine attempts: the serve CLI builds one per
    run and threads it through every engine it constructs, so an event
    consumed before a crash does not re-fire in the resumed attempt.
    """

    def __init__(self, plan: FaultPlan, telemetry=None):
        self.plan = plan
        self.telemetry = telemetry
        self.ckpt_writes = 0
        self._lock = threading.Lock()
        # round 18: the cluster coordinator installs its real-process
        # killer here so host_loss events SIGKILL a worker; None (the
        # single-process engines) raises HostLossError directly
        self.host_kill_fn = None

    # -- internals ---------------------------------------------------------

    def _take(self, kinds, at: int, edge: Optional[str] = None
              ) -> List[FaultEvent]:
        """Atomically claim the unfired events matching (kinds, at,
        edge). Claiming before firing keeps a fault one-shot even when
        a wedged attempt's daemon thread later reaches the same
        boundary as the recovered run."""
        with self._lock:
            out = []
            for ev in self.plan.events:
                if ev.fired or ev.kind not in kinds or ev.at != at:
                    continue
                if edge is not None and ev.kind in _EDGE_KINDS \
                        and ev.edge != edge:
                    continue
                ev.fired = True
                out.append(ev)
            return out

    def _emit(self, ev: FaultEvent, **ctx) -> None:
        if self.telemetry is not None:
            self.telemetry.event("fault_injected", **ev.describe(),
                                 **ctx)
            self.telemetry.registry.counter(
                "ppls_faults_injected_total",
                "fault-plan events fired, by kind",
                ("kind",)).labels(kind=ev.kind).inc()

    # -- engine hooks ------------------------------------------------------

    def _phase_edge(self, phase: int, edge: str, n_dev: int) -> None:
        for ev in self._take(_EDGE_KINDS, int(phase), edge=edge):
            self._emit(ev, phase=int(phase))
            if ev.kind == "sigterm":
                # the orchestrator-kill shape: deliver the real signal
                # so the serve loop's GracefulShutdown machinery (not
                # a test double) handles it at the next boundary
                import signal as _signal
                os.kill(os.getpid(), _signal.SIGTERM)
            elif ev.kind == "straggler":
                time.sleep(ev.seconds)
            elif ev.kind == "hang":
                # a wedged device: block this (daemonizable) thread
                # until past any watchdog; Event.wait, not time.sleep,
                # so no-op sleep monkeypatches in tests cannot defuse it
                threading.Event().wait(ev.seconds)
            elif ev.kind == "crash":
                raise InjectedCrash(
                    f"fault plan: phase-boundary crash at phase "
                    f"{phase}")
            elif ev.kind == "chip_loss":
                chip = ev.chip if ev.chip is not None else n_dev - 1
                raise ChipLossError(chip, n_dev,
                                    detail="fault plan injection")
            elif ev.kind == "host_loss":
                if self.host_kill_fn is not None:
                    # kill a REAL worker process: the loss surfaces
                    # at the coordinator's next RPC to it, exactly
                    # like an un-injected dead host
                    self.host_kill_fn(ev.chip)
                else:
                    proc = ev.chip if ev.chip is not None \
                        else n_dev - 1
                    raise HostLossError(proc, n_dev,
                                        detail="fault plan injection")

    def on_phase_open(self, phase: int, n_dev: int = 1) -> None:
        """Phase-open boundary (before admission): crashes here model
        the worst resume point — admissions scheduled for this phase
        replay in the recovered run."""
        self._phase_edge(phase, "open", n_dev)

    def on_phase_close(self, phase: int, n_dev: int = 1) -> None:
        self._phase_edge(phase, "close", n_dev)

    def on_admit(self, rid: int) -> bool:
        """Stream-admission boundary: True = poison this request's
        theta payload to NaN (post-validation — poison that slipped the
        gate)."""
        evs = self._take(("nan_poison",), int(rid))
        for ev in evs:
            self._emit(ev, rid=int(rid))
        return bool(evs)

    def on_checkpoint_write(self, path: str) -> None:
        """Checkpoint-write boundary: damage the snapshot JUST written
        (after its atomic rename — the damage models later media rot /
        mid-upload truncation, not a torn write)."""
        with self._lock:
            idx = self.ckpt_writes
            self.ckpt_writes += 1
        for ev in self._take(("ckpt_truncate", "ckpt_corrupt"), idx):
            self._emit(ev, path=path, write_index=idx)
            size = os.path.getsize(path)
            if ev.kind == "ckpt_truncate":
                with open(path, "r+b") as fh:
                    fh.truncate(max(size // 2, 1))
            else:
                with open(path, "r+b") as fh:
                    fh.seek(size // 2)
                    b = fh.read(1)
                    fh.seek(size // 2)
                    fh.write(bytes([b[0] ^ 0xFF]) if b else b"\xff")
