"""Hang/transient-failure guards for device-touching sections.

Promoted from ``bench.py`` (VERDICT r5 #4): the bench grew a watchdog
deadline + bounded transient-infra retry after round 3 lost a whole
round to one tunnel drop, and round 4's verdict noted a wedged device
blocks ``jax.device_get`` forever — the same failure shape as the
reference farmer's blocking recv (``aquadPartA.c:145``), which has no
recovery at all. Those guards are framework-level concerns, not bench
trivia: the CLI's ``--watchdog`` flag and any long-running engine
driver need exactly the same protection, so they live here and
``bench.py`` re-exports them.

Policy (unchanged from the bench):

* ``with_deadline(fn, seconds)`` runs ``fn`` in a worker thread and
  raises :class:`HangTimeout` on expiry. The hung thread cannot be
  killed — it is left daemonized; a truly wedged device times out the
  retry's fresh attempt too, so the caller reports a failure instead of
  hanging forever.
* ``with_retry(fn, attempts_log)`` retries ONLY transient
  infrastructure errors (:func:`is_transient` — tunnel/connection/
  INTERNAL strings, never this framework's own numerical guard
  messages) up to ``MAX_ATTEMPTS`` times under the deadline.
  ``FloatingPointError`` (the engines' NaN guard) always propagates.
"""

from __future__ import annotations

import os
import sys
import threading
import time

# Substrings that mark an exception as transient INFRASTRUCTURE (the
# tunneled-device failure modes observed across rounds), never produced
# by this framework's own numerical guards (those say "non-finite",
# "did not converge", "overflowed", "mismatch").
TRANSIENT_MARKERS = (
    "remote_compile", "response body", "read body", "connection",
    "Connection", "socket", "tunnel", "INTERNAL:", "UNAVAILABLE",
    "DEADLINE_EXCEEDED", "ABORTED", "heartbeat", "Broken pipe",
    "watchdog deadline",
)
MAX_ATTEMPTS = 3


class HangTimeout(RuntimeError):
    """A device section exceeded its watchdog deadline (hung device)."""


class InjectedCrash(RuntimeError):
    """A fault plan fired a phase-boundary crash (runtime/faults.py).
    Classified RECOVERABLE: the engine state on disk is exactly a
    crashed run's, so the supervisor resumes from the last snapshot."""


class ChipLossError(RuntimeError):
    """A chip (or host) left the mesh mid-run. The surviving-mesh size
    rides on the exception so the supervisor can resize-resume; on real
    hardware this is the classified face of a dead-device XLA error, in
    fault-plan runs it is injected at a phase boundary."""

    def __init__(self, chip: int, n_dev: int, detail: str = ""):
        self.chip = int(chip)
        self.n_dev = int(n_dev)
        self.surviving = max(int(n_dev) - 1, 0)
        super().__init__(
            f"chip {chip} lost from the {n_dev}-chip mesh"
            + (f" ({detail})" if detail else "")
            + f"; {self.surviving} chip(s) survive")


class HostLossError(ChipLossError):
    """A whole WORKER PROCESS (a host) left the cluster mid-run
    (round 18). The chip-level fields are reused at process
    granularity: ``chip`` is the lost process id, ``n_dev`` the
    process count it left, ``surviving`` the count after the loss.
    On the local cluster this is the classified face of a dead worker
    socket (or a fault-plan SIGKILL); on a TPU pod it is a dead
    host's coordination-service eviction."""

    def __init__(self, process: int, n_processes: int,
                 detail: str = ""):
        self.chip = int(process)
        self.n_dev = int(n_processes)
        self.surviving = max(int(n_processes) - 1, 0)
        RuntimeError.__init__(
            self,
            f"host (worker process) {process} lost from the "
            f"{n_processes}-process cluster"
            + (f" ({detail})" if detail else "")
            + f"; {self.surviving} process(es) survive")

    @property
    def process(self) -> int:
        return self.chip


class RetryBudgetExhausted(RuntimeError):
    """The retry loop's total-deadline budget ran out before the next
    backoff could be paid; carries the last underlying failure."""


def is_transient(msg: str) -> bool:
    """True when an exception message matches a known transient
    infrastructure failure (retry) rather than a numerical one (fail)."""
    return any(marker in msg for marker in TRANSIENT_MARKERS)


def classify_failure(exc: BaseException) -> str:
    """Failure taxonomy of the round-14 supervisor:

    * ``host_loss``  — a :class:`HostLossError` (round 18): a worker
      PROCESS died; recover by discovering the surviving topology and
      re-dealing the lost host's outstanding work onto it;
    * ``chip_loss``  — a :class:`ChipLossError`: recover by resuming the
      latest snapshot onto the surviving (smaller) mesh;
    * ``poison``     — a ``FloatingPointError`` (the engines' NaN
      guard): data, not infrastructure — never retried; engines running
      with quarantine enabled retire the poisoned request as a failed
      record instead of surfacing this at all;
    * ``transient``  — watchdog expiry, injected phase-boundary
      crashes, and the tunnel/connection failure strings of
      :data:`TRANSIENT_MARKERS`: recover by deterministic exponential
      backoff + resume;
    * ``fatal``      — everything else (bugs, sizing errors): propagate.
    """
    if isinstance(exc, HostLossError):
        return "host_loss"
    if isinstance(exc, ChipLossError):
        return "chip_loss"
    if isinstance(exc, FloatingPointError):
        return "poison"
    if isinstance(exc, RetryBudgetExhausted):
        # the budget is already spent — its message EMBEDS the last
        # transient failure's text, so the marker scan below would
        # misread it as retryable and retry past the exhausted budget
        return "fatal"
    if isinstance(exc, (HangTimeout, InjectedCrash)):
        return "transient"
    if is_transient(f"{type(exc).__name__}: {exc}"):
        return "transient"
    return "fatal"


def backoff_seconds(attempt: int, base: float = 10.0,
                    cap: float = 120.0) -> float:
    """DETERMINISTIC exponential backoff: base * 2^(attempt-1), capped.
    No jitter by design — recovery schedules must replay identically
    under a seeded fault plan (the same reproducibility contract as
    every other schedule in this package)."""
    return min(float(base) * (2.0 ** (max(int(attempt), 1) - 1)),
               float(cap))


def default_watchdog_seconds() -> float:
    """Deadline per device-section attempt. Generous: a cold compile of
    the full cycle program takes ~2 min on this rig; a hang blocks
    forever. Overridable for tests via PPLS_BENCH_WATCHDOG_S."""
    return float(os.environ.get("PPLS_BENCH_WATCHDOG_S", "900"))


def _log(msg):
    print(msg, file=sys.stderr, flush=True)


def with_deadline(fn, seconds: float, what: str = "device section"):
    """Run ``fn()`` in a worker thread with a deadline.

    On expiry raises :class:`HangTimeout` (classified transient by
    :func:`is_transient` via its message). The hung thread cannot be
    killed — it is left daemonized; if the device is truly wedged the
    retry's fresh attempt times out too and the caller records a failure
    instead of eating the whole run (VERDICT r4 #5; the reference's
    analogous hang is the farmer's blocking recv, aquadPartA.c:145,
    which has no recovery at all).
    """
    box = {}

    def worker():
        try:
            box["value"] = fn()
        except BaseException as e:  # noqa: BLE001 — re-raised in caller
            box["error"] = e

    t = threading.Thread(target=worker, daemon=True)
    t.start()
    t.join(seconds)
    if t.is_alive():
        raise HangTimeout(
            f"{what}: watchdog deadline {seconds:.0f}s exceeded "
            f"(hung device run?)")
    if "error" in box:
        raise box["error"]
    return box.get("value")


def _count_retry(reason: str) -> None:
    """Registry face of the retry loop (round 14): every retried
    failure increments ``ppls_retries_total{reason}`` on the process
    default telemetry, so recovery activity is a scrapeable signal and
    not only a stderr line."""
    from ppls_tpu.obs.telemetry import default_telemetry
    default_telemetry().registry.counter(
        "ppls_retries_total",
        "retried transient failures by classified reason",
        ("reason",)).labels(reason=reason).inc()


def with_retry(fn, attempts_log, what="device section",
               deadline: float = None, log=_log,
               backoff_base: float = 10.0, backoff_cap: float = 120.0,
               total_deadline: float = None):
    """Run ``fn`` under the watchdog deadline with up to MAX_ATTEMPTS
    tries, retrying ONLY transient infra errors (including watchdog
    expiry). FloatingPointError (the engines' NaN guard) and any
    non-transient exception propagate immediately. Each retried error is
    appended to ``attempts_log`` for the caller's record.

    Round 14: the retry delay is DETERMINISTIC exponential backoff
    (:func:`backoff_seconds` — base * 2^(attempt-1), capped; the
    historical fixed 10 s is attempt 1 of the default schedule), every
    retry counts into ``ppls_retries_total{reason}``, and
    ``total_deadline`` bounds the WHOLE loop: when the elapsed wall
    plus the next backoff would exceed it, the loop raises
    :class:`RetryBudgetExhausted` instead of sleeping into a budget it
    cannot keep."""
    if deadline is None:
        deadline = default_watchdog_seconds()
    t_start = time.monotonic()
    for attempt in range(1, MAX_ATTEMPTS + 1):
        if attempt == 1 and os.environ.pop("PPLS_BENCH_INJECT_TRANSIENT",
                                           None):
            # test hook, consumed on first use so it injects exactly one
            # failure per process: prove a first-attempt tunnel drop
            # still yields a valid record (VERDICT r3 #1 criterion)
            attempts_log.append("injected: INTERNAL: simulated tunnel drop")
            log(f"[guard] {what}: injected transient error "
                f"(attempt 1/{MAX_ATTEMPTS}); retrying")
            _count_retry("injected")
            continue
        target = fn
        if attempt == 1 and os.environ.pop("PPLS_BENCH_INJECT_HANG", None):
            # test hook: a first-attempt hang must be caught by the
            # watchdog and retried, not wedge the round (VERDICT r4 #5)
            def target():
                time.sleep(deadline + 30)
        try:
            return with_deadline(target, deadline, what)
        except FloatingPointError:
            raise                      # numerical NaN guard: never retry
        except Exception as e:         # noqa: BLE001 — classified below
            msg = f"{type(e).__name__}: {e}"
            if is_transient(msg) and attempt < MAX_ATTEMPTS:
                delay = backoff_seconds(attempt, backoff_base,
                                        backoff_cap)
                if total_deadline is not None and \
                        time.monotonic() - t_start + delay \
                        > total_deadline:
                    raise RetryBudgetExhausted(
                        f"{what}: total retry deadline "
                        f"{total_deadline:.0f}s would be exceeded by "
                        f"the next {delay:.0f}s backoff (attempt "
                        f"{attempt}/{MAX_ATTEMPTS}); last failure: "
                        f"{msg[:200]}") from e
                attempts_log.append(msg[:300])
                _count_retry("watchdog" if isinstance(e, HangTimeout)
                             else "transient")
                log(f"[guard] {what}: transient infra error "
                    f"(attempt {attempt}/{MAX_ATTEMPTS}): "
                    f"{msg[:120]} ... retrying in {delay:.0f}s")
                time.sleep(delay)
                continue
            raise
    raise RuntimeError(f"{what}: all {MAX_ATTEMPTS} attempts consumed "
                       f"by injected test hooks")


def run_with_watchdog(run_fn, seconds: float, what: str = "engine run",
                      resume_fn=None, log=_log, telemetry=None,
                      checkpoint_path: str = None):
    """CLI-level watchdog: run an engine under a deadline; on expiry,
    fall back to ``resume_fn`` (typically a checkpoint resume) once.

    The shape ``timeout + checkpoint => resume``: a checkpointed engine
    leaves its last leg snapshot on disk, so when the live run wedges,
    one fresh attempt that RESUMES from the snapshot recovers all work
    up to the last leg boundary instead of replaying from scratch. With
    no ``resume_fn`` the timeout simply propagates.

    DEADLINE SIZING CONTRACT: a timed-out attempt cannot be killed —
    its daemonized thread keeps running (with_deadline). If ``seconds``
    is shorter than a LEGITIMATE run (e.g. a cold compile), the stale
    attempt and the resume race on the same device queue and, for a
    checkpointed run, on the same snapshot path — the stale attempt
    can overwrite the resume's newer snapshot with an older one. Set
    the deadline well above the worst-case healthy run time (this is a
    hang detector, not a scheduler); the bench's 900 s default
    (PPLS_BENCH_WATCHDOG_S) was sized to cover a cold compile on the
    slowest observed rig.

    ``telemetry`` (round 14): when given, the recovery records its
    PROVENANCE in the events timeline — a ``watchdog_resume`` event
    naming which checkpoint the retry resumed from and which attempt
    this was — so a post-mortem can attribute every resumed leg.
    """
    try:
        return with_deadline(run_fn, seconds, what)
    except HangTimeout as e:
        if resume_fn is None:
            raise
        log(f"[guard] {what}: {e}; resuming from checkpoint")
        if telemetry is not None:
            telemetry.event(
                "watchdog_resume", what=what, attempt=2,
                deadline_s=float(seconds),
                checkpoint=checkpoint_path or "",
                reason=str(e)[:200])
        _count_retry("watchdog")
        return with_deadline(resume_fn, seconds, f"{what} (resume)")


class GracefulShutdown:
    """Cooperative SIGTERM/SIGINT handling for long-running serve
    loops (round 16, the zero-downtime-restart half).

    A context manager that installs signal handlers which only SET A
    FLAG — the loop checks :attr:`requested` at its phase boundaries
    and winds down in order: stop accepting ingest, write the final
    checkpoint (queue snapshot included), close the span timeline
    balanced, print the summary, exit 0. Killing mid-phase therefore
    never tears a span or loses an acknowledged request: the signal
    lands whenever it lands, the reaction happens at the next boundary.

    Installing a handler is only legal on the main thread; off the
    main thread (e.g. an engine attempt under ``with_deadline``'s
    worker) the manager degrades to a no-op flag holder so the serve
    loop can use it unconditionally.
    """

    def __init__(self, signals=None):
        import signal as _signal
        self._signal = _signal
        self.signals = tuple(signals) if signals is not None else (
            _signal.SIGTERM, _signal.SIGINT)
        self._old = {}
        self.signal_name: str = ""
        self._flag = threading.Event()
        self._installed = False

    @property
    def requested(self) -> bool:
        return self._flag.is_set()

    def _handler(self, signum, frame):
        try:
            self.signal_name = self._signal.Signals(signum).name
        except ValueError:
            self.signal_name = str(signum)
        self._flag.set()

    def __enter__(self) -> "GracefulShutdown":
        if threading.current_thread() is threading.main_thread():
            for s in self.signals:
                self._old[s] = self._signal.signal(s, self._handler)
            self._installed = True
        return self

    def __exit__(self, *exc) -> None:
        if self._installed:
            for s, old in self._old.items():
                self._signal.signal(s, old)
            self._old.clear()
            self._installed = False


class Supervisor:
    """Self-healing recovery loop around a resumable engine run.

    The round-14 growth of ``with_retry``/``run_with_watchdog``: one
    loop that CLASSIFIES every failure (:func:`classify_failure`) and
    applies the matching recovery instead of a single retry policy:

    * ``transient`` (watchdog expiry, injected phase-boundary crash,
      tunnel drops) — deterministic exponential backoff
      (:func:`backoff_seconds`), then re-run ``run_fn``. ``run_fn``
      must be SELF-RESUMING: a checkpointed serve loop that picks up
      its own latest snapshot (the CLI's make-engine shape);
    * ``chip_loss`` — call ``resize_fn(exc)``, which re-targets the
      run at the surviving mesh (resize-resume through the elastic
      ``mesh_resize`` checkpoint rule) and returns the replacement
      ``run_fn``; a loss on a 1-chip mesh is fatal (nothing survives);
    * ``poison`` — never retried here: engines running under this
      supervisor quarantine poisoned requests at the retire boundary
      (``StreamEngine(quarantine=True)``), so a surfacing
      ``FloatingPointError`` means quarantine was off — re-raised with
      that hint;
    * ``fatal`` — re-raised.

    Every classification and recovery emits a telemetry event
    (``supervisor_failure`` / ``supervisor_recovery``) and counts into
    ``ppls_supervisor_failures_total{kind}`` /
    ``ppls_supervisor_recoveries_total{action}`` on the supervisor's
    registry, so a fault-plan run's recovery story is fully
    attribution-backed.

    ``deadline`` (seconds) arms a per-attempt hang watchdog
    (:func:`with_deadline`) around every run; size it well above a
    healthy phase (the deadline-sizing contract above).
    ``total_deadline`` bounds the whole supervised run: when the next
    backoff would exceed it, :class:`RetryBudgetExhausted` is raised.
    """

    def __init__(self, run_fn, *, resize_fn=None,
                 deadline: float = None,
                 max_attempts: int = 2 * MAX_ATTEMPTS,
                 backoff_base: float = 1.0, backoff_cap: float = 60.0,
                 total_deadline: float = None,
                 telemetry=None, log=_log, sleep=time.sleep):
        self.run_fn = run_fn
        self.resize_fn = resize_fn
        self.deadline = deadline
        self.max_attempts = int(max_attempts)
        self.backoff_base = float(backoff_base)
        self.backoff_cap = float(backoff_cap)
        self.total_deadline = total_deadline
        self.telemetry = telemetry
        self._log = log
        self._sleep = sleep
        self.attempts = 0
        self.recoveries = []      # (kind, action) history, for tests

    def _event(self, name: str, **attrs) -> None:
        if self.telemetry is not None:
            self.telemetry.event(name, **attrs)

    def _count(self, metric: str, label: str, value: str) -> None:
        if self.telemetry is not None:
            self.telemetry.registry.counter(
                metric, "supervisor failure/recovery accounting",
                (label,)).labels(**{label: value}).inc()

    def _attempt(self):
        if self.deadline is not None:
            return with_deadline(self.run_fn, self.deadline,
                                 "supervised run")
        return self.run_fn()

    def _resize_with_backoff(self, exc, kind: str, t_start: float):
        """Round 18 (the resize-abort fix): the chip/host-loss resize
        recovery gets the SAME deterministic backoff-with-budget the
        transient arm has. A resize racing a slow worker teardown (its
        socket still half-open, its snapshot still renaming into
        place) used to abort the whole supervised run on the first
        failed ``resize_fn`` call; now each failed resize attempt is
        classified, backs off deterministically, and retries until the
        attempt/deadline budget is spent. Fatal/poison resize failures
        (a store-fit refusal, a corrupt-identity mismatch) still
        propagate immediately — only infrastructure-shaped failures
        are worth waiting out."""
        resize_attempt = 0
        while True:
            try:
                return self.resize_fn(exc)
            except BaseException as re:  # noqa: BLE001 — classified
                rkind = classify_failure(re)
                rmsg = f"{type(re).__name__}: {re}"
                self.attempts += 1
                self._event("supervisor_failure",
                            kind=f"resize_{rkind}",
                            attempt=self.attempts,
                            error=rmsg[:200])
                self._count("ppls_supervisor_failures_total",
                            "kind", f"resize_{rkind}")
                if rkind in ("fatal", "poison") \
                        or self.attempts >= self.max_attempts:
                    raise
                resize_attempt += 1
                delay = backoff_seconds(resize_attempt,
                                        self.backoff_base,
                                        self.backoff_cap)
                if self.total_deadline is not None and \
                        time.monotonic() - t_start + delay \
                        > self.total_deadline:
                    raise RetryBudgetExhausted(
                        f"supervised resize: total deadline "
                        f"{self.total_deadline:.0f}s would be "
                        f"exceeded by the next {delay:.0f}s backoff; "
                        f"last failure: {rmsg[:200]}") from re
                self._log(f"[supervisor] resize attempt "
                          f"{resize_attempt} failed ({rmsg[:120]}) "
                          f"... retrying in {delay:.1f}s")
                self.recoveries.append((kind, "resize_backoff"))
                self._event("supervisor_recovery",
                            action="resize_backoff",
                            backoff_s=delay, attempt=self.attempts)
                self._count("ppls_supervisor_recoveries_total",
                            "action", "resize_backoff")
                self._sleep(delay)

    def run(self):
        t_start = time.monotonic()
        backoff_attempt = 0       # resets after a successful resize
        while True:
            self.attempts += 1
            try:
                return self._attempt()
            except BaseException as e:  # noqa: BLE001 — classified
                kind = classify_failure(e)
                msg = f"{type(e).__name__}: {e}"
                self._event("supervisor_failure", kind=kind,
                            attempt=self.attempts, error=msg[:200])
                self._count("ppls_supervisor_failures_total", "kind",
                            kind)
                if kind in ("chip_loss", "host_loss") \
                        and self.resize_fn is not None:
                    surviving = getattr(e, "surviving", 0)
                    if surviving < 1:
                        self._log(f"[supervisor] {msg}: nothing "
                                  f"survives; giving up")
                        raise
                    self._log(f"[supervisor] {msg}: resize-resuming "
                              f"onto {surviving} survivor(s)")
                    self.run_fn = self._resize_with_backoff(
                        e, kind, t_start)
                    self.recoveries.append((kind, "resize_resume"))
                    self._event("supervisor_recovery",
                                action="resize_resume",
                                surviving=surviving,
                                attempt=self.attempts)
                    self._count("ppls_supervisor_recoveries_total",
                                "action", "resize_resume")
                    backoff_attempt = 0
                    continue
                if kind == "transient" \
                        and self.attempts < self.max_attempts:
                    backoff_attempt += 1
                    delay = backoff_seconds(
                        backoff_attempt, self.backoff_base,
                        self.backoff_cap)
                    if self.total_deadline is not None and \
                            time.monotonic() - t_start + delay \
                            > self.total_deadline:
                        raise RetryBudgetExhausted(
                            f"supervised run: total deadline "
                            f"{self.total_deadline:.0f}s would be "
                            f"exceeded by the next {delay:.0f}s "
                            f"backoff; last failure: {msg[:200]}"
                        ) from e
                    self._log(f"[supervisor] transient failure "
                              f"(attempt {self.attempts}/"
                              f"{self.max_attempts}): {msg[:120]} "
                              f"... resuming in {delay:.1f}s")
                    self.recoveries.append((kind, "backoff_resume"))
                    self._event("supervisor_recovery",
                                action="backoff_resume",
                                backoff_s=delay,
                                attempt=self.attempts)
                    self._count("ppls_supervisor_recoveries_total",
                                "action", "backoff_resume")
                    self._count("ppls_retries_total", "reason",
                                "supervisor")
                    self._sleep(delay)
                    continue
                if kind == "poison":
                    self._log(f"[supervisor] poisoned data surfaced "
                              f"({msg[:120]}); enable engine-level "
                              f"quarantine to retire it as a failed "
                              f"record instead")
                raise
