"""Hang/transient-failure guards for device-touching sections.

Promoted from ``bench.py`` (VERDICT r5 #4): the bench grew a watchdog
deadline + bounded transient-infra retry after round 3 lost a whole
round to one tunnel drop, and round 4's verdict noted a wedged device
blocks ``jax.device_get`` forever — the same failure shape as the
reference farmer's blocking recv (``aquadPartA.c:145``), which has no
recovery at all. Those guards are framework-level concerns, not bench
trivia: the CLI's ``--watchdog`` flag and any long-running engine
driver need exactly the same protection, so they live here and
``bench.py`` re-exports them.

Policy (unchanged from the bench):

* ``with_deadline(fn, seconds)`` runs ``fn`` in a worker thread and
  raises :class:`HangTimeout` on expiry. The hung thread cannot be
  killed — it is left daemonized; a truly wedged device times out the
  retry's fresh attempt too, so the caller reports a failure instead of
  hanging forever.
* ``with_retry(fn, attempts_log)`` retries ONLY transient
  infrastructure errors (:func:`is_transient` — tunnel/connection/
  INTERNAL strings, never this framework's own numerical guard
  messages) up to ``MAX_ATTEMPTS`` times under the deadline.
  ``FloatingPointError`` (the engines' NaN guard) always propagates.
"""

from __future__ import annotations

import os
import sys
import threading
import time

# Substrings that mark an exception as transient INFRASTRUCTURE (the
# tunneled-device failure modes observed across rounds), never produced
# by this framework's own numerical guards (those say "non-finite",
# "did not converge", "overflowed", "mismatch").
TRANSIENT_MARKERS = (
    "remote_compile", "response body", "read body", "connection",
    "Connection", "socket", "tunnel", "INTERNAL:", "UNAVAILABLE",
    "DEADLINE_EXCEEDED", "ABORTED", "heartbeat", "Broken pipe",
    "watchdog deadline",
)
MAX_ATTEMPTS = 3


class HangTimeout(RuntimeError):
    """A device section exceeded its watchdog deadline (hung device)."""


def is_transient(msg: str) -> bool:
    """True when an exception message matches a known transient
    infrastructure failure (retry) rather than a numerical one (fail)."""
    return any(marker in msg for marker in TRANSIENT_MARKERS)


def default_watchdog_seconds() -> float:
    """Deadline per device-section attempt. Generous: a cold compile of
    the full cycle program takes ~2 min on this rig; a hang blocks
    forever. Overridable for tests via PPLS_BENCH_WATCHDOG_S."""
    return float(os.environ.get("PPLS_BENCH_WATCHDOG_S", "900"))


def _log(msg):
    print(msg, file=sys.stderr, flush=True)


def with_deadline(fn, seconds: float, what: str = "device section"):
    """Run ``fn()`` in a worker thread with a deadline.

    On expiry raises :class:`HangTimeout` (classified transient by
    :func:`is_transient` via its message). The hung thread cannot be
    killed — it is left daemonized; if the device is truly wedged the
    retry's fresh attempt times out too and the caller records a failure
    instead of eating the whole run (VERDICT r4 #5; the reference's
    analogous hang is the farmer's blocking recv, aquadPartA.c:145,
    which has no recovery at all).
    """
    box = {}

    def worker():
        try:
            box["value"] = fn()
        except BaseException as e:  # noqa: BLE001 — re-raised in caller
            box["error"] = e

    t = threading.Thread(target=worker, daemon=True)
    t.start()
    t.join(seconds)
    if t.is_alive():
        raise HangTimeout(
            f"{what}: watchdog deadline {seconds:.0f}s exceeded "
            f"(hung device run?)")
    if "error" in box:
        raise box["error"]
    return box.get("value")


def with_retry(fn, attempts_log, what="device section",
               deadline: float = None, log=_log):
    """Run ``fn`` under the watchdog deadline with up to MAX_ATTEMPTS
    tries, retrying ONLY transient infra errors (including watchdog
    expiry). FloatingPointError (the engines' NaN guard) and any
    non-transient exception propagate immediately. Each retried error is
    appended to ``attempts_log`` for the caller's record."""
    if deadline is None:
        deadline = default_watchdog_seconds()
    for attempt in range(1, MAX_ATTEMPTS + 1):
        if attempt == 1 and os.environ.pop("PPLS_BENCH_INJECT_TRANSIENT",
                                           None):
            # test hook, consumed on first use so it injects exactly one
            # failure per process: prove a first-attempt tunnel drop
            # still yields a valid record (VERDICT r3 #1 criterion)
            attempts_log.append("injected: INTERNAL: simulated tunnel drop")
            log(f"[guard] {what}: injected transient error "
                f"(attempt 1/{MAX_ATTEMPTS}); retrying")
            continue
        target = fn
        if attempt == 1 and os.environ.pop("PPLS_BENCH_INJECT_HANG", None):
            # test hook: a first-attempt hang must be caught by the
            # watchdog and retried, not wedge the round (VERDICT r4 #5)
            def target():
                time.sleep(deadline + 30)
        try:
            return with_deadline(target, deadline, what)
        except FloatingPointError:
            raise                      # numerical NaN guard: never retry
        except Exception as e:         # noqa: BLE001 — classified below
            msg = f"{type(e).__name__}: {e}"
            if is_transient(msg) and attempt < MAX_ATTEMPTS:
                attempts_log.append(msg[:300])
                log(f"[guard] {what}: transient infra error "
                    f"(attempt {attempt}/{MAX_ATTEMPTS}): "
                    f"{msg[:120]} ... retrying in 10s")
                time.sleep(10)
                continue
            raise
    raise RuntimeError(f"{what}: all {MAX_ATTEMPTS} attempts consumed "
                       f"by injected test hooks")


def run_with_watchdog(run_fn, seconds: float, what: str = "engine run",
                      resume_fn=None, log=_log):
    """CLI-level watchdog: run an engine under a deadline; on expiry,
    fall back to ``resume_fn`` (typically a checkpoint resume) once.

    The shape ``timeout + checkpoint => resume``: a checkpointed engine
    leaves its last leg snapshot on disk, so when the live run wedges,
    one fresh attempt that RESUMES from the snapshot recovers all work
    up to the last leg boundary instead of replaying from scratch. With
    no ``resume_fn`` the timeout simply propagates.

    DEADLINE SIZING CONTRACT: a timed-out attempt cannot be killed —
    its daemonized thread keeps running (with_deadline). If ``seconds``
    is shorter than a LEGITIMATE run (e.g. a cold compile), the stale
    attempt and the resume race on the same device queue and, for a
    checkpointed run, on the same snapshot path — the stale attempt
    can overwrite the resume's newer snapshot with an older one. Set
    the deadline well above the worst-case healthy run time (this is a
    hang detector, not a scheduler); the bench's 900 s default
    (PPLS_BENCH_WATCHDOG_S) was sized to cover a cold compile on the
    slowest observed rig.
    """
    try:
        return with_deadline(run_fn, seconds, what)
    except HangTimeout as e:
        if resume_fn is None:
            raise
        log(f"[guard] {what}: {e}; resuming from checkpoint")
        return with_deadline(resume_fn, seconds, f"{what} (resume)")
