"""Host-side frontier engine: the farmer, re-drawn for SPMD hardware.

The reference farmer (``aquadPartA.c:125-173``) owns a LIFO bag of interval
tasks and dispatches them one at a time to whichever worker is idle —
demand-driven load balancing at single-task granularity, 4 MPI messages per
split round-trip (SURVEY.md §3, hot-loop economics).

On a TPU the same capabilities invert: the host owns a *wavefront frontier*
(all pending intervals) and dispatches the entire generation as one padded,
masked, fixed-width batch per round. A batched launch is intrinsically
load-balanced across a chip's lanes; the bag's dynamic growth becomes
host-side compaction of the split outputs between rounds; termination
(``aquadPartA.c:166``: bag empty and all workers idle) becomes "frontier
empty". The reference workload runs in 15 rounds with a peak frontier of
1642 intervals (SURVEY.md §0) instead of 6567 message round-trips.

This engine is the fully-general path: unbounded frontier growth (numpy
arrays on host), bucketed batch widths to bound recompilation, per-round
checkpointability. The fully-on-device variant lives in
``ppls_tpu.parallel.device_engine``.
"""

from __future__ import annotations

import dataclasses
import functools
import time
from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ppls_tpu.config import QuadConfig, Rule
from ppls_tpu.models.integrands import get_integrand
from ppls_tpu.ops.reduction import neumaier_add_host
from ppls_tpu.ops.rules import EVALS_PER_TASK, eval_batch
from ppls_tpu.utils.metrics import RoundStats, RunMetrics


@dataclasses.dataclass
class IntegrationResult:
    area: float
    config: QuadConfig
    metrics: RunMetrics
    exact: Optional[float] = None

    @property
    def global_error(self) -> Optional[float]:
        """Achieved |area - exact|; the reference cannot report this
        (its eps is a per-interval split tolerance, not a global bound —
        SURVEY.md §0)."""
        if self.exact is None:
            return None
        return abs(self.area - self.exact)


def _bucket_width(n: int, min_batch: int) -> int:
    """Next power of two >= max(n, min_batch): bounds jit recompilations to
    O(log(peak frontier)) distinct shapes."""
    w = max(int(min_batch), 1)
    while w < n:
        w <<= 1
    return w


@functools.lru_cache(maxsize=64)
def _round_step(f: Callable, eps: float, rule: Rule):
    """Jitted one-round step, cached per (integrand fn, eps, rule).

    Keyed on the function object itself — not the registry name — so
    re-registering an integrand under the same name never serves a stale
    compiled step.

    (l, r, active) -> (leaf_sum, split_mask): evaluate every active
    interval, sum the accepted values deterministically, and return which
    intervals must split. The shape-polymorphic jit cache handles the
    bucketed widths.
    """

    @jax.jit
    def step(l, r, active):
        value, _err, split = eval_batch(l, r, f, eps, rule)
        split = jnp.logical_and(split, active)
        accept = jnp.logical_and(active, jnp.logical_not(split))
        leaf_sum = jnp.sum(jnp.where(accept, value, 0.0))
        return leaf_sum, split

    return step


def integrate(config: QuadConfig = QuadConfig(),
              frontier: Optional[np.ndarray] = None,
              area_acc: Tuple[float, float] = (0.0, 0.0),
              metrics: Optional[RunMetrics] = None,
              on_round: Optional[Callable] = None) -> IntegrationResult:
    """Adaptively integrate per ``config``; host-driven wavefront loop.

    ``frontier``/``area_acc``/``metrics`` allow resuming a checkpointed run
    (see ``ppls_tpu.runtime.checkpoint``): pass the saved frontier and
    accumulator and the loop continues where it stopped.

    ``on_round(round_index, frontier, area_acc, metrics)`` is invoked after
    each wavefront round — the hook used for checkpointing and tracing.
    """
    entry = get_integrand(config.integrand)
    step = _round_step(entry.fn, float(config.eps), Rule(config.rule))
    dtype = np.dtype(config.dtype)

    if frontier is None:
        frontier = np.array([[config.a, config.b]], dtype=dtype)
    else:
        frontier = np.asarray(frontier, dtype=dtype).reshape(-1, 2)
    s, c = area_acc
    metrics = metrics or RunMetrics()
    start_rounds = metrics.rounds

    t0 = time.perf_counter()
    while frontier.shape[0] > 0:
        if metrics.rounds - start_rounds >= config.max_rounds:
            raise RuntimeError(
                f"max_rounds={config.max_rounds} exceeded with "
                f"{frontier.shape[0]} intervals pending; raise max_rounds "
                f"or loosen eps"
            )
        n = frontier.shape[0]
        width = _bucket_width(n, config.min_batch)
        # Padding lanes hold an in-domain point (first pending midpoint):
        # masked lanes still execute the integrand, and out-of-domain
        # values (NaN/Inf) hit TPU f64-emulation slow paths.
        fill = 0.5 * (frontier[0, 0] + frontier[0, 1])
        l = np.full(width, fill, dtype=dtype)
        r = np.full(width, fill, dtype=dtype)
        l[:n] = frontier[:, 0]
        r[:n] = frontier[:, 1]
        active = np.zeros(width, dtype=bool)
        active[:n] = True

        leaf_sum, split = step(jnp.asarray(l), jnp.asarray(r),
                               jnp.asarray(active))
        split_np = np.asarray(split)[:n]
        n_split = int(split_np.sum())

        s, c = neumaier_add_host(s, c, float(leaf_sum))

        # Compact the split outputs into the next frontier: both halves of
        # each split interval (the worker's two tag-0 sends,
        # aquadPartA.c:192-197), left children first — a deterministic
        # breadth-first ordering.
        if n_split:
            ls = frontier[split_np, 0]
            rs = frontier[split_np, 1]
            mid = (ls + rs) * 0.5
            nxt = np.empty((2 * n_split, 2), dtype=dtype)
            nxt[0::2, 0] = ls
            nxt[0::2, 1] = mid
            nxt[1::2, 0] = mid
            nxt[1::2, 1] = rs
            next_frontier = nxt
        else:
            next_frontier = np.empty((0, 2), dtype=dtype)

        metrics.record_round(RoundStats(
            round_index=metrics.rounds,
            frontier_width=n,
            splits=n_split,
            leaves=n - n_split,
            padded_width=width,
        ))
        frontier = next_frontier
        if on_round is not None:
            on_round(metrics.rounds, frontier, (s, c), metrics)

    metrics.wall_time_s += time.perf_counter() - t0
    metrics.max_depth = max(metrics.rounds - 1, 0)
    metrics.integrand_evals = metrics.tasks * EVALS_PER_TASK[Rule(config.rule)]
    metrics.tasks_per_chip = [metrics.tasks]

    return IntegrationResult(
        area=s + c,
        config=config,
        metrics=metrics,
        exact=entry.exact(config.a, config.b),
    )
