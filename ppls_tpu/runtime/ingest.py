"""Async request ingest for ``ppls-tpu serve`` (round 16).

The reference farmer reads its whole workload at startup; until round
16 this reproduction's serve loop did the same — a stdin JSONL list
materialized before the first phase. This module is the ASYNC half of
the multi-tenant front-end: a tiny stdlib HTTP server (the same
ThreadingHTTPServer shape as ``obs.server.MetricsServer``) that
accepts request records WHILE the phase loop runs, feeding the
engine's pending queue through a caller-supplied, lock-guarded submit
callback.

Protocol (deliberately minimal, curl-from-memory friendly):

* ``POST /submit`` — body is JSONL: one request record per line,
  ``{"theta": T | [T...], "bounds": [A, B], "tenant": "...",
  "priority": P, "deadline_phases": D}`` (tenant/priority/deadline
  optional). The response is JSONL too, one line per request line, in
  order: ``{"rid": N, "accepted": true}`` for an acknowledged
  admission-queue entry, ``{"rid": N, "accepted": false, "shed":
  true, "reason": ...}`` when the engine's shed policy refused it, or
  ``{"accepted": false, "error": ...}`` for a malformed line (bad
  JSON, bad domain, over-limit theta batch). A malformed line NEVER
  aborts the batch or the serve loop — every line gets its verdict.
* ``GET /`` (any path) — a JSON stats object from the caller's
  ``stats_fn`` (queue depth, resident count, phase), so a load
  balancer has a health/backpressure signal.

ACKNOWLEDGMENT CONTRACT: a ``{"accepted": true}`` response means the
request is in the engine's pending queue, which every checkpoint
snapshot includes — so a SIGTERM after the ack can never lose it (the
zero-lost-acks restart contract, BASELINE.md round 16). The submit
callback runs under the serve loop's engine lock; the ack is written
only after it returns.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

# bound per-request body size: an over-limit submission gets an
# explicit rejection, never an OOM (1 MiB is ~10k request lines)
MAX_BODY_BYTES = 1 << 20


class EngineHandle:
    """Lock-guarded publication cell for the live engine.

    The serve loop and the ingest handler threads share exactly one
    piece of mutable state: WHICH engine attempt (if any) is alive and
    may receive submissions. Round 16 fixed, by hand review, the race
    where an ingest ack landed in a dead engine during the
    supervisor's backoff window — the handle was being cleared outside
    the lock that the submit path held. This class makes that fix
    structural: ``_eng`` is touched ONLY inside ``with self._lock``
    blocks, and graftlint GL11 (``tools/graftlint/rules/locks.py``)
    lints the discipline so the next edit cannot quietly regress it.

    The lock is REENTRANT and exposed via :meth:`lock`: the serve loop
    holds it across multi-operation critical sections (submit burst +
    phase step + clear-on-death) while the methods here re-acquire it
    harmlessly, so callers compose ``with handle.lock():`` around
    whatever sequence must be atomic against the handler threads.
    """

    def __init__(self):
        self._lock = threading.RLock()
        self._eng = None

    def lock(self):
        """The owning RLock, for caller-composed critical sections."""
        return self._lock

    def publish(self, eng) -> None:
        """Make ``eng`` the live engine the handler threads may use."""
        with self._lock:
            self._eng = eng

    def clear(self) -> None:
        """Un-publish (a failed attempt's engine is DEAD state: its
        resume restores the last snapshot, so an ack landing in it
        would be silently lost — callers must clear UNDER the same
        lock that guards the submit path, which this method does)."""
        with self._lock:
            self._eng = None

    def peek(self):
        """The live engine or None. The reference is only safe to USE
        while the caller still holds :meth:`lock` (reentrant, so
        calling this inside a ``with handle.lock():`` block is the
        intended shape); a bare peek is only for read-only stats."""
        with self._lock:
            return self._eng


def parse_request_record(d: dict, theta_block: int = 1,
                         dispatch: bool = False) -> dict:
    """Validate + normalize one ingest/JSONL request record into the
    ``StreamEngine.submit`` kwargs shape. Raises ``ValueError`` with a
    precise message on every malformed shape — the caller turns that
    into the per-line rejection record instead of crashing the loop.

    Accepted keys: ``theta`` (number, or list of <= theta_block
    numbers), ``bounds`` ([lo, hi] finite numbers), optional
    ``tenant`` (str), ``priority`` (int), ``deadline_phases``
    (int >= 1), ``arrival_phase`` (int >= 0, list-driven mode only).
    Domain checks beyond shape (integrand ds-domain, queue policy)
    stay with the engine.

    ``dispatch=True`` (round 21, the heterogeneous pool) additionally
    accepts the per-request ROUTING KEYS: ``eps`` (positive finite
    number inside the dispatchable band range) and ``rule`` (a
    :class:`~ppls_tpu.config.Rule` member name) — validated through
    the dispatcher's canonicalizer, so an out-of-band eps, an unknown
    rule, an over-cap theta batch, or a theta batch on a non-TRAPEZOID
    rule all yield the same per-line rejection record here instead of
    a crash later. On a single-engine serve (the default) those keys
    stay UNKNOWN and reject exactly as before."""
    if not isinstance(d, dict):
        raise ValueError("request record must be a JSON object")
    unknown = set(d) - {"theta", "bounds", "tenant", "priority",
                        "deadline_phases", "arrival_phase"}
    if dispatch:
        unknown -= {"eps", "rule"}
    if unknown:
        raise ValueError(f"unknown request keys: {sorted(unknown)}")
    if "theta" not in d or "bounds" not in d:
        raise ValueError("request record needs 'theta' and 'bounds'")
    th = d["theta"]
    if isinstance(th, list):
        if not th or not all(isinstance(x, (int, float))
                             and not isinstance(x, bool) for x in th):
            raise ValueError("'theta' list must hold numbers")
        if len(th) > max(int(theta_block), 1):
            raise ValueError(
                f"theta batch of {len(th)} exceeds this engine's "
                f"theta_block={theta_block}")
        theta = tuple(float(x) for x in th)
    elif isinstance(th, (int, float)) and not isinstance(th, bool):
        theta = float(th)
    else:
        raise ValueError("'theta' must be a number or a list of "
                         "numbers")
    b = d["bounds"]
    if not isinstance(b, list) or len(b) != 2 \
            or not all(isinstance(x, (int, float))
                       and not isinstance(x, bool) for x in b):
        raise ValueError("'bounds' must be [lo, hi] numbers")
    out = {"theta": theta, "bounds": (float(b[0]), float(b[1]))}
    if "tenant" in d:
        if not isinstance(d["tenant"], str) or not d["tenant"]:
            raise ValueError("'tenant' must be a non-empty string")
        out["tenant"] = d["tenant"]
    if "priority" in d:
        p = d["priority"]
        if not isinstance(p, int) or isinstance(p, bool):
            raise ValueError("'priority' must be an integer")
        out["priority"] = p
    if "deadline_phases" in d and d["deadline_phases"] is not None:
        dp = d["deadline_phases"]
        if not isinstance(dp, int) or isinstance(dp, bool) or dp < 1:
            raise ValueError("'deadline_phases' must be an integer "
                             ">= 1")
        out["deadline_phases"] = dp
    if "arrival_phase" in d:
        ap = d["arrival_phase"]
        if not isinstance(ap, int) or isinstance(ap, bool) or ap < 0:
            raise ValueError("'arrival_phase' must be an integer >= 0")
        out["arrival_phase"] = ap
    if dispatch:
        eps = d.get("eps")
        rule = d.get("rule")
        if eps is not None and (not isinstance(eps, (int, float))
                                or isinstance(eps, bool)):
            raise ValueError("'eps' must be a number")
        if rule is not None and not isinstance(rule, str):
            raise ValueError("'rule' must be a string")
        # full routing-key validation through the canonicalizer (band
        # range, rule membership, bucket cap, batch-rule cross checks)
        # — absent keys validate against placeholder defaults so a
        # bad theta batch still rejects here; the dispatcher's own
        # defaults apply at submit
        from ppls_tpu.runtime.dispatch import canonical_key
        canonical_key(1e-6 if eps is None else eps,
                      "trapezoid" if rule is None else rule,
                      out["theta"])
        if eps is not None:
            out["eps"] = float(eps)
        if rule is not None:
            out["rule"] = str(rule).strip().lower()
    return out


def ingest_lines(text: str, submit_fn) -> list:
    """Feed a JSONL body through ``submit_fn`` line by line; returns
    one response record per non-empty line (see the module docstring
    for the shapes). A malformed line yields a rejection record and
    the remaining lines still process — the never-crash contract the
    serve loop's stdin path shares."""
    out = []
    for i, line in enumerate(text.splitlines(), 1):
        line = line.strip()
        if not line:
            continue
        try:
            d = json.loads(line)
        except json.JSONDecodeError as e:
            out.append({"accepted": False, "line": i,
                        "error": f"unparseable JSON: {e}"[:200]})
            continue
        try:
            out.append(submit_fn(d))
        except ValueError as e:
            out.append({"accepted": False, "line": i,
                        "error": str(e)[:200]})
    return out


class IngestServer:
    """Threaded ingest endpoint over a caller-supplied submit
    callback. ``submit_fn(record_dict) -> response_dict`` must be
    thread-safe (the serve CLI wraps it in the engine lock) and raise
    ``ValueError`` for malformed records. ``stats_fn()`` (optional)
    backs the GET health/backpressure response."""

    def __init__(self, submit_fn, port: int = 0,
                 host: str = "127.0.0.1", stats_fn=None):
        self.submit_fn = submit_fn
        self.stats_fn = stats_fn
        outer = self

        class _Handler(BaseHTTPRequestHandler):
            def _reply(self, code: int, body: bytes,
                       ctype: str = "application/json"):
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_POST(self):      # noqa: N802 — stdlib API name
                n = int(self.headers.get("Content-Length") or 0)
                if n > MAX_BODY_BYTES:
                    self._reply(413, json.dumps(
                        {"accepted": False,
                         "error": f"body over {MAX_BODY_BYTES} "
                                  f"bytes"}).encode() + b"\n")
                    return
                body = self.rfile.read(n).decode("utf-8", "replace")
                responses = ingest_lines(body, outer.submit_fn)
                self._reply(200, ("\n".join(
                    json.dumps(r) for r in responses)
                    + "\n").encode("utf-8"),
                    ctype="application/jsonl")

            def do_GET(self):       # noqa: N802 — stdlib API name
                stats = outer.stats_fn() if outer.stats_fn else {}
                self._reply(200, (json.dumps(stats) + "\n").encode())

            def log_message(self, *args):   # keep stderr clean
                pass

        self._httpd = ThreadingHTTPServer((host, port), _Handler)
        self.host = host
        self.port = int(self._httpd.server_address[1])
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="ppls-ingest",
            daemon=True)
        self._thread.start()

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}/submit"

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5)
