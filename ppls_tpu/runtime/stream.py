"""Continuous-batching streaming walker: phase-boundary admission and
retirement of concurrent integration requests.

The reference farmer never idles a worker while the bag is non-empty
(``aquadPartA.c:156-165``) — but the batch engines still run one
request set to completion: every ``integrate_family_walker(_dd)`` call
pays full seed/compile/drain cost, and lanes idle through the drain
tail while new work waits at the host. This module is the
iteration-level scheduler that removes the *between-runs* cliff — the
same shape as continuous batching in LLM inference serving (Orca-style
iteration-level scheduling): requests are ADMITTED into free family
slots at natural phase boundaries instead of waiting for the whole
batch to finish, and finished requests RETIRE individually with their
exact segment-summed area.

Architecture (one phase = one engine cycle of the walker):

* a host-side REQUEST QUEUE holds pending requests (one request = one
  integral: integrand parameter theta + bounds; eps/rule are engine
  configuration because they are static arguments of the compiled
  cycle program);
* a fixed pool of ``slots`` FAMILY SLOTS indexes the per-family
  accumulator; a free-list recycles slot ids as requests retire. The
  per-task theta is a bag column, so a slot is purely an accumulator
  index — admission is one contiguous seed-row push onto the bag top
  plus an accumulator/counter clear for the recycled ids (the
  "family-slot recycle in the phase-end credit path" hook);
* each phase runs ONE cycle of the walker device program
  (``walker.run_stream_cycle`` — the identical
  breed -> sort -> walk -> expand -> drain body as
  ``integrate_family_walker``, in-kernel refill included), which also
  returns the per-family DONE MASK (``walker.family_live_counts`` ==
  0), a monotonic last-credited phase counter, and a device-counted
  per-phase stats row;
* retirement: a slot whose live count hits zero has its whole pending
  set completed (lane state folds back into the bag at every cycle
  edge), so its running area — Neumaier-compensated across phases so
  the result does not depend on how the admission schedule partitioned
  its leaves into phases — is final and exact; the result is emitted
  and the slot returns to the free list;
* the engine never idles below the walk-engagement floor while offered
  load remains: admission happens BEFORE the cycle, so newly admitted
  seeds breed and deal into the vacated root-queue slack in the same
  phase.

Checkpointing: ``snapshot()`` atomically writes queue + walker state
(live bag prefix, compensated accumulator pair, slot table, pending
queue, per-request latency bookkeeping) through the standard
``runtime.checkpoint`` container; ``StreamEngine.resume`` restores it
and the continued stream replays the identical per-phase computation
(same bit-identity contract as the batch walkers' leg resume).

The multi-chip variant (``engine="walker-dd"``) drives the
demand-driven sharded walker one cycle per phase; admission is folded
into ``mesh.phase_reshard``'s occupancy decision (rebalance / admit /
terminate) so admitted seeds join the same depth-stratified cross-chip
deal the phase boundary already pays (``sharded_walker.py``).

Round 16 — OVERLOAD-HARDENED MULTI-TENANCY. Requests carry a
``tenant``, a ``priority`` class, and an optional ``deadline_phases``
budget, and the engine grows the dispatcher-tier controls the
"millions of users" direction needs:

* **Admission control**: per-tenant TOKEN BUCKETS (``tenant_quotas``:
  ``rate`` tokens refilled per phase up to ``burst``) gate slot
  allocation, and admission picks by ``(-priority, rid)`` — higher
  classes admit first, FIFO within a class — instead of raw FIFO. A
  tenant out of tokens is SKIPPED (its requests stay queued), never
  crashed.
* **Load shedding**: ``queue_limit`` bounds the pending queue. An
  arriving request that would overflow it triggers the deterministic
  shed policy — the LOWEST-PRIORITY, OLDEST queued request is the
  victim; if the arrival does not strictly outrank it, the arrival
  itself is shed. Every shed consumes a rid (so resume prefix-skip
  stays aligned), emits a ``request_shed`` event +
  ``ppls_requests_shed_total{tenant,reason}``, lands in
  ``StreamEngine.shed`` / ``StreamResult.shed``, and fires the
  ``on_shed`` callback (the serve CLI's explicit JSONL rejection
  record).
* **Deadlines**: a request must retire by phase ``submit_phase +
  deadline_phases``. A QUEUED request that can no longer meet its
  deadline is shed (``deadline_exceeded``); an IN-FLIGHT request that
  misses it retires through the round-14 failed-record path
  (``failed=True, failure="deadline_exceeded"``) and its live bag rows
  are COMPACTED OUT by a jitted cancel program (stable partition —
  surviving rows keep their order, so the continued schedule replays
  deterministically), freeing the slot immediately.
* **Per-tenant SLO accounting**: retire-latency histograms labeled by
  tenant and by priority class on the same registry bench/serve/
  ``/metrics`` read, so p50/p99 per class is one quantile path
  everywhere.

All of it is host-side boundary policy: the compiled cycle program is
untouched, the compile-once invariant holds (the cancel program is its
own one-shape jit, like the admit program), and every decision is a
pure function of the schedule + device-counted state, so the round-8
determinism contracts (rerun, kill-and-resume) extend to shed and
deadline behavior unchanged.
"""

from __future__ import annotations

import dataclasses
import functools
import os
import time
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ppls_tpu.config import Rule
from ppls_tpu.obs.flight import ChipFlightRecorder
from ppls_tpu.obs.telemetry import Telemetry
from ppls_tpu.parallel.bag_engine import (DEPTH_BITS, DEPTH_MASK,
                                          BagState)
from ppls_tpu.parallel.walker import (
    DEFAULT_LANES,
    N_WASTE,
    STREAM_STAT_FIELDS,
    normalize_theta_batch,
    run_stream_cycle,
    validate_theta_block,
    walker_sizing,
)

# STREAM_STAT_FIELDS columns that accumulate as registry counters
# (everything except the running max). live_tasks/live_families keep
# their historical summed-over-phases totals semantics: the sum is the
# task-phase / family-phase residency integral.
_COUNTER_STATS = tuple(k for k in STREAM_STAT_FIELDS if k != "maxd")


@dataclasses.dataclass
class StreamRequest:
    """One pending integration request: one 1D integral (scalar
    ``theta``), or — on a ``theta_block`` > 1 engine (round 13) — a
    THETA BATCH: up to T per-user thetas scored over one shared
    union-refinement frontier (``theta`` is then a tuple).

    Round 16: ``tenant``/``priority`` drive admission control and the
    shed policy; ``deadline_phases`` is the request's phase budget
    (retire by ``submit_phase + deadline_phases`` or fail). Defaults
    keep pre-round-16 snapshots and callers unchanged."""

    rid: int
    theta: object                 # float, or tuple of floats (batch)
    bounds: Tuple[float, float]
    submit_phase: int
    submit_t: float
    tenant: str = "default"
    priority: int = 1
    deadline_phases: Optional[int] = None

    @property
    def thetas(self) -> Tuple[float, ...]:
        t = self.theta
        return tuple(t) if isinstance(t, (tuple, list)) else (float(t),)

    @property
    def deadline_phase(self) -> Optional[int]:
        """Last phase index at which this request may retire."""
        if self.deadline_phases is None:
            return None
        return self.submit_phase + int(self.deadline_phases)


@dataclasses.dataclass
class ShedRecord:
    """A request refused by admission control (round 16): the explicit
    rejection record the overload contract demands — every shed
    request is visible as a JSONL line / ``request_shed`` event /
    ``ppls_requests_shed_total{tenant,reason}`` increment, never a
    silent drop. Shed requests CONSUME a rid, so the resume driver's
    next_rid prefix-skip stays aligned with the submission order."""

    rid: int
    theta: object
    bounds: Tuple[float, float]
    tenant: str
    priority: int
    reason: str                   # "queue_full" | "deadline_exceeded"
    phase: int                    # phase index the shed happened at
    submit_phase: int


@dataclasses.dataclass
class CompletedRequest:
    """A retired request: exact area + latency accounting.

    ``phases_in_flight`` counts device phases from admission through
    retirement inclusive; ``latency_phases`` additionally includes
    queue wait (submit -> retire). ``last_credited_phase`` is the
    device-counted monotonic counter from the cycle program (-1 for a
    zero-area integral that never credited).
    """

    rid: int
    theta: object
    bounds: Tuple[float, float]
    area: float               # scalar requests; first theta on batches
    submit_phase: int
    admit_phase: int
    retire_phase: int
    latency_s: float
    first_seeded_phase: int
    last_credited_phase: int
    # round 13 (theta_block > 1): the request's per-theta areas, in
    # submission order (len == len(request.theta)); None on scalar
    # engines so pre-round-13 snapshots replay unchanged
    areas: Optional[List[float]] = None
    # round 14: True when the request retired through the QUARANTINE
    # path (non-finite area on an engine running with quarantine=True)
    # — the area fields then carry the non-finite values for the
    # record, and consumers must treat the request as FAILED, not
    # integrate-d. Default False keeps pre-round-14 snapshots loading.
    failed: bool = False
    # round 16: tenancy + the failure taxonomy ("nan" quarantine vs
    # "deadline_exceeded" expiry); defaults keep old snapshots loading
    tenant: str = "default"
    priority: int = 1
    failure: Optional[str] = None
    # round 18: True when the request completed on the CPU SPILLOVER
    # backend (off-mesh pure-f64 bag rounds) instead of the engine —
    # the attribution marker of graceful degradation. Default False
    # keeps pre-round-18 snapshots loading.
    spillover: bool = False

    @property
    def phases_in_flight(self) -> int:
        return self.retire_phase - self.admit_phase + 1

    @property
    def latency_phases(self) -> int:
        return self.retire_phase - self.submit_phase + 1


@dataclasses.dataclass
class StreamResult:
    """Aggregate result of a finished stream (``StreamEngine.run``)."""

    completed: List[CompletedRequest]
    phases: int
    wall_s: float
    totals: dict                 # registry-sourced STREAM_STAT_FIELDS sums
    phase_stats: np.ndarray      # (phases, len(STREAM_STAT_FIELDS)) i64
    # per-slot streaming surface (device-counted; the walker hooks):
    fam_done: Optional[np.ndarray] = None         # (slots,) bool
    fam_first_phase: Optional[np.ndarray] = None  # (slots,) i32, -1=never
    fam_last_phase: Optional[np.ndarray] = None   # (slots,) i32, -1=never
    # registry latency histograms (round 10): the ONE quantile path
    # bench + serve both read — None on hand-assembled results, where
    # latency_percentiles() rebuilds transient histograms from
    # `completed` through the identical bucket tables
    latency_hist_phases: Optional[object] = None
    latency_hist_seconds: Optional[object] = None
    # shared per-round record (satellite 1): one RoundStats per phase,
    # from the device-counted phase rows
    per_round: List = dataclasses.field(default_factory=list)
    # round 16: every request refused by admission control (queue
    # overflow / unmeetable deadline) — the overload accounting
    # invariant is len(completed) + len(shed) == requests submitted
    shed: List = dataclasses.field(default_factory=list)

    @property
    def areas(self) -> np.ndarray:
        """Areas in request-id order (the deterministic comparison
        surface for the batch-vs-streamed tests)."""
        done = sorted(self.completed, key=lambda c: c.rid)
        return np.array([c.area for c in done])

    @property
    def requests_per_sec(self) -> float:
        return len(self.completed) / self.wall_s if self.wall_s else 0.0

    def latency_percentiles(self) -> dict:
        """p50/p99 request latency in phases and seconds (the bench's
        latency definition: submit -> retire, queue wait included).

        Round 10: sourced from the registry's exponential-bucket
        histograms through the deterministic bucket-edge quantile
        (``obs.registry.Histogram.quantile``), so bench and serve
        report IDENTICAL numbers on identical runs — the previous
        ``np.percentile`` over a sorted list interpolated across tied
        phase counts, which let two readers of the same run disagree
        in the last digits."""
        if not self.completed:
            return {}
        hp, hs = self.latency_hist_phases, self.latency_hist_seconds
        if hp is None or hs is None or hp.count != len(self.completed):
            # hand-assembled result: rebuild through the same buckets
            from ppls_tpu.obs.registry import (PHASE_BUCKETS,
                                               SECONDS_BUCKETS,
                                               Histogram)
            hp = Histogram(PHASE_BUCKETS)
            hs = Histogram(SECONDS_BUCKETS)
            for c in self.completed:
                hp.observe(c.latency_phases)
                hs.observe(c.latency_s)
        return {
            "p50_phases": float(hp.quantile(0.5)),
            "p99_phases": float(hp.quantile(0.99)),
            "p50_s": float(hs.quantile(0.5)),
            "p99_s": float(hs.quantile(0.99)),
        }

    def class_latency_percentiles(self) -> dict:
        """p50/p99 retire latency (phases) PER PRIORITY CLASS, through
        the same deterministic bucket-edge quantile as
        :meth:`latency_percentiles` — the per-class SLO numbers the
        serve summary, ``/metrics`` (labeled histograms), and
        ``bench.py stream`` all report identically. Failed retirements
        (quarantine, deadline) are included: SLO math must see the
        failures, not only the successes."""
        from ppls_tpu.obs.registry import PHASE_BUCKETS, Histogram
        by_class: dict = {}
        for c in self.completed:
            h = by_class.setdefault(int(c.priority),
                                    Histogram(PHASE_BUCKETS))
            h.observe(c.latency_phases)
        return {
            str(p): {
                "count": h.count,
                "p50_phases": float(h.quantile(0.5)),
                "p99_phases": float(h.quantile(0.99)),
            } for p, h in sorted(by_class.items())}

    def spillover_summary(self) -> dict:
        """Graceful-degradation accounting (round 18): how much of
        the completed work ran on the CPU spillover backend instead of
        the engine, from the deterministic completed record."""
        done = [c for c in self.completed
                if getattr(c, "spillover", False)]
        return {
            "spillover_completed": len(done),
            "spillover_fraction": (len(done) / len(self.completed)
                                   if self.completed else 0.0),
        }

    def tenant_summary(self) -> dict:
        """Per-tenant accounting: retired / failed / shed counts and
        shed reasons — the registry's labeled counters, recomputed from
        the deterministic record so hand-assembled results report the
        identical numbers."""
        out: dict = {}

        def row(tenant):
            return out.setdefault(str(tenant), {
                "completed": 0, "failed": 0, "shed": 0,
                "shed_reasons": {}})

        for c in self.completed:
            r = row(c.tenant)
            r["completed"] += 1
            if c.failed:
                r["failed"] += 1
        for s in self.shed:
            r = row(s.tenant)
            r["shed"] += 1
            r["shed_reasons"][s.reason] = \
                r["shed_reasons"].get(s.reason, 0) + 1
        return out

    def occupancy_summary(self, lanes: int) -> dict:
        """Steady-state occupancy from the device-counted phase rows."""
        from ppls_tpu.parallel.walker import WASTE_FIELDS
        t = self.totals
        wsteps = int(t.get("wsteps", 0))
        out = {
            "lane_efficiency": (int(t["wtasks"]) / (wsteps * lanes)
                                if wsteps else 0.0),
            "walker_fraction": (int(t["wtasks"]) / int(t["tasks"])
                                if t.get("tasks") else 0.0),
        }
        buckets = {k: int(t.get(k, 0)) for k in WASTE_FIELDS}
        if any(buckets.values()):
            from ppls_tpu.obs.telemetry import build_attribution
            out["attribution"] = build_attribution(buckets,
                                                   wsteps * lanes)
        ps = self.phase_stats
        if ps is not None and len(ps):
            j = STREAM_STAT_FIELDS.index("live_families")
            k = STREAM_STAT_FIELDS.index("live_tasks")
            out["mean_live_families"] = float(ps[:, j].mean())
            out["mean_live_tasks"] = float(ps[:, k].mean())
        return out


@functools.partial(jax.jit, static_argnames=("capacity",))
def _admit_program(bag: BagState, acc, acc_c, fam_last,
                   seeds_l, seeds_r, seeds_th, seeds_meta, n_new,
                   clear, *, capacity: int):
    """Push ``n_new`` seed rows (dense prefix of the fixed-width seed
    arrays; pad rows carry benign in-domain fill) onto the bag top and
    clear the recycled slots' accumulator/counter state. One compiled
    program per admit-window width."""
    start = bag.count
    bag_l = lax.dynamic_update_slice(bag.bag_l, seeds_l, (start,))
    bag_r = lax.dynamic_update_slice(bag.bag_r, seeds_r, (start,))
    bag_th = lax.dynamic_update_slice(bag.bag_th, seeds_th, (start,))
    bag_meta = lax.dynamic_update_slice(bag.bag_meta, seeds_meta,
                                        (start,))
    count = start + n_new
    overflow = jnp.logical_or(
        bag.overflow, count > jnp.asarray(capacity, jnp.int32))
    # round 13: on a theta-blocked engine the accumulator pair is
    # (slots * T,) while the clear mask stays per-slot — expand it
    clear_acc = (jnp.repeat(clear, acc.shape[0] // clear.shape[0])
                 if acc.shape[0] != clear.shape[0] else clear)
    return (bag._replace(bag_l=bag_l, bag_r=bag_r, bag_th=bag_th,
                         bag_meta=bag_meta, count=count,
                         overflow=overflow),
            jnp.where(clear_acc, 0.0, acc),
            jnp.where(clear_acc, 0.0, acc_c),
            jnp.where(clear, jnp.int32(-1), fam_last))


@jax.jit
def _cancel_program(bag: BagState, kill):
    """Compact the live prefix, dropping every row whose family slot is
    in the ``kill`` mask (deadline expiry, round 16). A STABLE
    partition: surviving rows keep their relative bag order, so the
    continued phase schedule is the deterministic function of state the
    resume/rerun contracts rely on. Dropped rows become dead fill past
    the new count — they were real in-domain intervals, which is
    exactly the benign-fill requirement. One compiled shape (the whole
    store), like the admit program."""
    n = bag.bag_l.shape[0]
    idx = jnp.arange(n, dtype=jnp.int32)
    live = idx < bag.count
    slot = jnp.right_shift(bag.bag_meta, DEPTH_BITS)
    killed = jnp.logical_and(
        kill[jnp.clip(slot, 0, kill.shape[0] - 1)], live)
    keep = jnp.logical_and(live, jnp.logical_not(killed))
    order = jnp.argsort(jnp.where(keep, 0, 1).astype(jnp.int32),
                        stable=True)
    return bag._replace(
        bag_l=jnp.take(bag.bag_l, order),
        bag_r=jnp.take(bag.bag_r, order),
        bag_th=jnp.take(bag.bag_th, order),
        bag_meta=jnp.take(bag.bag_meta, order),
        count=jnp.sum(keep).astype(jnp.int32))


@jax.jit
def _dd_cancel_program(bl, br, bth, bm, counts, kill):
    """Per-chip twin of :func:`_cancel_program` over the flattened
    ``(n_dev * store,)`` dd stores: each chip's local queue compacts
    independently (element-wise, zero collectives)."""
    n_dev = counts.shape[0]
    store = bl.shape[0] // n_dev

    def one(l, r, th, m, cnt, kill):
        idx = jnp.arange(store, dtype=jnp.int32)
        live = idx < cnt
        slot = jnp.right_shift(m, DEPTH_BITS)
        killed = jnp.logical_and(
            kill[jnp.clip(slot, 0, kill.shape[0] - 1)], live)
        keep = jnp.logical_and(live, jnp.logical_not(killed))
        order = jnp.argsort(jnp.where(keep, 0, 1).astype(jnp.int32),
                            stable=True)
        return (jnp.take(l, order), jnp.take(r, order),
                jnp.take(th, order), jnp.take(m, order),
                jnp.sum(keep).astype(jnp.int32))

    l2, r2, th2, m2, cnt2 = jax.vmap(
        one, in_axes=(0, 0, 0, 0, 0, None))(
        bl.reshape(n_dev, store), br.reshape(n_dev, store),
        bth.reshape(n_dev, store), bm.reshape(n_dev, store),
        counts, kill)
    return (l2.reshape(-1), r2.reshape(-1), th2.reshape(-1),
            m2.reshape(-1), cnt2)


def _stream_identity(engine: str, family: str, eps: float, rule: Rule,
                     slots: int, lanes: int, chunk: int, capacity: int,
                     roots_per_lane: int, refill_slots: int,
                     n_dev: int = 1) -> dict:
    from ppls_tpu.runtime.checkpoint import engine_name
    return {"engine": engine_name(engine, rule), "fname": family,
            "eps": float(eps), "m": int(slots), "lanes": int(lanes),
            "chunk": int(chunk), "capacity": int(capacity),
            "roots_per_lane": int(roots_per_lane),
            "refill_slots": int(refill_slots), "n_dev": int(n_dev)}


class StreamEngine:
    """Long-lived streaming integration service over the walker.

    ``family`` is the integrand registry name (both the f64 integrand
    and its ds twin resolve from it). ``eps``/``rule`` are per-engine,
    not per-request: they are static arguments of the compiled cycle
    program, so a mixed-eps workload runs one engine per (eps, rule)
    group. ``slots`` bounds the number of CONCURRENTLY RESIDENT
    requests (the family-slot pool); the pending queue is unbounded.

    Typical driving loop::

        eng = StreamEngine("sin_recip_scaled", eps=1e-8, slots=32, ...)
        eng.submit(theta=1.25, bounds=(1e-3, 1.0))
        ...
        done = eng.step()        # one phase: admit -> cycle -> retire
        result = eng.drain()     # run phases until everything retires

    or the one-shot ``run(requests, arrival_phase=...)`` used by the
    bench and the ``serve`` CLI's synthetic mode.
    """

    def __init__(self, family: str, eps: float,
                 rule: Rule = Rule.TRAPEZOID,
                 slots: int = 64,
                 chunk: int = 1 << 13,
                 capacity: int = 1 << 20,
                 lanes: int = DEFAULT_LANES,
                 roots_per_lane: int = 12,
                 refill_slots: int = 8,
                 seg_iters: int = 2048,
                 max_segments: int = 1 << 18,
                 min_active_frac: float = 0.1,
                 exit_frac: Optional[float] = None,
                 suspend_frac: Optional[float] = None,
                 sort_roots: bool = True,
                 sort_skip_ratio: float = 8.0,
                 f64_rounds: int = 0,
                 scout_dtype: Optional[str] = None,
                 double_buffer: bool = False,
                 reduced_integrands: bool = False,
                 theta_block: int = 1,
                 admit_window: Optional[int] = None,
                 interpret: Optional[bool] = None,
                 engine: str = "walker",
                 mesh=None, n_devices: Optional[int] = None,
                 checkpoint_path: Optional[str] = None,
                 checkpoint_every: int = 8,
                 telemetry: Optional[Telemetry] = None,
                 quarantine: bool = False,
                 fault_injector=None,
                 queue_limit: Optional[int] = None,
                 tenant_quotas: Optional[dict] = None,
                 default_deadline_phases: Optional[int] = None,
                 on_shed=None,
                 spillover: bool = False,
                 spillover_limit: int = 4,
                 slo_config=None,
                 adapt: bool = False,
                 checkpoint_background: bool = False):
        from ppls_tpu.models.integrands import get_family, get_family_ds
        if interpret is None:
            interpret = jax.default_backend() != "tpu"
        if lanes % 128:
            raise ValueError(
                f"lanes must be a multiple of 128, got {lanes}")
        if refill_slots < 0 or refill_slots > roots_per_lane:
            raise ValueError(
                f"refill_slots must be in [0, roots_per_lane="
                f"{roots_per_lane}], got {refill_slots}")
        if engine not in ("walker", "walker-dd"):
            raise ValueError(f"unknown stream engine {engine!r}")
        # round 12: scout/double-buffer are per-engine compile statics,
        # like eps/rule — one engine per mode (compile-once holds)
        from ppls_tpu.parallel.walker import resolve_scout_dtype
        if scout_dtype == "f32" and f64_rounds:
            # an EXPLICIT flag conflict is an error (same policy as
            # explicit-f32-with-Simpson); only the None/env default is
            # silently off in pure-f64 streaming mode
            raise ValueError(
                "scout_dtype='f32' is meaningless with f64_rounds > 0 "
                "(the pure-f64 streaming mode runs no Pallas kernel)")
        scout = resolve_scout_dtype(
            scout_dtype, Rule(rule)) and not f64_rounds
        from ppls_tpu.parallel.walker import validate_double_buffer
        validate_double_buffer(double_buffer, refill_slots)
        self._scout = bool(scout)
        self._double_buffer = bool(double_buffer)
        from ppls_tpu.parallel.walker import resolve_cadence
        from ppls_tpu.runtime.tune import (last_resolution,
                                           workload_signature)
        if engine == "walker-dd":
            _sig_mesh = (mesh.devices.size if mesh is not None
                         else int(n_devices) if n_devices
                         else len(jax.devices()))
        else:
            _sig_mesh = 1
        exit_frac, suspend_frac = resolve_cadence(
            exit_frac, suspend_frac, self._scout, refill_slots,
            signature=workload_signature(
                family, eps, Rule(rule),
                theta_block=int(theta_block), mesh_shape=_sig_mesh,
                scout=self._scout, refill_slots=int(refill_slots)))
        # round 20: remember which tier resolved the cadence (exact /
        # nearest table entry, hand default, or explicit caller
        # values) — published as a registry gauge below so a silent
        # fallback is visible on /metrics
        self._cadence_resolution = last_resolution()
        # theta_block composes with f64_rounds (the pure-f64 streaming
        # mode runs the union-refinement bag twin); scouting is the
        # only mode conflict, checked above
        self._theta_block = validate_theta_block(
            theta_block, lanes=int(lanes), refill_slots=refill_slots,
            rule=rule, m=slots)
        self.family = family
        self.f_theta = get_family(family)
        self.f_ds = get_family_ds(family,
                                  reduced=bool(reduced_integrands))
        self._reduced = bool(reduced_integrands) \
            and self.f_ds is not get_family_ds(family)
        self.eps = float(eps)
        self.rule = Rule(rule)
        self.slots = int(slots)
        self.engine = engine
        self.lanes = int(lanes)
        self.interpret = bool(interpret)
        target, breed_chunk, slack_chunk = walker_sizing(
            lanes, roots_per_lane, capacity, chunk,
            self._theta_block)
        self._store = capacity + 2 * slack_chunk
        self._capacity = int(capacity)
        self._chunk = int(chunk)
        self._roots_per_lane = int(roots_per_lane)
        self._refill_slots = int(refill_slots)
        self._cycle_kw = dict(
            f_theta=self.f_theta, f_ds=self.f_ds, eps=self.eps,
            m=self.slots, seg_iters=int(seg_iters),
            max_segments=int(max_segments),
            min_active_frac=float(min_active_frac),
            exit_frac=float(exit_frac),
            suspend_frac=float(suspend_frac),
            interpret=self.interpret, lanes=self.lanes,
            capacity=int(capacity), breed_chunk=int(breed_chunk),
            target=int(target), rule=self.rule,
            sort_roots=bool(sort_roots),
            refill_slots=int(refill_slots),
            sort_skip_ratio=float(sort_skip_ratio),
            f64_rounds=int(f64_rounds),
            scout=self._scout, double_buffer=self._double_buffer,
            theta_block=self._theta_block)
        # admit window: fixed seed-array width (one compiled admit
        # program); capped by the store slack so the push never clamps
        aw = slots if admit_window is None else int(admit_window)
        self._admit_window = max(1, min(aw, 2 * slack_chunk))

        # telemetry (round 10): per-engine handle by default so the
        # registry's per-run totals read back exactly; pass a shared
        # Telemetry (serve does: events file + metrics server) to pool.
        # All publishes below consume host values the phase boundary
        # already fetched — zero telemetry-added device syncs.
        self.telemetry = telemetry if telemetry is not None \
            else Telemetry()
        tel = self.telemetry
        self._stat_counters = {k: tel.stream_counter(k)
                               for k in _COUNTER_STATS}
        self._g_maxd = tel.stream_gauge(
            "max_depth", "max refinement depth seen across phases")
        self._g_queue = tel.stream_gauge(
            "queue_depth", "pending (not yet admitted) requests")
        self._g_resident = tel.stream_gauge(
            "resident", "requests holding a family slot")
        self._g_free = tel.stream_gauge("free_slots",
                                        "free family slots")
        self._g_phase = tel.stream_gauge("phase",
                                         "current phase index")
        self._g_live_tasks = tel.stream_gauge(
            "live_tasks_now", "live bag rows after the last phase")
        self._c_admitted = tel.registry.counter(
            "ppls_stream_admitted_total", "requests admitted to slots")
        self._c_retired = tel.registry.counter(
            "ppls_stream_retired_total", "requests retired with areas")
        self._h_lat_phases = tel.latency_phases_histogram()
        self._h_lat_seconds = tel.latency_seconds_histogram()
        # precomputed rolling quantiles (the same bucket-edge values a
        # scraper would derive from the histogram) so a bare curl of
        # /metrics shows p50/p99 without PromQL
        self._g_lat = {
            (q, unit): tel.stream_gauge(
                f"retire_latency_{unit}_p{int(q * 100)}",
                f"rolling p{int(q * 100)} retire latency ({unit}; "
                f"bucket-edge quantile)")
            for q in (0.5, 0.99) for unit in ("phases", "seconds")}
        # round 20: the cadence resolution tier as a labeled gauge —
        # the tuning table falling back to the hand tier must be
        # VISIBLE, not silent (tentpole layer 2 contract)
        self._g_tuning = tel.registry.gauge(
            "ppls_tuning_resolution",
            "cadence resolution tier for this engine (1 = the tier "
            "that resolved)", ("tier",))
        self._g_tuning.labels(
            tier=self._cadence_resolution["tier"]).set(1.0)

        # round 16: admission control + load shedding + deadlines.
        # queue_limit bounds the PENDING queue (None = the historical
        # unbounded queue); tenant_quotas maps tenant -> {"rate": R,
        # "burst": B} token buckets refilled per phase ("*" is the
        # default quota for tenants without their own entry; no dict =
        # no gating); default_deadline_phases applies to requests that
        # do not carry their own budget. All host-side policy — none
        # of it touches the compiled cycle program or the snapshot
        # identity (a resume must be driven with the same policy flags
        # for the shed schedule to replay, same as the arrival
        # schedule itself).
        self.queue_limit = (None if queue_limit is None
                            else int(queue_limit))
        if self.queue_limit is not None and self.queue_limit < 1:
            raise ValueError(
                f"queue_limit must be >= 1, got {queue_limit}")
        self.tenant_quotas = None
        if tenant_quotas:
            self.tenant_quotas = {}
            for name, q in tenant_quotas.items():
                rate = float(q.get("rate", 1.0))
                burst = float(q.get("burst", max(rate, 1.0)))
                if rate <= 0 or burst < 1.0:
                    # rate 0 would starve the tenant FOREVER (its
                    # queued requests never admit, never shed, and
                    # the drain loop never terminates) — quota
                    # throttles pacing; refusal is the queue bound's
                    # job
                    raise ValueError(
                        f"tenant quota {name!r}: rate must be > 0 "
                        f"and burst >= 1, got rate={rate} "
                        f"burst={burst}")
                self.tenant_quotas[str(name)] = {"rate": rate,
                                                 "burst": burst}
        self.default_deadline_phases = (
            None if default_deadline_phases is None
            else int(default_deadline_phases))
        if self.default_deadline_phases is not None \
                and self.default_deadline_phases < 1:
            # fail at construction, not at the first submit inside a
            # supervised serve loop (where it would burn the whole
            # retry budget re-crashing deterministically)
            raise ValueError(
                f"default_deadline_phases must be >= 1, got "
                f"{default_deadline_phases}")
        self.on_shed = on_shed
        self.shed: List[ShedRecord] = []
        self._tokens: dict = {}
        # round 18: CPU spillover — queue-overflow victims without a
        # deadline run as pure-f64 bag rounds off-mesh instead of
        # shedding (slower-but-correct capacity before rejection).
        # Host-side boundary policy like the shed machinery: never on
        # the snapshot identity, but the spill queue rides every
        # snapshot so an acknowledged spillover request survives a
        # restart.
        self.spillover_limit = int(spillover_limit)
        # bounded spill queue (round-18 review): beyond ~8 phases of
        # spillover backlog the victim sheds explicitly — sustained
        # deadline-less overload must not re-grow the unbounded
        # backlog queue_limit exists to prevent
        self._spill_cap = 8 * max(self.spillover_limit, 1)
        self._spill = None
        if spillover:
            from ppls_tpu.backends.spillover import SpilloverExecutor
            self._spill = SpilloverExecutor(
                family, self.eps, rule=self.rule,
                chunk=int(chunk),       # the executor owns the cap
                capacity=int(capacity), telemetry=tel)
        self._spill_queue: List[StreamRequest] = []
        self._c_spillover = tel.registry.counter(
            "ppls_stream_spillover_total",
            "requests completed on the CPU spillover backend "
            "instead of being shed")
        # round 20 (tentpole layer 3): ONLINE adaptation of the
        # host-side per-phase policy knobs — the admission budget
        # (starts conservative at half the compiled admit window,
        # opens toward the window under sustained backlog + underfed
        # lanes, decays when the queue drains) and the spillover batch
        # limit (grows under spill backlog, decays when it clears).
        # Both adjust within declared safe bands with hysteresis and
        # one-step-per-phase clamps (runtime.tune.OnlineAdapter), from
        # the phase-stats row the boundary already fetched — zero new
        # device fetches, and never past the compiled admit window
        # (no recompile can result). The adapter state rides every
        # snapshot so kill-and-resume replays the same trajectory.
        self._adapt = None
        self._g_adapt = {}
        if adapt:
            from ppls_tpu.runtime.tune import OnlineAdapter
            defaults = {
                "admit_budget": max(1, self._admit_window // 2),
                "spillover_limit": self.spillover_limit,
            }
            bands = {
                "admit_budget": (1, self._admit_window),
                "spillover_limit": (1, max(1, self._spill_cap // 2)),
            }
            self._adapt = OnlineAdapter(defaults, bands)
            self._g_adapt = {
                k: tel.stream_gauge(
                    f"adapt_{k}",
                    f"online-adapted value of the {k} knob")
                for k in sorted(defaults)}
            for k, g in self._g_adapt.items():
                g.set(float(self._adapt.values[k]))
        # round 16: a JSON-serializable scratch dict for the DRIVER'S
        # resume bookkeeping, carried by every snapshot. The serve CLI
        # stores its batch-list cursor here — rids alone cannot serve
        # as the list prefix once live ingest traffic (which also
        # consumes rids) interleaves with a request list.
        self.client_state: dict = {}

        # host bookkeeping
        self._pending: List[StreamRequest] = []
        self._free = list(range(self.slots))
        self._slot_req = {}          # slot -> StreamRequest + admit info
        self._records = {}           # rid -> dict(admit_phase, ...)
        self.completed: List[CompletedRequest] = []
        self._next_rid = 0
        self.phase = 0
        self._count = 0              # live bag rows after the last phase
        self._phase_rows: List[np.ndarray] = []
        self._fam_first = np.full(self.slots, -1, dtype=np.int32)
        self._last_fam_live = np.zeros(self.slots, dtype=np.int32)
        self._last_fam_last = np.full(self.slots, -1, dtype=np.int32)

        # device state (built lazily on the first admission so the
        # dead-slot fill can be an in-domain point of a real request)
        self._dev = None
        self._fill = None            # (fill_x, fill_th)

        # round 14: per-request NaN quarantine — a non-finite area at
        # retirement emits a FAILED CompletedRequest and frees the slot
        # while every healthy concurrent request retires normally,
        # instead of the engine-wide FloatingPointError (which stays
        # the default: loud is right when nobody supervises)
        self.quarantine = bool(quarantine)
        self._c_quarantined = tel.registry.counter(
            "ppls_stream_quarantined_total",
            "requests retired as failed through the NaN quarantine")
        # round 16: per-tenant SLO accounting on the same registry —
        # shed counter by (tenant, reason), deadline-expiry counter,
        # per-tenant retired counter, and latency histograms labeled
        # by tenant and by priority class (the summary's per-class
        # p50/p99 reads the identical bucket quantile)
        self._c_shed = tel.shed_counter()
        self._c_deadline = tel.registry.counter(
            "ppls_stream_deadline_exceeded_total",
            "in-flight requests retired failed at their phase "
            "deadline", ("tenant",))
        self._c_tenant_retired = tel.registry.counter(
            "ppls_stream_tenant_retired_total",
            "requests retired, by tenant", ("tenant",))
        self._h_class_lat = tel.class_latency_histogram()
        self._h_tenant_lat = tel.tenant_latency_histogram()
        # round 19: SLO burn-rate alerting — a phase-boundary
        # evaluator over the registry histograms/counters the
        # boundaries above already publish (no new device fetches;
        # GL06 boundary-hook-only holds for its emit sites too)
        self._slo = None
        if slo_config is not None:
            from ppls_tpu.obs.slo import SloEvaluator
            self._slo = SloEvaluator(slo_config, tel)
        # round 19: per-rid DISTRIBUTED TRACE state — one detached
        # request span per rid (opened at submit ack, closed at the
        # terminal disposition) and the token-bucket wait counter the
        # admit event reports. Host bookkeeping only; spans re-open on
        # resume so a continued timeline keeps its rid linkage.
        self._rid_spans: dict = {}
        self._token_waits: dict = {}
        # round 14: seeded fault injection (runtime/faults.py) — hooks
        # fire at the boundaries this engine already owns; None = no
        # plan armed, zero overhead
        self.fault_injector = fault_injector

        self.checkpoint_path = checkpoint_path
        self.checkpoint_every = max(int(checkpoint_every), 1)
        # round 22: route snapshot serialization through the module
        # background writer (overlapped boundaries). Write MECHANICS
        # only — the container bytes and the atomic-rename commit
        # point are identical to the sync path, so this is NOT part
        # of the snapshot identity and bit-identity across the flag
        # holds by construction.
        self.checkpoint_background = bool(checkpoint_background)
        if engine == "walker-dd":
            from ppls_tpu.parallel.mesh import make_mesh
            if refill_slots <= 0:
                raise ValueError(
                    "walker-dd streaming requires refill_slots > 0 "
                    "(admission rides the refill mode's phase reshard)")
            self._mesh = mesh if mesh is not None else make_mesh(
                n_devices)
            self._dd = None          # built lazily with the fill point
        else:
            self._mesh = None

    # ------------------------------------------------------------------
    # identity / snapshot
    # ------------------------------------------------------------------

    def _identity(self) -> dict:
        n_dev = self._mesh.devices.size if self._mesh is not None else 1
        ident = _stream_identity(
            f"{self.engine}-stream", self.family, self.eps, self.rule,
            self.slots, self.lanes, self._chunk, self._capacity,
            self._roots_per_lane, self._refill_slots, n_dev)
        # round 12: mode flags are identity (conditional keys keep
        # pre-round-12 snapshots loadable by default-mode engines)
        if self._scout:
            ident["scout"] = True
        if self._double_buffer:
            ident["double_buffer"] = True
        if self._reduced:
            ident["reduced"] = True
        if self._theta_block > 1:
            ident["theta_block"] = int(self._theta_block)
        # round 20: online adaptation changes the admission/spillover
        # schedule — a snapshot taken with it armed must not resume
        # onto an engine without it (and vice versa)
        if self._adapt is not None:
            ident["adapt"] = True
        return ident

    # ------------------------------------------------------------------
    # request intake
    # ------------------------------------------------------------------

    def submit(self, theta, bounds, tenant: str = "default",
               priority: int = 1,
               deadline_phases: Optional[int] = None) -> int:
        """Queue one integration request; returns its request id.

        On a ``theta_block`` = T > 1 engine (round 13) ``theta`` may be
        a sequence of up to T per-user thetas — the request becomes a
        THETA BATCH scored over one shared union-refinement frontier,
        retiring with per-theta areas (``CompletedRequest.areas``).
        Scalar theta stays valid on every engine.

        Round 16: ``tenant``/``priority``/``deadline_phases`` feed
        admission control. A malformed submission (bad domain, over-
        limit theta batch, bad priority/deadline) raises ``ValueError``
        BEFORE a rid is consumed — the caller owns the rejection
        record. A well-formed submission always consumes a rid; under
        a full ``queue_limit`` the deterministic shed policy then
        refuses either the lowest-priority-oldest queued request or
        this one (whichever ranks lower), recording it on
        ``self.shed`` — check the returned rid against the shed
        records to learn this request's fate."""
        from ppls_tpu.models.integrands import check_ds_domain
        bounds = (float(bounds[0]), float(bounds[1]))
        if isinstance(theta, (tuple, list, np.ndarray)):
            thetas = tuple(float(t) for t in np.asarray(theta).reshape(-1))
            if not thetas:
                raise ValueError("empty theta batch")
            if len(thetas) > self._theta_block:
                raise ValueError(
                    f"theta batch of {len(thetas)} exceeds this "
                    f"engine's theta_block={self._theta_block}")
            theta_store = thetas if self._theta_block > 1 \
                else thetas[0]
        else:
            thetas = (float(theta),)
            theta_store = float(theta)
        check_ds_domain(self.f_ds,
                        np.tile(np.array([bounds]), (len(thetas), 1)),
                        np.array(thetas))
        tenant = str(tenant)
        if not tenant or len(tenant) > 128:
            raise ValueError(
                f"tenant must be a non-empty string of <= 128 chars, "
                f"got {tenant!r}")
        priority = int(priority)
        if deadline_phases is None:
            deadline_phases = self.default_deadline_phases
        if deadline_phases is not None:
            deadline_phases = int(deadline_phases)
            if deadline_phases < 1:
                raise ValueError(
                    f"deadline_phases must be >= 1, got "
                    f"{deadline_phases}")
        rid = self._next_rid
        self._next_rid += 1
        req = StreamRequest(
            rid=rid, theta=theta_store, bounds=bounds,
            submit_phase=self.phase, submit_t=time.perf_counter(),
            tenant=tenant, priority=priority,
            deadline_phases=deadline_phases)
        # round 19: the rid's causal trace starts at the ack — a
        # detached span that outlives phase spans and closes at the
        # terminal disposition (retire / shed), every hop an explicit
        # child event
        self._rid_spans[rid] = self.telemetry.request_span(
            rid, tenant=tenant, priority=priority,
            submit_phase=req.submit_phase)
        if self.queue_limit is not None \
                and len(self._pending) >= self.queue_limit:
            # deterministic shed policy: the victim is the lowest-
            # priority OLDEST queued request; the arrival must
            # STRICTLY outrank it to displace it, else the arrival
            # itself is shed. Either way the queue never exceeds the
            # limit and every refusal is an explicit record.
            victim = min(self._pending,
                         key=lambda r: (r.priority, r.rid))
            if victim.priority < req.priority:
                self._pending.remove(victim)
                self._shed_or_spill(victim)
            else:
                self._shed_or_spill(req)
                return rid
        self._pending.append(req)
        return rid

    def _shed_or_spill(self, req: StreamRequest) -> None:
        """Queue-overflow policy (round 18): route the victim to the
        CPU spillover backend when one is armed and the request is
        spill-eligible (no deadline — slower capacity cannot bound
        latency); otherwise shed with the explicit record, as before."""
        spillable = (self._spill is not None
                     and req.deadline_phases is None)
        if spillable and len(self._spill_queue) < self._spill_cap:
            self._spill_queue.append(req)
            self.telemetry.request_event(
                self._rid_spans.get(req.rid), "spillover_enqueued",
                rid=req.rid, tenant=req.tenant,
                phase=self.phase, submit_phase=req.submit_phase)
            return
        self._shed(req,
                   "spill_queue_full" if spillable else "queue_full")

    def _quota_for(self, tenant: str) -> Optional[dict]:
        if self.tenant_quotas is None:
            return None
        return self.tenant_quotas.get(tenant,
                                      self.tenant_quotas.get("*"))

    def _shed(self, req: StreamRequest, reason: str) -> ShedRecord:
        rec = ShedRecord(
            rid=req.rid, theta=req.theta, bounds=req.bounds,
            tenant=req.tenant, priority=req.priority, reason=reason,
            phase=self.phase, submit_phase=req.submit_phase)
        self.shed.append(rec)
        self._c_shed.labels(tenant=req.tenant, reason=reason).inc()
        self._token_waits.pop(req.rid, None)   # terminal: no leak
        span = self._rid_spans.pop(req.rid, None)
        self.telemetry.request_event(
            span, "request_shed", rid=req.rid, tenant=req.tenant,
            priority=req.priority, reason=reason, phase=self.phase,
            submit_phase=req.submit_phase)
        if span is not None:
            # shed is a terminal disposition: the rid's trace closes
            # with the refusal on it
            span.close(disposition="shed", reason=reason,
                       phase=self.phase)
        if self.on_shed is not None:
            self.on_shed(rec)
        return rec

    @property
    def next_rid(self) -> int:
        """Request ids are assigned in submission order — a resumed
        driver replaying a deterministic request sequence skips the
        first ``next_rid`` entries (already submitted pre-crash)."""
        return self._next_rid

    @property
    def pending(self) -> int:
        return len(self._pending)

    @property
    def resident(self) -> int:
        return len(self._slot_req)

    @property
    def free_capacity(self) -> int:
        """Slot headroom not already spoken for by queued admissions —
        the round-21 dispatcher's routing gate: it deals a request to
        this engine only when a seat is (or will next phase be) free,
        so pool-scope admission control composes with the per-engine
        slot occupancy instead of hiding load in the pending queue."""
        return max(0, self.slots - self.resident - self.pending)

    @property
    def idle(self) -> bool:
        """Nothing queued, resident, live on device, or awaiting the
        spillover backend."""
        return not self._pending and not self._slot_req \
            and self._count == 0 and not self._spill_queue

    # ------------------------------------------------------------------
    # device state
    # ------------------------------------------------------------------

    def _ensure_state(self, first: StreamRequest):
        if self._dev is not None:
            return
        fill_x = 0.5 * (first.bounds[0] + first.bounds[1])
        fill_th = float(first.thetas[0])
        self._fill = (float(fill_x), fill_th)
        # round 13: per-slot theta rows; recycled rows are overwritten
        # at admission, un-admitted rows keep the benign fill theta
        self._theta_table = np.full(
            (self.slots, self._theta_block), fill_th, dtype=np.float64)
        self._build_store()

    def _build_dd_store(self):
        """Lazy build of the multi-chip streaming program + stores.

        The compiled phase program is ``build_dd_walker_run`` with
        ``max_cycles=1`` and ``admit_window`` > 0: one demand-driven
        cycle per call, with admission folded into the phase boundary
        (seeds enter each chip's local queue as the phase opens and
        ride ``mesh.phase_reshard``'s occupancy decision + stratified
        deal at its end) and per-chip family live counts returned for
        retirement.
        """
        from ppls_tpu.parallel.sharded_walker import (_dd_sizing,
                                                      build_dd_walker_run)
        mesh = self._mesh
        n_dev = mesh.devices.size
        ck = self._cycle_kw
        target_local, breed_chunk, store, reshard_window = _dd_sizing(
            self.lanes, self._capacity, self._chunk,
            self._roots_per_lane)
        slack = store - self._capacity
        aw = max(1, min(-(-self._admit_window // n_dev), slack))
        self._dd_aw = aw
        self._admit_window = min(self._admit_window, aw * n_dev)
        fill_x, fill_th = self._fill
        self._dd_run = build_dd_walker_run(
            mesh, self.family, self.eps, int(breed_chunk),
            self._capacity, self.slots, self.lanes,
            ck["seg_iters"], ck["max_segments"],
            ck["min_active_frac"], ck["exit_frac"], ck["suspend_frac"],
            int(target_local), self.interpret, 1, fill_x, fill_th,
            self.rule, ck["sort_roots"], ck["sort_skip_ratio"],
            self._refill_slots, int(reshard_window), admit_window=aw,
            scout=self._scout, double_buffer=self._double_buffer,
            reduced=self._reduced, theta_block=self._theta_block)
        self._dd_store = store
        self._dd_n_dev = n_dev
        m_eff = self.slots * self._theta_block
        z64 = jnp.zeros(n_dev, jnp.int64)
        self._dd_state = (
            jnp.full((n_dev * store,), fill_x, jnp.float64),
            jnp.full((n_dev * store,), fill_x, jnp.float64),
            jnp.full((n_dev * store,), fill_th, jnp.float64),
            jnp.zeros((n_dev * store,), jnp.int32),
            jnp.zeros(n_dev, jnp.int32),
            jnp.zeros((n_dev, m_eff), jnp.float64))
        self._dd_counters = tuple(z64 for _ in range(11)) + (
            jnp.zeros((n_dev, N_WASTE), jnp.int64),
            jnp.zeros((n_dev, 2), jnp.int64),
            jnp.zeros(n_dev, jnp.int32),
            jnp.zeros(n_dev, jnp.int32),
            jnp.zeros(n_dev, dtype=bool))
        self._dd_prev = np.zeros(11, dtype=np.int64)
        self._dd_prev_waste = np.zeros(N_WASTE, dtype=np.int64)
        self._dd_prev_evals = np.zeros(2, dtype=np.int64)
        self._dd_prev_acc = np.zeros(m_eff)
        self._dd_fam_last = np.full(self.slots, -1, np.int32)
        self._dd_rr = 0
        self._dd_admit = None
        # per-chip flight recorder (round 11): previous-phase per-chip
        # cumulative counters so each phase's chip spans carry DELTAS,
        # and per-chip live-row counts for the bank-occupancy deltas
        self._dd_prev_chip = {
            "wsteps": np.zeros(n_dev, np.int64),
            "tasks": np.zeros(n_dev, np.int64),
            "crounds": np.zeros(n_dev, np.int64),
            "waste": np.zeros((n_dev, N_WASTE), np.int64),
        }
        self._dd_prev_count = np.zeros(n_dev, np.int64)
        self._flight = ChipFlightRecorder(
            self.telemetry, n_dev, engine=f"{self.engine}-stream")
        self._dev = True        # marks state as built

    def _build_store(self):
        fill_x, fill_th = self._fill
        if self.engine == "walker-dd":
            self._build_dd_store()
            return
        store = self._store
        m_eff = self.slots * self._theta_block
        bag = BagState(
            bag_l=jnp.full(store, fill_x, jnp.float64),
            bag_r=jnp.full(store, fill_x, jnp.float64),
            bag_th=jnp.full(store, fill_th, jnp.float64),
            bag_meta=jnp.zeros(store, jnp.int32),
            count=jnp.asarray(0, jnp.int32),
            acc=jnp.zeros(m_eff, jnp.float64),
            tasks=jnp.zeros((), jnp.int64),
            splits=jnp.zeros((), jnp.int64),
            iters=jnp.zeros((), jnp.int64),
            max_depth=jnp.zeros((), jnp.int32),
            overflow=jnp.zeros((), bool))
        self._dev = dict(
            bag=bag,
            acc=jnp.zeros(m_eff, jnp.float64),
            acc_c=jnp.zeros(m_eff, jnp.float64),
            fam_last=jnp.full(self.slots, -1, jnp.int32))

    # ------------------------------------------------------------------
    # the phase loop
    # ------------------------------------------------------------------

    def _refill_tokens(self) -> None:
        """Phase-open token-bucket refill: deterministic, rate tokens
        per phase up to burst, for every tenant seen so far."""
        if self.tenant_quotas is None:
            return
        for tenant in self._tokens:
            q = self._quota_for(tenant)
            if q is not None:
                self._tokens[tenant] = min(
                    q["burst"], self._tokens[tenant] + q["rate"])

    def _shed_unmeetable(self) -> None:
        """Shed queued requests whose deadline can no longer be met
        (deadline phase already behind the current phase): spending a
        slot on them would only burn capacity the live requests need —
        the canonical overload-shedding move."""
        victims = [r for r in self._pending
                   if r.deadline_phase is not None
                   and r.deadline_phase < self.phase]
        for req in victims:
            self._pending.remove(req)
            self._shed(req, "deadline_exceeded")

    def _select_for_admission(self) -> List[StreamRequest]:
        """Pick this phase's admissions (round 16): budget = free
        slots x admit window x bag headroom, order = (-priority, rid)
        — higher classes first, FIFO within a class — gated by the
        per-tenant token buckets (an out-of-tokens tenant's requests
        are SKIPPED, not shed; they keep their queue position).
        Selected requests are removed from the pending queue and a
        token is consumed per admission."""
        import heapq
        cap = self._capacity
        if self.engine == "walker-dd" and self._mesh is not None:
            cap *= self._mesh.devices.size      # per-chip capacity
        room = cap - self._count
        budget = max(0, min(len(self._free), self._admit_window, room))
        if self._adapt is not None:
            # round 20: the online admission budget NARROWS the
            # compiled admit window within its safe band (the window
            # stays in the min above — the seed-array width is a
            # compile static the adapter must never exceed)
            budget = min(budget, self._adapt.values["admit_budget"])
        if not budget or not self._pending:
            return []
        chosen: List[StreamRequest] = []
        if self.tenant_quotas is None:
            # no token gating: the selection is exactly the budget-many
            # best-ranked requests — O(n log budget) instead of a full
            # sort every phase (the pending queue is the thing that
            # grows under the overload this tier exists for)
            chosen = heapq.nsmallest(
                budget, self._pending,
                key=lambda r: (-r.priority, r.rid))
        else:
            for req in sorted(self._pending,
                              key=lambda r: (-r.priority, r.rid)):
                if len(chosen) >= budget:
                    break
                q = self._quota_for(req.tenant)
                if q is not None:
                    if req.tenant not in self._tokens:
                        # first sight (incl. a pending request
                        # restored from a pre-round-16 snapshot):
                        # bucket starts full
                        self._tokens[req.tenant] = q["burst"]
                    if self._tokens[req.tenant] < 1.0:
                        # round 19: the token-bucket wait is a hop on
                        # the rid's causal trace — counted (the admit
                        # event reports the total) and emitted per
                        # waited phase, both deterministic functions
                        # of the schedule
                        self._token_waits[req.rid] = \
                            self._token_waits.get(req.rid, 0) + 1
                        self.telemetry.request_event(
                            self._rid_spans.get(req.rid),
                            "token_wait", rid=req.rid,
                            tenant=req.tenant, phase=self.phase)
                        continue
                    self._tokens[req.tenant] -= 1.0
                chosen.append(req)
        if chosen:
            taken = {r.rid for r in chosen}
            self._pending = [r for r in self._pending
                             if r.rid not in taken]
        return chosen

    def _admit(self) -> List[StreamRequest]:
        chosen = self._select_for_admission()
        if self._dev is None:
            if not chosen:
                return []
            self._ensure_state(chosen[0])
        if not chosen and not self._clear_pending():
            return []
        n_new = len(chosen)
        A = self._admit_window
        fill_x, fill_th = self._fill
        sl = np.full(A, fill_x)
        sr = np.full(A, fill_x)
        sth = np.full(A, fill_th)
        sm = np.zeros(A, dtype=np.int32)
        clear = np.zeros(self.slots, dtype=bool)
        admitted = []
        for i, req in enumerate(chosen):
            slot = self._free.pop(0)
            sl[i], sr[i] = req.bounds
            row = req.thetas
            # frontier rows carry the batch's REPRESENTATIVE theta
            # (row[0]) for work-scoring; short batches pad the slot's
            # theta row by replicating it (padded lanes vote and
            # credit identically — discarded at retirement)
            sth[i] = row[0]
            if self._theta_block > 1:
                pad = row + (row[0],) * (self._theta_block - len(row))
                self._theta_table[slot] = pad
            if self.fault_injector is not None \
                    and self.fault_injector.on_admit(req.rid):
                # nan_poison: corrupt the admitted theta payload AFTER
                # submit-time validation — poison that slipped the
                # gate; the engine genuinely computes with it and the
                # slot's area goes non-finite at retirement
                sth[i] = float("nan")
                if self._theta_block > 1:
                    self._theta_table[slot] = float("nan")
            sm[i] = np.int32(slot << DEPTH_BITS)
            clear[slot] = True       # recycle: zero the slot's acc pair
            self._slot_req[slot] = req
            self._records[req.rid] = dict(
                slot=slot, admit_phase=self.phase)
            self._fam_first[slot] = self.phase
            admitted.append(req)
            # round 19: the admit event is a request-span child and
            # carries the QUEUE-WAIT decomposition — total phases
            # queued, of which token-bucket waits (both exact
            # schedule functions; analyze_request sums them back to
            # the retire latency bit-for-bit)
            self.telemetry.request_event(
                self._rid_spans.get(req.rid),
                "admit", rid=req.rid, slot=slot, phase=self.phase,
                theta=(list(row) if self._theta_block > 1
                       else req.theta),
                bounds=list(req.bounds),
                submit_phase=req.submit_phase,
                queue_wait_phases=self.phase - req.submit_phase,
                token_wait_phases=self._token_waits.pop(req.rid, 0),
                tenant=req.tenant, priority=req.priority)
        if n_new:
            self._c_admitted.inc(n_new)
        self._apply_admit(sl, sr, sth, sm, n_new, clear)
        self._count += n_new
        return admitted

    def _clear_pending(self) -> bool:
        """Whether an admit call is needed even with zero admissions
        (no deferred clears in this design — clears ride admissions)."""
        return False

    def _apply_admit(self, sl, sr, sth, sm, n_new, clear):
        if self.engine == "walker-dd":
            # stage per-chip dense-prefix blocks for the next phase
            # call: the host deals requests round-robin over chips and
            # the device program pushes each chip's block as the phase
            # opens (build_dd_walker_run's admit_window path)
            n_dev, aw = self._dd_n_dev, self._dd_aw
            fill_x, fill_th = self._fill
            bl = np.full((n_dev, aw), fill_x)
            br = np.full((n_dev, aw), fill_x)
            bth = np.full((n_dev, aw), fill_th)
            bm = np.zeros((n_dev, aw), dtype=np.int32)
            cnt = np.zeros(n_dev, dtype=np.int32)
            for i in range(n_new):
                chip = self._dd_rr % n_dev
                self._dd_rr += 1
                k = cnt[chip]
                bl[chip, k], br[chip, k] = sl[i], sr[i]
                bth[chip, k] = sth[i]
                bm[chip, k] = sm[i]
                cnt[chip] = k + 1
            self._dd_admit = (bl.reshape(-1), br.reshape(-1),
                              bth.reshape(-1), bm.reshape(-1), cnt,
                              np.tile(clear, (n_dev, 1)))
            return
        d = self._dev
        bag, acc, acc_c, fam_last = _admit_program(
            d["bag"], d["acc"], d["acc_c"], d["fam_last"],
            jnp.asarray(sl), jnp.asarray(sr), jnp.asarray(sth),
            jnp.asarray(sm), jnp.asarray(n_new, jnp.int32),
            jnp.asarray(clear), capacity=self._capacity)
        self._dev = dict(bag=bag, acc=acc, acc_c=acc_c,
                         fam_last=fam_last)

    def _cycle_and_pull(self):
        """One device phase; returns (fam_live, acc, acc_c, fam_last,
        count, overflow, stats_row) as host values."""
        return self._cycle_pull(self._cycle_launch())

    def _cycle_launch(self):
        """LAUNCH half of the phase cycle (round 22, overlapped
        boundaries): enqueue the compiled cycle program and install
        the device-array carry — no ``device_get``, so the call
        returns while the device still computes. The opaque launch
        token it returns must be handed to :meth:`_cycle_pull` on the
        SAME engine before any other launch on this engine."""
        if self.engine == "walker-dd":
            return self._dd_cycle_launch()
        d = self._dev
        tt = (jnp.asarray(self._theta_table)
              if self._theta_block > 1 else None)
        out = run_stream_cycle(
            d["bag"], d["acc"], d["acc_c"], d["fam_last"],
            jnp.asarray(self.phase, jnp.int32), tt, **self._cycle_kw)
        self._dev = dict(bag=out.bag, acc=out.acc, acc_c=out.acc_c,
                         fam_last=out.fam_last)
        return out

    def _cycle_pull(self, out):
        """PULL half: block on the launch token's host fetch and fold
        the counter deltas (the only ``device_get`` of the phase)."""
        if self.engine == "walker-dd":
            return self._dd_cycle_pull(out)
        fam_live, acc, acc_c, fam_last, count, overflow, stats = \
            jax.device_get((out.fam_live, out.acc, out.acc_c,
                            out.fam_last, out.bag.count,
                            out.bag.overflow, out.stats))
        return (np.asarray(fam_live), np.asarray(acc),
                np.asarray(acc_c), np.asarray(fam_last), int(count),
                bool(overflow), np.asarray(stats))

    def _dd_cycle_and_pull(self):
        return self._dd_cycle_pull(self._dd_cycle_launch())

    def _dd_cycle_launch(self):
        n_dev, aw = self._dd_n_dev, self._dd_aw
        if self._dd_admit is None:
            # no admissions this phase: empty blocks, no clears
            fill_x, fill_th = self._fill
            self._dd_admit = (
                np.full(n_dev * aw, fill_x), np.full(n_dev * aw, fill_x),
                np.full(n_dev * aw, fill_th),
                np.zeros(n_dev * aw, np.int32),
                np.zeros(n_dev, np.int32),
                np.zeros((n_dev, self.slots), dtype=bool))
        adm = tuple(jnp.asarray(a) for a in self._dd_admit)
        self._dd_admit = None
        tt_arg = ()
        if self._theta_block > 1:
            tt_arg = (jnp.broadcast_to(
                jnp.asarray(self._theta_table)[None],
                (n_dev, self.slots, self._theta_block)),)
        out = self._dd_run(*self._dd_state, *self._dd_counters, *adm,
                           *tt_arg)
        # the carry for the NEXT launch is device-array refs off the
        # in-flight computation — installing it here (before any host
        # fetch) is what lets another engine's pull overlap this one's
        # device compute
        self._dd_state = out[:4] + (out[4], out[5])
        # cycles counter resets each phase call (max_cycles=1): pass
        # zeros back in, like the leg loop does between legs
        self._dd_counters = out[6:17] + (
            out[17], out[18], out[19],
            jnp.zeros(self._dd_n_dev, jnp.int32), out[21])
        return out

    def _dd_cycle_pull(self, out):
        fam_live_c = out[22]
        (count_c, acc_c2, ctr_h, waste_h, evals_h, maxd_c, ovf_c,
         fam_live) = jax.device_get(
            (out[4], out[5], out[6:17], out[17], out[18],
             out[19], out[21], fam_live_c))
        chip = {k: np.asarray(v, dtype=np.int64)
                for k, v in zip(
                    ("tasks", "splits", "btasks", "wtasks", "wsplits",
                     "roots", "rounds", "segs", "wsteps", "srows",
                     "crounds"), ctr_h)}
        chip["waste"] = np.asarray(waste_h, dtype=np.int64)
        totals = np.array([int(np.sum(chip[k])) for k in
                           ("tasks", "splits", "btasks", "wtasks",
                            "wsplits", "roots", "rounds", "segs",
                            "wsteps", "srows", "crounds")],
                          dtype=np.int64)
        delta = totals - self._dd_prev
        self._dd_prev = totals
        waste_tot = chip["waste"].sum(axis=0)
        waste_delta = waste_tot - self._dd_prev_waste
        self._dd_prev_waste = waste_tot
        evals_tot = np.asarray(evals_h, dtype=np.int64).sum(axis=0)
        evals_delta = evals_tot - self._dd_prev_evals
        self._dd_prev_evals = evals_tot
        # per-chip flight-recorder deltas (round 11): same fetch, host
        # subtraction — step() hands these to ChipFlightRecorder while
        # the phase span is still open
        count_pc = np.asarray(count_c, dtype=np.int64)
        self._chip_phase_rec = {
            "wsteps": chip["wsteps"] - self._dd_prev_chip["wsteps"],
            "tasks": chip["tasks"] - self._dd_prev_chip["tasks"],
            "waste": chip["waste"] - self._dd_prev_chip["waste"],
            "live_rows": count_pc,
            "bank_delta": count_pc - self._dd_prev_count,
            # crounds is replicated (every chip counts the same
            # lockstep boundaries): the scalar per-phase delta
            "crounds": int(chip["crounds"].max(initial=0)
                           - self._dd_prev_chip["crounds"]
                           .max(initial=0)),
        }
        self._dd_prev_chip = {k: chip[k].copy() for k in
                              ("wsteps", "tasks", "crounds", "waste")}
        self._dd_prev_count = count_pc
        acc = np.sum(np.asarray(acc_c2), axis=0)      # fixed chip order
        credited = acc != self._dd_prev_acc
        if self._theta_block > 1:
            # per-slot credit mark: any of the slot's T thetas credited
            credited = credited.reshape(
                self.slots, self._theta_block).any(axis=1)
        self._dd_fam_last = np.where(credited, self.phase,
                                     self._dd_fam_last).astype(np.int32)
        self._dd_prev_acc = acc
        fam_live_tot = np.sum(np.asarray(fam_live), axis=0)
        count = int(np.sum(count_pc))
        # CTR64 order: tasks, splits, btasks, wtasks, wsplits, roots,
        # rounds, segs, wsteps, srows, crounds -> STREAM_STAT_FIELDS
        # (splits and crounds land in the round-10 tail columns; the dd
        # stream is the one engine with a nonzero per-phase crounds;
        # round 11 appends the lane-waste bucket deltas)
        stats = np.concatenate([np.array([
            delta[0], delta[2], delta[3], delta[4], delta[5],
            delta[6], delta[7], delta[8], delta[9],
            int(np.max(np.asarray(maxd_c))),
            count, int(np.sum(fam_live_tot > 0)),
            delta[1], delta[10]], dtype=np.int64), waste_delta,
            evals_delta])

        return (fam_live_tot, acc, np.zeros_like(acc),
                self._dd_fam_last, count, bool(np.any(np.asarray(ovf_c))),
                stats)

    def _publish_phase_row(self, row: np.ndarray) -> dict:
        """Fold one device-counted phase row into the registry (the
        counters bench/serve/analyze all read). Host arithmetic on
        values :meth:`_cycle_and_pull` already fetched."""
        vals = {k: int(v) for k, v in zip(STREAM_STAT_FIELDS, row)}
        for k, c in self._stat_counters.items():
            c.inc(vals[k])
        self._g_maxd.set_max(vals["maxd"])
        self._g_live_tasks.set(vals["live_tasks"])
        return vals

    def _publish_gauges(self, step_wall_s: float = 0.0) -> None:
        self._g_queue.set(len(self._pending))
        self._g_resident.set(len(self._slot_req))
        self._g_free.set(len(self._free))
        self._g_phase.set(self.phase)
        for (q, unit), g in self._g_lat.items():
            h = (self._h_lat_phases if unit == "phases"
                 else self._h_lat_seconds)
            v = h.quantile(q)
            if v is not None:
                g.set(v)
        fn = (run_stream_cycle if self.engine == "walker"
              else getattr(self, "_dd_run", None))
        cache_size = getattr(fn, "_cache_size", None)
        if callable(cache_size):
            # compile observability (round 11): cache growth during
            # this step is a recompile — publish_compile emits the
            # jit_cache_entry event / recompile counter and attributes
            # this step's wall to the compile-wall counter
            self.telemetry.publish_compile(
                f"{self.engine}-stream", int(cache_size()),
                wall_s=step_wall_s)

    def _mesh_width(self) -> int:
        return self._mesh.devices.size if self._mesh is not None else 1

    def _account_retirement(self, c: CompletedRequest,
                            slot: int) -> None:
        """Registry + event accounting shared by every retirement path
        (normal, quarantine, deadline expiry): one place so the global
        and the tenant/class-labeled surfaces can never drift."""
        self._c_retired.inc()
        self._c_tenant_retired.labels(tenant=c.tenant).inc()
        self._h_lat_phases.observe(c.latency_phases)
        self._h_lat_seconds.observe(c.latency_s)
        self._h_class_lat.labels(priority=str(c.priority)) \
            .observe(c.latency_phases)
        self._h_tenant_lat.labels(tenant=c.tenant) \
            .observe(c.latency_phases)
        ok = not c.failed
        # every attr below except latency_s is device-counted or
        # schedule-determined: bit-stable across rerun and resume
        # (failed retirements carry area=None — the non-finite payload
        # would not be strict JSON)
        span = self._rid_spans.pop(c.rid, None)
        self.telemetry.request_event(
            span, "retire", rid=c.rid, slot=slot,
            area=(c.area if ok else None),
            **({"areas": c.areas}
               if c.areas is not None and ok else {}),
            failed=c.failed,
            **({"failure": c.failure} if c.failure else {}),
            **({"spillover": True}
               if getattr(c, "spillover", False) else {}),
            submit_phase=c.submit_phase,
            admit_phase=c.admit_phase,
            retire_phase=c.retire_phase,
            latency_phases=c.latency_phases,
            first_seeded_phase=c.first_seeded_phase,
            last_credited_phase=c.last_credited_phase,
            latency_s=round(c.latency_s, 6),
            tenant=c.tenant, priority=c.priority)
        if span is not None:
            # retirement closes the rid's trace — the span summary is
            # the deterministic latency record
            span.close(
                disposition=("failed" if c.failed else "retired"),
                **({"failure": c.failure} if c.failure else {}),
                retire_phase=c.retire_phase,
                latency_phases=c.latency_phases)

    def _cancel_slots(self, kill: np.ndarray) -> None:
        """Compact the cancelled slots' live rows out of the device
        bag(s) (deadline expiry). Between phases ALL walk state lives
        in the bag (lane state folds back at every cycle edge), so
        after the compaction nothing can credit the freed slots again
        — the same invariant the recycle path relies on. Rare-path
        boundary work: one jitted one-shape program + one count fetch."""
        k = jnp.asarray(kill)
        if self.engine == "walker-dd":
            bl, br, bth, bm, counts, acc = self._dd_state
            bl, br, bth, bm, counts = _dd_cancel_program(
                bl, br, bth, bm, counts, k)
            self._dd_state = (bl, br, bth, bm, counts, acc)
            self._count = int(np.sum(np.asarray(
                jax.device_get(counts))))
        else:
            d = self._dev
            bag = _cancel_program(d["bag"], k)
            self._dev = dict(d, bag=bag)
            self._count = int(jax.device_get(bag.count))
        # the cancelled slots are drained by construction now — keep
        # the host-side live view consistent for result()/idle
        self._last_fam_live = np.where(kill, 0, self._last_fam_live)

    def last_phase_row(self) -> Optional[dict]:
        """The most recent device-counted phase row as a field dict
        (None before the first non-idle phase). The cluster worker
        protocol reads its per-phase deltas here — host values the
        boundary already fetched, no device work."""
        if not self._phase_rows:
            return None
        return {k: int(v) for k, v in
                zip(STREAM_STAT_FIELDS, self._phase_rows[-1])}

    def phase_rows_len(self) -> int:
        """How many non-idle phase rows exist (the cluster worker
        pairs this with :meth:`last_phase_row` to tell a fresh row
        from a stale one across an idle phase)."""
        return len(self._phase_rows)

    def _run_spillover_phase(self) -> List[CompletedRequest]:
        """Phase-boundary spillover batch (round 18): up to
        ``spillover_limit`` queued overflow victims run TO COMPLETION
        on the CPU backend — deterministic schedule (rid order),
        host-side boundary work only. The completed record carries
        ``spillover=True`` and the engagement is device-counted by
        the bag engine's own task counters
        (``ppls_spillover_tasks_total``)."""
        if self._spill is None or not self._spill_queue:
            return []
        out = []
        n = 0
        limit = (self.spillover_limit if self._adapt is None
                 else self._adapt.values["spillover_limit"])
        while self._spill_queue and n < limit:
            req = self._spill_queue.pop(0)
            failed = False
            areas = None
            try:
                areas, _tasks, _wall = self._spill.run(req.theta,
                                                       req.bounds)
            except FloatingPointError:
                # the quarantine contract covers the spillover path
                # too: a poisoned request becomes a FAILED record,
                # never an engine-wide abort stranding healthy work
                if not self.quarantine:
                    raise
                failed = True
                self.telemetry.request_event(
                    self._rid_spans.get(req.rid), "quarantine",
                    rid=req.rid, phase=self.phase, spillover=True)
                self._c_quarantined.inc()
            batched = isinstance(req.theta, (tuple, list))
            c = CompletedRequest(
                rid=req.rid, theta=req.theta, bounds=req.bounds,
                area=(float("nan") if failed else areas[0]),
                areas=(list(areas) if batched and not failed
                       else None),
                submit_phase=req.submit_phase,
                admit_phase=self.phase, retire_phase=self.phase,
                latency_s=time.perf_counter() - req.submit_t,
                first_seeded_phase=-1, last_credited_phase=-1,
                failed=failed,
                failure=("nan" if failed else None),
                tenant=req.tenant, priority=req.priority,
                spillover=True)
            out.append(c)
            self._c_spillover.inc()
            self._account_retirement(c, slot=-1)
            n += 1
        return out

    def _maybe_adapt(self, vals: Optional[dict]) -> None:
        """Round 20 online adaptation at the phase boundary: derive
        per-knob pressures from the stats row this boundary already
        fetched (``vals``; None on idle phases) plus host queue
        depths, fold them through the adapter (hysteresis + one-step
        clamps + safe bands live there), emit one ``knob_adapt``
        timeline event per applied change, refresh the gauges. Pure
        host arithmetic — zero new device fetches — and every input
        is a deterministic function of the schedule, so a resumed run
        replays the identical trajectory from the snapshot state."""
        if self._adapt is None:
            return
        from ppls_tpu.runtime.tune import ADAPT_WASTE_FRAC
        a = self._adapt
        pressures = {}
        pending = len(self._pending)
        lazy = 0.0
        if vals is not None:
            denom = max(1, int(vals.get("wsteps", 0)) * self.lanes)
            lazy = (int(vals.get("drain_tail", 0))
                    + int(vals.get("masked_dead", 0))) / denom
        if pending > 0 and (vals is None
                            or lazy >= ADAPT_WASTE_FRAC):
            # backlog + underfed lanes (drain_tail/masked_dead share
            # of the phase's lane-steps): open the admission budget
            pressures["admit_budget"] = 1
        elif pending == 0 and a.values["admit_budget"] \
                > a.defaults["admit_budget"]:
            pressures["admit_budget"] = -1
        backlog = len(self._spill_queue)
        if backlog > a.values["spillover_limit"]:
            pressures["spillover_limit"] = 1
        elif backlog == 0 and a.values["spillover_limit"] \
                > a.defaults["spillover_limit"]:
            pressures["spillover_limit"] = -1
        for ch in a.observe(pressures):
            self.telemetry.event("knob_adapt", phase=self.phase,
                                 **ch)
        for k, g in self._g_adapt.items():
            g.set(float(a.values[k]))

    def step(self) -> List[CompletedRequest]:
        """One phase: admit -> cycle -> retire. Returns the requests
        retired this phase (empty when idle)."""
        return self.step_finish(self.step_begin())

    def step_begin(self):
        """LAUNCH half of one phase (round 22, overlapped
        boundaries): fault-open hook, phase span, admission policy,
        and the compiled cycle launch — everything up to (but
        excluding) the blocking host fetch. Returns an opaque token
        for :meth:`step_finish`; between the two calls NOTHING else
        may drive this engine (the dispatcher's overlapped turn loop
        owns that discipline), but OTHER engines may launch/finish
        freely — that interleaving is the whole point."""
        tel = self.telemetry
        t_step0 = time.perf_counter()
        if self.fault_injector is not None:
            # phase-OPEN fault boundary (before admission, before the
            # phase span): a crash/chip-loss here is the worst resume
            # point — this phase's admissions replay in the recovery
            self.fault_injector.on_phase_open(self.phase,
                                              n_dev=self._mesh_width())
        span = tel.span("phase", phase=self.phase)
        # round 16 phase-open policy: refill the tenant token buckets,
        # then shed queued requests whose deadline is already
        # unmeetable — both deterministic functions of the phase index
        self._refill_tokens()
        self._shed_unmeetable()
        self._admit()
        if self._count == 0 and not self._slot_req:
            return ("idle", span, t_step0, None)
        return ("cycle", span, t_step0, self._cycle_launch())

    def step_finish(self, token) -> List[CompletedRequest]:
        """PULL half of one phase: block on the launch's host fetch,
        then retire/account/snapshot exactly as the historical
        monolithic ``step`` did. ``step() ==
        step_finish(step_begin())`` bit-for-bit."""
        kind, span, t_step0, launch = token
        tel = self.telemetry
        if kind == "idle":
            # nothing live on device (and nothing was admissible): an
            # idle phase costs no device work — but a queued spillover
            # batch still runs (the drained-tail engagement case) —
            # and the phase counter still advances so open-loop
            # arrival schedules with gaps make progress
            spilled = self._run_spillover_phase()
            self.completed.extend(spilled)
            # round 20: idle phases still adapt (a drained-tail
            # spillover backlog is exactly the pressure the spillover
            # knob watches) — with no stats row, only the queue-depth
            # pressures apply
            self._maybe_adapt(None)
            self.phase += 1
            self._publish_gauges()
            if self._slo is not None:
                self._slo.evaluate_slo(self.phase)
            span.close(idle=not spilled, retired=len(spilled))
            # the idle branch still honors the snapshot cadence and
            # the phase-close fault boundary: a drained-tail spillover
            # run makes real progress here, and a kill mid-tail must
            # not re-run (and re-print) every completed bag round
            if self.checkpoint_path and \
                    self.phase % self.checkpoint_every == 0:
                self.snapshot()
            if self.fault_injector is not None:
                self.fault_injector.on_phase_close(
                    self.phase - 1, n_dev=self._mesh_width())
            return spilled
        (fam_live, acc, acc_c, fam_last, count, overflow,
         stats) = self._cycle_pull(launch)
        if self.engine == "walker-dd" and \
                getattr(self, "_chip_phase_rec", None) is not None:
            # per-chip flight recorder (round 11): chip child spans +
            # collective-boundary event under the still-open phase
            # span, from the deltas the pull above already computed
            rec = self._chip_phase_rec
            self._chip_phase_rec = None
            self._flight.record_phase(
                self.phase, wsteps=rec["wsteps"], tasks=rec["tasks"],
                live_rows=rec["live_rows"],
                bank_delta=rec["bank_delta"], waste=rec["waste"],
                crounds=rec["crounds"])
        self._last_fam_live = fam_live
        self._last_fam_last = np.asarray(fam_last, dtype=np.int32)
        if overflow:
            tel.event("overflow", phase=self.phase, count=int(count))
            span.close(error="overflow")
            raise RuntimeError(
                "stream walker bag overflowed; raise capacity or lower "
                "the offered load / admit window")
        self._count = count
        row = stats.astype(np.int64)
        self._phase_rows.append(row)
        vals = self._publish_phase_row(row)
        if tel.tracer.enabled:
            # round 19: per-rid phase residency — one request-span
            # child event per resident request, linking this phase's
            # span by id so the causal trace names every compute
            # phase the rid was live in. Slots bound the fan-out.
            for slot in sorted(self._slot_req):
                req = self._slot_req[slot]
                tel.request_event(
                    self._rid_spans.get(req.rid), "request_phase",
                    rid=req.rid, slot=slot, phase=self.phase,
                    phase_span=span.sid)
        retired = []
        now = time.perf_counter()
        for slot in sorted(self._slot_req):
            if fam_live[slot] != 0:
                continue
            req = self._slot_req.pop(slot)
            rec = self._records.pop(req.rid)
            T = self._theta_block
            if T > 1:
                seg = (acc.reshape(self.slots, T)[slot]
                       + acc_c.reshape(self.slots, T)[slot])
                areas = [float(v) for v in seg[:len(req.thetas)]]
                area = areas[0]
                finite = np.all(np.isfinite(areas))
            else:
                areas = None
                area = float(acc[slot] + acc_c[slot])
                finite = np.isfinite(area)
            if not finite and not self.quarantine:
                tel.event("nan_retire", rid=req.rid, slot=slot,
                          phase=self.phase)
                span.close(error="nan_retire")
                raise FloatingPointError(
                    f"stream request {req.rid} produced a non-finite "
                    f"area — refusing to report garbage")
            if not finite:
                # round 14 quarantine: the poison stays contained in
                # this slot's accumulator lane, which the recycle path
                # clears at the slot's next admission — every healthy
                # concurrent request retires through the branch below
                # untouched. The failed record keeps the request's
                # latency accounting so SLO math sees the failure.
                tel.request_event(self._rid_spans.get(req.rid),
                                  "quarantine", rid=req.rid,
                                  slot=slot, phase=self.phase)
                self._c_quarantined.inc()
            c = CompletedRequest(
                rid=req.rid, theta=req.theta, bounds=req.bounds,
                area=area, areas=areas,
                submit_phase=req.submit_phase,
                admit_phase=rec["admit_phase"],
                retire_phase=self.phase,
                latency_s=now - req.submit_t,
                first_seeded_phase=int(self._fam_first[slot]),
                last_credited_phase=int(fam_last[slot]),
                failed=not finite,
                tenant=req.tenant, priority=req.priority,
                failure=(None if finite else "nan"))
            retired.append(c)
            self._free.append(slot)
            self._account_retirement(c, slot)
        # round 16 DEADLINE EXPIRY: any still-resident request whose
        # deadline phase is this phase or earlier missed its budget —
        # retire it as a FAILED record (the round-14 path) and compact
        # its live rows out of the bag so the engine stops spending
        # lane-steps on work nobody will accept. The freed slot is
        # immediately reusable: after the compaction no row can credit
        # it, and the recycle path clears its accumulator at the next
        # admission.
        kill = None
        for slot in sorted(self._slot_req):
            req = self._slot_req[slot]
            dp = req.deadline_phase
            if dp is None or self.phase < dp:
                continue
            self._slot_req.pop(slot)
            rec = self._records.pop(req.rid)
            c = CompletedRequest(
                rid=req.rid, theta=req.theta, bounds=req.bounds,
                area=float("nan"), areas=None,
                submit_phase=req.submit_phase,
                admit_phase=rec["admit_phase"],
                retire_phase=self.phase,
                latency_s=now - req.submit_t,
                first_seeded_phase=int(self._fam_first[slot]),
                last_credited_phase=int(fam_last[slot]),
                failed=True, tenant=req.tenant,
                priority=req.priority, failure="deadline_exceeded")
            tel.request_event(self._rid_spans.get(req.rid),
                              "deadline_exceeded", rid=req.rid,
                              slot=slot, phase=self.phase,
                              deadline_phase=dp, tenant=req.tenant)
            self._c_deadline.labels(tenant=req.tenant).inc()
            retired.append(c)
            self._free.append(slot)
            if kill is None:
                kill = np.zeros(self.slots, dtype=bool)
            kill[slot] = True
            self._account_retirement(c, slot)
        if kill is not None:
            self._cancel_slots(kill)
        self._free.sort()
        retired.extend(self._run_spillover_phase())
        self.completed.extend(retired)
        # round 20: fold this phase's already-fetched stats row into
        # the online adapter (the values take effect NEXT phase)
        self._maybe_adapt(vals)
        self.phase += 1
        self._publish_gauges(step_wall_s=time.perf_counter() - t_step0)
        if self._slo is not None:
            # round 19: the burn-rate evaluator runs on the registry
            # state this boundary just published — the one device
            # fetch retirement already paid covers it
            self._slo.evaluate_slo(self.phase)
        # the phase span closes carrying the phase's device-counter
        # delta row — the timeline IS the per-phase stats trail
        span.close(retired=len(retired), **vals)
        if self.checkpoint_path and \
                self.phase % self.checkpoint_every == 0:
            self.snapshot()
        if self.fault_injector is not None:
            # phase-CLOSE fault boundary (after the snapshot, so a
            # close-keyed crash resumes from this phase's freshest
            # state); self.phase already advanced — key on the phase
            # that just closed
            self.fault_injector.on_phase_close(
                self.phase - 1, n_dev=self._mesh_width())
        return retired

    def drain(self, max_phases: int = 1 << 14,
              _crash_after_phases: Optional[int] = None
              ) -> List[CompletedRequest]:
        """Run phases until the engine is idle; returns everything
        retired during the drain."""
        done: List[CompletedRequest] = []
        phases = 0
        while not self.idle:
            done.extend(self.step())
            phases += 1
            if _crash_after_phases is not None \
                    and phases >= _crash_after_phases:
                raise RuntimeError(
                    f"simulated crash after {phases} phases (test hook)")
            if phases >= max_phases:
                raise RuntimeError(
                    f"stream did not drain in {max_phases} phases "
                    f"({self._count} tasks, {self.resident} resident, "
                    f"{self.pending} pending)")
        return done

    def run(self, requests: Sequence[Tuple[float, Tuple[float, float]]],
            arrival_phase: Optional[Sequence[int]] = None,
            _crash_after_phases: Optional[int] = None) -> StreamResult:
        """Convenience driver: submit ``requests`` (theta, bounds)
        pairs — or (theta, bounds, kwargs) triples carrying
        tenant/priority/deadline_phases (round 16) — all up front, or
        on the open-loop ``arrival_phase`` schedule (one target phase
        per request, non-decreasing) — and run phases until everything
        retires or is shed."""
        t0 = time.perf_counter()
        sched = ([0] * len(requests) if arrival_phase is None
                 else [int(p) for p in arrival_phase])
        if len(sched) != len(requests):
            raise ValueError("arrival_phase length != requests length")
        order = sorted(range(len(requests)), key=lambda i: sched[i])
        queue = [(sched[i], requests[i]) for i in order]
        phases0 = self.phase
        run_span = self.telemetry.span(
            "run", engine=f"{self.engine}-stream", requests=len(queue))
        k = 0
        phases = 0
        while k < len(queue) or not self.idle:
            while k < len(queue) and \
                    queue[k][0] <= self.phase - phases0:
                r = queue[k][1]
                th, b = r[0], r[1]
                kw2 = r[2] if len(r) > 2 else {}
                self.submit(th, b, **kw2)
                k += 1
            self.step()
            phases += 1
            if _crash_after_phases is not None \
                    and phases >= _crash_after_phases:
                raise RuntimeError(
                    f"simulated crash after {phases} phases (test hook)")
            if phases > (1 << 14):
                raise RuntimeError("stream did not converge")
        run_span.close(phases=phases, completed=len(self.completed))
        return self.result(wall_s=time.perf_counter() - t0)

    def result(self, wall_s: float = 0.0) -> StreamResult:
        from ppls_tpu.utils.metrics import round_stats_from_rows
        rows = (np.stack(self._phase_rows) if self._phase_rows
                else np.zeros((0, len(STREAM_STAT_FIELDS)), np.int64))
        # totals are REGISTRY-SOURCED (round 10): the counters the
        # metrics endpoint serves are the same numbers the bench and
        # the serve summary report — one accounting surface, no
        # ad-hoc twin sums to drift apart (the per-phase rows stay on
        # phase_stats for timeline consumers)
        reg = self.telemetry.registry
        totals = {k: int(reg.value(f"ppls_stream_{k}_total"))
                  for k in _COUNTER_STATS}
        totals["maxd"] = int(reg.value("ppls_stream_max_depth"))
        return StreamResult(completed=list(self.completed),
                            phases=self.phase, wall_s=wall_s,
                            totals=totals, phase_stats=rows,
                            fam_done=np.asarray(self._last_fam_live)
                            == 0,
                            fam_first_phase=self._fam_first.copy(),
                            fam_last_phase=self._last_fam_last.copy(),
                            latency_hist_phases=self._h_lat_phases
                            .solo(),
                            latency_hist_seconds=self._h_lat_seconds
                            .solo(),
                            per_round=round_stats_from_rows(
                                rows, STREAM_STAT_FIELDS),
                            shed=list(self.shed))

    def slo_health(self) -> dict:
        """The /health verdict (round 19): the SLO evaluator's
        current burning set, or a green default when no SLO config is
        armed — one shape for the serve CLI's health endpoint on both
        the single-process and cluster paths."""
        if self._slo is None:
            return {"ok": True, "burning": [], "phase": self.phase}
        return self._slo.health()

    def spillover_summary(self) -> dict:
        """Graceful-degradation accounting, the CLUSTER-shape twin
        (``ClusterStreamEngine.spillover_summary``): record counts
        plus the executor's device-counted task total — the serve
        summary's ``spillover`` block must not drift between the
        single-process and cluster paths."""
        done = [c for c in self.completed
                if getattr(c, "spillover", False)]
        total = len(self.completed)
        tasks = (self._spill.tasks_total
                 if self._spill is not None else 0)
        return {
            "spillover_completed": len(done),
            "spillover_fraction": (len(done) / total if total
                                   else 0.0),
            "spillover_tasks": int(tasks),
        }

    # ------------------------------------------------------------------
    # snapshot / resume
    # ------------------------------------------------------------------

    def snapshot(self):
        """Atomically write queue + walker state to checkpoint_path.
        Covers BOTH engines since round 11: the dd branch snapshots
        every chip's live bag prefix + per-chip counters + the host
        delta trackers, so a resumed dd stream replays the identical
        per-phase computation on the same mesh."""
        if not self.checkpoint_path:
            raise ValueError("no checkpoint_path configured")
        from ppls_tpu.runtime.checkpoint import save_family_checkpoint
        if self._dev is None:
            bag_cols = {}
            acc_pair = np.zeros((2, self.slots * self._theta_block))
            fam_last = [-1] * self.slots
            count = 0
            extra = {}
        elif self.engine == "walker-dd":
            bag_cols, count, acc_pair, fam_last, extra = \
                self._snapshot_dd_state()
        else:
            count, overflow = jax.device_get(
                (self._dev["bag"].count, self._dev["bag"].overflow))
            count = int(count)
            b = max(count, 1)
            bl, br, bth, bmeta, acc, acc_c, fam_last = jax.device_get(
                (self._dev["bag"].bag_l[:b], self._dev["bag"].bag_r[:b],
                 self._dev["bag"].bag_th[:b],
                 self._dev["bag"].bag_meta[:b],
                 self._dev["acc"], self._dev["acc_c"],
                 self._dev["fam_last"]))
            bag_cols = {"l": np.asarray(bl)[:count],
                        "r": np.asarray(br)[:count],
                        "th": np.asarray(bth)[:count],
                        "meta": np.asarray(bmeta)[:count]}
            acc_pair = np.stack([np.asarray(acc), np.asarray(acc_c)])
            fam_last = np.asarray(fam_last).tolist()
            extra = {}
        totals = {
            "phase": self.phase,
            "next_rid": self._next_rid,
            "fill": self._fill,
            "fam_first": self._fam_first.tolist(),
            "fam_last": fam_last,
            "phase_rows": [r.tolist() for r in self._phase_rows],
            "pending": [dataclasses.asdict(r) for r in self._pending],
            "resident": {
                str(slot): dict(dataclasses.asdict(req),
                                **self._records[req.rid])
                for slot, req in self._slot_req.items()},
            "completed": [dataclasses.asdict(c)
                          for c in self.completed],
            # round 16: the shed record + token-bucket state — a
            # resumed overload run must replay the same admission/shed
            # decisions and report the same accounting (the zero-lost-
            # acks contract covers refusals too: an acknowledged shed
            # stays a shed after the restart)
            "shed": [dataclasses.asdict(s) for s in self.shed],
            # round 18: acknowledged spillover-queued requests ride
            # the snapshot too (the zero-lost-acks contract covers
            # the spill queue exactly like the pending queue)
            "spill_queue": [dataclasses.asdict(r)
                            for r in self._spill_queue],
            # ... and so do the executor's device-counted engagement
            # totals (ppls_spillover_{requests,tasks}_total must not
            # restart at zero after a kill — same contract as the
            # cluster coordinator's snapshot)
            "spill_requests_total": int(
                self._spill.requests_total if self._spill else 0),
            "spill_tasks_total": int(
                self._spill.tasks_total if self._spill else 0),
            "tokens": dict(self._tokens),
            # round 19: the per-rid token-wait counters ride too — a
            # resumed admission must report the SAME token_wait_phases
            # on its admit event (the bit-for-bit trace contract) and
            # analyze_request must not misattribute the pre-kill waits
            # to backlog
            "token_waits": {str(k): int(v)
                            for k, v in self._token_waits.items()},
            "client_state": dict(self.client_state),
        }
        if self._adapt is not None:
            # round 20: the adapted knob values + pressure streaks
            # ride the snapshot — the resumed boundary continues the
            # identical adaptation trajectory mid-hysteresis
            totals["adapt"] = self._adapt.state()
        if self._theta_block > 1 and self._fill is not None:
            totals["theta_table"] = self._theta_table.tolist()
        totals.update(extra)
        writer = None
        if self.checkpoint_background:
            from ppls_tpu.runtime.checkpoint import background_writer
            writer = background_writer()
        save_family_checkpoint(
            self.checkpoint_path, identity=self._identity(),
            bag_cols=bag_cols, count=count, acc=acc_pair,
            totals=totals, writer=writer)
        self.telemetry.event(
            "checkpoint", phase=self.phase, count=count,
            pending=len(self._pending), resident=len(self._slot_req),
            completed=len(self.completed))
        if self.fault_injector is not None:
            # checkpoint-write fault boundary: ckpt_truncate /
            # ckpt_corrupt damage the snapshot just renamed into place
            # — the injector mutates the FILE, so a background write
            # must land before the hook fires
            if writer is not None:
                writer.flush()
            self.fault_injector.on_checkpoint_write(
                self.checkpoint_path)

    def _snapshot_dd_state(self):
        """Per-chip device state for a dd-stream snapshot: live bag
        prefixes (2D, one row per chip, like the batch dd engine's leg
        snapshot), the per-chip accumulator, the cumulative device
        counters, and the host-side delta trackers the phase loop needs
        to keep producing exact deltas after resume."""
        n_dev, store = self._dd_n_dev, self._dd_store
        bl, br, bth, bmeta, count_c, acc = self._dd_state
        counts = np.asarray(jax.device_get(count_c), dtype=np.int32)
        b = max(int(counts.max(initial=0)), 1)
        cols = {}
        for k, col in (("l", bl), ("r", br), ("th", bth),
                       ("meta", bmeta)):
            cols[k] = np.asarray(jax.device_get(
                col.reshape(n_dev, store)[:, :b]))
        cols["counts"] = counts
        acc_h = np.asarray(jax.device_get(acc))     # (n_dev, slots)
        ctr_h = jax.device_get(self._dd_counters)
        extra = {"dd": {
            # 11 cumulative CTR64 counters + waste/evals/maxd/ovf (the
            # zeroed cycles slot is rebuilt fresh on resume)
            "ctr": [np.asarray(c).tolist() for c in ctr_h[:11]],
            "waste": np.asarray(ctr_h[11]).tolist(),
            "evals": np.asarray(ctr_h[12]).tolist(),
            "maxd": np.asarray(ctr_h[13]).tolist(),
            "ovf": np.asarray(ctr_h[15]).tolist(),
            "prev": self._dd_prev.tolist(),
            "prev_waste": self._dd_prev_waste.tolist(),
            "prev_evals": self._dd_prev_evals.tolist(),
            "prev_acc": self._dd_prev_acc.tolist(),
            "prev_chip": {k: v.tolist()
                          for k, v in self._dd_prev_chip.items()},
            "prev_count": self._dd_prev_count.tolist(),
            "rr": self._dd_rr,
            # straggler streak state: persisted so a resume cannot
            # forget (or double-fire) an in-progress streak
            "flight_streak": list(self._flight._streak),
        }}
        return (cols, int(counts.sum()), acc_h,
                self._dd_fam_last.tolist(), extra)

    @classmethod
    def resume(cls, checkpoint_path: str, family: str, eps: float,
               mesh_resize: bool = False, **kwargs) -> "StreamEngine":
        """Rebuild a StreamEngine from its last snapshot. The engine
        configuration kwargs must match the snapshotted run (identity-
        checked); the continued stream replays the identical per-phase
        computation.

        ``mesh_resize=True`` (round 14, ``engine="walker-dd"``):
        elastic resume — a snapshot taken on an n-chip mesh may resume
        onto this engine's m != n chips. The per-chip queues re-deal
        depth-stratified (``mesh.host_strided_redeal``), counters
        reshard sum-preserving, and the queue/slot/latency bookkeeping
        carries over untouched; retirement and per-request areas
        continue seamlessly on the surviving mesh."""
        from ppls_tpu.runtime.checkpoint import load_family_checkpoint
        eng = cls(family, eps, checkpoint_path=checkpoint_path,
                  **kwargs)
        bag_cols, count, acc_pair, totals = load_family_checkpoint(
            checkpoint_path, eng._identity(), mesh_resize=mesh_resize)
        eng.phase = int(totals["phase"])
        eng._next_rid = int(totals["next_rid"])
        eng._fam_first = np.asarray(totals["fam_first"],
                                    dtype=np.int32)

        def _pad_row(r):
            # phase rows from snapshots that predate appended tail
            # columns (round 11's waste, round 12's eval split) pad
            # with zeros: STREAM_STAT_FIELDS only ever grows at the
            # tail, so positional replay stays correct and the
            # registry/result paths see uniform row widths
            row = np.asarray(r, dtype=np.int64)
            want = len(STREAM_STAT_FIELDS)
            if row.shape[0] < want:
                row = np.concatenate(
                    [row, np.zeros(want - row.shape[0], np.int64)])
            return row

        eng._phase_rows = [_pad_row(r) for r in totals["phase_rows"]]

        def _theta_in(v):
            # JSON round-trips theta batches as lists
            return tuple(v) if isinstance(v, list) else v

        def _req_in(d):
            # round-16 tenancy fields default for pre-round-16
            # snapshots (plain dict .get so old files keep loading)
            return StreamRequest(
                rid=d["rid"], theta=_theta_in(d["theta"]),
                bounds=tuple(d["bounds"]),
                submit_phase=d["submit_phase"],
                submit_t=time.perf_counter(),
                tenant=d.get("tenant", "default"),
                priority=int(d.get("priority", 1)),
                deadline_phases=d.get("deadline_phases"))

        eng._pending = [_req_in(d) for d in totals["pending"]]
        eng._spill_queue = [_req_in(d)
                            for d in totals.get("spill_queue", [])]
        if eng._spill_queue and eng._spill is None:
            # without the backend the spill queue can never drain:
            # idle stays False forever while every phase is a no-op —
            # refuse loudly instead of stranding acknowledged requests
            raise ValueError(
                f"snapshot carries {len(eng._spill_queue)} "
                f"spillover-queued request(s) but spillover is not "
                f"armed on this resume; pass spillover=True")
        if eng._spill is not None:
            # pre-crash engagement totals (old snapshots: zero); the
            # registry counters replay too so the /metrics exposition
            # matches the ints — same discipline as _replay_registry
            eng._spill.requests_total = int(
                totals.get("spill_requests_total", 0))
            eng._spill.tasks_total = int(
                totals.get("spill_tasks_total", 0))
            if eng._spill._c_req is not None:
                if eng._spill.requests_total:
                    eng._spill._c_req.inc(eng._spill.requests_total)
                if eng._spill.tasks_total:
                    eng._spill._c_tasks.inc(eng._spill.tasks_total)
        eng.completed = [CompletedRequest(
            **{k: (tuple(v) if k == "bounds"
                   else _theta_in(v) if k == "theta" else v)
               for k, v in d.items()}) for d in totals["completed"]]
        eng.shed = [ShedRecord(
            **{k: (tuple(v) if k == "bounds"
                   else _theta_in(v) if k == "theta" else v)
               for k, v in d.items()})
            for d in totals.get("shed", [])]
        eng._tokens = {str(k): float(v)
                       for k, v in totals.get("tokens", {}).items()}
        eng._token_waits = {int(k): int(v) for k, v in
                            totals.get("token_waits", {}).items()}
        eng.client_state = dict(totals.get("client_state", {}))
        adapt_state = totals.get("adapt")
        if adapt_state is not None:
            if eng._adapt is None:
                # unreachable through the identity check (the adapt
                # flag is identity), but a hand-edited snapshot must
                # still fail loudly, not silently replay un-adapted
                raise ValueError(
                    "snapshot carries online-adaptation state but "
                    "adapt is not armed on this resume; pass "
                    "adapt=True")
            eng._adapt.restore(adapt_state)
            for k, g in eng._g_adapt.items():
                g.set(float(eng._adapt.values[k]))
        for slot_s, d in totals["resident"].items():
            slot = int(slot_s)
            req = _req_in(d)
            eng._slot_req[slot] = req
            eng._records[req.rid] = dict(slot=slot,
                                         admit_phase=d["admit_phase"])
            eng._free.remove(slot)
        eng._count = int(count)
        if totals["fill"] is not None:
            eng._fill = tuple(totals["fill"])
            if eng._theta_block > 1:
                eng._theta_table = (
                    np.asarray(totals["theta_table"], dtype=np.float64)
                    if "theta_table" in totals else
                    np.full((eng.slots, eng._theta_block),
                            eng._fill[1], dtype=np.float64))
            eng._build_store()
            if eng.engine == "walker-dd":
                eng._restore_device_dd(bag_cols, totals,
                                       np.asarray(acc_pair))
            else:
                eng._restore_device(bag_cols, count, acc_pair,
                                    np.asarray(totals["fam_last"],
                                               dtype=np.int32))
        eng._replay_registry()
        if eng._slo is not None:
            # the burn windows re-base at the resume point: the
            # replayed cumulative counters must not read as one
            # giant window (spurious all-time burn alerts)
            eng._slo.seed_base(eng.phase)
        # round 19: restored LIVE rids re-open their request spans in
        # the appended segment, so every later hop (phase residency,
        # retirement) keeps its rid linkage — the per-rid timeline's
        # deterministic events replay bit-for-bit across the
        # kill-and-resume, same contract as the phase rows
        for req in (list(eng._pending) + list(eng._slot_req.values())
                    + list(eng._spill_queue)):
            eng._rid_spans[req.rid] = eng.telemetry.request_span(
                req.rid, tenant=req.tenant, priority=req.priority,
                submit_phase=req.submit_phase)
        eng.telemetry.event(
            "resume", phase=eng.phase, count=eng._count,
            pending=len(eng._pending), resident=len(eng._slot_req),
            completed=len(eng.completed))
        return eng

    def _replay_registry(self) -> None:
        """Rebuild the registry from the restored DETERMINISTIC record
        (device-counted phase rows, completed-request latencies) so a
        resumed run's registry-sourced totals and histogram quantiles
        match the uninterrupted run's bit-for-bit."""
        for row in self._phase_rows:
            self._publish_phase_row(np.asarray(row, dtype=np.int64))
        # spillover completions never held a slot, so they are not
        # part of the admitted count the undisturbed run produced
        n_admitted = sum(1 for c in self.completed
                         if not getattr(c, "spillover", False)) \
            + len(self._slot_req)
        if n_admitted:
            self._c_admitted.inc(n_admitted)
        for c in self.completed:
            self._c_retired.inc()
            self._c_tenant_retired.labels(tenant=c.tenant).inc()
            if getattr(c, "spillover", False):
                self._c_spillover.inc()
            if c.failed:
                # failure taxonomy (round 16): deadline expiries have
                # their own counter; every other failed record is the
                # round-14 NaN quarantine (old snapshots carry
                # failure=None)
                if c.failure == "deadline_exceeded":
                    self._c_deadline.labels(tenant=c.tenant).inc()
                else:
                    self._c_quarantined.inc()
            self._h_lat_phases.observe(c.latency_phases)
            self._h_lat_seconds.observe(c.latency_s)
            self._h_class_lat.labels(priority=str(c.priority)) \
                .observe(c.latency_phases)
            self._h_tenant_lat.labels(tenant=c.tenant) \
                .observe(c.latency_phases)
        for s in self.shed:
            self._c_shed.labels(tenant=s.tenant, reason=s.reason).inc()
        self._publish_gauges()

    def _restore_device_dd(self, bag_cols, totals, acc):
        """Rebuild the per-chip stores around the saved live prefixes
        (device-side overlay, same scheme as
        ``sharded_walker.resume_family_walker_dd``) and restore the
        cumulative counters + host delta trackers exactly, so the
        continued stream's phase rows and flight-recorder deltas are
        bit-identical to the undisturbed run's."""
        from ppls_tpu.parallel.mesh import device_store
        n_dev, store = self._dd_n_dev, self._dd_store
        dd = totals["dd"]
        fill_x, fill_th = self._fill
        counts = np.asarray(bag_cols.get("counts",
                                         np.zeros(n_dev, np.int32)),
                            dtype=np.int32)
        n_old = counts.shape[0]
        if n_old != n_dev:
            # elastic resume (round 14): the snapshot's mesh size
            # differs — re-deal queues and reshard counters onto THIS
            # engine's mesh before the store rebuild below
            bag_cols, counts, acc, dd = self._resize_dd_snapshot(
                bag_cols, counts, acc, dd, n_old)
        if bag_cols:
            bl = device_store(n_dev, store, fill_x, bag_cols["l"])
            br = device_store(n_dev, store, fill_x, bag_cols["r"])
            bth = device_store(n_dev, store, fill_th, bag_cols["th"])
            bm = device_store(n_dev, store, 0, bag_cols["meta"],
                              jnp.int32)
        else:
            bl = jnp.full((n_dev, store), fill_x, jnp.float64)
            br = jnp.full((n_dev, store), fill_x, jnp.float64)
            bth = jnp.full((n_dev, store), fill_th, jnp.float64)
            bm = jnp.zeros((n_dev, store), jnp.int32)
        self._dd_state = (
            jnp.asarray(bl).reshape(-1), jnp.asarray(br).reshape(-1),
            jnp.asarray(bth).reshape(-1), jnp.asarray(bm).reshape(-1),
            jnp.asarray(counts, dtype=jnp.int32),
            jnp.asarray(np.asarray(acc, dtype=np.float64).reshape(
                n_dev, self.slots * self._theta_block)))
        w_in = np.asarray(dd["waste"], dtype=np.int64).reshape(
            n_dev, -1)
        w_pad = np.zeros((n_dev, N_WASTE), dtype=np.int64)
        w_pad[:, :w_in.shape[1]] = w_in   # pre-round-13: 4 buckets
        self._dd_counters = tuple(
            jnp.asarray(np.asarray(v, dtype=np.int64))
            for v in dd["ctr"]) + (
            jnp.asarray(w_pad),
            jnp.asarray(np.asarray(dd.get(
                "evals", np.zeros((n_dev, 2))), dtype=np.int64)
                .reshape(n_dev, 2)),
            jnp.asarray(np.asarray(dd["maxd"], dtype=np.int32)),
            jnp.zeros(n_dev, jnp.int32),
            jnp.asarray(np.asarray(dd["ovf"], dtype=bool)))
        self._dd_prev = np.asarray(dd["prev"], dtype=np.int64)
        pw = np.asarray(dd["prev_waste"], dtype=np.int64)
        self._dd_prev_waste = np.concatenate(
            [pw, np.zeros(N_WASTE - pw.shape[0], np.int64)])
        self._dd_prev_evals = np.asarray(
            dd.get("prev_evals", np.zeros(2)), dtype=np.int64)
        self._dd_prev_acc = np.asarray(dd["prev_acc"],
                                       dtype=np.float64)
        self._dd_prev_chip = {
            k: np.asarray(v, dtype=np.int64)
            for k, v in dd["prev_chip"].items()}
        pcw = self._dd_prev_chip["waste"].reshape(n_dev, -1)
        if pcw.shape[1] < N_WASTE:
            pad = np.zeros((n_dev, N_WASTE), dtype=np.int64)
            pad[:, :pcw.shape[1]] = pcw
            self._dd_prev_chip["waste"] = pad
        self._dd_prev_count = np.asarray(dd["prev_count"],
                                         dtype=np.int64)
        self._dd_fam_last = np.asarray(totals["fam_last"],
                                       dtype=np.int32)
        self._dd_rr = int(dd["rr"])
        if "flight_streak" in dd:
            self._flight._streak = [int(v)
                                    for v in dd["flight_streak"]]

    def _resize_dd_snapshot(self, bag_cols, counts, acc, dd,
                            n_old: int):
        """Re-target an n_old-chip dd-stream snapshot at this engine's
        mesh (elastic resume): depth-stratified host re-deal of the
        per-chip queues (the same key ``phase_reshard`` deals by),
        sum-preserving counter reshard (replicated counters — crounds,
        maxd — replicate their maxima), and the host delta trackers
        REBUILT from the new layout so the first post-resize phase row
        reports exact deltas. The straggler streak resets: per-chip
        history cannot be attributed across a topology change."""
        from ppls_tpu.parallel.mesh import host_strided_redeal
        from ppls_tpu.parallel.sharded_walker import (CTR64, _CTR64_MAX)
        n_dev, store = self._dd_n_dev, self._dd_store
        fill_x, fill_th = self._fill
        m_eff = self.slots * self._theta_block

        if bag_cols:
            cols = {k: np.asarray(bag_cols[k])
                    for k in ("l", "r", "th", "meta")}
            dealt, counts = host_strided_redeal(
                cols, counts, n_dev,
                fills={"l": fill_x, "r": fill_x, "th": fill_th,
                       "meta": 0},
                sort_key=np.asarray(bag_cols["meta"]) & DEPTH_MASK)
            b_new = dealt["l"].shape[1]
            if b_new > store or int(counts.max(initial=0)) > store:
                raise ValueError(
                    f"mesh-resize resume: the re-dealt per-chip queue "
                    f"({b_new} rows) does not fit the {store}-row "
                    f"store of the {n_dev}-chip engine; raise "
                    f"capacity (or resume onto more chips)")
            bag_cols = dict(dealt, counts=counts)
        else:
            counts = np.zeros(n_dev, np.int32)

        def place_sum(vec, dtype):
            v = np.asarray(vec, dtype=dtype).reshape(n_old, -1)
            res = np.zeros((n_dev, v.shape[1]), dtype=dtype)
            res[0] = v.sum(axis=0)
            return res

        ctr_new = []
        for k, v in zip(CTR64, dd["ctr"]):
            if k in _CTR64_MAX:
                ctr_new.append(np.full(
                    n_dev, np.asarray(v, np.int64).max(initial=0),
                    np.int64))
            else:
                ctr_new.append(place_sum(v, np.int64)[:, 0])
        waste_new = place_sum(dd["waste"], np.int64)
        evals_new = place_sum(dd.get("evals",
                                     np.zeros((n_old, 2))), np.int64)
        maxd_new = np.full(
            n_dev, np.asarray(dd["maxd"], np.int32).max(initial=0),
            np.int32)
        ovf_new = np.full(n_dev, bool(np.any(np.asarray(dd["ovf"]))),
                          dtype=bool)
        acc = np.asarray(acc, np.float64).reshape(n_old, m_eff)
        acc_new = np.zeros((n_dev, m_eff), np.float64)
        # re-associating the cross-chip sum: exact (dyadic) workloads
        # stay bit-identical, ds workloads move within the documented
        # ~1e-9 schedule contract
        acc_new[0] = acc.sum(axis=0)

        dd = dict(dd)
        dd["ctr"] = [c.tolist() for c in ctr_new]
        dd["waste"] = waste_new.tolist()
        dd["evals"] = evals_new.tolist()
        dd["maxd"] = maxd_new.tolist()
        dd["ovf"] = ovf_new.tolist()
        # delta trackers: recomputed from the NEW layout (the stored
        # ones describe the old mesh — crounds' per-chip sum changes
        # with the chip count even though the replicated value did not)
        dd["prev"] = [int(c.sum()) for c in ctr_new]
        dd["prev_waste"] = waste_new.sum(axis=0).tolist()
        dd["prev_evals"] = evals_new.sum(axis=0).tolist()
        dd["prev_acc"] = acc_new.sum(axis=0).tolist()
        idx = {k: i for i, k in enumerate(CTR64)}
        dd["prev_chip"] = {
            "wsteps": ctr_new[idx["wsteps"]].tolist(),
            "tasks": ctr_new[idx["tasks"]].tolist(),
            "crounds": ctr_new[idx["crounds"]].tolist(),
            "waste": waste_new.tolist(),
        }
        dd["prev_count"] = counts.astype(np.int64).tolist()
        dd["flight_streak"] = [0] * n_dev
        self.telemetry.event(
            "mesh_resize", n_old=n_old, n_new=n_dev,
            rows=int(counts.sum()))
        return bag_cols, counts, acc_new, dd

    def _restore_device(self, bag_cols, count, acc_pair, fam_last):
        d = self._dev
        bag = d["bag"]
        if count:
            bag = bag._replace(
                bag_l=bag.bag_l.at[:count].set(bag_cols["l"]),
                bag_r=bag.bag_r.at[:count].set(bag_cols["r"]),
                bag_th=bag.bag_th.at[:count].set(bag_cols["th"]),
                bag_meta=bag.bag_meta.at[:count].set(
                    jnp.asarray(bag_cols["meta"], jnp.int32)))
        bag = bag._replace(count=jnp.asarray(count, jnp.int32))
        self._dev = dict(
            bag=bag,
            acc=jnp.asarray(acc_pair[0]),
            acc_c=jnp.asarray(acc_pair[1]),
            fam_last=jnp.asarray(fam_last, jnp.int32))

    def clear_snapshot(self):
        if self.checkpoint_background:
            from ppls_tpu.runtime.checkpoint import \
                flush_background_writer
            flush_background_writer()
        if self.checkpoint_path and os.path.exists(self.checkpoint_path):
            os.unlink(self.checkpoint_path)


def deep_trace_probes():
    """Traceable entry point for the semantic lint tier (round 17).

    The streaming engine's jitted phase program is
    ``walker.run_stream_cycle``; this probe builds it with THIS
    module's sizing conventions (the same ``walker_sizing`` call
    ``StreamEngine.__init__`` makes) over a tiny two-slot workload so
    ``tools/graftlint/deep.py`` can census its jaxpr (GL07-GL09) and
    pin its jaxpr-hash across differing operand values (GL10 — the
    semantic twin of the ``compile_once_guard`` fixture: ``phase``,
    the accumulators, and the bag payload are all traced operands, so
    two traces with different values must be IDENTICAL programs).
    """
    from ppls_tpu.models.integrands import get_family, get_family_ds
    from ppls_tpu.parallel.bag_engine import initial_bag
    slots, lanes, rpl, capacity, chunk = 2, 128, 4, 1 << 9, 1 << 7
    target, breed_chunk, slack = walker_sizing(lanes, rpl, capacity,
                                               chunk)
    statics = dict(
        f_theta=get_family("sin_scaled"),
        f_ds=get_family_ds("sin_scaled"),
        eps=1e-3, m=slots, seg_iters=64, max_segments=1 << 10,
        min_active_frac=0.1, exit_frac=0.80, suspend_frac=0.5,
        interpret=True, lanes=lanes, capacity=capacity,
        breed_chunk=breed_chunk, target=target, rule=Rule.TRAPEZOID,
        sort_roots=True, refill_slots=rpl, sort_skip_ratio=8.0,
        f64_rounds=0, scout=False, double_buffer=False, theta_block=1)

    def stream_fn(bag, acc, acc_c, fam_last, phase):
        return run_stream_cycle(bag, acc, acc_c, fam_last, phase, None,
                                **statics)

    def stream_ops(seed: int):
        bounds = np.tile(
            np.array([[0.125, 1.0 + 0.25 * seed]], dtype=np.float64),
            (slots, 1))
        theta = np.array([0.5, 0.75 + 0.125 * seed], dtype=np.float64)
        bag = initial_bag(bounds, capacity, slots, slack, theta=theta)
        acc = jnp.full(slots, 0.5 * seed, jnp.float64)
        acc_c = jnp.zeros(slots, jnp.float64)
        fam_last = jnp.full(slots, -1, jnp.int32)
        phase = jnp.asarray(3 + seed, jnp.int32)
        return (bag, acc, acc_c, fam_last, phase)

    return [("stream.run_stream_cycle", stream_fn, stream_ops)]
