"""Closed-loop autotuning (round 20): the attribution observatory
drives the performance knobs.

The reference farmer (aquadPartA.c) has ONE implicit tuning decision —
LIFO bag order — and wins load balance for free. Our engines instead
carry ~8 hand-tuned statics (refill cadence, exit/suspend thresholds,
double-buffer swap, theta_block, breed target, reshard window,
spillover limit) whose values were picked by hand in rounds 5-13 and
frozen into ``walker.resolve_cadence``. Rounds 5-6 built exact
device-counted lane-waste attribution precisely so these knobs could
be machine-driven; this module closes the loop in three layers:

1. **Offline search** (:func:`tune_workload`, driven by ``bench.py
   tune``): a staged coordinate-descent sweep seeded from the hand
   defaults. The five-bucket waste attribution of the best
   configuration so far picks the NEXT knob to move via
   :data:`BUCKET_KNOB_MAP` — the same dominant-bucket -> knob map
   ``tools/analyze_occupancy.py --attribution`` prints as its
   recommendation (one definition, no drift). The per-trial
   compile-once guard is deliberately relaxed (every distinct static
   combination compiles fresh) and the recompiles are counted into
   the entry's provenance. Results land in a committed
   ``tools/tuning_table.json`` keyed by workload signature + device
   kind.

2. **Table-driven resolution** (:func:`resolve_cadence_tuned`,
   consumed by ``walker.resolve_cadence`` — the one surface walker,
   dd, and stream already share): exact-signature match -> nearest
   signature -> hand-tuned default, with the resolution tier recorded
   (:func:`last_resolution`) so a silent fallback is visible on the
   bench record and the registry gauge.

3. **Online adaptation** (:class:`OnlineAdapter`, driven by
   ``StreamEngine`` at phase boundaries): the knobs that are host-side
   per-phase policy (admission budget, spillover batch limit) adjust
   within declared safe bands using the phase-stats row the boundary
   already fetched — zero extra device fetches, hysteresis + one-step-
   per-phase clamps so the trajectory is deterministic given the
   schedule, and the adapter state rides the snapshot so kill-and-
   resume replays bit-identically.

This module stays importable WITHOUT jax (like ``obs``): the
resolution half is pure host JSON, and the sweep half lazy-imports the
engines. ``analyze_occupancy --from-events`` depends on that.
"""

from __future__ import annotations

import json
import math
import os
from typing import Callable, Dict, List, Optional, Tuple

# ---------------------------------------------------------------------------
# the shared dominant-bucket -> knob map
# ---------------------------------------------------------------------------

# THE map (tentpole contract): which knob the tuner moves when a waste
# bucket dominates, and what the attribution printers recommend. One
# definition — the sweep's coordinate picker and analyze_occupancy's
# "recommended knob" line both read it, so they cannot drift.
#   refill_stall  -> the bank deal: more slots / double-buffer swap
#   masked_dead   -> the exit/suspend cadence thresholds
#   theta_overwalk-> the theta batch width
#   drain_tail    -> the breed target (roots_per_lane is its lever in
#                    walker_sizing) / the dd reshard window
BUCKET_KNOB_MAP: Dict[str, Tuple[str, ...]] = {
    "refill_stall": ("refill_slots", "double_buffer"),
    "masked_dead": ("exit_frac", "suspend_frac"),
    "theta_overwalk": ("theta_block",),
    "drain_tail": ("roots_per_lane", "reshard_window"),
}

# human hint per bucket, printed next to the knob names
BUCKET_KNOB_HINTS: Dict[str, str] = {
    "refill_stall": "raise the in-kernel bank deal (refill_slots) or "
                    "enable the double-buffer swap cadence",
    "masked_dead": "tighten the exit/suspend cadence thresholds",
    "theta_overwalk": "shrink theta_block (union-refinement overwalk "
                      "outruns the batch win)",
    "drain_tail": "raise the breed target (roots_per_lane sets it via "
                  "walker_sizing) or shrink the dd reshard window",
}


def recommend_knob(attribution: Optional[dict]) -> Optional[dict]:
    """The tuner's recommendation for an attribution record built by
    ``obs.telemetry.build_attribution``: which knob(s) to move for the
    dominant waste bucket, from :data:`BUCKET_KNOB_MAP`. Returns None
    when there is nothing to attack (fully eval-active)."""
    if not isinstance(attribution, dict):
        return None
    dom = attribution.get("dominant_waste")
    if dom is None or dom == "eval_active" or dom not in BUCKET_KNOB_MAP:
        return None
    return {
        "bucket": dom,
        "knobs": list(BUCKET_KNOB_MAP[dom]),
        "hint": BUCKET_KNOB_HINTS[dom],
    }


# ---------------------------------------------------------------------------
# workload signatures + the committed table
# ---------------------------------------------------------------------------

TABLE_SCHEMA = "ppls-tuning-table-v1"
ENTRY_SCHEMA = "ppls-tuning-entry-v1"

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
DEFAULT_TABLE_PATH = os.path.join(_REPO, "tools", "tuning_table.json")

# cadence safety bands: a committed table is DATA, and data can be
# wrong — values outside these bands (or a suspend >= exit pair) are
# discarded at resolution time and the hand default used instead, so a
# corrupt table can degrade to round-19 behavior but never wedge an
# engine (the legacy-mode hazard resolve_cadence documents).
CADENCE_SAFE_BANDS = {
    "exit_frac": (0.50, 0.99),
    "suspend_frac": (0.30, 0.95),
}


def hand_cadence_defaults(scout: bool, refill_slots: int
                          ) -> Tuple[float, float]:
    """The committed hand-tuned fallback tier: the round-5/round-12
    ``resolve_cadence`` values (the ONE definition —
    ``walker.resolve_cadence`` delegates here)."""
    tight = bool(scout) and int(refill_slots) > 0
    return (0.95 if tight else 0.80), (0.65 if tight else 0.50)


def eps_band(eps: float) -> int:
    """Decimal-exponent band of the tolerance: 1e-7 -> -7."""
    return int(round(math.log10(float(eps))))


def theta_band(theta_block: int) -> int:
    """theta_block band edge (1 / 32 / 256 / 4096): cadence economics
    shift with the union-refinement group width, not its exact value."""
    t = int(theta_block)
    for edge in (1, 32, 256):
        if t <= edge:
            return edge
    return 4096


def mode_string(scout: bool, refill_slots: int) -> str:
    """The mode fingerprint: scouting and in-kernel refill change the
    refill-cadence ECONOMICS (resolve_cadence docstring), so a tuned
    entry must never cross modes — 'scout-ikr' values applied to the
    legacy XLA-boundary engine can stop the walk phase engaging."""
    return ("scout" if scout else "f64") + \
        ("-ikr" if int(refill_slots) > 0 else "-xla")


def workload_signature(family: str, eps: float, rule,
                       theta_block: int = 1, mesh_shape: int = 1, *,
                       scout: bool = False,
                       refill_slots: int = 0) -> dict:
    """The tuning-table key material: family, eps band, rule,
    theta_block band, mesh shape, plus the mode fingerprint."""
    rule_name = getattr(rule, "name", None) or str(rule)
    return {
        "family": str(family),
        "eps_band": eps_band(eps),
        "rule": str(rule_name).lower(),
        "theta_band": theta_band(theta_block),
        "mesh_shape": int(mesh_shape),
        "mode": mode_string(scout, refill_slots),
    }


_SIG_FIELDS = ("family", "eps_band", "rule", "theta_band",
               "mesh_shape", "mode")


def signature_key(sig: dict, device: str) -> str:
    """Canonical string key of one (signature, device_kind) cell."""
    parts = [f"{k}={sig[k]}" for k in _SIG_FIELDS]
    parts.append(f"device={device}")
    return "|".join(parts)


def device_kind() -> str:
    """Coarse accelerator fingerprint ('cpu', 'tpu-v5e', ...). Tuned
    constants are device-generation-specific — the standing TPU
    blocker means every committed cpu entry re-tunes under real
    Mosaic lowering, by machinery instead of by hand."""
    try:
        import jax
        d = jax.devices()[0]
        kind = getattr(d, "device_kind", "") or jax.default_backend()
        return str(kind).lower().replace(" ", "-")
    except Exception:                                  # pragma: no cover
        return "unknown"


_TABLE_CACHE: Dict[str, tuple] = {}


def tuning_table_path() -> Optional[str]:
    """The table location: ``PPLS_TUNING_TABLE`` overrides (a path, or
    0/off to disable table-driven resolution entirely), else the
    committed ``tools/tuning_table.json``."""
    env = os.environ.get("PPLS_TUNING_TABLE")
    if env is not None:
        if env.strip().lower() in ("", "0", "off", "none"):
            return None
        return env
    return DEFAULT_TABLE_PATH


def load_tuning_table(path: Optional[str] = None) -> Optional[dict]:
    """Load (and mtime-cache) the tuning table; None when disabled,
    missing, or malformed — a broken table must degrade to hand
    defaults, never crash an engine constructor."""
    if path is None:
        path = tuning_table_path()
    if path is None:
        return None
    try:
        mtime = os.path.getmtime(path)
    except OSError:
        return None
    cached = _TABLE_CACHE.get(path)
    if cached is not None and cached[0] == mtime:
        return cached[1]
    try:
        with open(path, encoding="utf-8") as fh:
            table = json.load(fh)
    except (OSError, json.JSONDecodeError):
        return None
    if not isinstance(table, dict) \
            or table.get("schema") != TABLE_SCHEMA \
            or not isinstance(table.get("entries"), dict):
        return None
    _TABLE_CACHE[path] = (mtime, table)
    return table


def clear_table_cache() -> None:
    """Test hook: drop the mtime cache (monkeypatched paths)."""
    _TABLE_CACHE.clear()


def nearest_entry(entries: Dict[str, dict], sig: dict,
                  device: str) -> Optional[Tuple[str, dict]]:
    """The NEAREST-signature tier. Hard constraints first — device
    kind, rule, mode fingerprint, mesh shape, and theta band must
    match exactly (tuned values never cross an economics boundary) —
    then rank the survivors: family match (weight 4) beats eps-band
    proximity (weight 3 - |band distance|, floored at 0); candidates
    scoring 0 (nothing in common) fall through to the hand tier.
    Ties break on smaller eps distance, then lexicographic key, so
    the ordering is total and testable."""
    best: Optional[Tuple[int, int, str, dict]] = None
    for key in sorted(entries):
        ent = entries[key]
        s = ent.get("signature")
        if not isinstance(s, dict):
            continue
        if ent.get("device_kind") != device:
            continue
        if (s.get("rule") != sig["rule"]
                or s.get("mode") != sig["mode"]
                or s.get("mesh_shape") != sig["mesh_shape"]
                or s.get("theta_band") != sig["theta_band"]):
            continue
        try:
            d = abs(int(s.get("eps_band")) - int(sig["eps_band"]))
        except (TypeError, ValueError):
            continue
        score = (4 if s.get("family") == sig["family"] else 0) \
            + max(0, 3 - d)
        if score <= 0:
            continue
        cand = (score, -d, key, ent)
        if best is None or (cand[0], cand[1]) > (best[0], best[1]):
            best = cand
        # equal (score, distance): the earlier (lexicographically
        # smaller) key already holds — sorted() iteration order
    if best is None:
        return None
    return best[2], best[3]


def resolve_knobs(sig: Optional[dict], names: Tuple[str, ...],
                  path: Optional[str] = None
                  ) -> Tuple[Dict[str, object], str, Optional[str]]:
    """Three-tier lookup for ``names``: (values, tier, entry_key) with
    tier in {'exact', 'nearest', 'default'}. 'default' returns no
    values — the caller owns the hand fallback."""
    if sig is None:
        return {}, "default", None
    table = load_tuning_table(path)
    if table is None:
        return {}, "default", None
    entries = table["entries"]
    device = device_kind()
    key = signature_key(sig, device)
    ent = entries.get(key)
    tier = "exact"
    if not isinstance(ent, dict):
        near = nearest_entry(entries, sig, device)
        if near is None:
            return {}, "default", None
        key, ent = near
        tier = "nearest"
    knobs = ent.get("knobs")
    if not isinstance(knobs, dict):
        return {}, "default", None
    vals = {k: knobs[k] for k in names if k in knobs}
    if not vals:
        return {}, "default", None
    return vals, tier, key


def _cadence_pair_sane(exit_frac, suspend_frac) -> bool:
    for name, v in (("exit_frac", exit_frac),
                    ("suspend_frac", suspend_frac)):
        lo, hi = CADENCE_SAFE_BANDS[name]
        if not isinstance(v, (int, float)) or isinstance(v, bool) \
                or not math.isfinite(v) or not (lo <= v <= hi):
            return False
    return suspend_frac < exit_frac


_LAST_RESOLUTION = {"tier": "default", "key": None,
                    "exit_frac": None, "suspend_frac": None,
                    "signature": None}


def last_resolution() -> dict:
    """The most recent cadence resolution (tier + entry key + values):
    the bench record and the engines' registry gauge read it so a
    silent fallback to the hand tier stays visible."""
    return dict(_LAST_RESOLUTION)


def resolve_cadence_tuned(exit_frac: Optional[float],
                          suspend_frac: Optional[float],
                          scout: bool, refill_slots: int = 0, *,
                          signature: Optional[dict] = None,
                          path: Optional[str] = None
                          ) -> Tuple[float, float, str]:
    """The ONE cadence-resolution surface (walker, dd, and stream all
    reach it through ``walker.resolve_cadence``): explicit values win
    unconditionally ('explicit' tier); otherwise the tuning table
    (exact -> nearest signature), sanity-banded, with the hand-tuned
    round-12 defaults as the committed fallback tier. Returns
    ``(exit_frac, suspend_frac, tier)`` and records the resolution for
    :func:`last_resolution`."""
    de, ds = hand_cadence_defaults(scout, refill_slots)
    tier, key = "explicit", None
    if exit_frac is None or suspend_frac is None:
        vals, tier, key = resolve_knobs(
            signature, ("exit_frac", "suspend_frac"), path)
        te, ts = vals.get("exit_frac"), vals.get("suspend_frac")
        if tier != "default" and not _cadence_pair_sane(te, ts):
            # out-of-band table data: visible degrade to the hand tier
            te = ts = None
            tier, key = "default", None
        if exit_frac is None:
            exit_frac = te if te is not None else de
        if suspend_frac is None:
            suspend_frac = ts if ts is not None else ds
        if not _cadence_pair_sane(exit_frac, suspend_frac) \
                and tier in ("exact", "nearest"):
            # a sane table pair can still clash with ONE explicit
            # caller value — the pair contract (suspend < exit) wins
            exit_frac = de if te is not None else exit_frac
            suspend_frac = ds if ts is not None else suspend_frac
            tier, key = "default", None
    exit_frac, suspend_frac = float(exit_frac), float(suspend_frac)
    _LAST_RESOLUTION.update(
        tier=tier, key=key, exit_frac=exit_frac,
        suspend_frac=suspend_frac, signature=signature)
    return exit_frac, suspend_frac, tier


# ---------------------------------------------------------------------------
# offline search: staged coordinate descent on the quick proxies
# ---------------------------------------------------------------------------

# the quick sweep's trial context: flagship mode (scout + in-kernel
# refill + double-buffer) at the interpret-proxy sizing — small enough
# that a budgeted CI sweep finishes, big enough that the attribution
# buckets are populated. roots_per_lane is deliberately above the
# bench-quick sizing so the breed-target lever has room to move.
TUNE_SIZING = dict(capacity=1 << 16, lanes=256, roots_per_lane=8,
                   refill_slots=4, seg_iters=32, min_active_frac=0.05,
                   scout_dtype="f32", double_buffer=True)
TUNE_M = 8

# the canonical tune workloads (family, eps, bounds): tolerances
# chosen so the walk phase genuinely engages at the quick sizing
# (sin_scaled converges in pure breed rounds above ~1e-8 — nothing to
# tune there)
TUNE_WORKLOADS = (
    ("sin_recip_scaled", 1e-7, (1e-2, 1.0)),
    ("sin_scaled", 1e-9, (0.0, 1.0)),
    ("cosh4_scaled", 1e-8, (0.0, 1.0)),
)

# value domains of the sweepable knobs (theta_block and the dd
# reshard_window appear in BUCKET_KNOB_MAP for the recommendation
# surface but are not swept by the quick trial context — theta band 1
# workloads and the single-chip mesh cannot measure them).
KNOB_DOMAINS: Dict[str, Tuple] = {
    "exit_frac": (0.80, 0.90, 0.95, 0.98),
    "suspend_frac": (0.50, 0.65, 0.80),
    "refill_slots": (2, 4, 8),
    "double_buffer": (True, False),
    "roots_per_lane": (4, 8, 12),
}

# stable fallback order once the dominant bucket's own knobs are
# exhausted: the sweep keeps spending budget instead of stalling
_SWEEP_ORDER = ("exit_frac", "suspend_frac", "refill_slots",
                "double_buffer", "roots_per_lane")


def valid_knob_combo(knobs: dict) -> bool:
    """The engines' own static-combination constraints (walker
    validates these loudly; the sweep must not burn budget on
    combinations that cannot construct)."""
    if knobs["refill_slots"] > knobs["roots_per_lane"]:
        return False
    if knobs["double_buffer"] and (
            knobs["refill_slots"] < 2 or knobs["refill_slots"] % 2):
        return False
    if knobs["suspend_frac"] >= knobs["exit_frac"]:
        return False
    return True


def pareto_improves(cand: dict, base: dict) -> bool:
    """'Beats the hand default' contract (one definition — the sweep's
    accept rule and bench_history's gate both use it): lane_efficiency
    must not drop, kernel_steps must not grow, and at least one must
    strictly improve. The reconciliation invariant must hold."""
    if not cand.get("reconciles", False):
        return False
    ce, be = float(cand["lane_efficiency"]), float(base["lane_efficiency"])
    cs, bs = int(cand["kernel_steps"]), int(base["kernel_steps"])
    return ce >= be and cs <= bs and (ce > be or cs < bs)


def measure_trial(family: str, eps: float, bounds, sizing: dict,
                  knobs: dict) -> dict:
    """One sweep trial: run the walker with the candidate knob values
    (cadence passed EXPLICITLY so the loaded table cannot contaminate
    the sweep) and return the device-counted quick proxies. The
    compile-once guard is deliberately relaxed here — each distinct
    static combination compiles fresh — and the pjit cache growth is
    returned as the trial's recompile count."""
    import numpy as np

    from ppls_tpu.models.integrands import get_family, get_family_ds
    from ppls_tpu.parallel.walker import (_run_cycles,
                                          integrate_family_walker)

    kw = dict(sizing)
    kw.pop("refill_slots", None)
    kw.pop("double_buffer", None)
    kw.pop("roots_per_lane", None)
    theta = 1.0 + np.arange(TUNE_M) / float(TUNE_M)
    cache0 = int(_run_cycles._cache_size())
    r = integrate_family_walker(
        get_family(family), get_family_ds(family), theta, bounds,
        float(eps),
        exit_frac=float(knobs["exit_frac"]),
        suspend_frac=float(knobs["suspend_frac"]),
        refill_slots=int(knobs["refill_slots"]),
        double_buffer=bool(knobs["double_buffer"]),
        roots_per_lane=int(knobs["roots_per_lane"]),
        **kw)
    attr = r.attribution() or {}
    return {
        "tasks": int(r.metrics.tasks),
        "cycles": int(r.cycles),
        "kernel_steps": int(r.kernel_steps),
        "lane_efficiency": round(float(r.lane_efficiency), 6),
        "dominant_waste": attr.get("dominant_waste"),
        "reconciles": bool(attr.get("reconciles", False)),
        "recompiles": int(_run_cycles._cache_size()) - cache0,
    }


def _knob_key(knobs: dict) -> tuple:
    return tuple(sorted((k, knobs[k]) for k in knobs))


def _next_candidate(best_knobs: dict, best_proxies: dict,
                    tried: set) -> Optional[Tuple[str, object]]:
    """The staged coordinate picker: the dominant waste bucket of the
    best configuration so far names the next knob through
    :data:`BUCKET_KNOB_MAP`; its untried domain values go first, then
    the remaining sweepable knobs in stable order."""
    dom = best_proxies.get("dominant_waste")
    order: List[str] = []
    for k in BUCKET_KNOB_MAP.get(dom, ()):
        if k in KNOB_DOMAINS:
            order.append(k)
    for k in _SWEEP_ORDER:
        if k not in order:
            order.append(k)
    for knob in order:
        for v in KNOB_DOMAINS[knob]:
            cand = dict(best_knobs)
            cand[knob] = v
            if not valid_knob_combo(cand):
                continue
            kk = _knob_key(cand)
            if kk in tried:
                continue
            return knob, v
    return None


def tune_workload(family: str, eps: float, bounds, *,
                  rule: str = "trapezoid",
                  sizing: Optional[dict] = None,
                  budget: int = 8, seed: int = 0,
                  measure: Optional[Callable[[dict], dict]] = None,
                  device: Optional[str] = None) -> dict:
    """The staged sweep for one workload signature: coordinate descent
    seeded from the hand defaults, attribution-picked knob order,
    Pareto acceptance (:func:`pareto_improves`), ``budget`` trials
    including the baseline. Deterministic given (seed, signature,
    measurement): no randomness is consumed, the seed is provenance —
    byte-identical re-runs are a test contract.

    ``measure`` injects the trial runner (tests stub it); the default
    is :func:`measure_trial` on the real walker."""
    sizing = dict(TUNE_SIZING if sizing is None else sizing)
    scout = sizing.get("scout_dtype") == "f32"
    de, ds = hand_cadence_defaults(scout, sizing.get("refill_slots", 0))
    base_knobs = {
        "exit_frac": de, "suspend_frac": ds,
        "refill_slots": int(sizing.get("refill_slots", 4)),
        "double_buffer": bool(sizing.get("double_buffer", True)),
        "roots_per_lane": int(sizing.get("roots_per_lane", 8)),
    }
    if measure is None:
        def measure(knobs):
            return measure_trial(family, eps, bounds, sizing, knobs)
    sig = workload_signature(
        family, eps, rule, theta_block=1, mesh_shape=1, scout=scout,
        refill_slots=base_knobs["refill_slots"])
    dev = device if device is not None else device_kind()

    base_p = measure(base_knobs)
    trials = [{"knobs": dict(base_knobs), "proxies": base_p,
               "accepted": True, "moved": None}]
    tried = {_knob_key(base_knobs)}
    best_knobs, best_p = dict(base_knobs), base_p
    recompiles = int(base_p.get("recompiles", 0))
    while len(trials) < max(1, int(budget)):
        nxt = _next_candidate(best_knobs, best_p, tried)
        if nxt is None:
            break
        knob, value = nxt
        cand = dict(best_knobs)
        cand[knob] = value
        tried.add(_knob_key(cand))
        p = measure(cand)
        recompiles += int(p.get("recompiles", 0))
        accepted = pareto_improves(p, best_p)
        trials.append({"knobs": cand, "proxies": p,
                       "accepted": accepted,
                       "moved": {"knob": knob, "value": value,
                                 "bucket": best_p.get(
                                     "dominant_waste")}})
        if accepted:
            best_knobs, best_p = cand, p

    def _prox(p):
        return {"tasks": int(p["tasks"]),
                "kernel_steps": int(p["kernel_steps"]),
                "lane_efficiency": float(p["lane_efficiency"])}

    entry = {
        "schema": ENTRY_SCHEMA,
        "signature": sig,
        "device_kind": dev,
        "knobs": {k: best_knobs[k] for k in sorted(best_knobs)},
        "baseline": _prox(base_p),
        "tuned": _prox(best_p),
        "provenance": {
            "trials": len(trials),
            "recompiles": recompiles,
            "reconciles": bool(best_p.get("reconciles", False)
                               and base_p.get("reconciles", False)),
            "seed": int(seed),
            "budget": int(budget),
            "improved": pareto_improves(best_p, base_p),
            "eps": float(eps),
            "bounds": [float(bounds[0]), float(bounds[1])],
            "sizing": {k: sizing[k] for k in sorted(sizing)},
            "path": [
                {"moved": t["moved"], "accepted": t["accepted"],
                 "kernel_steps": int(t["proxies"]["kernel_steps"]),
                 "lane_efficiency": float(
                     t["proxies"]["lane_efficiency"])}
                for t in trials[1:]],
        },
    }
    return entry


def entry_key(entry: dict) -> str:
    return signature_key(entry["signature"], entry["device_kind"])


def update_table(table: Optional[dict], entry: dict) -> dict:
    """Insert/replace one entry; creates the table envelope when
    needed. Returns the (mutated) table."""
    if not isinstance(table, dict) or table.get("schema") != TABLE_SCHEMA:
        table = {"schema": TABLE_SCHEMA, "entries": {}}
    table.setdefault("entries", {})[entry_key(entry)] = entry
    return table


def write_table(path: str, table: dict) -> None:
    tmp = f"{path}.tmp"
    with open(tmp, "w", encoding="utf-8") as fh:
        json.dump(table, fh, indent=1, sort_keys=True)
        fh.write("\n")
    os.replace(tmp, path)
    _TABLE_CACHE.pop(path, None)


# ---------------------------------------------------------------------------
# online adaptation (stream phase boundaries)
# ---------------------------------------------------------------------------

# hysteresis: a knob moves only after this many CONSECUTIVE phases of
# same-direction pressure, and by at most one step per phase — the
# trajectory is a pure function of the phase-row/queue schedule, so a
# resumed run replays it bit-identically from the snapshot state.
ADAPT_HYSTERESIS = 2

# drain_tail + masked_dead lane-step share above which a backlogged
# phase reads as "lanes underfed" (admission pressure up)
ADAPT_WASTE_FRAC = 0.10


def online_safe_bands(defaults: Dict[str, int]) -> Dict[str, tuple]:
    """Declared safe bands for the online knobs, relative to the
    engine's configured values: the admission budget may trickle down
    to 1 but never exceed the COMPILED admit window (the seed-array
    width is a static — exceeding it would recompile); the spillover
    batch limit stays within the spill queue's 8x sizing."""
    bands = {}
    if "admit_budget" in defaults:
        bands["admit_budget"] = (1, max(1, int(defaults["admit_budget"])))
    if "spillover_limit" in defaults:
        d = max(1, int(defaults["spillover_limit"]))
        bands["spillover_limit"] = (1, 4 * d)
    return bands


class OnlineAdapter:
    """Deterministic per-phase knob adapter (tentpole layer 3).

    Pure host arithmetic over values the phase boundary already holds:
    per-knob signed pressure streaks, :data:`ADAPT_HYSTERESIS` phases
    of agreement before a move, one step per phase, hard-clamped to
    the declared safe band. ``state()``/``restore()`` ride the stream
    snapshot so kill-and-resume replays the identical trajectory."""

    def __init__(self, defaults: Dict[str, int],
                 bands: Optional[Dict[str, tuple]] = None):
        self.defaults = {k: int(v) for k, v in defaults.items()}
        self.bands = {k: (int(lo), int(hi)) for k, (lo, hi) in
                      (bands if bands is not None
                       else online_safe_bands(defaults)).items()}
        for k, v in self.defaults.items():
            lo, hi = self.bands[k]
            if not lo <= v <= hi:
                raise ValueError(
                    f"online knob {k}: default {v} outside its safe "
                    f"band [{lo}, {hi}]")
        self.values = dict(self.defaults)
        self.streaks = {k: 0 for k in self.defaults}

    def observe(self, pressures: Dict[str, int]) -> List[dict]:
        """Fold one phase's signed pressures (-1/0/+1 per knob) into
        the streaks; returns the applied changes (possibly empty),
        each ``{"knob", "from", "to"}``."""
        changes = []
        for k in sorted(self.values):
            p = int(pressures.get(k, 0))
            if p == 0:
                self.streaks[k] = 0
                continue
            s = self.streaks[k]
            s = s + p if s * p >= 0 else p   # direction flip resets
            if abs(s) >= ADAPT_HYSTERESIS:
                lo, hi = self.bands[k]
                old = self.values[k]
                new = min(hi, max(lo, old + (1 if s > 0 else -1)))
                self.streaks[k] = 0
                if new != old:
                    self.values[k] = new
                    changes.append({"knob": k, "from": old, "to": new})
            else:
                self.streaks[k] = s
        return changes

    def state(self) -> dict:
        return {"values": dict(self.values),
                "streaks": dict(self.streaks)}

    def restore(self, state: dict) -> None:
        vals = state.get("values", {})
        streaks = state.get("streaks", {})
        for k in self.values:
            if k in vals:
                lo, hi = self.bands[k]
                v = int(vals[k])
                if not lo <= v <= hi:
                    raise ValueError(
                        f"snapshot adapt state: {k}={v} outside the "
                        f"declared safe band [{lo}, {hi}]")
                self.values[k] = v
            if k in streaks:
                self.streaks[k] = int(streaks[k])
