from ppls_tpu.utils.metrics import RoundStats, RunMetrics

__all__ = ["RoundStats", "RunMetrics"]
