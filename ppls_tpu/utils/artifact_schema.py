"""Tiny schema check for the bench/multichip artifact records.

The round artifacts (BENCH_r*.json, MULTICHIP_r*.json, and every JSON
line bench.py prints) are consumed by the round driver and by humans
diffing rounds — a malformed block silently DROPS from the trajectory
(the driver skips unparseable/shapeless records), which reads as "no
regression" when the truth is "no data". Every bench entry point
validates its record through :func:`validate_record` before printing,
and ``tools/check_artifacts.py`` (run by ``tools/ci.sh``) validates
the committed artifact files, so a malformed block fails loudly at
write time and at CI time instead of vanishing.

The schema is deliberately minimal — the shared envelope every record
carries, not the per-leg payloads:

* ``metric``: non-empty str
* ``value``: finite number (0.0 is the legitimate failure value)
* ``unit``: non-empty str
* ``vs_baseline``: finite number (error records may omit it)

Secondary legs (``secondary`` dict) are validated recursively with the
same envelope unless they are error records (``{"error": ...}``) or
explicitly skipped (``{"skipped": ...}``).

Round 10 adds a second document type: the telemetry EVENT LOG
(``ppls-tpu serve --events``, ``obs.spans.SpanTracer``) —
``validate_events_text`` checks the span/event JSONL shape (record
kinds, required keys, per-segment monotonic timestamps, span-nesting
balance) so a truncated or hand-edited timeline fails CI instead of
silently replaying as a partial run.
"""

from __future__ import annotations

import json
import math
from typing import List


class ArtifactSchemaError(ValueError):
    """A bench/multichip record violates the artifact envelope."""


def _is_finite_number(v) -> bool:
    return isinstance(v, (int, float)) and not isinstance(v, bool) \
        and math.isfinite(v)


def validate_record(rec: dict, *, where: str = "record",
                    require_vs_baseline: bool = True) -> dict:
    """Validate one bench record envelope; returns ``rec`` unchanged so
    call sites can wrap their final ``print(json.dumps(...))``.
    Raises :class:`ArtifactSchemaError` with the offending field."""
    if not isinstance(rec, dict):
        raise ArtifactSchemaError(f"{where}: not a JSON object")
    if "error" in rec and not isinstance(rec.get("error"), str):
        raise ArtifactSchemaError(f"{where}: 'error' must be a string")
    if not isinstance(rec.get("metric"), str) or not rec["metric"]:
        raise ArtifactSchemaError(f"{where}: missing/empty 'metric'")
    if not _is_finite_number(rec.get("value")):
        raise ArtifactSchemaError(
            f"{where}: 'value' must be a finite number, got "
            f"{rec.get('value')!r}")
    if not isinstance(rec.get("unit"), str) or not rec["unit"]:
        raise ArtifactSchemaError(f"{where}: missing/empty 'unit'")
    if require_vs_baseline and "error" not in rec \
            and not _is_finite_number(rec.get("vs_baseline")):
        raise ArtifactSchemaError(
            f"{where}: 'vs_baseline' must be a finite number, got "
            f"{rec.get('vs_baseline')!r}")
    sec = rec.get("secondary")
    if sec is not None:
        if not isinstance(sec, dict):
            raise ArtifactSchemaError(f"{where}: 'secondary' must be "
                                      f"an object")
        for name, sub in sec.items():
            if not isinstance(sub, dict):
                raise ArtifactSchemaError(
                    f"{where}.secondary.{name}: not an object")
            if "error" in sub or "skipped" in sub:
                continue
            # secondaries carry heterogeneous payloads (some are
            # records, some comparison blocks): require the metric
            # label, and check 'value' finiteness only when present —
            # a NaN/None value is the silent-poison case
            if not isinstance(sub.get("metric"), str) \
                    or not sub["metric"]:
                raise ArtifactSchemaError(
                    f"{where}.secondary.{name}: missing/empty 'metric'")
            if "value" in sub and not _is_finite_number(sub["value"]):
                raise ArtifactSchemaError(
                    f"{where}.secondary.{name}: 'value' must be a "
                    f"finite number, got {sub.get('value')!r}")
    return rec


def validate_artifact_text(text: str, *, where: str = "artifact",
                           require_records: bool = True) -> List[str]:
    """Validate every bench record found in an artifact's text.

    Two shapes are handled: the round driver's WRAPPER object (one
    pretty-printed JSON object whose ``tail`` string holds the bench's
    stdout/stderr tail — the records are JSON lines inside it), and a
    raw line stream (bench stdout piped directly). Only lines parsing
    as objects with a ``metric`` key are treated as bench records.
    Returns a list of problem strings (empty = clean);
    ``require_records`` flags an artifact with no records at all (the
    silent-drop outcome) — disable it for artifacts that legitimately
    carry none (e.g. the multichip dryrun log).
    """
    try:
        wrapper = json.loads(text)
    except json.JSONDecodeError:
        wrapper = None
    problems: List[str] = []
    found = 0
    if isinstance(wrapper, dict):
        if "metric" in wrapper:
            found += 1
            try:
                validate_record(wrapper, where=where)
            except ArtifactSchemaError as e:
                problems.append(str(e))
        tail = wrapper.get("tail")
        if isinstance(tail, str):
            sub, sub_found = _scan_lines(tail, f"{where}:tail")
            problems += sub
            found += sub_found
    else:
        sub, sub_found = _scan_lines(text, where)
        problems += sub
        found += sub_found
    if require_records and not found:
        problems.append(f"{where}: no bench records found")
    return problems


EVENT_KINDS = ("meta", "span_open", "span_close", "event")

# the per-rid trace event vocabulary (round 19): every one of these
# must link to an OPEN request span for its rid when the rid-linkage
# check is armed
RID_TRACE_EVENTS = ("admit", "request_dealt", "token_wait",
                    "request_phase", "spillover_enqueued",
                    "request_redeal", "quarantine",
                    "deadline_exceeded", "retire", "request_shed")


def validate_events_text(text: str, *, where: str = "events",
                         require_balanced: bool = True,
                         check_rid_linkage: bool = False) -> List[str]:
    """Validate a telemetry event log (``obs.spans`` JSONL timeline).

    Per line: a JSON object with ``ev`` in :data:`EVENT_KINDS`; every
    non-meta record carries a finite ``t`` that is non-decreasing
    WITHIN its segment (a ``meta`` line starts a new segment — the
    serve resume path appends one, restarting the monotonic clock);
    ``span_open`` carries int ``id``, non-empty ``name`` and a
    ``parent`` that is null or an OPEN span id; ``span_close`` closes
    an open id; ``event`` carries a non-empty ``name``; ``attrs``
    (when present) is an object. ``require_balanced=False`` tolerates
    unclosed spans — the shape a killed run leaves behind.

    ``check_rid_linkage=True`` (round 19) additionally enforces the
    REQUEST-TRACE contract on timelines that carry it: every
    rid-bearing trace event (:data:`RID_TRACE_EVENTS`) must link to a
    ``request`` span OPEN for that rid in its segment (resumed
    segments re-open live rids' spans, so this holds across
    kill-and-resume), and a terminal event (retire / request_shed)
    must be followed by that rid's span close within the segment —
    zero orphan spans, zero orphan hops. Timelines predating the
    request-trace tier fail this check; leave it off for them.

    Returns a list of problem strings (empty = clean).
    """
    problems: List[str] = []
    open_spans: set = set()
    last_t = None
    found = 0
    # rid-linkage state (reset per segment, like span ids)
    req_sids: dict = {}          # open request-span id -> rid
    rid_open: set = set()        # rids with an open request span
    rid_terminal_open: set = set()   # terminal seen, span still open
    for i, line in enumerate(text.splitlines(), 1):
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            problems.append(f"{where}:{i}: unparseable event line")
            continue
        if not isinstance(rec, dict):
            problems.append(f"{where}:{i}: not a JSON object")
            continue
        found += 1
        ev = rec.get("ev")
        if ev not in EVENT_KINDS:
            problems.append(f"{where}:{i}: unknown ev {ev!r}")
            continue
        if ev == "meta":
            # new segment (the resume-append path): the monotonic
            # clock AND the span-id space restart. Spans the previous
            # segment left open are the crashed-run shape — flagged
            # only under require_balanced, then forgotten so the new
            # segment's ids (restarting at 0) don't read as reopens.
            last_t = None
            if require_balanced and open_spans:
                problems.append(
                    f"{where}:{i}: {len(open_spans)} span(s) left "
                    f"open at segment boundary: {sorted(open_spans)}")
            open_spans.clear()
            if check_rid_linkage and rid_terminal_open:
                problems.append(
                    f"{where}:{i}: request span(s) for retired/shed "
                    f"rid(s) {sorted(rid_terminal_open)[:8]} never "
                    f"closed in their segment")
            req_sids.clear()
            rid_open.clear()
            rid_terminal_open.clear()
            if rec.get("schema") != "ppls-events-v1":
                problems.append(f"{where}:{i}: meta without "
                                f"schema=ppls-events-v1")
            continue
        t = rec.get("t")
        if not _is_finite_number(t):
            problems.append(f"{where}:{i}: missing/non-finite 't'")
        elif last_t is not None and t < last_t:
            problems.append(f"{where}:{i}: timestamp goes backwards "
                            f"({t} < {last_t})")
        else:
            last_t = t
        attrs = rec.get("attrs")
        if attrs is not None and not isinstance(attrs, dict):
            problems.append(f"{where}:{i}: 'attrs' must be an object")
        if ev == "span_open":
            sid = rec.get("id")
            if not isinstance(sid, int):
                problems.append(f"{where}:{i}: span_open without int "
                                f"'id'")
                continue
            parent = rec.get("parent")
            if parent is not None and parent not in open_spans:
                problems.append(f"{where}:{i}: parent {parent} is not "
                                f"an open span")
            if not isinstance(rec.get("name"), str) or not rec["name"]:
                problems.append(f"{where}:{i}: span_open without "
                                f"'name'")
            if sid in open_spans:
                problems.append(f"{where}:{i}: span id {sid} reopened")
            open_spans.add(sid)
            if check_rid_linkage and rec.get("name") == "request":
                rid = (attrs or {}).get("rid")
                if not isinstance(rid, int):
                    problems.append(f"{where}:{i}: request span "
                                    f"without int 'rid'")
                else:
                    req_sids[sid] = rid
                    rid_open.add(rid)
        elif ev == "span_close":
            sid = rec.get("id")
            if sid not in open_spans:
                problems.append(f"{where}:{i}: span_close for "
                                f"unopened id {sid!r}")
            else:
                open_spans.discard(sid)
            if check_rid_linkage and sid in req_sids:
                rid = req_sids.pop(sid)
                rid_open.discard(rid)
                rid_terminal_open.discard(rid)
        elif ev == "event":
            if not isinstance(rec.get("name"), str) or not rec["name"]:
                problems.append(f"{where}:{i}: event without 'name'")
            elif check_rid_linkage \
                    and rec["name"] in RID_TRACE_EVENTS:
                rid = (attrs or {}).get("rid")
                if not isinstance(rid, int):
                    problems.append(
                        f"{where}:{i}: trace event "
                        f"{rec['name']!r} without int 'rid'")
                elif rid not in rid_open:
                    problems.append(
                        f"{where}:{i}: orphan trace event "
                        f"{rec['name']!r} — rid {rid} has no open "
                        f"request span in this segment")
                elif rec["name"] in ("retire", "request_shed"):
                    rid_terminal_open.add(rid)
    if not found:
        problems.append(f"{where}: no event records found")
    elif require_balanced and open_spans:
        problems.append(f"{where}: {len(open_spans)} span(s) never "
                        f"closed: {sorted(open_spans)}")
    if check_rid_linkage and rid_terminal_open:
        problems.append(
            f"{where}: request span(s) for retired/shed rid(s) "
            f"{sorted(rid_terminal_open)[:8]} never closed")
    return problems


def validate_serve_output_text(text: str, *, where: str = "serve"
                               ) -> List[str]:
    """Validate a ``ppls-tpu serve`` stdout stream (round 16): the
    third artifact document type — the JSONL request ledger a
    multi-tenant overload run leaves behind.

    Shape: every JSON line is a RETIRE record (``rid`` + ``area``),
    a SHED record (``shed: true`` with rid/tenant/reason — the
    explicit rejection every load-shed request must get), a REJECTION
    (``rejected: true`` with an error — malformed input lines), or
    the single SUMMARY line (``summary: true``). Accounting
    invariants, deduped by rid because a watchdog/supervisor resume
    may legitimately replay post-snapshot lines: distinct retire rids
    == ``summary.completed``; distinct shed rids == ``summary.shed``
    (when reported); no rid both retires and sheds; failed retire
    records carry ``area: null``. Returns problem strings (empty =
    clean).

    SCOPE: one ledger must cover one PROCESS LINEAGE's whole request
    set. In-process supervisor resumes are covered (their stdout
    accumulates every line). A zero-downtime RESTART (SIGTERM + new
    process) splits the ledger: the second process's summary counts
    snapshot-restored records its own stdout never printed —
    CONCATENATE the processes' outputs (minus the earlier summaries)
    before validating, as the restart tests do."""
    problems: List[str] = []
    summaries = []
    retire_rids, shed_rids = set(), set()
    failed_rids = set()
    for i, line in enumerate(text.splitlines(), 1):
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            problems.append(f"{where}:{i}: unparseable JSON line")
            continue
        if not isinstance(rec, dict):
            problems.append(f"{where}:{i}: not a JSON object")
            continue
        if rec.get("summary"):
            summaries.append((i, rec))
        elif rec.get("shed"):
            if not isinstance(rec.get("rid"), int) \
                    or not isinstance(rec.get("tenant"), str) \
                    or not isinstance(rec.get("reason"), str):
                problems.append(f"{where}:{i}: shed record without "
                                f"rid/tenant/reason")
            else:
                shed_rids.add(rec["rid"])
        elif rec.get("rejected"):
            if not isinstance(rec.get("error"), str):
                problems.append(f"{where}:{i}: rejection record "
                                f"without 'error'")
        elif "rid" in rec and "area" in rec:
            if not isinstance(rec["rid"], int):
                problems.append(f"{where}:{i}: non-int rid")
                continue
            retire_rids.add(rec["rid"])
            if rec.get("failed"):
                failed_rids.add(rec["rid"])
                if rec["area"] is not None:
                    problems.append(
                        f"{where}:{i}: failed retire record must "
                        f"carry area null, got {rec['area']!r}")
            elif not _is_finite_number(rec.get("area")):
                problems.append(
                    f"{where}:{i}: retire record with non-finite "
                    f"area {rec.get('area')!r}")
        else:
            problems.append(f"{where}:{i}: unrecognized serve record "
                            f"shape (not retire/shed/rejected/"
                            f"summary)")
    if len(summaries) != 1:
        problems.append(f"{where}: expected exactly 1 summary line, "
                        f"found {len(summaries)}")
        return problems
    _, s = summaries[0]
    for key in ("completed", "phases", "totals", "latency"):
        if key not in s:
            problems.append(f"{where}: summary missing {key!r}")
    if isinstance(s.get("completed"), int) \
            and len(retire_rids) != s["completed"]:
        problems.append(
            f"{where}: summary.completed={s['completed']} but "
            f"{len(retire_rids)} distinct retire rids in the stream")
    if isinstance(s.get("shed"), int) \
            and len(shed_rids) != s["shed"]:
        problems.append(
            f"{where}: summary.shed={s['shed']} but "
            f"{len(shed_rids)} distinct shed rids in the stream")
    both = retire_rids & shed_rids
    if both:
        problems.append(f"{where}: rid(s) both retired and shed: "
                        f"{sorted(both)[:8]}")
    if isinstance(s.get("failed"), int) \
            and len(failed_rids) != s["failed"]:
        problems.append(
            f"{where}: summary.failed={s['failed']} but "
            f"{len(failed_rids)} distinct failed retire rids")
    return problems


def _scan_lines(text: str, where: str):
    """Scan a raw log/stdout stream for bench-record JSON lines;
    returns (problems, records_found)."""
    problems: List[str] = []
    found = 0
    for i, line in enumerate(text.splitlines(), 1):
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            obj = json.loads(line)
        except json.JSONDecodeError:
            if '"metric"' in line:
                # a truncated/garbled bench record is exactly the
                # silent-drop failure mode this check exists for
                problems.append(f"{where}:{i}: unparseable bench "
                                f"record line")
            continue
        if not isinstance(obj, dict) or "metric" not in obj:
            continue                 # some other JSON block (e.g. logs)
        found += 1
        try:
            validate_record(obj, where=f"{where}:{i}")
        except ArtifactSchemaError as e:
            problems.append(str(e))
    return problems, found


# --- round 23: replay dedup shared by the events analyzers ------------------

def dedup_replayed(records: List[dict], key_fn) -> List[dict]:
    """Collapse replayed duplicates out of an events stream: after a
    kill-and-resume, the replayed turns re-emit their events with
    IDENTICAL content (that is the determinism contract), so each
    record collapses onto its original. First occurrence wins — file
    order is emission order, so the original precedes its replay —
    which also keeps the analyzers order-stable. Records whose key is
    None are kept verbatim (no identity to collapse on).

    One definition, used by both ``analyze_occupancy --from-events``
    and ``analyze_request`` (they previously carried copies)."""
    out: List[dict] = []
    seen = set()
    for r in records:
        k = key_fn(r)
        if k is None:
            out.append(r)
            continue
        if k in seen:
            continue
        seen.add(k)
        out.append(r)
    return out


def dedup_by_rid(records: List[dict]) -> List[dict]:
    """Replay dedup keyed on the request id — the common case: one
    retire/shed event per rid survives, replays collapse."""
    return dedup_replayed(records, lambda r: r.get("rid"))


# --- round 17: graftlint --format json documents ---------------------------

def validate_graftlint_json(doc, where: str = "graftlint") -> List[str]:
    """Validate a ``python -m tools.graftlint --format json`` document:
    the machine-readable lint ledger ci.sh feeds to annotation tooling.
    One record per violation with the full line-free key, counts that
    reconcile with the record list, and an ``ok`` flag consistent with
    the new-violation count — a malformed or self-inconsistent ledger
    must fail CI loudly, exactly like a malformed bench record."""
    import re
    problems: List[str] = []
    if not isinstance(doc, dict):
        return [f"{where}: document is not a JSON object"]
    if doc.get("schema") != "graftlint-v1":
        problems.append(f"{where}: schema != 'graftlint-v1' "
                        f"({doc.get('schema')!r})")
    if not isinstance(doc.get("target"), str) or not doc.get("target"):
        problems.append(f"{where}: missing/empty 'target'")
    if not isinstance(doc.get("deep"), bool):
        problems.append(f"{where}: 'deep' must be a bool")
    # "runtime" arrived with the GL12-GL14 tier (round 23); older
    # ledgers legitimately lack it, but a present field must be a bool
    if "runtime" in doc and not isinstance(doc["runtime"], bool):
        problems.append(f"{where}: 'runtime' must be a bool")
    vs = doc.get("violations")
    if not isinstance(vs, list):
        return problems + [f"{where}: 'violations' must be a list"]
    code_re = re.compile(r"^GL\d{2}$")
    n_new = n_known = 0
    for i, v in enumerate(vs):
        w = f"{where}: violations[{i}]"
        if not isinstance(v, dict):
            problems.append(f"{w}: not an object")
            continue
        for k, t in (("key", str), ("code", str), ("path", str),
                     ("symbol", str), ("message", str), ("line", int),
                     ("grandfathered", bool)):
            if not isinstance(v.get(k), t) or (t is str and not v[k]):
                problems.append(f"{w}: missing/invalid {k!r}")
        code = v.get("code")
        if isinstance(code, str) and not code_re.match(code):
            problems.append(f"{w}: code {code!r} is not GLxx")
        # "tier" is optional (round-23 ledgers carry it) but a
        # present value must be a known tier name
        if "tier" in v and v["tier"] not in ("ast", "deep", "runtime"):
            problems.append(f"{w}: tier {v.get('tier')!r} is not one "
                            f"of ast/deep/runtime")
        key = v.get("key")
        if isinstance(key, str) and isinstance(code, str) \
                and isinstance(v.get("path"), str) \
                and isinstance(v.get("symbol"), str) \
                and key != f"{code}:{v['path']}:{v['symbol']}":
            problems.append(f"{w}: key {key!r} != code:path:symbol")
        if v.get("grandfathered") is True:
            n_known += 1
            if not isinstance(v.get("reason"), str):
                problems.append(f"{w}: grandfathered record lacks a "
                                f"'reason'")
        elif v.get("grandfathered") is False:
            n_new += 1
    stale = doc.get("stale")
    if not isinstance(stale, list) \
            or not all(isinstance(s, str) for s in stale):
        problems.append(f"{where}: 'stale' must be a list of keys")
    counts = doc.get("counts")
    if not isinstance(counts, dict):
        problems.append(f"{where}: missing 'counts'")
    else:
        expect = {"total": n_new + n_known, "new": n_new,
                  "grandfathered": n_known,
                  "stale": len(stale) if isinstance(stale, list)
                  else counts.get("stale")}
        for k, e in expect.items():
            if counts.get(k) != e:
                problems.append(
                    f"{where}: counts.{k}={counts.get(k)!r} does not "
                    f"reconcile with the record list ({e})")
    if isinstance(doc.get("ok"), bool) and doc["ok"] != (n_new == 0):
        problems.append(f"{where}: ok={doc['ok']} but {n_new} new "
                        f"violation record(s)")
    elif not isinstance(doc.get("ok"), bool):
        problems.append(f"{where}: 'ok' must be a bool")
    return problems


def validate_graftlint_text(text: str,
                            where: str = "graftlint") -> List[str]:
    try:
        doc = json.loads(text)
    except json.JSONDecodeError as e:
        return [f"{where}: unparseable JSON: {e}"]
    return validate_graftlint_json(doc, where=where)


def validate_tuning_table_json(doc, where: str = "tuning") -> List[str]:
    """Validate a ``bench.py tune`` tuning-table document (round 20):
    the committed knob store every engine's cadence resolution reads.
    Each entry must carry its full signature (the key string must
    round-trip from it), the tuned knob values, baseline/tuned quick
    proxies, and sweep provenance (trial count, recompile count,
    reconciliation status, seed/budget) — a table whose provenance is
    missing cannot be audited and must fail CI loudly, exactly like a
    malformed bench record. Performance floors (tuned beats default on
    >= 2 families) are the bench gate's job, not the schema's."""
    problems: List[str] = []
    if not isinstance(doc, dict):
        return [f"{where}: document is not a JSON object"]
    if doc.get("schema") != "ppls-tuning-table-v1":
        problems.append(f"{where}: schema != 'ppls-tuning-table-v1' "
                        f"({doc.get('schema')!r})")
    entries = doc.get("entries")
    if not isinstance(entries, dict):
        return problems + [f"{where}: 'entries' must be an object"]
    sig_fields = ("family", "eps_band", "rule", "theta_band",
                  "mesh_shape", "mode")
    for key in sorted(entries):
        e = entries[key]
        w = f"{where}: entries[{key!r}]"
        if not isinstance(e, dict):
            problems.append(f"{w}: not an object")
            continue
        if e.get("schema") != "ppls-tuning-entry-v1":
            problems.append(f"{w}: entry schema != "
                            f"'ppls-tuning-entry-v1'")
        sig = e.get("signature")
        if not isinstance(sig, dict):
            problems.append(f"{w}: missing 'signature'")
        else:
            for k in sig_fields:
                if k not in sig:
                    problems.append(f"{w}: signature lacks {k!r}")
            dev = e.get("device_kind")
            if not isinstance(dev, str) or not dev:
                problems.append(f"{w}: missing 'device_kind'")
            elif all(k in sig for k in sig_fields):
                expect = "|".join(
                    [f"{k}={sig[k]}" for k in sig_fields]
                    + [f"device={dev}"])
                if key != expect:
                    problems.append(f"{w}: key does not round-trip "
                                    f"from its signature ({expect!r})")
        knobs = e.get("knobs")
        if not isinstance(knobs, dict) or not knobs:
            problems.append(f"{w}: missing 'knobs'")
        for blk in ("baseline", "tuned"):
            b = e.get(blk)
            if not isinstance(b, dict):
                problems.append(f"{w}: missing {blk!r} proxies")
                continue
            for k in ("tasks", "kernel_steps", "lane_efficiency"):
                v = b.get(k)
                if not isinstance(v, (int, float)) \
                        or isinstance(v, bool) or v < 0:
                    problems.append(f"{w}: {blk}.{k} missing or "
                                    f"non-numeric")
        prov = e.get("provenance")
        if not isinstance(prov, dict):
            problems.append(f"{w}: missing 'provenance'")
            continue
        for k, t in (("trials", int), ("recompiles", int),
                     ("reconciles", bool), ("seed", int),
                     ("budget", int), ("improved", bool)):
            if not isinstance(prov.get(k), t) \
                    or (t is int and isinstance(prov.get(k), bool)):
                problems.append(f"{w}: provenance.{k} missing/invalid")
        if isinstance(prov.get("trials"), int) \
                and not isinstance(prov.get("trials"), bool) \
                and prov["trials"] < 1:
            problems.append(f"{w}: provenance.trials < 1")
        path = prov.get("path")
        if not isinstance(path, list):
            problems.append(f"{w}: provenance.path must be a list")
        elif isinstance(prov.get("trials"), int) \
                and not isinstance(prov.get("trials"), bool) \
                and len(path) != prov["trials"] - 1:
            problems.append(
                f"{w}: provenance.path has {len(path)} move(s) but "
                f"trials={prov['trials']} (expected trials - 1)")
    return problems


def validate_tuning_table_text(text: str,
                               where: str = "tuning") -> List[str]:
    try:
        doc = json.loads(text)
    except json.JSONDecodeError as e:
        return [f"{where}: unparseable JSON: {e}"]
    return validate_tuning_table_json(doc, where=where)
