"""Persistent XLA compilation cache (opt-out via PPLS_NO_COMPILE_CACHE).

Full walker-cycle programs take minutes to compile on this rig's
remote-compile path, and every process (bench, CLI, TPU test lane,
tools) used to pay that again: the round-5 TPU lane spent ~14 of its
15:39 minutes recompiling programs the bench had already built.
Verified on the tunneled backend: a 232 s compile replays from the
on-disk cache in ~3 s in a fresh process.

Keyed by HLO hash, so stale entries are impossible — a code change
simply misses and recompiles.
"""

import os


def enable_compile_cache(path: str | None = None) -> str | None:
    """Point JAX at a persistent on-disk compilation cache and return
    its path (None when disabled via PPLS_NO_COMPILE_CACHE=1)."""
    if os.environ.get("PPLS_NO_COMPILE_CACHE"):
        return None
    import jax

    path = (path or os.environ.get("PPLS_COMPILE_CACHE")
            or os.path.join(os.path.expanduser("~"), ".cache",
                            "ppls_tpu_xla"))
    os.makedirs(path, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", path)
    # cache anything that took noticeable compile time; tiny programs
    # recompile faster than they deserialize
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 2)
    return path
