"""Persistent XLA compilation cache (opt-out via PPLS_NO_COMPILE_CACHE).

Keyed by HLO hash, so stale entries are impossible — a code change
simply misses and recompiles.

Measured reach on this rig (round 5): XLA-only programs replay across
processes (a 232 s compile returned in ~3 s from a fresh process), but
programs embedding Mosaic/Pallas custom calls — the walker cycle
engines — MISS across processes (the flagship recompiled in ~300 s
from a warm 245 MB cache; the serialized kernel payload appears to
carry process-varying bytes that perturb the key). Net: the bag/2D/
QMC/sharded non-walker programs and all within-process reuse benefit;
the walker's cross-process compile cost remains until the upstream
key instability is fixed. Left enabled because it never hurts
correctness and already removes minutes from mixed workloads.
"""

import os


def enable_compile_cache(path: str | None = None) -> str | None:
    """Point JAX at a persistent on-disk compilation cache and return
    its path (None when disabled via PPLS_NO_COMPILE_CACHE=1)."""
    if os.environ.get("PPLS_NO_COMPILE_CACHE"):
        return None
    import jax

    path = (path or os.environ.get("PPLS_COMPILE_CACHE")
            or os.path.join(os.path.expanduser("~"), ".cache",
                            "ppls_tpu_xla"))
    os.makedirs(path, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", path)
    # cache anything that took noticeable compile time; tiny programs
    # recompile faster than they deserialize
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 2)
    return path
