"""Metrics and observability.

The reference's entire observability surface is one counter table —
``tasks_per_process[]`` incremented per dispatch (``aquadPartA.c:162``) and
printed at exit (``aquadPartA.c:109-118``) — plus the final area. Here
every run produces per-round wavefront statistics (frontier width, accept
rate, split rate), cumulative task/eval counts that reproduce the
reference's histogram at chip granularity, achieved global error when the
analytic integral is known, and throughput in subintervals/sec/chip (the
BASELINE.json north-star metric).
"""

from __future__ import annotations

import dataclasses
import json
from typing import List, Optional, Sequence


@dataclasses.dataclass
class RoundStats:
    """One wavefront round (one device launch generation)."""

    round_index: int
    frontier_width: int      # active intervals evaluated this round
    splits: int              # intervals that refined
    leaves: int              # intervals accepted into the area
    padded_width: int = 0    # padded batch width actually launched

    @property
    def accept_rate(self) -> float:
        return self.leaves / self.frontier_width if self.frontier_width else 0.0


@dataclasses.dataclass
class RunMetrics:
    """Aggregate metrics for one integration run."""

    tasks: int = 0           # total intervals evaluated (reference: 6567)
    splits: int = 0          # reference: 3283
    leaves: int = 0          # reference: 3284
    rounds: int = 0          # wavefront rounds (reference workload: 15)
    max_depth: int = 0       # refinement depth (reference: 14)
    integrand_evals: int = 0  # distinct f(x) evaluations
    wall_time_s: float = 0.0
    n_chips: int = 1
    tasks_per_chip: Optional[List[int]] = None  # parity histogram analog
    per_round: List[RoundStats] = dataclasses.field(default_factory=list)

    @property
    def evals_per_sec_per_chip(self) -> float:
        if self.wall_time_s <= 0:
            return 0.0
        return self.integrand_evals / self.wall_time_s / max(self.n_chips, 1)

    @property
    def tasks_per_sec_per_chip(self) -> float:
        if self.wall_time_s <= 0:
            return 0.0
        return self.tasks / self.wall_time_s / max(self.n_chips, 1)

    def record_round(self, stats: RoundStats) -> None:
        """Legacy wavefront-engine hook: append AND accumulate the
        aggregate counters from the round. The walker/stream engines
        count their aggregates on-device instead — they populate
        ``per_round`` directly via :func:`round_stats_from_rows`
        without double-counting through this method."""
        self.per_round.append(stats)
        self.rounds = len(self.per_round)
        self.tasks += stats.frontier_width
        self.splits += stats.splits
        self.leaves += stats.leaves

    def histogram_str(self) -> str:
        """Tasks-per-chip table in the spirit of ``aquadPartA.c:109-118``."""
        counts = self.tasks_per_chip or [self.tasks]
        head = "\t".join(str(i) for i in range(len(counts)))
        body = "\t".join(str(c) for c in counts)
        return f"Tasks Per Chip\n{head}\n{body}"

    def to_json(self) -> str:
        d = dataclasses.asdict(self)
        d["evals_per_sec_per_chip"] = self.evals_per_sec_per_chip
        return json.dumps(d)


def round_stats_from_rows(rows, fields: Sequence[str],
                          padded_width: int = 0) -> List[RoundStats]:
    """Convert device-counted per-cycle/per-phase stat rows into the
    shared :class:`RoundStats` record type (round 10: one per-round
    record across ALL engines — the walker engines predating this
    helper left ``per_round`` empty and only the legacy bag engines
    populated it).

    ``rows`` is the (n, len(fields)) integer array an engine's stats
    ring / phase log produced; ``fields`` its column-name tuple, which
    must carry ``tasks`` and ``splits`` columns (both
    ``CYCLE_STAT_FIELDS`` and ``STREAM_STAT_FIELDS`` do). One
    ``RoundStats`` per row: frontier_width = that round's device-
    counted tasks, leaves = tasks - splits (every task either splits
    or is accepted — the reference invariant, ``aquadPartA.c``'s
    3283/3284 split of 6567).
    """
    if rows is None or len(rows) == 0:
        return []
    i_t = list(fields).index("tasks")
    i_s = list(fields).index("splits")
    out: List[RoundStats] = []
    for i, row in enumerate(rows):
        t, s = int(row[i_t]), int(row[i_s])
        out.append(RoundStats(round_index=i, frontier_width=t,
                              splits=s, leaves=t - s,
                              padded_width=padded_width))
    return out
