"""Profiler tracing — the observability the reference lacks entirely
(its only artifact is the task-count histogram, ``aquadPartA.c:109-118``).

``trace(dir)`` wraps ``jax.profiler`` so any engine run can be captured
and inspected in TensorBoard/Perfetto (kernel timelines, HBM traffic,
per-op costs on the real chip):

    with trace("/tmp/ppls-trace"):
        integrate_family_walker(...)

Exposed on the CLI as ``--trace DIR`` (all modes). Complements the
host-side per-round ``RoundStats`` (utils/metrics.py) and the loop-body
microbenchmarks in ``tools/profile_bag.py``.
"""

from __future__ import annotations

import contextlib
from typing import Iterator, Optional


@contextlib.contextmanager
def trace(trace_dir: Optional[str]) -> Iterator[None]:
    """Capture a jax.profiler trace into ``trace_dir`` (no-op if None).
    The directory is created if missing — a ``--trace`` run must not
    die after the integration finished because the capture dir's
    parent path was never made."""
    if not trace_dir:
        yield
        return
    import os

    import jax

    os.makedirs(trace_dir, exist_ok=True)
    with jax.profiler.trace(trace_dir):
        yield


def annotate(name: str):
    """Named sub-span inside a trace (jax.profiler.TraceAnnotation)."""
    import jax

    return jax.profiler.TraceAnnotation(name)
