"""Test env: 8 virtual CPU devices, f64 enabled.

Must run before the first ``import jax`` anywhere in the test process
(SURVEY.md §4: multi-device tests on CPU via
``--xla_force_host_platform_device_count`` so no TPU cluster is needed).
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

# The sandbox pre-imports jax via a sitecustomize (PYTHONPATH points at an
# axon site dir), so the env var alone can be too late; the config update
# still wins as long as no backend has initialized.
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)
