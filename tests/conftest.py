"""Test env: 8 virtual CPU devices, f64 enabled — plus a real-TPU lane.

Default lane: force an 8-virtual-device CPU mesh so multi-chip sharding is
testable with no TPU cluster (SURVEY.md §4). Must run before the first
``import jax`` anywhere in the test process.

TPU lane: ``PPLS_TEST_PLATFORM=tpu python -m pytest tests/ -m tpu -q``
keeps whatever real accelerator the environment exposes and runs only the
``@pytest.mark.tpu`` subset. This lane exists because both round-2 bugs
(f64-emulation exponent underflow in ``exact_segment_sum``; the NaN runs
it caused) were TPU-only behaviors the forced-CPU suite structurally could
not catch (VERDICT r2, Weak #4).
"""

import os

import pytest

TPU_LANE = os.environ.get("PPLS_TEST_PLATFORM", "").lower() == "tpu"

if not TPU_LANE:
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()
    os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

if not TPU_LANE:
    # The sandbox pre-imports jax via a sitecustomize (PYTHONPATH points at
    # an axon site dir), so the env var alone can be too late; the config
    # update still wins as long as no backend has initialized.
    jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

# Strict-mode sanitizer lane (ISSUE 5): implicit rank promotion is how
# silent wrong-shape broadcasts slip into the f64 accumulator paths —
# the whole suite runs with it forbidden. Package code must broadcast
# explicitly ([None], broadcast_to, reshape).
jax.config.update("jax_numpy_rank_promotion", "raise")

# Opt-in loud-NaN lane: PPLS_DEBUG_NANS=1 re-runs the suite with
# jax_debug_nans, so ANY NaN produced inside a jitted program raises
# FloatingPointError at the producing primitive instead of flowing into
# an accumulator. Not the default because several tests create NaNs on
# purpose — those carry ``@pytest.mark.nan_injection``, and the autouse
# fixture below turns the flag off for exactly their duration.
if os.environ.get("PPLS_DEBUG_NANS", "") == "1":
    jax.config.update("jax_debug_nans", True)

# Persistent XLA compile cache: the TPU lane's full-cycle programs take
# minutes each on the remote-compile path; cached replays take seconds
# (utils/compile_cache.py). Safe for the CPU lane too (HLO-hash keyed).
from ppls_tpu.utils.compile_cache import enable_compile_cache  # noqa: E402

enable_compile_cache()


def pytest_sessionstart(session):
    if TPU_LANE:
        import time
        session._ppls_lane_t0 = time.time()


def pytest_sessionfinish(session, exitstatus):
    """TPU-lane wall-time artifact (VERDICT r5 Weak #4): append this
    session's wall time to TPU_LANE_TIMES.json (repo root; override
    with PPLS_TPU_LANE_TIME_FILE) so lane growth is visible
    round-over-round instead of silently doubling again."""
    if not TPU_LANE or not hasattr(session, "_ppls_lane_t0"):
        return
    import json
    import sys
    import time

    path = os.environ.get(
        "PPLS_TPU_LANE_TIME_FILE",
        os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "TPU_LANE_TIMES.json"))
    rec = {
        "when": time.strftime("%Y-%m-%dT%H:%M:%S"),
        "wall_s": round(time.time() - session._ppls_lane_t0, 1),
        "args": " ".join(sys.argv[1:]),
        "collected": int(getattr(session, "testscollected", 0)),
        "exitstatus": int(getattr(exitstatus, "value", exitstatus)),
    }
    try:
        with open(path) as fh:
            data = json.load(fh)
        if not isinstance(data, list):
            data = [data]
    except Exception:  # noqa: BLE001 — first run / unreadable file
        data = []
    data.append(rec)
    try:
        with open(path, "w") as fh:
            json.dump(data, fh, indent=1)
    except OSError:
        pass  # a read-only checkout must not fail the lane


@pytest.fixture(autouse=True)
def _nan_injection_flag(request):
    """Deliberate-NaN tests (``@pytest.mark.nan_injection``) must run
    with jax_debug_nans OFF even in the PPLS_DEBUG_NANS=1 lane: they
    pin NaN *propagation* contracts (NaN-err root ordering, the
    retire-path FloatingPointError), which debug-nans would preempt at
    the producing primitive. The previous flag value is restored so
    the lane stays on for every other test."""
    if "nan_injection" not in request.keywords:
        yield
        return
    prev = jax.config.jax_debug_nans
    jax.config.update("jax_debug_nans", False)
    try:
        yield
    finally:
        jax.config.update("jax_debug_nans", prev)


@pytest.fixture
def compile_once_guard():
    """Retracing guard (ISSUE 5): assert the given jitted entries
    compile EXACTLY ONCE inside the guarded block.

    Usage::

        with compile_once_guard(run_stream_cycle):
            eng.run(reqs, arrival_phase=[0, 1, 2])   # 3+ phases

    ``_cache_size()`` counts distinct (shapes, statics, weak-types)
    signatures in the pjit cache — a count > 1 means a static-arg or
    weak-type drifted between calls and the "one compiled program
    serves the whole stream/run" contract silently became
    one-compile-per-phase (the recompile-storm shape GL05 guards
    statically; this fixture guards it dynamically).
    """
    import contextlib

    @contextlib.contextmanager
    def guard(*jitted_fns):
        for fn in jitted_fns:
            fn._clear_cache()
        yield
        for fn in jitted_fns:
            n = fn._cache_size()
            assert n == 1, (
                f"{getattr(fn, '__name__', fn)!r} compiled {n} times "
                f"inside the guarded block (expected exactly once): a "
                f"static argument or weak-type is varying across "
                f"calls — recompile storm")

    return guard


def pytest_collection_modifyitems(config, items):
    """Skip @pytest.mark.tpu tests unless a real accelerator is visible."""
    on_accel = jax.default_backend() != "cpu"
    skip = pytest.mark.skip(
        reason="needs a real TPU (run: PPLS_TEST_PLATFORM=tpu "
               "pytest tests/ -m tpu)")
    for item in items:
        if "tpu" in item.keywords and not on_accel:
            item.add_marker(skip)
