"""Artifact schema check (ppls_tpu.utils.artifact_schema +
tools/check_artifacts.py): malformed bench records must fail loudly at
write time and at CI time instead of silently dropping from the
round-over-round trajectory."""

import json

import pytest

from ppls_tpu.utils.artifact_schema import (ArtifactSchemaError,
                                            validate_artifact_text,
                                            validate_record)

GOOD = {"metric": "subintervals evaluated/sec/chip", "value": 1.5e9,
        "unit": "subintervals/s/chip", "vs_baseline": 101.0}


def test_validate_record_accepts_good():
    assert validate_record(dict(GOOD)) == GOOD


def test_validate_record_accepts_failure_value():
    # 0.0 is the legitimate failure value; error records may omit the
    # baseline ratio
    validate_record({"metric": "m", "value": 0.0, "unit": "u",
                     "vs_baseline": 0.0, "error": "boom"})
    validate_record({"metric": "m", "value": 0.0, "unit": "u",
                     "error": "boom"})


@pytest.mark.parametrize("broken", [
    {"value": 1.0, "unit": "u", "vs_baseline": 0.0},          # no metric
    {"metric": "m", "unit": "u", "vs_baseline": 0.0},         # no value
    {"metric": "m", "value": float("nan"), "unit": "u",
     "vs_baseline": 0.0},                                     # NaN value
    {"metric": "m", "value": "12", "unit": "u",
     "vs_baseline": 0.0},                                     # str value
    {"metric": "m", "value": 1.0, "vs_baseline": 0.0},        # no unit
    {"metric": "m", "value": 1.0, "unit": "u"},               # no ratio
])
def test_validate_record_rejects_broken(broken):
    with pytest.raises(ArtifactSchemaError):
        validate_record(broken)


def test_validate_record_secondary_poison():
    rec = dict(GOOD, secondary={"2d": {"metric": "2d",
                                       "value": float("nan")}})
    with pytest.raises(ArtifactSchemaError, match="secondary.2d"):
        validate_record(rec)
    rec = dict(GOOD, secondary={"2d": {"error": "failed"},
                                "qmc": {"skipped": "no tpu"}})
    validate_record(rec)          # error/skipped secondaries pass


def test_validate_artifact_wrapper_shape():
    # the round driver's wrapper: records live as JSON lines inside
    # the "tail" string
    wrapper = {"n": 8, "rc": 0,
               "tail": "some log line\n" + json.dumps(GOOD) + "\n"}
    assert validate_artifact_text(json.dumps(wrapper)) == []
    # a garbled record inside the tail is caught
    bad = json.dumps(GOOD)[:-20] + "..."
    wrapper["tail"] = bad + "\n"
    problems = validate_artifact_text(json.dumps(wrapper))
    assert problems and "unparseable" in problems[0]


def test_validate_artifact_raw_stream():
    text = "log\n" + json.dumps(GOOD) + "\n"
    assert validate_artifact_text(text) == []
    assert validate_artifact_text("nothing here\n") \
        == ["artifact: no bench records found"]


def test_committed_artifacts_validate():
    # the repo's own round artifacts must pass the gate CI runs
    import subprocess
    import sys
    import os
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run(
        [sys.executable, os.path.join(root, "tools",
                                      "check_artifacts.py")],
        capture_output=True, text=True)
    assert proc.returncode == 0, proc.stdout + proc.stderr
