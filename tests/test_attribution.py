"""Round-11 lane-waste attribution: the performance-attribution
observatory's accounting core.

Acceptance surface:

* the four device-counted buckets (eval_active / masked_dead /
  refill_stall / drain_tail) RECONCILE EXACTLY to lanes x kernel steps
  — per cycle, per run, per stream phase, and per chip on the dd
  engine (walker, dd, and stream engines all asserted);
* the accounting survives checkpoint legs and kill-and-resume;
* the decomposition is readable offline from an events timeline
  (``analyze_occupancy --from-events``) and names the dominant bucket.
"""

import json
import subprocess
import sys

import numpy as np

from ppls_tpu.models.integrands import get_family, get_family_ds
from ppls_tpu.parallel.walker import (CYCLE_STAT_FIELDS,
                                      STREAM_STAT_FIELDS, WASTE_FIELDS,
                                      integrate_family_walker)

BOUNDS = (1e-2, 1.0)
EPS = 1e-7
WKW = dict(capacity=1 << 16, lanes=256, roots_per_lane=2,
           refill_slots=2, seg_iters=32, min_active_frac=0.05)
THETA = 1.0 + np.arange(6) / 6.0


def _run(refill_slots, **kw):
    base = dict(WKW, refill_slots=refill_slots)
    base.update(kw)
    return integrate_family_walker(
        get_family("sin_recip_scaled"),
        get_family_ds("sin_recip_scaled"),
        THETA, BOUNDS, EPS, **base)


def _assert_cycle_reconciliation(r):
    iw = [CYCLE_STAT_FIELDS.index(k) for k in WASTE_FIELDS]
    istep = CYCLE_STAT_FIELDS.index("walker_steps")
    for row in np.asarray(r.cycle_stats):
        assert sum(int(row[i]) for i in iw) \
            == int(row[istep]) * r.lanes, row


def test_walker_refill_buckets_reconcile():
    r = _run(refill_slots=2)
    a = r.attribution()
    assert a is not None and a["reconciles"], a
    assert a["lane_cycles"] == r.kernel_steps * r.lanes
    assert sum(a["buckets"].values()) == a["lane_cycles"]
    # the useful bucket dominates on a healthy run, and the kernel's
    # tasks are a subset of eval-active steps (one test per task)
    assert a["buckets"]["eval_active"] > a["lane_cycles"] // 2
    assert a["buckets"]["eval_active"] >= r.metrics.tasks * 0.9
    assert a["dominant_waste"] in WASTE_FIELDS[1:]
    _assert_cycle_reconciliation(r)


def test_walker_legacy_buckets_reconcile():
    r = _run(refill_slots=0)
    a = r.attribution()
    assert a is not None and a["reconciles"], a
    _assert_cycle_reconciliation(r)
    # legacy mode has no in-kernel bank: drain_tail only appears once
    # the queue is dry, stall while it is not — both causes must be
    # distinguishable (non-negative, summing with the rest exactly)
    assert all(v >= 0 for v in a["buckets"].values())


def test_walker_attribution_survives_checkpoint_resume(tmp_path):
    base = _run(refill_slots=2)
    path = str(tmp_path / "w.ckpt")
    legged = _run(refill_slots=2, checkpoint_path=path,
                  checkpoint_every=1)
    # leg boundaries replay the identical per-cycle computation: the
    # device-counted buckets accumulate to the same totals
    assert np.array_equal(np.asarray(legged.waste),
                          np.asarray(base.waste))
    assert legged.attribution()["reconciles"]


def test_stream_phase_rows_reconcile():
    from ppls_tpu.runtime.stream import StreamEngine
    eng = StreamEngine("sin_recip_scaled", EPS, slots=8,
                       chunk=1 << 10, **WKW)
    res = eng.run([(float(t), BOUNDS) for t in THETA],
                  arrival_phase=[0, 0, 1, 2, 3, 5])
    iw = [STREAM_STAT_FIELDS.index(k) for k in WASTE_FIELDS]
    istep = STREAM_STAT_FIELDS.index("wsteps")
    lanes = WKW["lanes"]
    assert len(res.phase_stats)
    for row in res.phase_stats:
        assert sum(int(row[i]) for i in iw) == int(row[istep]) * lanes
    # registry-sourced totals carry the same buckets
    tot_buckets = sum(int(res.totals[k]) for k in WASTE_FIELDS)
    assert tot_buckets == int(res.totals["wsteps"]) * lanes
    occ = res.occupancy_summary(lanes)
    assert occ["attribution"]["reconciles"]
    assert occ["attribution"]["dominant_waste"] in WASTE_FIELDS[1:]


def test_dd_walker_buckets_reconcile_per_chip():
    from ppls_tpu.parallel.sharded_walker import (
        integrate_family_walker_dd)
    r = integrate_family_walker_dd(
        "sin_recip_scaled", THETA, (1e-3, 1.0), 1e-9,
        chunk=1 << 8, capacity=1 << 16, lanes=256, roots_per_lane=2,
        refill_slots=2, n_devices=8)
    a = r.attribution()
    assert a is not None and a["reconciles"], a
    from ppls_tpu.parallel.walker import N_WASTE
    assert r.waste_per_chip.shape == (8, N_WASTE)
    assert np.array_equal(r.waste_per_chip.sum(axis=0), r.waste)
    # the mesh-aggregate reconciliation: kernel_steps is the per-chip
    # sum, lanes is per chip, so buckets == kernel_steps * lanes
    assert int(r.waste.sum()) == r.kernel_steps * r.lanes


def test_dd_attribution_survives_checkpoint_resume(tmp_path):
    from ppls_tpu.parallel.sharded_walker import (
        integrate_family_walker_dd, resume_family_walker_dd)
    dkw = dict(chunk=1 << 8, capacity=1 << 16, lanes=256,
               roots_per_lane=2, refill_slots=2, n_devices=8)
    base = integrate_family_walker_dd(
        "sin_recip_scaled", THETA, (1e-3, 1.0), 1e-9, **dkw)
    path = str(tmp_path / "dd.ckpt")
    try:
        integrate_family_walker_dd(
            "sin_recip_scaled", THETA, (1e-3, 1.0), 1e-9,
            checkpoint_path=path, checkpoint_every=1,
            _crash_after_legs=1, **dkw)
        raise AssertionError("crash hook did not fire")
    except RuntimeError as e:
        assert "simulated crash" in str(e)
    resumed = resume_family_walker_dd(
        path, "sin_recip_scaled", THETA, (1e-3, 1.0), 1e-9,
        checkpoint_every=1, **dkw)
    assert np.array_equal(resumed.waste, base.waste)
    assert np.array_equal(resumed.waste_per_chip, base.waste_per_chip)


def test_analyze_occupancy_from_events_prints_attribution(tmp_path):
    import os

    from ppls_tpu.obs import Telemetry
    from ppls_tpu.runtime.stream import StreamEngine
    ev = str(tmp_path / "run.jsonl")
    tel = Telemetry(events_path=ev)
    eng = StreamEngine("sin_recip_scaled", EPS, slots=8,
                       chunk=1 << 10, telemetry=tel, **WKW)
    eng.run([(float(t), BOUNDS) for t in THETA])
    tel.close()
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    r = subprocess.run(
        [sys.executable, "tools/analyze_occupancy.py", "--from-events",
         ev, "--lanes", str(WKW["lanes"])],
        capture_output=True, text=True, cwd=repo, timeout=120)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "lane-waste attribution" in r.stdout
    assert "dominant waste bucket:" in r.stdout
    assert "-> OK" in r.stdout        # offline reconciliation holds
    # round 20: the printer recommends the dominant bucket's knob from
    # the SAME map the tuner sweeps (tune.BUCKET_KNOB_MAP) — one
    # definition, asserted end to end through the CLI
    from ppls_tpu.runtime.tune import BUCKET_KNOB_MAP
    dom = r.stdout.split("dominant waste bucket:")[1].split()[0]
    if dom in BUCKET_KNOB_MAP:
        assert "recommended knob: " \
            + ", ".join(BUCKET_KNOB_MAP[dom]) in r.stdout
