"""Backend shim tests: C sequential baseline + MPI parity harness
(SURVEY.md §7 step 6). The MPI path is gated on an MPI toolchain."""

import pytest

from ppls_tpu.backends import build_seq, mpi_available, run_mpi, run_seq
from ppls_tpu.config import REFERENCE_CONFIG, Rule
from ppls_tpu.runtime.host_frontier import integrate

needs_cc = pytest.mark.skipif(build_seq() is None,
                              reason="no C compiler on PATH")


@needs_cc
def test_seq_backend_golden():
    res = run_seq(REFERENCE_CONFIG)
    assert f"{res.area:.6f}" == "7583461.801486"
    assert res.metrics.tasks == 6567
    assert res.metrics.splits == 3283
    assert res.metrics.max_depth == 14


@needs_cc
def test_seq_matches_jax_backend():
    c = run_seq(REFERENCE_CONFIG)
    j = integrate(REFERENCE_CONFIG)
    # Same task tree; printed-precision identical area (summation orders
    # differ: LIFO vs breadth-first).
    assert c.metrics.tasks == j.metrics.tasks
    assert c.metrics.splits == j.metrics.splits
    assert abs(c.area - j.area) < 1e-6


def test_backend_rejects_simpson():
    with pytest.raises(ValueError, match="trapezoid"):
        run_seq(REFERENCE_CONFIG.replace(rule=Rule.SIMPSON))


def test_backend_rejects_unknown_integrand():
    with pytest.raises(ValueError, match="integrands"):
        run_seq(REFERENCE_CONFIG.replace(integrand="runge"))


def test_mpi_gated():
    if not mpi_available():
        with pytest.raises(RuntimeError, match="mpicc"):
            run_mpi(REFERENCE_CONFIG)
    else:
        res = run_mpi(REFERENCE_CONFIG, n_workers=4)
        assert f"{res.area:.6f}" == "7583461.801486"
        assert res.metrics.tasks == 6567


@needs_cc
def test_mpi_stub_golden_parity():
    """VERDICT Missing #1: the farmer/worker PROTOCOL executes on this
    toolchain-less host via the single-process MPI stub (csrc/
    mpi_stub.h — ranks as threads, in-process mailboxes) and
    reproduces the golden numbers the real-MPI path is pinned to."""
    from ppls_tpu.backends.mpi_backend import run_mpi_stub

    res = run_mpi_stub(REFERENCE_CONFIG, n_workers=4)
    assert f"{res.area:.6f}" == "7583461.801486"
    assert res.metrics.tasks == 6567
    assert res.metrics.splits == 3283
    assert res.metrics.max_depth == 14
    # demand-driven dispatch fed every worker rank (cf. the
    # reference's 1679/1605/1682/1601 — aquadPartA.c:36); rank 0 is
    # the farmer and holds no tasks. No balance RATIO is asserted:
    # the split across pthread ranks is OS-scheduler-dependent and a
    # bound would flake on a loaded CI host — the protocol contract
    # is the golden area/task parity above plus task conservation.
    tpr = res.metrics.tasks_per_chip
    assert tpr[0] == 0 and len(tpr) == 5
    workers = tpr[1:]
    assert sum(workers) == 6567
    assert min(workers) > 0


@needs_cc
def test_mpi_stub_worker_count_invariance():
    from ppls_tpu.backends.mpi_backend import run_mpi_stub

    a1 = run_mpi_stub(REFERENCE_CONFIG, n_workers=1)
    a7 = run_mpi_stub(REFERENCE_CONFIG, n_workers=7)
    # compensated farmer accumulation: same task tree, same area at
    # printed precision regardless of worker count / arrival order
    assert a1.metrics.tasks == a7.metrics.tasks == 6567
    assert f"{a1.area:.6f}" == f"{a7.area:.6f}" == "7583461.801486"


def test_cli_family_mode(capsys):
    from ppls_tpu.__main__ import main
    rc = main(["family", "--m", "4", "--eps", "1e-5", "--chunk", "512",
               "--capacity", "32768", "-a", "1e-2", "--json"])
    assert rc == 0
    import json as _json
    out = _json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["tasks"] > 0
    assert out["abs_error"] is not None and out["abs_error"] < 1e-3


def test_cli_2d_mode(capsys):
    from ppls_tpu.__main__ import main
    rc = main(["2d", "--eps", "1e-6", "--json"])
    assert rc == 0
    import json as _json
    out = _json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["global_error"] < 1e-5


def test_cli_qmc_mode(capsys):
    from ppls_tpu.__main__ import main
    rc = main(["qmc", "--n", "65536", "--genz", "continuous", "--json"])
    assert rc == 0
    import json as _json
    out = _json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["families"]["continuous"]["rel_error"] < 1e-3
