"""Backend shim tests: C sequential baseline + MPI parity harness
(SURVEY.md §7 step 6). The MPI path is gated on an MPI toolchain."""

import pytest

from ppls_tpu.backends import build_seq, mpi_available, run_mpi, run_seq
from ppls_tpu.config import REFERENCE_CONFIG, Rule
from ppls_tpu.runtime.host_frontier import integrate

needs_cc = pytest.mark.skipif(build_seq() is None,
                              reason="no C compiler on PATH")


@needs_cc
def test_seq_backend_golden():
    res = run_seq(REFERENCE_CONFIG)
    assert f"{res.area:.6f}" == "7583461.801486"
    assert res.metrics.tasks == 6567
    assert res.metrics.splits == 3283
    assert res.metrics.max_depth == 14


@needs_cc
def test_seq_matches_jax_backend():
    c = run_seq(REFERENCE_CONFIG)
    j = integrate(REFERENCE_CONFIG)
    # Same task tree; printed-precision identical area (summation orders
    # differ: LIFO vs breadth-first).
    assert c.metrics.tasks == j.metrics.tasks
    assert c.metrics.splits == j.metrics.splits
    assert abs(c.area - j.area) < 1e-6


def test_backend_rejects_simpson():
    with pytest.raises(ValueError, match="trapezoid"):
        run_seq(REFERENCE_CONFIG.replace(rule=Rule.SIMPSON))


def test_backend_rejects_unknown_integrand():
    with pytest.raises(ValueError, match="integrands"):
        run_seq(REFERENCE_CONFIG.replace(integrand="runge"))


def test_mpi_gated():
    if not mpi_available():
        with pytest.raises(RuntimeError, match="mpicc"):
            run_mpi(REFERENCE_CONFIG)
    else:
        res = run_mpi(REFERENCE_CONFIG, n_workers=4)
        assert f"{res.area:.6f}" == "7583461.801486"
        assert res.metrics.tasks == 6567
