"""Bag engine (chunked LIFO, multi-problem family) tests."""

import numpy as np
import pytest

from ppls_tpu.config import OSC_CONFIG, REFERENCE_CONFIG, Rule
from ppls_tpu.models.integrands import get_family
from ppls_tpu.parallel.bag_engine import integrate_bag, integrate_family
from ppls_tpu.runtime.host_frontier import integrate


def test_bag_golden_area():
    r = integrate_bag(REFERENCE_CONFIG.replace(capacity=1 << 16), chunk=1024)
    assert f"{r.areas[0]:.6f}" == "7583461.801486"
    assert r.metrics.tasks == 6567
    assert r.metrics.splits == 3283


def test_bag_matches_host_engine_oscillatory():
    cfg = OSC_CONFIG.replace(capacity=1 << 18)
    bag = integrate_bag(cfg, chunk=1 << 12)
    host = integrate(cfg)
    assert bag.metrics.tasks == host.metrics.tasks
    assert abs(bag.areas[0] - host.area) < 1e-10


def test_family_matches_single_runs():
    f = get_family("sin_scaled")
    theta = np.array([1.0, 3.0, 10.0])
    fam = integrate_family(f, theta, (0.0, 2.0), 1e-8,
                           chunk=1 << 10, capacity=1 << 16)
    # compare each family member against the closed form of its integral
    import math
    for i, s in enumerate(theta):
        exact = (1.0 - math.cos(s * 2.0)) / s
        assert abs(fam.areas[i] - exact) < 1e-5, (i, s)
    assert fam.metrics.tasks == fam.metrics.splits + fam.metrics.leaves


def test_family_lane_efficiency_reported():
    f = get_family("sin_recip_scaled")
    theta = 1.0 + np.arange(8) / 8.0
    r = integrate_family(f, theta, (1e-4, 1.0), 1e-6,
                         chunk=1 << 10, capacity=1 << 18)
    assert 0.0 < r.lane_efficiency <= 1.0
    assert len(r.areas) == 8


def test_bag_overflow_detected():
    with pytest.raises(RuntimeError, match="overflow"):
        integrate_bag(REFERENCE_CONFIG.replace(capacity=64), chunk=32)


def test_bag_deterministic():
    cfg = REFERENCE_CONFIG.replace(capacity=1 << 16)
    a1 = integrate_bag(cfg, chunk=512).areas[0]
    a2 = integrate_bag(cfg, chunk=512).areas[0]
    assert a1 == a2


@pytest.mark.nan_injection
def test_nan_areas_raise_not_report():
    # An engine returning NaN must raise, not hand garbage to callers —
    # the round-2 bench recorded a "perfect" gate over all-NaN areas
    # because nothing between the accumulator and the JSON line checked
    # finiteness (VERDICT r2 Weak #1/#2). nan_injection: pins the
    # ACCUMULATOR-path raise, which debug-nans would preempt.
    import jax.numpy as jnp

    with pytest.raises(FloatingPointError, match="non-finite"):
        integrate_family(lambda x, th: x * jnp.nan, [0.0], (0.0, 1.0),
                         1e-3, chunk=256, capacity=1 << 12)


def test_family_exact_reference_values():
    # The mpmath closed forms behind the bench's abs-error metric, validated
    # against independent high-precision quadrature / elementary identities.
    import mpmath

    from ppls_tpu.models.integrands import family_exact, get_integrand

    # sin_recip_scaled at theta=1 vs mpmath adaptive quadrature with the
    # oscillatory region finely subdivided (agrees to ~1e-15).
    (v,) = family_exact("sin_recip_scaled", 1e-4, 1.0, [1.0])
    with mpmath.workdps(30):
        pts = [mpmath.mpf("1e-4")] + [mpmath.mpf(1) / k
                                      for k in range(9999, 0, -937)] + [1]
        q = float(mpmath.quad(lambda x: mpmath.sin(1 / x), pts, maxdegree=10))
    assert abs(v - q) < 1e-12
    # ... and theta=1 must agree with the sin_recip integrand's own
    # antiderivative (same function, two independent code paths).
    assert abs(v - get_integrand("sin_recip").exact(1e-4, 1.0)) < 1e-13

    (w,) = family_exact("sin_scaled", 0.0, 2.0, [3.0])
    import math
    assert abs(w - (1.0 - math.cos(6.0)) / 3.0) < 1e-14

    assert family_exact("no_such_family", 0.0, 1.0, [1.0]) is None


def test_family_achieved_abs_error_oscillatory():
    # North-star metric pair: the engine's global error on the flagship
    # family must be reportable and small. eps is a per-interval split
    # tolerance (like the reference's EPSILON, aquadPartA.c:45), so global
    # error accumulates over leaves; measured ~2e-5 at eps=1e-8 and ~1e-6
    # at eps=1e-10 on this workload.
    from ppls_tpu.models.integrands import family_exact

    theta = np.array([1.0, 1.5])
    f = get_family("sin_recip_scaled")
    r = integrate_family(f, theta, (1e-4, 1.0), 1e-8,
                         chunk=1 << 11, capacity=1 << 19)
    exact = family_exact("sin_recip_scaled", 1e-4, 1.0, theta)
    err = np.max(np.abs(r.areas - np.asarray(exact)))
    assert err < 1e-4, err
