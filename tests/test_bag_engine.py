"""Bag engine (chunked LIFO, multi-problem family) tests."""

import numpy as np
import pytest

from ppls_tpu.config import OSC_CONFIG, REFERENCE_CONFIG, Rule
from ppls_tpu.models.integrands import get_family
from ppls_tpu.parallel.bag_engine import integrate_bag, integrate_family
from ppls_tpu.runtime.host_frontier import integrate


def test_bag_golden_area():
    r = integrate_bag(REFERENCE_CONFIG.replace(capacity=1 << 16), chunk=1024)
    assert f"{r.areas[0]:.6f}" == "7583461.801486"
    assert r.metrics.tasks == 6567
    assert r.metrics.splits == 3283


def test_bag_matches_host_engine_oscillatory():
    cfg = OSC_CONFIG.replace(capacity=1 << 18)
    bag = integrate_bag(cfg, chunk=1 << 12)
    host = integrate(cfg)
    assert bag.metrics.tasks == host.metrics.tasks
    assert abs(bag.areas[0] - host.area) < 1e-10


def test_family_matches_single_runs():
    f = get_family("sin_scaled")
    theta = np.array([1.0, 3.0, 10.0])
    fam = integrate_family(f, theta, (0.0, 2.0), 1e-8,
                           chunk=1 << 10, capacity=1 << 16)
    # compare each family member against the closed form of its integral
    import math
    for i, s in enumerate(theta):
        exact = (1.0 - math.cos(s * 2.0)) / s
        assert abs(fam.areas[i] - exact) < 1e-5, (i, s)
    assert fam.metrics.tasks == fam.metrics.splits + fam.metrics.leaves


def test_family_lane_efficiency_reported():
    f = get_family("sin_recip_scaled")
    theta = 1.0 + np.arange(8) / 8.0
    r = integrate_family(f, theta, (1e-4, 1.0), 1e-6,
                         chunk=1 << 10, capacity=1 << 18)
    assert 0.0 < r.lane_efficiency <= 1.0
    assert len(r.areas) == 8


def test_bag_overflow_detected():
    with pytest.raises(RuntimeError, match="overflow"):
        integrate_bag(REFERENCE_CONFIG.replace(capacity=64), chunk=32)


def test_bag_deterministic():
    cfg = REFERENCE_CONFIG.replace(capacity=1 << 16)
    a1 = integrate_bag(cfg, chunk=512).areas[0]
    a2 = integrate_bag(cfg, chunk=512).areas[0]
    assert a1 == a2


def test_nan_areas_raise_not_report():
    # An engine returning NaN must raise, not hand garbage to callers —
    # the round-2 bench recorded a "perfect" gate over all-NaN areas
    # because nothing between the accumulator and the JSON line checked
    # finiteness (VERDICT r2 Weak #1/#2).
    import jax.numpy as jnp

    with pytest.raises(FloatingPointError, match="non-finite"):
        integrate_family(lambda x, th: x * jnp.nan, [0.0], (0.0, 1.0),
                         1e-3, chunk=256, capacity=1 << 12)
