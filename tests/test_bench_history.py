"""Bench observatory (round 11): trajectory normalization and the
quick-proxy regression gate.

The acceptance fixture: a record with an injected 2x slowdown (doubled
kernel_steps) MUST trip the gate; the committed reference passes
against itself; a workload-identity drift refuses to compare instead
of silently passing.
"""

import copy
import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from tools.bench_history import (  # noqa: E402
    check_trajectory,
    gate_record,
    load_trajectory,
)

REF_PATH = os.path.join(REPO, "tools", "bench_quick_ref.json")


def _ref():
    with open(REF_PATH, encoding="utf-8") as fh:
        return json.load(fh)


def test_committed_reference_exists_and_reconciles():
    ref = _ref()
    w = ref["walker"]
    assert w["tasks"] > 0 and w["kernel_steps"] > 0
    a = w["attribution"]
    assert a["reconciles"] is True
    assert sum(a["buckets"].values()) == a["lane_cycles"]


def test_committed_artifacts_pass_check():
    traj = load_trajectory()
    assert check_trajectory(traj) == []
    bench = [r for r in traj["rounds"] if r["kind"] == "bench"]
    assert len(bench) >= 5
    # the trajectory is normalized: every non-error round carries the
    # primary metric with a finite positive value
    for r in bench:
        assert r["primary"] is not None
        if "error" not in r["primary"]:
            assert r["primary"]["value"] > 0


def test_check_flags_malformed_rounds(tmp_path):
    good = tmp_path / "BENCH_r90.json"
    good.write_text(json.dumps({
        "n": 90, "tail": json.dumps(
            {"metric": "subintervals evaluated/sec/chip",
             "value": 1.0, "unit": "x", "vs_baseline": 1.0})}))
    empty = tmp_path / "BENCH_r91.json"
    empty.write_text(json.dumps({"n": 91, "tail": "no records here"}))
    traj = load_trajectory([str(good), str(empty)])
    probs = check_trajectory(traj)
    assert any("silent-drop" in p for p in probs)
    # duplicate/regressing round index flagged too
    dup = tmp_path / "BENCH_r90b.json"   # also parses round 90
    dup.write_text(good.read_text())
    traj2 = load_trajectory([str(good), str(dup)])
    assert any("strictly increasing" in p
               for p in check_trajectory(traj2))


def test_gate_passes_reference_against_itself():
    ref = _ref()
    assert gate_record(copy.deepcopy(ref), ref) == []


def test_gate_trips_on_injected_2x_slowdown():
    """THE acceptance fixture: double the device-counted kernel steps
    (a 2x slowdown at identical work) and the gate must fail."""
    ref = _ref()
    bad = copy.deepcopy(ref)
    bad["walker"]["kernel_steps"] *= 2
    fails = gate_record(bad, ref)
    assert any("kernel_steps" in f for f in fails), fails


def test_gate_trips_on_efficiency_drop_and_boundary_growth():
    ref = _ref()
    bad = copy.deepcopy(ref)
    bad["walker"]["lane_efficiency"] = \
        ref["walker"]["lane_efficiency"] * 0.5
    assert any("lane_efficiency" in f for f in gate_record(bad, ref))
    bad2 = copy.deepcopy(ref)
    bad2["walker"]["boundaries_rounds_plus_segs"] = \
        ref["walker"]["boundaries_rounds_plus_segs"] * 3
    assert any("boundaries" in f for f in gate_record(bad2, ref))


def test_gate_refuses_workload_drift():
    ref = _ref()
    drifted = copy.deepcopy(ref)
    drifted["walker"]["tasks"] = int(ref["walker"]["tasks"] * 2)
    fails = gate_record(drifted, ref)
    assert len(fails) == 1 and "workload drifted" in fails[0]


def test_gate_trips_on_broken_reconciliation():
    ref = _ref()
    bad = copy.deepcopy(ref)
    bad["walker"]["attribution"]["reconciles"] = False
    assert any("reconcile" in f for f in gate_record(bad, ref))


def test_stream_gate_round16():
    """Round-16 multi-tenant SLO gate: passes the committed reference
    against itself; trips on shed-fraction drift past the absolute
    band, per-class p99 growth past the band, a vanished priority
    class, and a broken completed+shed accounting invariant; and
    SKIPS cleanly for pre-round-16 references/records without the
    stream block."""
    from tools.bench_history import (GATE_SHED_ABS_TOL,
                                     GATE_STREAM_P99_TOL,
                                     gate_stream_record)
    ref = _ref()
    assert isinstance(ref.get("stream"), dict), \
        "committed quick ref must carry the round-16 stream block"
    assert gate_stream_record(copy.deepcopy(ref), ref) == []

    bad = copy.deepcopy(ref)
    bad["stream"]["shed_fraction"] = \
        ref["stream"]["shed_fraction"] + GATE_SHED_ABS_TOL + 0.01
    assert any("shed_fraction" in f
               for f in gate_stream_record(bad, ref))

    bad2 = copy.deepcopy(ref)
    klass = sorted(ref["stream"]["latency_by_class"])[0]
    row = bad2["stream"]["latency_by_class"][klass]
    row["p99_phases"] = (ref["stream"]["latency_by_class"][klass]
                         ["p99_phases"]
                         * (1.0 + GATE_STREAM_P99_TOL) * 2)
    assert any("p99" in f for f in gate_stream_record(bad2, ref))

    bad3 = copy.deepcopy(ref)
    del bad3["stream"]["latency_by_class"][klass]
    assert any("vanished" in f for f in gate_stream_record(bad3, ref))

    bad4 = copy.deepcopy(ref)
    bad4["stream"]["accounting_ok"] = False
    assert any("completed + shed" in f
               for f in gate_stream_record(bad4, ref))

    # pre-round-16 shapes skip the gate instead of failing it
    old_ref = copy.deepcopy(ref)
    del old_ref["stream"]
    assert gate_stream_record(copy.deepcopy(ref), old_ref) == []
    no_cur = copy.deepcopy(ref)
    del no_cur["stream"]
    assert gate_stream_record(no_cur, ref) == []


@pytest.mark.parametrize("inject,expect_rc", [(False, 0), (True, 1)])
def test_gate_cli_level(tmp_path, inject, expect_rc):
    """CLI-level twin of the fixture test: the exact invocation ci.sh
    runs, against a good and an injected-slowdown record file. (The
    --gate path reads JSON only — no engine import, subprocess-cheap.)"""
    rec = _ref()
    if inject:
        rec["walker"]["kernel_steps"] *= 2
    p = tmp_path / "rec.json"
    p.write_text(json.dumps(rec))
    r = subprocess.run(
        [sys.executable, "tools/bench_history.py", "--gate", str(p)],
        capture_output=True, text=True, cwd=REPO, timeout=60)
    assert r.returncode == expect_rc, r.stdout + r.stderr
    assert ("TRIPPED" if inject else "passed") in r.stdout


def test_check_cli_level():
    r = subprocess.run(
        [sys.executable, "tools/bench_history.py", "--check"],
        capture_output=True, text=True, cwd=REPO, timeout=60)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "problem(s)" in r.stdout
