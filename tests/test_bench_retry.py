"""The bench's transient-infra retry (VERDICT r3 #1).

Round 3's official perf artifact recorded 0.0 because ONE transient
tunnel drop ("response body closed") during warmup hit a no-retry path.
These tests pin the fix: transient infrastructure errors retry (bounded)
and are recorded; numerical failures — the NaN guard, gate misses —
still fail immediately.
"""

import sys
from pathlib import Path

import pytest

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import bench  # noqa: E402


def test_transient_classification():
    # the exact round-3 killer
    assert bench.is_transient(
        "INTERNAL: http://127.0.0.1:8083/remote_compile: read body: "
        "response body closed before all bytes were read")
    assert bench.is_transient("UNAVAILABLE: socket closed")
    assert bench.is_transient("ConnectionResetError: peer reset")
    # the framework's own numerical guards must NOT look transient
    assert not bench.is_transient(
        "walker produced 3/1024 non-finite areas (NaN/inf)")
    assert not bench.is_transient("area mismatch vs C baseline: 1.2e-3")
    assert not bench.is_transient(
        "walker did not converge in 64 cycles (12 tasks left)")
    assert not bench.is_transient("walker bag overflowed; raise capacity")


def test_retry_recovers_from_transient(monkeypatch):
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] == 1:
            raise RuntimeError(
                "INTERNAL: remote_compile: response body closed")
        return 42

    attempts = []
    monkeypatch.setattr(bench.time, "sleep", lambda s: None)
    assert bench.with_retry(flaky, attempts) == 42
    assert calls["n"] == 2
    assert len(attempts) == 1 and "remote_compile" in attempts[0]


def test_retry_exhausts_then_raises(monkeypatch):
    def always_down():
        raise RuntimeError("UNAVAILABLE: tunnel down")

    attempts = []
    monkeypatch.setattr(bench.time, "sleep", lambda s: None)
    with pytest.raises(RuntimeError, match="tunnel down"):
        bench.with_retry(always_down, attempts)
    assert len(attempts) == bench.MAX_ATTEMPTS - 1


def test_numerical_failures_never_retry(monkeypatch):
    calls = {"n": 0}

    def nan_guard():
        calls["n"] += 1
        raise FloatingPointError("walker produced 5/1024 non-finite areas")

    monkeypatch.setattr(bench.time, "sleep", lambda s: None)
    with pytest.raises(FloatingPointError):
        bench.with_retry(nan_guard, [])
    assert calls["n"] == 1

    calls["n"] = 0

    def engine_error():
        calls["n"] += 1
        raise RuntimeError("walker did not converge in 64 cycles")

    with pytest.raises(RuntimeError):
        bench.with_retry(engine_error, [])
    assert calls["n"] == 1


def test_injected_transient_still_succeeds(monkeypatch):
    """The VERDICT acceptance criterion: a simulated transient exception
    on the first attempt still yields a valid result."""
    monkeypatch.setenv("PPLS_BENCH_INJECT_TRANSIENT", "1")
    attempts = []
    assert bench.with_retry(lambda: "ok", attempts) == "ok"
    assert attempts and "injected" in attempts[0]


def test_watchdog_catches_hang_and_retries(monkeypatch):
    """VERDICT r4 #5 acceptance: a simulated first-attempt hang produces
    a retried attempt log and a valid result, not a wedged round."""
    monkeypatch.setenv("PPLS_BENCH_INJECT_HANG", "1")
    monkeypatch.setenv("PPLS_BENCH_WATCHDOG_S", "0.2")
    # cap every sleep at 0.5s: the injected hang still outlives the
    # 0.2s watchdog (so it IS caught) and the 10s retry backoff shrinks
    orig_sleep = bench.time.sleep
    monkeypatch.setattr(bench.time, "sleep",
                        lambda s: orig_sleep(min(s, 0.5)))
    attempts = []
    assert bench.with_retry(lambda: "ok", attempts) == "ok"
    assert attempts and "watchdog deadline" in attempts[0]


def test_watchdog_expiry_is_transient():
    assert bench.is_transient(
        "HangTimeout: pipelined timing: watchdog deadline 900s exceeded "
        "(hung device run?)")


def test_watchdog_exhaustion_fails_not_wedges(monkeypatch):
    """A truly wedged device: every attempt times out; the bench must
    raise (-> one failed JSON line) within bounded time."""
    monkeypatch.setenv("PPLS_BENCH_WATCHDOG_S", "0.2")
    monkeypatch.setattr(bench.time, "sleep", lambda s: None)
    import threading
    import time as _time

    def wedged():
        # Event.wait, not time.sleep: sleep is no-op-patched above (the
        # bench retry backoff shares the same module object)
        threading.Event().wait(5)

    attempts = []
    t0 = _time.perf_counter()
    with pytest.raises(bench.HangTimeout, match="watchdog deadline"):
        bench.with_retry(wedged, attempts)
    assert _time.perf_counter() - t0 < 10
    assert len(attempts) == bench.MAX_ATTEMPTS - 1


def test_headroom_metrics_derivation_from_seg_stats():
    """The acceptance derivation (ISSUE r6): kernel_wall_frac /
    kernel_ceiling_frac come from the seg-stats step counter — kernel
    lane-steps = sum(steps column) * lanes, kernel seconds estimated as
    lane-steps / same-day ceiling."""
    import numpy as np

    # a fake seg-stats ring: [steps, live_at_exit, queue_left, refilled]
    ss = np.array([[100, 200, 50, 10],
                   [250, 180, 0, 0],
                   [150, 90, 0, 0]], dtype=np.int64)
    kernel_steps = int(ss[:, 0].sum())          # 500 — the wsteps counter
    lanes = 1 << 14
    wall_s = 2.0
    ceiling = 4.55e9
    rec = bench.headroom_metrics(kernel_steps, lanes, wall_s, ceiling)
    lane_steps = 500 * lanes
    assert rec["kernel_lane_steps"] == lane_steps
    assert rec["kernel_lane_steps_per_sec"] == round(lane_steps / 2.0, 1)
    want = round((lane_steps / 2.0) / ceiling, 4)
    assert rec["kernel_ceiling_frac"] == want
    # the two fracs are one number read two ways (kernel seconds are
    # ESTIMATED via the ceiling): share-of-wall == share-of-ceiling
    assert rec["kernel_wall_frac"] == rec["kernel_ceiling_frac"]
    assert 0.0 < rec["kernel_ceiling_frac"] < 1.0


def test_headroom_metrics_without_ceiling():
    rec = bench.headroom_metrics(500, 128, 1.0, None)
    assert rec["kernel_wall_frac"] is None
    assert rec["kernel_ceiling_frac"] is None
    assert rec["kernel_lane_steps_per_sec"] == round(500 * 128 / 1.0, 1)


def test_watchdog_passes_results_and_errors_through():
    assert bench.with_deadline(lambda: 7, 5.0) == 7
    with pytest.raises(ValueError, match="inner"):
        bench.with_deadline(lambda: (_ for _ in ()).throw(
            ValueError("inner")), 5.0)
