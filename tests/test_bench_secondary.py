"""Secondary-bench foundations (VERDICT r5 #2 + #8).

The 2D and QMC secondaries historically recorded vs_baseline = 0.0 —
no denominator existed. Round 7 gives the 2D bench a C rectangle-bag
twin (backends/csrc/aquad_seq.c 2d mode) and the QMC bench a host/
numpy lattice baseline. These tests pin the parts that must be TRUE
for those denominators to be honest: the C 2D engine makes the exact
same f64 split decisions as the jax engine (cells conserve, areas
agree to summation noise), the ring integrand's closed form is right,
and the numpy lattice baseline computes the device estimator exactly.
"""

import shutil

import numpy as np
import pytest

from ppls_tpu.config import Rule
from ppls_tpu.models.integrands import get_integrand_2d
from ppls_tpu.parallel.cubature import integrate_2d

needs_cc = pytest.mark.skipif(
    not any(shutil.which(c) for c in ("cc", "gcc", "clang")),
    reason="no C compiler for the seq backend")

BOUNDS = (0.0, 1.0, 0.0, 1.0)


@needs_cc
@pytest.mark.parametrize("name,eps", [("gauss2d_peak", 1e-8),
                                      ("gauss2d_ring", 1e-8)])
def test_c_2d_twin_matches_jax_engine(name, eps):
    from ppls_tpu.backends.mpi_backend import run_seq_2d

    entry = get_integrand_2d(name)
    r = integrate_2d(entry.fn, BOUNDS, eps, rule=Rule.TRAPEZOID,
                     chunk=1 << 11, capacity=1 << 20)
    c = run_seq_2d(name, *BOUNDS, eps)
    # same f64 9-point test on both sides: identical split decisions
    assert r.metrics.tasks == c["tasks"], (r.metrics.tasks, c["tasks"])
    assert r.metrics.splits == c["splits"]
    # areas differ only by summation order (C is Neumaier-compensated)
    assert abs(r.area - c["area"]) < 1e-12
    assert c["evals"] == 9 * c["tasks"]


def test_gauss2d_ring_exact_formula():
    # the closed form must match what the adaptive engine converges to
    entry = get_integrand_2d("gauss2d_ring")
    exact = entry.exact(*BOUNDS)
    r = integrate_2d(entry.fn, BOUNDS, 1e-9, rule=Rule.SIMPSON,
                     chunk=1 << 11, capacity=1 << 20, exact=exact)
    assert r.global_error < 1e-7, (r.area, exact)
    # the form is domain-locked: the truncation bound only holds with
    # the ridge >= 4 sigma inside the box
    with pytest.raises(ValueError, match="standard"):
        entry.exact(0.0, 2.0, 0.0, 2.0)


def test_qmc_numpy_baseline_matches_device_estimator():
    """The denominator must compute the SAME estimator: identical
    lattice, identical shifts, identical mean — so the ratio measures
    hardware + implementation, not a different algorithm."""
    import sys
    from pathlib import Path
    sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
    from bench import _qmc_numpy_baseline
    from ppls_tpu.models.genz import GENZ, genz_params
    from ppls_tpu.parallel.qmc import integrate_qmc

    n, shifts = 1 << 16, 4
    a, u = genz_params("oscillatory", 8, seed=0)
    fam = GENZ["oscillatory"]
    r = integrate_qmc(fam.fn, a, u, n_points=n, n_shifts=shifts,
                      fn_name="oscillatory")
    rng = np.random.default_rng(17)        # integrate_qmc default seed
    shift_arr = rng.random((shifts, 8))
    cpu = _qmc_numpy_baseline(n, shift_arr, a, u)
    assert cpu["points"] == n * shifts
    assert abs(cpu["value"] - r.value) < 1e-11, (cpu["value"], r.value)
