"""Checkpoint/resume round-trip tests (SURVEY.md §5: the reference has no
checkpointing; here any round boundary is a resume point)."""

import os

import numpy as np
import pytest

from ppls_tpu.config import REFERENCE_CONFIG
from ppls_tpu.runtime.checkpoint import (
    Checkpointer,
    load_checkpoint,
    resume,
    save_checkpoint,
)
from ppls_tpu.runtime.host_frontier import integrate


def test_save_load_roundtrip(tmp_path):
    path = str(tmp_path / "run.ckpt")
    frontier = np.array([[0.0, 1.0], [1.0, 2.5]])
    from ppls_tpu.utils.metrics import RoundStats, RunMetrics

    m = RunMetrics()
    m.record_round(RoundStats(round_index=0, frontier_width=1, splits=1,
                              leaves=0, padded_width=256))
    save_checkpoint(path, frontier, (1.5, -2e-17), m)
    f2, (s, c), m2, cfg2 = load_checkpoint(path)
    np.testing.assert_array_equal(f2, frontier)
    assert (s, c) == (1.5, -2e-17)
    assert m2.tasks == m.tasks and m2.rounds == m.rounds
    assert m2.per_round[0].frontier_width == 1
    assert cfg2 is None  # no config supplied at save time


def test_interrupt_and_resume_exact(tmp_path):
    path = str(tmp_path / "run.ckpt")
    full = integrate(REFERENCE_CONFIG)

    class Interrupt(Exception):
        pass

    ckpt = Checkpointer(path, config=REFERENCE_CONFIG)

    def crashing_hook(round_index, frontier, acc, metrics):
        ckpt.hook(round_index, frontier, acc, metrics)
        if round_index == 7:
            raise Interrupt  # simulated failure mid-run

    with pytest.raises(Interrupt):
        integrate(REFERENCE_CONFIG, on_round=crashing_hook)

    assert os.path.exists(path)
    res = resume(path, REFERENCE_CONFIG)
    assert res.area == full.area  # bit-identical to the uninterrupted run
    assert res.metrics.tasks == full.metrics.tasks == 6567
    assert res.metrics.rounds == 15


def test_resume_rejects_mismatched_config(tmp_path):
    """A snapshot from one problem must not silently resume another
    (ADVICE r1: stale/blended results with no error)."""
    path = str(tmp_path / "run.ckpt")
    ckpt = Checkpointer(path, config=REFERENCE_CONFIG)
    integrate(REFERENCE_CONFIG, on_round=ckpt.hook)

    with pytest.raises(ValueError, match="different problem"):
        resume(path, REFERENCE_CONFIG.replace(eps=1e-6))
    with pytest.raises(ValueError, match="different problem"):
        resume(path, REFERENCE_CONFIG.replace(integrand="sin"))


def test_resume_finished_run_warns(tmp_path):
    path = str(tmp_path / "run.ckpt")
    ckpt = Checkpointer(path, config=REFERENCE_CONFIG)
    full = integrate(REFERENCE_CONFIG, on_round=ckpt.hook)

    with pytest.warns(UserWarning, match="empty frontier"):
        res = resume(path, REFERENCE_CONFIG)
    assert res.area == full.area
