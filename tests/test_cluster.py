"""Multi-process resilience (round 18, ISSUE 13).

Acceptance surface of the robustness tentpole:

* BOOTSTRAP — real worker subprocesses behind one coordinator; the
  ``jax.distributed.initialize`` code path (the TPU-pod bootstrap)
  exercised for real on this container's CPU coordination service;
  the coordinator-held manifest (process -> devices) joins the
  checkpoint identity so cross-topology resume is deliberate;
* SURVIVING-HOST DISCOVERY — SIGKILL one worker mid-stream; the
  supervisor's new ``host_loss`` arm discovers the surviving topology
  (ping, not a hand-built smaller mesh) and re-deals the lost host's
  outstanding requests through ``mesh.host_strided_redeal``;
  per-request areas BIT-IDENTICAL to the undisturbed run on the
  dyadic-exact workload, zero lost acknowledged requests;
* CROSS-TOPOLOGY RESUME both directions (n->m and m->n) behind the
  ``cluster_resize`` gate, and the corrupt-snapshot-on-ONE-host path
  routing through recovery (replay from the coordinator ledger)
  instead of poisoning the cluster;
* CPU SPILLOVER — queue-overflow victims run as pure-f64 bag rounds
  off-mesh instead of shedding; spillover areas bit-identical to the
  engine path on dyadic workloads; engagement device-counted;
* ``host_loss`` fault kind opt-in: the seeded-schedule pool is
  regression-pinned so existing seeds keep their schedules;
* supervisor resize-backoff fix: a resize racing a slow worker
  teardown retries with the deterministic backoff instead of
  aborting after one attempt.

Worker engines run the pure-f64 streaming mode (``f64_rounds``) over
the PACKAGE-registered dyadic family ``quad_scaled`` — worker
subprocesses cannot see test-module registrations, and dyadic credits
make per-request areas schedule-independent to the bit.
"""

import json
import os

import numpy as np
import pytest

from ppls_tpu.runtime import guard
from ppls_tpu.runtime.cluster import ClusterStreamEngine
from ppls_tpu.runtime.faults import (FAULT_KINDS, PHASE_KINDS,
                                     FaultEvent, FaultInjector,
                                     FaultPlan)
from ppls_tpu.runtime.stream import StreamEngine
from ppls_tpu.obs import Telemetry

# worker sizing: pure-f64 streaming (no Pallas) — fast in a
# subprocess, and the mode the dyadic bit-identity contract is
# stated on
WKW = dict(slots=4, chunk=1 << 10, capacity=1 << 16, lanes=256,
           roots_per_lane=2, refill_slots=2, seg_iters=32,
           min_active_frac=0.05, f64_rounds=2)

THETA6 = [1.0, 1.25, 1.5, 2.0, 0.75, 3.0]
REQS6 = [(t, (0.0, 1.0)) for t in THETA6]
ARR6 = [0, 0, 1, 2, 3, 4]


@pytest.fixture(scope="module")
def base6():
    """Single-engine ground truth for the dyadic workload (the
    undisturbed run every recovery contract compares against)."""
    return StreamEngine("quad_scaled", 1e-9, **WKW).run(
        REQS6, arrival_phase=ARR6)


def _drive(eng, reqs, arr):
    k = eng.next_rid
    while not eng.idle or k < len(reqs):
        while k < len(reqs) and arr[k] <= eng.phase:
            eng.submit(*reqs[k])
            k += 1
        eng.step()
    return eng.result()


def _spying_telemetry():
    tel = Telemetry()
    events = []
    orig = tel.event

    def spy(name, **kw):
        events.append((name, kw))
        return orig(name, **kw)

    tel.event = spy
    return tel, events


# ---------------------------------------------------------------------------
# host_loss fault kind: opt-in, seeded pool regression-pinned
# ---------------------------------------------------------------------------

def test_host_loss_fault_kind_is_opt_in_and_pool_unchanged():
    assert "host_loss" in FAULT_KINDS
    assert "host_loss" not in PHASE_KINDS
    # the same-seed-same-schedule contract: adding host_loss must not
    # move ANY existing seed's schedule (pinned pre-round-18 values)
    assert [e.describe() for e in FaultPlan.seeded(0).events] == [
        {"kind": "chip_loss", "at": 1}, {"kind": "crash", "at": 1},
        {"kind": "hang", "at": 3, "seconds": 1048576.0},
        {"kind": "nan_poison", "at": 8}]
    assert [e.describe() for e in FaultPlan.seeded(3).events] == [
        {"kind": "nan_poison", "at": 1},
        {"kind": "chip_loss", "at": 3},
        {"kind": "nan_poison", "at": 7},
        {"kind": "chip_loss", "at": 9}]
    for seed in range(24):
        kinds = {e.kind for e in FaultPlan.seeded(seed).events}
        assert "host_loss" not in kinds and "sigterm" not in kinds


def test_host_loss_event_without_kill_hook_raises_directly():
    inj = FaultInjector(FaultPlan.from_events(
        [{"kind": "host_loss", "at": 2, "chip": 1}]))
    inj.on_phase_open(1, n_dev=3)          # not its phase: no fire
    with pytest.raises(guard.HostLossError) as ei:
        inj.on_phase_open(2, n_dev=3)
    assert ei.value.process == 1
    assert ei.value.surviving == 2
    assert guard.classify_failure(ei.value) == "host_loss"
    # one-shot: the claimed event never re-fires
    inj.on_phase_open(2, n_dev=3)


def test_host_loss_event_with_kill_hook_calls_it():
    inj = FaultInjector(FaultPlan.from_events(
        [{"kind": "host_loss", "at": 1}]))
    killed = []
    inj.host_kill_fn = killed.append
    inj.on_phase_open(1, n_dev=2)
    assert killed == [None]                # default: coordinator picks
    ev = FaultEvent(kind="host_loss", at=4, chip=0)
    assert ev.describe() == {"kind": "host_loss", "at": 4, "chip": 0}


# ---------------------------------------------------------------------------
# supervisor: resize failures retry with deterministic backoff
# ---------------------------------------------------------------------------

def test_supervisor_resize_backoff_retries_then_recovers():
    """Satellite fix: a resize racing a slow worker teardown (its
    first attempts fail with a transient connection error) must back
    off deterministically and retry, not abort the supervised run."""
    calls = {"run": 0, "resize": 0}
    slept = []

    def loop():
        calls["run"] += 1
        if calls["run"] == 1:
            raise guard.HostLossError(1, 2, detail="test kill")
        return "recovered"

    def resize_fn(exc):
        calls["resize"] += 1
        if calls["resize"] < 3:
            raise ConnectionError(
                "connection reset by worker teardown race")
        return loop

    sup = guard.Supervisor(loop, resize_fn=resize_fn,
                           backoff_base=1.0, backoff_cap=60.0,
                           log=lambda m: None, sleep=slept.append)
    assert sup.run() == "recovered"
    assert calls["resize"] == 3
    assert slept == [1.0, 2.0]             # deterministic exponential
    assert sup.recoveries == [
        ("host_loss", "resize_backoff"),
        ("host_loss", "resize_backoff"),
        ("host_loss", "resize_resume")]


def test_supervisor_resize_backoff_budget_exhausts():
    def loop():
        raise guard.HostLossError(0, 2, detail="test kill")

    def resize_fn(exc):
        raise ConnectionError("connection reset")

    # backoff schedule 10, 20, ...: the second resize failure's 20 s
    # backoff would blow the 15 s budget -> RetryBudgetExhausted
    # (no real sleeping: the first 10 s backoff is a no-op stub and
    # elapsed wall stays ~0)
    sup = guard.Supervisor(
        loop, resize_fn=resize_fn, backoff_base=10.0,
        total_deadline=15.0, log=lambda m: None,
        sleep=lambda s: None)
    with pytest.raises(guard.RetryBudgetExhausted):
        sup.run()
    assert ("host_loss", "resize_backoff") in sup.recoveries


def test_supervisor_fatal_resize_failure_propagates():
    def loop():
        raise guard.HostLossError(0, 2, detail="test kill")

    def resize_fn(exc):
        raise ValueError("store does not fit")   # classified fatal

    sup = guard.Supervisor(loop, resize_fn=resize_fn,
                           log=lambda m: None, sleep=lambda s: None)
    with pytest.raises(ValueError, match="store does not fit"):
        sup.run()
    assert sup.recoveries == []


# ---------------------------------------------------------------------------
# CPU spillover (single engine)
# ---------------------------------------------------------------------------

def test_spillover_engages_under_overload_and_matches_engine():
    """Overload victims run off-mesh instead of shedding; spillover
    areas are BIT-IDENTICAL to the engine path on the dyadic
    workload, and the accounting invariant holds with zero sheds."""
    reqs = [(t, (0.0, 1.0))
            for t in [1.0, 1.25, 1.5, 2.0, 0.75, 3.0, 1.75, 2.5]]
    base = StreamEngine("quad_scaled", 1e-9, **WKW).run(reqs)
    tel, events = _spying_telemetry()
    eng = StreamEngine("quad_scaled", 1e-9, queue_limit=2,
                       spillover=True, spillover_limit=2,
                       telemetry=tel, **WKW)
    res = eng.run(reqs, arrival_phase=[0] * len(reqs))
    assert np.array_equal(res.areas, base.areas)
    assert len(res.completed) == len(reqs)
    assert not res.shed
    s = res.spillover_summary()
    assert s["spillover_completed"] > 0
    assert 0.0 < s["spillover_fraction"] <= 1.0
    assert any(n == "spillover_enqueued" for n, _ in events)
    # device-counted engagement on the registry
    assert tel.registry.value("ppls_spillover_tasks_total") > 0
    assert tel.registry.value("ppls_stream_spillover_total") \
        == s["spillover_completed"]


def test_spillover_deadline_requests_still_shed():
    """Slower capacity cannot bound latency: a deadline-carrying
    overflow victim sheds with the explicit record, as before."""
    eng = StreamEngine("quad_scaled", 1e-9, queue_limit=1,
                       spillover=True, **WKW)
    for t in [1.0, 1.25, 1.5]:
        eng.submit(t, (0.0, 1.0), deadline_phases=2)
    assert len(eng.shed) == 2
    assert all(s.reason == "queue_full" for s in eng.shed)
    eng.drain()


def test_spillover_queue_survives_kill_and_resume(tmp_path):
    """The zero-lost-acks contract covers the spill queue: a crash
    with spillover work queued resumes and completes everything with
    the identical areas."""
    reqs = [(t, (0.0, 1.0))
            for t in [1.0, 1.25, 1.5, 2.0, 0.75, 3.0, 1.75, 2.5]]
    base = StreamEngine("quad_scaled", 1e-9, **WKW).run(reqs)
    ck = str(tmp_path / "spill.ckpt")
    kw = dict(WKW, queue_limit=2, spillover=True, spillover_limit=1)
    eng = StreamEngine("quad_scaled", 1e-9, checkpoint_path=ck,
                       checkpoint_every=1, **kw)
    with pytest.raises(RuntimeError, match="simulated crash"):
        eng.run(reqs, arrival_phase=[0] * len(reqs),
                _crash_after_phases=2)
    eng2 = StreamEngine.resume(ck, "quad_scaled", 1e-9,
                               checkpoint_every=1, **kw)
    assert eng2._spill_queue            # acked work survived the kill
    res = _drive(eng2, reqs, [0] * len(reqs))
    assert np.array_equal(res.areas, base.areas)
    assert len(res.completed) == len(reqs)


def test_spillover_executor_matches_bag_engine():
    from ppls_tpu.backends.spillover import (SpilloverExecutor,
                                             spillover_available)
    from ppls_tpu.parallel.bag_engine import integrate_family
    from ppls_tpu.models.integrands import get_family
    assert spillover_available()
    ex = SpilloverExecutor("quad_scaled", 1e-9, chunk=1 << 10,
                           capacity=1 << 16)
    areas, tasks, wall = ex.run(1.5, (0.0, 1.0))
    ref = integrate_family(get_family("quad_scaled"),
                           np.array([1.5]), (0.0, 1.0), 1e-9,
                           chunk=1 << 10, capacity=1 << 16)
    assert areas == [float(np.asarray(ref.areas)[0])]
    assert tasks == int(ref.metrics.tasks) > 0
    assert ex.tasks_total == tasks


def test_spillover_single_backend_dispatch():
    from ppls_tpu.backends import run_spillover_single
    from ppls_tpu.config import QuadConfig
    cfg = QuadConfig(integrand="sin", a=0.0, b=1.0, eps=1e-6,
                     capacity=1 << 16)
    res = run_spillover_single(cfg)
    assert res.exact is not None
    assert res.global_error < 1e-3
    assert res.metrics.tasks > 0


# ---------------------------------------------------------------------------
# the cluster: bootstrap, parity, manifest identity
# ---------------------------------------------------------------------------

def test_cluster_bootstrap_manifest_and_area_parity(base6, tmp_path):
    tel, events = _spying_telemetry()
    ck = str(tmp_path / "c.ckpt")
    eng = ClusterStreamEngine("quad_scaled", 1e-9, n_processes=2,
                              worker_kw=WKW, telemetry=tel,
                              checkpoint_path=ck)
    try:
        ident = eng.manifest.identity()
        assert ident["processes"] == 2
        assert len(ident["devices"]) == 2
        assert all(d >= 1 for d in ident["devices"])
        assert any(n == "cluster_bootstrap" for n, _ in events)
        res = eng.run(REQS6, arrival_phase=ARR6)
        # per-request areas: bit-identical to the single-process
        # engine (requests are the unit of cross-host state; dyadic
        # credits are schedule-independent to the bit)
        assert np.array_equal(res.areas, base6.areas)
        assert len(res.completed) == len(REQS6)
        # the manifest rides the checkpoint identity
        eng.snapshot()
        from ppls_tpu.runtime.checkpoint import \
            load_family_checkpoint
        with pytest.raises(ValueError, match="different run"):
            load_family_checkpoint(ck, {"engine": "cluster-stream"})
    finally:
        eng.close()


def test_cluster_host_loss_discovery_redeal_bit_identical(base6):
    """THE ROUND-18 ACCEPTANCE, engine level: SIGKILL worker 1
    mid-stream; the supervisor's host_loss arm discovers the
    surviving topology and re-deals through host_strided_redeal;
    areas bit-identical, zero lost acknowledged requests."""
    tel, events = _spying_telemetry()
    inj = FaultInjector(FaultPlan.from_events(
        [{"kind": "host_loss", "at": 2, "chip": 1}]), telemetry=tel)
    eng = ClusterStreamEngine("quad_scaled", 1e-9, n_processes=2,
                              worker_kw=WKW, fault_injector=inj,
                              telemetry=tel)

    def loop():
        return _drive(eng, REQS6, ARR6)

    def resize_fn(exc):
        eng.recover_host_loss(exc)
        return loop

    sup = guard.Supervisor(loop, resize_fn=resize_fn,
                           log=lambda m: None, sleep=lambda s: None)
    try:
        res = sup.run()
        assert sup.recoveries == [("host_loss", "resize_resume")]
        assert eng.manifest.identity()["processes"] == 1
        assert np.array_equal(res.areas, base6.areas)
        # zero lost acks: every submitted rid retired exactly once
        assert sorted(c.rid for c in res.completed) \
            == list(range(len(REQS6)))
        names = [n for n, _ in events]
        assert "host_killed" in names
        assert "host_loss_discovery" in names
        assert "cluster_redeal" in names
        assert eng.redeal_walls and eng.redeal_walls[0] < 30.0
    finally:
        eng.close()


def test_cluster_cross_topology_resume_both_directions(base6,
                                                       tmp_path):
    ck = str(tmp_path / "xt.ckpt")
    eng = ClusterStreamEngine("quad_scaled", 1e-9, n_processes=2,
                              worker_kw=WKW, checkpoint_path=ck,
                              checkpoint_every=1)
    try:
        with pytest.raises(RuntimeError, match="simulated crash"):
            eng.run(REQS6, arrival_phase=ARR6,
                    _crash_after_phases=3)
    finally:
        eng.close()
    # without the flag: the deliberate-resize gate refuses
    with pytest.raises(ValueError, match="different run"):
        ClusterStreamEngine.resume(ck, "quad_scaled", 1e-9,
                                   n_processes=1, worker_kw=WKW)
    # n -> m (2 -> 1): outstanding re-deals, drain completes, areas
    # bit-identical
    e1 = ClusterStreamEngine.resume(ck, "quad_scaled", 1e-9,
                                    n_processes=1, worker_kw=WKW,
                                    cluster_resize=True,
                                    checkpoint_every=1)
    try:
        res = _drive(e1, REQS6, ARR6)
        assert np.array_equal(res.areas, base6.areas)
        assert len(res.completed) == len(REQS6)
        e1.snapshot()
    finally:
        e1.close()
    # m -> n (1 -> 2): the finished ledger carries over intact
    e2 = ClusterStreamEngine.resume(ck, "quad_scaled", 1e-9,
                                    n_processes=2, worker_kw=WKW,
                                    cluster_resize=True)
    try:
        assert len(e2.completed) == len(REQS6)
        assert e2.idle
        assert np.array_equal(e2.result().areas, base6.areas)
    finally:
        e2.close()


def test_cluster_corrupt_worker_snapshot_is_recoverable(base6,
                                                        tmp_path):
    """CheckpointCorruptError on ONE host routes through recovery
    (fresh worker + ledger replay), never poisons the cluster."""
    ck = str(tmp_path / "cw.ckpt")
    eng = ClusterStreamEngine("quad_scaled", 1e-9, n_processes=2,
                              worker_kw=WKW, checkpoint_path=ck,
                              checkpoint_every=1)
    try:
        with pytest.raises(RuntimeError, match="simulated crash"):
            eng.run(REQS6, arrival_phase=ARR6,
                    _crash_after_phases=3)
    finally:
        eng.close()
    p0 = ck + ".p0"
    assert os.path.exists(p0)
    with open(p0, "r+b") as fh:           # truncation: always caught
        fh.truncate(os.path.getsize(p0) // 2)
    tel, events = _spying_telemetry()
    e2 = ClusterStreamEngine.resume(ck, "quad_scaled", 1e-9,
                                    n_processes=2, worker_kw=WKW,
                                    checkpoint_every=1,
                                    telemetry=tel)
    try:
        res = _drive(e2, REQS6, ARR6)
        assert np.array_equal(res.areas, base6.areas)
        assert len(res.completed) == len(REQS6)
        assert any(n == "worker_snapshot_corrupt" for n, _ in events)
    finally:
        e2.close()


def test_cluster_spillover_under_overload_and_host_loss(base6):
    """The degraded-cluster acceptance: overload + one host killed —
    the survivors shed load to the CPU spillover backend (engaged
    share > 0) before shedding any request, and every area still
    matches the undisturbed single-engine run to the bit."""
    inj = FaultInjector(FaultPlan.from_events(
        [{"kind": "host_loss", "at": 1, "chip": 1}]))
    eng = ClusterStreamEngine("quad_scaled", 1e-9, n_processes=2,
                              worker_kw=WKW, fault_injector=inj,
                              queue_limit=2, spillover=True,
                              spillover_limit=2)

    def loop():
        return _drive(eng, REQS6, [0] * len(REQS6))

    def resize_fn(exc):
        eng.recover_host_loss(exc)
        return loop

    sup = guard.Supervisor(loop, resize_fn=resize_fn,
                           log=lambda m: None, sleep=lambda s: None)
    base = StreamEngine("quad_scaled", 1e-9, **WKW).run(REQS6)
    try:
        res = sup.run()
        assert len(res.completed) == len(REQS6)
        assert not res.shed                 # spillover, not rejection
        assert np.array_equal(res.areas, base.areas)
        s = eng.spillover_summary()
        assert s["spillover_completed"] > 0
        assert s["spillover_tasks"] > 0     # device-counted share
        assert 0.0 < s["spillover_fraction"] <= 1.0
    finally:
        eng.close()


def test_jax_distributed_bootstrap_code_path():
    """The TPU-pod bootstrap for real: two workers call
    ``jax.distributed.initialize`` against a shared coordination
    service and each reports the GLOBAL device picture spanning both
    processes — proving the initialize code path works on this
    container (cross-process computations stay host-local; that is
    the documented CPU-backend limitation the census pins)."""
    eng = ClusterStreamEngine("quad_scaled", 1e-9, n_processes=2,
                              worker_kw=dict(WKW),
                              jax_distributed=True)
    try:
        infos = [w.hello.get("jax_distributed")
                 for w in eng._workers]
        assert all(i is not None for i in infos)
        local = [i["local_devices"] for i in infos]
        assert all(i["global_devices"] == sum(local) for i in infos)
        assert sorted(i["process_id"] for i in infos) == [0, 1]
        # and the cluster still serves over it
        res = eng.run(REQS6[:2])
        assert len(res.completed) == 2
    finally:
        eng.close()


# ---------------------------------------------------------------------------
# serve CLI: the full acceptance (kill one HOST under --supervise)
# ---------------------------------------------------------------------------

def _cli_wkw() -> dict:
    """Worker kwargs matching what the serve CLI sends its workers —
    the engine-level crash runs in the CLI restart tests must produce
    per-worker snapshots the CLI-spawned workers can resume, so the
    identity-bearing keys (and ONLY the keys the CLI passes) agree."""
    kw = dict(WKW, theta_block=1)
    for k in ("roots_per_lane", "seg_iters", "min_active_frac"):
        kw.pop(k, None)
    return kw


def _serve_cluster_args(tmp_path, tag, extra):
    ev = str(tmp_path / f"{tag}.events.jsonl")
    return [
        "serve", "--processes", "2", "--f64-rounds", "2",
        "--family", "quad_scaled",
        # DYADIC thetas (the linspace default is not): per-request
        # areas are then schedule-independent to the bit, which is
        # what the kill-vs-undisturbed comparison asserts
        "--theta", "1.0,1.25,1.5,2.0,0.75,3.0",
        "--arrival-rate", "2", "--seed", "0", "--eps", "1e-9",
        "-a", "0.0", "-b", "1.0", "--slots", "4",
        "--chunk", "1024", "--capacity", "65536",
        "--lanes", "256", "--refill-slots", "2",
        "--events", ev] + extra, ev


def test_serve_cli_kill_one_host_under_supervise(tmp_path, capsys):
    """THE ROUND-18 ACCEPTANCE, CLI level: kill one HOST mid-stream
    under ``serve --supervise`` on a 2-process local cluster — the
    run resumes onto the survivor, per-request areas are
    bit-identical to the undisturbed run, zero lost acks, and the
    events timeline validates with per-process spans."""
    from ppls_tpu import __main__ as cli
    from ppls_tpu.utils.artifact_schema import (
        validate_events_text, validate_serve_output_text)

    argv, ev0 = _serve_cluster_args(tmp_path, "base", [])
    assert cli.main(argv) == 0
    out0 = capsys.readouterr().out
    base = {d["rid"]: d["area"] for d in
            map(json.loads, out0.strip().splitlines())
            if "rid" in d and not d.get("summary")}

    argv, ev1 = _serve_cluster_args(
        tmp_path, "kill",
        ["--supervise", "--fault-plan",
         '[{"kind": "host_loss", "at": 2, "chip": 1}]'])
    assert cli.main(argv) == 0
    out1 = capsys.readouterr().out
    lines = [json.loads(ln) for ln in out1.strip().splitlines()]
    summary = lines[-1]
    assert summary["summary"] and summary["supervised"]
    assert summary["completed"] == 6                # zero lost acks
    assert summary["manifest"]["processes"] == 1    # survivor only
    assert [r["kind"] for r in summary["recoveries"]] \
        == ["host_loss"]
    assert summary["redeal_walls_s"]
    got = {d["rid"]: d["area"] for d in lines[:-1]
           if "rid" in d and not d.get("summary")}
    assert got == base                              # bit-identical
    assert validate_serve_output_text(out1) == []
    ev_text = open(ev1).read()
    assert validate_events_text(ev_text) == []
    # the flight recorder's per-process spans tell the story
    recs = [json.loads(ln) for ln in ev_text.splitlines()
            if ln.strip()]
    assert any(d.get("ev") == "span_open"
               and d.get("name") == "process" for d in recs)
    names = {d.get("name") for d in recs if d.get("ev") == "event"}
    assert {"cluster_bootstrap", "host_killed",
            "host_loss_discovery", "cluster_redeal"} <= names


def test_serve_cli_cluster_checkpoint_restart(base6, tmp_path,
                                              capsys):
    """Review fix (round 18): the CLI restart path used to pass
    checkpoint_path twice into ``ClusterStreamEngine.resume`` (once
    positionally, once inside the kwarg dict) and crash with a
    TypeError — the advertised zero-lost-acks restart never worked.
    Crash an engine-level run mid-stream, then restart through the
    REAL serve CLI pointing at its snapshot: every request completes
    with the undisturbed areas."""
    from ppls_tpu import __main__ as cli

    ck = str(tmp_path / "cli.ckpt")
    # theta_block=1 matches the CLI's worker_kw so the snapshot
    # identity agrees between the two spellings
    eng = ClusterStreamEngine("quad_scaled", 1e-9, n_processes=2,
                              worker_kw=_cli_wkw(),
                              checkpoint_path=ck, checkpoint_every=1)
    try:
        with pytest.raises(RuntimeError, match="simulated crash"):
            eng.run(REQS6, arrival_phase=ARR6,
                    _crash_after_phases=3)
    finally:
        eng.close()
    assert os.path.exists(ck)

    argv, _ev = _serve_cluster_args(tmp_path, "restart",
                                    ["--checkpoint", ck])
    assert cli.main(argv) == 0
    lines = [json.loads(ln) for ln in
             capsys.readouterr().out.strip().splitlines()]
    summary = lines[-1]
    assert summary["summary"] and summary["completed"] == 6
    got = {d["rid"]: d["area"] for d in lines[:-1]
           if "rid" in d and not d.get("summary")}
    assert sorted(got) == list(range(6))
    assert np.array_equal(
        np.array([got[r] for r in sorted(got)]), base6.areas)
    assert not os.path.exists(ck)       # drained runs clean up


def test_serve_cli_cluster_sigterm_graceful_restart(base6, tmp_path,
                                                    capsys):
    """Review fix (round 18): the cluster serve path had NO
    GracefulShutdown — a fault-plan SIGTERM killed the coordinator
    with exit 143 (no final snapshot beyond the cadence, no summary
    line). The documented sigterm contract now holds under
    --processes too: flag at the boundary, final snapshot KEPT,
    summary carries "terminated", exit 0, and the same-command
    restart completes with zero lost acks and the undisturbed
    areas."""
    from ppls_tpu import __main__ as cli

    ck = str(tmp_path / "sig.ckpt")
    argv, ev1 = _serve_cluster_args(
        tmp_path, "sig",
        ["--checkpoint", ck, "--checkpoint-every", "1",
         "--fault-plan",
         '[{"kind": "sigterm", "at": 2, "edge": "close"}]'])
    assert cli.main(argv) == 0
    lines1 = [json.loads(ln) for ln in
              capsys.readouterr().out.strip().splitlines()]
    s1 = lines1[-1]
    assert s1["summary"] and s1.get("terminated") == "SIGTERM"
    assert os.path.exists(ck), "graceful shutdown must keep the " \
                               "snapshot (it IS the restart state)"
    argv, ev2 = _serve_cluster_args(tmp_path, "sig2",
                                    ["--checkpoint", ck])
    assert cli.main(argv) == 0
    lines2 = [json.loads(ln) for ln in
              capsys.readouterr().out.strip().splitlines()]
    s2 = lines2[-1]
    assert s2["summary"] and s2["completed"] == 6
    got = {}
    for d in lines1[:-1] + lines2[:-1]:
        if "rid" in d and not d.get("summary"):
            got[d["rid"]] = d["area"]
    assert sorted(got) == list(range(6))
    assert np.array_equal(
        np.array([got[r] for r in sorted(got)]), base6.areas)
    # round 19 (trace linkage under chaos): BOTH lineage segments
    # satisfy the rid-linkage contract — zero orphan spans — and the
    # union of the two timelines carries the restart trail plus one
    # retire per acknowledged rid
    from ppls_tpu.utils.artifact_schema import validate_events_text
    for p in (ev1, ev2):
        assert validate_events_text(open(p).read(),
                                    check_rid_linkage=True) == [], p
    names1, retires = set(), {}
    for p in (ev1, ev2):
        for ln in open(p):
            r = json.loads(ln)
            if r.get("ev") == "event":
                names1.add(r["name"])
                if r["name"] == "retire":
                    retires[r["attrs"]["rid"]] = r["attrs"]
    assert "graceful_shutdown" in names1      # the restart trail...
    assert "cluster_resume" in names1         # ...on the timelines
    assert sorted(retires) == list(range(6))


def test_serve_cli_cluster_watchdog_hang_rebuilds_engine(
        base6, tmp_path, capsys):
    """Review fix (round 18): a --watchdog timeout abandons its
    attempt thread mid-phase, so the supervisor's transient retry
    must NOT re-drive the same live cluster — the stale thread may
    still own the worker sockets, and two drivers desync the
    newline-JSON command/reply pairing. The retry now force-kills
    the stale cluster and rebuilds from the checkpoint (the
    single-process loop's self-resuming shape). Inject a
    forever-hang at a phase boundary: the watchdog fires, and the
    rebuilt engine finishes with zero lost acks and the undisturbed
    areas."""
    from ppls_tpu import __main__ as cli

    ck = str(tmp_path / "hang.ckpt")
    argv, _ev = _serve_cluster_args(
        tmp_path, "hang",
        ["--supervise", "--watchdog", "15",
         "--checkpoint", ck, "--checkpoint-every", "1",
         "--fault-plan", '[{"kind": "hang", "at": 2}]'])
    assert cli.main(argv) == 0
    lines = [json.loads(ln) for ln in
             capsys.readouterr().out.strip().splitlines()]
    summary = lines[-1]
    assert summary["summary"] and summary["supervised"]
    assert summary["completed"] == 6                # zero lost acks
    assert summary["attempts"] >= 2
    assert {"kind": "transient", "action": "backoff_resume"} \
        in summary["recoveries"]
    assert [f["kind"] for f in summary["faults_injected"]] \
        == ["hang"]
    # the rebuilt ledger re-prints from 0 (rid dedupe, the restart
    # contract) — dedupe and compare against the undisturbed run
    got = {d["rid"]: d["area"] for d in lines[:-1]
           if "rid" in d and not d.get("summary")}
    assert sorted(got) == list(range(6))
    assert np.array_equal(
        np.array([got[r] for r in sorted(got)]), base6.areas)


def test_serve_cli_cluster_corrupt_coordinator_starts_clean(
        base6, tmp_path, capsys):
    """Review fix (round 18): a corrupt COORDINATOR snapshot must
    take the per-process sibling snapshots down with it — a fresh
    coordinator re-issues grids from 0, so a stale worker gmap would
    credit ghost retirements against the wrong new request."""
    from ppls_tpu import __main__ as cli

    ck = str(tmp_path / "corrupt.ckpt")
    eng = ClusterStreamEngine("quad_scaled", 1e-9, n_processes=2,
                              worker_kw=_cli_wkw(),
                              checkpoint_path=ck, checkpoint_every=1)
    try:
        with pytest.raises(RuntimeError, match="simulated crash"):
            eng.run(REQS6, arrival_phase=ARR6,
                    _crash_after_phases=3)
    finally:
        eng.close()
    assert os.path.exists(ck + ".p0")
    with open(ck, "r+b") as fh:
        fh.truncate(os.path.getsize(ck) // 2)

    argv, _ev = _serve_cluster_args(tmp_path, "fresh",
                                    ["--checkpoint", ck])
    assert cli.main(argv) == 0
    lines = [json.loads(ln) for ln in
             capsys.readouterr().out.strip().splitlines()]
    summary = lines[-1]
    assert summary["summary"] and summary["completed"] == 6
    got = {d["rid"]: d["area"] for d in lines[:-1]
           if "rid" in d and not d.get("summary")}
    assert sorted(got) == list(range(6))
    assert np.array_equal(
        np.array([got[r] for r in sorted(got)]), base6.areas)


def test_cluster_deal_partial_failure_preserves_survivor_batches(
        base6):
    """Review fix (round 18): a worker death surfacing DURING the
    deal must not strand the batches destined for later, still-alive
    workers — un-sent batches roll back to pending (state no recovery
    arm would otherwise cover) and the run completes on the
    survivor."""
    eng = ClusterStreamEngine("quad_scaled", 1e-9, n_processes=2,
                              worker_kw=WKW)
    try:
        for t in THETA6:
            eng.submit(t, (0.0, 1.0))
        eng.kill_process(0)             # dies before the next deal
        with pytest.raises(guard.HostLossError):
            eng.step()
        # worker 1's batch rolled back instead of vanishing
        assert eng.pending > 0
        assert eng.recover_host_loss() == 1
        res = _drive(eng, [], [])
        assert sorted(c.rid for c in res.completed) \
            == list(range(len(THETA6)))
        assert np.array_equal(res.areas, base6.areas)
    finally:
        eng.close()


def test_spillover_resume_without_backend_refuses(tmp_path):
    """Review fix (round 18): a snapshot carrying a non-empty spill
    queue resumed WITHOUT spillover armed used to hang forever (idle
    never True, every phase a no-op); now it refuses loudly."""
    reqs = [(t, (0.0, 1.0))
            for t in [1.0, 1.25, 1.5, 2.0, 0.75, 3.0, 1.75, 2.5]]
    ck = str(tmp_path / "nospill.ckpt")
    kw = dict(WKW, queue_limit=2, spillover=True, spillover_limit=1)
    eng = StreamEngine("quad_scaled", 1e-9, checkpoint_path=ck,
                       checkpoint_every=1, **kw)
    with pytest.raises(RuntimeError, match="simulated crash"):
        eng.run(reqs, arrival_phase=[0] * len(reqs),
                _crash_after_phases=2)
    with pytest.raises(ValueError, match="spillover"):
        StreamEngine.resume(ck, "quad_scaled", 1e-9,
                            checkpoint_every=1,
                            **dict(WKW, queue_limit=2))


def test_serve_cli_cluster_refuses_tenant_quotas(tmp_path):
    """The cluster coordinator does not implement per-tenant token
    buckets — the flag must refuse loudly, not silently drop."""
    from ppls_tpu import __main__ as cli
    argv, _ev = _serve_cluster_args(
        tmp_path, "quotas",
        ["--tenant-quotas", '{"a": {"rate": 1, "burst": 1}}'])
    with pytest.raises(SystemExit, match="tenant-quotas"):
        cli.main(argv)


def test_serve_cli_cluster_metrics_port_serves_federated(tmp_path):
    """Round 19: the --metrics-port+--processes refusal is LIFTED —
    the cluster serve exposes ONE federated /metrics surface (worker
    registries under process labels + the coordinator's own) whose
    cluster totals reconcile exactly with the summary, scraped LIVE
    over HTTP (PPLS_SERVE_METRICS_HOLD keeps the listener up past
    the summary line so the final sample is race-free)."""
    import re
    import subprocess
    import sys as _sys
    import time
    import urllib.request
    argv, _ev = _serve_cluster_args(tmp_path, "mport",
                                    ["--metrics-port", "0"])
    out_p = tmp_path / "mport.out"
    err_p = tmp_path / "mport.err"
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PPLS_SERVE_METRICS_HOLD="10")
    with open(out_p, "w") as fo, open(err_p, "w") as fe:
        proc = subprocess.Popen(
            [_sys.executable, "-m", "ppls_tpu"] + argv,
            stdout=fo, stderr=fe, env=env)
        try:
            url = None
            deadline = time.monotonic() + 120
            while time.monotonic() < deadline and url is None:
                m = re.search(r"metrics on (http://\S+)",
                              open(err_p).read())
                if m:
                    url = m.group(1)
                elif proc.poll() is not None:
                    raise AssertionError(
                        f"serve exited rc={proc.returncode} before "
                        f"announcing metrics: {open(err_p).read()}")
                else:
                    time.sleep(0.2)
            # scrape DURING the run until the summary lands, then one
            # final post-drain sample inside the hold window
            summary = None
            expo = ""
            while time.monotonic() < deadline and summary is None:
                with urllib.request.urlopen(url, timeout=10) as r:
                    expo = r.read().decode()
                for ln in open(out_p).read().splitlines():
                    if ln.strip().startswith("{"):
                        rec = json.loads(ln)
                        if rec.get("summary"):
                            summary = rec
                time.sleep(0.1)
            assert summary is not None, "no summary within budget"
            with urllib.request.urlopen(url, timeout=10) as r:
                expo = r.read().decode()
        finally:
            proc.kill()
            proc.wait(timeout=30)
    assert summary["metrics_url"] == url
    # the reconciliation invariant on the final scrape: coordinator-
    # merged retired counter == sum over worker processes (+0
    # spillover here) == summary.completed
    vals = {}
    for ln in expo.splitlines():
        m = re.match(r'ppls_stream_retired_total\{process="([^"]+)"\}'
                     r' (\S+)', ln)
        if m:
            vals[m.group(1)] = float(m.group(2))
    workers = sum(v for k, v in vals.items() if k != "coordinator")
    assert vals.get("coordinator") == summary["completed"] == 6
    assert workers == summary["completed"]


def test_serve_cli_cluster_refuses_bad_process_counts(tmp_path):
    """Review fix (round 18): --processes 0 used to fall through the
    truthiness check into the SINGLE-process serve path (a sweep
    script got a silently different engine for P=0) and negative
    counts surfaced as raw tracebacks — both are clean usage errors
    now."""
    from ppls_tpu import __main__ as cli
    for bad in ("0", "-1"):
        argv, _ev = _serve_cluster_args(tmp_path, f"p{bad}",
                                        ["--processes", bad])
        with pytest.raises(SystemExit, match="processes"):
            cli.main(argv)


def test_spillover_idle_tail_phases_checkpoint(tmp_path):
    """Review fix (round 18): the idle branch of ``step()`` (device
    drained, spill queue still busy) used to skip the checkpoint
    cadence entirely — a kill mid-tail replayed every completed bag
    round and re-printed its rids. Idle phases now honor
    checkpoint_every like every other phase."""
    # 12 dyadic thetas: pending holds queue_limit=2, the spill queue
    # caps at 8, the rest shed — by the time the two admitted
    # requests retire (~6 phases, one spill batch each) the device is
    # drained with spillover work still queued: the tail state
    reqs = [(t, (0.0, 1.0))
            for t in [1.0, 1.25, 1.5, 2.0, 0.75, 3.0, 1.75, 2.5,
                      0.5, 1.125, 2.25, 2.75]]
    ck = str(tmp_path / "tail.ckpt")
    kw = dict(WKW, queue_limit=2, spillover=True, spillover_limit=1)
    eng = StreamEngine("quad_scaled", 1e-9, checkpoint_path=ck,
                       checkpoint_every=1, **kw)
    for r in reqs:
        eng.submit(*r)
    for _ in range(64):        # drive to the drained-tail state
        if eng._count == 0 and not eng.pending and eng._spill_queue:
            break
        eng.step()
    qlen = len(eng._spill_queue)
    assert qlen >= 1
    eng.step()                 # one IDLE phase: spillover batch only
    assert len(eng._spill_queue) == qlen - 1
    eng2 = StreamEngine.resume(ck, "quad_scaled", 1e-9,
                               checkpoint_every=1, **kw)
    # the idle phase checkpointed: the resumed queue matches the live
    # one instead of replaying the whole tail
    assert len(eng2._spill_queue) == len(eng._spill_queue)
    assert eng2.phase == eng.phase


def test_spillover_engagement_totals_survive_kill_and_resume(
        tmp_path):
    """Review fix (round 18): the single-process snapshot persisted
    the spill QUEUE but not the executor's engagement totals, so
    ``ppls_spillover_{requests,tasks}_total`` restarted at zero after
    every kill — the device-counted engagement metric the bench gate
    keys on underreported all pre-crash work."""
    reqs = [(t, (0.0, 1.0))
            for t in [1.0, 1.25, 1.5, 2.0, 0.75, 3.0, 1.75, 2.5]]
    ck = str(tmp_path / "spilltot.ckpt")
    kw = dict(WKW, queue_limit=2, spillover=True, spillover_limit=1)
    eng = StreamEngine("quad_scaled", 1e-9, checkpoint_path=ck,
                       checkpoint_every=1, **kw)
    with pytest.raises(RuntimeError, match="simulated crash"):
        eng.run(reqs, arrival_phase=[0] * len(reqs),
                _crash_after_phases=3)
    pre_req = eng._spill.requests_total
    pre_tasks = eng._spill.tasks_total
    assert pre_req > 0 and pre_tasks > 0    # spillover engaged
    eng2 = StreamEngine.resume(ck, "quad_scaled", 1e-9,
                               checkpoint_every=1, **kw)
    # the snapshot may trail the crash by at most the final phase's
    # batch; it must never restart at zero
    assert 0 < eng2._spill.requests_total <= pre_req
    assert 0 < eng2._spill.tasks_total <= pre_tasks
    # the registry exposition replays the restored totals too
    assert eng2.telemetry.registry.value(
        "ppls_spillover_requests_total") \
        == eng2._spill.requests_total
    restored = eng2._spill.tasks_total
    res = _drive(eng2, reqs, [0] * len(reqs))
    assert len(res.completed) == len(reqs)
    assert eng2._spill.tasks_total > restored   # kept accumulating


def test_spillover_queue_is_bounded_then_sheds():
    """Review fix (round 18): the spill queue is capped (8x
    spillover_limit) — sustained deadline-less overload beyond it
    sheds with an explicit record instead of re-growing the unbounded
    backlog queue_limit exists to prevent."""
    eng = StreamEngine("quad_scaled", 1e-9, queue_limit=1,
                       spillover=True, spillover_limit=1, **WKW)
    for k in range(12):
        eng.submit(1.0 + 0.25 * k, (0.0, 1.0))
    assert len(eng._spill_queue) == 8          # the cap
    assert len(eng.shed) == 3                  # 12 - 1 pending - 8
    assert all(s.reason == "spill_queue_full" for s in eng.shed)
    res = _drive(eng, [], [])
    assert len(res.completed) == 9
    assert not any(c.failed for c in res.completed)


def test_spillover_quarantines_poisoned_request():
    """Review fix (round 18): the NaN-quarantine contract covers the
    spillover path — a poisoned spilled request retires as a FAILED
    record while healthy concurrent work (engine and spillover alike)
    completes, never an engine-wide FloatingPointError."""
    eng = StreamEngine("quad_scaled", 1e-9, queue_limit=1,
                       spillover=True, spillover_limit=2,
                       quarantine=True, **WKW)
    eng.submit(2.0, (0.0, 1.0))                # engine path
    eng.submit(3.0, (0.0, 1.0))                # healthy spill
    eng.submit(1.5, (0.0, 1.0))                # to be poisoned
    assert len(eng._spill_queue) == 2
    # the round-14 injector shape: corrupt POST-validation, so the
    # engine genuinely computes with the non-finite payload
    eng._spill_queue[1].theta = float("nan")
    res = _drive(eng, [], [])
    assert len(res.completed) == 3
    by_rid = {c.rid: c for c in res.completed}
    assert by_rid[2].failed and by_rid[2].failure == "nan"
    assert by_rid[2].spillover
    assert not by_rid[0].failed and not by_rid[1].failed


def test_cluster_worker_deadline_sheds_reach_coordinator():
    """Review fix (round 18): a worker-side deadline shed is a
    TERMINAL outcome the coordinator must adopt — otherwise the
    ledger entry stays 'dealt' forever and the cluster never goes
    idle. Also pins the coordinator's mirrored pre-rid validation."""
    eng = ClusterStreamEngine("quad_scaled", 1e-9, n_processes=1,
                              worker_kw=WKW)
    try:
        with pytest.raises(ValueError, match="deadline_phases"):
            eng.submit(1.0, (0.0, 1.0), deadline_phases=0)
        with pytest.raises(ValueError, match="theta_block"):
            eng.submit([1.0, 2.0], (0.0, 1.0))
        for t in THETA6:
            eng.submit(t, (0.0, 1.0), deadline_phases=1)
        for _ in range(60):
            eng.step()
            if eng.idle:
                break
        assert eng.idle                    # terminates, never spins
        res = eng.result()
        assert len(res.completed) + len(res.shed) == len(THETA6)
        # every acknowledged rid ends in exactly one terminal state
        rids = sorted([c.rid for c in res.completed]
                      + [s.rid for s in res.shed])
        assert rids == list(range(len(THETA6)))
    finally:
        eng.close()
