"""2D adaptive tensor-product cubature tests (BASELINE config #4)."""

import numpy as np
import pytest

from ppls_tpu.config import Rule
from ppls_tpu.models.integrands import get_integrand_2d
from ppls_tpu.parallel.cubature import integrate_2d


def _run(name, bounds, eps, **kw):
    entry = get_integrand_2d(name)
    exact = entry.exact(*bounds) if entry.exact else None
    return integrate_2d(entry.fn, bounds, eps, exact=exact, **kw)


def test_smooth_separable_converges():
    r = _run("cos_prod", (0.0, 1.0, 0.0, 2.0), 1e-8)
    assert r.global_error < 1e-8, r.global_error
    assert r.metrics.leaves == r.metrics.tasks - r.metrics.splits


def test_polynomial_exact_under_simpson():
    # x^2 y + x y^2 is cubic per axis: tensor-product Simpson integrates
    # it exactly — the first cell accepts with err ~ rounding.
    r = _run("poly_xy", (0.0, 1.0, 0.0, 1.0), 1e-9)
    assert r.global_error < 1e-12, r.global_error
    assert r.metrics.tasks <= 5


def test_trapezoid_rule_converges():
    # The reference-semantics twin: order-2, so the per-cell tolerance
    # leaves a larger (but bounded) global error: ~leaves * eps.
    r = _run("cos_prod", (0.0, 1.0, 0.0, 2.0), 1e-8, rule=Rule.TRAPEZOID)
    assert r.global_error < 5e-5, r.global_error
    r2 = _run("cos_prod", (0.0, 1.0, 0.0, 2.0), 1e-6, rule=Rule.TRAPEZOID)
    # order-2 convergence: tightening eps 100x cuts global error
    assert r.global_error < r2.global_error / 10.0


def test_peaked_gaussian_deep_refinement():
    # BASELINE config #4's stress case: refinement clusters around the
    # peak; Simpson at per-cell eps=1e-8 meets ~1e-8 global error.
    r = _run("gauss2d_peak", (0.0, 1.0, 0.0, 1.0), 1e-8,
             capacity=1 << 21)
    assert r.global_error < 1e-7, r.global_error
    assert r.metrics.max_depth >= 3
    assert r.metrics.tasks > 100


def test_anisotropic_bounds():
    # Non-square domain, off-center peak: closed form still matched.
    r = _run("gauss2d_peak", (0.25, 1.5, -0.5, 0.75), 1e-8,
             capacity=1 << 21)
    assert r.global_error < 1e-7, r.global_error


def test_deterministic():
    a1 = _run("gauss2d_peak", (0.0, 1.0, 0.0, 1.0), 1e-6).area
    a2 = _run("gauss2d_peak", (0.0, 1.0, 0.0, 1.0), 1e-6).area
    assert a1 == a2


def test_overflow_detected():
    with pytest.raises(RuntimeError, match="overflow"):
        _run("gauss2d_peak", (0.0, 1.0, 0.0, 1.0), 1e-12,
             chunk=64, capacity=128, rule=Rule.TRAPEZOID)


def test_sharded_2d_conserves_cells_and_area():
    # Split decisions are placement-independent: cell totals match the
    # single-chip engine exactly, the area to summation-order noise.
    from ppls_tpu.config import Rule
    from ppls_tpu.parallel.cubature import integrate_2d_sharded
    from ppls_tpu.parallel.mesh import make_mesh

    entry = get_integrand_2d("gauss2d_peak")
    bounds = (0.0, 1.0, 0.0, 1.0)
    eps = 1e-9
    kw = dict(rule=Rule.TRAPEZOID)
    s = integrate_2d_sharded(entry.fn, bounds, eps, chunk=1 << 8,
                             capacity=1 << 15, mesh=make_mesh(8),
                             exact=entry.exact(*bounds), **kw)
    b = integrate_2d(entry.fn, bounds, eps, chunk=1 << 10,
                     capacity=1 << 17, exact=entry.exact(*bounds), **kw)
    assert s.metrics.tasks == b.metrics.tasks
    assert abs(s.area - b.area) < 1e-12
    assert s.metrics.n_chips == 8
    assert sum(s.metrics.tasks_per_chip) == s.metrics.tasks
    # clustered refinement spreads across the mesh
    per = np.asarray(s.metrics.tasks_per_chip, dtype=np.float64)
    assert per.min() > 0


def test_sharded_2d_kill_and_resume_bit_identical(tmp_path):
    """VERDICT r4 #4: leg-boundary checkpointing for the sharded 2D
    cubature engine; kill-and-resume reproduces the uninterrupted area
    bit-for-bit on the virtual 8-mesh."""
    import pytest

    from ppls_tpu.models.integrands import get_integrand_2d
    from ppls_tpu.parallel.cubature import (integrate_2d_sharded,
                                            resume_2d_sharded)
    from ppls_tpu.parallel.mesh import make_mesh

    entry = get_integrand_2d("gauss2d_peak")
    bounds = (0.0, 1.0, 0.0, 1.0)
    eps = 1e-7
    kw = dict(chunk=1 << 8, capacity=1 << 15, mesh=make_mesh(8),
              rule=Rule.TRAPEZOID)
    base = integrate_2d_sharded(entry.fn, bounds, eps, **kw)
    path = str(tmp_path / "s2d.ckpt")
    with pytest.raises(RuntimeError, match="simulated crash"):
        integrate_2d_sharded(entry.fn, bounds, eps,
                             checkpoint_path=path, checkpoint_every=3,
                             _crash_after_legs=2, **kw)
    res = resume_2d_sharded(path, entry.fn, bounds, eps,
                            checkpoint_every=3, **kw)
    assert res.area == base.area                          # bit-for-bit
    assert res.metrics.tasks == base.metrics.tasks
    assert res.metrics.tasks_per_chip == base.metrics.tasks_per_chip
    import os
    assert not os.path.exists(path)


def test_sharded_2d_resume_rejects_mismatched_identity(tmp_path):
    import pytest

    from ppls_tpu.models.integrands import get_integrand_2d
    from ppls_tpu.parallel.cubature import (integrate_2d_sharded,
                                            resume_2d_sharded)
    from ppls_tpu.parallel.mesh import make_mesh

    entry = get_integrand_2d("gauss2d_peak")
    bounds = (0.0, 1.0, 0.0, 1.0)
    kw = dict(chunk=1 << 8, capacity=1 << 15, mesh=make_mesh(8),
              rule=Rule.TRAPEZOID)
    path = str(tmp_path / "s2d.ckpt")
    with pytest.raises(RuntimeError, match="simulated crash"):
        integrate_2d_sharded(entry.fn, bounds, 1e-7,
                             checkpoint_path=path, checkpoint_every=2,
                             _crash_after_legs=1, **kw)
    with pytest.raises(ValueError, match="different run"):
        resume_2d_sharded(path, entry.fn, bounds, 1e-8, **kw)
