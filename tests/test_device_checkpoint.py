"""Kill-and-resume checkpointing for the device-resident engines.

The contract (VERDICT r2 #9): a checkpointed run that dies mid-flight
and resumes from its last snapshot produces the SAME result as an
uninterrupted run — bit-for-bit on this (real-f64) test platform,
because leg boundaries only bound the iteration/cycle count and change
no per-chunk computation.
"""

import numpy as np
import pytest

from ppls_tpu.models.integrands import get_family, get_family_ds
from ppls_tpu.parallel.bag_engine import integrate_family, resume_family
from ppls_tpu.parallel.walker import (integrate_family_walker,
                                      resume_family_walker)

F = get_family("sin_recip_scaled")
F_DS = get_family_ds("sin_recip_scaled")
THETA = 1.0 + np.arange(4) / 4.0
BOUNDS = (1e-2, 1.0)
EPS = 1e-7
BAG_KW = dict(chunk=1 << 8, capacity=1 << 16)


def test_bag_kill_and_resume_bit_identical(tmp_path):
    base = integrate_family(F, THETA, BOUNDS, EPS, **BAG_KW)
    path = str(tmp_path / "bag.ckpt")
    with pytest.raises(RuntimeError, match="simulated crash"):
        integrate_family(F, THETA, BOUNDS, EPS, **BAG_KW,
                         checkpoint_path=path, checkpoint_every=8,
                         _crash_after_legs=2)
    res = resume_family(path, F, THETA, BOUNDS, EPS, **BAG_KW,
                        checkpoint_every=8)
    assert np.array_equal(res.areas, base.areas)          # bit-for-bit
    assert res.metrics.tasks == base.metrics.tasks
    assert res.metrics.splits == base.metrics.splits
    assert res.metrics.max_depth == base.metrics.max_depth


def test_bag_checkpointed_uninterrupted_matches(tmp_path):
    # Checkpointing overhead must not change the math even when no crash
    # happens.
    base = integrate_family(F, THETA, BOUNDS, EPS, **BAG_KW)
    res = integrate_family(F, THETA, BOUNDS, EPS, **BAG_KW,
                           checkpoint_path=str(tmp_path / "c.ckpt"),
                           checkpoint_every=16)
    assert np.array_equal(res.areas, base.areas)
    assert res.metrics.tasks == base.metrics.tasks


def test_completed_run_clears_snapshot(tmp_path):
    # A finished run must delete its last mid-run snapshot (ADVICE r3):
    # otherwise re-invoking the identical command finds the file and
    # silently resumes, replaying only the tail of the previous run.
    import os
    path = str(tmp_path / "done.ckpt")
    res = integrate_family(F, THETA, BOUNDS, EPS, **BAG_KW,
                           checkpoint_path=path, checkpoint_every=8)
    assert res.metrics.tasks > 0
    assert not os.path.exists(path)

    wpath = str(tmp_path / "done_w.ckpt")
    wres = integrate_family_walker(F, F_DS, THETA, BOUNDS, EPS, **WALK_KW,
                                   checkpoint_path=wpath,
                                   checkpoint_every=2)
    assert wres.metrics.tasks > 0
    assert not os.path.exists(wpath)


def test_bag_resume_rejects_mismatched_identity(tmp_path):
    path = str(tmp_path / "bag.ckpt")
    with pytest.raises(RuntimeError, match="simulated crash"):
        integrate_family(F, THETA, BOUNDS, EPS, **BAG_KW,
                         checkpoint_path=path, checkpoint_every=8,
                         _crash_after_legs=1)
    with pytest.raises(ValueError, match="different run"):
        resume_family(path, F, THETA, BOUNDS, 1e-6, **BAG_KW)


WALK_KW = dict(capacity=1 << 16, lanes=256, roots_per_lane=1,
               seg_iters=8, max_segments=1, max_cycles=256,
               min_active_frac=0.05)


def test_walker_kill_and_resume_bit_identical(tmp_path):
    # max_segments=1 forces many cycles, so there are real cycle
    # boundaries to snapshot at.
    base = integrate_family_walker(F, F_DS, THETA, BOUNDS, EPS, **WALK_KW)
    path = str(tmp_path / "walker.ckpt")
    with pytest.raises(RuntimeError, match="simulated crash"):
        integrate_family_walker(F, F_DS, THETA, BOUNDS, EPS, **WALK_KW,
                                checkpoint_path=path, checkpoint_every=2,
                                _crash_after_legs=2)
    res = resume_family_walker(path, F, F_DS, THETA, BOUNDS, EPS,
                               **WALK_KW, checkpoint_every=2)
    assert np.array_equal(res.areas, base.areas)          # bit-for-bit
    assert res.metrics.tasks == base.metrics.tasks
    assert res.cycles == base.cycles


def test_walker_kernel_refill_kill_and_resume_bit_identical(tmp_path):
    # The in-kernel-refill engine checkpoints at the same cycle
    # boundaries (all lane/bank state is folded back into the bag by
    # expand-pending), so kill-and-resume must stay bit-identical there
    # too — the flagship bench config's resume path.
    kw = dict(WALK_KW, refill_slots=1)      # roots_per_lane=1 cap
    base = integrate_family_walker(F, F_DS, THETA, BOUNDS, EPS, **kw)
    path = str(tmp_path / "walker_rf.ckpt")
    with pytest.raises(RuntimeError, match="simulated crash"):
        integrate_family_walker(F, F_DS, THETA, BOUNDS, EPS, **kw,
                                checkpoint_path=path, checkpoint_every=2,
                                _crash_after_legs=2)
    res = resume_family_walker(path, F, F_DS, THETA, BOUNDS, EPS,
                               **kw, checkpoint_every=2)
    assert np.array_equal(res.areas, base.areas)          # bit-for-bit
    assert res.metrics.tasks == base.metrics.tasks
    assert res.cycles == base.cycles
