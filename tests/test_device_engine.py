"""Fully-on-device lax.while_loop integrator tests."""

import jax.numpy as jnp
import numpy as np
import pytest

from ppls_tpu import QuadConfig, device_integrate, integrate
from ppls_tpu.config import REFERENCE_CONFIG, Rule
from ppls_tpu.parallel.device_engine import compact_children


def test_compact_children_dense_prefix():
    l = jnp.asarray([0.0, 1.0, 2.0, 3.0])
    r = jnp.asarray([1.0, 2.0, 3.0, 4.0])
    split = jnp.asarray([True, False, True, False])
    nl, nr, active, n = compact_children(l, r, split, capacity=8)
    assert int(n) == 4
    np.testing.assert_allclose(np.asarray(nl[:4]), [0.0, 0.5, 2.0, 2.5])
    np.testing.assert_allclose(np.asarray(nr[:4]), [0.5, 1.0, 2.5, 3.0])
    assert np.asarray(active).tolist() == [True] * 4 + [False] * 4


def test_compact_children_overflow_drops():
    l = jnp.zeros(4)
    r = jnp.ones(4)
    split = jnp.ones(4, dtype=bool)
    nl, nr, active, n = compact_children(l, r, split, capacity=4)
    assert int(n) == 8  # caller detects overflow via n > capacity
    assert np.asarray(active).sum() == 4  # mask capped at capacity


def test_device_matches_host_golden():
    cfg = REFERENCE_CONFIG.replace(capacity=4096)
    dev = device_integrate(cfg)
    host = integrate(cfg)
    assert f"{dev.area:.6f}" == "7583461.801486"
    assert dev.metrics.tasks == host.metrics.tasks == 6567
    assert dev.metrics.splits == 3283
    assert dev.metrics.rounds == 15
    # identical breadth-first ordering => bit-identical leaf sums per round,
    # same Kahan accumulation => bit-identical area
    assert dev.area == host.area


def test_device_overflow_falls_back_to_host():
    # Capacity 64 < peak frontier 1642: must overflow and fall back.
    cfg = REFERENCE_CONFIG.replace(capacity=64)
    res = device_integrate(cfg, fallback=True)
    assert f"{res.area:.6f}" == "7583461.801486"
    assert res.metrics.tasks == 6567


def test_device_overflow_raises_without_fallback():
    cfg = REFERENCE_CONFIG.replace(capacity=64)
    with pytest.raises(RuntimeError, match="overflow"):
        device_integrate(cfg, fallback=False)


def test_device_simpson_sin():
    cfg = QuadConfig(integrand="sin", a=0.0, b=1.0, eps=1e-8,
                     rule=Rule.SIMPSON, capacity=1024)
    res = device_integrate(cfg)
    assert res.global_error < 1e-7
