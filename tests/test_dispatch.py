"""Heterogeneous-shape dispatcher (runtime/dispatch.py, round 21).

Acceptance surface of the multi-engine serving tier:

* canonicalization: requests quantize onto the engine-key lattice
  (eps decimal band, rule, pow2 theta bucket) with every malformed
  shape rejected BEFORE pool state is consumed;
* ZERO RECOMPILES: a mixed-shape stream (two eps bands, a simpson
  request, a theta-block batch) drains with ``ppls_recompiles_total``
  == 0 — every shape change is a pool route, never a recompile — and
  the per-engine decomposition reconciles with the pool ledger;
* park/unpark bit-identity: an LRU-capped pool (``max_engines`` below
  the live key count) produces per-request areas BIT-IDENTICAL to the
  uncapped pool — parking is a checkpoint/resume round-trip, not an
  approximation;
* kill-and-resume: a mid-stream crash resumes from the coordinated
  cut (per-engine files + manifest-last) and the continued run is
  bit-identical to the undisturbed one, with the event timelines
  passing the rid-linkage contract;
* refusal: a manifest from a different pool configuration, or a cut
  blended with another pool's engine snapshot, refuses to resume.
"""

import glob
import os
import shutil

import numpy as np
import pytest

from ppls_tpu.config import Rule
from ppls_tpu.runtime.dispatch import (MAX_THETA_BUCKET,
                                       EngineDispatcher, EngineKey,
                                       canonical_key)

BOUNDS = (1e-2, 1.0)
# interpret-friendly engine sizing (the stream test config)
EKW = dict(chunk=1 << 10, capacity=1 << 16, lanes=256,
           roots_per_lane=2, refill_slots=2, seg_iters=32,
           min_active_frac=0.05)
DKW = dict(slots=8, max_engines=4, default_eps=1e-6,
           engine_kw=EKW)

# the mixed-shape workload: four engine keys across eight requests
MIXED = [
    (1.0, BOUNDS, {}),
    (1.05, BOUNDS, {"eps": 1e-7}),
    (1.1, BOUNDS, {"rule": "simpson"}),
    ((1.15, 1.2), BOUNDS, {}),
    (1.25, BOUNDS, {}),
    (1.3, BOUNDS, {"eps": 1e-7}),
    (1.35, BOUNDS, {"rule": "simpson"}),
    ((1.4, 1.45), BOUNDS, {}),
]
ARR = [0, 0, 0, 1, 1, 2, 2, 3]
MIXED_KEYS = {"e-6:trapezoid:t1", "e-7:trapezoid:t1",
              "e-6:simpson:t1", "e-6:trapezoid:t2"}


def _drive_to_drain(disp, reqs, arr):
    """Resume driver: submit the unconsumed arrival-schedule suffix
    (grids are submission-ordered, so next_rid is the cursor) and
    step to idle — the same loop shape the serve CLI runs."""
    k = disp.next_rid
    while not disp.idle or k < len(reqs):
        while k < len(reqs) and arr[k] <= disp.phase:
            r = reqs[k]
            disp.submit(r[0], r[1], **(r[2] if len(r) > 2 else {}))
            k += 1
        disp.step()
    return disp.result()


def test_canonical_key_lattice():
    k = canonical_key(1e-7, "trapezoid", 1.0)
    assert k == EngineKey(-7, "trapezoid", 1)
    assert str(k) == "e-7:trapezoid:t1"
    assert EngineKey.parse(str(k)) == k
    assert k.eps == 1e-7
    # eps quantizes to the nearest decimal band
    assert canonical_key(2e-7, "trapezoid", 1.0).eps_band == -7
    assert canonical_key(9e-7, "trapezoid", 1.0).eps_band == -6
    # theta batches bucket to the next power of two
    assert canonical_key(1e-6, "trapezoid", (1.0, 1.1)).theta_block \
        == 2
    assert canonical_key(1e-6, "trapezoid",
                         (1.0, 1.1, 1.2)).theta_block == 4
    assert canonical_key(
        1e-6, "trapezoid",
        tuple(1.0 + i / 64 for i in range(MAX_THETA_BUCKET))
    ).theta_block == MAX_THETA_BUCKET
    # rule accepts Rule members and sloppy strings alike
    assert canonical_key(1e-6, Rule.SIMPSON, 1.0).rule == "simpson"
    assert canonical_key(1e-6, " Simpson ", 1.0).rule == "simpson"


@pytest.mark.parametrize("eps,rule,theta,match", [
    (0.0, "trapezoid", 1.0, "finite and > 0"),
    (float("nan"), "trapezoid", 1.0, "finite and > 0"),
    ("x", "trapezoid", 1.0, "must be a number"),
    (1e-20, "trapezoid", 1.0, "outside the dispatchable range"),
    (1.0, "trapezoid", 1.0, "outside the dispatchable range"),
    (1e-6, "simpsonish", 1.0, "unknown rule"),
    (1e-6, "trapezoid", (), "empty theta batch"),
    (1e-6, "trapezoid",
     tuple(range(MAX_THETA_BUCKET + 1)), "bucket cap"),
    (1e-6, "simpson", (1.0, 1.1), "TRAPEZOID"),
])
def test_canonical_key_rejects(eps, rule, theta, match):
    with pytest.raises(ValueError, match=match):
        canonical_key(eps, rule, theta)


def test_dispatch_mixed_shapes_zero_recompiles():
    disp = EngineDispatcher("sin_recip_scaled", **DKW)
    res = disp.run(MIXED, arrival_phase=ARR)
    assert len(res.completed) == len(MIXED)
    assert np.all(np.isfinite(res.areas))
    # THE invariant this tier exists for: mixed shapes, zero recompiles
    assert disp.recompiles() == 0
    summary = disp.engines_summary()
    assert set(summary) == MIXED_KEYS
    assert all(e["state"] == "live" for e in summary.values())
    # per-engine decomposition reconciles with the pool ledger
    assert sum(e["completed"] for e in summary.values()) == len(MIXED)
    assert sum(int(e["phases"]) for e in summary.values()) >= 4
    # pool determinism: the identical workload replays bit-identically
    res2 = EngineDispatcher("sin_recip_scaled", **DKW).run(
        MIXED, arrival_phase=ARR)
    assert np.array_equal(res.areas, res2.areas)


def test_dispatch_park_unpark_determinism_and_parity():
    base = EngineDispatcher("sin_recip_scaled", **DKW).run(
        MIXED, arrival_phase=ARR)
    capped = EngineDispatcher("sin_recip_scaled",
                              **dict(DKW, max_engines=2))
    res = capped.run(MIXED, arrival_phase=ARR)
    # the cap forced real LRU parks (4 keys through 2 slots)
    parks = sum(child.value for _, child in capped._c_park.items())
    assert parks >= 2, "max_engines=2 never parked an engine"
    assert capped.recompiles() == 0
    assert len(res.completed) == len(MIXED)
    # parking changes WHEN requests reach their engine, so the
    # adaptive walk may legitimately stop at a different eps-valid
    # grid — parity with the uncapped pool is at tolerance scale,
    # while the capped schedule itself replays BIT-IDENTICALLY
    # (park/unpark is a deterministic checkpoint round-trip; the
    # bit-level park-file fidelity pin is the capped kill-and-resume
    # test below)
    assert np.max(np.abs(res.areas - base.areas)) < 5e-5
    res2 = EngineDispatcher("sin_recip_scaled",
                            **dict(DKW, max_engines=2)).run(
        MIXED, arrival_phase=ARR)
    assert np.array_equal(res.areas, res2.areas)
    summary = capped.engines_summary()
    assert set(summary) == MIXED_KEYS
    states = {e["state"] for e in summary.values()}
    assert "parked" in states, states


def test_dispatch_kill_and_resume_bit_identical(tmp_path):
    """Capped pool (max_engines=2, so the coordinated cut carries
    PARKED engines too): crash mid-stream, resume from the manifest,
    and the continued mixed run — park files, unparks and all — is
    bit-identical to the undisturbed one. Every timeline passes the
    rid-linkage contract."""
    from ppls_tpu.obs import Telemetry
    from ppls_tpu.utils.artifact_schema import validate_events_text

    kw = dict(DKW, max_engines=2)
    base_ev = str(tmp_path / "base.jsonl")
    tel = Telemetry(events_path=base_ev)
    base = EngineDispatcher("sin_recip_scaled", telemetry=tel,
                            **kw).run(MIXED, arrival_phase=ARR)
    tel.close()
    # clean pool timeline: balanced spans AND the rid-linkage contract
    assert validate_events_text(open(base_ev).read(),
                                check_rid_linkage=True) == []

    path = str(tmp_path / "pool.ckpt")
    crash_ev = str(tmp_path / "crash.jsonl")
    tel2 = Telemetry(events_path=crash_ev)
    disp = EngineDispatcher("sin_recip_scaled", telemetry=tel2,
                            checkpoint_path=path, checkpoint_every=1,
                            **kw)
    with pytest.raises(RuntimeError, match="simulated crash"):
        disp.run(MIXED, arrival_phase=ARR, _crash_after_turns=3)
    tel2.close()
    assert validate_events_text(open(crash_ev).read(),
                                require_balanced=False,
                                check_rid_linkage=True) == []

    resume_ev = str(tmp_path / "resume.jsonl")
    tel3 = Telemetry(events_path=resume_ev)
    disp2 = EngineDispatcher.resume(path, "sin_recip_scaled",
                                    telemetry=tel3,
                                    checkpoint_every=1, **kw)
    assert disp2.phase == 3
    assert disp2.recompiles() == 0
    res = _drive_to_drain(disp2, MIXED, ARR)
    tel3.close()
    assert validate_events_text(open(resume_ev).read(),
                                require_balanced=False,
                                check_rid_linkage=True) == []
    # the resumed mixed stream replays bit-identically
    assert np.array_equal(res.areas, base.areas)
    assert res.phases == base.phases
    assert len(res.completed) == len(base.completed)
    assert disp2.recompiles() == 0
    assert set(disp2.engines_summary()) == MIXED_KEYS


def test_dispatch_resume_refuses_other_config_and_pool(tmp_path):
    # a cheap single-key workload: config/blend refusal needs files,
    # not heterogeneity
    reqs = [(1.0 + i / 8, BOUNDS) for i in range(3)]

    a_dir = tmp_path / "a"
    a_dir.mkdir()
    a_path = str(a_dir / "pool.ckpt")
    disp_a = EngineDispatcher("sin_recip_scaled",
                              checkpoint_path=a_path,
                              checkpoint_every=1, **DKW)
    with pytest.raises(RuntimeError, match="simulated crash"):
        disp_a.run(reqs, _crash_after_turns=1)

    # manifest identity pins the pool configuration
    with pytest.raises(ValueError,
                       match="different pool configuration"):
        EngineDispatcher.resume(a_path, "sin_recip_scaled",
                                **dict(DKW, slots=4))

    # a second pool with the IDENTICAL configuration: its per-engine
    # snapshot must still refuse to blend into pool A's manifest
    # (pool ids differ even when every config knob matches)
    b_dir = tmp_path / "b"
    b_dir.mkdir()
    b_path = str(b_dir / "pool.ckpt")
    disp_b = EngineDispatcher("sin_recip_scaled",
                              checkpoint_path=b_path,
                              checkpoint_every=1, **DKW)
    with pytest.raises(RuntimeError, match="simulated crash"):
        disp_b.run(reqs, _crash_after_turns=1)
    a_cuts = sorted(glob.glob(os.path.join(str(a_dir),
                                           "pool.ckpt.c*")))
    b_cuts = sorted(glob.glob(os.path.join(str(b_dir),
                                           "pool.ckpt.c*")))
    assert a_cuts and b_cuts
    assert [os.path.basename(p) for p in a_cuts] \
        == [os.path.basename(p) for p in b_cuts]
    for src, dst in zip(b_cuts, a_cuts):
        shutil.copyfile(src, dst)
    with pytest.raises(ValueError, match="refusing to blend"):
        EngineDispatcher.resume(a_path, "sin_recip_scaled",
                                checkpoint_every=1, **DKW)


# ---------------------------------------------------------------------
# round 22: slot-credit leasing + overlapped phase boundaries


def test_dispatch_lease_turn_counts_pinned_on_seeded_stream():
    """The round-22 acceptance pin, on the SAME seeded mixed stream the
    committed bench reference measures: lease/overlap OFF replays the
    round-21 schedule exactly (9 turns / 1.5 mean retire latency — and
    the round-22 scheduler fix that stops a drained engine from
    burning a turn credit provably changed only intra-turn order, not
    the schedule), and lease+overlap ON drains the identical stream in
    6 turns at >= 1.2x better mean latency, zero recompiles both ways,
    with a balanced ledger and at least one overlapped boundary."""
    from tools.bench_history import (HETERO_EKW, HETERO_FAMILY,
                                     HETERO_MAX_ENGINES, HETERO_SLOTS,
                                     _hetero_requests)

    reqs, arr = _hetero_requests()
    d0 = EngineDispatcher(HETERO_FAMILY, slots=HETERO_SLOTS,
                          max_engines=HETERO_MAX_ENGINES,
                          engine_kw=dict(HETERO_EKW))
    r0 = d0.run(reqs, arrival_phase=arr)
    lat0 = [int(c.retire_phase) - int(c.submit_phase)
            for c in r0.completed]
    assert int(r0.phases) == 9, r0.phases      # committed round-21 ref
    assert float(np.mean(lat0)) == pytest.approx(1.5)
    assert d0.recompiles() == 0
    ls0 = d0.lease_summary()
    assert ls0["enabled"] is False
    assert ls0["donated"] == ls0["received"] == 0

    d1 = EngineDispatcher(HETERO_FAMILY, slots=HETERO_SLOTS,
                          max_engines=HETERO_MAX_ENGINES,
                          lease=True, overlap_boundaries=True,
                          engine_kw=dict(HETERO_EKW))
    r1 = d1.run(reqs, arrival_phase=arr)
    lat1 = [int(c.retire_phase) - int(c.submit_phase)
            for c in r1.completed]
    assert len(r1.completed) == len(reqs)
    assert np.all(np.isfinite(r1.areas))
    assert d1.recompiles() == 0
    # the ISSUE's >= 1.2x floor on both proxies, as exact pins (the
    # schedule is deterministic; a change that moves these moved the
    # lease policy and must re-justify the gate reference)
    assert int(r1.phases) == 6, r1.phases
    assert float(np.mean(lat0)) / float(np.mean(lat1)) >= 1.2
    ls = d1.lease_summary()
    assert ls["enabled"] and ls["overlap_boundaries"]
    assert ls["donated"] == ls["received"] >= 1
    assert ls["balanced"] is True
    assert sum(ls["by_donor"].values()) == ls["donated"]
    assert sum(ls["by_borrower"].values()) == ls["received"]
    assert ls["overlapped"] >= 1
    assert 0.0 < ls["overlap_fraction"] <= 1.0
    assert ls["boundaries"] >= ls["overlapped"]


def test_dispatch_overlap_matches_sync_bit_identical():
    """Overlapped boundaries are a WALL-CLOCK optimization only: with
    the identical lease schedule, launching every due cycle before the
    first stats fetch must produce bit-identical areas, the same turn
    count, and the same lease ledger as the serialized boundary — the
    only divergence allowed is the overlap tallies themselves."""
    d_sync = EngineDispatcher("sin_recip_scaled", lease=True, **DKW)
    r_sync = d_sync.run(MIXED, arrival_phase=ARR)
    d_ov = EngineDispatcher("sin_recip_scaled", lease=True,
                            overlap_boundaries=True, **DKW)
    r_ov = d_ov.run(MIXED, arrival_phase=ARR)
    assert np.array_equal(r_sync.areas, r_ov.areas)    # bit-for-bit
    assert r_sync.phases == r_ov.phases
    assert d_sync.recompiles() == 0 and d_ov.recompiles() == 0
    ls_s, ls_o = d_sync.lease_summary(), d_ov.lease_summary()
    assert ls_s["by_donor"] == ls_o["by_donor"]
    assert ls_s["by_borrower"] == ls_o["by_borrower"]
    assert ls_s["boundaries"] == ls_o["boundaries"]
    # sync mode never overlaps; overlap mode actually overlapped
    assert ls_s["overlapped"] == 0
    assert ls_o["overlapped"] >= 1


def test_dispatch_lease_park_unpark_capped(tmp_path):
    """Leases x the LRU cap: a PARKED engine donates its full per-turn
    budget (donor_parked grants in the timeline), the unparked engine
    still completes its routed work (its credits come back with it),
    and the capped lease schedule replays bit-identically."""
    import json as _json

    from ppls_tpu.obs import Telemetry

    ev = str(tmp_path / "lease.jsonl")
    tel = Telemetry(events_path=ev)
    kw = dict(DKW, max_engines=2)
    capped = EngineDispatcher("sin_recip_scaled", telemetry=tel,
                              lease=True, overlap_boundaries=True,
                              **kw)
    res = capped.run(MIXED, arrival_phase=ARR)
    tel.close()
    parks = sum(child.value for _, child in capped._c_park.items())
    assert parks >= 2, "max_engines=2 never parked an engine"
    assert capped.recompiles() == 0
    assert len(res.completed) == len(MIXED)
    ls = capped.lease_summary()
    assert ls["donated"] == ls["received"] >= 1
    assert ls["balanced"] is True
    grants = [r for r in
              (_json.loads(ln) for ln in open(ev) if ln.strip())
              if r.get("ev") == "event"
              and r.get("name") == "lease_grant"]
    assert sum(g["attrs"]["credits"] for g in grants) == ls["received"]
    # the S3 contract: parked engines' credits return to the pool —
    # at least one grant must name a parked donor
    parked_donors = {g["attrs"]["donor"] for g in grants
                     if g["attrs"]["donor_parked"]}
    assert parked_donors, [g["attrs"] for g in grants]
    # ...and unpark restores them: every parked donor came back and
    # finished its routed requests (donating while parked did not
    # strand its own backlog)
    summary = capped.engines_summary()
    for k in parked_donors:
        assert summary[k]["completed"] >= 1, (k, summary[k])
    assert sum(e["completed"] for e in summary.values()) == len(MIXED)
    # capped + leased, same workload: bit-identical replay
    res2 = EngineDispatcher("sin_recip_scaled", lease=True,
                            overlap_boundaries=True, **kw).run(
        MIXED, arrival_phase=ARR)
    assert np.array_equal(res.areas, res2.areas)


def test_dispatch_lease_capped_kill_and_resume_bit_identical(tmp_path):
    """The round-22 kill-and-resume acceptance: capped pool, leases in
    flight, overlapped boundaries and the BACKGROUND checkpoint writer
    active (overlap implies it) — crash after turn 3, resume from the
    coordinated cut, and the continued run is bit-identical to the
    undisturbed one INCLUDING the lease ledger: every grant replays
    onto the same (donor, borrower) cells."""
    kw = dict(DKW, max_engines=2, lease=True, overlap_boundaries=True)
    base_d = EngineDispatcher("sin_recip_scaled", **kw)
    base = base_d.run(MIXED, arrival_phase=ARR)
    ls_base = base_d.lease_summary()
    assert ls_base["donated"] >= 1         # leases actually in flight

    path = str(tmp_path / "pool.ckpt")
    disp = EngineDispatcher("sin_recip_scaled", checkpoint_path=path,
                            checkpoint_every=1, **kw)
    assert disp.checkpoint_background      # overlap => background writer
    with pytest.raises(RuntimeError, match="simulated crash"):
        disp.run(MIXED, arrival_phase=ARR, _crash_after_turns=3)

    disp2 = EngineDispatcher.resume(path, "sin_recip_scaled",
                                    checkpoint_every=1, **kw)
    assert disp2.phase == 3
    assert disp2.recompiles() == 0
    mid = disp2.lease_summary()
    assert mid["donated"] == mid["received"]   # the restored ledger
    res = _drive_to_drain(disp2, MIXED, ARR)
    assert np.array_equal(res.areas, base.areas)       # bit-for-bit
    assert res.phases == base.phases
    assert len(res.completed) == len(base.completed)
    ls = disp2.lease_summary()
    assert ls["by_donor"] == ls_base["by_donor"]
    assert ls["by_borrower"] == ls_base["by_borrower"]
    assert ls["donated"] == ls_base["donated"]
    assert ls["boundaries"] == ls_base["boundaries"]
    assert ls["balanced"] is True


def test_analyze_occupancy_lease_columns(tmp_path):
    """The offline decomposition (S2): a leased pool timeline replays
    through tools/analyze_occupancy.py --from-events with the
    idle-slot/lease columns present and BOTH reconciliations OK —
    per-engine retires vs distinct rids, and donated == borrowed
    across the deduped grants."""
    import subprocess
    import sys as _sys

    from ppls_tpu.obs import Telemetry

    ev = str(tmp_path / "pool.jsonl")
    tel = Telemetry(events_path=ev)
    disp = EngineDispatcher("sin_recip_scaled", telemetry=tel,
                            lease=True, overlap_boundaries=True,
                            **DKW)
    disp.run(MIXED, arrival_phase=ARR)
    tel.close()
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    r = subprocess.run(
        [_sys.executable, "tools/analyze_occupancy.py",
         "--from-events", ev, "--lanes", str(EKW["lanes"])],
        capture_output=True, text=True, cwd=repo, timeout=120)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "per-engine decomposition" in r.stdout
    assert "donated=" in r.stdout and "borrowed=" in r.stdout
    assert "leased_phases=" in r.stdout
    for line in r.stdout.splitlines():
        if "reconciliation:" in line:
            assert "OK" in line, line
    assert "lease reconciliation:" in r.stdout
