"""Double-single arithmetic validation against numpy float64."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ppls_tpu.ops import ds


def _rand(n, lo, hi, seed=0):
    rng = np.random.default_rng(seed)
    return rng.uniform(lo, hi, n)


def _to_ds(x):
    return ds.ds_from_f64(jnp.asarray(x))


def _rep(x):
    """The f64 value actually represented by the ds split of x — the
    correct reference input (the split itself drops ~5 mantissa bits,
    which cancellation can amplify arbitrarily in relative terms)."""
    hi, lo = ds.ds_from_f64(jnp.asarray(x))
    return np.asarray(hi, np.float64) + np.asarray(lo, np.float64)


def _err(ds_val, ref):
    got = np.asarray(ds.ds_to_f64(ds_val))
    return np.abs(got - ref)


def test_split_roundtrip():
    # ds carries ~48 of f64's 53 mantissa bits: rel error <= 2^-47.
    x = _rand(1000, -1e6, 1e6)
    hi, lo = _to_ds(x)
    np.testing.assert_allclose(np.asarray(hi, np.float64) +
                               np.asarray(lo, np.float64), x, rtol=2 ** -47)


@pytest.mark.parametrize("op,ref", [
    (ds.ds_add, lambda a, b: a + b),
    (ds.ds_sub, lambda a, b: a - b),
    (ds.ds_mul, lambda a, b: a * b),
    (ds.ds_div, lambda a, b: a / b),
])
def test_arith_close_to_f64(op, ref):
    a = _rand(4096, -100.0, 100.0, seed=1)
    b = _rand(4096, 0.1, 100.0, seed=2)
    got = op(_to_ds(a), _to_ds(b))
    expected = ref(_rep(a), _rep(b))
    # ds error is bounded in ulps of the INPUTS (2^-48 * |operand|);
    # cancellation makes result-relative error unbounded by design.
    scale = np.maximum(np.maximum(np.abs(_rep(a)), np.abs(_rep(b))),
                       np.abs(expected))
    rel = _err(got, expected) / scale
    assert rel.max() < 2 ** -46, rel.max()


def test_mul_exactness_small_ints():
    # products of small integers are exact in ds
    a = np.arange(1.0, 100.0)
    got = ds.ds_to_f64(ds.ds_mul(_to_ds(a), _to_ds(a)))
    np.testing.assert_array_equal(np.asarray(got), a * a)


def test_comparisons():
    a = np.array([1.0, 1.0, 2.0])
    b = np.array([1.0 + 1e-12, 1.0, 1.0])
    lt = np.asarray(ds.ds_lt(_to_ds(a), _to_ds(b)))
    gt = np.asarray(ds.ds_gt(_to_ds(a), _to_ds(b)))
    assert lt.tolist() == [True, False, False]
    assert gt.tolist() == [False, False, True]


def test_ds_sin_accuracy_small_args():
    x = _rand(1 << 14, -0.78, 0.78, seed=3)
    got = ds.ds_sin(_to_ds(x))
    assert _err(got, np.sin(_rep(x))).max() < 5e-14


def test_ds_sin_accuracy_medium_args():
    x = _rand(1 << 14, -30.0, 30.0, seed=4)
    got = ds.ds_sin(_to_ds(x))
    assert _err(got, np.sin(_rep(x))).max() < 5e-13


def test_ds_sin_accuracy_large_args():
    # the deep-quadrature regime: args up to 2e4 (theta/x at x=1e-4)
    x = _rand(1 << 14, 1.0, 2e4, seed=5)
    got = ds.ds_sin(_to_ds(x))
    assert _err(got, np.sin(_rep(x))).max() < 2e-11


def test_ds_sin_small_magnitude_args():
    # the XLA f64-emulation slow-path region — must be fast AND accurate
    x = _rand(1 << 14, 1e-4, 2e-3, seed=6)
    got = ds.ds_sin(_to_ds(x))
    assert _err(got, np.sin(_rep(x))).max() < 1e-14


def test_ds_cos():
    x = _rand(1 << 12, -10.0, 10.0, seed=7)
    got = ds.ds_cos(_to_ds(x))
    assert _err(got, np.cos(_rep(x))).max() < 5e-13


def test_jit_and_vmap_compatible():
    f = jax.jit(lambda hi, lo: ds.ds_sin((hi, lo)))
    x = _rand(128, -5.0, 5.0)
    hi, lo = _to_ds(x)
    got = f(hi, lo)
    assert _err(got, np.sin(_rep(x))).max() < 5e-13


def test_pow2_exact_where_exp2_is_not():
    # jnp.exp2 is approximate even at integer arguments on XLA backends
    # (~1e-6 rel in f32); the pow2 helpers must be bit-exact.
    import jax
    import jax.numpy as jnp

    from ppls_tpu.ops.pow2 import pow2_f32, pow2_f64

    k = jnp.asarray(np.arange(-126, 128), jnp.float32)
    got = np.asarray(jax.jit(pow2_f32)(k), np.float64)
    assert np.array_equal(got, 2.0 ** np.arange(-126, 128, dtype=np.float64))
    # flush below the normal range
    assert float(jax.jit(pow2_f32)(jnp.float32(-127.0))) == 0.0

    k64 = jnp.asarray(np.arange(-250, 251), jnp.float64)
    got64 = np.asarray(jax.jit(pow2_f64)(k64))
    assert np.array_equal(got64, 2.0 ** np.arange(-250, 251, dtype=np.float64))


def test_ds_exp_accuracy_both_modules():
    # exp over the gauss-relevant range; the fenced (XLA-level) module
    # must hold ds precision, which requires the exact pow2 scaling
    # (jnp.exp2's ~1e-6 integer-argument error was the dominant term).
    import jax
    import jax.numpy as jnp

    from ppls_tpu.ops import ds

    x = np.concatenate([np.linspace(-50.0, 5.0, 8192),
                        np.linspace(-1e-3, 1e-3, 512)])
    hi, lo = jax.jit(lambda v: ds.ds_exp(ds.ds_from_f64(v)))(jnp.asarray(x))
    got = np.asarray(hi, np.float64) + np.asarray(lo, np.float64)
    ref = np.exp(x)
    rel = np.abs(got - ref) / np.abs(ref)
    # |x| amplifies the ds argument error (rel ~ |x| * 2^-48)
    assert rel.max() < 1e-12, rel.max()

    # Below exp(-50) the ds PAIR cannot hold 2^-49 relative precision
    # (the lo limb needs hi * 2^-49 >= 2^-126): graceful degradation to
    # f32-hi accuracy, absolutely tiny for any quadrature use.
    xt = np.linspace(-85.0, -50.0, 1024)
    hi, lo = jax.jit(lambda v: ds.ds_exp(ds.ds_from_f64(v)))(jnp.asarray(xt))
    got = np.asarray(hi, np.float64) + np.asarray(lo, np.float64)
    assert np.abs(got - np.exp(xt)).max() < 1e-28


def test_gauss_center_ds_twin_matches_f64():
    import jax
    import jax.numpy as jnp

    from ppls_tpu.models.integrands import get_family, get_family_ds
    from ppls_tpu.ops import ds

    f = get_family("gauss_center")
    fds = get_family_ds("gauss_center")
    xs = np.linspace(0.49, 0.51, 8192)
    c = np.full_like(xs, 0.5)
    hi, lo = jax.jit(lambda v, cc: fds(ds.ds_from_f64(v),
                                       ds.ds_from_f64(cc), dsm=ds))(
        jnp.asarray(xs), jnp.asarray(c))
    got = np.asarray(hi, np.float64) + np.asarray(lo, np.float64)
    ref = np.asarray(jax.jit(f)(jnp.asarray(xs), jnp.asarray(c)))
    # rel error ~ |z| * 2^-48 with z = -500000 (x-c)^2 down to -50
    assert np.abs(got - ref).max() < 1e-11
