"""Failure-domain recovery (round 14, ISSUE 10).

Acceptance surface of the robustness tentpole:

* seeded fault injection: FaultPlan schedules are deterministic per
  seed, injector hooks fire each event exactly once at the boundary it
  keys on, and checkpoint-damage events produce files the hardened
  loader REFUSES (CheckpointCorruptError) instead of unpickling;
* checkpoint integrity: truncation and bit-flips of REAL snapshots are
  detected via the payload checksums + format-version field;
* guard growth: deterministic exponential backoff, the total-deadline
  retry budget, ppls_retries_total{reason}, and watchdog resume
  provenance in the events timeline;
* the self-healing Supervisor: transient -> backoff + resume,
  chip-loss -> resize-resume onto the surviving mesh, poison ->
  propagate (quarantine is the engine's job);
* ELASTIC MESH-RESIZE RESUME (the ROADMAP item-5 contract): kill one
  chip mid-stream on the virtual 8-mesh, resume the snapshot onto the
  surviving 7 chips through the depth-stratified redeal — per-request
  areas BIT-IDENTICAL to the undisturbed run on the dyadic-exact
  workload (where every credit and sum is exact, so no schedule or
  mesh size can move a bit), and within the documented ~1e-9 contract
  with the ds walker engaged;
* per-request NaN quarantine on walker, dd, and stream engines:
  poisoned request beside healthy concurrent requests — healthy areas
  bit-identical to a no-poison run, poisoned ones emit failed records.
"""

import json
import os

import numpy as np
import pytest

import jax.numpy as jnp

from ppls_tpu.models.integrands import (get_family, get_family_ds,
                                        register_family,
                                        register_family_ds)
from ppls_tpu.obs import MetricsRegistry, Telemetry
from ppls_tpu.ops import ds_kernel as dsk
from ppls_tpu.runtime import guard
from ppls_tpu.runtime.checkpoint import (CheckpointCorruptError,
                                         load_checkpoint,
                                         load_family_checkpoint,
                                         save_checkpoint,
                                         save_family_checkpoint)
from ppls_tpu.runtime.faults import (FaultEvent, FaultInjector,
                                     FaultPlan)
from ppls_tpu.runtime.stream import StreamEngine

BOUNDS = (1e-2, 1.0)
# the walker-test sizing (small, interpret-friendly; the dd variants
# match tests/test_stream.py so the compiled shard programs are shared
# within one pytest process)
KW = dict(slots=8, chunk=1 << 10, capacity=1 << 16, lanes=256,
          roots_per_lane=2, refill_slots=2, seg_iters=32,
          min_active_frac=0.05)
DD_KW = dict(KW, chunk=1 << 8, engine="walker-dd", n_devices=8)
WKW = dict(capacity=1 << 16, lanes=256, roots_per_lane=2,
           refill_slots=2, seg_iters=32, min_active_frac=0.05)


# dyadic-exact quadratic (the stream determinism family shape): every
# credit is exactly representable and every sum exact, so neither the
# admission schedule nor the MESH SIZE can move a bit — the
# bit-identity half of the resize-resume contract is assertable on it.
def _quad(x, th):
    return th * x * x


def _quad_ds(x, th, dsm=dsk):
    # dsm-parameterized (register_family_ds contract) so the
    # PPLS_SCOUT=1 lane can run these families through the scout
    # kernel's single-precision twins
    return dsm.ds_mul(th, dsm.ds_mul(x, x))


# th > 8 poisons the right half of the f64 domain with NaN (the
# injected data fault); the ds twin stays clean — the strict-modes
# loud-NaN family shape, reused for the quarantine contract.
def _poison(x, th):
    return jnp.where((th > 8.0) & (x > 0.5), jnp.nan, th * x * x)


register_family("quad_faults_test", _quad)
register_family_ds("quad_faults_test", _quad_ds)
register_family("poison_faults_test", _poison)
register_family_ds("poison_faults_test", _quad_ds)


# ---------------------------------------------------------------------------
# FaultPlan / FaultInjector
# ---------------------------------------------------------------------------

def test_fault_plan_seeded_deterministic():
    """The whole point of SEEDED chaos: the same seed must always
    yield the same schedule, so a chaos failure reproduces."""
    a, b = FaultPlan.seeded(7), FaultPlan.seeded(7)
    assert a.to_json() == b.to_json()
    assert len(a) == 4
    # a different seed draws a different schedule (any of 100 distinct
    # seeds colliding with seed 7 would be a broken generator)
    assert any(FaultPlan.seeded(s).to_json() != a.to_json()
               for s in range(100))


def test_fault_plan_spec_forms(tmp_path, monkeypatch):
    inline = '[{"kind": "crash", "at": 2}, {"kind": "nan_poison", "at": 1}]'
    p = FaultPlan.from_spec(inline)
    assert [e.kind for e in p.events] == ["crash", "nan_poison"]
    f = tmp_path / "plan.json"
    f.write_text(inline)
    assert FaultPlan.from_spec(f"@{f}").to_json() == p.to_json()
    assert FaultPlan.from_spec("seed:3:2").to_json() == \
        FaultPlan.seeded(3, n_events=2).to_json()
    assert FaultPlan.from_spec(None) is None
    assert FaultPlan.from_spec("") is None
    monkeypatch.setenv("PPLS_FAULT_PLAN", inline)
    assert FaultPlan.from_env().to_json() == p.to_json()
    with pytest.raises(ValueError, match="unknown fault kind"):
        FaultPlan.from_spec('[{"kind": "meteor", "at": 1}]')


def test_injector_fires_each_event_once_with_attribution():
    tel = Telemetry()
    plan = FaultPlan.from_events([
        {"kind": "crash", "at": 2},
        {"kind": "nan_poison", "at": 1},
        {"kind": "straggler", "at": 3, "seconds": 0.0}])
    inj = FaultInjector(plan, telemetry=tel)
    inj.on_phase_open(0)                       # nothing keyed here
    assert inj.on_admit(0) is False
    assert inj.on_admit(1) is True             # poison fires ...
    assert inj.on_admit(1) is False            # ... exactly once
    with pytest.raises(guard.InjectedCrash):
        inj.on_phase_open(2, n_dev=8)
    inj.on_phase_open(2, n_dev=8)              # consumed: no re-fire
    inj.on_phase_open(3)                       # straggler: sleeps 0s
    assert tel.registry.value("ppls_faults_injected_total",
                              kind="crash") == 1
    assert tel.registry.value("ppls_faults_injected_total",
                              kind="nan_poison") == 1
    assert tel.registry.value("ppls_faults_injected_total",
                              kind="straggler") == 1


def test_injector_chip_loss_carries_surviving_mesh():
    inj = FaultInjector(FaultPlan.from_events(
        [{"kind": "chip_loss", "at": 5, "chip": 3}]))
    with pytest.raises(guard.ChipLossError) as ei:
        inj.on_phase_open(5, n_dev=8)
    assert ei.value.chip == 3
    assert ei.value.n_dev == 8
    assert ei.value.surviving == 7


def _write_real_snapshot(path):
    save_family_checkpoint(
        path, identity={"engine": "walker", "fname": "f", "eps": 1e-7},
        bag_cols={"l": np.linspace(0, 1, 64),
                  "meta": np.arange(64, dtype=np.int32)},
        count=64, acc=np.array([1.5, 2.5]), totals={"tasks": 3})


def test_injector_checkpoint_damage_is_detected(tmp_path):
    """ckpt_truncate / ckpt_corrupt (keyed on the WRITE ordinal) must
    produce files the hardened loader refuses with the offending
    path."""
    ident = {"engine": "walker", "fname": "f", "eps": 1e-7}
    for kind in ("ckpt_truncate", "ckpt_corrupt"):
        path = str(tmp_path / f"{kind}.ckpt")
        inj = FaultInjector(FaultPlan.from_events(
            [{"kind": kind, "at": 1}]))
        _write_real_snapshot(path)
        inj.on_checkpoint_write(path)          # write 0: not keyed
        assert load_family_checkpoint(path, ident)[1] == 64
        _write_real_snapshot(path)
        inj.on_checkpoint_write(path)          # write 1: damage fires
        with pytest.raises(CheckpointCorruptError) as ei:
            load_family_checkpoint(path, ident)
        assert ei.value.path == path


# ---------------------------------------------------------------------------
# checkpoint integrity hardening (satellite 1)
# ---------------------------------------------------------------------------

def test_family_checkpoint_truncation_detected(tmp_path):
    path = str(tmp_path / "t.ckpt")
    _write_real_snapshot(path)
    data = open(path, "rb").read()
    with open(path, "wb") as fh:
        fh.write(data[: len(data) // 2])
    with pytest.raises(CheckpointCorruptError, match="corrupt"):
        load_family_checkpoint(path, {"engine": "walker", "fname": "f",
                                      "eps": 1e-7})


def test_family_checkpoint_bitflip_detected(tmp_path):
    path = str(tmp_path / "b.ckpt")
    _write_real_snapshot(path)
    data = bytearray(open(path, "rb").read())
    data[len(data) // 2] ^= 0xFF
    with open(path, "wb") as fh:
        fh.write(bytes(data))
    with pytest.raises(CheckpointCorruptError) as ei:
        load_family_checkpoint(path, {"engine": "walker", "fname": "f",
                                      "eps": 1e-7})
    assert ei.value.path == path


def test_missing_snapshot_is_not_reported_corrupt(tmp_path):
    """A MISSING file must surface as FileNotFoundError, never as
    CheckpointCorruptError (whose remedy — delete the file — would
    then itself fail)."""
    missing = str(tmp_path / "never_written.ckpt")
    with pytest.raises(FileNotFoundError):
        load_family_checkpoint(missing, {"engine": "walker"})
    with pytest.raises(FileNotFoundError):
        load_checkpoint(missing)


def test_host_checkpoint_corruption_detected(tmp_path):
    from ppls_tpu.utils.metrics import RunMetrics
    path = str(tmp_path / "h.ckpt")
    save_checkpoint(path, np.array([[0.0, 1.0]]), (1.0, 0.0),
                    RunMetrics())
    f2, acc, _m, _cfg = load_checkpoint(path)    # clean round-trip
    assert acc == (1.0, 0.0)
    data = bytearray(open(path, "rb").read())
    data[len(data) // 2] ^= 0xFF
    with open(path, "wb") as fh:
        fh.write(bytes(data))
    with pytest.raises(CheckpointCorruptError):
        load_checkpoint(path)


def test_checkpoint_carries_format_version_and_checksums(tmp_path):
    path = str(tmp_path / "v.ckpt")
    _write_real_snapshot(path)
    with np.load(path) as z:
        meta = json.loads(bytes(z["meta"]).decode())
    assert meta["format_version"] == 1
    assert set(meta["checksums"]) == {"acc", "bag_l", "bag_meta"}
    # identity mismatch is still the DIFFERENT-RUN ValueError, not a
    # corruption report
    with pytest.raises(ValueError, match="different run"):
        load_family_checkpoint(path, {"engine": "walker", "fname": "f",
                                      "eps": 1e-6})


def test_chaos_lane_verifies_on_write(tmp_path, monkeypatch):
    """PPLS_CHAOS=1 (the ci.sh chaos sub-lane): every snapshot write
    immediately re-opens and checksum-verifies itself."""
    monkeypatch.setenv("PPLS_CHAOS", "1")
    path = str(tmp_path / "c.ckpt")
    _write_real_snapshot(path)      # verify-on-write runs clean
    called = {}
    import ppls_tpu.runtime.checkpoint as ckpt

    real = ckpt._verify_payload

    def spy(*a, **k):
        called["yes"] = True
        return real(*a, **k)

    monkeypatch.setattr(ckpt, "_verify_payload", spy)
    _write_real_snapshot(path)
    assert called.get("yes"), "chaos lane did not verify on write"


# ---------------------------------------------------------------------------
# guard: backoff, budget, provenance, supervisor (satellite 2)
# ---------------------------------------------------------------------------

def test_backoff_schedule_is_deterministic_exponential():
    assert [guard.backoff_seconds(a, base=1.0, cap=60.0)
            for a in range(1, 6)] == [1.0, 2.0, 4.0, 8.0, 16.0]
    assert guard.backoff_seconds(10, base=1.0, cap=60.0) == 60.0


def test_with_retry_budget_and_counter(monkeypatch):
    from ppls_tpu.obs.telemetry import set_default
    prev = set_default(Telemetry())
    try:
        calls = []

        def flaky():
            calls.append(1)
            raise RuntimeError("Connection reset by peer")

        # budget too small for the first 10s backoff: the loop must
        # refuse to sleep into a deadline it cannot keep
        with pytest.raises(guard.RetryBudgetExhausted,
                           match="total retry deadline"):
            guard.with_retry(flaky, [], deadline=5.0,
                             total_deadline=1.0, log=lambda m: None)
        assert len(calls) == 1

        # with room to retry, every retry counts into the registry
        monkeypatch.setattr(guard.time, "sleep", lambda s: None)
        seen = []

        def flaky2():
            seen.append(1)
            if len(seen) < 3:
                raise RuntimeError("Connection reset by peer")
            return "ok"

        log = []
        assert guard.with_retry(flaky2, log, deadline=5.0,
                                log=lambda m: None) == "ok"
        assert len(log) == 2
        from ppls_tpu.obs.telemetry import default_telemetry
        assert default_telemetry().registry.value(
            "ppls_retries_total", reason="transient") == 2
    finally:
        set_default(prev)


def test_run_with_watchdog_records_resume_provenance():
    import threading
    tel = Telemetry()
    events = []
    orig = tel.event
    tel.event = lambda name, **a: (events.append((name, a)),
                                   orig(name, **a))
    out = guard.run_with_watchdog(
        lambda: threading.Event().wait(5), 0.2,
        resume_fn=lambda: "recovered", log=lambda m: None,
        telemetry=tel, checkpoint_path="/tmp/x.ckpt")
    assert out == "recovered"
    names = [n for n, _ in events]
    assert "watchdog_resume" in names
    attrs = dict(events[names.index("watchdog_resume")][1])
    assert attrs["checkpoint"] == "/tmp/x.ckpt"
    assert attrs["attempt"] == 2


def test_classify_failure_taxonomy():
    assert guard.classify_failure(guard.ChipLossError(1, 8)) \
        == "chip_loss"
    assert guard.classify_failure(FloatingPointError("nan")) == "poison"
    assert guard.classify_failure(guard.HangTimeout("watchdog deadline"
                                                    )) == "transient"
    assert guard.classify_failure(guard.InjectedCrash("x")) \
        == "transient"
    assert guard.classify_failure(RuntimeError("Connection reset")) \
        == "transient"
    assert guard.classify_failure(RuntimeError("sizing mismatch")) \
        == "fatal"
    # the budget-exhaustion message EMBEDS the last transient text —
    # it must still classify fatal, or a supervisor would retry past
    # the exhausted budget
    assert guard.classify_failure(guard.RetryBudgetExhausted(
        "total retry deadline 1s ... last failure: INTERNAL: tunnel "
        "drop")) == "fatal"


def test_supervisor_transient_backoff_then_success():
    sleeps = []
    calls = []

    def run():
        calls.append(1)
        if len(calls) < 3:
            raise guard.InjectedCrash("phase-boundary crash")
        return "done"

    sup = guard.Supervisor(run, backoff_base=0.5, backoff_cap=60.0,
                           telemetry=Telemetry(), log=lambda m: None,
                           sleep=sleeps.append)
    assert sup.run() == "done"
    assert sleeps == [0.5, 1.0]           # deterministic exponential
    assert sup.recoveries == [("transient", "backoff_resume")] * 2


def test_supervisor_chip_loss_resizes_and_exhausted_mesh_is_fatal():
    resized = []

    def run():
        if not resized:
            raise guard.ChipLossError(7, 8)
        return "resized-done"

    def resize_fn(exc):
        resized.append(exc.surviving)
        return run

    sup = guard.Supervisor(run, resize_fn=resize_fn,
                           log=lambda m: None, sleep=lambda s: None)
    assert sup.run() == "resized-done"
    assert resized == [7]
    assert sup.recoveries == [("chip_loss", "resize_resume")]

    # a loss on a 1-chip mesh leaves nothing to resume onto
    sup2 = guard.Supervisor(
        lambda: (_ for _ in ()).throw(guard.ChipLossError(0, 1)),
        resize_fn=lambda e: None, log=lambda m: None,
        sleep=lambda s: None)
    with pytest.raises(guard.ChipLossError):
        sup2.run()


def test_supervisor_poison_propagates():
    sup = guard.Supervisor(
        lambda: (_ for _ in ()).throw(FloatingPointError("nan area")),
        log=lambda m: None, sleep=lambda s: None)
    with pytest.raises(FloatingPointError):
        sup.run()
    assert sup.recoveries == []


# ---------------------------------------------------------------------------
# per-request NaN quarantine (satellite 3)
# ---------------------------------------------------------------------------

THETA_H = np.array([1.0, 1.25, 1.5, 2.0])
THETA_P = np.array([1.0, 1.25, 9.0, 2.0])      # slot 2 poisoned
_HEALTHY = [0, 1, 3]


@pytest.mark.nan_injection
def test_walker_quarantine_contains_poisoned_family():
    """Poisoned family beside healthy ones on the single-chip walker:
    quarantine marks exactly the poisoned slot, healthy areas are
    BIT-IDENTICAL to the no-poison run (dyadic-exact credits — the
    schedule perturbation cannot move a bit), and the default policy
    still raises loudly."""
    f, fds = get_family("poison_faults_test"), \
        get_family_ds("poison_faults_test")
    from ppls_tpu.parallel.walker import integrate_family_walker
    base = integrate_family_walker(f, fds, THETA_H, (0.0, 1.0), 1e-9,
                                   **WKW)
    assert base.failed is None
    res = integrate_family_walker(f, fds, THETA_P, (0.0, 1.0), 1e-9,
                                  nan_policy="quarantine", **WKW)
    assert list(res.failed) == [False, False, True, False]
    assert np.array_equal(res.areas[_HEALTHY], base.areas[_HEALTHY])
    with pytest.raises(FloatingPointError, match="non-finite"):
        integrate_family_walker(f, fds, THETA_P, (0.0, 1.0), 1e-9,
                                **WKW)
    with pytest.raises(ValueError, match="nan_policy"):
        integrate_family_walker(f, fds, THETA_P, (0.0, 1.0), 1e-9,
                                nan_policy="ignore", **WKW)


@pytest.mark.nan_injection
def test_dd_quarantine_contains_poisoned_family():
    from ppls_tpu.parallel.sharded_walker import (
        integrate_family_walker_dd)
    kw = dict(WKW, chunk=1 << 8, n_devices=8)
    base = integrate_family_walker_dd("poison_faults_test", THETA_H,
                                      (0.0, 1.0), 1e-9, **kw)
    res = integrate_family_walker_dd("poison_faults_test", THETA_P,
                                     (0.0, 1.0), 1e-9,
                                     nan_policy="quarantine", **kw)
    assert list(res.failed) == [False, False, True, False]
    assert np.array_equal(res.areas[_HEALTHY], base.areas[_HEALTHY])
    with pytest.raises(FloatingPointError):
        integrate_family_walker_dd("poison_faults_test", THETA_P,
                                   (0.0, 1.0), 1e-9, **kw)


@pytest.mark.nan_injection
def test_stream_quarantine_beside_healthy_concurrent_requests():
    """The streaming form of the contract, in the pure-f64 mode where
    bit-identity is provable: the poisoned request retires as a FAILED
    CompletedRequest while every healthy CONCURRENT request retires
    normally with areas bit-identical to the no-poison run — instead
    of the engine-wide FloatingPointError the default policy keeps."""
    kw = dict(KW, f64_rounds=4)
    healthy = [(t, (0.0, 1.0)) for t in [1.0, 1.25, 1.5, 2.0, 0.75]]
    base = StreamEngine("poison_faults_test", 1e-9, **kw).run(healthy)
    # poisoned request LAST so healthy rids align across the two runs
    eng = StreamEngine("poison_faults_test", 1e-9, quarantine=True,
                       **kw)
    res = eng.run(healthy + [(9.0, (0.0, 1.0))])
    by_rid = {c.rid: c for c in res.completed}
    assert by_rid[5].failed and not np.isfinite(by_rid[5].area)
    assert all(not by_rid[r].failed for r in range(5))
    assert np.array_equal(res.areas[:5], base.areas)
    assert eng.telemetry.registry.value(
        "ppls_stream_quarantined_total") == 1
    # default policy: loud engine-wide failure, unchanged
    with pytest.raises(FloatingPointError, match="non-finite"):
        StreamEngine("poison_faults_test", 1e-9, **kw).run(
            healthy + [(9.0, (0.0, 1.0))])


@pytest.mark.nan_injection
def test_stream_injector_nan_poison_quarantined():
    """The fault-plan form: nan_poison corrupts the admitted theta
    payload (post-validation) and the quarantine path contains it."""
    inj = FaultInjector(FaultPlan.from_events(
        [{"kind": "nan_poison", "at": 1}]))
    eng = StreamEngine("quad_faults_test", 1e-9, quarantine=True,
                       fault_injector=inj, **KW)
    res = eng.run([(t, (0.0, 1.0)) for t in [1.0, 1.25, 1.5, 2.0]])
    by_rid = {c.rid: c for c in res.completed}
    assert by_rid[1].failed
    assert sorted(r for r in by_rid if not by_rid[r].failed) \
        == [0, 2, 3]
    assert inj.plan.events[0].fired


# ---------------------------------------------------------------------------
# elastic mesh-resize resume (the tentpole acceptance)
# ---------------------------------------------------------------------------

def _drive(eng, reqs, arr):
    k = eng.next_rid
    while not eng.idle or k < len(reqs):
        while k < len(reqs) and arr[k] <= eng.phase:
            eng.submit(*reqs[k])
            k += 1
        eng.step()
    return eng.result()


THETA6 = [1.0, 1.25, 1.5, 2.0, 0.75, 3.0]
REQS6 = [(t, (0.0, 1.0)) for t in THETA6]
ARR6 = [0, 0, 1, 2, 3, 4]


def test_stream_dd_resize_resume_bit_identical_on_dyadic(tmp_path):
    """THE ROADMAP item-5 acceptance: kill mid-stream on the virtual
    8-mesh, resume the snapshot onto the surviving 7 chips through the
    depth-stratified redeal — per-request areas BIT-IDENTICAL to the
    undisturbed run (dyadic-exact workload: every credit and cross-
    chip sum is exact, so neither the schedule nor the mesh size can
    move a bit). Without mesh_resize the mismatch still refuses."""
    base = StreamEngine("quad_faults_test", 1e-9, **DD_KW).run(
        REQS6, arrival_phase=ARR6)
    ck = str(tmp_path / "dd.ckpt")
    eng = StreamEngine("quad_faults_test", 1e-9, checkpoint_path=ck,
                       checkpoint_every=1, **DD_KW)
    with pytest.raises(RuntimeError, match="simulated crash"):
        eng.run(REQS6, arrival_phase=ARR6, _crash_after_phases=3)

    kw7 = dict(DD_KW, n_devices=7)
    with pytest.raises(ValueError, match="different run"):
        StreamEngine.resume(ck, "quad_faults_test", 1e-9,
                            checkpoint_every=1, **kw7)
    eng2 = StreamEngine.resume(ck, "quad_faults_test", 1e-9,
                               mesh_resize=True, checkpoint_every=1,
                               **kw7)
    assert eng2.phase == 3
    res = _drive(eng2, REQS6, ARR6)
    assert np.array_equal(res.areas, base.areas)       # bit-for-bit
    assert len(res.completed) == len(REQS6)
    assert res.phases == base.phases


def test_supervisor_chip_loss_resize_resume_end_to_end(tmp_path):
    """The full self-healing loop, engine-level: a fault plan kills
    chip 7 at phase 3, the Supervisor classifies the ChipLossError and
    resize-resumes the serve loop onto the 7 surviving chips, and the
    drained stream's areas are bit-identical to the undisturbed run."""
    base = StreamEngine("quad_faults_test", 1e-9, **DD_KW).run(
        REQS6, arrival_phase=ARR6)
    ck = str(tmp_path / "sup.ckpt")
    inj = FaultInjector(FaultPlan.from_events(
        [{"kind": "chip_loss", "at": 3}]))
    state = {"n": 8}

    def loop():
        kw = dict(DD_KW, n_devices=state["n"])
        if os.path.exists(ck):
            eng = StreamEngine.resume(ck, "quad_faults_test", 1e-9,
                                      mesh_resize=True,
                                      checkpoint_every=1,
                                      fault_injector=inj,
                                      quarantine=True, **kw)
        else:
            eng = StreamEngine("quad_faults_test", 1e-9,
                               checkpoint_path=ck, checkpoint_every=1,
                               fault_injector=inj, quarantine=True,
                               **kw)
        return _drive(eng, REQS6, ARR6)

    def resize_fn(exc):
        state["n"] = exc.surviving
        return loop

    sup = guard.Supervisor(loop, resize_fn=resize_fn,
                           telemetry=Telemetry(), log=lambda m: None,
                           sleep=lambda s: None)
    res = sup.run()
    assert sup.recoveries == [("chip_loss", "resize_resume")]
    assert state["n"] == 7
    assert np.array_equal(res.areas, base.areas)
    assert len(res.completed) == len(REQS6)


def test_stream_dd_resize_resume_ds_walker_contract(tmp_path):
    """With the ds walker engaged (real transcendental family) the
    leaf->engine assignment is schedule-dependent, so resize-resume
    meets the documented ~1e-9 contract rather than bit-identity."""
    reqs = [(float(t), (1e-3, 1.0))
            for t in 1.0 + np.arange(6) / 6.0]
    kw = dict(DD_KW)
    base = StreamEngine("sin_recip_scaled", 1e-9, **kw).run(
        reqs, arrival_phase=ARR6)
    ck = str(tmp_path / "ds.ckpt")
    eng = StreamEngine("sin_recip_scaled", 1e-9, checkpoint_path=ck,
                       checkpoint_every=1, **kw)
    with pytest.raises(RuntimeError, match="simulated crash"):
        eng.run(reqs, arrival_phase=ARR6, _crash_after_phases=3)
    eng2 = StreamEngine.resume(ck, "sin_recip_scaled", 1e-9,
                               mesh_resize=True, checkpoint_every=1,
                               **dict(kw, n_devices=7))
    res = _drive(eng2, reqs, ARR6)
    assert len(res.completed) == len(reqs)
    assert np.max(np.abs(res.areas - base.areas)) < 3e-9


def test_batch_dd_resize_resume_and_identity_drift(tmp_path):
    """The batch dd walker resumes its leg snapshot onto a SMALLER
    mesh (ds-walker workload: the documented ~1e-9 contract — the
    dyadic bit-identity half lives on the stream tests above, where
    the walker engages; this family's multi-cycle run is what makes
    the leg snapshot exist at all). Also pins: without mesh_resize the
    mismatch refuses, and WITH it any non-n_dev identity drift (eps)
    still refuses."""
    from ppls_tpu.parallel.sharded_walker import (
        integrate_family_walker_dd, resume_family_walker_dd)
    theta = np.array([1.0, 1.25, 1.5, 2.0])
    kw = dict(WKW, chunk=1 << 8)
    base = integrate_family_walker_dd("sin_recip_scaled", theta,
                                      BOUNDS, 1e-7, n_devices=8, **kw)
    path = str(tmp_path / "dd.ckpt")
    with pytest.raises(RuntimeError, match="simulated crash"):
        integrate_family_walker_dd(
            "sin_recip_scaled", theta, BOUNDS, 1e-7, n_devices=8,
            checkpoint_path=path, checkpoint_every=1,
            _crash_after_legs=1, **kw)
    # without the flag: the historical refusal, unchanged
    with pytest.raises(ValueError, match="different run"):
        resume_family_walker_dd(path, "sin_recip_scaled", theta,
                                BOUNDS, 1e-7, n_devices=7, **kw)
    # with the flag: any OTHER identity drift still refuses
    with pytest.raises(ValueError, match="different run"):
        resume_family_walker_dd(path, "sin_recip_scaled", theta,
                                BOUNDS, 1e-8, n_devices=7,
                                mesh_resize=True, **kw)
    res = resume_family_walker_dd(
        path, "sin_recip_scaled", theta, BOUNDS, 1e-7,
        n_devices=7, mesh_resize=True, **kw)
    assert np.max(np.abs(res.areas - base.areas)) < 3e-9


# ---------------------------------------------------------------------------
# serve CLI: fault plan drains to a correct summary (tentpole wiring)
# ---------------------------------------------------------------------------

@pytest.mark.nan_injection
def test_serve_cli_fault_plan_drains_green(tmp_path, capsys):
    """`ppls-tpu serve --fault-plan ...` with a crash + a poisoned
    request: the auto-armed supervisor recovers the crash from the
    snapshot, the poison retires as a failed record, and the summary
    reports the recovery story — no operator intervention."""
    from ppls_tpu import __main__ as cli
    ck = str(tmp_path / "cli.ckpt")
    rc = cli.main([
        "serve", "--synthetic", "6", "--arrival-rate", "2",
        "--seed", "0", "--eps", "1e-6", "-a", "1e-2", "-b", "1.0",
        "--slots", "8", "--chunk", "512", "--capacity", "65536",
        "--lanes", "256", "--refill-slots", "2",
        "--checkpoint", ck, "--checkpoint-every", "1",
        "--watchdog", "60",
        "--fault-plan",
        '[{"kind": "nan_poison", "at": 1}, {"kind": "crash", "at": 3}]',
        ])
    assert rc == 0
    lines = [json.loads(ln) for ln
             in capsys.readouterr().out.strip().splitlines()]
    summary = lines[-1]
    assert summary["summary"] and summary["supervised"]
    assert summary["completed"] == 6
    assert summary["failed"] == 1
    assert {r["action"] for r in summary["recoveries"]} \
        == {"backoff_resume"}
    assert {e["kind"] for e in summary["faults_injected"]} \
        == {"nan_poison", "crash"}
    # the poisoned rid reports area null + failed, exactly once among
    # the FINAL dedupe-by-rid view; healthy rids report finite areas
    by_rid = {}
    for d in lines[:-1]:
        by_rid[d["rid"]] = d          # last write wins (dedupe rule)
    assert by_rid[1]["failed"] and by_rid[1]["area"] is None
    assert all(isinstance(by_rid[r]["area"], float)
               for r in by_rid if r != 1)
