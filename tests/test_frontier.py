"""Golden parity with the reference (SURVEY.md §0 verified ground truth):
Area=7583461.801486 at printed precision, 6567 tasks (3283 splits + 3284
leaves), depth 14, 15 wavefront rounds, peak frontier 1642."""

import numpy as np
import pytest

from ppls_tpu import QuadConfig, integrate
from ppls_tpu.config import REFERENCE_CONFIG, Rule


@pytest.fixture(scope="module")
def reference_run():
    return integrate(REFERENCE_CONFIG)


def test_golden_area(reference_run):
    # The header transcript's printed value (aquadPartA.c:32) at %lf
    # precision (6 decimal places).
    assert f"{reference_run.area:.6f}" == "7583461.801486"


def test_golden_task_counts(reference_run):
    m = reference_run.metrics
    assert m.tasks == 6567
    assert m.splits == 3283
    assert m.leaves == 3284


def test_golden_depth_and_rounds(reference_run):
    m = reference_run.metrics
    assert m.rounds == 15
    assert m.max_depth == 14
    assert max(s.frontier_width for s in m.per_round) == 1642


def test_global_error_vs_analytic(reference_run):
    # SURVEY.md §0: global abs error ~0.44 — eps is a local tolerance.
    assert reference_run.exact is not None
    assert abs(reference_run.global_error - 0.439990) < 1e-5


def test_eval_count_minimal(reference_run):
    # 3 evals per task (minimal), not the reference's 5 (SURVEY.md §2).
    assert reference_run.metrics.integrand_evals == 6567 * 3


def test_simpson_beats_trapezoid_globally():
    trap = integrate(REFERENCE_CONFIG)
    simp = integrate(REFERENCE_CONFIG.replace(rule=Rule.SIMPSON))
    assert simp.global_error < trap.global_error
    assert simp.metrics.tasks < trap.metrics.tasks  # fewer, smarter tasks


def test_sin_config():
    res = integrate(QuadConfig(integrand="sin", a=0.0, b=1.0, eps=1e-6))
    assert abs(res.area - res.exact) < 1e-4  # local tol -> small global err


def test_resume_midway_matches_full_run():
    # Checkpointability of the engine state: stop after round 5, resume
    # with the saved frontier/accumulator, and land on the identical area.
    from ppls_tpu.runtime.host_frontier import integrate as run

    full = run(REFERENCE_CONFIG)

    saved = {}

    class Stop(Exception):
        pass

    def hook(round_idx, frontier, acc, metrics):
        if round_idx == 5:
            saved["frontier"] = frontier.copy()
            saved["acc"] = acc
            raise Stop

    with pytest.raises(Stop):
        run(REFERENCE_CONFIG, on_round=hook)

    resumed = run(REFERENCE_CONFIG, frontier=saved["frontier"],
                  area_acc=saved["acc"])
    assert resumed.area == full.area


def test_deterministic_across_runs():
    a1 = integrate(REFERENCE_CONFIG).area
    a2 = integrate(REFERENCE_CONFIG).area
    assert a1 == a2  # bit-identical, unlike MPI arrival-order sums


def test_runge_adaptive():
    res = integrate(QuadConfig(integrand="runge", a=-1.0, b=1.0, eps=1e-8,
                               rule=Rule.SIMPSON))
    assert res.global_error < 1e-6
