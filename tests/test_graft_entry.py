"""The driver's entry points must stay runnable — these are the two
functions the round driver actually executes (`__graft_entry__.entry` and
`__graft_entry__.dryrun_multichip`), so CI runs them too (VERDICT r1 weak
point 7: the one thing the driver calls was the one thing CI didn't run).
"""

import sys
from pathlib import Path

import jax
import numpy as np

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import __graft_entry__ as graft  # noqa: E402


def test_entry_compiles_and_steps():
    fn, args = graft.entry()
    jitted = jax.jit(fn)
    out = jitted(*args)
    state = jax.device_get(out)
    assert int(state.tasks) > 0
    assert np.all(np.isfinite(np.asarray(state.acc)))


def test_dryrun_multichip_inprocess():
    # The conftest exposes 8 virtual CPU devices, so this exercises the
    # in-process path — the same sharded program the driver validates.
    graft.dryrun_multichip(8)


def test_dryrun_multichip_subprocess():
    # Ask for more devices than are visible to force the subprocess
    # re-exec path — the one the driver hits on the 1-TPU bench host.
    graft.dryrun_multichip(16)
