"""tools/graftlint: each GL rule catches its deliberately-broken
fixture and stays silent on the fixed twin.

Pure AST analysis — no jax execution — so these run in milliseconds.
The fixtures are small temp packages shaped like the real modules
(``pkg/parallel/...``), because GL02/GL04 scope by path convention.
The GL01 fixture reproduces the PR-2 bug shape: ``refill_slots``
changing the meaning of persisted state without joining the snapshot
identity surface.
"""

import json
import os
import subprocess
import sys
import textwrap

import pytest

from tools.graftlint.core import (load_baseline, run_lint,
                                  split_new_and_known, write_baseline)


def _mkpkg(tmp_path, files):
    """files: {relative path under pkg/: source}. Returns pkg dir."""
    root = tmp_path / "pkg"
    for rel, src in files.items():
        p = root / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    return str(root)


def _codes(violations):
    return sorted({v.code for v in violations})


# ---------------------------------------------------------------------------
# GL01 — snapshot-identity completeness (the PR-2 refill_slots shape)
# ---------------------------------------------------------------------------

GL01_BROKEN = """
    from typing import NamedTuple

    class _StreamCarry(NamedTuple):
        bag_l: object
        acc: object
        tasks: object
        refill_slots: object    # <- PR-2 shape: never persisted

    def run_cycles(c: _StreamCarry):
        return c

    def integrate(state, checkpoint_path):
        out = run_cycles(state)
        identity = {"engine": "walker", "eps": 1e-6}
        save_family_checkpoint(
            checkpoint_path, identity=identity,
            bag_cols={"l": out.bag_l}, count=1, acc=out.acc,
            totals={"tasks": 0})
        return out
"""


def test_gl01_catches_missing_carry_field(tmp_path):
    pkg = _mkpkg(tmp_path, {"parallel/walker.py": GL01_BROKEN})
    got = [v for v in run_lint(pkg) if v.code == "GL01"]
    assert len(got) == 1, got
    assert got[0].symbol == "_StreamCarry.refill_slots"
    assert "refill_slots" in got[0].message
    # bag_l is covered via the l/r/th/meta alias map, acc and tasks
    # via the save call's keywords/strings — only the PR-2 field fires


def test_gl01_fixed_by_joining_identity(tmp_path):
    fixed = GL01_BROKEN.replace(
        '{"engine": "walker", "eps": 1e-6}',
        '{"engine": "walker", "eps": 1e-6, "refill_slots": 2}')
    pkg = _mkpkg(tmp_path, {"parallel/walker.py": fixed})
    assert [v for v in run_lint(pkg) if v.code == "GL01"] == []


def test_gl01_ignores_kernel_internal_carries(tmp_path):
    # A carry never referenced by snapshot code (the _WalkCarry shape:
    # folded back into the bag before any checkpoint) is out of scope.
    src = GL01_BROKEN + """
    class _InnerCarry(NamedTuple):
        scratch: object

    def _kernel_loop(c: _InnerCarry):
        return c
    """
    pkg = _mkpkg(tmp_path, {"parallel/walker.py": src})
    got = [v for v in run_lint(pkg) if v.code == "GL01"]
    assert [v.symbol for v in got] == ["_StreamCarry.refill_slots"]


# ---------------------------------------------------------------------------
# GL02 — f64 dtype discipline
# ---------------------------------------------------------------------------

GL02_BROKEN = """
    import jax.numpy as jnp

    def seed(n):
        a = jnp.zeros(n)                      # dtype-less
        b = jnp.zeros(n, jnp.float64)         # ok: positional dtype
        c = jnp.full(n, 0.5, dtype=jnp.float64)   # ok: kw dtype
        d = jnp.asarray([1.0, 2.0])           # dtype-less literal
        e = jnp.asarray(n)                    # ok: inherits
        return a, b, c, d, e

    def downcast(x):
        return x.astype(jnp.float32)          # f32 in a numeric path
"""


def test_gl02_catches_dtype_less_and_f32(tmp_path):
    pkg = _mkpkg(tmp_path, {"parallel/num.py": GL02_BROKEN})
    got = [v for v in run_lint(pkg) if v.code == "GL02"]
    syms = sorted(v.symbol for v in got)
    assert syms == ["downcast:float32", "seed:dtype-less-asarray",
                    "seed:dtype-less-zeros"], got


def test_gl02_scoped_to_numeric_paths(tmp_path):
    # the same source outside parallel/ and ops/ is not in scope
    pkg = _mkpkg(tmp_path, {"utils/num.py": GL02_BROKEN})
    assert [v for v in run_lint(pkg) if v.code == "GL02"] == []


def test_gl02_ds_limb_modules_exempt_from_f32(tmp_path):
    # ops/ds_kernel.py IS f32 by representation — only the dtype-less
    # creation check applies there, not the float32 check
    pkg = _mkpkg(tmp_path, {"ops/ds_kernel.py": GL02_BROKEN})
    syms = sorted(v.symbol for v in run_lint(pkg) if v.code == "GL02")
    assert syms == ["seed:dtype-less-asarray", "seed:dtype-less-zeros"]


def test_gl02_scout_surface_declared_module_carved_out(tmp_path):
    # round 12: ops/scout_kernel.py is on the DECLARED scout-dtype
    # surface — the float32 check is carved out there, but the
    # dtype-less-creation check still applies (a declaration is not a
    # blanket exemption)
    pkg = _mkpkg(tmp_path, {"ops/scout_kernel.py": GL02_BROKEN})
    syms = sorted(v.symbol for v in run_lint(pkg) if v.code == "GL02")
    assert syms == ["seed:dtype-less-asarray", "seed:dtype-less-zeros"]


def test_gl02_f32_outside_declared_scout_surface_still_fails(tmp_path):
    # an UNDECLARED scout-flavored module gets no carve-out: the
    # surface is a reviewed allowlist (module + symbol), so deliberate
    # f32 added anywhere else must either join the declaration (a
    # code-reviewed diff of GL02_SCOUT_SURFACE) or fail the lint —
    # the baseline shrinks or holds, it never silently grows
    pkg = _mkpkg(tmp_path, {"ops/scout_helpers.py": GL02_BROKEN,
                            "parallel/scout_pass.py": GL02_BROKEN})
    syms = sorted(v.symbol for v in run_lint(pkg) if v.code == "GL02")
    assert syms.count("downcast:float32") == 2, syms


def test_gl02_scout_surface_entries_carry_reasons():
    # every declared (module, symbol) pair must state WHY f32 is
    # deliberate there — an empty reason is an undocumented exemption
    from tools.graftlint.rules import GL02_SCOUT_SURFACE
    assert GL02_SCOUT_SURFACE, "the scout surface declaration is gone"
    for module, symbols in GL02_SCOUT_SURFACE.items():
        assert symbols, f"{module}: empty symbol list"
        for sym, reason in symbols.items():
            assert isinstance(reason, str) and len(reason) > 20, \
                f"{module}:{sym} lacks a substantive reason"


# ---------------------------------------------------------------------------
# GL03 — host sync reachable from a jitted root
# ---------------------------------------------------------------------------

GL03_BROKEN = """
    import functools
    import jax
    import numpy as np

    def helper(x):
        return np.asarray(x)                  # host sync, reachable

    def host_only(x):
        return np.asarray(x)                  # NOT reachable: silent

    @functools.partial(jax.jit, static_argnames=("n",))
    def entry(x, *, n: int):
        y = helper(x)
        k = int(x)                            # coerces a traced value
        m = int(n)                            # ok: static config
        s = int(x.shape[0])                   # ok: shapes are static
        return y, k, m, s
"""


def test_gl03_walks_call_graph_from_jit_roots(tmp_path):
    pkg = _mkpkg(tmp_path, {"parallel/hot.py": GL03_BROKEN})
    got = [v for v in run_lint(pkg) if v.code == "GL03"]
    syms = sorted(v.symbol for v in got)
    assert syms == ["entry:int()", "helper:np.asarray"], got


def test_gl03_cross_module_reachability(tmp_path):
    pkg = _mkpkg(tmp_path, {
        "parallel/helpers.py": """
            import numpy as np

            def pull(x):
                return np.asarray(x)
        """,
        "parallel/hot.py": """
            import functools
            import jax
            from pkg.parallel.helpers import pull

            @functools.partial(jax.jit, static_argnames=())
            def entry(x):
                return pull(x)
        """,
    })
    got = [v for v in run_lint(pkg) if v.code == "GL03"]
    assert [v.symbol for v in got] == ["pull:np.asarray"]
    assert got[0].path.endswith("helpers.py")


def test_gl03_jit_builder_roots(tmp_path):
    # the sharded-engine shape: jax.jit(wrapper(body)) — body is a root
    pkg = _mkpkg(tmp_path, {"parallel/sharded_thing.py": """
        import jax

        def build(mesh):
            def body(x):
                return jax.device_get(x)      # sync inside the program
            return jax.jit(wrap(body))
    """})
    got = [v for v in run_lint(pkg) if v.code == "GL03"]
    assert [v.symbol for v in got] == ["body:jax.device_get"]


# ---------------------------------------------------------------------------
# GL04 — uncounted collectives in the dd engine
# ---------------------------------------------------------------------------

GL04_BROKEN = """
    from jax import lax

    def bad_balance(x, axis):
        g = lax.all_gather(x, axis)           # uncounted collective
        return lax.psum(g, axis)

    def good_balance(x, axis, crounds):
        g = lax.all_gather(x, axis)
        return lax.psum(g, axis), crounds + 1
"""


def test_gl04_catches_uncounted_collectives(tmp_path):
    pkg = _mkpkg(tmp_path, {"parallel/sharded_walker.py": GL04_BROKEN})
    got = [v for v in run_lint(pkg) if v.code == "GL04"]
    assert [v.symbol for v in got] == ["bad_balance"]
    assert "2 collective(s)" in got[0].message


def test_gl04_scoped_to_dd_engine(tmp_path):
    # collectives in the wavefront/bag engines are not crounds-audited
    pkg = _mkpkg(tmp_path, {"parallel/sharded_bag.py": GL04_BROKEN})
    assert [v for v in run_lint(pkg) if v.code == "GL04"] == []


def test_gl04_docstring_mention_does_not_count(tmp_path):
    # prose is not accounting: a docstring saying "crounds is handled
    # by the caller" must not suppress the rule — the allowlist (with
    # a reviewable reason) is the only caller-counts-it escape hatch
    src = GL04_BROKEN.replace(
        "def bad_balance(x, axis):",
        'def bad_balance(x, axis):\n'
        '        "crounds is handled by the caller, trust me"')
    pkg = _mkpkg(tmp_path, {"parallel/sharded_walker.py": src})
    got = [v for v in run_lint(pkg) if v.code == "GL04"]
    assert [v.symbol for v in got] == ["bad_balance"]


# ---------------------------------------------------------------------------
# GL05 — static-arg drift
# ---------------------------------------------------------------------------

GL05_BROKEN = """
    import functools
    import jax
    from typing import Callable

    @functools.partial(jax.jit, static_argnames=("f", "epz"))
    def run(x, *, f: Callable, eps: float = 1e-6):
        return f(x) * eps

    @functools.partial(jax.jit, static_argnames=("n",))
    def rep(x, *, n: int):
        return x * n

    def storm(xs):
        out = []
        for i in range(8):
            out.append(rep(xs, n=i))          # recompiles per iter
        return out

    def fine(xs, n):
        return [rep(x, n=n) for x in xs]      # static is loop-invariant
"""


def test_gl05_catches_all_three_drifts(tmp_path):
    pkg = _mkpkg(tmp_path, {"parallel/cfg.py": GL05_BROKEN})
    got = sorted(v.symbol for v in run_lint(pkg) if v.code == "GL05")
    assert got == ["run:eps:undeclared-static",
                   "run:epz:not-a-param",
                   "storm:rep.n:loop-varying"], got


def test_gl05_positional_config_params_flagged(tmp_path):
    # config leaks through positional-or-keyword params just the same
    # as through keyword-only ones
    pkg = _mkpkg(tmp_path, {"parallel/poscfg.py": """
        import functools
        import jax

        @functools.partial(jax.jit, static_argnames=())
        def run(x, eps: float = 1e-7):
            return x * eps
    """})
    got = [v for v in run_lint(pkg) if v.code == "GL05"]
    assert [v.symbol for v in got] == ["run:eps:undeclared-static"]


def test_gl05_call_sites_resolve_through_imports(tmp_path):
    # bare-name coincidences must not match: an unresolvable
    # obj.method(...) and a same-named NON-jitted local function are
    # not the jitted `rep` — only the import-resolved call fires
    pkg = _mkpkg(tmp_path, {
        "parallel/cfg.py": """
            import functools
            import jax

            @functools.partial(jax.jit, static_argnames=("n",))
            def rep(x, *, n: int):
                return x * n
        """,
        "parallel/other.py": """
            from pkg.parallel.cfg import rep

            def local_storm(xs):
                return [rep(xs, n=i) for i in range(4)]
        """,
        "parallel/decoy.py": """
            def rep(x, *, n):
                return x + n              # NOT jitted: loop-feeding ok

            def fine(xs, obj):
                out = []
                for i in range(4):
                    out.append(rep(xs, n=i))
                    out.append(obj.rep(xs, n=i))   # unresolvable attr
                return out
        """,
    })
    got = [v for v in run_lint(pkg) if v.symbol.endswith("loop-varying")]
    assert [v.symbol for v in got] == ["local_storm:rep.n:loop-varying"]
    assert got[0].path.endswith("other.py")


# ---------------------------------------------------------------------------
# GL06 — telemetry publishes only at host boundaries
# ---------------------------------------------------------------------------

GL06_BROKEN = """
    import functools
    import jax
    from pkg.obs.telemetry import default_telemetry

    def publish(x):
        tel = default_telemetry()             # obs name, reachable
        tel.event("phase", tasks=x)           # emit inside the trace
        return x

    @functools.partial(jax.jit, static_argnames=())
    def entry(x):
        return publish(x)

    def boundary_hook(row):
        # the sanctioned shape: a host boundary hook publishing values
        # the boundary already fetched — NOT reachable from any root
        tel = default_telemetry()
        tel.event("phase", tasks=row)
        tel.registry.counter("t").inc(row)
"""


def test_gl06_catches_telemetry_in_traced_path(tmp_path):
    pkg = _mkpkg(tmp_path, {"parallel/hot.py": GL06_BROKEN})
    got = [v for v in run_lint(pkg) if v.code == "GL06"]
    syms = sorted(v.symbol for v in got)
    # both the obs-imported name call and the .event emit fire; the
    # boundary hook (unreachable from the jit root) stays silent
    assert syms == ["publish:default_telemetry", "publish:event"], got
    assert "trace time" in got[0].message


def test_gl06_fixed_by_moving_to_boundary_hook(tmp_path):
    # the fixed twin: the jitted entry no longer calls the publisher —
    # telemetry only happens in the host boundary hook
    fixed = GL06_BROKEN.replace("return publish(x)", "return x")
    pkg = _mkpkg(tmp_path, {"parallel/hot.py": fixed})
    assert [v for v in run_lint(pkg) if v.code == "GL06"] == []


def test_gl06_api_names_need_obs_import(tmp_path):
    # `.inc()` / `.observe()` attribute spellings only count in modules
    # that bind something from obs — a jax `.at[i].set()`-style
    # coincidence in a non-telemetry module must not fire
    src = """
        import functools
        import jax

        class Thing:
            def inc(self, n):
                return n

        def helper(x, t: "Thing"):
            t.inc(1)                      # same spelling, not obs
            return x

        @functools.partial(jax.jit, static_argnames=())
        def entry(x):
            return helper(x, Thing())
    """
    pkg = _mkpkg(tmp_path, {"parallel/hot.py": src})
    assert [v for v in run_lint(pkg) if v.code == "GL06"] == []


def test_gl06_module_alias_calls_flagged(tmp_path):
    # obs reached through a module alias (`from pkg.obs import
    # telemetry as t; t.default_telemetry()`) fires too
    pkg = _mkpkg(tmp_path, {"parallel/hot.py": """
        import functools
        import jax
        from pkg.obs import telemetry as t

        @functools.partial(jax.jit, static_argnames=())
        def entry(x):
            t.default_telemetry()
            return x
    """})
    got = [v for v in run_lint(pkg) if v.code == "GL06"]
    assert [v.symbol for v in got] == ["entry:t.default_telemetry"]


GL06_CHIP_SPANS = """
    import functools
    import jax
    from pkg.obs.flight import ChipFlightRecorder

    def emit_chips(tel, fr, rows):
        # per-chip flight-recorder emit: sanctioned ONLY as a host
        # boundary hook
        fr.record_phase(0, wsteps=rows, tasks=rows, live_rows=rows,
                        bank_delta=rows)
        for chip, r in enumerate(rows):
            tel.span("chip", chip=chip).close(wsteps=r)

    @functools.partial(jax.jit, static_argnames=())
    def cycle(x, tel, fr):
        emit_chips(tel, fr, [x])        # traced path: must be flagged
        return x

    def boundary_hook(tel, fr, rows):
        # the fixed shape: the same emits, unreachable from any root
        emit_chips(tel, fr, rows)
"""


def test_gl06_flags_per_chip_span_emits_in_traced_path(tmp_path):
    """Round-11 fixture: the flight recorder's per-chip span emit
    sites (record_phase, .span('chip')) obey the boundary-hook-only
    rule — inside a jit-reachable function they are violations."""
    pkg = _mkpkg(tmp_path, {"parallel/hot.py": GL06_CHIP_SPANS})
    got = sorted(v.symbol for v in run_lint(pkg) if v.code == "GL06")
    assert "emit_chips:record_phase" in got, got
    assert "emit_chips:span" in got, got


def test_gl06_per_chip_span_boundary_hook_clean(tmp_path):
    # the fixed twin: drop the traced call — the boundary hook's
    # identical emits stay silent (0 new baseline entries)
    fixed = GL06_CHIP_SPANS.replace(
        "emit_chips(tel, fr, [x])        # traced path: must be flagged",
        "pass")
    pkg = _mkpkg(tmp_path, {"parallel/hot.py": fixed})
    assert [v for v in run_lint(pkg) if v.code == "GL06"] == []


GL06_ROUND19_EMITS = """
    import functools
    import jax
    from pkg.obs.telemetry import Telemetry

    def trace_request(tel, slo, fed, rid, dump):
        # the round-19 emit surface: request-trace helpers, the SLO
        # burn evaluator, and the federation merge — boundary-hook
        # only, like every other telemetry publish
        span = tel.request_span(rid, tenant="a")
        tel.request_event(span, "admit", rid=rid)
        slo.evaluate_slo(rid)
        fed.ingest_dump("0", dump)

    @functools.partial(jax.jit, static_argnames=())
    def cycle(x, tel, slo, fed):
        trace_request(tel, slo, fed, x, {})   # traced path: flagged
        return x

    def boundary_hook(tel, slo, fed, rid, dump):
        trace_request(tel, slo, fed, rid, dump)
"""


def test_gl06_flags_round19_emit_sites_in_traced_path(tmp_path):
    """Round-19 fixture: the NEW emit sites — request_span /
    request_event (trace context), evaluate_slo (the burn evaluator),
    ingest_dump (the federation merge) — are on the GL06 API surface:
    reachable from a jitted root, each is a violation."""
    pkg = _mkpkg(tmp_path, {"parallel/hot.py": GL06_ROUND19_EMITS})
    got = sorted(v.symbol for v in run_lint(pkg) if v.code == "GL06")
    assert "trace_request:request_span" in got, got
    assert "trace_request:request_event" in got, got
    assert "trace_request:evaluate_slo" in got, got
    assert "trace_request:ingest_dump" in got, got


def test_gl06_round19_emit_sites_boundary_hook_clean(tmp_path):
    # the fixed twin: unreachable from the jit root, same emits stay
    # silent — the baseline holds at 0 new entries
    fixed = GL06_ROUND19_EMITS.replace(
        "trace_request(tel, slo, fed, x, {})   # traced path: flagged",
        "pass")
    pkg = _mkpkg(tmp_path, {"parallel/hot.py": fixed})
    assert [v for v in run_lint(pkg) if v.code == "GL06"] == []


def test_gl06_real_package_clean():
    # the package-level acceptance: all telemetry publishes live in
    # boundary hooks (zero new baseline entries for GL06)
    from tools.graftlint.rules import rule_gl06
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    got = [v for v in run_lint(os.path.join(repo, "ppls_tpu"),
                               rules=(rule_gl06,))]
    assert got == [], "\n".join(v.render() for v in got)


# ---------------------------------------------------------------------------
# pragmas, baseline workflow, and the real package
# ---------------------------------------------------------------------------

def test_inline_pragma_suppresses(tmp_path):
    src = GL02_BROKEN.replace(
        "a = jnp.zeros(n)                      # dtype-less",
        "a = jnp.zeros(n)  # graftlint: GL02 (shape probe)")
    pkg = _mkpkg(tmp_path, {"parallel/num.py": src})
    syms = sorted(v.symbol for v in run_lint(pkg) if v.code == "GL02")
    assert "seed:dtype-less-zeros" not in syms


def test_pragma_reason_cannot_escalate_to_off(tmp_path):
    # "off" inside a parenthesized REASON is prose, not a directive:
    # a GL03 pragma with such a reason must not suppress the line's
    # GL02 violation too
    src = GL02_BROKEN.replace(
        "a = jnp.zeros(n)                      # dtype-less",
        "a = jnp.zeros(n)  # graftlint: GL03 (off the hot path)")
    pkg = _mkpkg(tmp_path, {"parallel/num.py": src})
    syms = sorted(v.symbol for v in run_lint(pkg) if v.code == "GL02")
    assert "seed:dtype-less-zeros" in syms


def test_baseline_grandfathers_and_reports_stale(tmp_path):
    pkg = _mkpkg(tmp_path, {"parallel/num.py": GL02_BROKEN})
    violations = run_lint(pkg)
    bpath = str(tmp_path / "baseline.json")
    write_baseline(bpath, violations)
    baseline = load_baseline(bpath)
    # all grandfathered: nothing new
    new, known, stale = split_new_and_known(violations, baseline)
    assert new == [] and len(known) == len(violations) and stale == []
    # fix one site -> its entry is reported stale, still nothing new
    fixed = GL02_BROKEN.replace("x.astype(jnp.float32)", "x")
    (tmp_path / "pkg/parallel/num.py").write_text(textwrap.dedent(fixed))
    new, known, stale = split_new_and_known(run_lint(pkg), baseline)
    assert new == []
    assert any("downcast:float32" in k for k in stale)


def test_single_file_target_rejected(tmp_path):
    # a lone-file lint would skip the cross-module and path-scoped
    # rules and report a false clean — refuse it loudly
    pkg = _mkpkg(tmp_path, {"parallel/num.py": GL02_BROKEN})
    with pytest.raises(ValueError, match="package directory"):
        run_lint(os.path.join(pkg, "parallel", "num.py"))


def test_write_baseline_preserves_comment_block(tmp_path):
    pkg = _mkpkg(tmp_path, {"parallel/num.py": GL02_BROKEN})
    bpath = tmp_path / "baseline.json"
    bpath.write_text(json.dumps(
        {"version": 1, "_comment": ["policy text"], "grandfathered": []}))
    write_baseline(str(bpath), run_lint(pkg))
    data = json.loads(bpath.read_text())
    assert data["_comment"] == ["policy text"]
    assert len(data["grandfathered"]) == 3


def test_violation_keys_are_line_free(tmp_path):
    # inserting code above a grandfathered site must not churn the key
    pkg = _mkpkg(tmp_path, {"parallel/num.py": GL02_BROKEN})
    k1 = {v.key for v in run_lint(pkg)}
    (tmp_path / "pkg/parallel/num.py").write_text(
        "# a new leading comment\n\n" + textwrap.dedent(GL02_BROKEN))
    k2 = {v.key for v in run_lint(pkg)}
    assert k1 == k2


def test_real_package_clean_against_committed_baseline():
    """The acceptance gate: ppls_tpu lints clean against the committed
    allowlist — no new violations, no stale entries. This is the same
    check tools/ci.sh step 4 runs."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    violations = run_lint(os.path.join(repo, "ppls_tpu"))
    baseline = load_baseline(
        os.path.join(repo, "tools", "graftlint_baseline.json"))
    # staleness scoped to the AST tier, exactly like the CLI: the
    # baseline also carries deep/runtime-tier entries whose rules did
    # not run here (tests/test_graftlint_runtime.py covers that tier)
    from tools.graftlint.rules import AST_CODES
    new, known, stale = split_new_and_known(violations, baseline,
                                            AST_CODES)
    assert new == [], "\n".join(v.render() for v in new)
    assert stale == [], stale


def test_cli_exit_codes(tmp_path):
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    pkg = _mkpkg(tmp_path, {"parallel/num.py": GL02_BROKEN})
    env = dict(os.environ, PYTHONPATH=repo)
    r = subprocess.run(
        [sys.executable, "-m", "tools.graftlint", pkg],
        capture_output=True, text=True, cwd=repo, env=env)
    assert r.returncode == 1, r.stdout + r.stderr
    assert "NEW violation" in r.stdout
    # with a full baseline the same tree is green
    bpath = str(tmp_path / "b.json")
    write_baseline(bpath, run_lint(pkg))
    r2 = subprocess.run(
        [sys.executable, "-m", "tools.graftlint", pkg,
         "--baseline", bpath],
        capture_output=True, text=True, cwd=repo, env=env)
    assert r2.returncode == 0, r2.stdout + r2.stderr
    assert "0 new" in r2.stdout


# ---------------------------------------------------------------------------
# Round 13 — theta_block joins the policed surfaces (GL01 + GL05)
# ---------------------------------------------------------------------------

GL01_THETA_BROKEN = """
    from typing import NamedTuple

    class _ThetaCarry(NamedTuple):
        bag_l: object
        acc: object
        tasks: object
        theta_block: object    # <- round-13 shape: a theta-batched
        #                        schedule resumed scalar would blend
        #                        (m, T) and (m,) accumulator layouts

    def run_cycles(c: _ThetaCarry):
        return c

    def integrate(state, checkpoint_path):
        out = run_cycles(state)
        identity = {"engine": "walker", "eps": 1e-6}
        save_family_checkpoint(
            checkpoint_path, identity=identity,
            bag_cols={"l": out.bag_l}, count=1, acc=out.acc,
            totals={"tasks": 0})
        return out
"""


def test_gl01_catches_missing_theta_block(tmp_path):
    # the round-13 twin of the PR-2 refill_slots near-miss: a carry
    # whose theta_block never reaches the snapshot identity fires
    pkg = _mkpkg(tmp_path, {"parallel/walker.py": GL01_THETA_BROKEN})
    got = [v for v in run_lint(pkg) if v.code == "GL01"]
    assert [v.symbol for v in got] == ["_ThetaCarry.theta_block"], got
    assert "theta_block" in got[0].message


def test_gl01_theta_block_fixed_by_joining_identity(tmp_path):
    fixed = GL01_THETA_BROKEN.replace(
        '{"engine": "walker", "eps": 1e-6}',
        '{"engine": "walker", "eps": 1e-6, "theta_block": 256}')
    pkg = _mkpkg(tmp_path, {"parallel/walker.py": fixed})
    assert [v for v in run_lint(pkg) if v.code == "GL01"] == []


def test_gl05_theta_block_must_be_declared_static(tmp_path):
    # theta_block is compile-shape config (it sizes the union-vote
    # reshape and the (m, T) credit width): feeding it traced would
    # fail at trace time or silently recompile — GL05 demands the
    # static declaration, and the declared form is clean
    pkg = _mkpkg(tmp_path, {"parallel/tcfg.py": """
        import functools
        import jax

        @functools.partial(jax.jit, static_argnames=())
        def run_theta(x, theta_block: int = 1):
            return x * theta_block
    """})
    got = [v for v in run_lint(pkg) if v.code == "GL05"]
    assert [v.symbol for v in got] == \
        ["run_theta:theta_block:undeclared-static"], got

    pkg2 = _mkpkg(tmp_path, {"parallel/tcfg2.py": """
        import functools
        import jax

        @functools.partial(jax.jit, static_argnames=("theta_block",))
        def run_theta(x, theta_block: int = 1):
            return x * theta_block
    """})
    # (same tmp root as the broken fixture: assert only on tcfg2)
    assert [v for v in run_lint(pkg2)
            if v.code == "GL05" and "tcfg2" in v.path] == []


def test_gl05_theta_block_loop_fed_static_flagged(tmp_path):
    # sweeping theta_block from a loop variable recompiles per T —
    # exactly the bench-theta shape that must stay a per-T explicit
    # call, not a hidden loop-varying static
    pkg = _mkpkg(tmp_path, {"parallel/tsweep.py": """
        import functools
        import jax

        @functools.partial(jax.jit, static_argnames=("theta_block",))
        def run_theta(x, *, theta_block: int):
            return x * theta_block

        def sweep(xs):
            return [run_theta(xs, theta_block=t) for t in range(8)]
    """})
    got = [v for v in run_lint(pkg) if v.code == "GL05"]
    assert [v.symbol for v in got] == \
        ["sweep:run_theta.theta_block:loop-varying"], got


# ---------------------------------------------------------------------------
# Round 14 — the elastic mesh_resize compat rule vs the GL01 surface
# ---------------------------------------------------------------------------

GL01_RESIZE_BROKEN = """
    from typing import NamedTuple

    class _ElasticCarry(NamedTuple):
        bag_l: object
        acc: object
        n_dev: object    # <- mesh size: per-chip state the resume
        #                   must re-deal, so it is identity

    def run_cycles(c: _ElasticCarry):
        return c

    def integrate(state, checkpoint_path):
        out = run_cycles(state)
        identity = {"engine": "walker-dd", "eps": 1e-6}
        save_family_checkpoint(
            checkpoint_path, identity=identity,
            bag_cols={"l": out.bag_l}, count=1, acc=out.acc,
            totals={})
        return out

    def resume(path, identity):
        return load_family_checkpoint(path, identity,
                                      mesh_resize=True)
"""


def test_gl01_mesh_resize_keyword_does_not_cover_n_dev(tmp_path):
    # the round-14 compat rule relaxes the n_dev COMPARISON at load
    # time — it must not relax the GL01 surface: a dd carry whose
    # n_dev never reaches the identity dict still fires even though
    # the resume path spells "mesh_resize"
    pkg = _mkpkg(tmp_path,
                 {"parallel/sharded_walker.py": GL01_RESIZE_BROKEN})
    got = [v for v in run_lint(pkg) if v.code == "GL01"]
    assert [v.symbol for v in got] == ["_ElasticCarry.n_dev"], got


def test_gl01_mesh_resize_fixed_by_identity_key(tmp_path):
    # the real engines' shape: n_dev ON the identity (the elastic
    # loader then relaxes exactly that one key under mesh_resize)
    fixed = GL01_RESIZE_BROKEN.replace(
        '{"engine": "walker-dd", "eps": 1e-6}',
        '{"engine": "walker-dd", "eps": 1e-6, "n_dev": 8}')
    pkg = _mkpkg(tmp_path, {"parallel/sharded_walker.py": fixed})
    assert [v for v in run_lint(pkg) if v.code == "GL01"] == []


# ---------------------------------------------------------------------------
# Round 18 — the cluster_resize compat rule vs the GL01 surface
# ---------------------------------------------------------------------------

GL01_CLUSTER_BROKEN = """
    from typing import NamedTuple

    class _CoordCarry(NamedTuple):
        bag_l: object
        acc: object
        cluster: object  # <- the process->devices manifest: the
        #                   topology the resume must re-deal by, so
        #                   it is identity

    def run_cluster(c: _CoordCarry):
        return c

    def integrate(state, checkpoint_path):
        out = run_cluster(state)
        identity = {"engine": "cluster-stream", "eps": 1e-6}
        save_family_checkpoint(
            checkpoint_path, identity=identity,
            bag_cols={"l": out.bag_l}, count=1, acc=out.acc,
            totals={})
        return out

    def resume(path, identity):
        return load_family_checkpoint(path, identity,
                                      cluster_resize=True)
"""


def test_gl01_cluster_resize_keyword_does_not_cover_manifest(
        tmp_path):
    # the round-18 compat rule relaxes the `cluster` COMPARISON at
    # load time — it must not relax the GL01 surface: a coordinator
    # carry whose manifest never reaches the identity dict still
    # fires even though the resume path spells "cluster_resize"
    pkg = _mkpkg(tmp_path,
                 {"runtime/cluster.py": GL01_CLUSTER_BROKEN})
    got = [v for v in run_lint(pkg) if v.code == "GL01"]
    assert [v.symbol for v in got] == ["_CoordCarry.cluster"], got


def test_gl01_cluster_manifest_fixed_by_identity_key(tmp_path):
    # the real coordinator's shape: the manifest ON the identity (the
    # elastic loader then relaxes exactly that one key under
    # cluster_resize — cross-topology resume stays deliberate)
    fixed = GL01_CLUSTER_BROKEN.replace(
        '{"engine": "cluster-stream", "eps": 1e-6}',
        '{"engine": "cluster-stream", "eps": 1e-6,\n'
        '                    "cluster": {"processes": 2}}')
    pkg = _mkpkg(tmp_path, {"runtime/cluster.py": fixed})
    assert [v for v in run_lint(pkg) if v.code == "GL01"] == []


# ---------------------------------------------------------------------------
# Round 17 — GL11 lock discipline (the PR-10 ingest race shape)
# ---------------------------------------------------------------------------

GL11_BROKEN = """
    import threading

    class EngineHandle:
        def __init__(self):
            self._lock = threading.RLock()
            self._eng = None        # construction: not yet shared

        def publish(self, eng):
            with self._lock:
                self._eng = eng

        def ack_submit(self, d):
            # THE PR-10 RACE SHAPE: the shared handle read outside the
            # engine lock — between this read and eng.submit() the
            # serve loop can crash and clear the handle, so the ack
            # lands in a DEAD engine and vanishes at resume
            eng = self._eng
            return eng.submit(d)

        def clear_on_death(self):
            self._eng = None        # write outside the lock: same race
"""


def test_gl11_flags_unlocked_handle_touch(tmp_path):
    pkg = _mkpkg(tmp_path, {"runtime/ingest.py": GL11_BROKEN})
    got = sorted(v.symbol for v in run_lint(pkg) if v.code == "GL11")
    assert got == ["EngineHandle.ack_submit:_eng",
                   "EngineHandle.clear_on_death:_eng"], got


def test_gl11_fixed_by_taking_the_lock(tmp_path):
    fixed = GL11_BROKEN.replace(
        "            eng = self._eng\n"
        "            return eng.submit(d)",
        "            with self._lock:\n"
        "                eng = self._eng\n"
        "                return eng.submit(d)").replace(
        "            self._eng = None        "
        "# write outside the lock: same race",
        "            with self._lock:\n"
        "                self._eng = None")
    pkg = _mkpkg(tmp_path, {"runtime/ingest.py": fixed})
    assert [v for v in run_lint(pkg) if v.code == "GL11"] == []


def test_gl11_init_is_exempt(tmp_path):
    # only ack_submit/clear_on_death fire above — __init__'s unlocked
    # assignment is construction, the object is not yet shared (the
    # declared unlocked_ok exemption)
    pkg = _mkpkg(tmp_path, {"runtime/ingest.py": GL11_BROKEN})
    got = [v for v in run_lint(pkg) if v.code == "GL11"]
    assert not any("__init__" in v.symbol for v in got)


def test_gl11_scoped_to_declared_modules(tmp_path):
    # the same source outside the declared lock-map modules is not in
    # scope: the map is the reviewed declaration of where shared
    # mutable state lives
    pkg = _mkpkg(tmp_path, {"runtime/other.py": GL11_BROKEN})
    assert [v for v in run_lint(pkg) if v.code == "GL11"] == []


def test_gl11_lock_map_entries_carry_reasons():
    # every declared module must state WHY its guarded set is what it
    # is — an empty reason is an undocumented threading contract
    from tools.graftlint.rules import GL11_LOCK_MAP
    assert "runtime/ingest.py" in GL11_LOCK_MAP
    assert "runtime/stream.py" in GL11_LOCK_MAP
    for module, entry in GL11_LOCK_MAP.items():
        assert entry["locks"], f"{module}: no lock declared"
        assert isinstance(entry["reason"], str) \
            and len(entry["reason"]) > 40, \
            f"{module} lacks a substantive reason"
    # ingest.py's guarded set is the PR-10 race armor — it must never
    # silently empty out
    assert "_eng" in GL11_LOCK_MAP["runtime/ingest.py"]["guarded"]


# ---------------------------------------------------------------------------
# Round 17 — the functools.partial call-graph fix (GL03/GL06 BFS)
# ---------------------------------------------------------------------------

GL03_PARTIAL_BROKEN = """
    import functools
    import jax
    import numpy as np

    def helper(k, x):
        return np.asarray(x)          # host sync behind a partial

    @functools.partial(jax.jit, static_argnames=())
    def entry(x):
        cb = functools.partial(helper, 2)
        return cb(x)
"""


def test_gl03_resolves_functools_partial_targets(tmp_path):
    # pre-round-17 the BFS only followed direct calls: `cb(x)` is an
    # unresolvable local name, so helper never joined the reachable
    # set and its np.asarray was silently invisible
    pkg = _mkpkg(tmp_path, {"parallel/hot.py": GL03_PARTIAL_BROKEN})
    got = [v for v in run_lint(pkg) if v.code == "GL03"]
    assert [v.symbol for v in got] == ["helper:np.asarray"], got


def test_gl03_partial_fixed_twin_clean(tmp_path):
    fixed = GL03_PARTIAL_BROKEN.replace("return np.asarray(x)",
                                        "return x + k")
    pkg = _mkpkg(tmp_path, {"parallel/hot.py": fixed})
    assert [v for v in run_lint(pkg) if v.code == "GL03"] == []


def test_gl03_partial_cross_module(tmp_path):
    # the partial edge resolves through import bindings like a direct
    # call: partial(pull, ...) in hot.py reaches helpers.pull
    pkg = _mkpkg(tmp_path, {
        "parallel/helpers.py": """
            import numpy as np

            def pull(k, x):
                return np.asarray(x)
        """,
        "parallel/hot.py": """
            import functools
            import jax
            from pkg.parallel.helpers import pull

            @functools.partial(jax.jit, static_argnames=())
            def entry(x):
                cb = functools.partial(pull, 1)
                return cb(x)
        """,
    })
    got = [v for v in run_lint(pkg) if v.code == "GL03"]
    assert [v.symbol for v in got] == ["pull:np.asarray"]
    assert got[0].path.endswith("helpers.py")


# ---------------------------------------------------------------------------
# Round 17 — --prune-stale, --format json, tier-scoped staleness
# ---------------------------------------------------------------------------

def test_prune_stale_rewrites_baseline(tmp_path):
    pkg = _mkpkg(tmp_path, {"parallel/num.py": GL02_BROKEN})
    bpath = tmp_path / "baseline.json"
    bpath.write_text(json.dumps(
        {"version": 1, "_comment": ["policy text"], "grandfathered": []}))
    write_baseline(str(bpath), run_lint(pkg))
    # hand the surviving entry a reason so the prune must preserve it
    doc = json.loads(bpath.read_text())
    for e in doc["grandfathered"]:
        e["reason"] = f"reviewed: {e['key']}"
    bpath.write_text(json.dumps(doc))
    # fix one site -> its entry is stale
    fixed = GL02_BROKEN.replace("x.astype(jnp.float32)", "x")
    (tmp_path / "pkg/parallel/num.py").write_text(textwrap.dedent(fixed))
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, PYTHONPATH=repo)
    r = subprocess.run(
        [sys.executable, "-m", "tools.graftlint", pkg,
         "--baseline", str(bpath), "--prune-stale", "--quiet"],
        capture_output=True, text=True, cwd=repo, env=env)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "pruned 1 stale" in r.stdout
    data = json.loads(bpath.read_text())
    # shrink-only: the fixed site's entry dropped, survivors verbatim,
    # the _comment policy block untouched
    assert data["_comment"] == ["policy text"]
    keys = [e["key"] for e in data["grandfathered"]]
    assert len(keys) == 2 and not any("float32" in k for k in keys)
    assert all(e["reason"].startswith("reviewed:")
               for e in data["grandfathered"])
    # a second prune is a no-op (nothing stale left)
    r2 = subprocess.run(
        [sys.executable, "-m", "tools.graftlint", pkg,
         "--baseline", str(bpath), "--prune-stale", "--quiet"],
        capture_output=True, text=True, cwd=repo, env=env)
    assert r2.returncode == 0 and "pruned 0" in r2.stdout


def test_format_json_records_and_schema(tmp_path):
    from ppls_tpu.utils.artifact_schema import validate_graftlint_json
    pkg = _mkpkg(tmp_path, {"parallel/num.py": GL02_BROKEN})
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, PYTHONPATH=repo)
    r = subprocess.run(
        [sys.executable, "-m", "tools.graftlint", pkg,
         "--format", "json"],
        capture_output=True, text=True, cwd=repo, env=env)
    assert r.returncode == 1, r.stdout + r.stderr   # new violations
    doc = json.loads(r.stdout)
    assert validate_graftlint_json(doc) == []
    # one record per violation, keys match the text-mode identities
    text_keys = sorted(v.key for v in run_lint(pkg))
    assert sorted(v["key"] for v in doc["violations"]) == text_keys
    assert doc["ok"] is False and doc["deep"] is False
    assert doc["counts"]["new"] == len(text_keys)
    # grandfathering the lot flips ok without changing the record count
    bpath = str(tmp_path / "b.json")
    write_baseline(bpath, run_lint(pkg))
    r2 = subprocess.run(
        [sys.executable, "-m", "tools.graftlint", pkg,
         "--baseline", bpath, "--format", "json"],
        capture_output=True, text=True, cwd=repo, env=env)
    assert r2.returncode == 0
    doc2 = json.loads(r2.stdout)
    assert validate_graftlint_json(doc2) == []
    assert doc2["ok"] is True
    assert all(v["grandfathered"] and "reason" in v
               for v in doc2["violations"])


def test_graftlint_json_validator_rejects_inconsistency():
    from ppls_tpu.utils.artifact_schema import validate_graftlint_json
    doc = {"schema": "graftlint-v1", "target": "pkg", "deep": False,
           "violations": [
               {"key": "GL02:pkg/a.py:f:float32", "code": "GL02",
                "path": "pkg/a.py", "line": 3, "symbol": "f:float32",
                "message": "m", "grandfathered": False}],
           "stale": [], "counts": {"total": 1, "new": 1,
                                   "grandfathered": 0, "stale": 0},
           "ok": True}    # ok contradicts the 1 new record
    problems = validate_graftlint_json(doc)
    assert any("ok=True" in p for p in problems)
    doc["ok"] = False
    assert validate_graftlint_json(doc) == []
    doc["counts"]["new"] = 2        # counts no longer reconcile
    assert any("counts.new" in p
               for p in validate_graftlint_json(doc))


def test_stale_scoped_to_codes_checked():
    # a grandfathered DEEP entry must not read as stale on a run that
    # never executed the deep rules (and vice versa the deep run still
    # sees it): tier-scoped staleness keeps the shrink-only contract
    # honest across `--deep` and plain invocations
    baseline = {"GL07:ppls_tpu/parallel/sharded_walker.py:dd_refill:"
                "psum": "deep-tier entry"}
    new, known, stale = split_new_and_known(
        [], baseline, codes_checked=("GL01", "GL02"))
    assert stale == []
    new, known, stale = split_new_and_known(
        [], baseline, codes_checked=("GL01", "GL07"))
    assert len(stale) == 1


def test_write_baseline_preserves_out_of_scope_tier_entries(tmp_path):
    # review finding (round 17): an AST-only --write-baseline must
    # carry the grandfathered DEEP entries (GL07-GL10) forward — their
    # rules never ran, so regenerating from the AST-only violation
    # list alone would silently delete reviewed exceptions and fail
    # the next --deep run
    pkg = _mkpkg(tmp_path, {"parallel/num.py": GL02_BROKEN})
    bpath = tmp_path / "baseline.json"
    deep_entry = {"key": "GL07:pkg/parallel/sw.py:dd:psum",
                  "reason": "reviewed deep exception"}
    bpath.write_text(json.dumps(
        {"version": 1, "grandfathered": [deep_entry]}))
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, PYTHONPATH=repo)
    r = subprocess.run(
        [sys.executable, "-m", "tools.graftlint", pkg,
         "--baseline", str(bpath), "--write-baseline"],
        capture_output=True, text=True, cwd=repo, env=env)
    assert r.returncode == 0, r.stdout + r.stderr
    data = json.loads(bpath.read_text())
    keys = [e["key"] for e in data["grandfathered"]]
    # the 3 AST violations regenerated AND the deep entry preserved
    assert "GL07:pkg/parallel/sw.py:dd:psum" in keys
    assert len(keys) == 4, keys
    kept = [e for e in data["grandfathered"]
            if e["key"] == deep_entry["key"]]
    assert kept[0]["reason"] == "reviewed deep exception"


def test_prune_stale_with_json_format_keeps_stdout_parseable(tmp_path):
    # review finding (round 17): --prune-stale's notice must not
    # corrupt the --format json ledger on stdout
    from ppls_tpu.utils.artifact_schema import validate_graftlint_json
    pkg = _mkpkg(tmp_path, {"parallel/num.py": GL02_BROKEN})
    bpath = str(tmp_path / "b.json")
    write_baseline(bpath, run_lint(pkg))
    fixed = GL02_BROKEN.replace("x.astype(jnp.float32)", "x")
    (tmp_path / "pkg/parallel/num.py").write_text(textwrap.dedent(fixed))
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, PYTHONPATH=repo)
    r = subprocess.run(
        [sys.executable, "-m", "tools.graftlint", pkg,
         "--baseline", bpath, "--prune-stale", "--format", "json"],
        capture_output=True, text=True, cwd=repo, env=env)
    assert r.returncode == 0, r.stdout + r.stderr
    doc = json.loads(r.stdout)          # stdout is pure JSON
    assert validate_graftlint_json(doc) == []
    assert "pruned 1 stale" in r.stderr  # the notice moved to stderr
    assert doc["counts"]["stale"] == 0   # pruned before emission
