"""tools/graftlint/deep.py: the jaxpr-level semantic tier (GL07-GL10).

Two kinds of coverage, mirroring tests/test_graftlint.py's pattern:

* BROKEN+FIXED toy targets per rule — tiny traced programs with an
  injected uncounted psum (GL07), an undeclared f32→f64 origin (GL08),
  a left-behind debug callback (GL09), and a value baked through a
  closure cell (GL10, the `_tt_cell` hazard shape sharded_walker
  documents) — each tripping its rule, each with a clean twin;
* the REAL package: every committed engine probe traces, the dd
  census reconciles with the declared crounds model in BOTH modes,
  jaxpr hashes are value-stable for `_run_cycles` /
  `run_stream_cycle` / `build_dd_walker_run`, and the whole deep tier
  runs clean against the committed baseline (the same check ci.sh's
  deep-lint step runs).

The real-package traces are collected ONCE per module (the deep
tier's trace-reuse contract) — this file adds ~10 s to tier-1, not
~10 s per test.
"""

import functools
import importlib.util
import os
import textwrap

import jax
import jax.numpy as jnp
import pytest
from jax import lax
from jax.sharding import PartitionSpec as P

from tools.graftlint import deep
from tools.graftlint.core import load_baseline, split_new_and_known
from tools.graftlint.deep import (DEEP_CODES, GL07_CROUNDS_MODEL,
                                  GL08_DTYPE_SURFACE, collect_traces,
                                  rule_gl07, rule_gl08, rule_gl09,
                                  rule_gl10, run_deep)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def real_traces():
    """ONE trace pass over the committed engine probes, shared by
    every real-package test below (the ci.sh deep step gets the same
    reuse inside a single CLI invocation)."""
    return collect_traces()


# ---------------------------------------------------------------------------
# GL07 — collective census vs the crounds model
# ---------------------------------------------------------------------------

def _toy_dd_probe(extra_psum: bool):
    """A tiny shard_map program: one counted psum, plus an optionally
    INJECTED second one (the uncounted-collective shape GL04 cannot
    see once it hides inside the shard body)."""
    from ppls_tpu.parallel.mesh import make_mesh, shard_map_compat
    mesh = make_mesh(2)

    def body(x):
        s = x + lax.psum(x, "d")
        if extra_psum:
            s = s + lax.psum(2.0 * x, "d")   # injected, uncounted
        return s

    fn = jax.jit(shard_map_compat(body, mesh=mesh, in_specs=(P("d"),),
                                  out_specs=P("d"), check_vma=False))

    def ops(seed: int):
        return (jnp.arange(8, dtype=jnp.float64) + seed,)

    return ("toy.dd", fn, ops, "pkg/toy.py")


TOY_MODEL = {"toy.dd": {"collectives": {"psum": 1},
                        "reason": "one counted occupancy psum"}}


def test_gl07_trips_on_injected_uncounted_psum():
    traces = collect_traces([_toy_dd_probe(extra_psum=True)])
    got = list(rule_gl07(traces, model=TOY_MODEL))
    assert [v.symbol for v in got] == ["dd:psum"], got
    assert "UNCOUNTED" in got[0].message
    assert got[0].key == "GL07:pkg/toy.py:dd:psum"


def test_gl07_clean_when_census_matches_model():
    traces = collect_traces([_toy_dd_probe(extra_psum=False)])
    assert list(rule_gl07(traces, model=TOY_MODEL)) == []


def test_gl07_reports_stale_model_entries():
    # the model declares MORE than the program pays: the entry must
    # shrink (the census table follows the baseline's shrink-only
    # contract, loudly)
    traces = collect_traces([_toy_dd_probe(extra_psum=False)])
    fat = {"toy.dd": {"collectives": {"psum": 3}, "reason": "stale"}}
    got = list(rule_gl07(traces, model=fat))
    assert [v.symbol for v in got] == ["dd:psum:stale-model"], got


def test_gl07_single_chip_programs_must_census_empty():
    # a target ABSENT from the model gets an implicit empty census: a
    # collective in a single-chip engine program always flags
    traces = collect_traces([_toy_dd_probe(extra_psum=False)])
    got = list(rule_gl07(traces, model={}))
    assert [v.symbol for v in got] == ["dd:psum"]


def test_gl07_real_census_reconciles_both_dd_modes(real_traces):
    """The acceptance pin: the traced dd programs' collective censuses
    equal the declared crounds model EXACTLY, refill and legacy."""
    by_name = {t.name: t for t in real_traces}
    for name in ("sharded_walker.dd_refill", "sharded_walker.dd_legacy"):
        tr = by_name[name]
        assert tr.error is None, tr.error
        got = deep._census(tr.jaxprs[0].jaxpr, deep.COLLECTIVE_PRIMS)
        assert got == GL07_CROUNDS_MODEL[name]["collectives"], \
            (name, got)
    # and the single-chip programs pay no collectives at all
    for name in ("walker._run_cycles", "stream.run_stream_cycle",
                 "bag_engine._run_bag", "device_engine._run"):
        tr = by_name[name]
        assert deep._census(tr.jaxprs[0].jaxpr,
                            deep.COLLECTIVE_PRIMS) == {}, name


def test_gl07_model_entries_carry_reasons():
    for name, entry in GL07_CROUNDS_MODEL.items():
        assert isinstance(entry["reason"], str) \
            and len(entry["reason"]) > 40, \
            f"{name} lacks a substantive reconciliation reason"


# ---------------------------------------------------------------------------
# GL08 — f32→f64 dtype-flow audit
# ---------------------------------------------------------------------------

def _import_from_file(tmp_path, name: str, src: str):
    p = tmp_path / f"{name}.py"
    p.write_text(textwrap.dedent(src))
    spec = importlib.util.spec_from_file_location(name, str(p))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_gl08_trips_on_undeclared_f32_to_f64_origin(tmp_path):
    # the convert must ORIGINATE in a real source file so the jaxpr's
    # source_info points somewhere attributable — an undeclared module
    # promoting f32 into the f64 path flags
    mod = _import_from_file(tmp_path, "gl08_broken", """
        import jax.numpy as jnp

        def sneaky_promote(x):
            return x.astype(jnp.float64) * 2.0
    """)

    def ops(seed: int):
        return (jnp.ones(4, jnp.float32) + seed,)

    traces = collect_traces([("toy.conv", mod.sneaky_promote, ops,
                              "pkg/toy.py")])
    got = list(rule_gl08(traces))
    assert [v.symbol for v in got] == ["sneaky_promote:f32-to-f64"], got
    assert "declared dtype surface" in got[0].message


def test_gl08_declared_origin_is_clean(tmp_path):
    mod = _import_from_file(tmp_path, "gl08_fixed", """
        import jax.numpy as jnp

        def limb_promote(x):
            return x.astype(jnp.float64) * 2.0
    """)

    def ops(seed: int):
        return (jnp.ones(4, jnp.float32) + seed,)

    traces = collect_traces([("toy.conv", mod.limb_promote, ops,
                              "pkg/toy.py")])
    surface = dict(GL08_DTYPE_SURFACE)
    surface["gl08_fixed.py"] = {
        "symbols": ("limb_promote",),
        "reason": "test: declared exact-limb promotion"}
    assert list(rule_gl08(traces, surface=surface)) == []


def test_gl08_real_package_origins_all_declared(real_traces):
    # every f32→f64 edge in every traced engine program originates in
    # the declared surface (ds limbs / pow2 / scout / the walker's
    # reviewed limb functions) — zero baseline entries needed
    assert list(rule_gl08(real_traces)) == []


def test_gl08_surface_entries_carry_reasons():
    for module, entry in GL08_DTYPE_SURFACE.items():
        assert entry["symbols"], f"{module}: empty symbol list"
        assert isinstance(entry["reason"], str) \
            and len(entry["reason"]) > 30, \
            f"{module} lacks a substantive reason"


# ---------------------------------------------------------------------------
# GL09 — host-interop census
# ---------------------------------------------------------------------------

def test_gl09_trips_on_left_behind_debug_callback():
    def leaky(x):
        jax.debug.print("x = {x}", x=x)     # fires per execution
        return x * 2.0

    def ops(seed: int):
        return (jnp.arange(4, dtype=jnp.float64) + seed,)

    traces = collect_traces([("toy.leak", leaky, ops, "pkg/toy.py")])
    got = list(rule_gl09(traces))
    assert [v.symbol for v in got] == ["leak:debug_callback"], got


def test_gl09_clean_without_callbacks():
    def clean(x):
        return x * 2.0

    def ops(seed: int):
        return (jnp.arange(4, dtype=jnp.float64) + seed,)

    traces = collect_traces([("toy.clean", clean, ops, "pkg/toy.py")])
    assert list(rule_gl09(traces)) == []


def test_gl09_real_engine_programs_are_interop_free(real_traces):
    assert list(rule_gl09(real_traces)) == []


# ---------------------------------------------------------------------------
# GL10 — compile-once-by-construction
# ---------------------------------------------------------------------------

def test_gl10_trips_on_value_baked_through_closure():
    # the `_tt_cell` hazard shape (sharded_walker binds its theta
    # table as a per-CALL operand precisely to avoid this): a cell the
    # operand builder mutates bakes a VALUE into the traced program —
    # one recompile per distinct value in production
    cell = {}

    def baked(x):
        return x * cell["v"]

    def ops(seed: int):
        cell["v"] = 1.0 + seed
        return (jnp.arange(4, dtype=jnp.float64),)

    traces = collect_traces([("toy.baked", baked, ops, "pkg/toy.py")])
    got = list(rule_gl10(traces))
    assert [v.symbol for v in got] == ["baked:jaxpr-hash"], got
    assert "recompile" in got[0].message


def test_gl10_trips_on_value_fed_static():
    # the accidental-static shape proper: a per-request value declared
    # static_argnames — the two traces bake different literals
    @functools.partial(jax.jit, static_argnames=("v",))
    def prog(x, *, v: float):
        return x * v

    def fn(x, seed_v):
        del seed_v      # the harness passes the value OUT of band...
        return prog(x, v=float(_gl10_static_cell["v"]))

    _gl10_static_cell = {}

    def ops(seed: int):
        _gl10_static_cell["v"] = 1.0 + seed
        return (jnp.arange(4, dtype=jnp.float64),
                jnp.asarray(seed, jnp.int32))

    traces = collect_traces([("toy.static", fn, ops, "pkg/toy.py")])
    got = list(rule_gl10(traces))
    assert [v.symbol for v in got] == ["static:jaxpr-hash"], got


def test_gl10_clean_when_value_is_traced_operand():
    def fixed(x, v):
        return x * v

    def ops(seed: int):
        return (jnp.arange(4, dtype=jnp.float64),
                jnp.asarray(1.0 + seed, jnp.float64))

    traces = collect_traces([("toy.fixed", fixed, ops, "pkg/toy.py")])
    assert list(rule_gl10(traces)) == []


def test_gl10_reports_trace_failures():
    def broken(x):
        raise TypeError("unhashable static drifted in")

    def ops(seed: int):
        return (jnp.arange(4, dtype=jnp.float64),)

    traces = collect_traces([("toy.broken", broken, ops,
                              "pkg/toy.py")])
    got = list(rule_gl10(traces))
    assert [v.symbol for v in got] == ["broken:trace-error"], got
    assert "unhashable" in got[0].message


def test_gl10_real_engine_programs_value_stable(real_traces):
    """The acceptance pin: `_run_cycles`, `run_stream_cycle`, and
    `build_dd_walker_run` (both modes) — plus the bag and wavefront
    programs — trace to IDENTICAL jaxprs across differing operand
    values. No accidental statics anywhere in the engine surface."""
    names = {t.name for t in real_traces}
    for required in ("walker._run_cycles", "stream.run_stream_cycle",
                     "sharded_walker.dd_refill",
                     "sharded_walker.dd_legacy",
                     "bag_engine._run_bag", "device_engine._run"):
        assert required in names, f"probe {required} missing"
    assert list(rule_gl10(real_traces)) == []


# ---------------------------------------------------------------------------
# the whole tier vs the committed baseline (ci.sh's deep-lint check)
# ---------------------------------------------------------------------------

def test_deep_tier_real_package_clean(real_traces):
    violations = run_deep(traces=real_traces)
    baseline = load_baseline(
        os.path.join(REPO, "tools", "graftlint_baseline.json"))
    new, _known, stale = split_new_and_known(violations, baseline,
                                             codes_checked=DEEP_CODES)
    assert new == [], "\n".join(v.render() for v in new)
    assert stale == [], stale
