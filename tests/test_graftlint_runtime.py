"""tools/graftlint --runtime (GL12-GL14): each rule catches its
deliberately-broken fixture and stays silent on the fixed twin.

The GL13 fixture reconstructs the round-19 EngineHandle deadlock shape
(``eng.step()`` under ``with handle.lock():`` inside the serve loop);
the GL12 fixture models the round-18 spillover-counter gap (a
``self.<attr>`` total that never rode the snapshot). Pure AST analysis
— no jax, no threads actually started — so these run in milliseconds.

Also covered here: the tier-merge dedupe (satellite: one key flagged
by two tiers reports once), the ``--since`` file-selection logic, the
baseline ``tier`` field, and the shared replay-dedup helpers the
events analyzers now import instead of carrying copies.
"""

import json
import os
import subprocess
import sys
import textwrap

import pytest

from ppls_tpu.utils.artifact_schema import (dedup_by_rid, dedup_replayed,
                                            validate_graftlint_json)
from tools.graftlint.core import (Violation, changed_paths_since,
                                  filter_to_changed, load_baseline,
                                  merge_tier, run_lint, tier_of,
                                  violations_to_json, write_baseline)
from tools.graftlint.rules.locks import GL11_LOCK_MAP
from tools.graftlint import runtime as rt
from tools.graftlint.runtime import (GL12_STATE_CLASSES, GL13_LOCK_DECLS,
                                     GL13_RPC_CALLS, GL14_SHARED_OK,
                                     RUNTIME_CODES, RUNTIME_RULES,
                                     run_runtime)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _mkpkg(tmp_path, files):
    """files: {relative path under pkg/: source}. Returns pkg dir."""
    root = tmp_path / "pkg"
    for rel, src in files.items():
        p = root / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    return str(root)


def _runtime(target):
    return run_lint(target, rules=RUNTIME_RULES)


# ---------------------------------------------------------------------------
# GL12 — snapshot-surface completeness (the round-18 spillover shape)
# ---------------------------------------------------------------------------

GL12_BROKEN = """
    class SpillEngine:
        def __init__(self):
            self.requests_total = 0
            self.tasks = 0
            self.cfg = {}

        def run(self):
            self.requests_total += 1
            self.tasks += 1

        def snapshot(self, path):
            payload = {"tasks": self.tasks}
            return payload
"""

GL12_FIXED = GL12_BROKEN.replace(
    '{"tasks": self.tasks}',
    '{"tasks": self.tasks, "requests_total": self.requests_total}')


def _declare_gl12(monkeypatch, suffix, spec):
    monkeypatch.setitem(GL12_STATE_CLASSES, suffix, spec)


def test_gl12_trips_on_snapshot_omission(tmp_path, monkeypatch):
    """Round-18 model: a mutated total whose spelling never reaches
    the snapshot payload flags; the persisted twin is clean."""
    _declare_gl12(monkeypatch, "spill_mod.py", {
        "SpillEngine": {"why": "fixture: totals must ride the snapshot",
                        "aliases": {}, "ephemeral": {}}})
    broken = _runtime(_mkpkg(tmp_path, {"spill_mod.py": GL12_BROKEN}))
    assert [v.symbol for v in broken] == ["SpillEngine.requests_total"]
    assert broken[0].code == "GL12"
    assert "round-18" in broken[0].message
    fixed = _runtime(_mkpkg(tmp_path, {"spill_mod.py": GL12_FIXED}))
    assert fixed == []


def test_gl12_init_only_attrs_are_exempt(tmp_path, monkeypatch):
    """``self.cfg`` is assigned only in __init__ (construction shape,
    not runtime mutation) — it must not flag even though no snapshot
    mentions it."""
    _declare_gl12(monkeypatch, "spill_mod.py", {
        "SpillEngine": {"why": "fixture", "aliases": {},
                        "ephemeral": {}}})
    vs = _runtime(_mkpkg(tmp_path, {"spill_mod.py": GL12_FIXED}))
    assert all(v.symbol != "SpillEngine.cfg" for v in vs)


def test_gl12_ephemeral_allowlist_clears(tmp_path, monkeypatch):
    _declare_gl12(monkeypatch, "spill_mod.py", {
        "SpillEngine": {
            "why": "fixture",
            "aliases": {},
            "ephemeral": {"requests_total":
                          "fixture: summary-line telemetry only"}}})
    vs = _runtime(_mkpkg(tmp_path, {"spill_mod.py": GL12_BROKEN}))
    assert vs == []


GL12_LEDGER = """
    class Ledger:
        def __init__(self):
            self._given = 0

        def grant(self):
            self._given += 1
"""

GL12_SAVER = """
    def snapshot_pool(pool, path):
        return {"given": pool.ledger._given}
"""


def test_gl12_alias_resolves_cross_module_surface(tmp_path, monkeypatch):
    """An attr persisted by ANOTHER module's snapshot under a
    different spelling is covered via a declared alias (the spillover
    totals ride the owning engine's totals block) — and without the
    alias it still flags (no string coincidence leaks through)."""
    files = {"led_mod.py": GL12_LEDGER, "saver.py": GL12_SAVER}
    _declare_gl12(monkeypatch, "led_mod.py", {
        "Ledger": {"why": "fixture",
                   "aliases": {"_given": ("given",)}, "ephemeral": {}}})
    assert _runtime(_mkpkg(tmp_path, files)) == []
    _declare_gl12(monkeypatch, "led_mod.py", {
        "Ledger": {"why": "fixture", "aliases": {}, "ephemeral": {}}})
    vs = _runtime(_mkpkg(tmp_path, files))
    assert [v.symbol for v in vs] == ["Ledger._given"]


GL12_RESTORE = """
    class Disp:
        def __init__(self):
            self._cut_files = {}

        def cut(self, n):
            self._cut_files[n] = "x"

    def resume_disp(disp, payload):
        disp._cut_files = dict(payload)
        return disp
"""


def test_gl12_restore_side_assignment_counts_as_surface(tmp_path,
                                                        monkeypatch):
    """Restore code that rebuilds an attr by assignment (no string
    key anywhere) covers it; dropping the restore function makes the
    same mutation flag."""
    _declare_gl12(monkeypatch, "rst_mod.py", {
        "Disp": {"why": "fixture", "aliases": {}, "ephemeral": {}}})
    assert _runtime(_mkpkg(tmp_path, {"rst_mod.py": GL12_RESTORE})) == []
    no_restore = GL12_RESTORE.split("def resume_disp")[0]
    vs = _runtime(_mkpkg(tmp_path, {"rst_mod.py": no_restore}))
    assert [v.symbol for v in vs] == ["Disp._cut_files"]


# ---------------------------------------------------------------------------
# GL13 — the round-19 deadlock shape, blocking heuristics, lock order
# ---------------------------------------------------------------------------

# Reconstructed round-19 shape: the serve loop (a CLOSURE, like the
# real one) steps the engine while holding the handle lock. The file
# is named __main__.py so the REAL GL13_LOCK_DECLS entry for the
# serve stack applies — no fixture-only declaration needed.
GL13_ROUND19_BROKEN = """
    def _main_serve(eng, handle):
        def serve_loop():
            while True:
                with handle.lock():
                    eng.submit(1)
                    eng.step()
        serve_loop()
"""

GL13_ROUND19_FIXED = """
    def _main_serve(eng, handle):
        def serve_loop():
            while True:
                with handle.lock():
                    eng.submit(1)
                eng.step()
        serve_loop()
"""


def test_gl13_round19_deadlock_shape_trips(tmp_path):
    vs = _runtime(_mkpkg(tmp_path,
                         {"__main__.py": GL13_ROUND19_BROKEN}))
    # exactly ONE violation: the nested serve_loop is scanned under
    # its own qualname, not double-attributed to _main_serve too
    assert [v.symbol for v in vs] == ["_main_serve.serve_loop:step"]
    assert vs[0].code == "GL13"
    assert "round-19" in vs[0].message


def test_gl13_round19_fixed_twin_is_clean(tmp_path):
    vs = _runtime(_mkpkg(tmp_path,
                         {"__main__.py": GL13_ROUND19_FIXED}))
    assert vs == []


GL13_BLOCKING = """
    class W:
        def pull(self):
            with self._cv:
                item = self._q.get()
            return item

        def pull_bounded(self):
            with self._cv:
                item = self._q.get(timeout=1)
                name = self.cfg.get("name")
            return item, name

        def flush(self):
            with self._cv:
                while self._busy:
                    self._cv.wait()
"""


def test_gl13_blocking_heuristics(tmp_path, monkeypatch):
    """Untimed ``.get()`` under a lock flags; ``get(timeout=)`` and
    ``dict.get(key)`` (has args) stay quiet; ``cv.wait()`` ON the
    held condition is the release-while-waiting idiom and is exempt."""
    monkeypatch.setitem(GL13_LOCK_DECLS, "cv_mod.py",
                        {"_cv": "W._cv"})
    vs = _runtime(_mkpkg(tmp_path, {"cv_mod.py": GL13_BLOCKING}))
    assert [v.symbol for v in vs] == ["W.pull:get"]


GL13_IPC = """
    class C:
        def outer(self):
            with self._lock:
                self.helper()

        def helper(self):
            return self.sock.recv(1024)
"""


def test_gl13_blocking_reached_interprocedurally(tmp_path, monkeypatch):
    """The blocking call sits in a CALLEE of the locked region — the
    BFS over resolved calls (self-method edge here) still finds it."""
    monkeypatch.setitem(GL13_LOCK_DECLS, "ipc_mod.py",
                        {"_lock": "C._lock"})
    vs = _runtime(_mkpkg(tmp_path, {"ipc_mod.py": GL13_IPC}))
    assert [v.symbol for v in vs] == ["C.helper:recv"]


GL13_CYCLE_BROKEN = """
    import threading

    _la = threading.Lock()
    _lb = threading.Lock()

    def f():
        with _la:
            with _lb:
                pass

    def g():
        with _lb:
            with _la:
                pass
"""

GL13_CYCLE_FIXED = GL13_CYCLE_BROKEN.replace(
    "with _lb:\n            with _la:",
    "with _la:\n            with _lb:")


def test_gl13_lock_order_cycle(tmp_path, monkeypatch):
    monkeypatch.setitem(GL13_LOCK_DECLS, "locks_mod.py",
                        {"_la": "LA", "_lb": "LB"})
    vs = _runtime(_mkpkg(tmp_path,
                         {"locks_mod.py": GL13_CYCLE_BROKEN}))
    assert [v.symbol for v in vs] == ["cycle:LA->LB->LA"]
    fixed = _runtime(_mkpkg(tmp_path,
                            {"locks_mod.py": GL13_CYCLE_FIXED}))
    assert fixed == []


GL13_NESTED_DEF = """
    import time

    def setup(handle):
        with handle.lock():
            def later():
                time.sleep(5)
            cb = later
        return cb
"""


def test_gl13_nested_def_body_not_attributed_to_lock_region(tmp_path):
    """Defining a closure under a lock is not executing it: the
    sleep inside ``later`` runs when CALLED (no lock held), so the
    shallow region walk must not flag it."""
    vs = _runtime(_mkpkg(tmp_path, {"__main__.py": GL13_NESTED_DEF}))
    assert vs == []


# ---------------------------------------------------------------------------
# GL14 — thread-shared-state audit
# ---------------------------------------------------------------------------

GL14_BROKEN = """
    import threading

    class Worker:
        def __init__(self):
            self.count = 0

        def start(self):
            self._t = threading.Thread(target=self._run, daemon=True)
            self._t.start()

        def _run(self):
            self.count += 1

        def read(self):
            return self.count
"""


def test_gl14_undeclared_cross_thread_attr_trips(tmp_path):
    vs = _runtime(_mkpkg(tmp_path, {"thr_mod.py": GL14_BROKEN}))
    assert [v.symbol for v in vs] == ["Worker.count"]
    assert vs[0].code == "GL14"
    # _t is written in start() but only touched main-side: no flag
    assert all("._t" not in v.symbol for v in vs)


def test_gl14_gl11_guarded_set_clears(tmp_path, monkeypatch):
    """Declaring the attr in the module's GL11 guarded set (the
    designed fix: name the lock that owns it) silences GL14."""
    monkeypatch.setitem(GL11_LOCK_MAP, "thr_mod.py", {
        "locks": ("_lock",), "guarded": ("count",),
        "unlocked_ok": ("__init__",),
        "reason": "fixture: count is owned by _lock"})
    vs = _runtime(_mkpkg(tmp_path, {"thr_mod.py": GL14_BROKEN}))
    assert vs == []


def test_gl14_shared_ok_allowlist_clears(tmp_path, monkeypatch):
    monkeypatch.setitem(GL14_SHARED_OK, "thr_mod.py",
                        {"count": "fixture: atomic by design"})
    vs = _runtime(_mkpkg(tmp_path, {"thr_mod.py": GL14_BROKEN}))
    assert vs == []


GL14_HANDLER = """
    from http.server import BaseHTTPRequestHandler

    class App:
        def __init__(self):
            self.n = 0

        def process(self):
            self.n += 1

        def report(self):
            return self.n

    class Handler(BaseHTTPRequestHandler):
        def do_GET(self):
            self.server.app.process()
"""


def test_gl14_http_handler_is_a_thread_entry(tmp_path):
    """``do_*`` methods of a BaseHTTPRequestHandler subclass run on
    server threads: state they reach (via the unique-method-name
    edge) and the main side also touches must be declared."""
    vs = _runtime(_mkpkg(tmp_path, {"srv_mod.py": GL14_HANDLER}))
    assert [v.symbol for v in vs] == ["App.n"]


# ---------------------------------------------------------------------------
# satellites: tier merge dedupe, --since selection, tier metadata
# ---------------------------------------------------------------------------

def _v(code, path, symbol, line=1):
    return Violation(code=code, path=path, line=line, symbol=symbol,
                     message=f"{code} fixture message for {symbol}")


def test_merge_tier_dedupes_overlapping_keys():
    """Artificially overlapping tiers: the same (code, path, symbol)
    key flagged by two tiers reports ONCE (first tier wins, its line
    preserved); genuinely new keys still append, and the result is
    re-sorted."""
    ast_tier = [_v("GL03", "pkg/a.py", "f:np.float32", line=10)]
    other = [_v("GL03", "pkg/a.py", "f:np.float32", line=99),
             _v("GL13", "pkg/b.py", "g:step")]
    merged = merge_tier(ast_tier, other)
    assert [v.key for v in merged] == [
        "GL03:pkg/a.py:f:np.float32", "GL13:pkg/b.py:g:step"]
    assert merged[0].line == 10
    # self-merge is a no-op
    assert [v.key for v in merge_tier(merged, merged)] \
        == [v.key for v in merged]


def test_filter_to_changed_keeps_only_changed_paths():
    vs = [_v("GL12", "ppls_tpu/runtime/stream.py", "A.x"),
          _v("GL13", "ppls_tpu/__main__.py", "f:step")]
    out = filter_to_changed(vs, {"ppls_tpu/__main__.py", "README.md"})
    assert [v.path for v in out] == ["ppls_tpu/__main__.py"]
    assert filter_to_changed(vs, set()) == []


def test_changed_paths_since_smoke_and_bad_ref():
    paths = changed_paths_since("HEAD", cwd=REPO)
    assert isinstance(paths, set)
    with pytest.raises(ValueError):
        changed_paths_since("no-such-ref-xyzzy", cwd=REPO)


def test_tier_of_agrees_with_tier_code_tuples():
    from tools.graftlint.deep import DEEP_CODES
    from tools.graftlint.rules import AST_CODES
    for c in AST_CODES:
        assert tier_of(c) == "ast"
    for c in DEEP_CODES:
        assert tier_of(c) == "deep"
    for c in RUNTIME_CODES:
        assert tier_of(c) == "runtime"


def test_write_baseline_entries_carry_tier(tmp_path):
    path = str(tmp_path / "base.json")
    write_baseline(path, [_v("GL12", "pkg/a.py", "A.x"),
                          _v("GL03", "pkg/b.py", "f:np.float32")])
    doc = json.load(open(path))
    tiers = {e["key"].split(":", 1)[0]: e["tier"]
             for e in doc["grandfathered"]}
    assert tiers == {"GL12": "runtime", "GL03": "ast"}


def test_json_doc_carries_runtime_flag_and_validates():
    vs = [_v("GL13", "pkg/a.py", "f:step")]
    doc = violations_to_json("pkg", vs, [], [], {}, deep=False,
                             runtime=True)
    assert doc["runtime"] is True
    assert doc["violations"][0]["tier"] == "runtime"
    assert validate_graftlint_json(doc) == []
    doc["runtime"] = "yes"
    assert any("'runtime'" in p for p in validate_graftlint_json(doc))
    doc["runtime"] = True
    doc["violations"][0]["tier"] = "bogus"
    assert any("tier" in p for p in validate_graftlint_json(doc))


# ---------------------------------------------------------------------------
# declared surfaces: reasons required (the allowlist review contract)
# ---------------------------------------------------------------------------

def test_gl12_state_class_declarations_carry_reasons():
    assert GL12_STATE_CLASSES, "the state-class map must not be empty"
    for suffix, classes in GL12_STATE_CLASSES.items():
        for cls, spec in classes.items():
            assert len(spec["why"]) > 20, (suffix, cls)
            for attr, reason in spec.get("ephemeral", {}).items():
                assert len(reason) > 40, \
                    f"{suffix}:{cls}.{attr} ephemeral needs a " \
                    f"substantive reviewed reason"


def test_gl13_declarations_carry_reasons():
    assert GL13_LOCK_DECLS
    for suffix, decls in GL13_LOCK_DECLS.items():
        assert decls, suffix
        for spelling, lock_id in decls.items():
            assert spelling and lock_id, (suffix, spelling)
    for name, reason in GL13_RPC_CALLS.items():
        assert len(reason) > 40, \
            f"declared blocking RPC {name!r} needs a reviewed reason"


def test_gl14_shared_ok_carries_reasons():
    for suffix, attrs in GL14_SHARED_OK.items():
        for attr, reason in attrs.items():
            assert len(reason) > 40, (suffix, attr)


# ---------------------------------------------------------------------------
# the real package: runtime tier clean vs the committed baseline
# ---------------------------------------------------------------------------

def test_real_package_runtime_tier_clean_vs_baseline():
    """Every runtime-tier finding on the committed ppls_tpu package is
    grandfathered WITH a substantive reason — 0 unreviewed entries.
    A new GL12/GL13/GL14 hit on the real serving stack fails here
    first (and in ci.sh step 4c)."""
    baseline = load_baseline(
        os.path.join(REPO, "tools", "graftlint_baseline.json"))
    vs = run_runtime(os.path.join(REPO, "ppls_tpu"))
    unreviewed = [v.key for v in vs
                  if len(baseline.get(v.key, "")) <= 40]
    assert unreviewed == []


def test_real_baseline_entries_all_carry_tier_field():
    doc = json.load(open(
        os.path.join(REPO, "tools", "graftlint_baseline.json")))
    for e in doc["grandfathered"]:
        code = e["key"].split(":", 1)[0]
        assert e.get("tier") == tier_of(code), e["key"]
        if e.get("tier") == "runtime":
            assert len(e.get("reason", "")) > 40, e["key"]


def test_cli_runtime_json_ledger_round_trip(tmp_path):
    """The exact ci.sh step 4c pipeline: --runtime --format json exits
    0 on the committed tree and the ledger validates."""
    env = dict(os.environ, PYTHONPATH=REPO)
    res = subprocess.run(
        [sys.executable, "-m", "tools.graftlint", "ppls_tpu",
         "--runtime", "--baseline", "tools/graftlint_baseline.json",
         "--format", "json"],
        cwd=REPO, env=env, capture_output=True, text=True)
    assert res.returncode == 0, res.stdout + res.stderr
    doc = json.loads(res.stdout)
    assert doc["runtime"] is True
    assert validate_graftlint_json(doc) == []
    assert any(v["tier"] == "runtime" for v in doc["violations"])


def test_cli_since_bad_ref_is_a_usage_error():
    env = dict(os.environ, PYTHONPATH=REPO)
    res = subprocess.run(
        [sys.executable, "-m", "tools.graftlint", "ppls_tpu",
         "--since", "no-such-ref-xyzzy"],
        cwd=REPO, env=env, capture_output=True, text=True)
    assert res.returncode == 2
    assert "--since" in res.stderr


# ---------------------------------------------------------------------------
# shared replay-dedup helpers (hoisted from the events analyzers)
# ---------------------------------------------------------------------------

def test_dedup_replayed_first_wins_and_none_passthrough():
    recs = [{"rid": 1, "seg": "orig"}, {"rid": 2},
            {"rid": 1, "seg": "replay"}, {"note": "no key"},
            {"note": "still no key"}]
    out = dedup_by_rid(recs)
    assert [r.get("rid") for r in out] == [1, 2, None, None]
    assert out[0]["seg"] == "orig"      # the original wins, not the replay
    by_pair = dedup_replayed(
        [{"phase": 1, "process": 0}, {"phase": 1, "process": 0},
         {"phase": 1, "process": 1}],
        lambda d: (d.get("phase"), d.get("process")))
    assert len(by_pair) == 2


def test_analyzers_import_the_shared_dedup(tmp_path):
    """Both analyzers use the hoisted helpers (no private copies):
    the request analyzer's redeal dedup collapses a replayed
    (phase, process) pair to one record."""
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import analyze_request
    finally:
        sys.path.pop(0)
    assert analyze_request.dedup_replayed is dedup_replayed
    trace = tmp_path / "events.jsonl"
    rows = [
        {"ev": "meta", "t": 0.0, "schema": "ppls-events-v1"},
        {"ev": "event", "t": 1.0, "name": "request_dealt",
         "attrs": {"rid": 7, "phase": 1, "process": 0}},
        {"ev": "event", "t": 1.5, "name": "request_redeal",
         "attrs": {"rid": 7, "phase": 2, "process": 0}},
        {"ev": "event", "t": 1.6, "name": "request_redeal",
         "attrs": {"rid": 7, "phase": 2, "process": 0}},
        {"ev": "event", "t": 2.0, "name": "retire",
         "attrs": {"rid": 7, "phase": 3, "latency_phases": 2}},
    ]
    trace.write_text("".join(json.dumps(r) + "\n" for r in rows))
    rids = analyze_request.load_trace([str(trace)])
    assert len(rids[7]["redeals"]) == 1
