"""runtime.guard promotion (VERDICT r5 #4) + the CLI --watchdog flag.

The hang/transient guards moved from bench.py into
ppls_tpu.runtime.guard so the CLI can wrap engine runs in the same
protection the bench already had; bench re-exports them (its own
test_bench_retry.py suite keeps covering that surface). Here: the
guard module's own API, the run_with_watchdog timeout=>resume shape,
and the CLI-level hang-injection acceptance (VERDICT r5 #4: a wedged
first attempt must recover from the checkpoint, not hang the process).
"""

import json
import os

import numpy as np
import pytest

from ppls_tpu.runtime import guard


def test_bench_reexports_are_the_guard_objects():
    import sys
    from pathlib import Path
    sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
    import bench
    assert bench.HangTimeout is guard.HangTimeout
    assert bench.is_transient is guard.is_transient
    assert bench.with_deadline is guard.with_deadline
    assert bench.MAX_ATTEMPTS == guard.MAX_ATTEMPTS


def test_run_with_watchdog_passthrough():
    assert guard.run_with_watchdog(lambda: 41, 5.0) == 41


def test_run_with_watchdog_resumes_after_hang():
    import threading
    calls = []

    def wedged():
        calls.append("run")
        threading.Event().wait(5)

    def resume():
        calls.append("resume")
        return "recovered"

    out = guard.run_with_watchdog(wedged, 0.2, resume_fn=resume,
                                  log=lambda m: None)
    assert out == "recovered"
    assert calls == ["run", "resume"]


def test_run_with_watchdog_no_resume_raises():
    import threading
    with pytest.raises(guard.HangTimeout, match="watchdog deadline"):
        guard.run_with_watchdog(lambda: threading.Event().wait(5), 0.2,
                                log=lambda m: None)


def test_cli_watchdog_hang_injection_resumes_from_checkpoint(
        tmp_path, capsys, monkeypatch):
    """The CLI acceptance (VERDICT r5 #4): a checkpointed family run
    whose first attempt hangs must — under --watchdog — time out,
    resume from the leg snapshot, and print the same result as an
    uninterrupted run."""
    from ppls_tpu.models.integrands import get_family
    from ppls_tpu.parallel.bag_engine import integrate_family
    from ppls_tpu import __main__ as cli

    theta = np.linspace(1.0, 2.0, 4, endpoint=False)
    bounds = (1e-2, 1.0)
    eps = 1e-6
    base = integrate_family(get_family("sin_recip_scaled"), theta,
                            bounds, eps, chunk=1 << 8,
                            capacity=1 << 14)

    # leave a mid-run leg snapshot behind (the state a wedged device
    # would have left), so the watchdog's retry takes the RESUME arm
    path = str(tmp_path / "cli.ckpt")
    with pytest.raises(RuntimeError, match="simulated crash"):
        integrate_family(get_family("sin_recip_scaled"), theta, bounds,
                         eps, chunk=1 << 8, capacity=1 << 14,
                         checkpoint_path=path, checkpoint_every=2,
                         _crash_after_legs=1)
    assert os.path.exists(path)

    monkeypatch.setenv("PPLS_CLI_INJECT_HANG", "1")
    rc = cli.main([
        "family", "--family", "sin_recip_scaled", "--engine", "bag",
        "--m", "4", "--theta0", "1.0", "--theta1", "2.0",
        "-a", "1e-2", "-b", "1.0", "--eps", "1e-6",
        "--chunk", str(1 << 8), "--capacity", str(1 << 14),
        # generous deadline: the resume attempt shares it, and under a
        # fully loaded test run its (cached) compile + checkpoint load
        # measured >0.5s — a tight value makes the RECOVERY arm time
        # out and flakes the test
        "--checkpoint", path, "--watchdog", "10", "--json"])
    assert rc == 0
    # the injection hook was consumed by the first (hung) attempt
    assert "PPLS_CLI_INJECT_HANG" not in os.environ
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["engine"] == "bag"
    np.testing.assert_allclose(out["areas_head"], base.areas[:4],
                               rtol=0, atol=1e-12)
    # a finished run clears its snapshot
    assert not os.path.exists(path)
