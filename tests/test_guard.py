"""runtime.guard promotion (VERDICT r5 #4) + the CLI --watchdog flag.

The hang/transient guards moved from bench.py into
ppls_tpu.runtime.guard so the CLI can wrap engine runs in the same
protection the bench already had; bench re-exports them (its own
test_bench_retry.py suite keeps covering that surface). Here: the
guard module's own API, the run_with_watchdog timeout=>resume shape,
and the CLI-level hang-injection acceptance (VERDICT r5 #4: a wedged
first attempt must recover from the checkpoint, not hang the process).
"""

import json
import os

import numpy as np
import pytest

from ppls_tpu.runtime import guard


def test_bench_reexports_are_the_guard_objects():
    import sys
    from pathlib import Path
    sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
    import bench
    assert bench.HangTimeout is guard.HangTimeout
    assert bench.is_transient is guard.is_transient
    assert bench.with_deadline is guard.with_deadline
    assert bench.MAX_ATTEMPTS == guard.MAX_ATTEMPTS


def test_run_with_watchdog_passthrough():
    assert guard.run_with_watchdog(lambda: 41, 5.0) == 41


def test_run_with_watchdog_resumes_after_hang():
    import threading
    calls = []

    def wedged():
        calls.append("run")
        threading.Event().wait(5)

    def resume():
        calls.append("resume")
        return "recovered"

    out = guard.run_with_watchdog(wedged, 0.2, resume_fn=resume,
                                  log=lambda m: None)
    assert out == "recovered"
    assert calls == ["run", "resume"]


def test_run_with_watchdog_no_resume_raises():
    import threading
    with pytest.raises(guard.HangTimeout, match="watchdog deadline"):
        guard.run_with_watchdog(lambda: threading.Event().wait(5), 0.2,
                                log=lambda m: None)


def test_supervisor_retry_budget_exhaustion_deterministic(monkeypatch):
    """Satellite (round 16): repeated transient faults exhaust the
    supervisor's total deadline DETERMINISTICALLY — the backoff
    schedule is the documented base*2^(n-1) capped sequence, the loop
    raises RetryBudgetExhausted instead of sleeping past the budget,
    and the attempts/recoveries record reports every retry. A fake
    clock advanced by the sleep stub makes the wall-clock budget check
    exact."""
    import time as _time
    sleeps = []
    attempts_seen = []
    clock = [1000.0]

    def fake_sleep(s):
        sleeps.append(s)
        clock[0] += s

    # guard.py does `import time` — patching the module attribute
    # covers both the supervisor's t_start and its budget check
    monkeypatch.setattr(_time, "monotonic", lambda: clock[0])

    def always_transient():
        attempts_seen.append(1)
        raise guard.InjectedCrash("fault plan: phase-boundary crash")

    sup = guard.Supervisor(
        always_transient, backoff_base=1.0, backoff_cap=4.0,
        max_attempts=100, total_deadline=10.0,
        sleep=fake_sleep, log=lambda m: None)
    with pytest.raises(guard.RetryBudgetExhausted,
                       match="total deadline") as ei:
        sup.run()
    # deterministic schedule: sleeps 1 + 2 + 4 pass (elapsed 7), the
    # FOURTH backoff (capped at 4: 7 + 4 > 10) is refused
    assert sleeps == [1.0, 2.0, 4.0]
    assert sup.attempts == 4 == len(attempts_seen)
    assert sup.recoveries == [("transient", "backoff_resume")] * 3
    # the last underlying failure rides on the exception
    assert "phase-boundary crash" in str(ei.value)
    # the exhausted budget classifies FATAL: a supervising layer must
    # not see the embedded transient text and retry past the budget
    assert guard.classify_failure(ei.value) == "fatal"


def test_supervisor_reports_attempts_and_recoveries_on_success():
    """Two transient failures then success: the summary-facing record
    (attempts / recoveries) counts every leg correctly."""
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise guard.InjectedCrash("fault plan: crash")
        return "done"

    sup = guard.Supervisor(flaky, backoff_base=0.0, backoff_cap=0.0,
                           sleep=lambda s: None, log=lambda m: None)
    assert sup.run() == "done"
    assert sup.attempts == 3
    assert sup.recoveries == [("transient", "backoff_resume")] * 2


def test_with_retry_total_deadline_exhaustion(monkeypatch):
    """with_retry's budget arm: when elapsed + next backoff would
    exceed total_deadline, RetryBudgetExhausted carries the last
    underlying failure instead of sleeping into a hopeless wait."""
    def always_fail():
        raise RuntimeError("connection reset by peer (tunnel)")

    log = []
    with pytest.raises(guard.RetryBudgetExhausted,
                       match="connection reset"):
        guard.with_retry(always_fail, log, what="t",
                         deadline=5.0, backoff_base=100.0,
                         total_deadline=1.0, log=lambda m: None)
    # refused BEFORE the first 100s backoff: nothing retried yet
    assert log == []


def test_graceful_shutdown_flag_and_restore():
    """GracefulShutdown (round 16): installs handlers on the main
    thread, a delivered SIGTERM only sets the flag (no exception), and
    the previous handlers are restored on exit."""
    import signal as _signal
    before = _signal.getsignal(_signal.SIGTERM)
    with guard.GracefulShutdown() as stop:
        assert not stop.requested
        os.kill(os.getpid(), _signal.SIGTERM)
        # the handler runs synchronously on the main thread at the
        # next bytecode boundary; the flag is the only effect
        for _ in range(100):
            if stop.requested:
                break
        assert stop.requested
        assert stop.signal_name == "SIGTERM"
    assert _signal.getsignal(_signal.SIGTERM) is before


def test_cli_watchdog_hang_injection_resumes_from_checkpoint(
        tmp_path, capsys, monkeypatch):
    """The CLI acceptance (VERDICT r5 #4): a checkpointed family run
    whose first attempt hangs must — under --watchdog — time out,
    resume from the leg snapshot, and print the same result as an
    uninterrupted run."""
    from ppls_tpu.models.integrands import get_family
    from ppls_tpu.parallel.bag_engine import integrate_family
    from ppls_tpu import __main__ as cli

    theta = np.linspace(1.0, 2.0, 4, endpoint=False)
    bounds = (1e-2, 1.0)
    eps = 1e-6
    base = integrate_family(get_family("sin_recip_scaled"), theta,
                            bounds, eps, chunk=1 << 8,
                            capacity=1 << 14)

    # leave a mid-run leg snapshot behind (the state a wedged device
    # would have left), so the watchdog's retry takes the RESUME arm
    path = str(tmp_path / "cli.ckpt")
    with pytest.raises(RuntimeError, match="simulated crash"):
        integrate_family(get_family("sin_recip_scaled"), theta, bounds,
                         eps, chunk=1 << 8, capacity=1 << 14,
                         checkpoint_path=path, checkpoint_every=2,
                         _crash_after_legs=1)
    assert os.path.exists(path)

    monkeypatch.setenv("PPLS_CLI_INJECT_HANG", "1")
    rc = cli.main([
        "family", "--family", "sin_recip_scaled", "--engine", "bag",
        "--m", "4", "--theta0", "1.0", "--theta1", "2.0",
        "-a", "1e-2", "-b", "1.0", "--eps", "1e-6",
        "--chunk", str(1 << 8), "--capacity", str(1 << 14),
        # generous deadline: the resume attempt shares it, and under a
        # fully loaded test run its (cached) compile + checkpoint load
        # measured >0.5s — a tight value makes the RECOVERY arm time
        # out and flakes the test
        "--checkpoint", path, "--watchdog", "10", "--json"])
    assert rc == 0
    # the injection hook was consumed by the first (hung) attempt
    assert "PPLS_CLI_INJECT_HANG" not in os.environ
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["engine"] == "bag"
    np.testing.assert_allclose(out["areas_head"], base.areas[:4],
                               rtol=0, atol=1e-12)
    # a finished run clears its snapshot
    assert not os.path.exists(path)
