"""Overload-hardened multi-tenant serving (round 16).

Acceptance surface of the ISSUE-11 tentpole:

* ADMISSION CONTROL: per-tenant token buckets gate slot allocation
  (out-of-tokens tenants wait, they are not shed), priority classes
  admit in (-priority, rid) order;
* LOAD SHEDDING: a bounded queue with the deterministic
  lowest-priority-oldest shed policy — every shed request consumes a
  rid and gets an explicit record (``request_shed`` event,
  ``ppls_requests_shed_total{tenant,reason}``, ``on_shed`` callback);
* DEADLINES: queued requests with unmeetable deadlines shed; in-flight
  requests that miss theirs retire as FAILED records
  (``deadline_exceeded``) and their live rows are compacted out, the
  slot immediately reusable with no cross-request contamination;
* DETERMINISM: the shed/deadline schedule is a pure function of the
  arrival schedule + device-counted state — bit-identical across
  rerun AND kill-and-resume, with the compile-once invariant intact;
* the serve CLI survives malformed JSONL input (per-line rejection
  records), SIGTERM (balanced spans + final checkpoint), and restarts
  with zero lost acknowledged requests.
"""

import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

from ppls_tpu.models.integrands import (register_family,
                                        register_family_ds)
from ppls_tpu.ops import ds_kernel as dsk
from ppls_tpu.runtime.stream import StreamEngine

BOUNDS = (1e-2, 1.0)
EPS = 1e-6
KW = dict(slots=4, chunk=1 << 10, capacity=1 << 16, lanes=256,
          roots_per_lane=2, refill_slots=2, seg_iters=32,
          min_active_frac=0.05)


# dyadic-exact quadratic family (the bit-identity workload of
# test_stream.py, registered under this module's own name)
def _quad(x, th):
    return th * x * x


def _quad_ds(x, th):
    return dsk.ds_mul(th, dsk.ds_mul(x, x))


register_family("quad_mt_test", _quad)
register_family_ds("quad_mt_test", _quad_ds)


# ---------------------------------------------------------------------------
# shed policy + admission control
# ---------------------------------------------------------------------------


def test_shed_policy_lowest_priority_oldest():
    """The deterministic shed policy: a full queue sheds its lowest-
    priority OLDEST entry when the arrival strictly outranks it, else
    the arrival itself — and every refusal is an explicit record."""
    eng = StreamEngine("sin_recip_scaled", EPS, queue_limit=2, **KW)
    sheds = []
    eng.on_shed = sheds.append
    r0 = eng.submit(1.0, BOUNDS, priority=0)
    r1 = eng.submit(1.1, BOUNDS, priority=0)
    # equal priority does NOT displace: the arrival is shed
    r2 = eng.submit(1.2, BOUNDS, priority=0)
    assert [s.rid for s in sheds] == [r2]
    assert sheds[0].reason == "queue_full"
    # a higher class displaces the lowest-priority-OLDEST (r0, not r1)
    r3 = eng.submit(1.3, BOUNDS, priority=2)
    assert [s.rid for s in sheds] == [r2, r0]
    assert eng.pending == 2
    # rids keep consuming through sheds (resume prefix-skip alignment)
    assert eng.next_rid == 4
    # registry face: ppls_requests_shed_total{tenant,reason}
    reg = eng.telemetry.registry
    assert reg.value("ppls_requests_shed_total", tenant="default",
                     reason="queue_full") == 2
    # the survivors drain normally
    done = eng.drain()
    assert sorted(c.rid for c in done) == [r1, r3]
    assert len(eng.completed) + len(eng.shed) == 4


def test_priority_classes_admit_first():
    """With one free slot per phase, the high class admits before
    older low-class requests (slot scarcity, no quotas)."""
    eng = StreamEngine("sin_recip_scaled", EPS,
                       **dict(KW, slots=1, admit_window=1))
    eng.submit(1.0, BOUNDS, priority=0)
    eng.submit(1.1, BOUNDS, priority=0)
    eng.submit(1.2, BOUNDS, priority=2)
    eng.drain()
    admit = {c.rid: c.admit_phase for c in eng.completed}
    assert admit[2] < admit[0] < admit[1]


def test_token_bucket_quota_paces_admission():
    """rate=1/burst=1 for the throttled tenant: one admission per
    phase even with free slots, while the unthrottled tenant admits
    immediately. Out-of-tokens requests WAIT (no shed)."""
    eng = StreamEngine(
        "sin_recip_scaled", EPS,
        tenant_quotas={"slow": {"rate": 1, "burst": 1}}, **KW)
    for i in range(3):
        eng.submit(1.0 + i / 10, BOUNDS, tenant="slow")
    eng.submit(1.5, BOUNDS, tenant="fast")
    eng.drain()
    assert not eng.shed
    admit = {c.rid: c.admit_phase for c in eng.completed}
    # the slow tenant's admissions are strictly paced across phases
    assert admit[0] < admit[1] < admit[2]
    # the unquota'd tenant was not throttled
    assert admit[3] == admit[0]


# ---------------------------------------------------------------------------
# deadlines
# ---------------------------------------------------------------------------


def test_deadline_unmeetable_queued_request_is_shed():
    eng = StreamEngine("sin_recip_scaled", EPS,
                       **dict(KW, slots=1, admit_window=1))
    eng.submit(1.0, BOUNDS)                       # occupies the slot
    eng.submit(1.1, BOUNDS, deadline_phases=1)    # starves behind it
    eng.drain()
    assert [s.rid for s in eng.shed] == [1]
    assert eng.shed[0].reason == "deadline_exceeded"
    assert [c.rid for c in eng.completed] == [0]


def test_deadline_expiry_in_flight_and_slot_reuse():
    """An in-flight request missing its deadline retires FAILED
    (``deadline_exceeded``), its rows are cancelled, healthy
    co-residents are untouched, and the freed slot computes a later
    request bit-equal to a solo run (no accumulator contamination)."""
    solo = StreamEngine("sin_recip_scaled", 1e-7, **KW)
    base = solo.run([(1.5, BOUNDS)]).completed[0].area

    eng = StreamEngine("sin_recip_scaled", 1e-7, **KW)
    eng.submit(1.0, BOUNDS, deadline_phases=2, tenant="impatient")
    eng.submit(1.9, BOUNDS)
    done = {c.rid: c for c in eng.drain()}
    assert done[0].failed and done[0].failure == "deadline_exceeded"
    assert done[0].tenant == "impatient"
    assert not np.isfinite(done[0].area)
    # the healthy co-resident's area is a real, finite answer
    assert np.isfinite(done[1].area)
    reg = eng.telemetry.registry
    assert reg.value("ppls_stream_deadline_exceeded_total",
                     tenant="impatient") == 1
    # quarantine counter NOT incremented (failure taxonomy is split)
    assert reg.value("ppls_stream_quarantined_total") == 0
    # the cancelled slot is immediately reusable and uncontaminated:
    # a fresh request through it (running alone, like the reference)
    # matches the solo run bit-for-bit
    eng.submit(1.5, BOUNDS)
    d2 = eng.drain()
    assert d2[0].area == base


def test_deadline_expiry_dd_engine():
    """The dd stream cancels per-chip (vmapped compaction)."""
    kw = dict(KW, chunk=1 << 8, engine="walker-dd", n_devices=8)
    eng = StreamEngine("sin_recip_scaled", 1e-9, **kw)
    eng.submit(1.0, (1e-3, 1.0), deadline_phases=1)
    eng.submit(1.9, (1e-3, 1.0))
    done = {c.rid: c for c in eng.drain()}
    assert done[0].failure == "deadline_exceeded"
    assert np.isfinite(done[1].area)
    eng.submit(1.5, (1e-3, 1.0))
    d2 = eng.drain()
    s2 = StreamEngine("sin_recip_scaled", 1e-9, **kw).run(
        [(1.5, (1e-3, 1.0))])
    assert d2[0].area == s2.completed[0].area


# ---------------------------------------------------------------------------
# determinism under overload
# ---------------------------------------------------------------------------

MT = dict(queue_limit=2,
          tenant_quotas={"free": {"rate": 0.5, "burst": 1}},
          default_deadline_phases=25)


def _mt_requests(k=12):
    reqs = []
    for i in range(k):
        reqs.append((1.0 + i / k, BOUNDS,
                     dict(tenant="free" if i % 2 else "pro",
                          priority=i % 3,
                          deadline_phases=(2 if i == 4 else None))))
    return reqs, [0, 0, 0, 1, 1, 1, 2, 2, 2, 3, 3, 3]


def _drive(eng, reqs, arr, k0=0, crash_after=None):
    k, phases = k0, 0
    while k < len(reqs) or not eng.idle:
        while k < len(reqs) and arr[k] <= eng.phase:
            eng.submit(*reqs[k][:2], **reqs[k][2])
            k += 1
        eng.step()
        phases += 1
        if crash_after is not None and phases >= crash_after:
            raise RuntimeError("simulated crash (test hook)")
    return eng.result()


def test_overload_shed_schedule_bit_identical_f64_mode():
    """Batch-level determinism extends to the shed schedule: the
    pure-f64 dyadic construction + deterministic policy means two
    identical overload runs agree on every area, every shed rid, and
    every phase count at the bit level."""
    kw = dict(KW, f64_rounds=4, slots=2)
    reqs = [(1.0 + i * 0.25, (0.0, 1.0),
             dict(priority=i % 3, tenant=f"t{i % 2}"))
            for i in range(10)]
    arr = [0] * 5 + [1] * 5
    r1 = _drive(StreamEngine("quad_mt_test", 1e-9, queue_limit=3,
                             **kw), reqs, arr)
    r2 = _drive(StreamEngine("quad_mt_test", 1e-9, queue_limit=3,
                             **kw), reqs, arr)
    assert len(r1.shed) > 0                       # overload really shed
    assert len(r1.completed) + len(r1.shed) == 10
    assert np.array_equal(r1.areas, r2.areas)
    assert [(s.rid, s.reason, s.phase) for s in r1.shed] \
        == [(s.rid, s.reason, s.phase) for s in r2.shed]
    assert r1.totals == r2.totals


def test_overload_kill_and_resume_zero_lost(tmp_path):
    """THE round-16 acceptance at engine level: kill mid-overload with
    a fault plan armed (NaN poison), resume from the snapshot — zero
    acknowledged requests lost (every rid retires or sheds exactly
    once), completed areas bit-identical to the undisturbed run,
    sheds/failures/totals identical, and zero recompiles throughout."""
    from ppls_tpu.runtime.faults import FaultInjector, FaultPlan

    reqs, arr = _mt_requests()

    def injector():
        return FaultInjector(FaultPlan.from_events(
            [{"kind": "nan_poison", "at": 2}]))

    base = _drive(StreamEngine(
        "sin_recip_scaled", EPS, quarantine=True,
        fault_injector=injector(), **KW, **MT), reqs, arr)
    assert sum(1 for c in base.completed if c.failed) >= 1

    path = str(tmp_path / "mt.ckpt")
    inj = injector()          # outlives the crashed attempt
    eng = StreamEngine("sin_recip_scaled", EPS, quarantine=True,
                       fault_injector=inj, checkpoint_path=path,
                       checkpoint_every=1, **KW, **MT)
    with pytest.raises(RuntimeError, match="simulated crash"):
        _drive(eng, reqs, arr, crash_after=5)
    eng2 = StreamEngine.resume(path, "sin_recip_scaled", EPS,
                               quarantine=True, fault_injector=inj,
                               checkpoint_every=1, **KW, **MT)
    res = _drive(eng2, reqs, arr, k0=eng2.next_rid)

    # zero lost acknowledged requests: every submitted rid accounted
    rids = {c.rid for c in res.completed} | {s.rid for s in res.shed}
    assert rids == set(range(len(reqs)))
    # completed areas bit-identical to the undisturbed run
    ok = [(c.rid, c.area) for c in base.completed if not c.failed]
    ok2 = [(c.rid, c.area) for c in res.completed if not c.failed]
    assert ok == ok2
    assert [(s.rid, s.reason, s.phase) for s in base.shed] \
        == [(s.rid, s.reason, s.phase) for s in res.shed]
    assert {(c.rid, c.failure) for c in base.completed if c.failed} \
        == {(c.rid, c.failure) for c in res.completed if c.failed}
    assert res.totals == base.totals
    assert res.phases == base.phases
    # compile-once held across kill + resume (the SLO the dispatcher
    # tier is judged by): zero recompiles on both engines
    for e in (eng2,):
        reg = e.telemetry.registry
        assert reg.value("ppls_recompiles_total",
                         engine="walker-stream", default=0.0) == 0.0
    # per-tenant summary survives the restart identically
    assert res.tenant_summary() == base.tenant_summary()
    assert res.class_latency_percentiles() \
        == base.class_latency_percentiles()


def test_snapshot_roundtrips_tokens_and_shed(tmp_path):
    """Token-bucket state and the shed ledger ride the snapshot."""
    path = str(tmp_path / "tk.ckpt")
    eng = StreamEngine(
        "sin_recip_scaled", EPS, queue_limit=1,
        tenant_quotas={"a": {"rate": 0.25, "burst": 2}},
        checkpoint_path=path, checkpoint_every=1, **KW)
    eng.submit(1.0, BOUNDS, tenant="a")
    # queue_limit=1: the queue already holds r0, so both follow-ups
    # shed (equal priority cannot displace)
    eng.submit(1.1, BOUNDS, tenant="a")
    eng.submit(1.2, BOUNDS, tenant="a")
    assert len(eng.shed) == 2
    eng.step()
    eng.snapshot()
    eng2 = StreamEngine.resume(
        path, "sin_recip_scaled", EPS, queue_limit=1,
        tenant_quotas={"a": {"rate": 0.25, "burst": 2}},
        checkpoint_every=1, **KW)
    assert [s.rid for s in eng2.shed] == [s.rid for s in eng.shed]
    assert eng2._tokens == eng._tokens
    reg = eng2.telemetry.registry
    assert reg.value("ppls_requests_shed_total", tenant="a",
                     reason="queue_full") == 2


def test_client_state_rides_the_snapshot(tmp_path):
    """The driver's resume bookkeeping (the serve CLI's batch-list
    cursor) survives kill+resume via ``client_state`` — rids alone
    cannot serve as the list prefix once live ingest traffic, which
    also consumes rids, interleaves with a request list."""
    path = str(tmp_path / "cs.ckpt")
    eng = StreamEngine("sin_recip_scaled", EPS, checkpoint_path=path,
                       checkpoint_every=1, **KW)
    eng.submit(1.0, BOUNDS)                  # batch entry 0
    eng.client_state["batch_cursor"] = 1
    eng.submit(1.2, BOUNDS, tenant="live")   # ingest rid, not batch
    eng.step()
    eng.snapshot()
    eng2 = StreamEngine.resume(path, "sin_recip_scaled", EPS,
                               checkpoint_every=1, **KW)
    # next_rid counts BOTH submissions; the cursor only the batch one
    assert eng2.next_rid == 2
    assert eng2.client_state == {"batch_cursor": 1}


# ---------------------------------------------------------------------------
# ingest + request-record parsing
# ---------------------------------------------------------------------------


def test_parse_request_record_validation():
    from ppls_tpu.runtime.ingest import parse_request_record
    ok = parse_request_record(
        {"theta": 1.5, "bounds": [0.0, 1.0], "tenant": "x",
         "priority": 2, "deadline_phases": 9, "arrival_phase": 3})
    assert ok == {"theta": 1.5, "bounds": (0.0, 1.0), "tenant": "x",
                  "priority": 2, "deadline_phases": 9,
                  "arrival_phase": 3}
    for bad in (
            {"bounds": [0, 1]},                           # no theta
            {"theta": "x", "bounds": [0, 1]},             # bad theta
            {"theta": 1.0, "bounds": [0]},                # bad bounds
            {"theta": [], "bounds": [0, 1]},              # empty batch
            {"theta": [1, 2], "bounds": [0, 1]},          # over limit
            {"theta": 1.0, "bounds": [0, 1], "priority": 1.5},
            {"theta": 1.0, "bounds": [0, 1], "deadline_phases": 0},
            {"theta": 1.0, "bounds": [0, 1], "nope": 1},  # unknown key
            [1, 2],                                       # not object
    ):
        with pytest.raises(ValueError):
            parse_request_record(bad, theta_block=1)


def test_ingest_server_roundtrip():
    """IngestServer unit level: per-line verdicts, malformed lines
    never abort the batch, GET serves the stats callback."""
    import urllib.request

    from ppls_tpu.runtime.ingest import IngestServer, parse_request_record

    seen = []

    def submit(d):
        rec = parse_request_record(d, theta_block=1)
        seen.append(rec)
        return {"rid": len(seen) - 1, "accepted": True}

    srv = IngestServer(submit, stats_fn=lambda: {"pending": len(seen)})
    try:
        body = (b'{"theta": 1.0, "bounds": [0.0, 1.0]}\n'
                b'garbage\n'
                b'{"theta": 1.0}\n'
                b'{"theta": 2.0, "bounds": [0.0, 1.0], '
                b'"tenant": "t"}\n')
        resp = urllib.request.urlopen(urllib.request.Request(
            srv.url, data=body, method="POST"), timeout=10)
        recs = [json.loads(ln) for ln in
                resp.read().decode().strip().splitlines()]
        assert [r.get("accepted") for r in recs] == [
            True, False, False, True]
        assert "unparseable" in recs[1]["error"]
        assert "bounds" in recs[2]["error"]
        assert len(seen) == 2 and seen[1]["tenant"] == "t"
        stats = json.loads(urllib.request.urlopen(
            f"http://{srv.host}:{srv.port}/", timeout=10).read())
        assert stats == {"pending": 2}
    finally:
        srv.close()


def test_serve_cli_malformed_jsonl_lines_continue(tmp_path, capsys):
    """Satellite 1: malformed stdin/file JSONL lines emit a per-line
    rejection record and the run continues — the first bad line no
    longer aborts the whole loop."""
    from ppls_tpu.__main__ import main
    req_file = tmp_path / "reqs.jsonl"
    req_file.write_text(
        '{"theta": 1.0, "bounds": [0.01, 1.0]}\n'
        'this is not json\n'
        '{"theta": "NaN-ish", "bounds": [0.01, 1.0]}\n'
        '{"theta": 1.5, "bounds": [0.01, 1.0], "tenant": "t2", '
        '"priority": 2}\n'
        '{"bounds": [0.01, 1.0]}\n')
    rc = main(["serve", "--slots", "4", "--chunk", "512",
               "--capacity", "65536", "--lanes", "256",
               "--refill-slots", "2", "--eps", "1e-6",
               "--requests", str(req_file)])
    assert rc == 0
    recs = [json.loads(ln) for ln in
            capsys.readouterr().out.strip().splitlines()
            if ln.startswith("{")]
    rejects = [r for r in recs if r.get("rejected")]
    retires = [r for r in recs if "area" in r and not r.get("summary")]
    summary = [r for r in recs if r.get("summary")][0]
    assert [r["line"] for r in rejects] == [2, 3, 5]
    assert all(r["error"] for r in rejects)
    assert len(retires) == 2 and summary["completed"] == 2
    assert {r["tenant"] for r in retires} == {"default", "t2"}
    # the ledger validates through the round-16 serve validator
    from ppls_tpu.utils.artifact_schema import \
        validate_serve_output_text
    out_text = "\n".join(json.dumps(r) for r in recs)
    assert validate_serve_output_text(out_text) == []


# ---------------------------------------------------------------------------
# signals: balanced spans + zero-downtime restart (subprocess level)
# ---------------------------------------------------------------------------

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SERVE_ARGS = ["--slots", "4", "--chunk", "512", "--capacity", "65536",
              "--lanes", "256", "--refill-slots", "2",
              "--eps", "1e-6", "-a", "1e-2", "-b", "1.0",
              "--arrival-rate", "2", "--seed", "5"]


def _run_serve(extra, env_extra=None, send_term_after_lines=None,
               timeout=300):
    """Drive a serve subprocess, optionally SIGTERM-ing it after N
    stdout lines. stdout is read EXCLUSIVELY via readline to EOF —
    mixing buffered manual reads with ``communicate()`` can silently
    drop lines the text wrapper already buffered (a harness bug that
    once masqueraded as a lost retire record); stderr drains on a
    thread so neither pipe can deadlock."""
    import threading
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    if env_extra:
        env.update(env_extra)
    proc = subprocess.Popen(
        [sys.executable, "-m", "ppls_tpu", "serve"] + SERVE_ARGS
        + extra, cwd=REPO, env=env, stdout=subprocess.PIPE,
        stderr=subprocess.PIPE, text=True)
    err_box = []
    drain = threading.Thread(
        target=lambda: err_box.append(proc.stderr.read()), daemon=True)
    drain.start()
    out_lines = []
    sent = send_term_after_lines is None
    for ln in proc.stdout:
        out_lines.append(ln)
        if not sent and len(out_lines) >= send_term_after_lines:
            proc.send_signal(signal.SIGTERM)
            sent = True
    rc = proc.wait(timeout=timeout)
    drain.join(timeout=10)
    return rc, "".join(out_lines), err_box[0] if err_box else ""


def test_serve_sigterm_closes_events_balanced(tmp_path):
    """Satellite 2: SIGTERM during a NON-checkpointed run still exits
    0 with a balanced span timeline and a summary line carrying the
    termination marker."""
    from ppls_tpu.utils.artifact_schema import validate_events_text
    ev = str(tmp_path / "sig.jsonl")
    rc, out, err = _run_serve(
        ["--synthetic", "8", "--events", ev],
        send_term_after_lines=1)
    assert rc == 0, err
    recs = [json.loads(ln) for ln in out.splitlines()
            if ln.startswith("{")]
    summary = [r for r in recs if r.get("summary")][-1]
    assert summary["terminated"] == "SIGTERM"
    # balanced spans — the crashed-prefix --unbalanced-ok waiver is
    # NOT needed for a graceful termination
    assert validate_events_text(open(ev).read()) == []


def test_serve_sigterm_restart_zero_lost_acks(tmp_path):
    """THE zero-downtime acceptance at true CLI level: a seeded
    overload run is killed by a fault-plan SIGTERM at a phase
    boundary (the deterministic orchestrator-kill), restarted with the
    same command line, and the union of the two ledgers equals the
    undisturbed run's — every acknowledged rid retires or sheds
    exactly once, completed areas bit-identical."""
    from ppls_tpu.utils.artifact_schema import \
        validate_serve_output_text
    common = ["--synthetic", "8", "--queue-limit", "3",
              "--tenants", "free:1:0,pro:1:2"]
    rc, out_base, err = _run_serve(common)
    assert rc == 0, err

    ck = str(tmp_path / "zd.ckpt")
    ev = str(tmp_path / "zd.jsonl")
    killed = common + ["--checkpoint", ck, "--checkpoint-every", "1",
                       "--events", ev, "--fault-plan",
                       '[{"kind": "sigterm", "at": 2, '
                       '"edge": "close"}]']
    rc1, out1, err1 = _run_serve(killed)
    assert rc1 == 0, err1
    s1 = [json.loads(ln) for ln in out1.splitlines()
          if ln.startswith("{")][-1]
    assert s1.get("terminated") == "SIGTERM"
    assert os.path.exists(ck), "graceful shutdown must keep the " \
                               "snapshot (it IS the restart state)"
    rc2, out2, err2 = _run_serve(killed)     # same command, restarted
    assert rc2 == 0, err2

    def ledger(text):
        retires, sheds = {}, {}
        for ln in text.splitlines():
            if not ln.startswith("{"):
                continue
            r = json.loads(ln)
            if r.get("summary") or r.get("rejected"):
                continue
            if r.get("shed"):
                sheds[r["rid"]] = r["reason"]
            elif "area" in r:
                retires[r["rid"]] = r["area"]
        return retires, sheds

    base_r, base_s = ledger(out_base)
    r1_, s1_ = ledger(out1)
    r2_, s2_ = ledger(out2)
    union_r = dict(r1_)
    union_r.update(r2_)
    union_s = dict(s1_)
    union_s.update(s2_)
    # zero lost acknowledged requests, bit-identical areas
    assert union_r == base_r
    assert union_s == base_s
    assert set(union_r) | set(union_s) == set(range(8))
    # the second process's summary reports the GLOBAL accounting
    # (snapshot-restored + new), i.e. the whole request set
    s2sum = [json.loads(ln) for ln in out2.splitlines()
             if ln.startswith("{")][-1]
    assert s2sum["completed"] == len(set(r1_) | set(r2_))
    assert s2sum["shed"] == len(set(s1_) | set(s2_))
    # the undisturbed single-process ledger validates end-to-end
    assert validate_serve_output_text(out_base) == []
    # a drained restart clears its snapshot (no stale restart state)
    assert not os.path.exists(ck)
