"""Unified telemetry layer (ppls_tpu/obs, round 10).

Acceptance surface of the observability tentpole:

* registry semantics (counters/gauges/histograms, labels, the
  deterministic bucket-edge quantile) and Prometheus exposition;
* span tracing: hierarchical JSONL timelines that validate against
  the events schema, with monotonic timestamps;
* the stream engine publishes per-phase device-counted rows into the
  registry (one fetch per boundary — the same host values the phase
  already pulled), and its totals/latency numbers are REGISTRY-
  SOURCED: bench, serve, and the metrics endpoint read one surface;
* events-log DETERMINISM: per-request retire records (areas, phase
  latencies, device-counter deltas) are bit-identical across a rerun
  and across a mid-stream kill-and-resume;
* the live metrics endpoint serves parseable exposition during a run;
* the shared per-round record (RoundStats) now populated by the
  walker cycle path and the stream phases (satellite 1);
* `tools/analyze_occupancy.py --from-events` replays a timeline
  offline (no jax import).
"""

import json
import math
import subprocess
import sys
import urllib.request

import numpy as np
import pytest

from ppls_tpu.obs import (Histogram, MetricsRegistry, MetricsServer,
                          PHASE_BUCKETS, RoundStats, SpanTracer,
                          Telemetry, exp_buckets)
from ppls_tpu.utils.artifact_schema import validate_events_text
from ppls_tpu.utils.metrics import round_stats_from_rows

BOUNDS = (1e-2, 1.0)
EPS = 1e-7
KW = dict(slots=8, chunk=1 << 10, capacity=1 << 16, lanes=256,
          roots_per_lane=2, refill_slots=2, seg_iters=32,
          min_active_frac=0.05)
THETA = 1.0 + np.arange(6) / 6.0
REQS = [(float(t), BOUNDS) for t in THETA]
ARRIVALS = [0, 0, 1, 2, 3, 5]


# ---------------------------------------------------------------------------
# registry
# ---------------------------------------------------------------------------

def test_counter_gauge_semantics():
    reg = MetricsRegistry()
    c = reg.counter("t_total", "help")
    c.inc()
    c.inc(41)
    assert c.value == 42
    with pytest.raises(ValueError, match=">= 0"):
        c.inc(-1)
    g = reg.gauge("g")
    g.set(7)
    g.set(3)
    assert g.value == 3
    g.set_max(9)
    g.set_max(2)
    assert g.value == 9
    # same-name re-registration returns the same family; a kind
    # mismatch is a hard error
    assert reg.counter("t_total").value == 42
    with pytest.raises(ValueError, match="re-registered"):
        reg.gauge("t_total")
    assert reg.value("t_total") == 42
    assert reg.value("never_touched", default=-1) == -1


def test_labeled_children_are_independent():
    reg = MetricsRegistry()
    fam = reg.counter("runs_total", labelnames=("engine",))
    fam.labels(engine="walker").inc(3)
    fam.labels(engine="bag").inc(5)
    assert fam.labels(engine="walker").value == 3
    assert fam.labels(engine="bag").value == 5
    with pytest.raises(ValueError, match="expected labels"):
        fam.labels(rule="simpson")
    with pytest.raises(ValueError, match="use .labels"):
        fam.inc()


def test_exp_buckets_shape():
    assert exp_buckets(1.0, 3) == (1.0, 1.5, 2.0, 3.0, 4.0, 6.0, 8.0)
    # ascending, ends one octave above start * 2^octaves
    assert list(PHASE_BUCKETS) == sorted(PHASE_BUCKETS)
    assert PHASE_BUCKETS[0] == 1.0 and PHASE_BUCKETS[-1] == 4096.0


def test_histogram_quantile_deterministic_under_ties():
    """The satellite-6 regression: equal phase counts must not produce
    order- or interpolation-dependent percentiles. The bucket-edge
    quantile maps every tied observation to the same bucket, so any
    insertion order reports the same p50/p99."""
    obs = [3, 3, 3, 3, 4, 4, 8, 8, 8, 2]
    outs = set()
    for perm in (obs, obs[::-1], sorted(obs)):
        h = Histogram(PHASE_BUCKETS)
        for v in perm:
            h.observe(v)
        outs.add((h.quantile(0.5), h.quantile(0.99)))
    assert len(outs) == 1
    p50, p99 = outs.pop()
    assert p50 == 3.0          # rank ceil(0.5*10)=5 lands in bucket 3
    assert p99 == 8.0
    # np.percentile would interpolate (3.5 between the tied 3s and 4s
    # at even ranks) — the exact defect the shared quantile removes
    assert float(np.percentile(obs, 50)) != p50 or True


def test_histogram_edges_and_overflow():
    h = Histogram((1.0, 2.0, 4.0))
    for v in (0.5, 1.0, 1.5, 4.0, 100.0):
        h.observe(v)
    assert h.count == 5
    assert h.counts == [2, 1, 1, 1]       # le-1, le-2, le-4, +Inf
    assert h.sum == pytest.approx(107.0)
    # p100 falls in the overflow bucket: report the tracked max, not inf
    assert h.quantile(1.0) == 100.0
    assert Histogram((1.0,)).quantile(0.5) is None
    with pytest.raises(ValueError, match="ascending"):
        Histogram((2.0, 1.0))
    with pytest.raises(ValueError, match="in \\[0, 1\\]"):
        h.quantile(1.5)


def test_exposition_prometheus_text():
    reg = MetricsRegistry()
    reg.counter("ppls_runs_total", "runs", ("engine",)) \
        .labels(engine="walker").inc(2)
    reg.gauge("ppls_queue_depth").set(5)
    h = reg.histogram("ppls_lat", "latency", buckets=(1.0, 2.0))
    h.observe(1)
    h.observe(3)
    text = reg.exposition()
    lines = text.splitlines()
    assert '# TYPE ppls_runs_total counter' in lines
    assert 'ppls_runs_total{engine="walker"} 2' in lines
    assert 'ppls_queue_depth 5' in lines
    assert 'ppls_lat_bucket{le="1"} 1' in lines
    assert 'ppls_lat_bucket{le="2"} 1' in lines       # cumulative
    assert 'ppls_lat_bucket{le="+Inf"} 2' in lines
    assert 'ppls_lat_sum 4' in lines
    assert 'ppls_lat_count 2' in lines


# ---------------------------------------------------------------------------
# spans + events schema
# ---------------------------------------------------------------------------

def test_span_tracer_timeline_shape(tmp_path):
    path = str(tmp_path / "ev.jsonl")
    tr = SpanTracer(path, meta={"mode": "test"})
    with tr.span("run", engine="walker"):
        with tr.span("phase", phase=0):
            tr.event("admit", rid=0)
        s = tr.span("phase", phase=1)
        tr.event("retire", rid=0, area=1.5)
        s.close(tasks=100)
    tr.close()
    text = open(path).read()
    assert validate_events_text(text) == []
    recs = [json.loads(ln) for ln in text.splitlines()]
    assert recs[0]["ev"] == "meta"
    assert recs[0]["attrs"] == {"mode": "test"}
    opens = [r for r in recs if r["ev"] == "span_open"]
    closes = [r for r in recs if r["ev"] == "span_close"]
    assert len(opens) == len(closes) == 3
    # hierarchy: both phase spans are children of the run span
    run_id = opens[0]["id"]
    assert [o["parent"] for o in opens] == [None, run_id, run_id]
    # the explicit close carries its summary attrs
    phase1_close = [c for c in closes if c["id"] == opens[2]["id"]][0]
    assert phase1_close["attrs"] == {"tasks": 100}
    # events attach to the innermost open span
    evs = [r for r in recs if r["ev"] == "event"]
    assert evs[0]["span"] == opens[1]["id"]
    # timestamps monotone
    ts = [r["t"] for r in recs]
    assert ts == sorted(ts)


def test_span_tracer_noop_without_path():
    tr = SpanTracer(None)
    with tr.span("run"):
        tr.event("x")
    tr.close()                  # no file, no error
    assert not tr.enabled


def test_events_validator_catches_broken_shapes():
    def probs(lines):
        return validate_events_text("\n".join(json.dumps(r)
                                              for r in lines))
    meta = {"ev": "meta", "schema": "ppls-events-v1", "t": 0.0}
    ok = [meta, {"ev": "span_open", "id": 0, "parent": None,
                 "name": "run", "t": 0.1},
          {"ev": "span_close", "id": 0, "t": 0.2}]
    assert probs(ok) == []
    assert any("backwards" in p for p in probs(
        ok[:2] + [{"ev": "span_close", "id": 0, "t": 0.05}]))
    assert any("unknown ev" in p for p in probs([meta, {"ev": "huh",
                                                        "t": 0.1}]))
    assert any("unopened" in p for p in probs(
        [meta, {"ev": "span_close", "id": 7, "t": 0.1}]))
    assert any("never closed" in p for p in probs(ok[:2]))
    # the crashed-run shape is tolerated when asked
    assert validate_events_text(
        "\n".join(json.dumps(r) for r in ok[:2]),
        require_balanced=False) == []
    # a resume segment restarts the monotonic clock legally
    resumed = ok + [dict(meta), {"ev": "event", "name": "resume",
                                 "t": 0.01}]
    assert probs(resumed) == []
    # ... and restarts the span-id space: a hard-killed first attempt
    # leaves id 0 open, the appended segment reopens id 0 — legal in
    # the crashed-run shape, flagged per segment under balance
    killed_then_resumed = [
        meta,
        {"ev": "span_open", "id": 0, "parent": None, "name": "run",
         "t": 0.1},                    # never closed: hard kill
        dict(meta),
        {"ev": "span_open", "id": 0, "parent": None, "name": "run",
         "t": 0.1},
        {"ev": "span_close", "id": 0, "t": 0.2}]
    text = "\n".join(json.dumps(r) for r in killed_then_resumed)
    assert validate_events_text(text, require_balanced=False) == []
    assert any("segment boundary" in p
               for p in validate_events_text(text))


# ---------------------------------------------------------------------------
# stream engine <-> registry/events integration
# ---------------------------------------------------------------------------

def _deterministic_events(path):
    """Extract the determinism comparison surface from an events file:
    retire records (minus wall-clock latency) and per-phase device-
    counter delta rows."""
    retires, phases = [], []
    for ln in open(path):
        r = json.loads(ln)
        if r["ev"] == "event" and r.get("name") == "retire":
            a = dict(r["attrs"])
            a.pop("latency_s", None)
            retires.append(a)
        elif r["ev"] == "span_close" and r.get("attrs", {}).get(
                "tasks") is not None:
            a = {k: v for k, v in r["attrs"].items()}
            phases.append(a)
    return (sorted(retires, key=lambda a: a["rid"]), phases)


def _run_stream(events_path, crash_after=None, checkpoint=None):
    from ppls_tpu.runtime.stream import StreamEngine
    tel = Telemetry(events_path=events_path)
    eng = StreamEngine("sin_recip_scaled", EPS, telemetry=tel,
                       checkpoint_path=checkpoint, checkpoint_every=1,
                       **KW)
    try:
        res = eng.run(REQS, arrival_phase=ARRIVALS,
                      _crash_after_phases=crash_after)
    finally:
        tel.close()
    return eng, res


def test_stream_totals_are_registry_sourced():
    eng, res = _run_stream(None)
    reg = eng.telemetry.registry
    rows = np.stack(eng._phase_rows)
    from ppls_tpu.parallel.walker import STREAM_STAT_FIELDS
    # the registry counters ARE the phase-row sums (one accounting)
    for i, k in enumerate(STREAM_STAT_FIELDS):
        if k == "maxd":
            continue
        assert reg.value(f"ppls_stream_{k}_total") == rows[:, i].sum(), k
        assert res.totals[k] == int(rows[:, i].sum())
    assert res.totals["maxd"] == int(
        rows[:, STREAM_STAT_FIELDS.index("maxd")].max())
    assert reg.value("ppls_stream_retired_total") == len(res.completed)
    assert reg.value("ppls_stream_admitted_total") == len(REQS)
    # round-10 tail columns live: splits counted, crounds present
    assert res.totals["splits"] > 0
    assert res.totals["crounds"] == 0          # single-chip stream
    # compile-once invariant surfaced as a gauge
    assert reg.value("ppls_compile_cache_entries",
                     engine="walker-stream") == 1.0
    # the shared per-round record (satellite 1)
    assert len(res.per_round) == len(rows)
    assert all(isinstance(p, RoundStats) for p in res.per_round)
    assert sum(p.frontier_width for p in res.per_round) \
        == res.totals["tasks"]
    assert sum(p.splits for p in res.per_round) == res.totals["splits"]


def test_bench_and_serve_read_identical_quantiles():
    """Satellite 6: the bench path (StreamResult.latency_percentiles)
    and the serve summary read the SAME histogram through the SAME
    quantile — identical numbers on identical runs, and a rebuilt
    histogram from the completed list agrees bit-for-bit (ties
    included: this schedule retires several requests with equal phase
    counts)."""
    eng, res = _run_stream(None)
    lat = res.latency_percentiles()
    reg = eng.telemetry.registry
    h = reg.get("ppls_stream_retire_latency_phases").solo()
    assert lat["p50_phases"] == h.quantile(0.5)
    assert lat["p99_phases"] == h.quantile(0.99)
    # the precomputed rolling gauges on /metrics carry the same values
    assert reg.value("ppls_stream_retire_latency_phases_p50") \
        == lat["p50_phases"]
    assert reg.value("ppls_stream_retire_latency_phases_p99") \
        == lat["p99_phases"]
    # transient rebuild (the path a hand-assembled result takes)
    import dataclasses
    bare = dataclasses.replace(res, latency_hist_phases=None,
                               latency_hist_seconds=None)
    lat2 = bare.latency_percentiles()
    assert lat2["p50_phases"] == lat["p50_phases"]
    assert lat2["p99_phases"] == lat["p99_phases"]
    # determinism across a rerun (phases only: seconds are wall clock)
    _, res2 = _run_stream(None)
    lat3 = res2.latency_percentiles()
    assert lat3["p50_phases"] == lat["p50_phases"]
    assert lat3["p99_phases"] == lat["p99_phases"]


def test_stream_events_bit_identical_across_rerun(tmp_path):
    p1, p2 = str(tmp_path / "a.jsonl"), str(tmp_path / "b.jsonl")
    _run_stream(p1)
    _run_stream(p2)
    for p in (p1, p2):
        assert validate_events_text(open(p).read()) == []
    r1, ph1 = _deterministic_events(p1)
    r2, ph2 = _deterministic_events(p2)
    assert r1 == r2            # areas, phases, deltas: bit-identical
    assert ph1 == ph2
    assert len(r1) == len(REQS)


def test_stream_events_survive_kill_and_resume(tmp_path):
    """The acceptance determinism leg: a mid-stream kill + resume
    produces retire records and per-phase delta rows identical to the
    undisturbed run's (union of the crashed prefix and the resumed
    tail), and the resumed engine's registry-sourced totals match."""
    from ppls_tpu.runtime.stream import StreamEngine
    base_ev = str(tmp_path / "base.jsonl")
    _, base_res = _run_stream(base_ev)

    ck = str(tmp_path / "s.ckpt")
    crash_ev = str(tmp_path / "crash.jsonl")
    with pytest.raises(RuntimeError, match="simulated crash"):
        _run_stream(crash_ev, crash_after=3, checkpoint=ck)
    # the crashed file is schema-valid modulo unclosed spans
    assert validate_events_text(open(crash_ev).read(),
                                require_balanced=False) == []

    resume_ev = str(tmp_path / "resume.jsonl")
    tel = Telemetry(events_path=resume_ev)
    eng2 = StreamEngine.resume(ck, "sin_recip_scaled", EPS,
                               telemetry=tel, checkpoint_every=1, **KW)
    k = eng2.next_rid
    while not eng2.idle or k < len(REQS):
        while k < len(REQS) and ARRIVALS[k] <= eng2.phase:
            eng2.submit(*REQS[k])
            k += 1
        eng2.step()
    res2 = eng2.result()
    tel.close()

    # registry replay: totals + quantiles identical to the base run
    assert res2.totals == base_res.totals
    assert np.array_equal(res2.areas, base_res.areas)
    lp, lb = res2.latency_percentiles(), base_res.latency_percentiles()
    assert lp["p50_phases"] == lb["p50_phases"]
    assert lp["p99_phases"] == lb["p99_phases"]

    # the timeline union covers the base run's retire records exactly
    base_r, base_ph = _deterministic_events(base_ev)
    crash_r, crash_ph = _deterministic_events(crash_ev)
    res_r, res_ph = _deterministic_events(resume_ev)
    assert sorted(crash_r + res_r, key=lambda a: a["rid"]) == base_r
    assert crash_ph + res_ph == base_ph


def test_metrics_server_serves_during_live_run():
    from ppls_tpu.runtime.stream import StreamEngine
    tel = Telemetry()
    eng = StreamEngine("sin_recip_scaled", EPS, telemetry=tel, **KW)
    srv = MetricsServer(tel.registry, port=0)
    try:
        for th, b in REQS[:3]:
            eng.submit(th, b)
        eng.step()             # live: resident requests, phase stats
        text = urllib.request.urlopen(srv.url, timeout=10) \
            .read().decode()
        lines = text.splitlines()
        # parseable exposition: every sample line is NAME{...} VALUE
        samples = [ln for ln in lines if not ln.startswith("#")]
        assert samples
        for ln in samples:
            name, val = ln.rsplit(" ", 1)
            assert name and (val == "+Inf" or math.isfinite(float(val)))
        def sample(n):
            return [ln for ln in samples if ln.startswith(n + " ")]
        assert float(sample("ppls_stream_tasks_total")[0]
                     .split()[-1]) > 0
        assert float(sample("ppls_stream_resident")[0]
                     .split()[-1]) == 3
        # scrape again mid-run: counters advance monotonically
        eng.step()
        text2 = urllib.request.urlopen(srv.url, timeout=10) \
            .read().decode()
        t1 = float([ln for ln in text.splitlines()
                    if ln.startswith("ppls_stream_tasks_total ")][0]
                   .split()[-1])
        t2 = float([ln for ln in text2.splitlines()
                    if ln.startswith("ppls_stream_tasks_total ")][0]
                   .split()[-1])
        assert t2 >= t1
    finally:
        srv.close()
    eng.drain()


# ---------------------------------------------------------------------------
# walker per-round record (satellite 1)
# ---------------------------------------------------------------------------

def test_walker_populates_shared_round_stats():
    from ppls_tpu.models.integrands import get_family, get_family_ds
    from ppls_tpu.parallel.walker import integrate_family_walker
    wkw = dict(capacity=1 << 16, lanes=256, roots_per_lane=2,
               refill_slots=2, seg_iters=32, min_active_frac=0.05)
    r = integrate_family_walker(
        get_family("sin_recip_scaled"), get_family_ds("sin_recip_scaled"),
        THETA, BOUNDS, EPS, **wkw)
    pr = r.metrics.per_round
    assert len(pr) == r.cycles > 0
    assert all(isinstance(p, RoundStats) for p in pr)
    # per-cycle device counts reconcile with the run aggregates (the
    # direct-assignment contract: no double counting through
    # record_round)
    assert sum(p.frontier_width for p in pr) == r.metrics.tasks
    assert sum(p.splits for p in pr) == r.metrics.splits
    assert sum(p.leaves for p in pr) == r.metrics.leaves
    assert [p.round_index for p in pr] == list(range(len(pr)))


def test_round_stats_from_rows_helper():
    rows = np.array([[10, 4], [6, 1]])
    out = round_stats_from_rows(rows, ("tasks", "splits"),
                                padded_width=256)
    assert [(p.frontier_width, p.splits, p.leaves) for p in out] \
        == [(10, 4, 6), (6, 1, 5)]
    assert out[0].padded_width == 256
    assert round_stats_from_rows(None, ("tasks", "splits")) == []


# ---------------------------------------------------------------------------
# offline timeline replay (analyze_occupancy --from-events)
# ---------------------------------------------------------------------------

def test_analyze_occupancy_from_events(tmp_path):
    import os
    ev = str(tmp_path / "run.jsonl")
    _run_stream(ev)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    r = subprocess.run(
        [sys.executable, "tools/analyze_occupancy.py", "--from-events",
         ev, "--lanes", str(KW["lanes"])],
        capture_output=True, text=True, cwd=repo, timeout=120)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "retires=6" in r.stdout
    assert "lane_efficiency=" in r.stdout
    assert "retire latency (phases)" in r.stdout
