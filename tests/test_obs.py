"""Unified telemetry layer (ppls_tpu/obs, round 10).

Acceptance surface of the observability tentpole:

* registry semantics (counters/gauges/histograms, labels, the
  deterministic bucket-edge quantile) and Prometheus exposition;
* span tracing: hierarchical JSONL timelines that validate against
  the events schema, with monotonic timestamps;
* the stream engine publishes per-phase device-counted rows into the
  registry (one fetch per boundary — the same host values the phase
  already pulled), and its totals/latency numbers are REGISTRY-
  SOURCED: bench, serve, and the metrics endpoint read one surface;
* events-log DETERMINISM: per-request retire records (areas, phase
  latencies, device-counter deltas) are bit-identical across a rerun
  and across a mid-stream kill-and-resume;
* the live metrics endpoint serves parseable exposition during a run;
* the shared per-round record (RoundStats) now populated by the
  walker cycle path and the stream phases (satellite 1);
* `tools/analyze_occupancy.py --from-events` replays a timeline
  offline (no jax import).
"""

import json
import math
import subprocess
import sys
import urllib.request

import numpy as np
import pytest

from ppls_tpu.obs import (Histogram, MetricsRegistry, MetricsServer,
                          PHASE_BUCKETS, RoundStats, SpanTracer,
                          Telemetry, exp_buckets)
from ppls_tpu.utils.artifact_schema import validate_events_text
from ppls_tpu.utils.metrics import round_stats_from_rows

BOUNDS = (1e-2, 1.0)
EPS = 1e-7
KW = dict(slots=8, chunk=1 << 10, capacity=1 << 16, lanes=256,
          roots_per_lane=2, refill_slots=2, seg_iters=32,
          min_active_frac=0.05)
THETA = 1.0 + np.arange(6) / 6.0
REQS = [(float(t), BOUNDS) for t in THETA]
ARRIVALS = [0, 0, 1, 2, 3, 5]


# ---------------------------------------------------------------------------
# registry
# ---------------------------------------------------------------------------

def test_counter_gauge_semantics():
    reg = MetricsRegistry()
    c = reg.counter("t_total", "help")
    c.inc()
    c.inc(41)
    assert c.value == 42
    with pytest.raises(ValueError, match=">= 0"):
        c.inc(-1)
    g = reg.gauge("g")
    g.set(7)
    g.set(3)
    assert g.value == 3
    g.set_max(9)
    g.set_max(2)
    assert g.value == 9
    # same-name re-registration returns the same family; a kind
    # mismatch is a hard error
    assert reg.counter("t_total").value == 42
    with pytest.raises(ValueError, match="re-registered"):
        reg.gauge("t_total")
    assert reg.value("t_total") == 42
    assert reg.value("never_touched", default=-1) == -1


def test_labeled_children_are_independent():
    reg = MetricsRegistry()
    fam = reg.counter("runs_total", labelnames=("engine",))
    fam.labels(engine="walker").inc(3)
    fam.labels(engine="bag").inc(5)
    assert fam.labels(engine="walker").value == 3
    assert fam.labels(engine="bag").value == 5
    with pytest.raises(ValueError, match="expected labels"):
        fam.labels(rule="simpson")
    with pytest.raises(ValueError, match="use .labels"):
        fam.inc()


def test_exp_buckets_shape():
    assert exp_buckets(1.0, 3) == (1.0, 1.5, 2.0, 3.0, 4.0, 6.0, 8.0)
    # ascending, ends one octave above start * 2^octaves
    assert list(PHASE_BUCKETS) == sorted(PHASE_BUCKETS)
    assert PHASE_BUCKETS[0] == 1.0 and PHASE_BUCKETS[-1] == 4096.0


def test_histogram_quantile_deterministic_under_ties():
    """The satellite-6 regression: equal phase counts must not produce
    order- or interpolation-dependent percentiles. The bucket-edge
    quantile maps every tied observation to the same bucket, so any
    insertion order reports the same p50/p99."""
    obs = [3, 3, 3, 3, 4, 4, 8, 8, 8, 2]
    outs = set()
    for perm in (obs, obs[::-1], sorted(obs)):
        h = Histogram(PHASE_BUCKETS)
        for v in perm:
            h.observe(v)
        outs.add((h.quantile(0.5), h.quantile(0.99)))
    assert len(outs) == 1
    p50, p99 = outs.pop()
    assert p50 == 3.0          # rank ceil(0.5*10)=5 lands in bucket 3
    assert p99 == 8.0
    # np.percentile would interpolate (3.5 between the tied 3s and 4s
    # at even ranks) — the exact defect the shared quantile removes
    assert float(np.percentile(obs, 50)) != p50 or True


def test_histogram_edges_and_overflow():
    h = Histogram((1.0, 2.0, 4.0))
    for v in (0.5, 1.0, 1.5, 4.0, 100.0):
        h.observe(v)
    assert h.count == 5
    assert h.counts == [2, 1, 1, 1]       # le-1, le-2, le-4, +Inf
    assert h.sum == pytest.approx(107.0)
    # p100 falls in the overflow bucket: report the tracked max, not inf
    assert h.quantile(1.0) == 100.0
    assert Histogram((1.0,)).quantile(0.5) is None
    with pytest.raises(ValueError, match="ascending"):
        Histogram((2.0, 1.0))
    with pytest.raises(ValueError, match="in \\[0, 1\\]"):
        h.quantile(1.5)


def test_exposition_prometheus_text():
    reg = MetricsRegistry()
    reg.counter("ppls_runs_total", "runs", ("engine",)) \
        .labels(engine="walker").inc(2)
    reg.gauge("ppls_queue_depth").set(5)
    h = reg.histogram("ppls_lat", "latency", buckets=(1.0, 2.0))
    h.observe(1)
    h.observe(3)
    text = reg.exposition()
    lines = text.splitlines()
    assert '# TYPE ppls_runs_total counter' in lines
    assert 'ppls_runs_total{engine="walker"} 2' in lines
    assert 'ppls_queue_depth 5' in lines
    assert 'ppls_lat_bucket{le="1"} 1' in lines
    assert 'ppls_lat_bucket{le="2"} 1' in lines       # cumulative
    assert 'ppls_lat_bucket{le="+Inf"} 2' in lines
    assert 'ppls_lat_sum 4' in lines
    assert 'ppls_lat_count 2' in lines


# ---------------------------------------------------------------------------
# spans + events schema
# ---------------------------------------------------------------------------

def test_span_tracer_timeline_shape(tmp_path):
    path = str(tmp_path / "ev.jsonl")
    tr = SpanTracer(path, meta={"mode": "test"})
    with tr.span("run", engine="walker"):
        with tr.span("phase", phase=0):
            tr.event("admit", rid=0)
        s = tr.span("phase", phase=1)
        tr.event("retire", rid=0, area=1.5)
        s.close(tasks=100)
    tr.close()
    text = open(path).read()
    assert validate_events_text(text) == []
    recs = [json.loads(ln) for ln in text.splitlines()]
    assert recs[0]["ev"] == "meta"
    assert recs[0]["attrs"] == {"mode": "test"}
    opens = [r for r in recs if r["ev"] == "span_open"]
    closes = [r for r in recs if r["ev"] == "span_close"]
    assert len(opens) == len(closes) == 3
    # hierarchy: both phase spans are children of the run span
    run_id = opens[0]["id"]
    assert [o["parent"] for o in opens] == [None, run_id, run_id]
    # the explicit close carries its summary attrs
    phase1_close = [c for c in closes if c["id"] == opens[2]["id"]][0]
    assert phase1_close["attrs"] == {"tasks": 100}
    # events attach to the innermost open span
    evs = [r for r in recs if r["ev"] == "event"]
    assert evs[0]["span"] == opens[1]["id"]
    # timestamps monotone
    ts = [r["t"] for r in recs]
    assert ts == sorted(ts)


def test_span_tracer_noop_without_path():
    tr = SpanTracer(None)
    with tr.span("run"):
        tr.event("x")
    tr.close()                  # no file, no error
    assert not tr.enabled


def test_events_validator_catches_broken_shapes():
    def probs(lines):
        return validate_events_text("\n".join(json.dumps(r)
                                              for r in lines))
    meta = {"ev": "meta", "schema": "ppls-events-v1", "t": 0.0}
    ok = [meta, {"ev": "span_open", "id": 0, "parent": None,
                 "name": "run", "t": 0.1},
          {"ev": "span_close", "id": 0, "t": 0.2}]
    assert probs(ok) == []
    assert any("backwards" in p for p in probs(
        ok[:2] + [{"ev": "span_close", "id": 0, "t": 0.05}]))
    assert any("unknown ev" in p for p in probs([meta, {"ev": "huh",
                                                        "t": 0.1}]))
    assert any("unopened" in p for p in probs(
        [meta, {"ev": "span_close", "id": 7, "t": 0.1}]))
    assert any("never closed" in p for p in probs(ok[:2]))
    # the crashed-run shape is tolerated when asked
    assert validate_events_text(
        "\n".join(json.dumps(r) for r in ok[:2]),
        require_balanced=False) == []
    # a resume segment restarts the monotonic clock legally
    resumed = ok + [dict(meta), {"ev": "event", "name": "resume",
                                 "t": 0.01}]
    assert probs(resumed) == []
    # ... and restarts the span-id space: a hard-killed first attempt
    # leaves id 0 open, the appended segment reopens id 0 — legal in
    # the crashed-run shape, flagged per segment under balance
    killed_then_resumed = [
        meta,
        {"ev": "span_open", "id": 0, "parent": None, "name": "run",
         "t": 0.1},                    # never closed: hard kill
        dict(meta),
        {"ev": "span_open", "id": 0, "parent": None, "name": "run",
         "t": 0.1},
        {"ev": "span_close", "id": 0, "t": 0.2}]
    text = "\n".join(json.dumps(r) for r in killed_then_resumed)
    assert validate_events_text(text, require_balanced=False) == []
    assert any("segment boundary" in p
               for p in validate_events_text(text))


# ---------------------------------------------------------------------------
# stream engine <-> registry/events integration
# ---------------------------------------------------------------------------

def _deterministic_events(path):
    """Extract the determinism comparison surface from an events file:
    retire records (minus wall-clock latency) and per-phase device-
    counter delta rows."""
    retires, phases = [], []
    for ln in open(path):
        r = json.loads(ln)
        if r["ev"] == "event" and r.get("name") == "retire":
            a = dict(r["attrs"])
            a.pop("latency_s", None)
            retires.append(a)
        elif r["ev"] == "span_close" and r.get("attrs", {}).get(
                "tasks") is not None:
            a = {k: v for k, v in r["attrs"].items()}
            phases.append(a)
    return (sorted(retires, key=lambda a: a["rid"]), phases)


def _run_stream(events_path, crash_after=None, checkpoint=None):
    from ppls_tpu.runtime.stream import StreamEngine
    tel = Telemetry(events_path=events_path)
    eng = StreamEngine("sin_recip_scaled", EPS, telemetry=tel,
                       checkpoint_path=checkpoint, checkpoint_every=1,
                       **KW)
    try:
        res = eng.run(REQS, arrival_phase=ARRIVALS,
                      _crash_after_phases=crash_after)
    finally:
        tel.close()
    return eng, res


def test_stream_totals_are_registry_sourced():
    eng, res = _run_stream(None)
    reg = eng.telemetry.registry
    rows = np.stack(eng._phase_rows)
    from ppls_tpu.parallel.walker import STREAM_STAT_FIELDS
    # the registry counters ARE the phase-row sums (one accounting)
    for i, k in enumerate(STREAM_STAT_FIELDS):
        if k == "maxd":
            continue
        assert reg.value(f"ppls_stream_{k}_total") == rows[:, i].sum(), k
        assert res.totals[k] == int(rows[:, i].sum())
    assert res.totals["maxd"] == int(
        rows[:, STREAM_STAT_FIELDS.index("maxd")].max())
    assert reg.value("ppls_stream_retired_total") == len(res.completed)
    assert reg.value("ppls_stream_admitted_total") == len(REQS)
    # round-10 tail columns live: splits counted, crounds present
    assert res.totals["splits"] > 0
    assert res.totals["crounds"] == 0          # single-chip stream
    # compile-once invariant surfaced on the registry: the cache-entry
    # gauge is live, and the engine's OWN telemetry saw zero growth
    # after its first observation (the absolute entry count belongs to
    # the process-shared run_stream_cycle cache, so earlier tests'
    # configs legitimately inflate it — round 11's recompile counter
    # is the order-robust form of the invariant)
    assert reg.value("ppls_compile_cache_entries",
                     engine="walker-stream") >= 1.0
    assert reg.value("ppls_recompiles_total", engine="walker-stream",
                     default=0.0) == 0.0
    # the shared per-round record (satellite 1)
    assert len(res.per_round) == len(rows)
    assert all(isinstance(p, RoundStats) for p in res.per_round)
    assert sum(p.frontier_width for p in res.per_round) \
        == res.totals["tasks"]
    assert sum(p.splits for p in res.per_round) == res.totals["splits"]


def test_bench_and_serve_read_identical_quantiles():
    """Satellite 6: the bench path (StreamResult.latency_percentiles)
    and the serve summary read the SAME histogram through the SAME
    quantile — identical numbers on identical runs, and a rebuilt
    histogram from the completed list agrees bit-for-bit (ties
    included: this schedule retires several requests with equal phase
    counts)."""
    eng, res = _run_stream(None)
    lat = res.latency_percentiles()
    reg = eng.telemetry.registry
    h = reg.get("ppls_stream_retire_latency_phases").solo()
    assert lat["p50_phases"] == h.quantile(0.5)
    assert lat["p99_phases"] == h.quantile(0.99)
    # the precomputed rolling gauges on /metrics carry the same values
    assert reg.value("ppls_stream_retire_latency_phases_p50") \
        == lat["p50_phases"]
    assert reg.value("ppls_stream_retire_latency_phases_p99") \
        == lat["p99_phases"]
    # transient rebuild (the path a hand-assembled result takes)
    import dataclasses
    bare = dataclasses.replace(res, latency_hist_phases=None,
                               latency_hist_seconds=None)
    lat2 = bare.latency_percentiles()
    assert lat2["p50_phases"] == lat["p50_phases"]
    assert lat2["p99_phases"] == lat["p99_phases"]
    # determinism across a rerun (phases only: seconds are wall clock)
    _, res2 = _run_stream(None)
    lat3 = res2.latency_percentiles()
    assert lat3["p50_phases"] == lat["p50_phases"]
    assert lat3["p99_phases"] == lat["p99_phases"]


def test_stream_events_bit_identical_across_rerun(tmp_path):
    p1, p2 = str(tmp_path / "a.jsonl"), str(tmp_path / "b.jsonl")
    _run_stream(p1)
    _run_stream(p2)
    for p in (p1, p2):
        assert validate_events_text(open(p).read()) == []
    r1, ph1 = _deterministic_events(p1)
    r2, ph2 = _deterministic_events(p2)
    assert r1 == r2            # areas, phases, deltas: bit-identical
    assert ph1 == ph2
    assert len(r1) == len(REQS)


def test_stream_events_survive_kill_and_resume(tmp_path):
    """The acceptance determinism leg: a mid-stream kill + resume
    produces retire records and per-phase delta rows identical to the
    undisturbed run's (union of the crashed prefix and the resumed
    tail), and the resumed engine's registry-sourced totals match."""
    from ppls_tpu.runtime.stream import StreamEngine
    base_ev = str(tmp_path / "base.jsonl")
    _, base_res = _run_stream(base_ev)

    ck = str(tmp_path / "s.ckpt")
    crash_ev = str(tmp_path / "crash.jsonl")
    with pytest.raises(RuntimeError, match="simulated crash"):
        _run_stream(crash_ev, crash_after=3, checkpoint=ck)
    # the crashed file is schema-valid modulo unclosed spans
    assert validate_events_text(open(crash_ev).read(),
                                require_balanced=False) == []

    resume_ev = str(tmp_path / "resume.jsonl")
    tel = Telemetry(events_path=resume_ev)
    eng2 = StreamEngine.resume(ck, "sin_recip_scaled", EPS,
                               telemetry=tel, checkpoint_every=1, **KW)
    k = eng2.next_rid
    while not eng2.idle or k < len(REQS):
        while k < len(REQS) and ARRIVALS[k] <= eng2.phase:
            eng2.submit(*REQS[k])
            k += 1
        eng2.step()
    res2 = eng2.result()
    tel.close()

    # registry replay: totals + quantiles identical to the base run
    assert res2.totals == base_res.totals
    assert np.array_equal(res2.areas, base_res.areas)
    lp, lb = res2.latency_percentiles(), base_res.latency_percentiles()
    assert lp["p50_phases"] == lb["p50_phases"]
    assert lp["p99_phases"] == lb["p99_phases"]

    # the timeline union covers the base run's retire records exactly
    base_r, base_ph = _deterministic_events(base_ev)
    crash_r, crash_ph = _deterministic_events(crash_ev)
    res_r, res_ph = _deterministic_events(resume_ev)
    assert sorted(crash_r + res_r, key=lambda a: a["rid"]) == base_r
    assert crash_ph + res_ph == base_ph


def test_metrics_server_serves_during_live_run():
    from ppls_tpu.runtime.stream import StreamEngine
    tel = Telemetry()
    eng = StreamEngine("sin_recip_scaled", EPS, telemetry=tel, **KW)
    srv = MetricsServer(tel.registry, port=0)
    try:
        for th, b in REQS[:3]:
            eng.submit(th, b)
        eng.step()             # live: resident requests, phase stats
        text = urllib.request.urlopen(srv.url, timeout=10) \
            .read().decode()
        lines = text.splitlines()
        # parseable exposition: every sample line is NAME{...} VALUE
        samples = [ln for ln in lines if not ln.startswith("#")]
        assert samples
        for ln in samples:
            name, val = ln.rsplit(" ", 1)
            assert name and (val == "+Inf" or math.isfinite(float(val)))
        def sample(n):
            return [ln for ln in samples if ln.startswith(n + " ")]
        assert float(sample("ppls_stream_tasks_total")[0]
                     .split()[-1]) > 0
        assert float(sample("ppls_stream_resident")[0]
                     .split()[-1]) == 3
        # scrape again mid-run: counters advance monotonically
        eng.step()
        text2 = urllib.request.urlopen(srv.url, timeout=10) \
            .read().decode()
        t1 = float([ln for ln in text.splitlines()
                    if ln.startswith("ppls_stream_tasks_total ")][0]
                   .split()[-1])
        t2 = float([ln for ln in text2.splitlines()
                    if ln.startswith("ppls_stream_tasks_total ")][0]
                   .split()[-1])
        assert t2 >= t1
    finally:
        srv.close()
    eng.drain()


# ---------------------------------------------------------------------------
# walker per-round record (satellite 1)
# ---------------------------------------------------------------------------

def test_walker_populates_shared_round_stats():
    from ppls_tpu.models.integrands import get_family, get_family_ds
    from ppls_tpu.parallel.walker import integrate_family_walker
    wkw = dict(capacity=1 << 16, lanes=256, roots_per_lane=2,
               refill_slots=2, seg_iters=32, min_active_frac=0.05)
    r = integrate_family_walker(
        get_family("sin_recip_scaled"), get_family_ds("sin_recip_scaled"),
        THETA, BOUNDS, EPS, **wkw)
    pr = r.metrics.per_round
    assert len(pr) == r.cycles > 0
    assert all(isinstance(p, RoundStats) for p in pr)
    # per-cycle device counts reconcile with the run aggregates (the
    # direct-assignment contract: no double counting through
    # record_round)
    assert sum(p.frontier_width for p in pr) == r.metrics.tasks
    assert sum(p.splits for p in pr) == r.metrics.splits
    assert sum(p.leaves for p in pr) == r.metrics.leaves
    assert [p.round_index for p in pr] == list(range(len(pr)))


def test_round_stats_from_rows_helper():
    rows = np.array([[10, 4], [6, 1]])
    out = round_stats_from_rows(rows, ("tasks", "splits"),
                                padded_width=256)
    assert [(p.frontier_width, p.splits, p.leaves) for p in out] \
        == [(10, 4, 6), (6, 1, 5)]
    assert out[0].padded_width == 256
    assert round_stats_from_rows(None, ("tasks", "splits")) == []


# ---------------------------------------------------------------------------
# offline timeline replay (analyze_occupancy --from-events)
# ---------------------------------------------------------------------------

# ---------------------------------------------------------------------------
# round 11: exposition escaping, compile events, flight recorder
# ---------------------------------------------------------------------------

def test_exposition_escapes_hostile_label_values():
    """Satellite regression: backslash/quote/newline in a label value
    must render as the text format's escapes, or the whole exposition
    becomes unparseable to a scraper."""
    reg = MetricsRegistry()
    hostile = 'bad"fam\\ily\nname'
    reg.counter("ppls_h_total", "h", ("family",)) \
        .labels(family=hostile).inc(3)
    reg.counter("ppls_help_total", 'why "quotes" and \\slashes\nhurt')
    text = reg.exposition()
    assert 'family="bad\\"fam\\\\ily\\nname"' in text
    # every line stays single-line and parses as NAME{...} VALUE
    for ln in text.splitlines():
        assert "\n" not in ln
        if not ln.startswith("#"):
            name, val = ln.rsplit(" ", 1)
            float(val)
    assert "# HELP ppls_help_total " \
        'why "quotes" and \\\\slashes\\nhurt' in text


def test_compile_events_and_recompile_counter(tmp_path):
    """Compile observability (round-11 tentpole c): the first phase
    records a jit_cache_entry baseline event; a recompile (different
    compile statics through the same telemetry handle) emits a growth
    event, bumps ppls_recompiles_total, and attributes compile wall."""
    from ppls_tpu.runtime.stream import StreamEngine
    ev = str(tmp_path / "c.jsonl")
    tel = Telemetry(events_path=ev)
    eng = StreamEngine("sin_recip_scaled", EPS, telemetry=tel, **KW)
    eng.run(REQS[:2])
    reg = tel.registry
    # compile-once holds: gauge present, zero recompiles
    assert reg.value("ppls_compile_cache_entries",
                     engine="walker-stream") >= 1
    assert reg.value("ppls_recompiles_total", engine="walker-stream",
                     default=0.0) == 0
    # force a recompile: a second engine with different compile
    # statics (slots -> m) sharing the SAME telemetry handle
    eng2 = StreamEngine("sin_recip_scaled", EPS, telemetry=tel,
                        **dict(KW, slots=5))
    eng2.run(REQS[:2])
    assert reg.value("ppls_recompiles_total",
                     engine="walker-stream") >= 1
    assert reg.value("ppls_compile_wall_seconds_total",
                     engine="walker-stream") > 0
    tel.close()
    recs = [json.loads(ln) for ln in open(ev)]
    cache_evs = [r for r in recs if r["ev"] == "event"
                 and r["name"] == "jit_cache_entry"]
    assert cache_evs, "no jit_cache_entry events in the timeline"
    growth = [r for r in cache_evs if r["attrs"]["new_entries"] > 0]
    assert growth and growth[0]["attrs"]["engine"] == "walker-stream"


def test_batch_walker_publishes_waste_and_compile():
    from ppls_tpu.models.integrands import get_family, get_family_ds
    from ppls_tpu.obs.telemetry import (Telemetry as _T, set_default)
    from ppls_tpu.parallel.walker import integrate_family_walker
    tel = _T()
    prev = set_default(tel)
    try:
        wkw = dict(capacity=1 << 16, lanes=256, roots_per_lane=2,
                   refill_slots=2, seg_iters=32, min_active_frac=0.05)
        r = integrate_family_walker(
            get_family("sin_recip_scaled"),
            get_family_ds("sin_recip_scaled"),
            THETA, BOUNDS, EPS, **wkw)
        reg = tel.registry
        total = sum(reg.value("ppls_lane_cycles_total",
                              engine="walker", bucket=b)
                    for b in ("eval_active", "masked_dead",
                              "refill_stall", "drain_tail"))
        assert total == r.kernel_steps * r.lanes
        assert reg.value("ppls_compile_cache_entries",
                         engine="walker") >= 1
    finally:
        set_default(prev)


def test_flight_recorder_straggler_detector():
    """Unit-level straggler contract: a chip whose kernel-step share
    exceeds the threshold for K CONSECUTIVE phases fires exactly one
    straggler event (then the streak restarts); an interrupted streak
    fires nothing."""
    from ppls_tpu.obs import ChipFlightRecorder
    tel = Telemetry()
    fr = ChipFlightRecorder(tel, 4, engine="t", straggler_share=0.5,
                            straggler_phases=3)
    skew = dict(tasks=[0] * 4, live_rows=[1] * 4, bank_delta=[0] * 4)
    hot = [90, 3, 3, 4]          # chip 0 share 0.9 > 0.5
    cold = [25, 25, 25, 25]
    # two hot phases, one cold (streak broken), two hot: no event yet
    for w in (hot, hot, cold, hot, hot):
        fr.record_phase(0, wsteps=w, **skew)
    assert tel.registry.value("ppls_straggler_events_total",
                              engine="t", default=0.0) == 0
    fr.record_phase(5, wsteps=hot, **skew)      # third consecutive
    assert tel.registry.value("ppls_straggler_events_total",
                              engine="t") == 1
    # streak restarted: two more hot phases don't re-fire ...
    fr.record_phase(6, wsteps=hot, **skew)
    fr.record_phase(7, wsteps=hot, **skew)
    assert tel.registry.value("ppls_straggler_events_total",
                              engine="t") == 1
    fr.record_phase(8, wsteps=hot, **skew)      # ... the third does
    assert tel.registry.value("ppls_straggler_events_total",
                              engine="t") == 2
    # chip-balance gauges live
    assert tel.registry.value("ppls_chip_spread", engine="t") > 1.0


def test_flight_recorder_emits_chip_spans_and_gauges(tmp_path):
    from ppls_tpu.obs import ChipFlightRecorder
    ev = str(tmp_path / "fr.jsonl")
    tel = Telemetry(events_path=ev)
    fr = ChipFlightRecorder(tel, 2, engine="t")
    with tel.span("phase", phase=0):
        fr.record_phase(0, wsteps=[10, 30], tasks=[5, 15],
                        live_rows=[100, 300], bank_delta=[-5, 5],
                        waste=[[8, 0, 1, 1], [25, 0, 2, 3]],
                        crounds=2)
    tel.close()
    text = open(ev).read()
    assert validate_events_text(text) == []
    recs = [json.loads(ln) for ln in text.splitlines()]
    phase_id = [r["id"] for r in recs if r["ev"] == "span_open"
                and r["name"] == "phase"][0]
    chips = [r for r in recs if r["ev"] == "span_open"
             and r["name"] == "chip"]
    assert [c["attrs"]["chip"] for c in chips] == [0, 1]
    assert all(c["parent"] == phase_id for c in chips)
    closes = {r["id"]: r["attrs"] for r in recs
              if r["ev"] == "span_close"}
    assert closes[chips[1]["id"]]["wsteps"] == 30
    assert closes[chips[1]["id"]]["eval_active"] == 25
    assert closes[chips[0]["id"]]["bank_delta"] == -5
    colls = [r for r in recs if r["ev"] == "event"
             and r["name"] == "collective_boundary"]
    assert len(colls) == 1 and colls[0]["attrs"]["crounds"] == 2
    assert tel.registry.value("ppls_chip_occupancy_max",
                              engine="t") == 300
    assert tel.registry.value("ppls_chip_occupancy_min",
                              engine="t") == 100
    assert tel.registry.value("ppls_chip_occupancy_spread",
                              engine="t") == 3.0


def test_events_validator_multi_segment_with_chip_spans():
    """Satellite 3: a RESUMED (multi-meta-segment) timeline carrying
    per-chip child spans must validate — balance and t-monotonicity
    hold PER SEGMENT — and an in-segment backwards timestamp or an
    unbalanced chip span is still caught."""
    def seg(t0, phases=1):
        out = [{"ev": "meta", "schema": "ppls-events-v1", "t": 0.0}]
        sid = 0
        t = t0
        for p in range(phases):
            out.append({"ev": "span_open", "id": sid, "parent": None,
                        "name": "phase", "t": t})
            pid = sid
            sid += 1
            for chip in range(2):
                out.append({"ev": "span_open", "id": sid,
                            "parent": pid, "name": "chip", "t": t,
                            "attrs": {"chip": chip}})
                out.append({"ev": "span_close", "id": sid, "t": t,
                            "attrs": {"wsteps": 7 + chip}})
                sid += 1
            t += 0.5
            out.append({"ev": "span_close", "id": pid, "t": t,
                        "attrs": {"tasks": 10}})
        return out

    # two segments; the second restarts the monotonic clock BELOW the
    # first's last t — legal across a meta boundary
    recs = seg(5.0, phases=2) + seg(0.1, phases=1)
    text = "\n".join(json.dumps(r) for r in recs)
    assert validate_events_text(text) == []

    # backwards t INSIDE the resumed segment: flagged
    bad = list(recs)
    bad.append({"ev": "event", "name": "x", "t": 0.0})
    assert any("backwards" in p for p in validate_events_text(
        "\n".join(json.dumps(r) for r in bad)))

    # a chip span left open at the crash point: flagged under balance,
    # tolerated in the crashed-run shape
    crash = recs + [{"ev": "meta", "schema": "ppls-events-v1",
                     "t": 0.0},
                    {"ev": "span_open", "id": 0, "parent": None,
                     "name": "phase", "t": 0.1},
                    {"ev": "span_open", "id": 1, "parent": 0,
                     "name": "chip", "t": 0.1}]
    text_c = "\n".join(json.dumps(r) for r in crash)
    assert any("never closed" in p for p in
               validate_events_text(text_c))
    assert validate_events_text(text_c, require_balanced=False) == []


def test_analyze_occupancy_from_events(tmp_path):
    import os
    ev = str(tmp_path / "run.jsonl")
    _run_stream(ev)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    r = subprocess.run(
        [sys.executable, "tools/analyze_occupancy.py", "--from-events",
         ev, "--lanes", str(KW["lanes"])],
        capture_output=True, text=True, cwd=repo, timeout=120)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "retires=6" in r.stdout
    assert "lane_efficiency=" in r.stdout
    assert "retire latency (phases)" in r.stdout
