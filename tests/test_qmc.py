"""8D Genz QMC tests on the virtual 8-device mesh (BASELINE config #5)."""

import numpy as np
import pytest

from ppls_tpu.models.genz import GENZ, genz_params, get_genz
from ppls_tpu.parallel.mesh import make_mesh
from ppls_tpu.parallel.qmc import integrate_qmc

D = 8
N = 1 << 16  # CI size; the bench uses 2^18/2^20

# Measured on the larger 2^18 lattice: worst family (oscillatory, small
# exact value) ~1e-3 relative; the others 1e-6..1e-4. CI tolerances at
# N=2^16 are ~4x looser (rank-1 lattice, ~O(1/N)).
TOL_REL = {
    "oscillatory": 2e-2,
    "product_peak": 1e-3,
    "corner_peak": 1e-3,
    "gaussian": 1e-3,
    "continuous": 1e-3,
    "discontinuous": 5e-3,
}


@pytest.mark.parametrize("name", sorted(GENZ))
def test_genz_family_within_tolerance(name):
    fam = get_genz(name)
    a, u = genz_params(name, D, seed=0)
    exact = fam.exact(a, u)
    r = integrate_qmc(fam.fn, a, u, n_points=N, mesh=make_mesh(8),
                      fn_name=name, exact=exact)
    rel = abs(r.value - exact) / max(abs(exact), 1e-300)
    assert rel < TOL_REL[name], (name, rel, exact, r.value)
    assert r.std_error >= 0.0
    assert r.metrics.n_chips == 8


def test_mesh_size_invariance():
    # The lattice and shifts are defined by (N, a_gen, seed) alone, so
    # the estimate is EXACTLY the mesh-partitioned same sum: 1 vs 8
    # chips agree to reduction-order noise.
    fam = get_genz("gaussian")
    a, u = genz_params("gaussian", D, seed=0)
    r1 = integrate_qmc(fam.fn, a, u, n_points=N, mesh=make_mesh(1),
                       fn_name="gaussian")
    r8 = integrate_qmc(fam.fn, a, u, n_points=N, mesh=make_mesh(8),
                       fn_name="gaussian")
    assert abs(r1.value - r8.value) < 1e-12


def test_deterministic():
    fam = get_genz("continuous")
    a, u = genz_params("continuous", D, seed=3)
    kw = dict(n_points=N, mesh=make_mesh(8), fn_name="continuous")
    assert integrate_qmc(fam.fn, a, u, **kw).value \
        == integrate_qmc(fam.fn, a, u, **kw).value


def test_stderr_brackets_error():
    # The shifted-lattice standard error should be the right order of
    # magnitude: the true error within 10 sigma for a smooth family.
    fam = get_genz("gaussian")
    a, u = genz_params("gaussian", D, seed=1)
    exact = fam.exact(a, u)
    r = integrate_qmc(fam.fn, a, u, n_points=N, mesh=make_mesh(8),
                      fn_name="gaussian", exact=exact)
    assert r.abs_error < 10.0 * max(r.std_error, 1e-12), \
        (r.abs_error, r.std_error)


def test_bad_args_rejected():
    fam = get_genz("gaussian")
    a, u = genz_params("gaussian", D, seed=0)
    with pytest.raises(ValueError, match="n_points"):
        integrate_qmc(fam.fn, a, u, n_points=12345, mesh=make_mesh(8))
