"""Round 12: range-reduced integrand forms vs the reference model zoo.

The ulp-equivalence protocol (BASELINE.md round 12): each reduced
form, evaluated as its plain-f64 model, must sit within the stated ulp
budget of the MPMATH ground truth of the reference integrand over the
bench domains — this verifies the mathematical identity and its f64
conditioning independently of ds arithmetic. The ds twins are then
held to the ds-level contract against the same references, and the
selection surface (``get_family_ds(..., reduced=True)``) is pinned.
"""

import math

import numpy as np
import pytest

from ppls_tpu.models.integrands import (
    DS_FAMILIES_REDUCED,
    cosh4_scaled_reduced_f64,
    family_exact,
    get_family,
    get_family_ds,
    sin_recip_scaled_reduced_f64,
)


def _ulps(a, ref):
    return np.abs(a - ref) / np.spacing(np.abs(ref))


# ---------------------------------------------------------------------------
# f64 ulp equivalence of the reduced forms (the identity itself)
# ---------------------------------------------------------------------------


def test_cosh4_reduced_f64_within_one_ulp_of_ground_truth():
    # bench domain of the reference problem: u = theta*x in [0, 10]
    # (theta <= 2 over [0, 5]). The reduced form must be AT LEAST as
    # close to ground truth as the reference f64 form — measured, it
    # is ~2.5x closer (the power-reduction identity removes the error
    # doubling of the reference's two squarings).
    import mpmath
    rng = np.random.default_rng(12)
    u = rng.uniform(0.0, 10.0, 400)
    red = cosh4_scaled_reduced_f64(u, 1.0)
    ref_f64 = (np.cosh(u) ** 2) ** 2
    with mpmath.workdps(40):
        truth = np.array([float(mpmath.cosh(mpmath.mpf(float(v))) ** 4)
                          for v in u])
    red_ulp = _ulps(red, truth)
    ref_ulp = _ulps(ref_f64, truth)
    assert red_ulp.max() <= 2.0, red_ulp.max()
    # strictly tighter than the reference form on its own worst cases
    assert red_ulp.max() < ref_ulp.max(), (red_ulp.max(), ref_ulp.max())
    assert red_ulp.mean() <= 1.0


def test_sin_recip_reduced_f64_within_one_ulp_of_reference():
    # bench domain: theta/x over [1e-4, 1] with theta in [1, 2] —
    # arguments up to 2e4. The pi-reduced form must agree with the
    # reference np.sin evaluation to <= 1 ulp everywhere.
    rng = np.random.default_rng(7)
    x = rng.uniform(1e-4, 1.0, 4000)
    for th in (1.0, 1.5, 1.9999):
        red = sin_recip_scaled_reduced_f64(x, th)
        ref = np.sin(th / x)
        d = np.abs(red - ref) / np.spacing(np.maximum(np.abs(ref),
                                                      1e-300))
        assert d.max() <= 1.0, (th, d.max())


# ---------------------------------------------------------------------------
# ds twins: reduced vs reference at the ds contract level
# ---------------------------------------------------------------------------


def _eval_ds(f_ds, x64, th):
    import jax.numpy as jnp
    x = jnp.asarray(x64, jnp.float64)
    xh = x.astype(jnp.float32)
    xl = (x - xh.astype(jnp.float64)).astype(jnp.float32)
    t = jnp.full_like(x, th)
    th_h = t.astype(jnp.float32)
    th_l = (t - th_h.astype(jnp.float64)).astype(jnp.float32)
    from ppls_tpu.ops import ds  # the fenced module: correct under XLA
    hi, lo = f_ds((xh, xl), (th_h, th_l), dsm=ds)
    return np.asarray(hi, np.float64) + np.asarray(lo, np.float64)


@pytest.mark.parametrize("name,domain,th,tol", [
    ("sin_recip_scaled", (1e-2, 1.0), 1.5, 5e-7),
    ("sin_scaled", (0.0, 50.0), 1.5, 5e-7),
    ("cosh4_scaled", (0.0, 5.0), 1.5, 2e-6),
])
def test_reduced_ds_twin_matches_reference_twin(name, domain, th, tol):
    # XLA-level (fenced-ds) pointwise agreement between the reduced and
    # reference twins; tolerance is relative to the value scale (the
    # interpret-mode ds contract, see walker.py's accuracy caveat)
    rng = np.random.default_rng(3)
    x = rng.uniform(domain[0] + 1e-9, domain[1], 2000)
    ref = _eval_ds(get_family_ds(name), x, th)
    red = _eval_ds(get_family_ds(name, reduced=True), x, th)
    scale = np.maximum(np.abs(ref), 1.0)
    assert np.max(np.abs(red - ref) / scale) < tol


def test_ds_sin_pi_matches_ds_sin_kernel_module():
    # the in-kernel reduced primitive vs the reference kernel sin,
    # across several pi-multiples and large arguments
    import jax.numpy as jnp
    from ppls_tpu.ops import ds_kernel as dsk
    rng = np.random.default_rng(5)
    x = np.concatenate([
        rng.uniform(-50.0, 50.0, 2000),
        rng.uniform(-2.0 ** 22, 2.0 ** 22, 2000),
        np.pi * np.arange(-8, 9),               # reduction boundaries
    ])
    xh = jnp.asarray(x).astype(jnp.float32)
    xl = (jnp.asarray(x) - xh.astype(jnp.float64)).astype(jnp.float32)
    a = dsk.ds_sin((xh, xl))
    b = dsk.ds_sin_pi((xh, xl))
    va = np.asarray(a[0], np.float64) + np.asarray(a[1], np.float64)
    vb = np.asarray(b[0], np.float64) + np.asarray(b[1], np.float64)
    # both are interpret-mode ds evaluations of the same function: they
    # agree to the (XLA-degraded) ds level
    assert np.max(np.abs(va - vb)) < 1e-6
    # and near zero-crossings of sin the absolute agreement holds too
    assert np.max(np.abs(vb - np.sin(x))) < 1e-5


# ---------------------------------------------------------------------------
# registry + end-to-end selection
# ---------------------------------------------------------------------------


def test_reduced_registry_and_fallback():
    assert {"cosh4_scaled", "sin_recip_scaled",
            "sin_scaled"} <= set(DS_FAMILIES_REDUCED)
    # families without a reduced twin fall back to the reference twin
    assert get_family_ds("gauss_center", reduced=True) \
        is get_family_ds("gauss_center")
    # reduced twins carry the SAME domain checks as the reference
    f = get_family_ds("sin_recip_scaled", reduced=True)
    with pytest.raises(ValueError, match="Cody-Waite"):
        f.ds_domain_check(np.array([[1e-9, 1.0]]), np.array([100.0]))


def test_cosh4_family_exact_reference_problem():
    # the registered closed form reproduces the reference problem's
    # exact integral (SURVEY.md section 0)
    v = family_exact("cosh4_scaled", 0.0, 5.0, [1.0])[0]
    assert abs(v - 7583461.361497) < 1e-5
    # and the antiderivative identity holds at another theta
    v2 = family_exact("cosh4_scaled", 0.0, 2.0, [2.0])[0]
    u = 4.0
    want = (3 * u / 8 + math.sinh(2 * u) / 4 + math.sinh(4 * u) / 32) / 2.0
    assert abs(v2 - want) < 1e-9 * abs(want)


def test_walker_runs_reduced_cosh4_to_reference_area():
    # end to end: the flagship walker integrates the REFERENCE problem
    # (cosh^4 on [0, 5]) through the reduced twin, scout + double
    # buffer on, and lands on the closed-form area at the interpret-
    # mode ds tolerance
    from ppls_tpu.parallel.walker import integrate_family_walker
    theta = np.array([1.0])
    exact = family_exact("cosh4_scaled", 0.0, 5.0, theta)[0]
    r = integrate_family_walker(
        get_family("cosh4_scaled"),
        get_family_ds("cosh4_scaled", reduced=True),
        theta, (0.0, 5.0), 1e-6,
        capacity=1 << 16, lanes=256, roots_per_lane=2, refill_slots=2,
        seg_iters=32, min_active_frac=0.05,
        scout_dtype="f32", double_buffer=True)
    assert abs(r.areas[0] - exact) / exact < 1e-6
    assert r.scout_evals > 0
