"""Deterministic / exact reduction primitive tests."""

import numpy as np
import pytest

import jax.numpy as jnp

from ppls_tpu.ops.reduction import (
    exact_segment_sum,
    kahan_add,
    kahan_init,
    kahan_sum,
    segment_sum_auto,
)


def _ground_truth(fam, leaf, m):
    out = np.zeros(m)
    np.add.at(out, fam, leaf)
    return out


@pytest.mark.parametrize("m", [300, 1024, 4096])
def test_exact_segment_sum_matches_np(m):
    rng = np.random.default_rng(7)
    n = 1 << 12
    fam = rng.integers(0, m, n).astype(np.int32)
    # wide dynamic range + signs, like adaptive-quadrature leaf areas
    leaf = rng.uniform(-1, 1, n) * 10.0 ** rng.uniform(-12, -3, n)
    leaf *= rng.random(n) < 0.5
    seg = np.asarray(exact_segment_sum(jnp.asarray(fam), jnp.asarray(leaf),
                                       m, n))
    ref = _ground_truth(fam, leaf, m)
    # "exact" = at or below one ulp of a sequential f64 accumulation
    assert np.abs(seg - ref).max() < 1e-17


def test_exact_segment_sum_wide_dynamic_range():
    """A tiny family sharing a chunk with an O(1) family must not be
    zeroed (72-bit digit coverage; absolute error <= n*amax*2^-73)."""
    n = 512
    fam = np.zeros(n, dtype=np.int32)
    fam[1] = 1
    leaf = np.zeros(n)
    leaf[0] = 1.0
    leaf[1] = 1e-17
    seg = np.asarray(exact_segment_sum(jnp.asarray(fam), jnp.asarray(leaf),
                                       300, n))
    assert seg[0] == 1.0
    assert abs(seg[1] - 1e-17) < 1e-21


def test_exact_segment_sum_empty_and_single():
    n = 256
    fam = jnp.zeros(n, dtype=jnp.int32)
    seg = np.asarray(exact_segment_sum(fam, jnp.zeros(n), 300, n))
    assert np.all(seg == 0.0)
    leaf = jnp.zeros(n).at[3].set(0.125)
    seg = np.asarray(exact_segment_sum(fam, leaf, 300, n))
    assert seg[0] == 0.125 and np.all(seg[1:] == 0.0)


def test_exact_segment_sum_beats_f32_matmul():
    """The accumulation that motivated this op: many same-sign terms
    whose f32 matmul reduction visibly drifts."""
    rng = np.random.default_rng(1)
    n = 1 << 14
    m = 512
    fam = rng.integers(0, m, n).astype(np.int32)
    leaf = rng.uniform(1e-8, 2e-7, n)
    ref = _ground_truth(fam, leaf, m)
    seg = np.asarray(exact_segment_sum(jnp.asarray(fam), jnp.asarray(leaf),
                                       m, n))
    assert np.abs(seg - ref).max() < 1e-18

    oh = (fam[:, None] == np.arange(m)[None, :]).astype(np.float32)
    f32_err = np.abs(leaf.astype(np.float32) @ oh - ref).max()
    assert f32_err > 1e-12  # the naive path really is that bad


def _dyadic_leaves(rng, n):
    """Leaf values on a coarse dyadic grid: every partial sum is
    exactly representable in f64, so any two EXACT lowerings of the
    same segmented sum must agree to the bit."""
    return (rng.integers(-(1 << 20), 1 << 20, n) * 2.0 ** -24)


def test_segment_sum_auto_force_exact_routes_small_m():
    """Round 20: force_exact sends the m == 1 and m <= 256 tiers
    through the error-free digit-plane path instead of the plain XLA
    reduce — segment_sum_auto becomes exact_segment_sum verbatim."""
    rng = np.random.default_rng(11)
    n = 1 << 10
    for m in (1, 64, 256):
        fam = rng.integers(0, m, n).astype(np.int32)
        leaf = rng.uniform(-1, 1, n) * 10.0 ** rng.uniform(-9, -3, n)
        forced = np.asarray(segment_sum_auto(
            jnp.asarray(fam), jnp.asarray(leaf), m, n,
            force_exact=True))
        direct = np.asarray(exact_segment_sum(
            jnp.asarray(fam), jnp.asarray(leaf), m, n))
        assert np.array_equal(forced, direct), m


def test_segment_sum_auto_force_exact_mesh_bit_equality():
    """The tier-boundary regression force_exact exists for: the
    sharded walker reduces m_local <= 256 per shard (mask tier) while
    the single chip reduces m = 1024 (digit-plane tier), so the two
    layouts can differ by ~1 ulp. With force_exact both layouts run
    the exact lowering, and on exactly-representable sums a single
    chip and a virtual 8-mesh agree TO THE BIT, shard by shard."""
    rng = np.random.default_rng(23)
    n, m, shards = 1 << 12, 1024, 8
    m_local = m // shards
    fam = rng.integers(0, m, n).astype(np.int32)
    leaf = _dyadic_leaves(rng, n)
    whole = np.asarray(segment_sum_auto(
        jnp.asarray(fam), jnp.asarray(leaf), m, n, force_exact=True))
    for d in range(shards):
        pick = (fam // m_local) == d
        lf, lv = fam[pick] % m_local, leaf[pick]
        local = np.asarray(segment_sum_auto(
            jnp.asarray(lf), jnp.asarray(lv), m_local, len(lv),
            force_exact=True))
        assert np.array_equal(local,
                              whole[d * m_local:(d + 1) * m_local]), d
    # and the forced path is still RIGHT, not merely consistent
    assert np.array_equal(whole, _ground_truth(fam, leaf, m))


def test_segment_sum_auto_env_knob(monkeypatch):
    rng = np.random.default_rng(5)
    n, m = 512, 128
    fam = rng.integers(0, m, n).astype(np.int32)
    leaf = rng.uniform(-1, 1, n) * 1e-6
    exact = np.asarray(exact_segment_sum(
        jnp.asarray(fam), jnp.asarray(leaf), m, n))
    monkeypatch.setenv("PPLS_EXACT_SEGSUM", "1")
    via_env = np.asarray(segment_sum_auto(
        jnp.asarray(fam), jnp.asarray(leaf), m, n))
    assert np.array_equal(via_env, exact)
    # 0/off spellings keep the default tier routing
    for off in ("0", "off", "false"):
        monkeypatch.setenv("PPLS_EXACT_SEGSUM", off)
        default = np.asarray(segment_sum_auto(
            jnp.asarray(fam), jnp.asarray(leaf), m, n))
        assert np.abs(default - exact).max() < 1e-18


def test_kahan_accumulates_small_terms():
    acc = kahan_init()
    for _ in range(1000):
        acc = kahan_add(acc, jnp.float64(1e-16))
    total = float(kahan_sum(kahan_add(acc, jnp.float64(1.0))))
    assert total == pytest.approx(1.0 + 1e-13, abs=1e-18)
