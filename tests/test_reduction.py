"""Deterministic / exact reduction primitive tests."""

import numpy as np
import pytest

import jax.numpy as jnp

from ppls_tpu.ops.reduction import (
    exact_segment_sum,
    kahan_add,
    kahan_init,
    kahan_sum,
)


def _ground_truth(fam, leaf, m):
    out = np.zeros(m)
    np.add.at(out, fam, leaf)
    return out


@pytest.mark.parametrize("m", [300, 1024, 4096])
def test_exact_segment_sum_matches_np(m):
    rng = np.random.default_rng(7)
    n = 1 << 12
    fam = rng.integers(0, m, n).astype(np.int32)
    # wide dynamic range + signs, like adaptive-quadrature leaf areas
    leaf = rng.uniform(-1, 1, n) * 10.0 ** rng.uniform(-12, -3, n)
    leaf *= rng.random(n) < 0.5
    seg = np.asarray(exact_segment_sum(jnp.asarray(fam), jnp.asarray(leaf),
                                       m, n))
    ref = _ground_truth(fam, leaf, m)
    # "exact" = at or below one ulp of a sequential f64 accumulation
    assert np.abs(seg - ref).max() < 1e-17


def test_exact_segment_sum_wide_dynamic_range():
    """A tiny family sharing a chunk with an O(1) family must not be
    zeroed (72-bit digit coverage; absolute error <= n*amax*2^-73)."""
    n = 512
    fam = np.zeros(n, dtype=np.int32)
    fam[1] = 1
    leaf = np.zeros(n)
    leaf[0] = 1.0
    leaf[1] = 1e-17
    seg = np.asarray(exact_segment_sum(jnp.asarray(fam), jnp.asarray(leaf),
                                       300, n))
    assert seg[0] == 1.0
    assert abs(seg[1] - 1e-17) < 1e-21


def test_exact_segment_sum_empty_and_single():
    n = 256
    fam = jnp.zeros(n, dtype=jnp.int32)
    seg = np.asarray(exact_segment_sum(fam, jnp.zeros(n), 300, n))
    assert np.all(seg == 0.0)
    leaf = jnp.zeros(n).at[3].set(0.125)
    seg = np.asarray(exact_segment_sum(fam, leaf, 300, n))
    assert seg[0] == 0.125 and np.all(seg[1:] == 0.0)


def test_exact_segment_sum_beats_f32_matmul():
    """The accumulation that motivated this op: many same-sign terms
    whose f32 matmul reduction visibly drifts."""
    rng = np.random.default_rng(1)
    n = 1 << 14
    m = 512
    fam = rng.integers(0, m, n).astype(np.int32)
    leaf = rng.uniform(1e-8, 2e-7, n)
    ref = _ground_truth(fam, leaf, m)
    seg = np.asarray(exact_segment_sum(jnp.asarray(fam), jnp.asarray(leaf),
                                       m, n))
    assert np.abs(seg - ref).max() < 1e-18

    oh = (fam[:, None] == np.arange(m)[None, :]).astype(np.float32)
    f32_err = np.abs(leaf.astype(np.float32) @ oh - ref).max()
    assert f32_err > 1e-12  # the naive path really is that bad


def test_kahan_accumulates_small_terms():
    acc = kahan_init()
    for _ in range(1000):
        acc = kahan_add(acc, jnp.float64(1e-16))
    total = float(kahan_sum(kahan_add(acc, jnp.float64(1.0))))
    assert total == pytest.approx(1.0 + 1e-13, abs=1e-18)
