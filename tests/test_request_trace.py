"""Request-scoped observability (round 19, ISSUE 14).

Acceptance surface of the observability tentpole:

* DISTRIBUTED REQUEST TRACING — every rid gets a causal trace
  (detached ``request`` span + child events: queue wait, token-bucket
  wait, admission, per-phase residency linked to phase spans,
  spillover hand-off, redeal-after-host-loss, shed, quarantine,
  retirement), on the single-process StreamEngine AND the cluster
  coordinator (trace context over the worker RPC); the per-rid
  timeline's deterministic events replay BIT-FOR-BIT across
  kill-and-resume;
* FEDERATED CLUSTER METRICS — worker registry dumps merge into one
  process-labeled registry; cluster totals reconcile EXACTLY
  (federated child == worker's own value; coordinator counters ==
  sum over workers + spillover);
* SLO BURN-RATE ALERTING — declarative targets, fast/slow phase
  windows, ``slo_burn`` events + counter + /health verdict;
* OFFLINE CRITICAL-PATH ANALYZER — ``tools/analyze_request.py``
  decompositions sum exactly to each recorded retire latency, on
  crashed-prefix and resumed multi-segment timelines;
* satellites: ``--events-max-mb`` segment rollover, hostile tenant
  ids end-to-end into Prometheus exposition, the rid-linkage
  validator flag, and trace linkage under chaos (host loss /
  restart).

Engines run the pure-f64 streaming mode over the dyadic
``quad_scaled`` family: per-request areas (and therefore every
deterministic trace attr) are schedule-independent to the bit.
"""

import json
import os
import re
import sys
import urllib.request

import numpy as np
import pytest

from ppls_tpu.obs import (FederatedMetrics, MetricsRegistry,
                          MetricsServer, SloEvaluator, Telemetry)
from ppls_tpu.runtime import guard
from ppls_tpu.runtime.cluster import ClusterStreamEngine
from ppls_tpu.runtime.faults import (FaultEvent, FaultInjector,
                                     FaultPlan)
from ppls_tpu.runtime.stream import StreamEngine
from ppls_tpu.utils.artifact_schema import validate_events_text

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
from tools.analyze_request import analyze, expand_paths  # noqa: E402

KW = dict(slots=4, chunk=1 << 10, capacity=1 << 16, lanes=256,
          roots_per_lane=2, refill_slots=2, seg_iters=32,
          min_active_frac=0.05, f64_rounds=2)
THETA6 = [1.0, 1.25, 1.5, 2.0, 0.75, 3.0]
REQS6 = [(t, (0.0, 1.0)) for t in THETA6]
ARR6 = [0, 0, 1, 2, 3, 4]


def _recs(path):
    with open(path, encoding="utf-8") as fh:
        return [json.loads(ln) for ln in fh if ln.strip()]


def _rid_trace(paths):
    """The DETERMINISTIC per-rid trace surface: terminal + admit-edge
    events with their schedule/device-determined attrs, plus the
    (rid, phase) residency set — deduped across segments/files, the
    kill-and-resume comparison object."""
    keep = {
        "admit": ("rid", "slot", "phase", "submit_phase",
                  "queue_wait_phases", "token_wait_phases", "tenant",
                  "priority"),
        "request_dealt": ("rid", "phase", "submit_phase",
                          "queue_wait_phases"),
        "retire": ("rid", "area", "failed", "submit_phase",
                   "admit_phase", "retire_phase", "latency_phases",
                   "tenant", "priority"),
        "request_shed": ("rid", "tenant", "priority", "reason",
                         "phase", "submit_phase"),
    }
    out = {}
    residency = set()
    for p in paths:
        for r in _recs(p):
            if r.get("ev") != "event":
                continue
            a = r.get("attrs") or {}
            if r["name"] == "request_phase":
                residency.add((a["rid"], a["phase"]))
            elif r["name"] in keep:
                key = (a["rid"], r["name"])
                val = {k: a.get(k) for k in keep[r["name"]]}
                if key in out:
                    assert out[key] == val, (
                        "replayed trace event diverged", key,
                        out[key], val)
                out[key] = val
    return out, residency


def _run_stream(path, crash_after=None, checkpoint=None, **extra):
    tel = Telemetry(events_path=path, meta={"mode": "trace-test"})
    eng = StreamEngine("quad_scaled", 1e-9, telemetry=tel,
                       checkpoint_path=checkpoint,
                       checkpoint_every=1, **dict(KW, **extra))
    try:
        res = eng.run(REQS6, arrival_phase=ARR6,
                      _crash_after_phases=crash_after)
    finally:
        tel.close()
    return eng, res


# ---------------------------------------------------------------------------
# tentpole 1: request tracing, single engine
# ---------------------------------------------------------------------------

def test_request_trace_single_engine(tmp_path):
    ev = str(tmp_path / "t.jsonl")
    _eng, res = _run_stream(ev)
    text = open(ev).read()
    # schema-valid INCLUDING the rid-linkage contract
    assert validate_events_text(text, check_rid_linkage=True) == []
    recs = _recs(ev)
    spans = [r for r in recs if r.get("ev") == "span_open"
             and r.get("name") == "request"]
    closed = {r["id"] for r in recs if r.get("ev") == "span_close"}
    assert len(spans) == len(REQS6)
    assert all(r["id"] in closed for r in spans)
    trace, residency = _rid_trace([ev])
    for rid in range(len(REQS6)):
        assert (rid, "admit") in trace
        assert (rid, "retire") in trace
        t = trace[(rid, "retire")]
        # residency covers admit..retire exactly (one event per live
        # phase, linked to that phase's span)
        phases = sorted(ph for r, ph in residency if r == rid)
        assert phases == list(range(t["admit_phase"],
                                    t["retire_phase"] + 1))
    # every request_phase event links rid span AND phase span
    by_id = {r["id"]: r for r in recs if r.get("ev") == "span_open"}
    for r in recs:
        if r.get("ev") == "event" and r["name"] == "request_phase":
            assert by_id[r["span"]]["name"] == "request"
            assert by_id[r["attrs"]["phase_span"]]["name"] == "phase"


def test_request_trace_bit_identical_kill_and_resume(tmp_path):
    base_ev = str(tmp_path / "base.jsonl")
    _run_stream(base_ev)
    ck = str(tmp_path / "s.ckpt")
    crash_ev = str(tmp_path / "crash.jsonl")
    with pytest.raises(RuntimeError, match="simulated crash"):
        _run_stream(crash_ev, crash_after=3, checkpoint=ck)
    assert validate_events_text(open(crash_ev).read(),
                                require_balanced=False,
                                check_rid_linkage=True) == []
    resume_ev = str(tmp_path / "resume.jsonl")
    tel = Telemetry(events_path=resume_ev)
    eng2 = StreamEngine.resume(ck, "quad_scaled", 1e-9,
                               telemetry=tel, checkpoint_every=1,
                               **KW)
    k = eng2.next_rid
    while not eng2.idle or k < len(REQS6):
        while k < len(REQS6) and ARR6[k] <= eng2.phase:
            eng2.submit(*REQS6[k])
            k += 1
        eng2.step()
    tel.close()
    assert validate_events_text(open(resume_ev).read(),
                                check_rid_linkage=True) == []
    base_tr, base_res = _rid_trace([base_ev])
    kill_tr, kill_res = _rid_trace([crash_ev, resume_ev])
    # THE BIT-FOR-BIT CONTRACT: the per-rid deterministic trace of the
    # killed+resumed lineage equals the undisturbed run's exactly
    assert kill_tr == base_tr
    assert kill_res == base_res


def test_trace_covers_shed_spillover_and_token_wait(tmp_path):
    ev = str(tmp_path / "mix.jsonl")
    tel = Telemetry(events_path=ev, meta={})
    eng = StreamEngine(
        "quad_scaled", 1e-9, telemetry=tel, queue_limit=2,
        tenant_quotas={"*": {"rate": 0.25, "burst": 1}},
        spillover=True, spillover_limit=1, **dict(KW, slots=2))
    # 12 one-tenant arrivals at once: 2 queue (token-paced at 1 admit
    # per 4 phases), 8 spill, 2 shed spill_queue_full
    thetas = THETA6 + [1.75, 2.5, 0.5, 3.5, 1.125, 2.25]
    reqs = [(t, (0.0, 1.0), {"tenant": "t0"}) for t in thetas]
    res = eng.run(reqs, arrival_phase=[0] * len(reqs))
    tel.close()
    assert validate_events_text(open(ev).read(),
                                check_rid_linkage=True) == []
    names = {}
    for r in _recs(ev):
        if r.get("ev") == "event":
            names[r["name"]] = names.get(r["name"], 0) + 1
    assert names.get("spillover_enqueued", 0) > 0
    assert names.get("token_wait", 0) > 0
    rep = analyze([ev])
    assert rep["exact"]
    assert len(rep["requests"]) == len(res.completed)
    assert len(rep["shed"]) == len(res.shed)
    # token waits surface as a distinct latency component somewhere
    assert any(d["components"]["token_wait"] > 0
               for d in rep["requests"])
    assert any(d["spillover"] for d in rep["requests"])


# ---------------------------------------------------------------------------
# satellite: --events-max-mb segment rollover
# ---------------------------------------------------------------------------

def test_events_rollover_segments_stay_valid(tmp_path):
    ev = str(tmp_path / "roll.jsonl")
    tel = Telemetry(events_path=ev, meta={"mode": "roll"},
                    events_max_bytes=4096)
    eng = StreamEngine("quad_scaled", 1e-9, telemetry=tel, **KW)
    res = eng.run(REQS6, arrival_phase=ARR6)
    tel.close()
    paths = expand_paths([ev])
    assert len(paths) > 1, "cap never rolled the file"
    for p in paths:
        assert validate_events_text(
            open(p).read(), where=os.path.basename(p),
            check_rid_linkage=True) == [], p
    # the analyzer reads the whole chain and stays exact
    rep = analyze(paths)
    assert rep["exact"]
    assert len(rep["requests"]) == len(res.completed) == len(REQS6)
    # the cap is soft by at most one phase's records (a roll defers
    # while a phase span is mid-flight)
    for p in paths[:-1]:
        assert os.path.getsize(p) < 2 * 4096
    # REVIEW FIX: an append-resume must CONTINUE the rolled-segment
    # numbering — the old tracer restarted at .1 and os.replace'd the
    # previous lineage's oldest segment out of existence
    n_before = len(paths)
    first_seg = open(paths[0]).read()
    tel2 = Telemetry(events_path=ev, append=True,
                     events_max_bytes=4096)
    eng2 = StreamEngine("quad_scaled", 1e-9, telemetry=tel2, **KW)
    eng2.run(REQS6, arrival_phase=ARR6)
    tel2.close()
    paths2 = expand_paths([ev])
    assert len(paths2) > n_before, "resume never rolled"
    assert open(paths2[0]).read() == first_seg, \
        "resume rollover clobbered the oldest rolled segment"
    # ... while a FRESH (non-append) open clears the stale chain
    tel3 = Telemetry(events_path=ev, events_max_bytes=1 << 20)
    tel3.span("run").close()
    tel3.close()
    assert expand_paths([ev]) == [ev]


# ---------------------------------------------------------------------------
# satellite: the rid-linkage validator flag
# ---------------------------------------------------------------------------

def test_rid_linkage_validator_flags_broken_shapes():
    meta = json.dumps({"ev": "meta", "schema": "ppls-events-v1",
                       "t": 0.0, "wall": 1.0, "attrs": {}})
    orphan = "\n".join([
        meta,
        json.dumps({"ev": "event", "name": "retire", "span": None,
                    "t": 0.1, "attrs": {"rid": 7}}),
    ]) + "\n"
    # without the flag: legacy timelines (no request spans) stay valid
    assert validate_events_text(orphan) == []
    got = validate_events_text(orphan, check_rid_linkage=True)
    assert any("orphan trace event" in p for p in got)

    unclosed = "\n".join([
        meta,
        json.dumps({"ev": "span_open", "id": 0, "parent": None,
                    "name": "request", "t": 0.1,
                    "attrs": {"rid": 3}}),
        json.dumps({"ev": "event", "name": "retire", "span": 0,
                    "t": 0.2, "attrs": {"rid": 3}}),
    ]) + "\n"
    got = validate_events_text(unclosed, require_balanced=False,
                               check_rid_linkage=True)
    assert any("never closed" in p for p in got)

    clean = "\n".join([
        meta,
        json.dumps({"ev": "span_open", "id": 0, "parent": None,
                    "name": "request", "t": 0.1,
                    "attrs": {"rid": 3}}),
        json.dumps({"ev": "event", "name": "retire", "span": 0,
                    "t": 0.2, "attrs": {"rid": 3}}),
        json.dumps({"ev": "span_close", "id": 0, "t": 0.3,
                    "attrs": {}}),
    ]) + "\n"
    assert validate_events_text(clean, check_rid_linkage=True) == []


# ---------------------------------------------------------------------------
# satellite: hostile tenant ids -> /metrics, end to end
# ---------------------------------------------------------------------------

_METRIC_LINE = re.compile(
    r'^[a-zA-Z_:][a-zA-Z0-9_:]*'
    r'(\{([a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\\n]|\\["\\n])*",?)*\})?'
    r' \S+$')


def _parse_exposition_strict(text):
    """A deliberately STRICT text-format parser: every non-comment
    line must match the metric-line grammar (label values fully
    escaped — a raw quote/newline/backslash breaks the match) and
    label values must unescape cleanly."""
    values = {}
    for ln in text.splitlines():
        if not ln or ln.startswith("#"):
            continue
        assert _METRIC_LINE.match(ln), f"unparseable line: {ln!r}"
        if "{" in ln:
            name = ln[:ln.index("{")]
            body = ln[ln.index("{") + 1:ln.rindex("}")]
            labels = {}
            for m in re.finditer(
                    r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\\n]|'
                    r'\\["\\n])*)"', body):
                raw = m.group(2)
                labels[m.group(1)] = (raw.replace("\\n", "\n")
                                      .replace('\\"', '"')
                                      .replace("\\\\", "\\"))
            values.setdefault(name, []).append(labels)
    return values


def test_hostile_tenant_ids_reach_metrics_clean(tmp_path):
    """Satellite 2: quote/backslash/newline tenant names through
    POST /submit -> engine -> registry -> a live /metrics scrape that
    must parse clean under a strict text-format grammar."""
    from ppls_tpu.runtime.ingest import (IngestServer,
                                         parse_request_record)
    hostile = ['evil"quote', "back\\slash", "new\nline"]
    tel = Telemetry()
    eng = StreamEngine("quad_scaled", 1e-9, telemetry=tel, **KW)
    srv = MetricsServer(tel.registry, port=0)
    ing = IngestServer(
        lambda d: {"rid": eng.submit(
            **{k: v for k, v in parse_request_record(d).items()
               if k != "arrival_phase"}), "accepted": True},
        port=0)
    try:
        body = "\n".join(json.dumps(
            {"theta": 1.0 + 0.25 * i, "bounds": [0.0, 1.0],
             "tenant": t}) for i, t in enumerate(hostile))
        req = urllib.request.Request(
            ing.url, data=body.encode(), method="POST")
        with urllib.request.urlopen(req, timeout=30) as resp:
            acks = [json.loads(ln) for ln in
                    resp.read().decode().splitlines()]
        assert all(a.get("accepted") for a in acks), acks
        eng.drain()
        with urllib.request.urlopen(srv.url, timeout=30) as resp:
            expo = resp.read().decode()
        parsed = _parse_exposition_strict(expo)
        seen = {lb["tenant"] for lb in
                parsed.get("ppls_stream_tenant_retired_total", [])}
        # the hostile names ROUND-TRIP: escaped on the wire, original
        # bytes after unescaping
        assert set(hostile) <= seen, (hostile, seen)
    finally:
        ing.close()
        srv.close()
        tel.close()


# ---------------------------------------------------------------------------
# tentpole 3: SLO burn-rate alerting
# ---------------------------------------------------------------------------

def test_slo_config_validation():
    from ppls_tpu.obs.slo import parse_slo_config
    good = parse_slo_config(
        '{"slos": [{"slo": "shed_fraction", "objective": 0.95}]}')
    assert good["windows"]["fast"] == 8
    for bad, msg in [
            ('{"slos": []}', "non-empty"),
            ('{"slos": [{"slo": "nope", "objective": 0.9}]}', "slo"),
            ('{"slos": [{"slo": "shed_fraction", "objective": 2}]}',
             "objective"),
            ('{"slos": [{"slo": "p99_latency_phases", '
             '"objective": 0.9}]}', "target"),
            ('{"windows": {"fast": 9, "slow": 4}, "slos": '
             '[{"slo": "shed_fraction", "objective": 0.9}]}',
             "fast"),
            # REVIEW FIX: class scope on counter-backed SLOs refuses
            # (the counters carry no class label — it would silently
            # monitor the global value under a class-labeled gauge)
            ('{"slos": [{"slo": "shed_fraction", "objective": 0.9, '
             '"class": "2"}]}', "class"),
    ]:
        with pytest.raises(ValueError, match=msg):
            parse_slo_config(bad)


def test_slo_burn_fires_and_rearms():
    tel = Telemetry()
    h = tel.class_latency_histogram()
    ev = SloEvaluator(
        {"windows": {"fast": 2, "slow": 4},
         "burn_thresholds": {"fast": 2.0, "slow": 2.0},
         "slos": [{"slo": "p99_latency_phases", "target": 4,
                   "objective": 0.9, "class": "1"}]}, tel)
    reg = tel.registry
    for ph in range(1, 5):
        h.labels(priority="1").observe(20)     # every retire breaches
        burning = ev.evaluate_slo(ph)
    assert burning and not ev.health()["ok"]
    assert reg.value("ppls_slo_burn_total", tenant="*",
                     slo="p99_latency_phases", **{"class": "1"}) == 1
    # staying in the burning state does NOT re-count (one increment
    # per ENTRY); gauges keep updating
    h.labels(priority="1").observe(20)
    ev.evaluate_slo(5)
    assert reg.value("ppls_slo_burn_total", tenant="*",
                     slo="p99_latency_phases", **{"class": "1"}) == 1
    # quiet windows: burn decays, state re-arms, health goes green
    for ph in range(6, 16):
        h.labels(priority="1").observe(1)      # within target
        burning = ev.evaluate_slo(ph)
    assert not burning and ev.health()["ok"]
    # a fresh breach after re-arm fires a SECOND alert
    for ph in range(16, 22):
        h.labels(priority="1").observe(20)
        ev.evaluate_slo(ph)
    assert reg.value("ppls_slo_burn_total", tenant="*",
                     slo="p99_latency_phases", **{"class": "1"}) == 2


def test_slo_resume_rebase_no_spurious_burn():
    """REVIEW FIX: a resumed evaluator sees the REPLAYED cumulative
    counters with an empty window ring — without the resume re-base
    (seed_base) its first evaluations reported the all-time error
    rate as the windowed burn and 503'd a healthy service."""
    tel = Telemetry()
    shed = tel.shed_counter()
    retired = tel.registry.counter(
        "ppls_stream_tenant_retired_total", "t", ("tenant",))
    # "replayed" history: a brutal early overload, long since past
    shed.labels(tenant="a", reason="queue_full").inc(50)
    retired.labels(tenant="a").inc(50)
    ev = SloEvaluator(
        {"windows": {"fast": 2, "slow": 4},
         "burn_thresholds": {"fast": 2.0, "slow": 2.0},
         "slos": [{"slo": "shed_fraction", "objective": 0.9}]}, tel)
    ev.seed_base(100)              # the resume re-base
    for ph in range(101, 107):     # healthy post-resume traffic
        retired.labels(tenant="a").inc(3)
        burning = ev.evaluate_slo(ph)
    assert burning == [] and ev.health()["ok"]
    assert tel.registry.value("ppls_slo_burn_total", tenant="*",
                              slo="shed_fraction",
                              **{"class": "*"}) == 0


def test_token_waits_survive_kill_and_resume(tmp_path):
    """REVIEW FIX: the per-rid token-wait counters ride the snapshot
    — a resumed admission reports the SAME token_wait_phases as the
    undisturbed run (the bit-for-bit trace contract), instead of
    silently reattributing pre-kill waits to backlog."""
    quota = {"*": {"rate": 0.25, "burst": 1}}
    reqs = [(t, (0.0, 1.0)) for t in THETA6[:3]]

    def run(path, crash_after=None, checkpoint=None):
        tel = Telemetry(events_path=path)
        eng = StreamEngine("quad_scaled", 1e-9, telemetry=tel,
                           tenant_quotas=quota,
                           checkpoint_path=checkpoint,
                           checkpoint_every=1, **KW)
        try:
            eng.run(reqs, arrival_phase=[0, 0, 0],
                    _crash_after_phases=crash_after)
        finally:
            tel.close()
        return eng

    base_ev = str(tmp_path / "b.jsonl")
    run(base_ev)
    ck = str(tmp_path / "t.ckpt")
    crash_ev = str(tmp_path / "c.jsonl")
    with pytest.raises(RuntimeError, match="simulated crash"):
        run(crash_ev, crash_after=2, checkpoint=ck)
    resume_ev = str(tmp_path / "r.jsonl")
    tel = Telemetry(events_path=resume_ev)
    eng2 = StreamEngine.resume(ck, "quad_scaled", 1e-9,
                               telemetry=tel, tenant_quotas=quota,
                               checkpoint_every=1, **KW)
    while not eng2.idle:
        eng2.step()
    tel.close()
    base_tr, _ = _rid_trace([base_ev])
    kill_tr, _ = _rid_trace([crash_ev, resume_ev])
    assert kill_tr == base_tr
    waits = [base_tr[(r, "admit")]["token_wait_phases"]
             for r in range(3)]
    assert any(w > 0 for w in waits), waits   # the scenario binds


def test_slo_engine_integration_emits_burn_events(tmp_path):
    ev_path = str(tmp_path / "slo.jsonl")
    tel = Telemetry(events_path=ev_path)
    eng = StreamEngine(
        "quad_scaled", 1e-9, telemetry=tel,
        slo_config={"windows": {"fast": 2, "slow": 4},
                    "burn_thresholds": {"fast": 1.0, "slow": 1.0},
                    "slos": [{"slo": "p99_latency_phases",
                              "target": 1, "objective": 0.99}]},
        **KW)
    eng.run(REQS6, arrival_phase=ARR6)
    assert not eng.slo_health()["ok"]
    tel.close()
    burns = [r for r in _recs(ev_path)
             if r.get("ev") == "event" and r["name"] == "slo_burn"]
    assert burns, "no slo_burn event reached the timeline"
    assert burns[0]["attrs"]["fast_burn"] >= 1.0
    reg = eng.telemetry.registry
    assert reg.value("ppls_slo_burn_total", tenant="*", **{
        "class": "*"}, slo="p99_latency_phases") >= 1


def test_health_endpoint_serves_verdict():
    tel = Telemetry()
    eng = StreamEngine("quad_scaled", 1e-9, telemetry=tel, **KW)
    srv = MetricsServer(tel.registry, port=0,
                        health_fn=eng.slo_health)
    try:
        url = f"http://{srv.host}:{srv.port}/health"
        with urllib.request.urlopen(url, timeout=30) as resp:
            verdict = json.loads(resp.read().decode())
        assert verdict["ok"] is True and verdict["burning"] == []
        # /metrics still serves text on every other path
        with urllib.request.urlopen(srv.url, timeout=30) as resp:
            assert resp.headers["Content-Type"].startswith(
                "text/plain")
    finally:
        srv.close()


# ---------------------------------------------------------------------------
# tentpole 2 + acceptance: federation + chaos trace on the cluster
# ---------------------------------------------------------------------------

def test_federation_merge_unit():
    w = MetricsRegistry()
    w.counter("ppls_x_total", "x", ("tenant",)).labels(
        tenant="a").inc(3)
    w.histogram("ppls_stream_retire_latency_phases", "lat").observe(5)
    fed = FederatedMetrics()
    fed.ingest_dump("0", w.dump())
    w.counter("ppls_x_total", "x", ("tenant",)).labels(
        tenant="a").inc(2)
    w.histogram("ppls_stream_retire_latency_phases", "lat").observe(9)
    fed.ingest_dump("0", w.dump())          # cumulative re-ship
    fed.ingest_dump("0", w.dump())          # idempotent retransmit
    assert fed.reconcile() == []
    assert fed.sum_over_workers("ppls_x_total", tenant="a") == 5.0
    hist = fed.registry.get("ppls_stream_retire_latency_phases")
    child = hist.labels(process="0")
    # bucket-edge quantile: 9 lands in the (8, 12] bucket
    assert child.count == 2 and child.quantile(0.99) == 12.0
    # fresh-restart clamp: a from-zero re-report must not go negative
    w2 = MetricsRegistry()
    w2.counter("ppls_x_total", "x", ("tenant",)).labels(
        tenant="a").inc(1)
    fed.ingest_dump("0", w2.dump())
    assert fed.sum_over_workers("ppls_x_total", tenant="a") == 6.0


def test_cluster_chaos_federation_trace_and_decomposition(tmp_path):
    """THE ROUND-19 ACCEPTANCE: a --processes 2 chaos run (host_loss
    + overload) must produce (1) one federated metrics surface whose
    cluster totals reconcile exactly with the per-worker counters,
    (2) a per-rid trace for every acknowledged request with the
    redeal trail present and zero orphan spans, and (3)
    analyze_request decompositions whose components sum exactly to
    each recorded retire latency."""
    ev_path = str(tmp_path / "chaos.jsonl")
    tel = Telemetry(events_path=ev_path, meta={"mode": "chaos"})
    inj = FaultInjector(FaultPlan.from_events(
        [{"kind": "host_loss", "at": 2, "chip": 1}]), telemetry=tel)
    eng = ClusterStreamEngine(
        "quad_scaled", 1e-9, n_processes=2, worker_kw=KW,
        fault_injector=inj, telemetry=tel, queue_limit=3,
        spillover=True, spillover_limit=2,
        slo_config={"slos": [{"slo": "shed_fraction",
                              "objective": 0.95}]})
    reqs = REQS6 + [(1.75, (0.0, 1.0)), (2.5, (0.0, 1.0))]

    def loop():
        k = eng.next_rid
        while not eng.idle or k < len(reqs):
            while k < len(reqs) and eng.phase >= 0 and k < len(reqs):
                eng.submit(*reqs[k])
                k += 1
            eng.step()
        return eng.result()

    def resize_fn(exc):
        eng.recover_host_loss(exc)
        return loop

    sup = guard.Supervisor(loop, resize_fn=resize_fn,
                           log=lambda m: None, sleep=lambda s: None)
    base = StreamEngine("quad_scaled", 1e-9, **KW).run(reqs)
    try:
        res = sup.run()
        assert sup.recoveries == [("host_loss", "resize_resume")]
        assert len(res.completed) == len(reqs)
        assert np.array_equal(res.areas, base.areas)

        # (1) FEDERATION RECONCILES EXACTLY
        assert eng.federation_reconcile() == []
        spill = eng.spillover_summary()["spillover_completed"]
        worker_retired = eng._federation.sum_over_workers(
            "ppls_stream_retired_total")
        coord = eng.federated_registry.get(
            "ppls_stream_retired_total").labels(
            process="coordinator").value
        assert coord == len(res.completed)
        assert worker_retired + spill == coord
        expo = eng.federated_registry.exposition()
        assert 'process="coordinator"' in expo
        assert 'process="0"' in expo
    finally:
        eng.close()
        tel.close()

    # (2) PER-RID TRACE with the redeal trail, zero orphans
    text = open(ev_path).read()
    assert validate_events_text(text,
                                check_rid_linkage=True) == []
    recs = _recs(ev_path)
    names = [r["name"] for r in recs if r.get("ev") == "event"]
    assert "host_killed" in names
    assert "host_loss_discovery" in names
    assert "cluster_redeal" in names
    assert "request_redeal" in names        # the per-rid redeal hop
    trace, _res_set = _rid_trace([ev_path])
    for rid in range(len(reqs)):
        assert (rid, "retire") in trace, f"rid {rid} has no trace"
    # process spans carry the rid linkage the workers shipped back
    proc_spans = [r for r in recs if r.get("ev") == "span_close"
                  and "rids" in (r.get("attrs") or {})]
    assert proc_spans, "no process span carries rid linkage"

    # (3) DECOMPOSITIONS SUM EXACTLY
    rep = analyze([ev_path])
    assert rep["exact"]
    assert len(rep["requests"]) == len(reqs)
    assert not rep["incomplete"]
    assert any(d["redeals"] > 0 for d in rep["requests"])


def test_cluster_trace_survives_kill_and_resume(tmp_path):
    base_ev = str(tmp_path / "b.jsonl")
    tel0 = Telemetry(events_path=base_ev)
    e0 = ClusterStreamEngine("quad_scaled", 1e-9, n_processes=2,
                             worker_kw=KW, telemetry=tel0)
    try:
        e0.run(REQS6, arrival_phase=ARR6)
    finally:
        e0.close()
        tel0.close()

    ck = str(tmp_path / "c.ckpt")
    kill_ev = str(tmp_path / "k.jsonl")
    tel1 = Telemetry(events_path=kill_ev)
    e1 = ClusterStreamEngine("quad_scaled", 1e-9, n_processes=2,
                             worker_kw=KW, telemetry=tel1,
                             checkpoint_path=ck, checkpoint_every=1)
    try:
        with pytest.raises(RuntimeError, match="simulated crash"):
            e1.run(REQS6, arrival_phase=ARR6, _crash_after_phases=3)
    finally:
        e1.close()
        tel1.close()
    assert validate_events_text(open(kill_ev).read(),
                                require_balanced=False,
                                check_rid_linkage=True) == []

    tel2 = Telemetry(events_path=kill_ev, append=True)
    e2 = ClusterStreamEngine.resume(ck, "quad_scaled", 1e-9,
                                    n_processes=2, worker_kw=KW,
                                    telemetry=tel2,
                                    checkpoint_every=1)
    try:
        k = e2.next_rid
        while not e2.idle or k < len(REQS6):
            while k < len(REQS6) and ARR6[k] <= e2.phase:
                e2.submit(*REQS6[k])
                k += 1
            e2.step()
        res = e2.result()
        assert len(res.completed) == len(REQS6)
    finally:
        e2.close()
        tel2.close()

    base_tr, _ = _rid_trace([base_ev])
    kill_tr, _ = _rid_trace([kill_ev])
    assert kill_tr == base_tr
    rep = analyze([kill_ev])
    assert rep["exact"] and not rep["incomplete"]
    assert len(rep["requests"]) == len(REQS6)
