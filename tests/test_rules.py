"""Unit tests of the quadrature rules against closed-form integrals
(SURVEY.md §4: built from scratch against verified ground truth — the
reference has no tests)."""

import jax.numpy as jnp
import numpy as np
import pytest

from ppls_tpu import eval_batch, get_integrand
from ppls_tpu.config import Rule
from ppls_tpu.ops.rules import simpson_batch, trapezoid_batch


def test_trapezoid_reference_semantics_single_interval():
    # The very first task of the reference run: [0, 5] at eps=1e-3 must
    # split (cosh^4 is wildly non-linear there), and the discrepancy must
    # match the hand-computed trapezoid formulas of aquadPartA.c:185-191.
    f = get_integrand("cosh4").fn
    l = jnp.asarray([0.0])
    r = jnp.asarray([5.0])
    value, err, split = trapezoid_batch(l, r, f, 1e-3)
    fl, fm, fr = float(f(0.0)), float(f(2.5)), float(f(5.0))
    lrarea = (fl + fr) * 5.0 / 2.0
    larea = (fl + fm) * 2.5 / 2.0
    rarea = (fm + fr) * 2.5 / 2.0
    np.testing.assert_allclose(float(value[0]), larea + rarea, rtol=1e-14)
    np.testing.assert_allclose(float(err[0]), abs(larea + rarea - lrarea),
                               rtol=1e-9)
    assert bool(split[0])


def test_trapezoid_strict_inequality():
    # Reference splits on err > eps strictly (aquadPartA.c:191): an
    # interval whose discrepancy equals eps exactly must be accepted.
    # A linear integrand has zero discrepancy -> never splits even at eps=0.
    f = lambda x: 2.0 * x
    _, err, split = trapezoid_batch(jnp.asarray([0.0]), jnp.asarray([1.0]), f, 0.0)
    assert float(err[0]) == 0.0
    assert not bool(split[0])


def test_simpson_exact_on_cubic():
    # Simpson integrates cubics exactly: one interval, no split, value exact.
    f = get_integrand("poly3").fn
    value, err, split = simpson_batch(
        jnp.asarray([0.0]), jnp.asarray([2.0]), f, 1e-12)
    np.testing.assert_allclose(float(value[0]), 4.0, rtol=1e-14)
    assert not bool(split[0])


@pytest.mark.parametrize("rule", [Rule.TRAPEZOID, Rule.SIMPSON])
def test_batch_matches_scalar(rule):
    # Batched evaluation is elementwise-identical to per-interval eval.
    f = get_integrand("sin").fn
    l = jnp.linspace(0.0, 2.0, 64)
    r = l + 0.25
    bv, be, bs = eval_batch(l, r, f, 1e-6, rule)
    for i in [0, 17, 63]:
        sv, se, ss = eval_batch(l[i:i + 1], r[i:i + 1], f, 1e-6, rule)
        np.testing.assert_array_equal(np.asarray(bv[i]), np.asarray(sv[0]))
        np.testing.assert_array_equal(np.asarray(be[i]), np.asarray(se[0]))
        assert bool(bs[i]) == bool(ss[0])


def test_partition_additivity():
    # Property: accepted value of [a,b] halves equals sum over the same
    # halves evaluated as separate intervals (tolerance monotonicity basis).
    f = get_integrand("exp").fn
    v_whole, _, _ = trapezoid_batch(
        jnp.asarray([0.0]), jnp.asarray([1.0]), f, 1e30)
    # The accepted value of [0,1] is by construction the sum of the plain
    # trapezoids on its halves (aquadPartA.c:189-190,199).
    def coarse_trap(l, r):
        return (np.exp(l) + np.exp(r)) * (r - l) / 2.0

    expected = coarse_trap(0.0, 0.5) + coarse_trap(0.5, 1.0)
    np.testing.assert_allclose(float(v_whole[0]), expected, rtol=1e-14)


def test_integrand_registry():
    from ppls_tpu import INTEGRANDS
    for name in ["cosh4", "sin", "sin_recip", "gauss_peak", "poly3", "exp",
                 "runge"]:
        assert name in INTEGRANDS
    # Analytic values sane
    assert abs(get_integrand("cosh4").exact(0.0, 5.0) - 7583461.361497) < 1e-3
    # ∫₀¹ sin(1/x) dx = sin(1) − Ci(1) (improper but convergent at 0)
    import math
    assert abs(get_integrand("sin_recip").exact(0.0, 1.0)
               - (math.sin(1.0) - 0.3374039229009681)) < 1e-12
