"""Round 12: mixed-precision scouting + double-buffered root banks.

Contracts pinned here:

* SCOUT AREA CONTRACT — scout mode's decisions are ds-confirmed except
  decisive splits (which only over-refine), so per-family areas stay
  within the documented ~1e-9 schedule contract of the non-scout refill
  run, while every rerun of the SAME mode is bit-identical.
* DEVICE-COUNTED EVAL SPLIT — scout_evals/confirm_evals are populated
  in scout mode, zero otherwise, and the non-scout confirm count
  equals the eval_active waste bucket (each live lane-step is exactly
  one real eval).
* GUARD BAND — a wide guard forces (nearly) every decision through the
  ds confirm pass: confirm volume responds to the band, i.e. the
  fallback path is real, not decorative.
* RECONCILIATION — the four lane-waste buckets still sum to
  lanes x kernel steps in scout and double-buffer modes, on walker,
  dd (virtual 8-mesh), and stream engines.
* DOUBLE-BUFFER ROLLING DEAL — one phase consumes more of the
  work-sorted queue than the single-deal R*lanes window (the swap path
  actually fires), with area parity.
* CHECKPOINT IDENTITY (ISSUE 8 satellite) — kill-and-resume stays
  bit-identical in scout + double-buffer modes on walker, dd, and
  stream, a snapshot written in one mode refuses to resume in another,
  and the mode flags ride the snapshot identity.
"""

import numpy as np
import pytest

from ppls_tpu.models.integrands import get_family, get_family_ds
from ppls_tpu.parallel.walker import (WASTE_FIELDS,
                                      integrate_family_walker,
                                      resume_family_walker)

F = get_family("sin_recip_scaled")
F_DS = get_family_ds("sin_recip_scaled")
THETA = 1.0 + np.arange(8) / 8.0
BOUNDS = (1e-2, 1.0)
EPS = 1e-7
KW = dict(capacity=1 << 16, lanes=256, roots_per_lane=2,
          refill_slots=2, seg_iters=32, min_active_frac=0.05)


def _run(**over):
    kw = dict(KW)
    kw.update(over)
    return integrate_family_walker(F, F_DS, THETA, BOUNDS, EPS, **kw)


# ---------------------------------------------------------------------------
# scout mode
# ---------------------------------------------------------------------------


def test_scout_area_contract_and_counters():
    # explicit "f64": the baseline must stay non-scout even under the
    # PPLS_SCOUT=1 ci lane
    base = _run(scout_dtype="f64")
    sc = _run(scout_dtype="f32")
    # schedule contract: decisions are ds-confirmed (accepts) or
    # over-refining (decisive splits); areas track the plain refill run
    assert np.max(np.abs(sc.areas - base.areas)) < 3e-9
    # device-counted eval split: scout mode populates both counters
    assert sc.scout_evals > 0
    assert sc.confirm_evals > 0
    # confirm pass fires on a strict subset of scout tests (decisive
    # splits skip ds entirely — that is the whole saving)
    assert sc.confirm_evals < 3 * sc.scout_evals
    # non-scout: zero scout evals, and the confirm count IS the
    # eval_active bucket (one real eval per live lane-step)
    assert base.scout_evals == 0
    assert base.confirm_evals == int(base.waste[0])
    assert not base.evals_estimated and not sc.evals_estimated


def test_scout_raises_lane_efficiency():
    # the fused-load scout step makes every live lane-step a test:
    # tasks/lane-steps climbs past the non-scout trapezoid structural
    # cap (~2/3) toward the occupancy ceiling
    base = _run(scout_dtype="f64")
    sc = _run(scout_dtype="f32")
    assert sc.lane_efficiency > base.lane_efficiency * 1.3, \
        (base.lane_efficiency, sc.lane_efficiency)
    assert sc.lane_efficiency > 2.0 / 3.0


def test_scout_rerun_bit_identical_and_reconciles():
    r1 = _run(scout_dtype="f32")
    r2 = _run(scout_dtype="f32")
    assert np.array_equal(r1.areas, r2.areas)
    a = r1.attribution()
    assert a["reconciles"], a
    assert sum(a["buckets"].values()) == r1.kernel_steps * r1.lanes


def test_scout_guard_band_fallback_is_real(monkeypatch):
    # widen the guard band 10000x: almost nothing is decisively split
    # any more, so (nearly) every test must fall back to the ds
    # confirm pass — the confirm share responds to the band
    import ppls_tpu.parallel.walker as W
    narrow = _run(scout_dtype="f32")
    monkeypatch.setattr(W, "_SCOUT_BAND",
                        np.float32(W.SCOUT_GUARD_ULPS * 2.0 ** -23
                                   * 1e4))
    W.scout_twin.cache_clear()
    wide = _run(scout_dtype="f32", capacity=1 << 15)  # fresh compile key
    ratio_n = narrow.confirm_evals / max(narrow.scout_evals, 1)
    ratio_w = wide.confirm_evals / max(wide.scout_evals, 1)
    assert ratio_w > ratio_n, (ratio_n, ratio_w)
    # and the wide-band run still lands on the same areas (everything
    # ds-confirmed is the baseline decision procedure)
    assert np.max(np.abs(wide.areas - narrow.areas)) < 3e-9


def test_scout_rejects_simpson():
    from ppls_tpu.config import Rule
    with pytest.raises(ValueError, match="TRAPEZOID"):
        _run(scout_dtype="f32", rule=Rule.SIMPSON)


def test_scout_env_lane(monkeypatch):
    # PPLS_SCOUT=1 force-enables scouting on default-mode runs — the
    # ci.sh f32-rot lane's mechanism
    explicit = _run(scout_dtype="f32")
    monkeypatch.setenv("PPLS_SCOUT", "1")
    env = _run()
    assert env.scout_evals > 0
    assert np.array_equal(env.areas, explicit.areas)


def test_flagship_proxy_lane_efficiency_target():
    # ISSUE 8 acceptance: interpret-mode flagship proxy (the
    # analyze_occupancy --attribution workload) reaches
    # lane_efficiency >= 0.85 with scout + double-buffer + the
    # mode-aware cadence, reconciliation intact
    m = 64
    theta = 1.0 + np.arange(m) / m
    r = integrate_family_walker(
        F, F_DS, theta, (1e-3, 1.0), 1e-8,
        capacity=1 << 18, lanes=256, roots_per_lane=8, refill_slots=8,
        seg_iters=256, min_active_frac=0.05,
        scout_dtype="f32", double_buffer=True)
    a = r.attribution()
    assert a["reconciles"]
    assert r.lane_efficiency >= 0.85, (r.lane_efficiency, a)


# ---------------------------------------------------------------------------
# double-buffered root banks
# ---------------------------------------------------------------------------

DEEP_KW = dict(capacity=1 << 17, lanes=256, roots_per_lane=8,
               refill_slots=2, seg_iters=64, min_active_frac=0.05)


def test_double_buffer_rolls_past_single_deal_window():
    # a workload whose bred queue exceeds R*lanes: the rolling deal
    # must consume MORE roots per cycle than the single-deal window
    # (i.e. the swap path fires), with area parity
    from ppls_tpu.parallel.walker import CYCLE_STAT_FIELDS
    kw = dict(DEEP_KW)
    base = integrate_family_walker(F, F_DS, THETA, BOUNDS, 1e-8, **kw)
    db = integrate_family_walker(F, F_DS, THETA, BOUNDS, 1e-8,
                                 double_buffer=True, **kw)
    ic = CYCLE_STAT_FIELDS.index("roots_consumed")
    per_cycle_base = np.asarray(base.cycle_stats)[:, ic]
    per_cycle_db = np.asarray(db.cycle_stats)[:, ic]
    assert per_cycle_db.max() > per_cycle_base.max(), \
        (per_cycle_base.tolist(), per_cycle_db.tolist())
    assert np.max(np.abs(db.areas - base.areas)) < 3e-9
    assert db.attribution()["reconciles"]
    # root conservation: every bred root is walked or re-bred, never
    # lost across swaps (task totals agree up to split-decision drift)
    drift = abs(db.metrics.tasks - base.metrics.tasks) \
        / base.metrics.tasks
    assert drift < 1e-3, (db.metrics.tasks, base.metrics.tasks)


def test_double_buffer_rerun_bit_identical():
    r1 = _run(double_buffer=True)
    r2 = _run(double_buffer=True)
    assert np.array_equal(r1.areas, r2.areas)
    assert r1.metrics.tasks == r2.metrics.tasks


def test_double_buffer_requires_even_refill():
    with pytest.raises(ValueError, match="even refill_slots"):
        _run(double_buffer=True, refill_slots=1, roots_per_lane=1)
    with pytest.raises(ValueError, match="even refill_slots"):
        _run(double_buffer=True, refill_slots=0)


# ---------------------------------------------------------------------------
# checkpoint identity (satellite: kill-and-resume in the new modes)
# ---------------------------------------------------------------------------

CKPT_KW = dict(capacity=1 << 16, lanes=256, roots_per_lane=2,
               refill_slots=2, seg_iters=8, max_segments=1,
               max_cycles=256, min_active_frac=0.05)


@pytest.mark.parametrize("mode", [
    dict(scout_dtype="f32"),
    dict(double_buffer=True),
    dict(scout_dtype="f32", double_buffer=True),
])
def test_walker_kill_and_resume_bit_identical_in_new_modes(tmp_path,
                                                           mode):
    kw = dict(CKPT_KW, **mode)
    base = integrate_family_walker(F, F_DS, THETA, BOUNDS, EPS, **kw)
    path = str(tmp_path / "w.ckpt")
    with pytest.raises(RuntimeError, match="simulated crash"):
        integrate_family_walker(F, F_DS, THETA, BOUNDS, EPS, **kw,
                                checkpoint_path=path,
                                checkpoint_every=2, _crash_after_legs=2)
    res = resume_family_walker(path, F, F_DS, THETA, BOUNDS, EPS,
                               **kw, checkpoint_every=2)
    assert np.array_equal(res.areas, base.areas)          # bit-for-bit
    assert res.metrics.tasks == base.metrics.tasks
    assert res.scout_evals == base.scout_evals
    assert res.confirm_evals == base.confirm_evals
    assert np.array_equal(np.asarray(res.waste),
                          np.asarray(base.waste))


def test_walker_mode_flags_are_snapshot_identity(tmp_path):
    # a scout-mode snapshot must refuse to resume as a default-mode run
    # (and vice versa): the schedules differ inside the guard band
    path = str(tmp_path / "w.ckpt")
    kw = dict(CKPT_KW, scout_dtype="f32")
    with pytest.raises(RuntimeError, match="simulated crash"):
        integrate_family_walker(F, F_DS, THETA, BOUNDS, EPS, **kw,
                                checkpoint_path=path,
                                checkpoint_every=2, _crash_after_legs=1)
    with pytest.raises(ValueError, match="different run"):
        resume_family_walker(path, F, F_DS, THETA, BOUNDS, EPS,
                             scout_dtype="f64", **CKPT_KW,
                             checkpoint_every=2)


def test_dd_kill_and_resume_bit_identical_scout_db(tmp_path):
    # the virtual 8-mesh dd engine, scout + double-buffer on
    from ppls_tpu.parallel.sharded_walker import (
        integrate_family_walker_dd, resume_family_walker_dd)
    # max_segments=1 + a small seg_iters bounds each walk phase's step
    # budget, forcing several cycles so there are real leg boundaries
    # to crash at (the rolling deal otherwise finishes this workload
    # in fewer cycles than the crash leg)
    kw = dict(chunk=1 << 8, capacity=1 << 16, lanes=256,
              roots_per_lane=2, seg_iters=8, max_segments=1,
              max_cycles=256, min_active_frac=0.05,
              n_devices=8, refill_slots=2, scout_dtype="f32",
              double_buffer=True)
    theta = [1.0, 1.5]
    dd_bounds = (1e-3, 1.0)   # deep enough for >= 3 cycles at this
    #                           step budget on the 8-chip mesh
    base = integrate_family_walker_dd("sin_recip_scaled", theta,
                                      dd_bounds, 1e-9, **kw)
    assert base.scout_evals > 0
    assert base.attribution()["reconciles"]
    path = str(tmp_path / "dd.ckpt")
    with pytest.raises(RuntimeError, match="simulated crash"):
        integrate_family_walker_dd("sin_recip_scaled", theta, dd_bounds,
                                   1e-9, checkpoint_path=path,
                                   checkpoint_every=1,
                                   _crash_after_legs=2, **kw)
    res = resume_family_walker_dd(path, "sin_recip_scaled", theta,
                                  dd_bounds, 1e-9, checkpoint_every=1,
                                  **kw)
    assert np.array_equal(res.areas, base.areas)          # bit-for-bit
    assert res.metrics.tasks == base.metrics.tasks
    assert res.scout_evals == base.scout_evals
    # mode flags are dd identity too
    with pytest.raises(RuntimeError, match="simulated crash"):
        integrate_family_walker_dd("sin_recip_scaled", theta, dd_bounds,
                                   1e-9, checkpoint_path=path,
                                   checkpoint_every=1,
                                   _crash_after_legs=1, **kw)
    plain = dict(kw)
    plain.pop("scout_dtype")
    plain.pop("double_buffer")
    with pytest.raises(ValueError, match="different run"):
        resume_family_walker_dd(path, "sin_recip_scaled", theta,
                                BOUNDS, 1e-9, **plain)


def test_stream_kill_and_resume_bit_identical_scout_db(tmp_path):
    # mid-stream kill + resume with scouting and the rolling deal on:
    # the continued stream replays bit-identically (satellite: the
    # shadow half-bank is intra-phase state, folded back into the bag
    # at every phase edge, so phase-boundary snapshots stay complete)
    from ppls_tpu.runtime.stream import StreamEngine
    skw = dict(slots=8, chunk=1 << 10, capacity=1 << 16, lanes=256,
               roots_per_lane=2, refill_slots=2, seg_iters=32,
               min_active_frac=0.05, scout_dtype="f32",
               double_buffer=True)
    reqs = [(float(t), BOUNDS) for t in THETA[:6]]
    arr = [0, 0, 1, 2, 3, 5]
    base = StreamEngine("sin_recip_scaled", EPS, **skw).run(
        reqs, arrival_phase=arr)
    assert int(base.totals["scout_evals"]) > 0
    path = str(tmp_path / "stream.ckpt")
    eng = StreamEngine("sin_recip_scaled", EPS, checkpoint_path=path,
                       checkpoint_every=1, **skw)
    with pytest.raises(RuntimeError, match="simulated crash"):
        eng.run(reqs, arrival_phase=arr, _crash_after_phases=3)
    eng2 = StreamEngine.resume(path, "sin_recip_scaled", EPS,
                               checkpoint_every=1, **skw)
    k = eng2.next_rid
    while not eng2.idle or k < len(reqs):
        while k < len(reqs) and arr[k] <= eng2.phase:
            eng2.submit(*reqs[k])
            k += 1
        eng2.step()
    res = eng2.result()
    assert np.array_equal(res.areas, base.areas)          # bit-for-bit
    assert res.phases == base.phases
    assert res.totals == base.totals
    # stream identity carries the mode flags: a default-mode engine
    # must not resume this snapshot
    eng3 = StreamEngine("sin_recip_scaled", EPS, checkpoint_path=path,
                        checkpoint_every=1, **skw)
    with pytest.raises(RuntimeError, match="simulated crash"):
        eng3.run(reqs, arrival_phase=arr, _crash_after_phases=2)
    plain = {k: v for k, v in skw.items()
             if k not in ("scout_dtype", "double_buffer")}
    with pytest.raises(ValueError, match="different run"):
        StreamEngine.resume(path, "sin_recip_scaled", EPS,
                            checkpoint_every=1, **plain)


def test_stream_resume_pads_pre_round12_phase_rows(tmp_path):
    # back-compat: a snapshot whose phase rows predate the round-12
    # tail columns (18-wide) must still resume — the replay pads the
    # missing eval columns with zeros instead of KeyError-ing the
    # registry (STREAM_STAT_FIELDS only ever grows at the tail)
    import json

    from ppls_tpu.parallel.walker import STREAM_STAT_FIELDS
    from ppls_tpu.runtime.checkpoint import (load_family_checkpoint,
                                             save_family_checkpoint)
    from ppls_tpu.runtime.stream import StreamEngine
    skw = dict(slots=8, chunk=1 << 10, capacity=1 << 16, lanes=256,
               roots_per_lane=2, refill_slots=2, seg_iters=32,
               min_active_frac=0.05)
    reqs = [(float(t), BOUNDS) for t in THETA[:4]]
    path = str(tmp_path / "s.ckpt")
    eng = StreamEngine("sin_recip_scaled", EPS, checkpoint_path=path,
                       checkpoint_every=1, **skw)
    with pytest.raises(RuntimeError, match="simulated crash"):
        eng.run(reqs, _crash_after_phases=2)
    # rewrite the snapshot with TRUNCATED (pre-round-12-width) rows
    bag_cols, count, acc, totals = load_family_checkpoint(
        path, eng._identity())
    totals = json.loads(json.dumps(totals))
    totals["phase_rows"] = [list(r)[:len(STREAM_STAT_FIELDS) - 2]
                            for r in totals["phase_rows"]]
    save_family_checkpoint(path, identity=eng._identity(),
                           bag_cols=bag_cols, count=count, acc=acc,
                           totals=totals)
    eng2 = StreamEngine.resume(path, "sin_recip_scaled", EPS,
                               checkpoint_every=1, **skw)
    while not eng2.idle:
        eng2.step()
    res = eng2.result()
    assert len(res.completed) == len(reqs)
    # padded rows stack uniformly and the registry totals resolve
    assert res.phase_stats.shape[1] == len(STREAM_STAT_FIELDS)
    assert int(res.totals["tasks"]) > 0


def test_stream_rejects_explicit_scout_with_f64_rounds():
    from ppls_tpu.runtime.stream import StreamEngine
    with pytest.raises(ValueError, match="f64_rounds"):
        StreamEngine("sin_recip_scaled", EPS, slots=4, lanes=256,
                     refill_slots=2, f64_rounds=2, scout_dtype="f32")


def test_stream_scout_phase_rows_reconcile():
    # per-phase reconciliation with the new tail columns: buckets sum
    # to lanes x wsteps for every phase row, and the eval columns are
    # device-counted
    from ppls_tpu.parallel.walker import STREAM_STAT_FIELDS
    from ppls_tpu.runtime.stream import StreamEngine
    skw = dict(slots=8, chunk=1 << 10, capacity=1 << 16, lanes=256,
               roots_per_lane=2, refill_slots=2, seg_iters=32,
               min_active_frac=0.05, scout_dtype="f32")
    reqs = [(float(t), BOUNDS) for t in THETA[:4]]
    res = StreamEngine("sin_recip_scaled", EPS, **skw).run(reqs)
    iw = [STREAM_STAT_FIELDS.index(k) for k in WASTE_FIELDS]
    isteps = STREAM_STAT_FIELDS.index("wsteps")
    for row in np.asarray(res.phase_stats):
        assert sum(int(row[i]) for i in iw) \
            == int(row[isteps]) * skw["lanes"], row
    assert int(res.totals["scout_evals"]) > 0
    assert int(res.totals["confirm_evals"]) > 0
