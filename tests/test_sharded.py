"""Multi-chip shard_map integrator on the 8-device CPU mesh
(SURVEY.md §4: no TPU cluster needed in CI)."""

import jax
import numpy as np
import pytest

from ppls_tpu import QuadConfig, sharded_integrate
from ppls_tpu.config import REFERENCE_CONFIG, Rule
from ppls_tpu.parallel.mesh import make_mesh


@pytest.fixture(scope="module")
def mesh8():
    assert len(jax.devices()) == 8, "conftest must provide 8 CPU devices"
    return make_mesh(8)


def test_sharded_golden_area(mesh8):
    cfg = REFERENCE_CONFIG.replace(capacity=1 << 14)
    res = sharded_integrate(cfg, mesh=mesh8)
    assert f"{res.area:.6f}" == "7583461.801486"
    assert res.metrics.tasks == 6567
    assert res.metrics.splits == 3283
    assert res.metrics.rounds == 15
    assert res.metrics.n_chips == 8


def test_sharded_tasks_histogram_balanced(mesh8):
    # The demand-driven rebalance should spread tasks within ~2x across
    # chips (the reference's 4 workers got 1679/1605/1682/1601 —
    # aquadPartA.c:36).
    cfg = REFERENCE_CONFIG.replace(capacity=1 << 14)
    res = sharded_integrate(cfg, mesh=mesh8)
    counts = res.metrics.tasks_per_chip
    assert len(counts) == 8
    assert sum(counts) == 6567
    assert max(counts) <= 2 * max(min(counts), 1)


def test_sharded_matches_mesh_sizes():
    # Same area across 1-, 2-, 4-, 8-chip meshes (reduction is
    # deterministic per shape; cross-shape differences stay within fp noise).
    areas = []
    for n in [1, 2, 4, 8]:
        mesh = make_mesh(n)
        cfg = REFERENCE_CONFIG.replace(capacity=1 << 14)
        areas.append(sharded_integrate(cfg, mesh=mesh).area)
    for a in areas[1:]:
        np.testing.assert_allclose(a, areas[0], rtol=1e-12)
    # and every mesh shape prints the golden value
    for a in areas:
        assert f"{a:.6f}" == "7583461.801486"


def test_sharded_deep_simpson(mesh8):
    cfg = QuadConfig(integrand="runge", a=-1.0, b=1.0, eps=1e-10,
                     rule=Rule.SIMPSON, capacity=1 << 14, max_rounds=64)
    res = sharded_integrate(cfg, mesh=mesh8)
    assert res.global_error < 1e-8


def test_sharded_overflow_raises(mesh8):
    cfg = REFERENCE_CONFIG.replace(capacity=128)  # 16/chip < peak 1642
    with pytest.raises(RuntimeError, match="overflow"):
        sharded_integrate(cfg, mesh=mesh8)


def test_sharded_kill_and_resume_matches_uninterrupted(mesh8, tmp_path):
    """Wavefront recovery (VERDICT Missing #4): the last engine with
    no recovery path. Leg snapshots reuse the sharded-bag checkpoint
    container with FULL per-chip frontier columns (position-preserving
    — the child compaction is position-sensitive), so kill-and-resume
    replays the identical collective round sequence bit-for-bit."""
    import os

    from ppls_tpu.parallel.sharded import resume_sharded

    cfg = REFERENCE_CONFIG.replace(capacity=1 << 14)
    base = sharded_integrate(cfg, mesh=mesh8)
    path = str(tmp_path / "wavefront.ckpt")
    with pytest.raises(RuntimeError, match="simulated crash"):
        sharded_integrate(cfg, mesh=mesh8, checkpoint_path=path,
                          checkpoint_every=4, _crash_after_legs=2)
    res = resume_sharded(path, cfg, mesh=mesh8, checkpoint_every=4)
    assert res.area == base.area                       # bit-for-bit
    assert res.metrics.tasks == base.metrics.tasks
    assert res.metrics.rounds == base.metrics.rounds
    assert res.metrics.tasks_per_chip == base.metrics.tasks_per_chip
    assert not os.path.exists(path)   # finished run clears its snapshot


def test_sharded_resume_rejects_mismatched_identity(mesh8, tmp_path):
    from ppls_tpu.parallel.sharded import resume_sharded

    cfg = REFERENCE_CONFIG.replace(capacity=1 << 14)
    path = str(tmp_path / "wavefront.ckpt")
    with pytest.raises(RuntimeError, match="simulated crash"):
        sharded_integrate(cfg, mesh=mesh8, checkpoint_path=path,
                          checkpoint_every=4, _crash_after_legs=1)
    with pytest.raises(ValueError, match="different run"):
        resume_sharded(path, cfg.replace(eps=1e-4), mesh=mesh8)
