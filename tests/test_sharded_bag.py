"""Sharded family/bag engine tests (virtual 8-device CPU mesh)."""

import numpy as np
import pytest

from ppls_tpu.models.integrands import get_family
from ppls_tpu.parallel.bag_engine import integrate_family
from ppls_tpu.parallel.mesh import make_mesh
from ppls_tpu.parallel.sharded_bag import integrate_family_sharded

THETA = 1.0 + np.arange(12) / 12.0
BOUNDS = (1e-2, 1.0)


def _single(eps):
    f = get_family("sin_recip_scaled")
    return integrate_family(f, THETA, BOUNDS, eps,
                            chunk=1 << 10, capacity=1 << 17)


def test_sharded_bag_conserves_tasks_and_areas():
    # Split decisions are pointwise f64 and placement-independent, so the
    # total task count must match the single-chip engine EXACTLY; areas
    # differ only by summation order.
    eps = 1e-7
    s = integrate_family_sharded("sin_recip_scaled", THETA, BOUNDS, eps,
                                 chunk=1 << 8, capacity=1 << 15,
                                 mesh=make_mesh(8))
    b = _single(eps)
    assert s.metrics.tasks == b.metrics.tasks
    assert s.metrics.splits == b.metrics.splits
    assert np.max(np.abs(s.areas - b.areas)) < 1e-9
    assert s.metrics.n_chips == 8
    assert len(s.metrics.tasks_per_chip) == 8
    assert sum(s.metrics.tasks_per_chip) == s.metrics.tasks


def test_sharded_bag_balances_load():
    # Clustered refinement (deep splitting near x=1e-2) must spread over
    # the mesh: the per-chip histogram stays within 3x of the mean (the
    # reference's 4-worker histogram at aquadPartA.c:34-36 spreads ~5%;
    # chunked granularity is coarser).
    s = integrate_family_sharded("sin_recip_scaled", THETA, BOUNDS, 1e-7,
                                 chunk=1 << 8, capacity=1 << 15,
                                 mesh=make_mesh(8))
    per = np.asarray(s.metrics.tasks_per_chip, dtype=np.float64)
    mean = per.mean()
    assert per.max() < 3.0 * mean, per.tolist()
    assert per.min() > 0, per.tolist()


def test_sharded_bag_mesh_size_consistency():
    # Same problem on 2-, 4- and 8-chip meshes: identical task totals,
    # areas within summation-order noise.
    eps = 1e-6
    results = [
        integrate_family_sharded("sin_recip_scaled", THETA, BOUNDS, eps,
                                 chunk=1 << 8, capacity=1 << 15,
                                 mesh=make_mesh(n))
        for n in (2, 4, 8)
    ]
    t0 = results[0].metrics.tasks
    for res in results[1:]:
        assert res.metrics.tasks == t0
        assert np.max(np.abs(res.areas - results[0].areas)) < 1e-9


def test_sharded_bag_deterministic():
    kw = dict(chunk=1 << 8, capacity=1 << 15, mesh=make_mesh(8))
    a1 = integrate_family_sharded("sin_recip_scaled", THETA, BOUNDS, 1e-6,
                                  **kw)
    a2 = integrate_family_sharded("sin_recip_scaled", THETA, BOUNDS, 1e-6,
                                  **kw)
    assert np.array_equal(a1.areas, a2.areas)
    assert a1.metrics.tasks_per_chip == a2.metrics.tasks_per_chip


def test_sharded_bag_overflow_detected():
    with pytest.raises(RuntimeError, match="overflow"):
        integrate_family_sharded("sin_recip_scaled", THETA, BOUNDS, 1e-9,
                                 chunk=1 << 6, capacity=1 << 7,
                                 mesh=make_mesh(2))


def test_sharded_bag_kill_and_resume_bit_identical(tmp_path):
    """VERDICT r4 #4: leg-boundary checkpointing for the sharded bag.
    A crash after 2 legs + resume must reproduce the uninterrupted run
    bit-for-bit (legs only bound the collective round count)."""
    from ppls_tpu.parallel.sharded_bag import resume_family_sharded

    eps = 1e-7
    kw = dict(chunk=1 << 8, capacity=1 << 15, mesh=make_mesh(8))
    base = integrate_family_sharded("sin_recip_scaled", THETA, BOUNDS,
                                    eps, **kw)
    path = str(tmp_path / "sb.ckpt")
    with pytest.raises(RuntimeError, match="simulated crash"):
        integrate_family_sharded("sin_recip_scaled", THETA, BOUNDS, eps,
                                 checkpoint_path=path, checkpoint_every=4,
                                 _crash_after_legs=2, **kw)
    res = resume_family_sharded(path, "sin_recip_scaled", THETA, BOUNDS,
                                eps, checkpoint_every=4, **kw)
    assert np.array_equal(res.areas, base.areas)          # bit-for-bit
    assert res.metrics.tasks == base.metrics.tasks
    assert res.metrics.splits == base.metrics.splits
    assert res.metrics.tasks_per_chip == base.metrics.tasks_per_chip
    import os
    assert not os.path.exists(path)   # completed run clears its snapshot


def test_sharded_bag_resume_rejects_mismatched_identity(tmp_path):
    from ppls_tpu.parallel.sharded_bag import resume_family_sharded

    kw = dict(chunk=1 << 8, capacity=1 << 15, mesh=make_mesh(8))
    path = str(tmp_path / "sb.ckpt")
    with pytest.raises(RuntimeError, match="simulated crash"):
        integrate_family_sharded("sin_recip_scaled", THETA, BOUNDS, 1e-7,
                                 checkpoint_path=path, checkpoint_every=2,
                                 _crash_after_legs=1, **kw)
    with pytest.raises(ValueError, match="different run"):
        resume_family_sharded(path, "sin_recip_scaled", THETA, BOUNDS,
                              1e-8, **kw)
