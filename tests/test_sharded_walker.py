"""Demand-driven multi-chip walker (VERDICT r3 #3 + #7).

Acceptance (the judge's criterion): ONE deep family — the case the
round-robin family deal structurally cannot balance — finishes with
near-uniform tasks_per_chip (max/min < 2) on the virtual 8-mesh, with
areas matching the single-chip engines within the ds contract. Plus
kill-and-resume checkpointing for the multi-chip run.
"""

import numpy as np
import pytest

from ppls_tpu.models.integrands import get_family
from ppls_tpu.parallel.bag_engine import integrate_family
from ppls_tpu.parallel.sharded_walker import (integrate_family_walker_dd,
                                              resume_family_walker_dd)

BOUNDS = (1e-3, 1.0)
EPS = 1e-9
KW = dict(chunk=1 << 8, capacity=1 << 16, lanes=256, roots_per_lane=2,
          seg_iters=32, min_active_frac=0.05, n_devices=8)


def _bag(theta, eps=EPS):
    return integrate_family(get_family("sin_recip_scaled"), theta, BOUNDS,
                            eps, chunk=1 << 10, capacity=1 << 17)


def test_one_deep_family_balances_across_mesh():
    # The reference's defining capability (aquadPartA.c:156-165): all
    # work starts as ONE seed on one chip; demand-driven re-shard must
    # spread it over the whole mesh.
    theta = [1.0]
    r = integrate_family_walker_dd("sin_recip_scaled", theta, BOUNDS,
                                   EPS, **KW)
    b = _bag(theta)
    assert np.max(np.abs(r.areas - b.areas)) < 1e-9
    tpc = r.metrics.tasks_per_chip
    assert len(tpc) == 8 and min(tpc) > 0
    assert max(tpc) / min(tpc) < 2.0, tpc
    # conservation of the tree across the mesh (split decisions are
    # placement-independent at this eps)
    drift = abs(r.metrics.tasks - b.metrics.tasks) / b.metrics.tasks
    assert drift < 1e-3, (r.metrics.tasks, b.metrics.tasks)


def test_multi_family_parity():
    theta = 1.0 + np.arange(8) / 8.0
    r = integrate_family_walker_dd("sin_recip_scaled", theta, BOUNDS,
                                   EPS, **KW)
    b = _bag(theta)
    assert np.max(np.abs(r.areas - b.areas)) < 1e-9
    tpc = r.metrics.tasks_per_chip
    assert max(tpc) / min(tpc) < 2.0, tpc


def test_dd_sort_window_uses_breed_chunk(monkeypatch):
    """ADVICE r5 #3 lock: the dd engine's work-ordering window must be
    2 * breed_chunk (from _dd_sizing), matching walker._run_cycles.
    (The r5 advice misread the old parameter name — the call site
    already passed breed_chunk through an argument NAMED `chunk`, so
    behavior was correct; the parameter is now named breed_chunk and
    this test pins the window against any future regression to the
    caller's raw pop-chunk.) Captures the window actually passed
    inside the freshly-built shard program."""
    import ppls_tpu.parallel.sharded_walker as SW
    from ppls_tpu.parallel.walker import _order_roots_by_work as real

    seen = {}

    def spy(bag, **kwargs):
        seen["window"] = kwargs.get("window")
        return real(bag, **kwargs)

    monkeypatch.setattr(SW, "_order_roots_by_work", spy)
    # chunk differs from every other dd test in this process so
    # build_dd_walker_run's lru_cache cannot serve a program traced
    # before the spy was installed
    kw = dict(KW, chunk=1 << 7)
    r = integrate_family_walker_dd("sin_recip_scaled", [1.0], BOUNDS,
                                   1e-6, **kw)
    assert np.all(np.isfinite(r.areas))
    _tl, breed_chunk, _store, _rw = SW._dd_sizing(
        kw["lanes"], kw["capacity"], kw["chunk"], kw["roots_per_lane"])
    assert seen["window"] == 2 * breed_chunk, (seen, breed_chunk)


def test_dd_kill_and_resume_matches_uninterrupted(tmp_path):
    # VERDICT r3 #7: kill-and-resume on the virtual 8-mesh reproduces
    # the uninterrupted areas exactly (leg boundaries replay identical
    # per-cycle computation; cross-leg additions happen on device via
    # the re-fed accumulator columns).
    theta = [1.0, 1.5]
    base = integrate_family_walker_dd("sin_recip_scaled", theta, BOUNDS,
                                      EPS, **KW)
    path = str(tmp_path / "dd.ckpt")
    with pytest.raises(RuntimeError, match="simulated crash"):
        integrate_family_walker_dd("sin_recip_scaled", theta, BOUNDS, EPS,
                                   checkpoint_path=path,
                                   checkpoint_every=1,
                                   _crash_after_legs=2, **KW)
    res = resume_family_walker_dd(path, "sin_recip_scaled", theta, BOUNDS,
                                  EPS, checkpoint_every=1, **KW)
    assert np.array_equal(res.areas, base.areas)          # bit-for-bit
    assert res.metrics.tasks == base.metrics.tasks
    assert res.metrics.splits == base.metrics.splits
    import os
    assert not os.path.exists(path)   # completed run clears its snapshot


def test_dd_resume_rejects_mismatched_identity(tmp_path):
    theta = [1.0, 1.5]
    path = str(tmp_path / "dd.ckpt")
    with pytest.raises(RuntimeError, match="simulated crash"):
        integrate_family_walker_dd("sin_recip_scaled", theta, BOUNDS, EPS,
                                   checkpoint_path=path,
                                   checkpoint_every=1,
                                   _crash_after_legs=1, **KW)
    with pytest.raises(ValueError, match="different run"):
        resume_family_walker_dd(path, "sin_recip_scaled", theta, BOUNDS,
                                1e-8, **KW)


def test_dd_refill_parity_balance_and_fewer_collectives():
    """Round-7 tentpole: the dd walk phase runs out of per-chip VMEM
    root banks (walker's in-kernel refill) with ONE phase-granular
    collective rebalance per phase. Acceptance: parity + near-uniform
    balance + a per-phase collective count STRICTLY below the legacy
    per-cycle engine's on the same one-deep-family workload."""
    theta = [1.0]
    rf = integrate_family_walker_dd("sin_recip_scaled", theta, BOUNDS,
                                    EPS, refill_slots=2, **KW)
    leg = integrate_family_walker_dd("sin_recip_scaled", theta, BOUNDS,
                                     EPS, **KW)
    b = _bag(theta)
    assert np.max(np.abs(rf.areas - b.areas)) < 1e-9
    # exact task conservation vs the f64 bag at this eps (split
    # decisions are placement- and engine-independent)
    drift = abs(rf.metrics.tasks - b.metrics.tasks) / b.metrics.tasks
    assert drift < 1e-3, (rf.metrics.tasks, b.metrics.tasks)
    tpc = rf.metrics.tasks_per_chip
    assert len(tpc) == 8 and min(tpc) > 0
    # looser than legacy's < 2.0: refill mode rebalances once per walk
    # phase (depth-stratified deal) instead of every breed round, so
    # within-phase skew is visible in the totals — the deliberate
    # trade for collapsing the per-round collective chain (the
    # strictly-below assertion beneath is the number bought with it)
    assert max(tpc) / min(tpc) < 4.0, tpc
    assert rf.refill_slots == 2
    # the acceptance number: collectives per walk phase, strictly below
    assert rf.collective_rounds > 0 and leg.collective_rounds > 0
    assert (rf.collective_rounds_per_cycle
            < leg.collective_rounds_per_cycle), (
        rf.collective_rounds_per_cycle, leg.collective_rounds_per_cycle)


def test_dd_refill_slots_validation():
    with pytest.raises(ValueError, match="refill_slots"):
        integrate_family_walker_dd("sin_recip_scaled", [1.0], BOUNDS,
                                   EPS, refill_slots=3, **KW)


def test_dd_refill_kill_and_resume_matches_uninterrupted(tmp_path):
    # acceptance: kill-and-resume bit-identical in BOTH dd modes — this
    # is the refill-mode twin of the legacy test above (leg boundaries
    # fold all lane/bank state back into the bag, so legs replay the
    # identical per-cycle computation)
    theta = [1.0, 1.5]
    kw = dict(KW, refill_slots=2)
    base = integrate_family_walker_dd("sin_recip_scaled", theta, BOUNDS,
                                      EPS, **kw)
    path = str(tmp_path / "ddrf.ckpt")
    with pytest.raises(RuntimeError, match="simulated crash"):
        integrate_family_walker_dd("sin_recip_scaled", theta, BOUNDS, EPS,
                                   checkpoint_path=path,
                                   checkpoint_every=1,
                                   _crash_after_legs=2, **kw)
    res = resume_family_walker_dd(path, "sin_recip_scaled", theta, BOUNDS,
                                  EPS, checkpoint_every=1, **kw)
    assert np.array_equal(res.areas, base.areas)          # bit-for-bit
    assert res.metrics.tasks == base.metrics.tasks
    assert res.metrics.splits == base.metrics.splits
    import os
    assert not os.path.exists(path)


def test_dd_refill_checkpoint_identity_distinct(tmp_path):
    # a refill-mode snapshot must not resume a legacy-mode run: the
    # per-cycle computation differs (bank deal vs boundary refill), so
    # blending the modes would break the bit-identical contract
    theta = [1.0, 1.5]
    path = str(tmp_path / "ddrf.ckpt")
    with pytest.raises(RuntimeError, match="simulated crash"):
        integrate_family_walker_dd("sin_recip_scaled", theta, BOUNDS, EPS,
                                   checkpoint_path=path,
                                   checkpoint_every=1, refill_slots=2,
                                   _crash_after_legs=1, **KW)
    with pytest.raises(ValueError, match="different run"):
        resume_family_walker_dd(path, "sin_recip_scaled", theta, BOUNDS,
                                EPS, **KW)   # legacy resume: refused


def test_dd_simpson_parity_on_mesh():
    """VERDICT r4 #2: both rules behind one interface on the sharded
    walkers. Simpson through the full collective-breed dd engine on the
    virtual 8-mesh must match the f64 Simpson bag within the ds
    contract and still balance the mesh."""
    from ppls_tpu.config import Rule

    theta = 1.0 + np.arange(4) / 4.0
    r = integrate_family_walker_dd("sin_recip_scaled", theta, BOUNDS,
                                   EPS, rule=Rule.SIMPSON, **KW)
    b = integrate_family(get_family("sin_recip_scaled"), theta, BOUNDS,
                         EPS, rule=Rule.SIMPSON,
                         chunk=1 << 10, capacity=1 << 17)
    # interpret-mode ds Simpson vs f64: borderline-flip contract (the
    # walker module docstring), looser than the trapezoid 1e-9 above
    assert np.max(np.abs(r.areas - b.areas)) < 1e-7
    drift = abs(r.metrics.tasks - b.metrics.tasks) / b.metrics.tasks
    assert drift < 0.3, (r.metrics.tasks, b.metrics.tasks)
    tpc = r.metrics.tasks_per_chip
    assert max(tpc) / max(min(tpc), 1) < 3.0, tpc
    # Simpson's O(h^6) convergence leaves only ~10k tasks across 8 chips
    # at this eps — breed covers most of it; the assert pins ENGAGEMENT
    # (kernel ran at all), parity above pins correctness
    assert r.walker_fraction > 0.05, r.walker_fraction


def test_dd_simpson_checkpoint_identity_distinct(tmp_path):
    # a Simpson snapshot must not resume a trapezoid run (engine name
    # carries the rule)
    from ppls_tpu.config import Rule

    theta = [1.0, 1.5]
    path = str(tmp_path / "dd.ckpt")
    with pytest.raises(RuntimeError, match="simulated crash"):
        integrate_family_walker_dd("sin_recip_scaled", theta, BOUNDS, EPS,
                                   checkpoint_path=path,
                                   checkpoint_every=1, rule=Rule.SIMPSON,
                                   _crash_after_legs=1, **KW)
    with pytest.raises(ValueError, match="different run"):
        resume_family_walker_dd(path, "sin_recip_scaled", theta, BOUNDS,
                                EPS, **KW)   # trapezoid resume: refused
