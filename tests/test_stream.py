"""Continuous-batching streaming walker (runtime/stream.py).

Acceptance surface of the streaming tentpole:

* parity: streamed per-request areas match the batch walker / f64 bag
  within the engine's documented ds contract;
* DETERMINISM: the same request set admitted in one batch vs streamed
  over N arrival phases yields BIT-IDENTICAL per-request areas — pinned
  in the pure-f64 streaming mode (``f64_rounds``) on a dyadic-exact
  workload, where every split decision and leaf value is pointwise f64
  and every accumulation is exact, so the admission schedule provably
  cannot move a bit. (With the ds walker engaged, which engine
  evaluates a given leaf depends on co-residents — the documented
  ~1e-9 contract applies and is asserted separately.)
* kill-and-resume mid-stream restores queue + walker and completes
  with identical results (replay identity, same contract as the batch
  engines' leg resume);
* the dd stream (virtual 8-mesh): admission folded into the phase
  boundary, parity + retirement;
* K small requests streamed beat K cold per-request walker calls on
  the device-counted boundary proxies (the CPU-assertable form of the
  >= 3x wall acceptance ratio).
"""

import numpy as np
import pytest

from ppls_tpu.models.integrands import (get_family, get_family_ds,
                                        register_family,
                                        register_family_ds)
from ppls_tpu.ops import ds_kernel as dsk
from ppls_tpu.parallel.walker import integrate_family_walker
from ppls_tpu.runtime.stream import StreamEngine

BOUNDS = (1e-2, 1.0)
EPS = 1e-7
# small interpret-friendly config (the walker test sizing)
KW = dict(slots=8, chunk=1 << 10, capacity=1 << 16, lanes=256,
          roots_per_lane=2, refill_slots=2, seg_iters=32,
          min_active_frac=0.05)
WKW = dict(capacity=1 << 16, lanes=256, roots_per_lane=2,
           refill_slots=2, seg_iters=32, min_active_frac=0.05)

THETA = 1.0 + np.arange(6) / 6.0
REQS = [(float(t), BOUNDS) for t in THETA]


# dyadic-exact quadratic family for the bit-identity contract: on
# [0, 1] every node endpoint is a dyadic rational, th * x^2 with a
# few-bit th keeps every leaf value exactly representable, and the
# trapezoid test error is constant-curvature (uniform-depth trees).
def _quad(x, th):
    return th * x * x


def _quad_ds(x, th):
    return dsk.ds_mul(th, dsk.ds_mul(x, x))


register_family("quad_stream_test", _quad)
register_family_ds("quad_stream_test", _quad_ds)


def test_stream_matches_batch_walker():
    eng = StreamEngine("sin_recip_scaled", EPS, **KW)
    res = eng.run(REQS)
    b = integrate_family_walker(
        get_family("sin_recip_scaled"), get_family_ds("sin_recip_scaled"),
        THETA, BOUNDS, EPS, **WKW)
    assert len(res.completed) == len(REQS)
    assert np.max(np.abs(res.areas - b.areas)) < 3e-9
    # task conservation: the streamed engine does the same work, it
    # does not silently degrade or duplicate
    drift = abs(res.totals["tasks"] - b.metrics.tasks) / b.metrics.tasks
    assert drift < 0.02, (res.totals["tasks"], b.metrics.tasks)
    # the walker (not the f64 drain) owns the hot share while streaming
    occ = res.occupancy_summary(KW["lanes"])
    assert occ["walker_fraction"] > 0.5, occ
    # per-request latency accounting is populated and monotone
    for c in res.completed:
        assert c.retire_phase >= c.admit_phase >= c.submit_phase
        assert c.phases_in_flight >= 1
        assert c.last_credited_phase <= c.retire_phase


def test_stream_arrival_schedule_parity():
    # streamed over arrival phases: same areas within the ds contract
    # (which engine evaluates a leaf is schedule-dependent — the
    # bit-level contract is the f64-mode test below)
    e1 = StreamEngine("sin_recip_scaled", EPS, **KW)
    r1 = e1.run(REQS)
    e2 = StreamEngine("sin_recip_scaled", EPS, **KW)
    r2 = e2.run(REQS, arrival_phase=[0, 0, 1, 2, 3, 5])
    assert np.max(np.abs(r1.areas - r2.areas)) < 3e-9
    assert len(r2.completed) == len(REQS)
    # later arrivals really were admitted later
    admits = {c.rid: c.admit_phase for c in r2.completed}
    assert admits[5] >= 5


def test_stream_batch_vs_streamed_bit_identity_f64_mode():
    """The determinism acceptance: one-batch admission vs N arrival
    phases, bit-identical per-request areas. Pure-f64 phases
    (f64_rounds) + dyadic workload: split decisions and leaf values
    are pointwise f64 (schedule-independent) and every sum is exact,
    so equality holds at the bit level BY CONSTRUCTION — this test
    pins the construction."""
    kw = dict(KW, f64_rounds=4)
    theta = [1.0, 1.25, 1.5, 2.0, 0.75, 3.0]
    reqs = [(t, (0.0, 1.0)) for t in theta]
    r1 = StreamEngine("quad_stream_test", 1e-9, **kw).run(reqs)
    r2 = StreamEngine("quad_stream_test", 1e-9, **kw).run(
        reqs, arrival_phase=[0, 1, 2, 3, 5, 8])
    assert len(r1.completed) == len(reqs)
    assert len(r2.completed) == len(reqs)
    assert np.array_equal(r1.areas, r2.areas)          # bit-for-bit
    # identical work too: pointwise f64 decisions conserve the tree
    assert r1.totals["tasks"] == r2.totals["tasks"]
    # and the areas are right (exact integral th/3 up to eps-level)
    assert np.max(np.abs(r1.areas - np.asarray(theta) / 3.0)) < 1e-6


def test_stream_kill_and_resume_matches_uninterrupted(tmp_path):
    arr = [0, 0, 1, 2, 3, 5]
    base = StreamEngine("sin_recip_scaled", EPS, **KW).run(
        REQS, arrival_phase=arr)
    path = str(tmp_path / "stream.ckpt")
    eng = StreamEngine("sin_recip_scaled", EPS, checkpoint_path=path,
                       checkpoint_every=1, **KW)
    with pytest.raises(RuntimeError, match="simulated crash"):
        eng.run(REQS, arrival_phase=arr, _crash_after_phases=3)
    eng2 = StreamEngine.resume(path, "sin_recip_scaled", EPS,
                               checkpoint_every=1, **KW)
    assert eng2.phase == 3
    # replay the rest of the arrival schedule: rids are submission-
    # ordered, so the resumed driver skips the already-submitted prefix
    k = eng2.next_rid
    while not eng2.idle or k < len(REQS):
        while k < len(REQS) and arr[k] <= eng2.phase:
            eng2.submit(*REQS[k])
            k += 1
        eng2.step()
    res = eng2.result()
    assert np.array_equal(res.areas, base.areas)       # bit-for-bit
    assert res.phases == base.phases
    assert len(res.completed) == len(base.completed)


def test_stream_background_checkpoint_writer_bit_identical(tmp_path):
    """Round 22: moving checkpoint serialization to the background
    writer changes WHERE the np.savez happens, not WHAT is committed —
    kill-and-resume through background-written cuts restores the same
    coordinated state and the continued run stays bit-identical to the
    undisturbed (synchronous-writer) one. The read path flushes the
    writer, so an in-process resume can never race a queued cut."""
    arr = [0, 0, 1, 2, 3, 5]
    base = StreamEngine("sin_recip_scaled", EPS, **KW).run(
        REQS, arrival_phase=arr)
    path = str(tmp_path / "stream.ckpt")
    eng = StreamEngine("sin_recip_scaled", EPS, checkpoint_path=path,
                       checkpoint_every=1, checkpoint_background=True,
                       **KW)
    with pytest.raises(RuntimeError, match="simulated crash"):
        eng.run(REQS, arrival_phase=arr, _crash_after_phases=3)
    # the background flag is write MECHANICS, not snapshot identity:
    # a resume may run either mode against the same container
    eng2 = StreamEngine.resume(path, "sin_recip_scaled", EPS,
                               checkpoint_every=1,
                               checkpoint_background=True, **KW)
    assert eng2.phase == 3
    k = eng2.next_rid
    while not eng2.idle or k < len(REQS):
        while k < len(REQS) and arr[k] <= eng2.phase:
            eng2.submit(*REQS[k])
            k += 1
        eng2.step()
    res = eng2.result()
    assert np.array_equal(res.areas, base.areas)       # bit-for-bit
    assert res.phases == base.phases
    assert len(res.completed) == len(base.completed)


def test_stream_resume_rejects_mismatched_identity(tmp_path):
    path = str(tmp_path / "stream.ckpt")
    eng = StreamEngine("sin_recip_scaled", EPS, checkpoint_path=path,
                       checkpoint_every=1, **KW)
    with pytest.raises(RuntimeError, match="simulated crash"):
        eng.run(REQS, _crash_after_phases=1)
    with pytest.raises(ValueError, match="different run"):
        StreamEngine.resume(path, "sin_recip_scaled", 1e-8, **KW)


def test_stream_dd_parity_on_mesh():
    """The dd engine streams too: admission folded into the phase
    boundary (seeds enter each chip's queue as the phase opens and
    ride phase_reshard's occupancy decision + stratified deal)."""
    from ppls_tpu.parallel.bag_engine import integrate_family

    kw = dict(KW, chunk=1 << 8, engine="walker-dd", n_devices=8)
    eng = StreamEngine("sin_recip_scaled", 1e-9, **kw)
    res = eng.run([(float(t), (1e-3, 1.0)) for t in THETA],
                  arrival_phase=[0, 0, 1, 2, 3, 4])
    b = integrate_family(get_family("sin_recip_scaled"), THETA,
                         (1e-3, 1.0), 1e-9,
                         chunk=1 << 10, capacity=1 << 17)
    assert len(res.completed) == len(THETA)
    assert np.max(np.abs(res.areas - b.areas)) < 1e-9
    occ = res.occupancy_summary(KW["lanes"])
    assert occ["walker_fraction"] > 0.3, occ


def test_stream_dd_requires_refill():
    with pytest.raises(ValueError, match="refill_slots"):
        StreamEngine("sin_recip_scaled", EPS,
                     **dict(KW, refill_slots=0, engine="walker-dd",
                            n_devices=8))


def _dd_events_surface(path):
    """Deterministic comparison surface of a dd timeline: retire
    records (minus wall latency), phase delta rows, and the round-11
    per-chip flight-recorder span attrs — all device-counted."""
    import json as _json
    retires, phases, chips = [], [], []
    for ln in open(path):
        r = _json.loads(ln)
        if r["ev"] == "event" and r.get("name") == "retire":
            a = dict(r["attrs"])
            a.pop("latency_s", None)
            retires.append(a)
        elif r["ev"] == "span_close":
            a = r.get("attrs") or {}
            if "wsteps" in a and "live_rows" in a:
                chips.append(a)                  # chip child span
            elif a.get("tasks") is not None:
                phases.append(a)                 # phase span
    return sorted(retires, key=lambda a: a["rid"]), phases, chips


def test_stream_dd_kill_and_resume_with_flight_recorder(tmp_path):
    """Round-11 acceptance: the dd stream snapshots/resumes on the
    virtual 8-mesh, and the per-chip flight-recorder events file
    validates and is BIT-FOR-BIT identical (device-counted surface)
    between the undisturbed run and the crashed-prefix + resumed-tail
    union — chip spans, phase rows, and retire records alike."""
    from ppls_tpu.obs import Telemetry
    from ppls_tpu.utils.artifact_schema import validate_events_text

    kw = dict(KW, chunk=1 << 8, engine="walker-dd", n_devices=8)
    reqs = [(float(t), (1e-3, 1.0)) for t in THETA]
    arr = [0, 0, 1, 2, 3, 4]

    base_ev = str(tmp_path / "base.jsonl")
    tel = Telemetry(events_path=base_ev)
    base = StreamEngine("sin_recip_scaled", 1e-9, telemetry=tel,
                        **kw).run(reqs, arrival_phase=arr)
    tel.close()
    assert validate_events_text(open(base_ev).read()) == []
    base_r, base_p, base_c = _dd_events_surface(base_ev)
    assert base_c, "no per-chip flight-recorder spans in the timeline"
    assert len(base_c) % 8 == 0         # 8 chips per recorded phase

    ck = str(tmp_path / "dd.ckpt")
    crash_ev = str(tmp_path / "crash.jsonl")
    tel2 = Telemetry(events_path=crash_ev)
    eng = StreamEngine("sin_recip_scaled", 1e-9, telemetry=tel2,
                       checkpoint_path=ck, checkpoint_every=1, **kw)
    with pytest.raises(RuntimeError, match="simulated crash"):
        eng.run(reqs, arrival_phase=arr, _crash_after_phases=3)
    tel2.close()
    assert validate_events_text(open(crash_ev).read(),
                                require_balanced=False) == []

    resume_ev = str(tmp_path / "resume.jsonl")
    tel3 = Telemetry(events_path=resume_ev)
    eng2 = StreamEngine.resume(ck, "sin_recip_scaled", 1e-9,
                               telemetry=tel3, checkpoint_every=1,
                               **kw)
    assert eng2.phase == 3
    k = eng2.next_rid
    while not eng2.idle or k < len(reqs):
        while k < len(reqs) and arr[k] <= eng2.phase:
            eng2.submit(*reqs[k])
            k += 1
        eng2.step()
    res2 = eng2.result()
    tel3.close()

    # areas, registry totals (lane-waste buckets included), and phase
    # count replay bit-for-bit
    assert np.array_equal(res2.areas, base.areas)
    assert res2.totals == base.totals
    assert res2.phases == base.phases
    # the timeline union equals the undisturbed run's, chip spans too
    crash_r, crash_p, crash_c = _dd_events_surface(crash_ev)
    res_r, res_p, res_c = _dd_events_surface(resume_ev)
    assert sorted(crash_r + res_r, key=lambda a: a["rid"]) == base_r
    assert crash_p + res_p == base_p
    assert crash_c + res_c == base_c


def test_stream_beats_cold_calls_device_proxies():
    """The >= 3x acceptance for K small requests, in its CPU-
    assertable device-counted form: K cold per-request walker calls
    pay K full breed/walk/drain boundary cadences; the stream shares
    them. (Wall ratios on this container time the interpreter — the
    bench records both; the proxy is what a CPU round can assert.)"""
    K = 8
    theta = 1.0 + np.arange(K) / K
    f = get_family("sin_recip_scaled")
    fds = get_family_ds("sin_recip_scaled")
    cold_boundaries = 0
    cold_areas = np.empty(K)
    for i, t in enumerate(theta):
        r1 = integrate_family_walker(f, fds, [float(t)], BOUNDS, EPS,
                                     **WKW)
        cold_areas[i] = r1.areas[0]
        # rounds includes breed+drain rounds AND walker segments — the
        # per-run boundary cadence (walker._assemble_result)
        cold_boundaries += r1.metrics.rounds
    res = StreamEngine("sin_recip_scaled", EPS, **KW).run(
        [(float(t), BOUNDS) for t in theta])
    stream_boundaries = int(res.totals["rounds"] + res.totals["segs"])
    assert np.max(np.abs(res.areas - cold_areas)) < 3e-9
    assert stream_boundaries > 0
    ratio = cold_boundaries / stream_boundaries
    assert ratio >= 3.0, (cold_boundaries, stream_boundaries)


def test_stream_request_validation():
    eng = StreamEngine("sin_recip_scaled", EPS, **KW)
    # out-of-ds-domain request refused at submit, not at retire
    with pytest.raises(ValueError, match="Cody-Waite"):
        eng.submit(2.0, (1e-7, 1.0))
    assert eng.pending == 0


def test_serve_cli_events_and_metrics_port(tmp_path, capsys):
    """The round-10 serve surface: a seeded synthetic run with
    --events produces a schema-valid timeline whose retire records
    (areas, phase latencies, device-counter deltas) are bit-identical
    across a rerun; --metrics-port 0 binds and announces an ephemeral
    endpoint for the run's lifetime."""
    import json as _json

    from ppls_tpu.__main__ import main
    from ppls_tpu.utils.artifact_schema import validate_events_text

    def run(ev_path):
        rc = main(["serve", "--slots", "8", "--chunk", "512",
                   "--capacity", "65536", "--lanes", "256",
                   "--refill-slots", "2", "--synthetic", "4",
                   "--arrival-rate", "2", "--seed", "7",
                   "--eps", "1e-6", "-a", "1e-2", "-b", "1.0",
                   "--events", ev_path, "--metrics-port", "0"])
        assert rc == 0
        out = capsys.readouterr().out
        return [_json.loads(ln) for ln in out.strip().splitlines()
                if ln.startswith("{")]

    def surface(ev_path):
        retires, deltas = [], []
        for ln in open(ev_path):
            r = _json.loads(ln)
            if r["ev"] == "event" and r.get("name") == "retire":
                a = dict(r["attrs"])
                a.pop("latency_s", None)
                retires.append(a)
            elif r["ev"] == "span_close" \
                    and r.get("attrs", {}).get("tasks") is not None:
                deltas.append(r["attrs"])
        return sorted(retires, key=lambda a: a["rid"]), deltas

    e1, e2 = str(tmp_path / "a.jsonl"), str(tmp_path / "b.jsonl")
    recs1 = run(e1)
    recs2 = run(e2)
    for p in (e1, e2):
        assert validate_events_text(open(p).read()) == []
    assert surface(e1) == surface(e2)
    assert len(surface(e1)[0]) == 4
    # the summary's latency block comes from the same histogram
    # quantile every other reader uses — identical across the rerun
    s1 = [r for r in recs1 if r.get("summary")][0]
    s2 = [r for r in recs2 if r.get("summary")][0]
    assert s1["latency"]["p50_phases"] == s2["latency"]["p50_phases"]
    assert s1["totals"] == s2["totals"]
    # retire areas in the JSONL stream match the events timeline
    areas_stream = {r["rid"]: r["area"] for r in recs1
                    if not r.get("summary")}
    areas_events = {a["rid"]: a["area"] for a in surface(e1)[0]}
    assert areas_stream == areas_events


def test_serve_cli_metrics_port_zero_binds_free_port(tmp_path):
    """Satellite: ``--metrics-port 0`` must bind an ephemeral port,
    announce it on stderr BEFORE the run starts (the only usable
    configuration on shared CI hosts), serve parseable exposition
    while the run is live, and repeat the bound port on the summary
    line. Run at true CLI level (subprocess) so the announcement
    ordering is the real one."""
    import json
    import os
    import re
    import subprocess
    import sys as _sys
    import urllib.request

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.Popen(
        [_sys.executable, "-m", "ppls_tpu", "serve",
         "--slots", "8", "--chunk", "512", "--capacity", "65536",
         "--lanes", "256", "--refill-slots", "2",
         "--synthetic", "3", "--arrival-rate", "2", "--seed", "3",
         "--eps", "1e-5", "-a", "1e-2", "-b", "1.0",
         "--metrics-port", "0"],
        cwd=repo, env=env, stdout=subprocess.PIPE,
        stderr=subprocess.PIPE, text=True)
    try:
        # the announcement is printed before the first phase (and
        # before the engine compiles), so it arrives well before exit
        line = proc.stderr.readline()
        m = re.search(r"metrics on (http://127\.0\.0\.1:(\d+)/metrics)",
                      line)
        assert m, f"no metrics announcement, got {line!r}"
        url, port = m.group(1), int(m.group(2))
        assert port != 0
        # scrape while the run is live (compile alone takes seconds)
        text = urllib.request.urlopen(url, timeout=10).read().decode()
        assert text.endswith("\n")
        out, err = proc.communicate(timeout=600)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.communicate()
    assert proc.returncode == 0, err
    summary = [json.loads(ln) for ln in out.splitlines()
               if ln.startswith("{")][-1]
    assert summary.get("summary") is True
    assert summary["metrics_port"] == port
    assert summary["metrics_url"] == url


def test_serve_cli_synthetic(capsys):
    import json as _json

    from ppls_tpu.__main__ import main
    rc = main(["serve", "--slots", "8", "--chunk", "512",
               "--capacity", "65536", "--lanes", "256",
               "--refill-slots", "2", "--synthetic", "4",
               "--arrival-rate", "2", "--eps", "1e-6",
               "-a", "1e-2", "-b", "1.0"])
    assert rc == 0
    lines = [ln for ln in capsys.readouterr().out.strip().splitlines()
             if ln.startswith("{")]
    recs = [_json.loads(ln) for ln in lines]
    summary = [r for r in recs if r.get("summary")]
    results = [r for r in recs if not r.get("summary")]
    assert len(summary) == 1 and len(results) == 4
    assert summary[0]["completed"] == 4
    assert summary[0]["requests_per_sec"] > 0
    assert {"p50_phases", "p99_phases"} <= set(summary[0]["latency"])
    for r in results:
        assert np.isfinite(r["area"])
        assert r["phases_in_flight"] >= 1
