"""Strict-mode sanitizer lane (ISSUE 5).

Three runtime contracts the static rules (tools/graftlint) cannot
prove, pinned dynamically:

* rank-promotion discipline: the whole suite runs with
  ``jax_numpy_rank_promotion="raise"`` (tests/conftest.py) — these
  tests pin that the flag is really live in-process, so a conftest
  refactor can't silently turn the sanitizer off;
* retracing guard: ``run_stream_cycle`` and the walker cycle
  (``_run_cycles``) compile EXACTLY ONCE across a multi-phase streamed
  run / a 2-leg kill-and-resume — the "one compiled program serves the
  whole stream" claim, asserted on the pjit cache itself;
* loud-NaN contract: a NaN integrand surfaces as a
  ``FloatingPointError`` through admit -> walk -> retire, never as a
  silently-wrong finite area; the opt-in ``PPLS_DEBUG_NANS=1`` lane
  (conftest) tightens this to raise at the producing primitive, and
  the injection test proves that mode end-to-end here regardless of
  the env flag.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ppls_tpu.models.integrands import (get_family, get_family_ds,
                                        register_family,
                                        register_family_ds)
from ppls_tpu.ops import ds_kernel as dsk
from ppls_tpu.parallel.walker import (_run_cycles,
                                      integrate_family_walker,
                                      resume_family_walker,
                                      run_stream_cycle)
from ppls_tpu.runtime.stream import StreamEngine

# the walker-test sizing (small, interpret-friendly)
STREAM_KW = dict(slots=8, chunk=1 << 10, capacity=1 << 16, lanes=256,
                 roots_per_lane=2, refill_slots=2, seg_iters=32,
                 min_active_frac=0.05)
WALK_KW = dict(capacity=1 << 16, lanes=256, roots_per_lane=1,
               seg_iters=8, max_segments=1, max_cycles=256,
               min_active_frac=0.05)
BOUNDS = (1e-2, 1.0)
EPS = 1e-7
THETA = 1.0 + np.arange(4) / 4.0


# ---------------------------------------------------------------------------
# rank promotion
# ---------------------------------------------------------------------------

def test_rank_promotion_strict_mode_is_live():
    """The sanitizer must actually be on in this process — not just
    written in conftest. An implicit (2,2)+(2,) promotion must raise,
    and the package import must not have flipped the flag back."""
    assert jax.config.jax_numpy_rank_promotion == "raise"
    with pytest.raises((ValueError, TypeError)):
        _ = jnp.ones((2, 2), jnp.float64) + jnp.ones(2, jnp.float64)


def test_explicit_broadcast_still_allowed():
    # The strict mode forbids IMPLICIT rank promotion only: the
    # explicit spellings the package uses ([None], broadcast_to)
    # must keep working.
    a = jnp.ones((2, 2), jnp.float64)
    b = jnp.ones(2, jnp.float64)
    out = a + b[None, :]
    assert out.shape == (2, 2)
    out2 = a + jnp.broadcast_to(b, (2, 2))
    assert out2.shape == (2, 2)


# ---------------------------------------------------------------------------
# retracing guards (compile exactly once)
# ---------------------------------------------------------------------------

def test_stream_cycle_compiles_exactly_once(compile_once_guard):
    """A multi-phase streamed run (6 requests over 6 arrival phases)
    drives run_stream_cycle once per phase; the phase index is traced
    and everything else is static-stable, so the pjit cache must hold
    EXACTLY ONE entry at the end. A second entry = some config leaked
    into the traced signature and the stream recompiles per phase."""
    reqs = [(float(t), BOUNDS) for t in 1.0 + np.arange(6) / 6.0]
    with compile_once_guard(run_stream_cycle):
        eng = StreamEngine("sin_recip_scaled", EPS, **STREAM_KW)
        res = eng.run(reqs, arrival_phase=[0, 1, 2, 3, 4, 5])
    assert len(res.completed) == len(reqs)
    assert res.phases >= 3


def test_walker_resume_compiles_exactly_once(compile_once_guard,
                                             tmp_path):
    """A 2-leg kill-and-resume walker run calls _run_cycles once per
    leg in the dying process and again per leg in the resuming one —
    all with identical statics (max_cycles=checkpoint_every), so one
    compiled program must serve every leg."""
    f = get_family("sin_recip_scaled")
    f_ds = get_family_ds("sin_recip_scaled")
    path = str(tmp_path / "walker.ckpt")
    with compile_once_guard(_run_cycles):
        with pytest.raises(RuntimeError, match="simulated crash"):
            integrate_family_walker(
                f, f_ds, THETA, BOUNDS, EPS, **WALK_KW,
                checkpoint_path=path, checkpoint_every=2,
                _crash_after_legs=1)
        res = resume_family_walker(path, f, f_ds, THETA, BOUNDS, EPS,
                                   **WALK_KW, checkpoint_every=2)
    assert res.metrics.tasks > 0


# ---------------------------------------------------------------------------
# loud-NaN contract (admit -> walk -> retire)
# ---------------------------------------------------------------------------

def _nan_inject(x, th):
    """th > 8 poisons the right half of the domain with NaN — the
    injected fault for the loud-NaN contract. Healthy thetas are the
    dyadic quadratic of the stream determinism tests."""
    poisoned = (th > 8.0) & (x > 0.5)
    return jnp.where(poisoned, jnp.nan, th * x * x)


def _nan_inject_ds(x, th):
    # ds twin (only engaged by the Pallas walker; the injection tests
    # run the pure-f64 streaming mode where every value is f64)
    return dsk.ds_mul(th, dsk.ds_mul(x, x))


register_family("nan_inject_test", _nan_inject)
register_family_ds("nan_inject_test", _nan_inject_ds)


@pytest.mark.nan_injection
def test_stream_nan_injection_surfaces_loudly():
    """A NaN integrand must travel admit -> walk -> retire and raise
    at retirement — NOT retire as a silently-wrong finite area, and
    NOT poison the healthy co-resident request's accounting path.
    (Pure-f64 streaming mode: in walker mode NaN-err roots are
    deliberately kept live for re-breeding, which is the right
    batch-engine behavior but would keep a permanently-NaN family
    in-flight forever. nan_injection marker: this pins the RETIRE-path
    contract, so debug-nans must not preempt the NaN's journey.)"""
    kw = dict(STREAM_KW, f64_rounds=4)
    eng = StreamEngine("nan_inject_test", 1e-9, **kw)
    eng.submit(1.0, (0.0, 1.0))      # healthy
    eng.submit(9.0, (0.0, 1.0))      # poisoned
    with pytest.raises(FloatingPointError, match="non-finite"):
        eng.drain()


def test_stream_nan_injection_debug_nans_lane():
    """The jax_debug_nans lane tightens the contract: the
    FloatingPointError fires at the PRODUCING primitive inside the
    jitted phase program, before the NaN ever reaches an accumulator.
    A healthy stream first proves the lane is usable (no false
    positives), then the injected fault proves it is loud."""
    prev = jax.config.jax_debug_nans
    jax.config.update("jax_debug_nans", True)
    try:
        kw = dict(STREAM_KW, f64_rounds=4)
        healthy = StreamEngine("nan_inject_test", 1e-9, **kw)
        res = healthy.run([(1.0, (0.0, 1.0)), (2.0, (0.0, 1.0))])
        assert len(res.completed) == 2
        assert np.all(np.isfinite(res.areas))

        eng = StreamEngine("nan_inject_test", 1e-9, **kw)
        eng.submit(9.0, (0.0, 1.0))
        with pytest.raises(FloatingPointError):
            eng.drain()
    finally:
        # restore, don't hardcode False: in the PPLS_DEBUG_NANS=1 lane
        # the flag must stay ON for the rest of the suite
        jax.config.update("jax_debug_nans", prev)
