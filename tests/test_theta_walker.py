"""Round 13 — many-theta amortized walker (``theta_block`` = T > 1).

One walker frontier scores a batch of T per-user thetas per interval:
groups of T adjacent SIMD lanes share one (i, d) DFS walk, the split
test runs in UNION-REFINEMENT mode (split iff ANY unretired theta
fails), per-theta accepts retire thetas individually through the
(mk_i, mk_d) ancestor markers, and credit lands in a (slots, T)
accumulator through the exact segment sum.

Contracts pinned here:

* PER-THETA QUALITY — each theta's credited leaf set is at least as
  refined as its solo run, so its area error versus the exact integral
  is never worse than the solo run's plus one eps. (The raw
  batched-minus-solo gap is bounded by SOLO's own global error, which
  is O(leaves x eps) under the per-leaf test semantics — the batched
  run is the MORE accurate of the two; BASELINE.md round 13.)
* RECONCILIATION — the five lane-waste buckets (theta_overwalk
  appended in round 13) partition lanes x kernel steps exactly, on the
  walker, the dd engine, and the stream.
* KILL-AND-RESUME — theta-batched runs snapshot/resume bit-identically
  on the walker, the dd engine (virtual 8-mesh), and the stream, and
  ``theta_block`` is snapshot identity (cross-mode resume refuses).
"""

import json

import numpy as np
import pytest

from ppls_tpu.models.integrands import (FAMILY_EXACT_VEC, family_exact,
                                        get_family, get_family_ds)
from ppls_tpu.parallel.walker import (N_WASTE, WASTE_FIELDS,
                                      integrate_family_walker,
                                      normalize_theta_batch,
                                      resume_family_walker,
                                      validate_theta_block)
from ppls_tpu.config import Rule

F = get_family("sin_scaled")
F_DS = get_family_ds("sin_scaled")
B = (0.0, 1.0)
EPS = 1e-6
T = 8
# one shared sizing so the jitted cycle program compiles once across
# this module (compile-once guard economics)
KW = dict(capacity=1 << 16, lanes=256, roots_per_lane=2,
          refill_slots=2, seg_iters=2048, min_active_frac=0.05)


def _exact(th):
    return np.asarray(family_exact("sin_scaled", *B, th))


# ---------------------------------------------------------------------------
# validation surface
# ---------------------------------------------------------------------------


def test_validate_theta_block_errors():
    with pytest.raises(ValueError, match="power of two"):
        validate_theta_block(6, lanes=256, refill_slots=2,
                             rule=Rule.TRAPEZOID, m=1)
    with pytest.raises(ValueError, match="divide lanes"):
        validate_theta_block(512, lanes=256, refill_slots=2,
                             rule=Rule.TRAPEZOID, m=1)
    with pytest.raises(ValueError, match="refill_slots"):
        validate_theta_block(8, lanes=256, refill_slots=0,
                             rule=Rule.TRAPEZOID, m=1)
    with pytest.raises(ValueError, match="TRAPEZOID"):
        validate_theta_block(8, lanes=256, refill_slots=2,
                             rule=Rule.SIMPSON, m=1)
    with pytest.raises(ValueError, match="fam field"):
        validate_theta_block(2048, lanes=4096, refill_slots=2,
                             rule=Rule.TRAPEZOID, m=64)
    assert validate_theta_block(1, lanes=256, refill_slots=0,
                                rule=Rule.SIMPSON, m=1) == 1


def test_normalize_theta_batch_shapes():
    t2, rep = normalize_theta_batch([1.0, 2.0, 3.0], 1)
    assert t2.shape == (3, 1) and np.array_equal(rep, [1.0, 2.0, 3.0])
    t2, rep = normalize_theta_batch([1.0, 2.0], 2)    # (T,) -> (1, T)
    assert t2.shape == (1, 2) and rep.tolist() == [1.0]
    t2, rep = normalize_theta_batch([[1., 2.], [3., 4.]], 2)
    assert t2.shape == (2, 2) and rep.tolist() == [1.0, 3.0]
    with pytest.raises(ValueError, match="exactly T"):
        normalize_theta_batch([1.0, 2.0, 3.0], 2)


# ---------------------------------------------------------------------------
# the per-theta quality property (union-refinement contract)
# ---------------------------------------------------------------------------


def test_property_random_batch_per_theta_quality():
    # every theta of a RANDOM batch: the batched area is within eps of
    # its solo-run area modulo the solo run's own distance from truth —
    # equivalently, batched error vs exact never exceeds solo error
    # vs exact + eps (each theta's batched leaf set is at least as
    # refined as its solo run's)
    rng = np.random.default_rng(1337)
    th = np.sort(rng.uniform(1.0, 4.0, T))
    r = integrate_family_walker(F, F_DS, th.reshape(1, T), B, EPS,
                                theta_block=T, **KW)
    assert r.areas.shape == (1, T)
    ex = _exact(th)
    solo = np.array([
        integrate_family_walker(F, F_DS, [t], B, EPS, **KW).areas[0]
        for t in th])
    solo_err = np.abs(solo - ex)
    batched_err = np.abs(r.areas[0] - ex)
    assert np.all(batched_err <= solo_err + EPS), \
        (batched_err, solo_err)
    # ... which bounds the distance to the solo areas themselves
    assert np.all(np.abs(r.areas[0] - solo) <= solo_err + EPS)


def test_theta_rerun_bit_identical():
    th = np.linspace(1.0, 4.0, T).reshape(1, T)
    r1 = integrate_family_walker(F, F_DS, th, B, EPS,
                                 theta_block=T, **KW)
    r2 = integrate_family_walker(F, F_DS, th, B, EPS,
                                 theta_block=T, **KW)
    assert np.array_equal(r1.areas, r2.areas)
    assert r1.metrics.tasks == r2.metrics.tasks


def test_scout_and_double_buffer_compose_with_theta():
    th = np.linspace(1.0, 4.0, T).reshape(1, T)
    base = integrate_family_walker(F, F_DS, th, B, EPS,
                                   theta_block=T, **KW)
    sc = integrate_family_walker(F, F_DS, th, B, EPS, theta_block=T,
                                 scout_dtype="f32", **KW)
    db = integrate_family_walker(F, F_DS, th, B, EPS, theta_block=T,
                                 double_buffer=True, **KW)
    # the scout confirm pass re-takes every credit in full ds and the
    # rolling deal only reorders bank windows — areas stay within the
    # interpret-mode ds noise floor of the plain theta run
    assert np.max(np.abs(base.areas - sc.areas)) <= 1e-9
    assert np.max(np.abs(base.areas - db.areas)) <= 1e-9
    assert sc.scout_evals > 0
    assert sc.attribution()["reconciles"]
    assert db.attribution()["reconciles"]


# ---------------------------------------------------------------------------
# lane-waste reconciliation with theta_overwalk
# ---------------------------------------------------------------------------


def test_waste_reconciles_with_live_overwalk_bucket():
    assert WASTE_FIELDS[4] == "theta_overwalk" and N_WASTE == 5
    th = np.linspace(1.0, 4.0, T).reshape(1, T)
    r = integrate_family_walker(F, F_DS, th, B, 1e-7,
                                theta_block=T, **KW)
    a = r.attribution()
    assert a["reconciles"]
    assert int(np.asarray(r.waste).sum()) == r.kernel_steps * r.lanes
    # a heterogeneous theta batch at this eps retires thetas early:
    # the overwalk bucket must be LIVE, not vacuously zero
    assert int(r.waste[4]) > 0
    # scalar runs keep the bucket identically zero
    r1 = integrate_family_walker(F, F_DS, [1.5], B, 1e-7, **KW)
    assert int(r1.waste[4]) == 0 and r1.attribution()["reconciles"]


# ---------------------------------------------------------------------------
# kill-and-resume bit-identity + snapshot identity (walker)
# ---------------------------------------------------------------------------

# m = 3 slots: breeding doubles 3 -> 96 > one deal (64 roots), so the
# run spans >= 2 cycles and a real leg boundary exists to crash at
CKPT_TH = np.linspace(1.0, 2.5, 3 * T).reshape(3, T)
CKPT_B = (1e-2, 1.0)
CKPT_EPS = 1e-8
F_R = get_family("sin_recip_scaled")
F_R_DS = get_family_ds("sin_recip_scaled")


@pytest.mark.parametrize("mode", [dict(), dict(scout_dtype="f32")])
def test_theta_kill_and_resume_bit_identical(tmp_path, mode):
    kw = dict(KW, theta_block=T, **mode)
    base = integrate_family_walker(F_R, F_R_DS, CKPT_TH, CKPT_B,
                                   CKPT_EPS, **kw)
    assert base.cycles >= 2      # a real leg boundary exists
    path = str(tmp_path / "wt.ckpt")
    with pytest.raises(RuntimeError, match="simulated crash"):
        integrate_family_walker(F_R, F_R_DS, CKPT_TH, CKPT_B,
                                CKPT_EPS, **kw, checkpoint_path=path,
                                checkpoint_every=1,
                                _crash_after_legs=1)
    res = resume_family_walker(path, F_R, F_R_DS, CKPT_TH, CKPT_B,
                               CKPT_EPS, **kw, checkpoint_every=1)
    assert np.array_equal(res.areas, base.areas)          # bit-for-bit
    assert res.metrics.tasks == base.metrics.tasks
    assert np.array_equal(np.asarray(res.waste),
                          np.asarray(base.waste))


def test_theta_block_is_snapshot_identity(tmp_path):
    path = str(tmp_path / "wt.ckpt")
    kw = dict(KW, theta_block=T)
    with pytest.raises(RuntimeError, match="simulated crash"):
        integrate_family_walker(F_R, F_R_DS, CKPT_TH, CKPT_B,
                                CKPT_EPS, **kw, checkpoint_path=path,
                                checkpoint_every=1,
                                _crash_after_legs=1)
    # a scalar engine must refuse the theta-batched snapshot (the
    # (m, T) accumulator layout and union schedule are identity)
    with pytest.raises(ValueError, match="different run"):
        resume_family_walker(path, F_R, F_R_DS,
                             CKPT_TH.reshape(-1), CKPT_B, CKPT_EPS,
                             **KW, checkpoint_every=1)


# ---------------------------------------------------------------------------
# dd engine (virtual 8-mesh)
# ---------------------------------------------------------------------------

DD_KW = dict(chunk=1 << 8, capacity=1 << 16, lanes=256,
             roots_per_lane=2, refill_slots=2, n_devices=8,
             min_active_frac=0.05)


def test_dd_theta_quality_and_reconciliation():
    from ppls_tpu.parallel.sharded_walker import (
        integrate_family_walker_dd)
    th = np.linspace(1.0, 4.0, T)
    r = integrate_family_walker_dd(
        "sin_scaled", th.reshape(1, T), B, EPS, theta_block=T,
        **DD_KW)
    assert r.areas.shape == (1, T)
    a = r.attribution()
    assert a["reconciles"]
    assert r.waste_per_chip.shape == (8, N_WASTE)
    ex = _exact(th)
    solo = np.array([
        integrate_family_walker(F, F_DS, [t], B, EPS, **KW).areas[0]
        for t in th])
    assert np.all(np.abs(r.areas[0] - ex)
                  <= np.abs(solo - ex) + EPS)


def test_dd_theta_kill_and_resume_bit_identical(tmp_path):
    from ppls_tpu.parallel.sharded_walker import (
        integrate_family_walker_dd, resume_family_walker_dd)
    kw = dict(DD_KW, theta_block=T)
    base = integrate_family_walker_dd(
        "sin_recip_scaled", CKPT_TH, CKPT_B, CKPT_EPS, **kw)
    assert base.cycles >= 2
    path = str(tmp_path / "ddt.ckpt")
    with pytest.raises(RuntimeError, match="simulated crash"):
        integrate_family_walker_dd(
            "sin_recip_scaled", CKPT_TH, CKPT_B, CKPT_EPS,
            checkpoint_path=path, checkpoint_every=1,
            _crash_after_legs=1, **kw)
    res = resume_family_walker_dd(
        path, "sin_recip_scaled", CKPT_TH, CKPT_B, CKPT_EPS,
        checkpoint_every=1, **kw)
    assert np.array_equal(res.areas, base.areas)          # bit-for-bit
    assert res.metrics.tasks == base.metrics.tasks
    assert np.array_equal(res.waste_per_chip, base.waste_per_chip)


# ---------------------------------------------------------------------------
# stream: theta-batch requests, retirement, kill-and-resume
# ---------------------------------------------------------------------------

SKW = dict(slots=4, chunk=1 << 9, capacity=1 << 16, lanes=256,
           roots_per_lane=2, refill_slots=2, seg_iters=2048,
           min_active_frac=0.05)


def test_stream_theta_batch_requests_retire_with_areas():
    from ppls_tpu.runtime.stream import StreamEngine
    eng = StreamEngine("sin_scaled", EPS, theta_block=T, **SKW)
    # a SHORT batch (padded by replication, pads discarded at emit),
    # a full batch, and a scalar request on the same engine
    r0 = eng.submit([1.0, 2.0, 3.0], B)
    r1 = eng.submit(list(np.linspace(1.0, 4.0, T)), B)
    r2 = eng.submit(1.5, B)
    done = {c.rid: c for c in eng.drain()}
    assert set(done) == {r0, r1, r2}
    assert len(done[r0].areas) == 3
    assert len(done[r1].areas) == T
    assert len(done[r2].areas) == 1
    for c in done.values():
        ths = np.asarray(c.theta if isinstance(c.theta, tuple)
                         else [c.theta])
        assert np.all(np.abs(np.asarray(c.areas) - _exact(ths))
                      <= 60 * EPS)      # solo-error-scale bound
        assert c.area == c.areas[0]
    res = eng.result()
    occ = res.occupancy_summary(SKW["lanes"])
    assert occ["attribution"]["reconciles"]
    with pytest.raises(ValueError, match="exceeds"):
        eng.submit(list(np.linspace(1.0, 2.0, T + 1)), B)


def test_stream_theta_kill_and_resume_bit_identical(tmp_path):
    from ppls_tpu.runtime.stream import StreamEngine
    reqs = [(tuple(np.linspace(1.0 + 0.1 * i, 2.0 + 0.1 * i, T)), B)
            for i in range(4)]
    arr = [0, 0, 1, 2]
    skw = dict(SKW, theta_block=T)
    base = StreamEngine("sin_scaled", EPS, **skw).run(
        reqs, arrival_phase=arr)
    assert int(base.totals.get("theta_overwalk", 0)) >= 0
    path = str(tmp_path / "st.ckpt")
    eng = StreamEngine("sin_scaled", EPS, checkpoint_path=path,
                       checkpoint_every=1, **skw)
    with pytest.raises(RuntimeError, match="simulated crash"):
        eng.run(reqs, arrival_phase=arr, _crash_after_phases=2)
    eng2 = StreamEngine.resume(path, "sin_scaled", EPS,
                               checkpoint_every=1, **skw)
    k = eng2.next_rid
    while not eng2.idle or k < len(reqs):
        while k < len(reqs) and arr[k] <= eng2.phase:
            eng2.submit(*reqs[k])
            k += 1
        eng2.step()
    res = eng2.result()
    assert np.array_equal(res.areas, base.areas)          # bit-for-bit
    base_areas = {c.rid: c.areas for c in base.completed}
    for c in res.completed:
        assert c.areas == base_areas[c.rid]               # per theta
    assert res.totals == base.totals
    # theta_block is stream identity: a scalar engine must refuse
    eng3 = StreamEngine("sin_scaled", EPS, checkpoint_path=path,
                        checkpoint_every=1, **skw)
    with pytest.raises(RuntimeError, match="simulated crash"):
        eng3.run(reqs, arrival_phase=arr, _crash_after_phases=1)
    with pytest.raises(ValueError, match="different run"):
        StreamEngine.resume(path, "sin_scaled", EPS,
                            checkpoint_every=1, **SKW)


# ---------------------------------------------------------------------------
# satellites: vectorized family_exact, CLI --theta forms
# ---------------------------------------------------------------------------


def test_family_exact_vectorized_matches_mpmath():
    th = np.linspace(1.0, 4.0, 16)
    for name, a, b in (("sin_scaled", 0.0, 1.0),
                       ("cosh4_scaled", 0.0, 2.0)):
        loop = family_exact(name, a, b, th, prefer_vec=False)
        vec = family_exact(name, a, b, th, prefer_vec=True)
        assert isinstance(vec, np.ndarray)
        assert np.max(np.abs((vec - loop)
                             / np.maximum(np.abs(loop), 1e-300))) \
            < 1e-12
    # the big-batch path defaults to the vectorized form and keeps
    # shape; 2048 thetas must not be a hot mpmath loop
    big = np.linspace(1.0, 4.0, 2048).reshape(8, 256)
    v = family_exact("sin_scaled", 0.0, 1.0, big)
    assert v.shape == (8, 256)
    assert "sin_scaled" in FAMILY_EXACT_VEC


def test_cli_theta_arg_forms(tmp_path):
    from ppls_tpu.__main__ import theta_batch_arg
    assert theta_batch_arg("1.5") == 1.5                  # scalar
    assert theta_batch_arg("1,2.5,3") == [1.0, 2.5, 3.0]  # comma list
    p = tmp_path / "t.json"
    p.write_text(json.dumps([[1.0, 2.0], [3.0, 4.0]]))
    assert theta_batch_arg("@" + str(p)) == [[1.0, 2.0], [3.0, 4.0]]
    p2 = tmp_path / "t2.json"
    p2.write_text("2.25")
    assert theta_batch_arg("@" + str(p2)) == 2.25


def test_cli_scalar_backcompat_parse():
    # the scalar path must be untouched: no --theta builds the same
    # linspace family run arguments as before round 13
    from ppls_tpu.__main__ import build_parser
    args = build_parser().parse_args(
        ["family", "--engine", "walker", "--m", "4"])
    assert args.theta is None and args.theta_block == 1
    args2 = build_parser().parse_args(
        ["family", "--engine", "walker", "--theta", "1,2",
         "--theta-block", "2"])
    assert args2.theta == [1.0, 2.0] and args2.theta_block == 2


def test_dd_stream_theta_snapshot_resume_state_roundtrip(tmp_path):
    # regression (round-13 review): _restore_device_dd must rebuild
    # the (n_dev, slots * T) accumulator — the scalar reshape crashed
    # every theta-batched dd-stream resume. State-only roundtrip: the
    # store builds and snapshots WITHOUT running a phase (no shard
    # compile), which is exactly the path the reshape sits on.
    from ppls_tpu.runtime.stream import StreamEngine
    kw = dict(SKW, theta_block=T, engine="walker-dd", n_devices=8)
    eng = StreamEngine("sin_scaled", EPS,
                       checkpoint_path=str(tmp_path / "ddst.ckpt"),
                       **kw)
    eng.submit([1.0, 2.0], B)
    eng._ensure_state(eng._pending[0])      # build stores, no phase
    eng._theta_table[1] = 7.0
    eng.snapshot()
    eng2 = StreamEngine.resume(str(tmp_path / "ddst.ckpt"),
                               "sin_scaled", EPS, **kw)
    assert eng2._dd_state[5].shape == (8, kw["slots"] * T)
    assert np.array_equal(eng2._theta_table, eng._theta_table)
    assert eng2.pending == 1
