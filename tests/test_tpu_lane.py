"""Real-TPU regression lane (``@pytest.mark.tpu``).

Every test here targets a behavior that differs between real f64 (the
forced-CPU default lane) and the TPU's emulated f64 (an f32 pair: ~49-bit
mantissa, f32 exponent range). Both round-2 bugs lived exactly in that
gap — reintroducing either must fail this lane:

* ``exact_segment_sum``'s old 1e-300 zero-guard flushed to 0.0 on device
  (f32 exponent range), so an all-zero leaf vector produced
  log2(0) -> NaN and poisoned every m>256 family run (VERDICT r2 Weak #1).
* The bench gate then *passed* on the NaN output (Weak #2) — the engine
  now raises on non-finite areas, asserted here on device.

Run: ``PPLS_TEST_PLATFORM=tpu python -m pytest tests/ -m tpu -q``

SMOKE SUBSET (VERDICT r5 Weak #4 — the full lane hit 14m49s and keeps
growing): ``PPLS_TEST_PLATFORM=tpu python -m pytest tests/ -m "tpu and
smoke" -q`` runs a <=5-minute core — the golden reference area, the
segment-sum edge cases behind both round-2 device-only bugs, and one
walker parity — for time-pressured rounds; conftest.py records every
TPU-lane session's wall time in TPU_LANE_TIMES.json so lane growth is
visible round-over-round either way.
"""

import math

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from ppls_tpu.ops.reduction import exact_segment_sum

pytestmark = pytest.mark.tpu


def _segsum(fam, leaf, m, n):
    return jax.jit(exact_segment_sum, static_argnums=(2, 3))(fam, leaf, m, n)


def test_f64_emulation_exponent_range_assumption():
    # Documents the platform fact the clamp in exact_segment_sum relies on:
    # 2^-40 must survive on device. (On real f64 hardware this is trivially
    # true; on TPU double-f32 emulation it holds while 1e-300 does not.)
    assert float(jax.device_put(jnp.exp2(jnp.float64(-40.0)))) > 0.0


@pytest.mark.smoke
def test_segment_sum_all_zero_leaf_is_zero_not_nan():
    # The exact round-2 failure mode: every popped task splits, leaf
    # vector all-zero -> old code: scale=0 -> 0/0=NaN forever.
    fam = jnp.zeros(1024, dtype=jnp.int32)
    leaf = jnp.zeros(1024, dtype=jnp.float64)
    out = np.asarray(_segsum(fam, leaf, 300, 1024))
    np.testing.assert_array_equal(out, 0.0)


@pytest.mark.smoke
def test_segment_sum_wide_dynamic_range_vs_fsum():
    rng = np.random.default_rng(0)
    n, m = 1 << 13, 512
    fam_h = rng.integers(0, m, n).astype(np.int32)
    vals = rng.standard_normal(n) * np.exp2(
        rng.integers(-60, 10, n).astype(np.float64))
    out = np.asarray(_segsum(jnp.asarray(fam_h), jnp.asarray(vals), m, n))
    ref = np.array([math.fsum(vals[fam_h == j]) for j in range(m)])
    assert np.all(np.isfinite(out))
    # Bound: double-f32 input representation error (~2^-49 relative on the
    # largest leaves), not reduction drift.
    amax = np.max(np.abs(vals))
    assert np.max(np.abs(out - ref)) < amax * 2.0 ** -45


def test_segment_sum_tiny_amax_below_clamp():
    # Leaves entirely below the 2^-40 clamp must come back finite (may be
    # flushed toward zero — absolute error far below any gate).
    rng = np.random.default_rng(1)
    n, m = 1024, 300
    fam = jnp.asarray(rng.integers(0, m, n), dtype=jnp.int32)
    leaf = jnp.asarray(rng.standard_normal(n) * np.exp2(-80.0))
    out = np.asarray(_segsum(fam, leaf, m, n))
    assert np.all(np.isfinite(out))
    assert np.max(np.abs(out)) < 2.0 ** -40


def test_family_engine_m_gt_256_finite_on_device():
    # integrate_family with m>256 takes the exact_segment_sum path; at the
    # start of a deep run every lane splits (all-zero leaf chunk) — the
    # round-2 NaN trigger. Also exercises the engine's own finiteness raise.
    from ppls_tpu.models.integrands import get_family
    from ppls_tpu.parallel.bag_engine import integrate_family

    f = get_family("sin_recip_scaled")
    theta = 1.0 + np.arange(300) / 300
    res = integrate_family(f, theta, (1e-4, 1.0), 1e-4,
                           chunk=1 << 12, capacity=1 << 19)
    assert np.all(np.isfinite(res.areas))
    # Thetas span [1, 2); the integral falls from ~0.503 (theta=1) to
    # ~0.068 (theta->2) — values cross-checked against the forced-CPU
    # real-f64 engine (identical at printed precision).
    assert np.all((res.areas > 0.05) & (res.areas < 0.9))


@pytest.mark.smoke
def test_device_engine_golden_area_on_device():
    # Reference golden config (aquadPartA.c:32) end-to-end on the real TPU.
    from ppls_tpu.config import QuadConfig
    from ppls_tpu.parallel.device_engine import device_integrate

    cfg = QuadConfig(integrand="cosh4", a=0.0, b=5.0, eps=1e-3,
                     capacity=4096, max_rounds=64)
    res = device_integrate(cfg)
    assert abs(res.area - 7583461.801486) < 1e-5
    assert res.metrics.tasks == 6567


@pytest.mark.smoke
def test_walker_parity_on_device():
    # The Pallas walker (real Mosaic codegen, not interpret mode) at the
    # bench's operating tolerance. The walker's ds split test diverges
    # from f64 only where the error estimate lands within ds noise of
    # eps; at eps=1e-10 the crossing happens far below the noise floor,
    # so decisions (and areas) agree essentially exactly (measured
    # |w-b| ~ 1e-14, zero task drift). At looser eps (1e-7..1e-8 on
    # deep-oscillatory domains) borderline flips contribute O(flips*eps)
    # area divergence with UNCHANGED quality vs the exact integral —
    # that regime is covered by tests/test_walker.py's contract, not
    # re-tested here.
    from ppls_tpu.models.integrands import get_family, get_family_ds
    from ppls_tpu.parallel.bag_engine import integrate_family
    from ppls_tpu.parallel.walker import integrate_family_walker

    f = get_family("sin_recip_scaled")
    fds = get_family_ds("sin_recip_scaled")
    theta = 1.0 + np.arange(8) / 8.0
    eps = 1e-10
    w = integrate_family_walker(f, fds, theta, (1e-3, 1.0), eps,
                                capacity=1 << 21, lanes=1 << 12,
                                roots_per_lane=4, seg_iters=32,
                                min_active_frac=0.05)
    b = integrate_family(f, theta, (1e-3, 1.0), eps,
                         chunk=1 << 13, capacity=1 << 21)
    assert np.all(np.isfinite(w.areas))
    assert np.max(np.abs(w.areas - b.areas)) < 1e-9
    assert abs(w.metrics.tasks - b.metrics.tasks) / b.metrics.tasks < 1e-4
    assert w.walker_fraction > 0.5, w.walker_fraction


def test_walker_flagship_operating_point():
    # The bench's EXACT operating point (VERDICT r3 #5): a=1e-4,
    # eps=1e-10, default engine parameters (lanes=2^14, early-exit
    # segments, suspend/re-breed tails, in-kernel INIT endpoint evals)
    # — where ds_div/ds_sin arguments reach theta/1e-4 ~ 2e4 and the
    # reduction depth is 10x the shallower parity test above. A scaled
    # family slice (m=32 of the bench's 1024) keeps the runtime in
    # test range; everything else matches bench.py. The round-2 bug
    # classes (ds range/exponent underflow) and the round-4 seeding
    # miscompile (roots silently dropped -> area loss ~1e-5 and task
    # drift) all fail these assertions.
    from ppls_tpu.models.integrands import get_family, get_family_ds
    from ppls_tpu.parallel.bag_engine import integrate_family
    from ppls_tpu.parallel.walker import integrate_family_walker

    f = get_family("sin_recip_scaled")
    fds = get_family_ds("sin_recip_scaled")
    m = 32
    theta = 1.0 + np.arange(m) / m
    eps = 1e-10
    w = integrate_family_walker(f, fds, theta, (1e-4, 1.0), eps,
                                capacity=1 << 22)
    b = integrate_family(f, theta, (1e-4, 1.0), eps,
                         chunk=1 << 15, capacity=1 << 22)
    assert np.all(np.isfinite(w.areas))
    assert np.max(np.abs(w.areas - b.areas)) < 1e-9          # parity
    drift = abs(w.metrics.tasks - b.metrics.tasks) / b.metrics.tasks
    assert drift < 1e-4, (w.metrics.tasks, b.metrics.tasks)
    # engine-health floor: at m=32 the breed share is larger than the
    # bench's m=1024 (walker fraction 0.74 vs 0.99 measured) — the
    # assertion guards collapse, not the bench's exact share
    assert w.walker_fraction > 0.6, w.walker_fraction
    assert 0.2 < w.lane_efficiency <= 2.0 / 3.0 + 1e-6, w.lane_efficiency


def test_walker_kernel_refill_flagship_point_on_device():
    # The round-6 flagship config: IN-KERNEL refill through real Mosaic
    # codegen (the private VMEM root bank, the in-kernel lax.cond refill
    # event, the per-slot result bank) at the bench operating point's
    # scaled slice. Catches any Mosaic lowering gap interpret mode
    # cannot see — exactly the class of failure bench.py's
    # refill_fallback guards the artifact against.
    from ppls_tpu.models.integrands import get_family, get_family_ds
    from ppls_tpu.parallel.bag_engine import integrate_family
    from ppls_tpu.parallel.walker import integrate_family_walker

    f = get_family("sin_recip_scaled")
    fds = get_family_ds("sin_recip_scaled")
    m = 32
    theta = 1.0 + np.arange(m) / m
    eps = 1e-10
    w = integrate_family_walker(f, fds, theta, (1e-4, 1.0), eps,
                                capacity=1 << 22, refill_slots=8)
    b = integrate_family(f, theta, (1e-4, 1.0), eps,
                         chunk=1 << 15, capacity=1 << 22)
    assert np.all(np.isfinite(w.areas))
    assert np.max(np.abs(w.areas - b.areas)) < 1e-9          # parity
    drift = abs(w.metrics.tasks - b.metrics.tasks) / b.metrics.tasks
    assert drift < 1e-4, (w.metrics.tasks, b.metrics.tasks)
    assert w.walker_fraction > 0.6, w.walker_fraction
    assert w.kernel_steps > 0


def test_walker_gauss_family_on_device():
    # ds_exp inside real Mosaic codegen (exact pow2 scaling + fence-free
    # transforms), on the clustered-refinement Gaussian family.
    from ppls_tpu.models.integrands import get_family, get_family_ds
    from ppls_tpu.parallel.bag_engine import integrate_family
    from ppls_tpu.parallel.walker import integrate_family_walker

    f = get_family("gauss_center")
    fds = get_family_ds("gauss_center")
    theta = np.array([0.4995, 0.5, 0.5005])
    eps = 1e-9
    w = integrate_family_walker(f, fds, theta, (0.4, 0.6), eps,
                                capacity=1 << 16, lanes=256,
                                roots_per_lane=1, seg_iters=32,
                                min_active_frac=0.05)
    b = integrate_family(f, theta, (0.4, 0.6), eps,
                         chunk=1 << 10, capacity=1 << 16)
    assert np.all(b.areas > 1e-3)
    assert np.max(np.abs(w.areas - b.areas)) < 3e-9


def test_walker_simpson_parity_on_device():
    # Simpson+Richardson in the real Mosaic kernel (VERDICT r3 #4): ds
    # split decisions match f64 exactly at this operating point, and
    # the DS-constant 1/6, 1/12, 1/15 scalings keep values at the ds
    # noise floor (an f32 literal constant costs a SYSTEMATIC 3e-8
    # relative on every accepted value — caught by this test).
    from ppls_tpu.config import Rule
    from ppls_tpu.models.integrands import get_family, get_family_ds
    from ppls_tpu.parallel.bag_engine import integrate_family
    from ppls_tpu.parallel.walker import integrate_family_walker

    f = get_family("sin_recip_scaled")
    fds = get_family_ds("sin_recip_scaled")
    theta = 1.0 + np.arange(4) / 4.0
    eps = 1e-12
    w = integrate_family_walker(f, fds, theta, (1e-2, 1.0), eps,
                                rule=Rule.SIMPSON, capacity=1 << 16,
                                lanes=256, roots_per_lane=1,
                                seg_iters=32, min_active_frac=0.05)
    b = integrate_family(f, theta, (1e-2, 1.0), eps, rule=Rule.SIMPSON,
                         chunk=1 << 10, capacity=1 << 16)
    assert np.max(np.abs(w.areas - b.areas)) < 1e-12
    assert w.metrics.tasks == b.metrics.tasks
