"""utils/tracing.trace(): the jax.profiler wrapper (satellite of the
round-10 telemetry tentpole — previously untested).

Covers the no-op path (None dir must not touch the profiler), the
directory-creation contract (a --trace run must not die on a missing
capture dir), the CLI ``--trace`` plumb, and one real capture on the
CPU backend.
"""

import os

import pytest

from ppls_tpu.utils import tracing


class _Recorder:
    """Stand-in for jax.profiler.trace: records entry/exit."""

    def __init__(self):
        self.dirs = []
        self.active = 0

    def __call__(self, trace_dir):
        rec = self

        class _Cm:
            def __enter__(self):
                rec.dirs.append(trace_dir)
                rec.active += 1

            def __exit__(self, *a):
                rec.active -= 1

        return _Cm()


@pytest.fixture
def profiler_recorder(monkeypatch):
    import jax
    rec = _Recorder()
    monkeypatch.setattr(jax.profiler, "trace", rec)
    return rec


def test_trace_none_is_noop(profiler_recorder):
    ran = False
    with tracing.trace(None):
        ran = True
    with tracing.trace(""):
        pass
    assert ran
    assert profiler_recorder.dirs == []     # profiler never touched


def test_trace_creates_directory_and_wraps(tmp_path,
                                           profiler_recorder):
    d = str(tmp_path / "deep" / "trace-out")
    assert not os.path.isdir(d)
    with tracing.trace(d):
        # the capture dir exists by the time the body runs, and the
        # profiler context is active around it
        assert os.path.isdir(d)
        assert profiler_recorder.active == 1
    assert profiler_recorder.dirs == [d]
    assert profiler_recorder.active == 0
    # idempotent on an existing dir
    with tracing.trace(d):
        pass
    assert profiler_recorder.dirs == [d, d]


def test_cli_trace_plumb(tmp_path, capsys, profiler_recorder):
    """``--trace DIR`` wraps the WHOLE dispatched run (all modes go
    through main's single trace() context)."""
    from ppls_tpu.__main__ import main
    d = str(tmp_path / "cli-trace")
    rc = main(["--trace", d, "--engine", "host", "--eps", "1e-1",
               "--max-rounds", "64"])
    assert rc == 0
    assert profiler_recorder.dirs == [d]
    assert os.path.isdir(d)
    assert "Area=" in capsys.readouterr().out


def test_trace_real_capture_smoke(tmp_path):
    """One real jax.profiler capture on the CPU backend: the wrapper
    must hand usable artifacts to TensorBoard/Perfetto, not just an
    empty dir."""
    import jax
    import jax.numpy as jnp

    d = str(tmp_path / "real")
    with tracing.trace(d):
        jax.device_get(jnp.arange(8.0) * 2.0)
    # the profiler writes under <dir>/plugins/profile/<ts>/...
    found = []
    for root, _dirs, files in os.walk(d):
        found.extend(files)
    assert found, f"profiler left no artifacts under {d}"


def test_annotate_returns_context_manager():
    with tracing.annotate("test-span"):
        pass
