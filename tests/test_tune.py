"""Closed-loop autotuning (runtime/tune.py, round 20).

Acceptance surface of the autotuning tentpole:

* the dominant-bucket -> knob map is ONE definition: per-bucket
  recommendation fixtures here, and the analyze_occupancy printer test
  (test_attribution.py) asserts the same line reaches the CLI;
* TABLE DETERMINISM: the same (seed, signature, measurements) yields a
  byte-identical table entry — the committed table is reproducible;
* nearest-signature resolution is a total, testable order: hard
  constraints (device/rule/mode/mesh/theta band) are never crossed,
  family match outranks eps proximity, ties break lexicographically;
* the cadence resolution tiers (explicit > exact > nearest > hand
  default) with loud degradation on insane table data;
* online adaptation is deterministic and snapshot-safe: hysteresis +
  one-step clamps at the unit level, and a killed-and-resumed adapting
  stream replays BIT-IDENTICALLY (areas and adapter state);
* compile-once holds OUTSIDE tune trials: a served engine with the
  tuned table loaded pins ppls_recompiles_total at 0.
"""

import json

import numpy as np
import pytest

from ppls_tpu.runtime import tune
from ppls_tpu.runtime.tune import (BUCKET_KNOB_MAP, CADENCE_SAFE_BANDS,
                                   OnlineAdapter, clear_table_cache,
                                   hand_cadence_defaults, nearest_entry,
                                   pareto_improves, recommend_knob,
                                   resolve_cadence_tuned, signature_key,
                                   tune_workload, update_table,
                                   workload_signature, write_table)

# ---------------------------------------------------------------------------
# the shared bucket -> knob map (satellite 2's fixture half)
# ---------------------------------------------------------------------------


def _attr(dom):
    return {"dominant_waste": dom, "lane_cycles": 1000,
            "reconciles": True}


@pytest.mark.parametrize("bucket,first_knob", [
    ("refill_stall", "refill_slots"),
    ("masked_dead", "exit_frac"),
    ("theta_overwalk", "theta_block"),
    ("drain_tail", "roots_per_lane"),
])
def test_recommend_knob_per_bucket(bucket, first_knob):
    rec = recommend_knob(_attr(bucket))
    assert rec is not None
    assert rec["bucket"] == bucket
    assert rec["knobs"] == list(BUCKET_KNOB_MAP[bucket])
    assert rec["knobs"][0] == first_knob
    assert rec["hint"]


def test_recommend_knob_nothing_to_attack():
    # fully eval-active (or missing attribution): no recommendation
    assert recommend_knob(_attr("eval_active")) is None
    assert recommend_knob(None) is None
    assert recommend_knob({}) is None


# ---------------------------------------------------------------------------
# signatures + resolution tiers
# ---------------------------------------------------------------------------

SIG = workload_signature("sin_recip_scaled", 1e-7, "trapezoid",
                         scout=True, refill_slots=4)


def test_signature_key_shape():
    assert SIG == {"family": "sin_recip_scaled", "eps_band": -7,
                   "rule": "trapezoid", "theta_band": 1,
                   "mesh_shape": 1, "mode": "scout-ikr"}
    key = signature_key(SIG, "cpu")
    assert key == ("family=sin_recip_scaled|eps_band=-7|rule=trapezoid"
                   "|theta_band=1|mesh_shape=1|mode=scout-ikr"
                   "|device=cpu")


def _entry(sig, device="cpu", exit_frac=0.90, suspend_frac=0.65):
    return {"schema": tune.ENTRY_SCHEMA, "signature": sig,
            "device_kind": device,
            "knobs": {"exit_frac": exit_frac,
                      "suspend_frac": suspend_frac},
            "baseline": {"tasks": 10, "kernel_steps": 10,
                         "lane_efficiency": 0.5},
            "tuned": {"tasks": 10, "kernel_steps": 8,
                      "lane_efficiency": 0.6},
            "provenance": {"trials": 2, "recompiles": 1,
                           "reconciles": True, "seed": 0, "budget": 2,
                           "improved": True, "eps": 1e-7,
                           "bounds": [0.0, 1.0], "sizing": {},
                           "path": [{"moved": None, "accepted": True,
                                     "kernel_steps": 8,
                                     "lane_efficiency": 0.6}]}}


def _table(*entries):
    t = None
    for e in entries:
        t = update_table(t, e)
    return t


def _sig(family="sin_recip_scaled", eps=1e-7, rule="trapezoid",
         theta_block=1, mesh_shape=1, scout=True, refill_slots=4):
    return workload_signature(family, eps, rule, theta_block,
                              mesh_shape, scout=scout,
                              refill_slots=refill_slots)


def test_nearest_entry_hard_constraints_never_cross():
    # same family, wrong mode / mesh / theta band / rule / device:
    # NEVER eligible, whatever the score would be
    others = [
        _sig(scout=False),                      # mode f64-ikr
        _sig(refill_slots=0),                   # mode scout-xla
        _sig(mesh_shape=8),
        _sig(theta_block=64),
        _sig(rule="simpson"),
    ]
    entries = _table(*[_entry(s) for s in others])["entries"]
    assert nearest_entry(entries, _sig(), "cpu") is None
    ent = _table(_entry(_sig()))["entries"]
    assert nearest_entry(ent, _sig(), "tpu-v5e") is None


def test_nearest_entry_family_beats_eps_proximity():
    same_fam_far = _entry(_sig(eps=1e-9))       # family match, d=2
    other_fam_close = _entry(_sig(family="sin_scaled"))  # d=0, no fam
    entries = _table(same_fam_far, other_fam_close)["entries"]
    key, ent = nearest_entry(entries, _sig(), "cpu")
    assert ent["signature"]["family"] == "sin_recip_scaled"
    # among same-family candidates, smaller eps distance wins
    closer = _entry(_sig(eps=1e-8))
    entries = _table(same_fam_far, closer)["entries"]
    key, ent = nearest_entry(entries, _sig(), "cpu")
    assert ent["signature"]["eps_band"] == -8


def test_nearest_entry_score_floor_and_tie_break():
    # nothing in common (different family, eps 4+ bands away): score 0
    # falls through to the hand tier
    far = _entry(_sig(family="sin_scaled", eps=1e-12))
    assert nearest_entry(_table(far)["entries"], _sig(), "cpu") is None
    # exact (score, distance) tie: lexicographically smaller key wins
    a = _entry(_sig(family="cosh4_scaled"))
    b = _entry(_sig(family="sin_scaled"))
    entries = _table(a, b)["entries"]
    key, ent = nearest_entry(entries, _sig(family="quad_scaled"),
                             "cpu")
    assert ent["signature"]["family"] == "cosh4_scaled"
    assert key == min(entries)


@pytest.fixture
def table_env(tmp_path, monkeypatch):
    """Point PPLS_TUNING_TABLE at a writable temp table."""
    path = str(tmp_path / "table.json")
    monkeypatch.setenv("PPLS_TUNING_TABLE", path)
    clear_table_cache()
    yield path
    clear_table_cache()


def test_resolve_cadence_tiers(table_env):
    de, ds = hand_cadence_defaults(True, 4)
    # no table on disk: hand default
    assert resolve_cadence_tuned(None, None, True, 4,
                                 signature=_sig()) == (de, ds,
                                                       "default")
    # explicit values always win, table or not
    assert resolve_cadence_tuned(0.77, 0.55, True, 4,
                                 signature=_sig()) \
        == (0.77, 0.55, "explicit")
    write_table(table_env, _table(_entry(_sig(), tune.device_kind())))
    e, s, tier = resolve_cadence_tuned(None, None, True, 4,
                                       signature=_sig())
    assert (e, s, tier) == (0.90, 0.65, "exact")
    # eps one band off: the nearest tier serves the same values
    e, s, tier = resolve_cadence_tuned(None, None, True, 4,
                                       signature=_sig(eps=1e-8))
    assert (e, s, tier) == (0.90, 0.65, "nearest")
    # the resolution is recorded for the gauge/bench record
    last = tune.last_resolution()
    assert last["tier"] == "nearest"
    assert last["exit_frac"] == 0.90


def test_resolve_cadence_insane_table_degrades_loudly(table_env):
    de, ds = hand_cadence_defaults(True, 4)
    lo, hi = CADENCE_SAFE_BANDS["exit_frac"]
    write_table(table_env, _table(
        _entry(_sig(), tune.device_kind(), exit_frac=hi + 0.5)))
    e, s, tier = resolve_cadence_tuned(None, None, True, 4,
                                       signature=_sig())
    assert (e, s, tier) == (de, ds, "default")
    # suspend >= exit is equally insane
    write_table(table_env, _table(
        _entry(_sig(), tune.device_kind(), exit_frac=0.8,
               suspend_frac=0.8)))
    clear_table_cache()
    assert resolve_cadence_tuned(None, None, True, 4,
                                 signature=_sig())[2] == "default"


def test_table_env_off_disables(table_env, monkeypatch):
    write_table(table_env, _table(_entry(_sig(), tune.device_kind())))
    monkeypatch.setenv("PPLS_TUNING_TABLE", "off")
    clear_table_cache()
    de, ds = hand_cadence_defaults(True, 4)
    assert resolve_cadence_tuned(None, None, True, 4,
                                 signature=_sig()) == (de, ds,
                                                       "default")


# ---------------------------------------------------------------------------
# sweep determinism (satellite 3a)
# ---------------------------------------------------------------------------


def _stub_measure():
    """Deterministic fake trial runner: masked_dead dominates until
    exit_frac tightens to 0.98, then nothing improves further."""
    def measure(knobs):
        good = knobs["exit_frac"] >= 0.98
        return {"tasks": 100, "cycles": 50,
                "kernel_steps": 40 if good else 50,
                "lane_efficiency": 0.8 if good else 0.6,
                "dominant_waste": ("drain_tail" if good
                                   else "masked_dead"),
                "reconciles": True, "recompiles": 1}
    return measure


def test_tune_workload_byte_identical_rerun():
    kw = dict(budget=6, seed=3, measure=_stub_measure(), device="cpu")
    e1 = tune_workload("sin_recip_scaled", 1e-7, (1e-2, 1.0), **kw)
    e2 = tune_workload("sin_recip_scaled", 1e-7, (1e-2, 1.0), **kw)
    assert json.dumps(e1, sort_keys=True) == json.dumps(e2,
                                                        sort_keys=True)
    # the sweep found the stubbed optimum, via the bucket's own knob
    assert e1["knobs"]["exit_frac"] == 0.98
    assert e1["provenance"]["improved"] is True
    assert e1["provenance"]["trials"] == 6
    assert e1["provenance"]["recompiles"] == 6
    moved = [t["moved"]["knob"] for t in e1["provenance"]["path"]]
    # masked_dead dominated the baseline: cadence knobs tried first
    assert moved[0] in BUCKET_KNOB_MAP["masked_dead"]
    # provenance records which bucket picked each move
    assert e1["provenance"]["path"][0]["moved"]["bucket"] \
        == "masked_dead"


def test_tune_workload_no_improvement_keeps_baseline():
    def flat(knobs):
        return {"tasks": 100, "cycles": 50, "kernel_steps": 50,
                "lane_efficiency": 0.6, "dominant_waste": "drain_tail",
                "reconciles": True, "recompiles": 1}
    e = tune_workload("sin_recip_scaled", 1e-7, (1e-2, 1.0),
                      budget=4, measure=flat, device="cpu")
    assert e["provenance"]["improved"] is False
    assert e["knobs"]["exit_frac"] \
        == hand_cadence_defaults(True, 4)[0]
    assert e["tuned"] == e["baseline"]


def test_pareto_contract():
    base = {"lane_efficiency": 0.6, "kernel_steps": 50,
            "reconciles": True}
    better = dict(base, lane_efficiency=0.7)
    assert pareto_improves(better, base)
    # reconciliation is mandatory
    assert not pareto_improves(dict(better, reconciles=False), base)
    # a trade (faster but less efficient) is NOT an improvement
    assert not pareto_improves(
        dict(base, lane_efficiency=0.5, kernel_steps=40), base)
    # equality on both axes is not an improvement either
    assert not pareto_improves(dict(base), base)


# ---------------------------------------------------------------------------
# online adaptation units
# ---------------------------------------------------------------------------


def test_online_adapter_hysteresis_and_clamps():
    a = OnlineAdapter({"admit_budget": 4},
                      {"admit_budget": (1, 8)})
    # one phase of pressure: hysteresis holds the value
    assert a.observe({"admit_budget": 1}) == []
    assert a.values["admit_budget"] == 4
    # second consecutive phase: one step, streak resets
    assert a.observe({"admit_budget": 1}) \
        == [{"knob": "admit_budget", "from": 4, "to": 5}]
    # direction flip resets the streak
    assert a.observe({"admit_budget": -1}) == []
    assert a.observe({"admit_budget": 1}) == []
    assert a.observe({"admit_budget": 1})[0]["to"] == 6
    # band clamp: never leaves [1, 8] however long the pressure
    for _ in range(20):
        a.observe({"admit_budget": 1})
    assert a.values["admit_budget"] == 8
    for _ in range(40):
        a.observe({"admit_budget": -1})
    assert a.values["admit_budget"] == 1


def test_online_adapter_state_roundtrip_and_band_check():
    a = OnlineAdapter({"admit_budget": 4}, {"admit_budget": (1, 8)})
    a.observe({"admit_budget": 1})
    st = a.state()
    b = OnlineAdapter({"admit_budget": 4}, {"admit_budget": (1, 8)})
    b.restore(st)
    assert b.state() == st
    with pytest.raises(ValueError, match="safe band"):
        b.restore({"values": {"admit_budget": 99}})
    with pytest.raises(ValueError, match="safe band"):
        OnlineAdapter({"admit_budget": 16}, {"admit_budget": (1, 8)})


# ---------------------------------------------------------------------------
# the adapting stream: determinism + kill-and-resume (satellite 3c)
# ---------------------------------------------------------------------------

_STREAM_KW = dict(slots=2, chunk=1 << 10, capacity=1 << 16, lanes=256,
                  roots_per_lane=2, refill_slots=2, seg_iters=32,
                  min_active_frac=0.05, adapt=True)
_EPS = 1e-7
_REQS = [(float(t), (1e-2, 1.0))
         for t in 1.0 + np.arange(8) / 8.0]


def _drive(eng, reqs, arr, k=0, hist=None):
    while not eng.idle or k < len(reqs):
        while k < len(reqs) and arr[k] <= eng.phase:
            eng.submit(*reqs[k])
            k += 1
        eng.step()
        if hist is not None and eng._adapt is not None:
            hist.append(dict(eng._adapt.values))
    return eng.result()


def test_stream_adaptation_fires_and_is_deterministic():
    from ppls_tpu.runtime.stream import StreamEngine
    arr = [0] * len(_REQS)            # burst: sustained backlog
    e1 = StreamEngine("sin_recip_scaled", _EPS, **_STREAM_KW)
    h1 = []
    r1 = _drive(e1, _REQS, arr, hist=h1)
    assert len(r1.completed) == len(_REQS)
    # the sustained backlog actually moved a knob at some boundary
    assert e1._adapt is not None
    assert any(h != h1[0] for h in h1), h1
    # re-run: identical trajectory (pure function of the schedule)
    e2 = StreamEngine("sin_recip_scaled", _EPS, **_STREAM_KW)
    h2 = []
    r2 = _drive(e2, _REQS, arr, hist=h2)
    assert np.array_equal(r1.areas, r2.areas)
    assert h1 == h2
    assert e1._adapt.state() == e2._adapt.state()


def test_stream_adapt_kill_and_resume_bit_identity(tmp_path):
    from ppls_tpu.runtime.stream import StreamEngine
    arr = [0, 0, 0, 0, 1, 2, 3, 5]
    base_eng = StreamEngine("sin_recip_scaled", _EPS, **_STREAM_KW)
    base = _drive(base_eng, _REQS, arr)
    path = str(tmp_path / "adapt.ckpt")
    eng = StreamEngine("sin_recip_scaled", _EPS, checkpoint_path=path,
                       checkpoint_every=1, **_STREAM_KW)
    with pytest.raises(RuntimeError, match="simulated crash"):
        eng.run(_REQS, arrival_phase=arr, _crash_after_phases=3)
    # the kill landed mid-adaptation: the snapshot carries live state
    eng2 = StreamEngine.resume(path, "sin_recip_scaled", _EPS,
                               checkpoint_every=1, **_STREAM_KW)
    assert eng2.phase == 3
    assert eng2._adapt.state() == eng._adapt.state()
    res = _drive(eng2, _REQS, arr, k=eng2.next_rid)
    assert np.array_equal(res.areas, base.areas)       # bit-for-bit
    assert res.phases == base.phases
    assert eng2._adapt.state() == base_eng._adapt.state()


def test_stream_adapt_resume_requires_armed_adapter(tmp_path):
    from ppls_tpu.runtime.stream import StreamEngine
    path = str(tmp_path / "adapt2.ckpt")
    eng = StreamEngine("sin_recip_scaled", _EPS, checkpoint_path=path,
                       checkpoint_every=1, **_STREAM_KW)
    with pytest.raises(RuntimeError, match="simulated crash"):
        eng.run(_REQS, _crash_after_phases=2)
    kw = dict(_STREAM_KW, adapt=False)
    with pytest.raises(ValueError):
        StreamEngine.resume(path, "sin_recip_scaled", _EPS,
                            checkpoint_every=1, **kw)


# ---------------------------------------------------------------------------
# compile-once holds outside tune trials (satellite 3d)
# ---------------------------------------------------------------------------


def test_served_path_zero_recompiles_with_table_loaded(tmp_path,
                                                       monkeypatch):
    """The relaxation is scoped to tune trials: an engine resolving
    its cadence from a loaded table serves with ppls_recompiles_total
    pinned at 0 (and the resolution tier visible on the registry)."""
    from ppls_tpu.obs import Telemetry
    from ppls_tpu.runtime.stream import StreamEngine
    sig = _sig(refill_slots=2)
    path = str(tmp_path / "served.json")
    write_table(path, _table(_entry(sig, tune.device_kind())))
    monkeypatch.setenv("PPLS_TUNING_TABLE", path)
    clear_table_cache()
    try:
        tel = Telemetry()
        kw = dict(_STREAM_KW, adapt=False, scout_dtype="f32",
                  telemetry=tel)
        eng = StreamEngine("sin_recip_scaled", _EPS, **kw)
        assert eng._cadence_resolution["tier"] == "exact"
        assert eng._cycle_kw["exit_frac"] == 0.90
        assert eng._cycle_kw["suspend_frac"] == 0.65
        r = eng.run(_REQS[:4])
        assert len(r.completed) == 4
        reg = tel.registry
        assert reg.value("ppls_recompiles_total",
                         engine="walker-stream", default=0.0) == 0.0
        assert reg.value("ppls_tuning_resolution", tier="exact") == 1.0
    finally:
        clear_table_cache()
