"""Pallas subtree-walker engine tests (interpret mode on CPU).

The walker's split test runs in double-single f32, so its areas and task
counts are NOT bit-identical to the f64 bag engine: borderline split
decisions can flip and leaf values carry ~1e-14 relative ds error each
(walker.py module docstring). Tolerances here encode the observed
contract on this workload: areas ~1e-9, task drift well under 0.1%.

These run the same orchestration code (`_run_cycles`) as the TPU path,
with the Pallas kernel in interpret mode; the real-TPU twin lives in the
`-m tpu` lane (tests/test_tpu_lane.py).
"""

import numpy as np
import pytest

from ppls_tpu.models.integrands import get_family, get_family_ds
from ppls_tpu.parallel.bag_engine import integrate_family
from ppls_tpu.parallel.walker import integrate_family_walker


THETA = 1.0 + np.arange(4) / 4.0
BOUNDS = (1e-2, 1.0)
F = get_family("sin_recip_scaled")
F_DS = get_family_ds("sin_recip_scaled")

# Small-lane config so interpret mode stays fast; roots_per_lane=1 keeps
# the breed target (lanes) below the workload's peak frontier so the
# walker actually engages, and a low occupancy threshold keeps the deep
# tail in the kernel instead of the f64 drain.
KW = dict(capacity=1 << 16, lanes=256, roots_per_lane=1, seg_iters=32,
          min_active_frac=0.05)


def _bag(eps, theta=THETA, bounds=BOUNDS):
    return integrate_family(F, theta, bounds, eps,
                            chunk=1 << 10, capacity=1 << 16)


def test_walker_parity_vs_bag():
    eps = 1e-7
    w = integrate_family_walker(F, F_DS, THETA, BOUNDS, eps, **KW)
    b = _bag(eps)
    assert np.max(np.abs(w.areas - b.areas)) < 3e-9
    # ds split decisions may flip near the tolerance boundary
    drift = abs(w.metrics.tasks - b.metrics.tasks) / b.metrics.tasks
    assert drift < 1e-3, (w.metrics.tasks, b.metrics.tasks)
    assert w.metrics.tasks == w.metrics.splits + w.metrics.leaves


def test_walker_actually_walks():
    # The engine must not silently degrade into a pure bag run: on a deep
    # workload with a small breed target most tasks go through the kernel.
    w = integrate_family_walker(F, F_DS, THETA, BOUNDS, 1e-7, **KW)
    assert w.walker_fraction > 0.5, w.walker_fraction
    assert 0.0 < w.lane_efficiency <= 1.0


def test_walker_small_workload_falls_back():
    # Trivial run (huge eps): the seed tasks accept in the first breed
    # round, the bag empties before any frontier peak, and the walker
    # must return the exact f64 result with fraction 0.
    eps = 10.0
    w = integrate_family_walker(F, F_DS, THETA, BOUNDS, eps, **KW)
    b = _bag(eps)
    assert np.max(np.abs(w.areas - b.areas)) < 1e-15
    assert w.metrics.tasks == b.metrics.tasks
    assert w.walker_fraction == 0.0

    # Shallow-but-nontrivial run: breeding peak-stops early, the walker
    # takes part, and areas agree within the ds contract.
    eps = 1e-3
    w = integrate_family_walker(F, F_DS, THETA, BOUNDS, eps, **KW)
    b = _bag(eps)
    assert np.max(np.abs(w.areas - b.areas)) < 3e-9


def test_walker_mopup_via_forced_suspension():
    # max_segments=1 suspends nearly every lane mid-walk: the result must
    # still be correct via _expand_pending -> f64 drain (the mop-up path),
    # over multiple cycles.
    eps = 1e-7
    w = integrate_family_walker(F, F_DS, THETA, BOUNDS, eps,
                                capacity=1 << 16, lanes=256,
                                roots_per_lane=1, seg_iters=8,
                                max_segments=1, max_cycles=256)
    b = _bag(eps)
    assert np.max(np.abs(w.areas - b.areas)) < 3e-9
    drift = abs(w.metrics.tasks - b.metrics.tasks) / b.metrics.tasks
    assert drift < 1e-3


def test_walker_depth_overflow_mopup(monkeypatch):
    # Lanes whose subtree exceeds MAX_REL_DEPTH park with the _OVF flag
    # and their pending (i, d) set must be finished by the bag. Shrink the
    # cap to force the path. seg_iters differs from other tests so the
    # jitted _run_cycles cache cannot reuse a kernel traced with the
    # original constant.
    import ppls_tpu.parallel.walker as W
    monkeypatch.setattr(W, "MAX_REL_DEPTH", 4)
    eps = 1e-7
    w = integrate_family_walker(F, F_DS, THETA, BOUNDS, eps,
                                capacity=1 << 16, lanes=256,
                                roots_per_lane=1, seg_iters=33,
                                max_cycles=256)
    b = _bag(eps)
    assert np.max(np.abs(w.areas - b.areas)) < 3e-9
    # With the cap biting on every subtree, pending nodes are re-derived
    # from (i, d) in f64 (a + i*w*2^-d) rather than by repeated midpoint
    # bisection; the coordinate rounding differences flip borderline split
    # decisions far more often than in normal operation.
    drift = abs(w.metrics.tasks - b.metrics.tasks) / b.metrics.tasks
    assert drift < 0.05


def test_walker_deterministic():
    w1 = integrate_family_walker(F, F_DS, THETA, BOUNDS, 1e-6, **KW)
    w2 = integrate_family_walker(F, F_DS, THETA, BOUNDS, 1e-6, **KW)
    assert np.array_equal(w1.areas, w2.areas)
    assert w1.metrics.tasks == w2.metrics.tasks


def test_walker_rejects_bad_lanes():
    with pytest.raises(ValueError, match="multiple of 128"):
        integrate_family_walker(F, F_DS, THETA, BOUNDS, 1e-6, lanes=100)


def test_walker_sharded_matches_single_chip():
    # The multi-chip flagship path (the demand-driven engine — the pmap
    # family-deal variant was retired in round 5, see walker.py's note)
    # on the virtual 8-device mesh: same per-family computations up to
    # banking-order/borderline-flip ds noise vs the single-chip walker.
    from ppls_tpu.parallel.mesh import make_mesh
    from ppls_tpu.parallel.sharded_walker import integrate_family_walker_dd

    theta = 1.0 + np.arange(12) / 12.0
    eps = 1e-7
    s = integrate_family_walker_dd("sin_recip_scaled", theta, BOUNDS, eps,
                                   mesh=make_mesh(8), chunk=1 << 8, **KW)
    b = integrate_family_walker(F, F_DS, theta, BOUNDS, eps, **KW)
    assert np.max(np.abs(s.areas - b.areas)) < 1e-7
    drift = abs(s.metrics.tasks - b.metrics.tasks) / b.metrics.tasks
    assert drift < 0.01
    assert s.metrics.n_chips == 8
    assert len(s.metrics.tasks_per_chip) == 8
    assert sum(s.metrics.tasks_per_chip) == s.metrics.tasks
    # engagement: areas alone can't tell an all-f64 run from a walker
    # run — the Pallas kernel must own a real share on the mesh too
    assert s.walker_fraction > 0.2, s.walker_fraction


def test_walker_gauss_family():
    # The ds_exp-based family twin: sharply peaked Gaussians (sigma=1e-3)
    # — the clustered-refinement stress case — through the walker kernel.
    # Peaks sit near the dyadic sample points: a sigma=1e-3 peak at an
    # arbitrary offset is invisible to the first few trapezoid tests and
    # BOTH engines consistently accept 0 (inherent adaptive-quadrature
    # behavior, not an engine property).
    f = get_family("gauss_center")
    fds = get_family_ds("gauss_center")
    theta = np.array([0.4995, 0.5, 0.5005])
    eps = 1e-9
    w = integrate_family_walker(f, fds, theta, (0.4, 0.6), eps, **KW)
    b = integrate_family(f, theta, (0.4, 0.6), eps,
                         chunk=1 << 10, capacity=1 << 16)
    assert np.all(b.areas > 1e-3)          # every peak actually resolved
    assert np.max(np.abs(w.areas - b.areas)) < 3e-9
    drift = abs(w.metrics.tasks - b.metrics.tasks) / b.metrics.tasks
    assert drift < 1e-2
    assert w.walker_fraction > 0.2, w.walker_fraction


def test_walker_sharded_more_chips_than_families():
    # More chips than seed families: the collective breed re-shards the
    # three trees over all 8 chips; idle-at-seed chips still join.
    from ppls_tpu.parallel.mesh import make_mesh
    from ppls_tpu.parallel.sharded_walker import integrate_family_walker_dd

    theta = np.array([1.0, 1.5, 2.0])
    s = integrate_family_walker_dd("sin_recip_scaled", theta, BOUNDS,
                                   1e-6, mesh=make_mesh(8),
                                   chunk=1 << 8, **KW)
    b = integrate_family_walker(F, F_DS, theta, BOUNDS, 1e-6, **KW)
    assert np.all(np.isfinite(s.areas))
    assert np.max(np.abs(s.areas - b.areas)) < 1e-7


def test_ds_domain_guard_rejects_out_of_range():
    # VERDICT r3 #6: an out-of-range (bounds, theta) must raise up front
    # with a clear message — the ds transcendentals return silently
    # WRONG values (not NaN) outside their Cody-Waite validity, so no
    # runtime gate can catch it after the fact.
    with pytest.raises(ValueError, match="Cody-Waite"):
        integrate_family_walker(F, F_DS, [2.0], (1e-7, 1.0), 1e-6, **KW)
    # per-family bounds: only the offending member matters
    with pytest.raises(ValueError, match="Cody-Waite"):
        integrate_family_walker(
            F, F_DS, [1.0, 2.0], np.array([[1e-2, 1.0], [1e-7, 1.0]]),
            1e-6, **KW)
    # pole/nonpositive domain is its own error
    with pytest.raises(ValueError, match="bounds > 0"):
        integrate_family_walker(F, F_DS, [1.0], (-1.0, 1.0), 1e-6, **KW)
    # sin_scaled twin: arg = theta * x
    fs = get_family("sin_scaled")
    fs_ds = get_family_ds("sin_scaled")
    with pytest.raises(ValueError, match="Cody-Waite"):
        integrate_family_walker(fs, fs_ds, [1e9], (0.0, 1.0), 1e-6, **KW)


def test_ds_domain_guard_sharded_entry():
    from ppls_tpu.parallel.sharded_walker import integrate_family_walker_dd
    with pytest.raises(ValueError, match="Cody-Waite"):
        integrate_family_walker_dd("sin_recip_scaled", [2.0], (1e-7, 1.0),
                                   1e-6, capacity=1 << 14, lanes=256,
                                   n_devices=2)


def test_walker_simpson_matches_bag_simpson():
    # VERDICT r3 #4: both rules behind one interface, on the flagship
    # engine. Simpson's O(h^6) accepts make the tree far shallower, so
    # a tighter eps keeps a real workload.
    #
    # Interpret-mode caveat: under pallas interpret the fence-free ds
    # arithmetic degrades toward f32 (XLA's simplifier breaks the
    # error-free transforms — walker.py's refill notes), so Simpson's
    # cancellation-heavy |S2-S1|/15 estimate flips ~20% of borderline
    # split decisions here. Quality is unchanged (asserted vs exact
    # below); the REAL-Mosaic twin in tests/test_tpu_lane.py pins the
    # strict contract (measured: 0 task drift, 5.3e-15 area agreement).
    from ppls_tpu.config import Rule
    from ppls_tpu.models.integrands import family_exact
    eps = 1e-12
    w = integrate_family_walker(F, F_DS, THETA, BOUNDS, eps,
                                rule=Rule.SIMPSON, **KW)
    b = integrate_family(F, THETA, BOUNDS, eps, rule=Rule.SIMPSON,
                         chunk=1 << 10, capacity=1 << 16)
    exact = np.asarray(family_exact("sin_recip_scaled", *BOUNDS, THETA))
    assert np.max(np.abs(w.areas - exact)) < 1e-8      # quality holds
    assert np.max(np.abs(w.areas - b.areas)) < 1e-7
    drift = abs(w.metrics.tasks - b.metrics.tasks) / b.metrics.tasks
    assert drift < 0.3, (w.metrics.tasks, b.metrics.tasks)
    assert w.walker_fraction > 0.3, w.walker_fraction
    # Simpson pays ~3 kernel evals/task; the bag pays 5
    per_task = w.metrics.integrand_evals / w.metrics.tasks
    assert per_task < 4.5, per_task


def test_walker_simpson_beats_trapezoid_on_smooth():
    # the point of offering Simpson: far fewer tasks at equal quality
    from ppls_tpu.config import Rule
    from ppls_tpu.models.integrands import family_exact
    eps = 1e-10
    ws = integrate_family_walker(F, F_DS, THETA, BOUNDS, eps,
                                 rule=Rule.SIMPSON, **KW)
    wt = integrate_family_walker(F, F_DS, THETA, BOUNDS, eps, **KW)
    exact = np.asarray(family_exact("sin_recip_scaled", *BOUNDS, THETA))
    assert np.max(np.abs(ws.areas - exact)) < 1e-6
    assert ws.metrics.tasks < wt.metrics.tasks / 4, (
        ws.metrics.tasks, wt.metrics.tasks)


def _toy_bag(l, r, th, meta, store=8):
    """Hand-built BagState for unit-testing the root-ordering pass."""
    import jax.numpy as jnp
    from ppls_tpu.parallel.bag_engine import BagState
    n = len(l)
    pad = store - n
    f64 = lambda x, fill: jnp.asarray(list(x) + [fill] * pad,
                                      dtype=jnp.float64)
    return BagState(
        bag_l=f64(l, 0.25), bag_r=f64(r, 0.75),
        bag_th=f64(th, 1.0),
        bag_meta=jnp.asarray(list(meta) + [0] * pad, dtype=jnp.int32),
        count=jnp.int32(n),
        acc=jnp.zeros(1, jnp.float64),
        tasks=jnp.zeros((), jnp.int64), splits=jnp.zeros((), jnp.int64),
        iters=jnp.zeros((), jnp.int64),
        max_depth=jnp.zeros((), jnp.int32),
        overflow=jnp.zeros((), bool))


# f(x, th) = th * x^2: constant curvature, so the one-step trapezoid
# error estimate of a unit interval is proportional to th — the test
# can dial each root's work score (and inject NaN) through th alone.
def _quad_family(x, th):
    return th * x * x


@pytest.mark.nan_injection
def test_order_roots_nan_key_stays_in_live_prefix():
    """ADVICE r5 #1 regression: a live root whose one-step error
    estimate is NaN must stay INSIDE the live prefix of the sorted
    queue. The pre-fix key (jnp.where(live, err, inf)) let lax.sort's
    total order place the NaN row after the +inf-keyed dead rows —
    outside the live prefix, silently dropping the root's whole
    subtree and promoting a dead fill row in its place; this test
    fails on that key and passes on the NaN->inf mapping."""
    from ppls_tpu.config import Rule
    from ppls_tpu.parallel.walker import _order_roots_by_work

    bag = _toy_bag(l=[0.0, 1.0, 2.0, 3.0], r=[1.0, 2.0, 3.0, 4.0],
                   th=[4.0, 1.0, np.nan, 2.0], meta=[10, 11, 12, 13])
    out, scored = _order_roots_by_work(
        bag, f_theta=_quad_family, eps=1e-6, rule=Rule.TRAPEZOID,
        window=8)
    assert int(scored) == 4
    live_meta = sorted(np.asarray(out.bag_meta[:4]).tolist())
    # the drop check: all four roots — including the NaN one — survive
    # in the live prefix
    assert live_meta == [10, 11, 12, 13], live_meta
    # ascending work order with the NaN root keyed +inf: last live slot
    live_th = np.asarray(out.bag_th[:4])
    assert live_th[:3].tolist() == [1.0, 2.0, 4.0], live_th
    assert np.isnan(live_th[3])


def test_order_roots_homogeneous_window_skips_sort():
    """A window whose finite error spread is within sort_skip_ratio
    (~one refinement level) is left untouched — the sort is pure cost
    on an already-homogeneous queue; a wider spread still sorts."""
    import numpy as np
    from ppls_tpu.config import Rule
    from ppls_tpu.parallel.walker import _order_roots_by_work

    # errors proportional to th: spread 3.0/1.5 = 2 < 8
    kw = dict(f_theta=_quad_family, eps=1e-6, rule=Rule.TRAPEZOID,
              window=8)
    bag = _toy_bag(l=[0.0, 1.0, 2.0], r=[1.0, 2.0, 3.0],
                   th=[3.0, 1.5, 2.0], meta=[20, 21, 22])
    out, _ = _order_roots_by_work(bag, skip_ratio=8.0, **kw)
    assert np.asarray(out.bag_th[:3]).tolist() == [3.0, 1.5, 2.0]
    out, _ = _order_roots_by_work(bag, skip_ratio=0.0, **kw)
    assert np.asarray(out.bag_th[:3]).tolist() == [1.5, 2.0, 3.0]
    # spread 16 > 8: the skip must NOT engage
    bag = _toy_bag(l=[0.0, 1.0, 2.0], r=[1.0, 2.0, 3.0],
                   th=[16.0, 1.0, 2.0], meta=[30, 31, 32])
    out, _ = _order_roots_by_work(bag, skip_ratio=8.0, **kw)
    assert np.asarray(out.bag_th[:3]).tolist() == [1.0, 2.0, 16.0]


KW_RF = dict(KW, roots_per_lane=2, refill_slots=2)


def test_walker_kernel_refill_parity_vs_bag():
    # The in-kernel-refill engine (zero boundary sorts; the flagship
    # bench configuration) must meet the same parity contract as the
    # XLA-boundary engine.
    eps = 1e-7
    w = integrate_family_walker(F, F_DS, THETA, BOUNDS, eps, **KW_RF)
    b = _bag(eps)
    assert np.max(np.abs(w.areas - b.areas)) < 3e-9
    drift = abs(w.metrics.tasks - b.metrics.tasks) / b.metrics.tasks
    assert drift < 1e-3, (w.metrics.tasks, b.metrics.tasks)
    assert w.walker_fraction > 0.5, w.walker_fraction
    assert w.kernel_steps > 0
    # in-kernel-refill runs can't reconstruct boundary occupancy from
    # the seg-stats endpoints — the summary must say so, not guess
    occ = w.occupancy_summary()
    assert occ["mode"] == "in-kernel-refill"
    assert occ["est_occupancy"] is None


def test_walker_kernel_refill_deterministic():
    w1 = integrate_family_walker(F, F_DS, THETA, BOUNDS, 1e-6, **KW_RF)
    w2 = integrate_family_walker(F, F_DS, THETA, BOUNDS, 1e-6, **KW_RF)
    assert np.array_equal(w1.areas, w2.areas)
    assert w1.metrics.tasks == w2.metrics.tasks


def test_walker_kernel_refill_depth_overflow_mopup(monkeypatch):
    # An OVF lane inside the refill kernel must never take another
    # private root (its pending (i, d) set feeds the mop-up), and its
    # untaken slots must be re-pushed. seg_iters differs from the other
    # refill tests so the jit cache cannot reuse a kernel traced with
    # the original depth cap.
    import ppls_tpu.parallel.walker as W
    monkeypatch.setattr(W, "MAX_REL_DEPTH", 4)
    eps = 1e-7
    w = integrate_family_walker(F, F_DS, THETA, BOUNDS, eps,
                                capacity=1 << 16, lanes=256,
                                roots_per_lane=2, refill_slots=2,
                                seg_iters=34, max_cycles=256)
    b = _bag(eps)
    assert np.max(np.abs(w.areas - b.areas)) < 3e-9
    drift = abs(w.metrics.tasks - b.metrics.tasks) / b.metrics.tasks
    assert drift < 0.05


def test_walker_kernel_refill_simpson():
    from ppls_tpu.config import Rule
    from ppls_tpu.models.integrands import family_exact
    eps = 1e-12
    w = integrate_family_walker(F, F_DS, THETA, BOUNDS, eps,
                                rule=Rule.SIMPSON, **KW_RF)
    exact = np.asarray(family_exact("sin_recip_scaled", *BOUNDS, THETA))
    assert np.max(np.abs(w.areas - exact)) < 1e-8
    assert w.walker_fraction > 0.3, w.walker_fraction


def test_walker_refill_slots_validation():
    with pytest.raises(ValueError, match="refill_slots"):
        integrate_family_walker(F, F_DS, THETA, BOUNDS, 1e-6,
                                refill_slots=3, **KW)   # roots_per_lane=1
    with pytest.raises(ValueError, match="refill_slots"):
        integrate_family_walker(F, F_DS, THETA, BOUNDS, 1e-6,
                                refill_slots=-1, **KW)


def test_cycle_stats_record_sort_rows():
    # ADVICE r5 #4: the sort-pass eval accounting is backed by a
    # device-side live-row count, recorded per cycle in the stats ring.
    from ppls_tpu.parallel.walker import CYCLE_STAT_FIELDS
    w = integrate_family_walker(F, F_DS, THETA, BOUNDS, 1e-7, **KW)
    cs = w.cycle_stats
    assert cs is not None and len(cs)
    j = CYCLE_STAT_FIELDS.index("sort_rows")
    k = CYCLE_STAT_FIELDS.index("roots_consumed")
    assert cs[:, j].sum() > 0
    # every consumed root came off a scored window top, so the scored
    # total can never undercut the consumed total
    assert cs[:, j].sum() >= cs[:, k].sum()
    w0 = integrate_family_walker(F, F_DS, THETA, BOUNDS, 1e-7,
                                 sort_roots=False, **KW)
    cs0 = w0.cycle_stats
    assert cs0[:, j].sum() == 0


def test_walker_engages_on_collapsing_frontier():
    """VERDICT r4 #9: a family mix whose BFS frontier is non-monotone —
    collapsing far below the breed target mid-breed (63 trivial members
    accept in round one: frontier 64 -> 2) while ONE deep member has
    barely started — must still engage the walker, not silently
    degrade into an f64 bag run.

    What actually protects this edge (verified by cyc_stats here): each
    _breed call resets its peak detector, so the graduated-chunk breed
    phases and the next cycle's re-breed regrow the surviving deep
    frontier 2 -> target even though the mixed frontier shrank
    round-over-round; and the f64 drain stops at stop_count=target, so
    a sub-min_active remainder that regrows is handed back to the
    walker rather than run to completion in f64.

    The floor is 0.25, not the flagship's 0.99: on a ~2.3k-task
    workload the 2->256 regrowth itself processes a large share of all
    tasks in the breed phases (measured fraction ~0.36; a silent
    degradation reads ~0.0).
    """
    m = 64
    theta = 1.0 + np.arange(m) / m
    bounds = np.tile([0.7, 0.7 + 2.0 ** -10], (m, 1))
    bounds[0] = [1e-2, 1.0]     # the deep member: ~2.3k-task subtree
    eps = 1e-7
    w = integrate_family_walker(F, F_DS, theta, bounds, eps, **KW)
    b = integrate_family(F, theta, bounds, eps,
                         chunk=1 << 10, capacity=1 << 16)
    # ds-vs-f64 divergence on the deep member: the module contract at
    # eps=1e-7 on oscillatory domains is ~100x-eps-level (borderline
    # split flips), not the 3e-9 of the shallow-mix parity test above
    assert np.max(np.abs(w.areas - b.areas)) < 1e-5
    drift = abs(w.metrics.tasks - b.metrics.tasks) / b.metrics.tasks
    assert drift < 0.05, (w.metrics.tasks, b.metrics.tasks)
    # the deep member dominates the task count; the walker must own a
    # solid share of it despite the collapse
    assert w.metrics.tasks > 20 * m          # the mix IS deep-dominated
    assert w.walker_fraction > 0.25, w.walker_fraction
