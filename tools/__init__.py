# Makes `python -m tools.graftlint` resolvable from the repo root.
# The standalone scripts in this directory (bench helpers, check_artifacts)
# are still run by path; only graftlint is a real subpackage.
