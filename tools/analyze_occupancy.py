"""Decompose the flagship walker's wall time on real hardware.

VERDICT r4 item 1: before touching the kernel, find out where the
768 M -> 1 G subint/s gap actually lives. Three candidate sinks:

1. Parked lane-steps inside kernel segments (lane_efficiency 0.50 vs
   the ~0.67 trapezoid structural max: each task costs ~1.5 steps —
   one TEST plus amortized ~0.5 LOAD/INIT — so tasks/(steps*lanes)
   saturates at ~2/3 even at 100% occupancy).
2. Non-kernel device time: breed (f64 bag BFS), drain, XLA boundary
   work (bank/refill sorts, segment sums).
3. Host/tunnel overhead: per-dispatch eager initial_bag ops, per-
   collect device_get round-trips (~100-300 ms each on this rig).

Prints a section per measurement; run on the real chip:
    python tools/analyze_occupancy.py

Round 7: ``python tools/analyze_occupancy.py dd`` decomposes the
DEMAND-DRIVEN engine instead — refill vs legacy collective rounds per
cycle, per-chip balance, and the per-chip headroom split at the dd
lane count (main_dd).

Round 10: ``python tools/analyze_occupancy.py --from-events FILE
[--lanes N]`` replays a telemetry event log (``ppls-tpu serve
--events``, obs.spans JSONL) OFFLINE — no jax, no device — and prints
the same occupancy/boundary decomposition from the device-counter
deltas the phase spans carry, plus the retire-latency quantiles
through the shared histogram (identical numbers to the serve summary
by construction). This is the post-mortem path the CPU-only blocker
makes essential: a TPU-attached serve round is diagnosable from its
timeline alone.

Round 11: ``python tools/analyze_occupancy.py --attribution`` runs the
LANE-WASTE ATTRIBUTION decomposition — the five device-counted buckets
(eval_active / masked_dead / refill_stall / drain_tail /
theta_overwalk) that partition
every kernel lane-cycle, in both refill modes, with the reconciliation
invariant checked and the dominant waste bucket named (the number the
ceiling-hunt work is judged against). Offline too: ``--from-events``
prints the same decomposition from the waste tail columns the phase
spans now carry.

Round 21: ``--from-events`` on a ``serve --dispatch`` timeline
additionally prints the PER-ENGINE decomposition — every pool engine's
phase spans and retire events carry the ``engine=<keystr>`` label and
the pool emits ``engine_spinup``/``engine_park`` lifecycle events, so
phases/tasks/lane-efficiency/retire-latency split per engine key
offline, with the per-engine retire total reconciled against the
rid-deduped retire count.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main_from_events(path: str, lanes: int = 0) -> int:
    """Offline timeline decomposition (round 10): replay an obs.spans
    event log and print the phase/occupancy/latency breakdown from the
    device-counter deltas attached to the phase spans. No device, no
    compile cache, no engine imports — it works on any host that can
    read the file and import the (pure-Python) obs layer."""
    from ppls_tpu.obs.registry import PHASE_BUCKETS, Histogram
    from ppls_tpu.utils.artifact_schema import (dedup_by_rid,
                                                dedup_replayed,
                                                validate_events_text)

    with open(path, encoding="utf-8") as fh:
        text = fh.read()
    problems = validate_events_text(text, where=os.path.basename(path),
                                    require_balanced=False)
    for p in problems:
        print(f"WARNING schema: {p}")

    meta_attrs = {}
    phase_rows = []          # span_close attrs of "phase" spans
    phase_walls = []         # close.t - open.t per phase span
    open_phase = {}          # id -> (open t)
    open_engine = {}         # id -> engine label from the OPEN attrs
    open_leased = {}         # id -> phase ran on a donated credit
    names = {}               # id -> span name
    retires = []
    sheds = []               # request_shed events (round 16)
    spinups = []             # engine_spinup events (round 21 pool)
    parks = []               # engine_park events (round 21 pool)
    leases = []              # lease_grant events (round 22 ledger)
    checkpoints = 0
    segments = 0
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            continue          # already reported by the validator above
        if not isinstance(rec, dict):
            continue
        ev = rec.get("ev")
        if ev == "meta":
            segments += 1
            meta_attrs.update(rec.get("attrs") or {})
            # span ids restart per segment (resume-append): drop the
            # previous segment's bookkeeping so ids don't collide
            open_phase.clear()
            open_engine.clear()
            open_leased.clear()
            names.clear()
        elif ev == "span_open" and isinstance(rec.get("id"), int):
            names[rec["id"]] = rec.get("name")
            if rec.get("name") == "phase":
                open_phase[rec["id"]] = rec.get("t", 0.0)
                # the pool's engine label (and the round-22 leased
                # marker) ride the OPEN attrs (the close carries the
                # device-counter deltas); remember them so the
                # per-engine decomposition can key the row
                oattrs = rec.get("attrs") or {}
                eng = oattrs.get("engine")
                if eng:
                    open_engine[rec["id"]] = str(eng)
                if oattrs.get("leased"):
                    open_leased[rec["id"]] = True
        elif ev == "span_close":
            if names.get(rec.get("id")) == "phase":
                attrs = dict(rec.get("attrs") or {})
                attrs.setdefault("engine",
                                 open_engine.pop(rec.get("id"), None))
                attrs.setdefault("leased",
                                 open_leased.pop(rec.get("id"), False))
                if not attrs.get("idle"):
                    phase_rows.append(attrs)
                t0 = open_phase.pop(rec["id"], None)
                if t0 is not None:
                    phase_walls.append(rec.get("t", t0) - t0)
        elif ev == "event" and rec.get("name") == "retire":
            retires.append(rec.get("attrs") or {})
        elif ev == "event" and rec.get("name") == "request_shed":
            sheds.append(rec.get("attrs") or {})
        elif ev == "event" and rec.get("name") == "engine_spinup":
            spinups.append(rec.get("attrs") or {})
        elif ev == "event" and rec.get("name") == "engine_park":
            parks.append(rec.get("attrs") or {})
        elif ev == "event" and rec.get("name") == "lease_grant":
            leases.append(rec.get("attrs") or {})
        elif ev == "event" and rec.get("name") == "checkpoint":
            checkpoints += 1

    lanes = int(lanes or meta_attrs.get("lanes") or 0)
    print(f"=== timeline: {os.path.basename(path)} ===")
    print(f"meta: {meta_attrs}")
    print(f"segments={segments} (1 + one per resume), "
          f"device phases={len(phase_rows)}, retires={len(retires)}, "
          f"checkpoints={checkpoints}")

    def tot(key):
        return sum(int(r.get(key, 0)) for r in phase_rows)

    if phase_rows:
        tasks, wtasks, wsteps = tot("tasks"), tot("wtasks"), tot("wsteps")
        print(f"tasks={tasks} (walker {wtasks}, bag {tot('btasks')}), "
              f"splits={tot('splits')}, kernel steps={wsteps}")
        print(f"boundaries: rounds={tot('rounds')} segs={tot('segs')} "
              f"sort_rows={tot('srows')} crounds={tot('crounds')}")
        if lanes and wsteps:
            print(f"lane_efficiency={wtasks / (wsteps * lanes):.4f} "
                  f"(walker tasks / kernel lane-steps @ lanes={lanes})")
        print(f"walker_fraction="
              f"{wtasks / tasks if tasks else 0.0:.4f}")
        n = len(phase_rows)
        print(f"mean live_families={tot('live_families') / n:.2f}, "
              f"mean live_tasks={tot('live_tasks') / n:.1f}, "
              f"max depth={max(int(r.get('maxd', 0)) for r in phase_rows)}")
        if phase_walls:
            print(f"phase wall: mean={sum(phase_walls)/len(phase_walls)*1e3:.1f} ms "
                  f"max={max(phase_walls)*1e3:.1f} ms")
    if retires:
        h = Histogram(PHASE_BUCKETS)
        for r in retires:
            h.observe(int(r.get("latency_phases", 0)))
        print(f"retire latency (phases): p50={h.quantile(0.5)} "
              f"p99={h.quantile(0.99)} (shared histogram quantile — "
              f"identical to the serve summary)")
    # round-11 lane-waste attribution from the phase rows' tail columns
    from ppls_tpu.obs.telemetry import WASTE_BUCKETS
    if phase_rows and any(b in r for r in phase_rows
                          for b in WASTE_BUCKETS):
        buckets = {b: tot(b) for b in WASTE_BUCKETS}
        print_attribution(buckets, tot("wsteps"), lanes)
    # round-21 per-engine decomposition (heterogeneous dispatch pool):
    # every phase span and retire event a pool engine emits carries
    # the engine=<keystr> label, and the pool emits engine_spinup /
    # engine_park lifecycle events — so an offline timeline decomposes
    # per engine with no pool imports, the same way the summary's
    # `engines` block does online
    eng_labels = {str(r["engine"]) for r in phase_rows
                  if r.get("engine")}
    if spinups or parks or len(eng_labels) > 1:
        print("=== per-engine decomposition (dispatch pool) ===")

        def _row():
            return {"phases": 0, "leased_phases": 0, "tasks": 0,
                    "wtasks": 0, "wsteps": 0, "retired": 0,
                    "donated": 0, "borrowed": 0, "spinups": 0,
                    "unparks": 0, "parks": 0,
                    "hist": Histogram(PHASE_BUCKETS)}

        per = {}
        for r in phase_rows:
            row = per.setdefault(str(r.get("engine", "?")), _row())
            row["phases"] += 1
            if r.get("leased"):
                row["leased_phases"] += 1
            for k in ("tasks", "wtasks", "wsteps"):
                row[k] += int(r.get(k, 0))
        # round-22 lease ledger: grants dedup by (turn, donor,
        # borrower) — a resumed timeline legitimately replays the
        # post-snapshot turns' grant events (the replay IS the
        # determinism contract) and the turn counter rides the
        # snapshot, so the key collapses each replayed grant onto its
        # original
        lease_grants = dedup_replayed(
            leases, lambda g: (g.get("turn"), g.get("donor"),
                               g.get("borrower")))
        for g in lease_grants:
            n = int(g.get("credits", 1))
            per.setdefault(str(g.get("donor", "?")),
                           _row())["donated"] += n
            per.setdefault(str(g.get("borrower", "?")),
                           _row())["borrowed"] += n
        # rid-dedup before attributing: a resumed timeline replays
        # post-snapshot retire events (same rule as the SLO block)
        for r in dedup_by_rid(retires):
            row = per.setdefault(str(r.get("engine", "?")), _row())
            row["retired"] += 1
            row["hist"].observe(int(r.get("latency_phases", 0)))
        for s in spinups:
            row = per.setdefault(str(s.get("engine", "?")), _row())
            row["unparks" if s.get("resumed") else "spinups"] += 1
        for s in parks:
            per.setdefault(str(s.get("engine", "?")),
                           _row())["parks"] += 1
        for e, row in sorted(per.items()):
            eff = (f" lane_eff={row['wtasks'] / (row['wsteps'] * lanes):.4f}"
                   if lanes and row["wsteps"] else "")
            life = (f" spinups={row['spinups']} parks={row['parks']} "
                    f"unparks={row['unparks']}")
            # the round-22 idle-slot/lease column: credits this engine
            # DONATED (its slots sat idle, the pool lent them out) vs
            # credits it BORROWED, and how many of its phases actually
            # ran on a borrowed credit (leased= on the span)
            ls = (f" donated={row['donated']} "
                  f"borrowed={row['borrowed']} "
                  f"leased_phases={row['leased_phases']}"
                  if lease_grants else "")
            h = row["hist"]
            lat = (f" retire p50={h.quantile(0.5)} "
                   f"p99={h.quantile(0.99)}" if h.count else "")
            print(f"  {e}: phases={row['phases']} "
                  f"tasks={row['tasks']} retired={row['retired']}"
                  f"{eff}{lat}{ls}{life}")
        n_ret = len(dedup_by_rid(retires))
        n_per = sum(r["retired"] for r in per.values())
        print(f"  reconciliation: {n_per} per-engine retires vs "
              f"{n_ret} distinct retire rids -> "
              f"{'OK' if n_per == n_ret else 'FAIL'}")
        if lease_grants:
            # the lease sum invariant: every donated credit reconciles
            # against exactly one received credit (the ledger never
            # mints or loses a credit), and no engine ran more leased
            # phases than the credits it borrowed — so donated vs
            # native credits reconcile against the rid-deduped retire
            # totals above. Phase spans are NOT rid-deduped, so a
            # resumed (multi-segment) timeline legitimately replays
            # post-snapshot leased phases — the per-engine cap is only
            # a hard problem on a single-segment timeline.
            don = sum(r["donated"] for r in per.values())
            bor = sum(r["borrowed"] for r in per.values())
            over = [e for e, r in sorted(per.items())
                    if r["leased_phases"] > r["borrowed"]]
            lease_ok = don == bor and (not over or segments > 1)
            print(f"  lease reconciliation: donated {don} == "
                  f"borrowed {bor} across {len(lease_grants)} "
                  f"grant(s); leased phases <= borrowed per engine "
                  f"{'(replayed segments tolerated)' if segments > 1 else ''}"
                  f"-> {'OK' if lease_ok else 'FAIL'}")
            if not lease_ok:
                problems.append(
                    f"lease ledger failed to reconcile: donated={don} "
                    f"borrowed={bor} over-leased={over}")
    # round-16 multi-tenant SLO decomposition: per-class tail latency
    # + per-tenant retired/failed/shed accounting, offline from the
    # same retire/request_shed events serve emitted — identical
    # quantiles to the summary by the shared-histogram construction
    if any("tenant" in r for r in retires) or sheds:
        print("=== multi-tenant SLO ===")
        # dedup by rid first: a resumed (appended-segment) timeline
        # legitimately replays post-snapshot retire/shed events, and
        # counting them twice would overstate every number below (the
        # same rid-dedup rule validate_serve_output_text applies)
        retires = dedup_by_rid(retires)
        sheds = dedup_by_rid(sheds)
        by_class, tenants = {}, {}
        for r in retires:
            pri = r.get("priority", 1)
            by_class.setdefault(pri, Histogram(PHASE_BUCKETS)) \
                .observe(int(r.get("latency_phases", 0)))
            row = tenants.setdefault(str(r.get("tenant", "default")),
                                     {"completed": 0, "failed": 0,
                                      "shed": 0, "reasons": {}})
            row["completed"] += 1
            if r.get("failed"):
                row["failed"] += 1
        for s in sheds:
            row = tenants.setdefault(str(s.get("tenant", "default")),
                                     {"completed": 0, "failed": 0,
                                      "shed": 0, "reasons": {}})
            row["shed"] += 1
            reason = str(s.get("reason", "?"))
            row["reasons"][reason] = row["reasons"].get(reason, 0) + 1
        for pri, h in sorted(by_class.items()):
            print(f"  class {pri}: n={h.count} p50={h.quantile(0.5)} "
                  f"p99={h.quantile(0.99)} (phases)")
        for name, row in sorted(tenants.items()):
            extra = (f" reasons={row['reasons']}"
                     if row["reasons"] else "")
            print(f"  tenant {name}: completed={row['completed']} "
                  f"failed={row['failed']} shed={row['shed']}{extra}")
        print(f"  accounting: retired={len(retires)} "
              f"shed={len(sheds)} (every submitted rid is one or "
              f"the other)")
    return 1 if problems else 0


def print_attribution(buckets: dict, wsteps: int, lanes: int) -> None:
    """Attribution printer over the SHARED record builder
    (``obs.telemetry.build_attribution`` — the same dominant-bucket /
    reconciliation definitions bench and serve report)."""
    from ppls_tpu.obs.telemetry import build_attribution
    total = sum(buckets.values())
    a = build_attribution(buckets,
                          int(wsteps) * int(lanes) if lanes else total)
    print("=== lane-waste attribution ===")
    for k, v in a["buckets"].items():
        print(f"  {k:13s} {v:12d}  ({a['fractions'][k]:7.2%})")
    print(f"  reconciliation: sum={total} vs lanes x steps="
          f"{a['lane_cycles'] if lanes else 'unknown (pass --lanes)'} "
          f"-> {'OK' if a['reconciles'] and lanes else ('FAIL' if lanes else '?')}")
    dom = a["dominant_waste"]
    if dom is not None:
        print(f"  dominant waste bucket: {dom} "
              f"({a['fractions'][dom]:.2%} of lane-cycles) — attack "
              f"this one first")
    else:
        print("  dominant waste bucket: none (fully eval-active)")
    # round 20: the recommendation comes from the TUNER'S shared
    # dominant-bucket -> knob map (runtime.tune.BUCKET_KNOB_MAP — the
    # same map the bench.py tune sweep uses to pick its next knob; one
    # definition, no drift). tune stays importable without jax, so the
    # --from-events path gets the line too.
    from ppls_tpu.runtime.tune import recommend_knob
    rec = recommend_knob(a)
    if rec is not None:
        print(f"  recommended knob: {', '.join(rec['knobs'])} — "
              f"{rec['hint']}")


if "--from-events" in sys.argv:
    _i = sys.argv.index("--from-events")
    _lanes = 0
    if "--lanes" in sys.argv:
        _lanes = int(sys.argv[sys.argv.index("--lanes") + 1])
    sys.exit(main_from_events(sys.argv[_i + 1], lanes=_lanes))

from ppls_tpu.utils.compile_cache import enable_compile_cache
enable_compile_cache()

import jax
import jax.numpy as jnp
import numpy as np

from ppls_tpu.models.integrands import get_family, get_family_ds
from ppls_tpu.parallel.bag_engine import initial_bag
from ppls_tpu.parallel.walker import (MAX_REL_DEPTH, SEG_STAT_FIELDS,
                                      CYCLE_STAT_FIELDS, DEFAULT_LANES,
                                      collect_family_walker,
                                      dispatch_family_walker,
                                      integrate_family_walker)

M = 1024
EPS = 1e-10
BOUNDS = (1e-4, 1.0)


def sec(title):
    print(f"\n=== {title} ===", flush=True)


def main_dd():
    """Demand-driven decomposition (``python tools/analyze_occupancy.py
    dd``): the multi-chip refill-mode counters the round-7 tentpole is
    judged by — collective rounds per cycle (refill vs legacy on the
    same workload), per-chip task balance, lane efficiency, and the
    per-chip headroom split at the dd lane count."""
    from ppls_tpu.parallel.mesh import make_mesh
    from ppls_tpu.parallel.sharded_walker import (
        integrate_family_walker_dd)

    mesh = make_mesh()
    n_dev = mesh.devices.size
    m = int(os.environ.get("PPLS_ANALYZE_DD_M", "64"))
    lanes = 1 << 12
    theta = 1.0 + np.arange(m) / m
    dkw = dict(chunk=1 << 12, capacity=1 << 20, lanes=lanes,
               roots_per_lane=12, mesh=mesh)

    sec(f"dd warmup/compile ({n_dev} chip(s), refill R=8)")
    t0 = time.perf_counter()
    integrate_family_walker_dd("sin_recip_scaled", theta, BOUNDS, EPS,
                               refill_slots=8, **dkw)
    print(f"compile+run: {time.perf_counter()-t0:.1f} s")

    sec("dd refill vs legacy (warm)")
    t0 = time.perf_counter()
    rf = integrate_family_walker_dd("sin_recip_scaled", theta, BOUNDS,
                                    EPS, refill_slots=8, **dkw)
    t_rf = time.perf_counter() - t0
    t0 = time.perf_counter()
    lg = integrate_family_walker_dd("sin_recip_scaled", theta, BOUNDS,
                                    EPS, **dkw)
    t_lg = time.perf_counter() - t0
    for tag, r, t in (("refill", rf, t_rf), ("legacy", lg, t_lg)):
        tpc = r.metrics.tasks_per_chip
        print(f"  {tag:6s}: {r.metrics.tasks/t/1e6:7.1f} M subint/s "
              f"({t:.2f} s), cycles {r.cycles}, collectives "
              f"{r.collective_rounds} ({r.collective_rounds_per_cycle:.2f}"
              f"/cycle), lane_eff {r.lane_efficiency:.3f}, wfrac "
              f"{r.walker_fraction:.3f}, tpc max/min "
              f"{max(tpc)/max(min(tpc),1):.2f}")

    sec("dd per-chip headroom split")
    ceiling = None
    env_c = os.environ.get("PPLS_CEILING_GSTEPS")
    if env_c:
        ceiling = float(env_c) * 1e9
    elif jax.default_backend() == "tpu":
        from profile_walker import dd_kernel_ceiling_slope
        prof = dd_kernel_ceiling_slope()
        ceiling = prof["lane_steps_per_sec"]
        print(f"dd slope ceiling: {ceiling/1e9:.2f} G lane-steps/s "
              f"at lanes={lanes}")
    if ceiling:
        ach = rf.kernel_steps * lanes / (t_rf * n_dev)
        print(f"refill: {ach/1e9:.2f} G lane-steps/s/chip achieved "
              f"-> kernel_ceiling_frac {ach/ceiling:.3f} "
              f"(out-of-kernel share {1 - ach/ceiling:.3f})")
    else:
        print("no ceiling (off-TPU and no PPLS_CEILING_GSTEPS); "
              "skipping the split")


def main_attribution():
    """Round-11/12 tentpole decomposition (``--attribution``): run the
    walker across the engine modes — legacy boundary, in-kernel refill,
    and the round-12 scout + double-buffer flagship mode — and print
    the BEFORE/AFTER bucket decomposition: where every kernel
    lane-cycle went (five device-counted waste buckets), the
    reconciliation invariant, the dominant bucket by name, and the
    scout/confirm eval split. Sized for the flagship configuration on
    a TPU backend and for the interpret-mode flagship proxy elsewhere
    (the buckets are device-counted either way; the >=0.85 interpret
    lane-efficiency acceptance reads off the scout+db row)."""
    from ppls_tpu.parallel.walker import WASTE_FIELDS

    on_tpu = jax.default_backend() == "tpu"
    if on_tpu:
        m, eps, bounds = M, EPS, BOUNDS
        kw = dict(capacity=1 << 23)
        modes = (
            (dict(refill_slots=0), "legacy XLA-boundary"),
            (dict(refill_slots=8), "in-kernel refill (R=8)"),
            (dict(refill_slots=8, scout_dtype="f32",
                  double_buffer=True),
             "scout + double-buffer (flagship round 12)"),
        )
        lanes = DEFAULT_LANES
    else:
        # the interpret-mode FLAGSHIP PROXY: deep enough that the
        # drain tail amortizes like the real workload's
        m, eps, bounds = 64, 1e-8, (1e-3, 1.0)
        kw = dict(capacity=1 << 18, lanes=256, roots_per_lane=8,
                  seg_iters=256, min_active_frac=0.05)
        modes = (
            (dict(refill_slots=0), "legacy XLA-boundary"),
            (dict(refill_slots=8), "in-kernel refill (R=8)"),
            (dict(refill_slots=8, scout_dtype="f32",
                  double_buffer=True),
             "scout + double-buffer (flagship round 12)"),
        )
        lanes = 256
    theta = 1.0 + np.arange(m) / m
    f_theta = get_family("sin_recip_scaled")
    f_ds = get_family_ds("sin_recip_scaled")
    for mode_kw, label in modes:
        sec(f"attribution: {label}")
        r = integrate_family_walker(f_theta, f_ds, theta, bounds, eps,
                                    **mode_kw, **kw)
        a = r.attribution()
        print_attribution(a["buckets"], r.kernel_steps, lanes)
        cap = ("~1 fused scout test/step" if r.scout_evals
               else "structural max ~2/3 trapezoid")
        print(f"  lane_efficiency={r.lane_efficiency:.4f} "
              f"(tasks/lane-cycles; {cap}), cycles={r.cycles}")
        if r.scout_evals:
            print(f"  eval split: scout_evals={r.scout_evals} (f32), "
                  f"confirm_evals={r.confirm_evals} (full ds) — "
                  f"{r.confirm_evals / max(r.scout_evals + r.confirm_evals, 1):.1%}"
                  f" of kernel evals pay ds cost")
        assert a["reconciles"], "device-counted buckets failed to " \
            "reconcile — the accounting plumbing is broken"
        cs = r.cycle_stats
        if cs is not None and len(cs):
            iw = [CYCLE_STAT_FIELDS.index(k) for k in WASTE_FIELDS]
            istep = CYCLE_STAT_FIELDS.index("walker_steps")
            print("  per-cycle [steps, eval_active, masked_dead, "
                  "refill_stall, drain_tail, theta_overwalk]:")
            for row in cs.tolist():
                print(f"    {[row[istep]] + [row[i] for i in iw]}")


def main():
    theta = 1.0 + np.arange(M) / M
    f_theta = get_family("sin_recip_scaled")
    f_ds = get_family_ds("sin_recip_scaled")
    # match bench.py's flagship config (in-kernel refill); set
    # PPLS_ANALYZE_REFILL_SLOTS=0 to decompose the legacy boundary path
    kw = dict(capacity=1 << 23,
              refill_slots=int(os.environ.get(
                  "PPLS_ANALYZE_REFILL_SLOTS", "8")))

    sec("tunnel RTT (trivial device_get x5)")
    x = jnp.zeros(8)
    jax.device_get(x)
    rtts = []
    for _ in range(5):
        t0 = time.perf_counter()
        jax.device_get(x + 1.0)
        rtts.append(time.perf_counter() - t0)
    rtt = float(np.median(rtts))
    print(f"RTT median {rtt*1e3:.1f} ms  (all: "
          f"{[round(r*1e3,1) for r in rtts]})")

    sec("initial_bag eager construction cost")
    for rep in range(3):
        t0 = time.perf_counter()
        st = initial_bag(np.tile(np.array(BOUNDS), (M, 1)), 1 << 23, M,
                         1 << 17, theta=theta)
        jax.block_until_ready(st.bag_l)
        print(f"  pass {rep}: {time.perf_counter()-t0:.3f} s")

    sec("warmup/compile (first full run)")
    t0 = time.perf_counter()
    res = integrate_family_walker(f_theta, f_ds, theta, BOUNDS, EPS, **kw)
    print(f"compile+run: {time.perf_counter()-t0:.1f} s; "
          f"tasks={res.metrics.tasks}, lane_eff={res.lane_efficiency:.3f}, "
          f"walker_frac={res.walker_fraction:.3f}, cycles={res.cycles}")

    sec("solo run (dispatch + collect, cache-warm)")
    for rep in range(2):
        t0 = time.perf_counter()
        d = dispatch_family_walker(f_theta, f_ds, theta, BOUNDS, EPS, **kw)
        t1 = time.perf_counter()
        r = collect_family_walker(d)
        t2 = time.perf_counter()
        print(f"  pass {rep}: dispatch {t1-t0:.3f} s, collect {t2-t1:.3f} s"
              f" -> rate {r.metrics.tasks/(t2-t0)/1e6:.0f} M/s"
              f" (minus 1 RTT: {r.metrics.tasks/max(t2-t0-rtt,1e-9)/1e6:.0f})")

    sec("pipeline of 5 (as bench.py does)")
    t0 = time.perf_counter()
    ds = [dispatch_family_walker(f_theta, f_ds, theta, BOUNDS, EPS, **kw)
          for _ in range(5)]
    t_disp = time.perf_counter() - t0
    deltas = []
    prev = time.perf_counter()
    rs = []
    for d in ds:
        rs.append(collect_family_walker(d))
        now = time.perf_counter()
        deltas.append(now - prev)
        prev = now
    total = time.perf_counter() - t0
    tasks = sum(r.metrics.tasks for r in rs)
    print(f"dispatch-all {t_disp:.3f} s; collect deltas "
          f"{[round(x,3) for x in deltas]} s; total {total:.3f} s "
          f"-> sustained {tasks/total/1e6:.0f} M/s")
    pipe_total, pipe_tasks, pipe_rs = total, tasks, rs

    sec("single-dispatch x5 via fori-style re-dispatch of SAME state")
    # All 5 dispatches share one prebuilt initial state: dispatch cost is
    # then just jit-cache lookup + enqueue.
    from ppls_tpu.parallel.walker import _run_cycles, WalkerDispatch
    from ppls_tpu.config import Rule
    target = min(12 * DEFAULT_LANES, (1 << 23) // 2)
    breed_chunk = max(1 << int(target - 1).bit_length(), 1 << 15)
    slack = max(breed_chunk, -(-(MAX_REL_DEPTH + 1) * DEFAULT_LANES // 2))
    bounds_arr = np.tile(np.array(BOUNDS), (M, 1))
    state = initial_bag(bounds_arr, 1 << 23, M, slack, theta=theta)
    jax.block_until_ready(state.bag_l)
    ck = dict(f_theta=f_theta, f_ds=f_ds, eps=float(EPS), m=M,
              seg_iters=512, max_segments=1 << 18, min_active_frac=0.1,
              exit_frac=0.65, suspend_frac=0.5, interpret=False,
              lanes=DEFAULT_LANES, capacity=1 << 23,
              breed_chunk=breed_chunk, target=target, max_cycles=64,
              rule=Rule.TRAPEZOID)
    t0 = time.perf_counter()
    outs = [_run_cycles(state, **ck) for _ in range(5)]
    t_disp = time.perf_counter() - t0
    deltas = []
    prev = time.perf_counter()
    tot_tasks = 0
    for o in outs:
        tot_tasks += int(jax.device_get(o.tasks))
        now = time.perf_counter()
        deltas.append(now - prev)
        prev = now
    total = time.perf_counter() - t0
    print(f"dispatch-all {t_disp:.3f} s; collect deltas "
          f"{[round(x,3) for x in deltas]} s; total {total:.3f} s "
          f"-> sustained {tot_tasks/total/1e6:.0f} M/s")

    sec("occupancy summary (WalkerResult.occupancy_summary — the same "
        "reconstruction the bench artifact carries)")
    print(res.occupancy_summary())

    sec("headroom: kernel wall split vs profiled ceiling")
    # kernel seconds ~= kernel lane-steps / ceiling (ISSUE r6 / VERDICT
    # r5 #5). Ceiling: slope-profiled on-TPU in this same run, or the
    # PPLS_CEILING_GSTEPS override (G lane-steps/s) off-TPU.
    ceiling = None
    env_c = os.environ.get("PPLS_CEILING_GSTEPS")
    if env_c:
        ceiling = float(env_c) * 1e9
    elif jax.default_backend() == "tpu":
        from profile_walker import kernel_ceiling_slope
        prof = kernel_ceiling_slope()
        ceiling = prof["lane_steps_per_sec"]
        print(f"slope ceiling: {ceiling/1e9:.2f} G lane-steps/s "
              f"(outer {prof['outer_lo']} vs {prof['outer_hi']})")
    if ceiling:
        lane_steps = res.kernel_steps * DEFAULT_LANES
        pipe_rate = pipe_tasks / pipe_total   # the pipeline-of-5 above
        ach = (sum(r.kernel_steps for r in pipe_rs) * DEFAULT_LANES
               / pipe_total)
        print(f"pipeline of 5: {ach/1e9:.2f} G lane-steps/s achieved "
              f"-> kernel_ceiling_frac {ach/ceiling:.3f} "
              f"(out-of-kernel share {1 - ach/ceiling:.3f}) at "
              f"{pipe_rate/1e6:.0f} M subint/s")
        print(f"warm solo run: {lane_steps} lane-steps "
              f"~= {lane_steps/ceiling*1e3:.1f} ms of kernel at ceiling")
    else:
        print("no ceiling (off-TPU and no PPLS_CEILING_GSTEPS); "
              "skipping the split")

    sec("seg_stats occupancy breakdown (detail, from warm run)")
    ss = res.seg_stats
    if ss is None or not len(ss):
        print("no seg_stats")
    elif res.refill_slots:
        # in-kernel-refill rows: `refilled` counts a launch's in-kernel
        # takes and live_exit is sampled only at bank-dry/step-cap, so
        # the boundary live-lane reconstruction below does not apply
        # (occupancy_summary above already reports est_occupancy=None)
        print(f"in-kernel refill run (R={res.refill_slots}): boundary "
              f"reconstruction not applicable; first 12 rows "
              f"[steps, live_exit, queue_left, refilled]:")
        print(ss[:12].tolist())
    else:
        steps = ss[:, 0].astype(np.float64)
        live_exit = ss[:, 1].astype(np.float64)
        queue_left = ss[:, 2].astype(np.float64)
        refilled = ss[:, 3].astype(np.float64)
        lanes = DEFAULT_LANES
        # live at segment start ~= previous exit + the PREVIOUS row's
        # refills: row i records the boundary AFTER segment i's walk
        # (ADVICE r5 #2 — this loop used refilled[k], skewing every
        # cited occupancy number by one segment; occupancy_summary had
        # the correct convention, now shared above)
        live_start = np.empty_like(live_exit)
        live_start[0] = lanes  # initial seeding fills all lanes
        for k in range(1, len(ss)):
            live_start[k] = min(lanes, live_exit[k - 1] + refilled[k - 1])
        # trapezoidal estimate of within-segment mean occupancy
        occ = (live_start + live_exit) / (2 * lanes)
        w = steps / steps.sum()
        dry = queue_left <= 0
        print(f"segments={len(ss)}  total steps={int(steps.sum())}  "
              f"mean steps/seg={steps.mean():.0f}")
        print(f"steps-weighted est. occupancy: {float((occ*w).sum()):.3f}")
        print(f"dry-queue segments: {int(dry.sum())} "
              f"({float(steps[dry].sum()/steps.sum()):.2%} of steps, "
              f"est occ {float((occ[dry]*steps[dry]).sum()/max(steps[dry].sum(),1)):.3f})")
        fed = ~dry
        print(f"fed segments:       {int(fed.sum())} "
              f"({float(steps[fed].sum()/steps.sum()):.2%} of steps, "
              f"est occ {float((occ[fed]*steps[fed]).sum()/max(steps[fed].sum(),1)):.3f})")
        # histogram of steps by est occupancy bucket
        for lo in (0.9, 0.8, 0.7, 0.6, 0.5, 0.0):
            m_ = occ >= lo
            print(f"  occ>={lo:.1f}: {float(steps[m_].sum()/steps.sum()):.2%}"
                  f" of steps ({int(m_.sum())} segs)")
            steps = steps * ~m_  # remove counted
            occ = np.where(m_, -1, occ)
        print("first 12 rows [steps, live_exit, queue_left, refilled]:")
        print(ss[:12].tolist())

    sec("cyc_stats (from warm run)")
    cs = res.cycle_stats
    if cs is None or not len(cs):
        print("no cyc_stats")
    else:
        print(f"fields: {CYCLE_STAT_FIELDS}")
        for row in cs.tolist():
            print("  ", row)


if __name__ == "__main__":
    if len(sys.argv) > 1 and sys.argv[1] == "dd":
        main_dd()
    elif "--attribution" in sys.argv:
        main_attribution()
    else:
        main()
