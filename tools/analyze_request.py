#!/usr/bin/env python
"""Offline per-request critical-path analyzer (round 19).

``tools/analyze_occupancy.py --from-events`` answers "what did the
ENGINE do per phase"; this tool answers the question every serving
postmortem actually starts with: "where did REQUEST 17's latency go?"
It replays a ``ppls-tpu serve --events`` timeline (the round-19
request-scoped trace: detached ``request`` spans + their child events)
with no jax and no device, and prints:

* the PER-RID LATENCY DECOMPOSITION — submit -> admit (backlog wait
  vs token-bucket wait) -> compute phases (engine residency or the
  spillover hand-off) -> retirement, with the redeal/quarantine/
  deadline trail annotated. The components are exact phase counts
  that SUM EXACTLY to the recorded retire latency::

      backlog_wait + token_wait + in_flight == latency_phases

  (``--check`` exits nonzero on any rid where they do not);
* the TOP-K SLOWEST requests with their decompositions;
* PER-TENANT and PER-CLASS rollups (count / failed / shed / mean and
  max latency / mean queue wait);
* the incomplete set — rids with an opened trace but no terminal
  event, the shape a crashed prefix leaves behind (reported, never
  fatal: the tool works on crashed and resumed multi-segment
  timelines, deduping replayed events by rid).

Usage::

    python tools/analyze_request.py EVENTS.jsonl [MORE.jsonl ...]
        [--top K] [--json] [--check] [--tenant NAME]

Rolled segments (``--events-max-mb``) are picked up automatically:
passing ``EVENTS.jsonl`` also reads ``EVENTS.jsonl.1`` ... in order.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
from typing import Dict, List, Optional

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

# the per-rid trace vocabulary: ONE definition, shared with the
# rid-linkage validator so the analyzer and the schema check cannot
# drift apart
from ppls_tpu.utils.artifact_schema import (  # noqa: E402
    RID_TRACE_EVENTS as TRACE_EVENTS,
    dedup_replayed,
)


def expand_paths(paths: List[str]) -> List[str]:
    """Auto-include rolled segment siblings (``<p>.1`` ...) BEFORE the
    active file — rolled files are the older part of the timeline."""
    out: List[str] = []
    for p in paths:
        rolled = []
        for s in glob.glob(f"{p}.*"):
            suffix = s[len(p) + 1:]
            if suffix.isdigit():
                rolled.append((int(suffix), s))
        out.extend(s for _, s in sorted(rolled))
        out.append(p)
    return out


def load_trace(paths: List[str]) -> Dict[int, dict]:
    """Parse the per-rid trace out of one or more event files.

    Returns ``{rid: {"open": attrs|None, "events": {name: attrs or
    [attrs...]}, "phases": sorted [phase...], "redeals": [...],
    "token_waits": n}}`` with replayed duplicates (resume re-emits
    nothing, but a supervisor retry may re-append restored spans)
    deduped by rid / (rid, phase)."""
    rids: Dict[int, dict] = {}

    def rec_for(rid: int) -> dict:
        return rids.setdefault(int(rid), {
            "open": None, "terminal": None, "events": {},
            "phases": set(), "processes": set(), "redeals": [],
            "token_wait_events": set()})

    sid_rid: Dict[int, int] = {}
    for path in paths:
        with open(path, encoding="utf-8") as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if not isinstance(rec, dict):
                    continue
                ev = rec.get("ev")
                if ev == "meta":
                    sid_rid.clear()      # span ids restart per segment
                    continue
                attrs = rec.get("attrs") or {}
                if ev == "span_open" and rec.get("name") == "request":
                    rid = attrs.get("rid")
                    if rid is None:
                        continue
                    sid_rid[rec.get("id")] = int(rid)
                    r = rec_for(rid)
                    if r["open"] is None:
                        r["open"] = dict(attrs)
                    continue
                if ev != "event":
                    continue
                name = rec.get("name")
                rid = attrs.get("rid")
                if name not in TRACE_EVENTS or rid is None:
                    continue
                r = rec_for(rid)
                if name in ("retire", "request_shed"):
                    if r["terminal"] is None:
                        r["terminal"] = (name, dict(attrs))
                elif name in ("admit", "request_dealt",
                              "spillover_enqueued"):
                    r["events"].setdefault(name, dict(attrs))
                elif name == "request_phase":
                    r["phases"].add(int(attrs.get("phase", -1)))
                    if "process" in attrs:
                        r["processes"].add(attrs["process"])
                elif name == "token_wait":
                    r["token_wait_events"].add(
                        int(attrs.get("phase", -1)))
                elif name == "request_redeal":
                    r["redeals"].append(dict(attrs))
                else:   # quarantine / deadline_exceeded
                    r["events"].setdefault(name, dict(attrs))
    # replay dedup (shared helper): a resumed segment re-emits the
    # post-snapshot redeal events; one record per (phase, process)
    # survives, first (original) occurrence wins
    for r in rids.values():
        r["redeals"] = dedup_replayed(
            r["redeals"],
            lambda d: (d.get("phase"), d.get("process")))
    return rids


def decompose(rid: int, r: dict) -> Optional[dict]:
    """One rid's critical-path decomposition (None for non-retired
    rids — shed and incomplete traces are reported separately).

    EXACTNESS contract: ``backlog_wait + token_wait + in_flight ==
    latency_phases`` where latency_phases is the retire event's own
    recorded value — integers, no estimation."""
    if r["terminal"] is None or r["terminal"][0] != "retire":
        return None
    t = r["terminal"][1]
    admit_ev = r["events"].get("admit") or r["events"].get(
        "request_dealt") or {}
    submit = int(t.get("submit_phase",
                       admit_ev.get("submit_phase", 0)))
    admit = int(t.get("admit_phase", admit_ev.get("phase", submit)))
    retire = int(t.get("retire_phase", admit))
    latency = int(t.get("latency_phases", retire - submit + 1))
    token_wait = int(admit_ev.get("token_wait_phases",
                                  len(r["token_wait_events"])))
    queue_wait = admit - submit
    backlog_wait = queue_wait - token_wait
    in_flight = retire - admit + 1
    out = {
        "rid": int(rid),
        "tenant": t.get("tenant", "default"),
        "priority": t.get("priority", 1),
        "submit_phase": submit, "admit_phase": admit,
        "retire_phase": retire,
        "latency_phases": latency,
        "components": {
            "backlog_wait": backlog_wait,
            "token_wait": token_wait,
            "in_flight": in_flight,
        },
        "exact": backlog_wait + token_wait + in_flight == latency,
        "compute_phases": len(r["phases"]),
        "failed": bool(t.get("failed")),
        "failure": t.get("failure"),
        "spillover": bool(t.get("spillover")
                          or "spillover_enqueued" in r["events"]),
        "redeals": len(r["redeals"]),
    }
    if r["processes"]:
        out["processes"] = sorted(r["processes"], key=str)
    return out


def analyze(paths: List[str], top: int = 5) -> dict:
    """The whole report as one dict (the ``--json`` document and the
    test surface)."""
    rids = load_trace(paths)
    rows, shed, incomplete = [], [], []
    for rid in sorted(rids):
        r = rids[rid]
        d = decompose(rid, r)
        if d is not None:
            rows.append(d)
        elif r["terminal"] is not None:      # request_shed
            t = r["terminal"][1]
            shed.append({"rid": int(rid),
                         "tenant": t.get("tenant", "default"),
                         "reason": t.get("reason"),
                         "phase": t.get("phase")})
        else:
            incomplete.append(int(rid))

    def rollup(key_fn):
        acc: Dict[str, dict] = {}
        for d in rows:
            k = str(key_fn(d))
            a = acc.setdefault(k, {
                "count": 0, "failed": 0, "spillover": 0,
                "latency_sum": 0, "latency_max": 0,
                "queue_wait_sum": 0, "in_flight_sum": 0})
            a["count"] += 1
            a["failed"] += int(d["failed"])
            a["spillover"] += int(d["spillover"])
            a["latency_sum"] += d["latency_phases"]
            a["latency_max"] = max(a["latency_max"],
                                   d["latency_phases"])
            a["queue_wait_sum"] += (d["components"]["backlog_wait"]
                                    + d["components"]["token_wait"])
            a["in_flight_sum"] += d["components"]["in_flight"]
        for k, a in acc.items():
            n = max(a["count"], 1)
            a["latency_mean"] = round(a["latency_sum"] / n, 3)
            a["queue_wait_mean"] = round(a["queue_wait_sum"] / n, 3)
        for s in shed:
            if key_fn(s) is not None:
                acc.setdefault(str(key_fn(s)), {"count": 0}) \
                    .setdefault("shed", 0)
                acc[str(key_fn(s))]["shed"] = \
                    acc[str(key_fn(s))].get("shed", 0) + 1
        return dict(sorted(acc.items()))

    slowest = sorted(rows, key=lambda d: (-d["latency_phases"],
                                          d["rid"]))[:top]
    return {
        "requests": rows,
        "shed": shed,
        "incomplete": incomplete,
        "exact": all(d["exact"] for d in rows),
        "top_slowest": slowest,
        "by_tenant": rollup(lambda d: d.get("tenant")),
        "by_class": rollup(lambda d: d.get("priority")),
    }


def _fmt_row(d: dict) -> str:
    c = d["components"]
    trail = []
    if d["spillover"]:
        trail.append("spillover")
    if d["redeals"]:
        trail.append(f"redeal x{d['redeals']}")
    if d["failure"]:
        trail.append(d["failure"])
    return (f"  rid {d['rid']:>5}  {d['tenant']:<10} "
            f"p{d['priority']}  "
            f"lat={d['latency_phases']:>4}  "
            f"= backlog {c['backlog_wait']} + token "
            f"{c['token_wait']} + in-flight {c['in_flight']}"
            f"{'  [' + ', '.join(trail) + ']' if trail else ''}"
            f"{'' if d['exact'] else '  ** DOES NOT SUM **'}")


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="tools/analyze_request.py",
        description="per-request critical-path decomposition from a "
                    "ppls-tpu serve --events timeline")
    p.add_argument("events", nargs="+", help="event file(s); rolled "
                   "segments (<file>.N) are auto-included")
    p.add_argument("--top", type=int, default=5)
    p.add_argument("--tenant", default=None,
                   help="restrict the per-rid table to one tenant")
    p.add_argument("--json", action="store_true", dest="as_json")
    p.add_argument("--check", action="store_true",
                   help="exit 1 unless every decomposition sums "
                        "exactly to its recorded retire latency")
    args = p.parse_args(argv)

    paths = expand_paths(args.events)
    missing = [q for q in paths if not os.path.exists(q)]
    if missing:
        print(f"analyze_request: no such file: {missing[0]}",
              file=sys.stderr)
        return 2
    rep = analyze(paths, top=args.top)

    if args.as_json:
        print(json.dumps(rep, indent=1, sort_keys=True))
    else:
        rows = [d for d in rep["requests"]
                if args.tenant is None or d["tenant"] == args.tenant]
        print(f"=== request critical paths: "
              f"{', '.join(os.path.basename(q) for q in paths)} ===")
        print(f"retired={len(rep['requests'])} shed={len(rep['shed'])}"
              f" incomplete={len(rep['incomplete'])} "
              f"exact={'yes' if rep['exact'] else 'NO'}")
        for d in rows:
            print(_fmt_row(d))
        if rep["top_slowest"]:
            print(f"--- top {len(rep['top_slowest'])} slowest ---")
            for d in rep["top_slowest"]:
                print(_fmt_row(d))
        for title, block in (("tenant", rep["by_tenant"]),
                             ("class", rep["by_class"])):
            print(f"--- by {title} ---")
            for k, a in block.items():
                print(f"  {k:<10} n={a.get('count', 0):>4} "
                      f"failed={a.get('failed', 0)} "
                      f"shed={a.get('shed', 0)} "
                      f"lat mean/max="
                      f"{a.get('latency_mean', 0)}/"
                      f"{a.get('latency_max', 0)} "
                      f"queue mean={a.get('queue_wait_mean', 0)}")
        if rep["incomplete"]:
            print(f"--- incomplete (crashed prefix?) --- "
                  f"{rep['incomplete'][:16]}")
    if args.check and not rep["exact"]:
        bad = [d["rid"] for d in rep["requests"] if not d["exact"]]
        print(f"analyze_request: decomposition does not sum for "
              f"rid(s) {bad[:8]}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
