#!/usr/bin/env python
"""Bench observatory (round 11): the committed round artifacts as ONE
normalized trajectory, plus a device-counted regression gate.

The BENCH_r*/MULTICHIP_r* wrappers each hold a round's bench stdout in
a ``tail`` string; diffing rounds means eyeballing JSON lines buried in
five different files, and NOTHING fails when a round silently regresses
— the artifact schema check only proves records parse. This tool makes
the trajectory a first-class object:

* ``python tools/bench_history.py`` — print the normalized trajectory
  (``ppls-bench-history-v1``): one record per round with the primary
  metric, the device-counted proxy fields (lane_efficiency, occupancy,
  the round-11 lane-waste attribution when present), and the
  secondaries.
* ``python tools/bench_history.py --check`` — trajectory
  well-formedness over the committed artifacts: every BENCH round
  parses, carries a primary record with a finite value, rounds are
  strictly increasing, and error rounds (value 0 + error string) are
  reported as GAPS rather than silently blending into the curve.
* ``python tools/bench_history.py --gate RECORD.json`` — the
  REGRESSION GATE: compare a quick-proxy record (the ``bench.py
  quick`` walker block) against the committed reference
  ``tools/bench_quick_ref.json``. Device-counted proxies are
  bit-stable in interpret mode, so the gate can be tight on a CPU-only
  container where wall clocks measure the interpreter:
    - kernel_steps and boundary count must not grow past
      ``1 + tolerance`` (default 0.5: a 2x slowdown record trips);
    - lane_efficiency must not drop below ``1 - eff_tolerance``
      (default 0.15) of the reference;
    - the lane-waste attribution must reconcile;
    - tasks must stay within 20% of the reference — further drift
      means the workload itself changed and the reference must be
      re-recorded, not silently compared.
* ``python tools/bench_history.py --gate-run`` — run the quick walker
  proxy leg fresh (the exact ``bench.py quick`` walker configuration)
  and gate it; ``--update-ref`` records it as the new reference. This
  pair is the ci.sh step: committed ref vs fresh run must pass.
"""

from __future__ import annotations

import glob
import json
import math
import os
import sys
import time
from typing import List, Optional

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DEFAULT_REF = os.path.join(REPO, "tools", "bench_quick_ref.json")

# the bench.py quick walker leg's exact configuration (device-counted
# proxies are deterministic per jax version/backend at this sizing).
# Round 12: the quick leg runs the FLAGSHIP mode — mixed-precision
# scouting + double-buffered root banks — so the committed reference
# (recorded via the documented --update-ref flow) carries the
# scout-mode numbers and the gate defends them.
QUICK_WALKER_KW = dict(capacity=1 << 16, lanes=256, roots_per_lane=2,
                       refill_slots=2, seg_iters=32,
                       min_active_frac=0.05,
                       scout_dtype="f32", double_buffer=True)
QUICK_M = 8
QUICK_EPS = 1e-7
QUICK_BOUNDS = (1e-2, 1.0)

# Round 13: the many-theta amortization proxy leg (bench.py theta).
# One frontier scores a batch of T per-user thetas per interval; the
# proxy measures device-counted INTERVAL BOOKKEEPING PER THETA
# (kernel steps + boundaries, i.e. bag rounds + segments, divided by
# T) against a T=1 solo sweep at identical per-theta eps.
THETA_FAMILY = "sin_scaled"
THETA_EPS = 1e-5
THETA_BOUNDS = (0.0, 1.0)
THETA_RANGE = (1.0, 4.0)
THETA_LANES = 2048
THETA_SOLO_SAMPLES = 8
THETA_QUICK_T = (32, 256)
THETA_FULL_T = (32, 256, 2048)
THETA_KW = dict(capacity=1 << 16, roots_per_lane=8, refill_slots=8,
                seg_iters=64, min_active_frac=0.05)
# regression floor: the T=256 bookkeeping-per-theta reduction vs the
# T=1 sweep must stay above this multiple (the round-13 acceptance
# number), and must not drop more than GATE_THETA_TOL below the
# committed reference's measured reduction.
GATE_THETA_MIN_REDUCTION = 4.0
GATE_THETA_TOL = 0.25

# Round 16: the multi-tenant overload SLO proxy leg (bench.py stream
# --tenants / the quick record's multi_tenant block). A deterministic
# Poisson overload (offered load ~8 requests/phase against a 4-slot
# engine with a bounded queue) over three priority classes, with chaos
# injected (one NaN-poisoned admission, one straggler boundary): the
# device/schedule-counted outputs — shed fraction, per-class p50/p99
# retire latency in phases, the completed+shed accounting invariant —
# are bit-stable in interpret mode, so the gate can hold the
# multi-tenant numbers the way it holds lane efficiency.
STREAM_SLO_FAMILY = "sin_recip_scaled"
STREAM_SLO_EPS = 1e-6
STREAM_SLO_BOUNDS = (1e-2, 1.0)
STREAM_SLO_K = 24
STREAM_SLO_RATE = 8.0
STREAM_SLO_QUEUE_LIMIT = 6
STREAM_SLO_SEED = 23
STREAM_SLO_KW = dict(slots=4, chunk=1 << 10, capacity=1 << 16,
                     lanes=256, roots_per_lane=2, refill_slots=2,
                     seg_iters=32, min_active_frac=0.05)
STREAM_SLO_TENANTS = (("free", 0), ("std", 1), ("pro", 2))
# chaos: rid 2 is NaN-poisoned post-validation (quarantine must
# contain it), one straggler boundary adds recoverable wall noise
STREAM_SLO_FAULTS = (
    {"kind": "nan_poison", "at": 2},
    {"kind": "straggler", "at": 3, "seconds": 0.05},
)
# gate bands: shed fraction may drift +-0.15 absolute; per-class p99
# (phases) may grow <= 25% over the reference
GATE_SHED_ABS_TOL = 0.15
GATE_STREAM_P99_TOL = 0.25

# Round 18: the multi-host proxy leg (bench.py multihost / the quick
# record's multihost block). A real 2-process local cluster under
# overload (bounded coordinator queue + CPU spillover armed) with ONE
# HOST KILLED mid-stream: the proxies are the redeal wall (surviving-
# host discovery + host_strided_redeal of the lost host's outstanding
# requests), the spillover-engaged fraction (device-counted), the
# zero-lost-acks accounting invariant, and per-request-area
# bit-identity against the undisturbed single-engine run (the dyadic
# quad_scaled workload makes that assertable as a boolean).
MULTIHOST_FAMILY = "quad_scaled"
MULTIHOST_EPS = 1e-9
MULTIHOST_K = 8
MULTIHOST_PROCESSES = 2
MULTIHOST_QUEUE_LIMIT = 2
MULTIHOST_SPILL_LIMIT = 2
MULTIHOST_WKW = dict(slots=4, chunk=1 << 10, capacity=1 << 16,
                     lanes=256, roots_per_lane=2, refill_slots=2,
                     seg_iters=32, min_active_frac=0.05,
                     f64_rounds=2)
MULTIHOST_FAULTS = ({"kind": "host_loss", "at": 1, "chip": 1},)
# gate bands: spillover share must stay ENGAGED (> 0) and within
# +-0.25 absolute of the reference; the redeal must finish inside an
# absolute wall budget (generous — it is a host-side request re-deal,
# not a recompile)
GATE_SPILL_ABS_TOL = 0.25
GATE_REDEAL_WALL_BUDGET_S = 10.0

# Round 21: the heterogeneous-shape dispatcher proxy leg (bench.py
# stream --hetero / tools/ci.sh hetero step). A seeded open-loop
# mixed-SHAPE stream — every request carries eps/rule/theta routing
# keys cycling over >= 3 distinct (eps band, rule, theta bucket)
# compile statics — through the EngineDispatcher pool
# (runtime/dispatch.py), against the SERIALIZED one-engine-at-a-time
# baseline: the same requests partitioned by engine key and run to
# completion group after group. The gated numbers are all
# schedule-counted (bit-stable in interpret mode): the pool recompile
# count (THE invariant — 0), the completed+shed accounting, the
# per-engine-sums-to-pool reconciliation, and the work-conserving
# schedule's turn-count speedup + retire-latency win over serialized.
HETERO_FAMILY = "sin_recip_scaled"
HETERO_BOUNDS = (1e-2, 1.0)
HETERO_K = 16
HETERO_RATE = 4.0
HETERO_SEED = 31
HETERO_MAX_ENGINES = 4
HETERO_SLOTS = 4
HETERO_EKW = dict(chunk=1 << 10, capacity=1 << 16, lanes=256,
                  roots_per_lane=2, refill_slots=2, seg_iters=32,
                  min_active_frac=0.05)
# the distinct compile statics the acceptance names, cycled over the
# request stream (trapezoid t1 at two eps bands, a theta BATCH bucket,
# and a simpson engine)
HETERO_SHAPES = (
    {"eps": 1e-6},                          # -> e-6:trapezoid:t1
    {"eps": 1e-7},                          # -> e-7:trapezoid:t1
    {"eps": 1e-6, "batch": 2},              # -> e-6:trapezoid:t2
    {"eps": 1e-6, "rule": "simpson"},       # -> e-6:simpson:t1
)
# gate bands: the turn-count speedup over serialized must stay > 1
# (the work-conserving claim itself) and within 25% of the reference;
# pool p99 retire latency (turns) may grow <= 25% over it
GATE_DISPATCH_SPEEDUP_TOL = 0.25
GATE_DISPATCH_P99_TOL = 0.25
# Round 22: the slot-credit-leasing acceptance floor — with leasing +
# overlapped boundaries ON, drain turns and mean retire latency on
# the seeded stream must improve >= 1.2x over the committed round-21
# schedule (9 turns / 1.5 mean on HETERO_SEED=31). The nolease twin
# in the record re-measures that baseline every run, so drift in the
# round-21 schedule itself also surfaces here.
R21_DISPATCH_TURNS = 9
R21_DISPATCH_MEAN_LAT = 1.5
GATE_DISPATCH_LEASE_SPEEDUP = 1.2

# gate tolerances (the "stated tolerance" of the round-11 acceptance)
GATE_STEP_TOL = 0.5      # kernel_steps / boundaries may grow <= 1.5x
GATE_EFF_TOL = 0.15      # lane_efficiency may drop <= 15% (relative)
# Round 12: the lane_efficiency FLOOR — the quick-proxy efficiency may
# not drop more than 10% below the committed scout-mode reference.
# Tighter than the relative check above: this is the bound the
# lane-efficiency tentpole is held to between TPU rounds.
GATE_EFF_FLOOR_TOL = 0.10
GATE_TASK_TOL = 0.2      # beyond this the workload itself changed


def _records_from_wrapper(text: str) -> List[dict]:
    """Bench records inside a round wrapper (or a raw line stream)."""
    try:
        wrapper = json.loads(text)
    except json.JSONDecodeError:
        wrapper = None
    if isinstance(wrapper, dict) and "tail" in wrapper:
        lines = str(wrapper.get("tail", "")).splitlines()
    else:
        lines = text.splitlines()
    out = []
    for ln in lines:
        ln = ln.strip()
        if not ln.startswith("{"):
            continue
        try:
            obj = json.loads(ln)
        except json.JSONDecodeError:
            continue
        if isinstance(obj, dict) and "metric" in obj:
            out.append(obj)
    return out


def _round_index(path: str) -> Optional[int]:
    base = os.path.basename(path)
    digits = "".join(ch for ch in base if ch.isdigit())
    return int(digits) if digits else None


def load_trajectory(paths: Optional[List[str]] = None) -> dict:
    """Normalize the committed round artifacts into one trajectory."""
    if not paths:
        paths = sorted(glob.glob(os.path.join(REPO, "BENCH_r*.json"))) \
            + sorted(glob.glob(os.path.join(REPO, "MULTICHIP_r*.json")))
    rounds = []
    for p in paths:
        base = os.path.basename(p)
        kind = "bench" if base.startswith("BENCH") else "multichip"
        entry = {"round": _round_index(p), "source": base,
                 "kind": kind, "records": [], "primary": None}
        try:
            with open(p, encoding="utf-8") as fh:
                text = fh.read()
        except OSError as e:
            entry["error"] = f"unreadable: {e}"
            rounds.append(entry)
            continue
        recs = _records_from_wrapper(text)
        entry["records"] = [
            {k: r.get(k) for k in ("metric", "value", "unit",
                                   "vs_baseline", "error")
             if k in r} for r in recs]
        if recs:
            prim = recs[0]
            entry["primary"] = {
                "metric": prim.get("metric"),
                "value": prim.get("value"),
                "unit": prim.get("unit"),
                "vs_baseline": prim.get("vs_baseline"),
            }
            if "error" in prim:
                entry["primary"]["error"] = prim["error"]
            for k in ("lane_efficiency", "walker_fraction",
                      "occupancy", "attribution", "interpret_mode",
                      "interpret_mode_quick", "interpret_mode_smoke"):
                if k in prim:
                    entry[k] = prim[k]
            sec = prim.get("secondary")
            if isinstance(sec, dict):
                entry["secondary"] = {
                    name: {k: sub.get(k)
                           for k in ("metric", "value", "unit", "error",
                                     "skipped") if k in sub}
                    for name, sub in sec.items()
                    if isinstance(sub, dict)}
        rounds.append(entry)
    return {"schema": "ppls-bench-history-v1", "rounds": rounds}


def check_trajectory(traj: dict) -> List[str]:
    """Well-formedness problems in the committed trajectory."""
    problems: List[str] = []
    bench = [r for r in traj["rounds"] if r["kind"] == "bench"]
    if not bench:
        problems.append("no BENCH_r* artifacts found")
        return problems
    last = None
    for r in bench:
        where = r["source"]
        if r.get("error"):
            problems.append(f"{where}: {r['error']}")
            continue
        if r["round"] is None:
            problems.append(f"{where}: no round index in filename")
        elif last is not None and r["round"] <= last:
            problems.append(f"{where}: round {r['round']} not "
                            f"strictly increasing (prev {last})")
        last = r["round"] if r["round"] is not None else last
        if not r["records"]:
            problems.append(f"{where}: no bench records (silent-drop "
                            f"round)")
            continue
        prim = r["primary"]
        v = prim.get("value")
        if not isinstance(v, (int, float)) or isinstance(v, bool) \
                or not math.isfinite(v):
            problems.append(f"{where}: primary value not finite: {v!r}")
        elif v <= 0 and "error" not in prim:
            problems.append(f"{where}: primary value {v} is "
                            f"non-positive without an error record")
    for r in traj["rounds"]:
        if r["kind"] == "multichip" and r.get("error"):
            problems.append(f"{r['source']}: {r['error']}")
    return problems


def gaps(traj: dict) -> List[str]:
    """Error rounds — visible gaps in the curve, not failures."""
    out = []
    for r in traj["rounds"]:
        if r["kind"] == "bench" and r.get("primary") \
                and "error" in (r["primary"] or {}):
            out.append(f"{r['source']}: error round "
                       f"({r['primary'].get('error', '')[:60]})")
    return out


# ---------------------------------------------------------------------------
# quick-proxy regression gate
# ---------------------------------------------------------------------------


def run_quick_proxies() -> dict:
    """The ``bench.py quick`` walker leg, standalone: a small
    interpret-mode walker run whose DEVICE-COUNTED proxies (tasks,
    kernel steps, boundaries, lane efficiency, lane-waste attribution)
    are deterministic on a given jax version/backend."""
    import numpy as np

    from ppls_tpu.models.integrands import get_family, get_family_ds
    from ppls_tpu.parallel.walker import integrate_family_walker

    theta = 1.0 + np.arange(QUICK_M) / float(QUICK_M)
    r = integrate_family_walker(
        get_family("sin_recip_scaled"),
        get_family_ds("sin_recip_scaled"),
        theta, QUICK_BOUNDS, QUICK_EPS, **QUICK_WALKER_KW)
    # this dict IS bench.py quick's walker block (bench_quick imports
    # this function) — one definition, so the CI gate and the committed
    # quick records can never measure different workloads
    return {
        "metric": "interpret-mode quick proxies",
        "walker": {
            "tasks": int(r.metrics.tasks),
            "cycles": int(r.cycles),
            "kernel_steps": int(r.kernel_steps),
            "boundaries_rounds_plus_segs": int(r.metrics.rounds),
            "lane_efficiency": round(r.lane_efficiency, 4),
            "walker_fraction": round(r.walker_fraction, 4),
            # round 12: the device-counted eval split behind
            # evals_per_task (f32 scout pass vs full-ds confirm pass)
            "scout_evals": int(r.scout_evals),
            "confirm_evals": int(r.confirm_evals),
            "evals_per_task": round(
                r.metrics.integrand_evals / max(r.metrics.tasks, 1), 3),
            "scout_dtype": QUICK_WALKER_KW.get("scout_dtype", "f64"),
            "double_buffer": bool(
                QUICK_WALKER_KW.get("double_buffer", False)),
            "occupancy": r.occupancy_summary(),
            "attribution": r.attribution(),
        },
    }


def run_theta_proxies(ts=THETA_QUICK_T) -> dict:
    """The ``bench.py theta`` walker leg, standalone (one definition
    for the bench record, the committed gate reference, and the CI
    gate measurement — same ownership contract as
    :func:`run_quick_proxies`).

    Measures, per T in ``ts``: the device-counted interval bookkeeping
    (kernel steps + boundaries) per theta of a theta-blocked run over
    T thetas, the reduction versus a T=1 solo sweep at identical
    per-theta eps, thetas*tasks/s/chip (interpret rate off-TPU — the
    proxies are the signal), the theta_overwalk share, and the
    per-theta quality check: batched |area - exact| must not exceed
    the solo sweep's worst |area - exact| + eps (the union-refinement
    contract — each theta's leaf set is at least as refined as solo,
    so its error is never worse beyond one local eps; the raw
    batched-minus-solo gap is bounded by SOLO's own global error,
    which is O(leaves * eps) by the per-leaf test semantics)."""
    import time

    import numpy as np

    from ppls_tpu.models.integrands import (family_exact, get_family,
                                            get_family_ds)
    from ppls_tpu.parallel.walker import integrate_family_walker

    f = get_family(THETA_FAMILY)
    fds = get_family_ds(THETA_FAMILY)
    lo, hi = THETA_RANGE
    samples = np.linspace(lo, hi, THETA_SOLO_SAMPLES)
    solo_bk, solo_err = [], []
    ex_s = np.asarray(family_exact(THETA_FAMILY, *THETA_BOUNDS,
                                   samples))
    for t, e in zip(samples, ex_s):
        r = integrate_family_walker(f, fds, [t], THETA_BOUNDS,
                                    THETA_EPS, lanes=THETA_LANES,
                                    **THETA_KW)
        solo_bk.append(int(r.kernel_steps) + int(r.metrics.rounds))
        solo_err.append(abs(float(r.areas[0]) - float(e)))
    t1_per_theta = float(np.mean(solo_bk))
    solo_err = np.asarray(solo_err)
    solo_worst_err = float(np.max(solo_err))

    legs = {}
    for T in ts:
        # the batch EMBEDS the solo-sample thetas (first 8 entries) so
        # the quality bound is the real PER-THETA contract —
        # batched_err(theta) <= solo_err(theta) + eps at the very
        # thetas the solo sweep measured — not a cross-theta maximum
        thetas = np.linspace(lo, hi, int(T))
        thetas[:THETA_SOLO_SAMPLES] = samples
        thetas = thetas.reshape(1, int(T))
        t0 = time.perf_counter()
        r = integrate_family_walker(
            f, fds, thetas, THETA_BOUNDS, THETA_EPS,
            lanes=THETA_LANES, theta_block=int(T), **THETA_KW)
        wall = time.perf_counter() - t0
        ex = np.asarray(family_exact(THETA_FAMILY, *THETA_BOUNDS,
                                     thetas))
        err = float(np.max(np.abs(np.asarray(r.areas) - ex)))
        sample_err = np.abs(
            np.asarray(r.areas)[0, :THETA_SOLO_SAMPLES] - ex_s)
        bk = int(r.kernel_steps) + int(r.metrics.rounds)
        attr = r.attribution()
        legs[str(int(T))] = {
            "bookkeeping_steps_plus_boundaries": bk,
            "bookkeeping_per_theta": round(bk / int(T), 4),
            "reduction_vs_t1": round(
                t1_per_theta / max(bk / int(T), 1e-12), 2),
            "theta_tasks_per_s_per_chip": round(
                int(r.metrics.tasks) / max(wall, 1e-9), 1),
            "kernel_steps": int(r.kernel_steps),
            "boundaries_rounds_plus_segs": int(r.metrics.rounds),
            "cycles": int(r.cycles),
            "max_abs_err": err,
            "quality_vs_solo_ok": bool(
                np.all(sample_err <= solo_err + THETA_EPS)),
            "theta_overwalk_frac": attr["fractions"]["theta_overwalk"],
            "reconciles": bool(attr["reconciles"]),
        }
    return {
        "metric": "many-theta amortization proxies",
        "family": THETA_FAMILY, "eps": THETA_EPS,
        "bounds": list(THETA_BOUNDS), "lanes": THETA_LANES,
        "t1_bookkeeping_per_theta": round(t1_per_theta, 2),
        "t1_solo_samples": THETA_SOLO_SAMPLES,
        "solo_max_abs_err": solo_worst_err,
        "theta": legs,
    }


def run_stream_slo_proxies() -> dict:
    """The ``bench.py stream --tenants`` leg, standalone (one
    definition for the bench record, the committed gate reference, and
    the CI gate measurement — the :func:`run_quick_proxies` ownership
    contract).

    Drives the round-16 multi-tenant StreamEngine through a seeded
    Poisson overload at ~{rate} requests/phase across three priority
    classes with a bounded queue and chaos injected (NaN poison +
    straggler), and reports the SLO proxies the gate holds: shed
    fraction, per-class p50/p99 retire latency (phases), the
    completed+shed accounting invariant, and the quarantine count.
    Every reported number is schedule- or device-counted —
    deterministic in interpret mode."""
    import numpy as np

    from ppls_tpu.runtime.faults import FaultInjector, FaultPlan
    from ppls_tpu.runtime.stream import StreamEngine

    rng = np.random.default_rng(STREAM_SLO_SEED)
    k = STREAM_SLO_K
    gaps = rng.exponential(1.0 / STREAM_SLO_RATE, k)
    arrivals = [int(p) for p in
                np.floor(np.cumsum(gaps) - gaps[0]).astype(int)]
    reqs = []
    for i in range(k):
        tenant, pri = STREAM_SLO_TENANTS[i % len(STREAM_SLO_TENANTS)]
        reqs.append((1.0 + i / k, STREAM_SLO_BOUNDS,
                     {"tenant": tenant, "priority": pri}))
    injector = FaultInjector(FaultPlan.from_events(
        [dict(e) for e in STREAM_SLO_FAULTS]))
    eng = StreamEngine(
        STREAM_SLO_FAMILY, STREAM_SLO_EPS,
        queue_limit=STREAM_SLO_QUEUE_LIMIT, quarantine=True,
        fault_injector=injector, **STREAM_SLO_KW)
    res = eng.run(reqs, arrival_phase=arrivals)
    by_class = res.class_latency_percentiles()
    shed_reasons: dict = {}
    for s in res.shed:
        shed_reasons[s.reason] = shed_reasons.get(s.reason, 0) + 1
    failed = sum(1 for c in res.completed if c.failed)
    return {
        "metric": "multi-tenant overload SLO proxies",
        "family": STREAM_SLO_FAMILY, "eps": STREAM_SLO_EPS,
        "k_requests": k,
        "offered_load_req_per_phase": STREAM_SLO_RATE,
        "queue_limit": STREAM_SLO_QUEUE_LIMIT,
        "slots": STREAM_SLO_KW["slots"],
        "tenants": [t for t, _ in STREAM_SLO_TENANTS],
        "faults_injected": [e.describe()
                            for e in injector.plan.events if e.fired],
        "requests_per_sec": round(res.requests_per_sec, 3),
        "phases": res.phases,
        "completed": len(res.completed),
        "shed": len(res.shed),
        "shed_fraction": round(len(res.shed) / k, 4),
        "shed_reasons": shed_reasons,
        "failed": failed,
        "accounting_ok": len(res.completed) + len(res.shed) == k,
        "latency_by_class": by_class,
    }


def run_multihost_proxies() -> dict:
    """The ``bench.py multihost`` leg, standalone (one definition for
    the bench record, the committed gate reference, and the CI
    --gate-run measurement — the :func:`run_quick_proxies` ownership
    contract).

    Stands up a REAL 2-process local cluster (worker subprocesses
    behind the coordinator, ``runtime/cluster.py``) over the dyadic
    ``quad_scaled`` workload with a bounded coordinator queue and the
    CPU spillover backend armed, SIGKILLs worker 1 at phase 1 through
    the ``host_loss`` fault, and lets the supervisor's host-loss arm
    discover + re-deal. Proxies: redeal wall, spillover-engaged
    fraction (device-counted tasks included), the zero-lost-acks
    accounting invariant, and bit-identity of every per-request area
    against the undisturbed single-engine run."""
    import numpy as np

    from ppls_tpu.runtime import guard
    from ppls_tpu.runtime.cluster import ClusterStreamEngine
    from ppls_tpu.runtime.faults import FaultInjector, FaultPlan
    from ppls_tpu.runtime.stream import StreamEngine

    thetas = [1.0 + i / 4.0 for i in range(MULTIHOST_K)]
    reqs = [(t, (0.0, 1.0)) for t in thetas]
    base = StreamEngine(MULTIHOST_FAMILY, MULTIHOST_EPS,
                       **MULTIHOST_WKW).run(reqs)
    injector = FaultInjector(FaultPlan.from_events(
        [dict(e) for e in MULTIHOST_FAULTS]))
    eng = ClusterStreamEngine(
        MULTIHOST_FAMILY, MULTIHOST_EPS,
        n_processes=MULTIHOST_PROCESSES, worker_kw=MULTIHOST_WKW,
        fault_injector=injector,
        queue_limit=MULTIHOST_QUEUE_LIMIT, spillover=True,
        spillover_limit=MULTIHOST_SPILL_LIMIT)

    def loop():
        k = eng.next_rid
        while not eng.idle or k < len(reqs):
            while k < len(reqs):
                eng.submit(*reqs[k])
                k += 1
            eng.step()
        return eng.result()

    def resize_fn(exc):
        eng.recover_host_loss(exc)
        return loop

    sup = guard.Supervisor(loop, resize_fn=resize_fn,
                           log=lambda m: None,
                           sleep=lambda s: None)
    try:
        t0 = time.perf_counter()
        res = sup.run()
        wall = time.perf_counter() - t0
        spill = eng.spillover_summary()
        manifest = eng.manifest.identity()
        return {
            "metric": "multi-host cluster proxies",
            "family": MULTIHOST_FAMILY, "eps": MULTIHOST_EPS,
            "k_requests": MULTIHOST_K,
            "processes": MULTIHOST_PROCESSES,
            "processes_surviving": manifest["processes"],
            "queue_limit": MULTIHOST_QUEUE_LIMIT,
            "faults_injected": [e.describe()
                                for e in injector.plan.events
                                if e.fired],
            "recoveries": [{"kind": k_, "action": a}
                           for k_, a in sup.recoveries],
            "completed": len(res.completed),
            "shed": len(res.shed),
            "accounting_ok": (len(res.completed) + len(res.shed)
                              == MULTIHOST_K),
            "areas_bit_identical": bool(
                np.array_equal(res.areas, base.areas)),
            "redeal_wall_s": round(
                eng.redeal_walls[0] if eng.redeal_walls else -1.0,
                4),
            "spillover_fraction": round(
                spill["spillover_fraction"], 4),
            "spillover_completed": spill["spillover_completed"],
            "spillover_tasks": spill["spillover_tasks"],
            "wall_s": round(wall, 3),
        }
    finally:
        eng.close()


def _hetero_requests():
    """The seeded mixed-shape request stream: (theta, bounds, kwargs)
    triples whose kwargs carry the per-request eps/rule routing keys,
    plus the open-loop arrival schedule (pool turns)."""
    import numpy as np

    rng = np.random.default_rng(HETERO_SEED)
    gaps = rng.exponential(1.0 / HETERO_RATE, HETERO_K)
    arrivals = [int(p) for p in
                np.floor(np.cumsum(gaps) - gaps[0]).astype(int)]
    reqs = []
    for i in range(HETERO_K):
        shape = HETERO_SHAPES[i % len(HETERO_SHAPES)]
        b = int(shape.get("batch", 1))
        if b > 1:
            theta = tuple(1.0 + (i + j / 8.0) / HETERO_K
                          for j in range(b))
        else:
            theta = 1.0 + i / HETERO_K
        kw = {"eps": shape["eps"]}
        if "rule" in shape:
            kw["rule"] = shape["rule"]
        reqs.append((theta, HETERO_BOUNDS, kw))
    return reqs, arrivals


def run_hetero_dispatch_proxies() -> dict:
    """The ``bench.py stream --hetero`` leg, standalone (one
    definition for the bench record, the committed gate reference, and
    the CI --gate-run measurement — the :func:`run_quick_proxies`
    ownership contract).

    Drives the seeded mixed-shape stream through the
    :class:`~ppls_tpu.runtime.dispatch.EngineDispatcher` (>= 3
    distinct engine keys, zero recompiles end-to-end) — since round
    22 with slot-credit leasing + overlapped boundaries ON as the
    headline measurement, plus the round-21 lease-OFF twin of the
    identical stream (``*_nolease`` fields) so the lease win is
    measured against the committed round-21 baseline — then runs the
    SERIALIZED baseline — the same requests partitioned by engine key,
    each group's engine run to completion one after another — and
    reports the schedule-counted comparison: pool turns vs summed
    serialized phases, mean/p99 retire latency in turns for both. The
    work-conserving round-robin must beat serialized on both (the
    perf claim this tier exists for, assertable in interpret mode)."""
    import numpy as np

    from ppls_tpu.config import Rule
    from ppls_tpu.runtime.dispatch import (EngineDispatcher, EngineKey,
                                           canonical_key)
    from ppls_tpu.runtime.stream import StreamEngine

    reqs, arrivals = _hetero_requests()
    keys = sorted({str(canonical_key(r[2]["eps"],
                                     r[2].get("rule", "trapezoid"),
                                     r[0])) for r in reqs})

    # round-21 twin: the same stream with leasing/overlap OFF — the
    # committed-reference schedule (9 turns / 1.5 mean on the seed)
    disp0 = EngineDispatcher(HETERO_FAMILY, slots=HETERO_SLOTS,
                             max_engines=HETERO_MAX_ENGINES,
                             engine_kw=dict(HETERO_EKW))
    res0 = disp0.run(reqs, arrival_phase=arrivals)
    lat0 = [int(c.retire_phase) - int(c.submit_phase)
            for c in res0.completed]

    disp = EngineDispatcher(HETERO_FAMILY, slots=HETERO_SLOTS,
                            max_engines=HETERO_MAX_ENGINES,
                            lease=True, overlap_boundaries=True,
                            engine_kw=dict(HETERO_EKW))
    res = disp.run(reqs, arrival_phase=arrivals)
    lat = [int(c.retire_phase) - int(c.submit_phase)
           for c in res.completed]
    leases = disp.lease_summary()
    summary = disp.engines_summary()
    per_engine_completed = sum(v["completed"]
                               for v in summary.values())
    per_engine_shed = sum(v["shed"] for v in summary.values())

    # serialized one-engine-at-a-time baseline: group by engine key,
    # run each group's engine to completion before the next starts
    # (all of a group's requests available up front — the generous
    # reading of serialized, so beating it is the strong claim); a
    # request's serialized retire latency in GLOBAL phases is the
    # phases burned by every earlier group plus its own retire phase
    groups: dict = {}
    for (theta, bounds, kw2) in reqs:
        k = str(canonical_key(kw2["eps"],
                              kw2.get("rule", "trapezoid"), theta))
        groups.setdefault(k, []).append((theta, bounds))
    ser_phases = 0
    ser_lat: List[int] = []
    for keystr in sorted(groups):
        key = EngineKey.parse(keystr)
        eng = StreamEngine(HETERO_FAMILY, key.eps,
                           slots=HETERO_SLOTS, rule=Rule(key.rule),
                           theta_block=key.theta_block, **HETERO_EKW)
        r = eng.run(groups[keystr])
        for c in r.completed:
            ser_lat.append(ser_phases + int(c.retire_phase))
        ser_phases += int(r.phases)

    speedup = ser_phases / max(int(res.phases), 1)
    return {
        "metric": "heterogeneous dispatch proxies",
        "family": HETERO_FAMILY,
        "k_requests": HETERO_K,
        "max_engines": HETERO_MAX_ENGINES,
        "slots": HETERO_SLOTS,
        "engine_keys": keys,
        "n_engine_keys": len(keys),
        "recompiles": int(disp.recompiles())
                      + int(disp0.recompiles()),
        "completed": len(res.completed),
        "shed": len(res.shed),
        "accounting_ok": (len(res.completed) + len(res.shed)
                          == HETERO_K),
        "engines_reconcile": (
            per_engine_completed == len(res.completed)
            and per_engine_shed == len(res.shed)),
        "requests_per_sec": round(res.requests_per_sec, 3),
        "hetero_turns": int(res.phases),
        "serialized_phases_total": int(ser_phases),
        "turns_speedup_vs_serialized": round(speedup, 3),
        "mean_latency_turns": round(float(np.mean(lat)), 3),
        "p99_latency_turns": round(
            float(np.percentile(lat, 99)), 3),
        # round 22: the lease-OFF twin + the lease/overlap proxies
        "lease": True,
        "overlap_boundaries": True,
        "hetero_turns_nolease": int(res0.phases),
        "mean_latency_turns_nolease": round(
            float(np.mean(lat0)), 3),
        "p99_latency_turns_nolease": round(
            float(np.percentile(lat0, 99)), 3),
        "turns_speedup_vs_nolease": round(
            int(res0.phases) / max(int(res.phases), 1), 3),
        "lease_donated": int(leases["donated"]),
        "lease_received": int(leases["received"]),
        "lease_balanced": bool(leases["balanced"]),
        "boundaries_total": int(leases["boundaries"]),
        "boundaries_overlapped": int(leases["overlapped"]),
        "overlap_fraction": round(
            float(leases["overlap_fraction"]), 3),
        "overlap_wall_frac": round(
            float(leases["overlap_wall_frac"]), 3),
        "serialized_mean_latency_turns": round(
            float(np.mean(ser_lat)), 3),
        "serialized_p99_latency_turns": round(
            float(np.percentile(ser_lat, 99)), 3),
        "latency_beats_serialized": bool(
            float(np.mean(lat)) <= float(np.mean(ser_lat))),
        "per_engine": {
            k: {f: v[f] for f in ("state", "phases", "completed",
                                  "shed", "routed",
                                  "p99_latency_turns")}
            for k, v in summary.items()},
        "wall_s": round(res.wall_s, 3),
    }


def gate_dispatch_record(cur: dict, ref: dict) -> List[str]:
    """Round-21 heterogeneous-dispatch gate: zero recompiles on the
    mixed-shape stream (THE invariant), the completed+shed accounting
    and per-engine-sums-to-pool reconciliation, >= 3 distinct engine
    keys, the work-conserving schedule's turn-count speedup over the
    serialized baseline (> 1, within GATE_DISPATCH_SPEEDUP_TOL of the
    reference), and pool p99 retire latency within
    GATE_DISPATCH_P99_TOL of it. A reference WITHOUT a dispatch block
    skips the gate (pre-round-21 refs)."""
    rd = (ref or {}).get("dispatch")
    if not isinstance(rd, dict):
        return []
    cd = (cur or {}).get("dispatch")
    if not isinstance(cd, dict):
        # an offline --gate FILE record without the block; the CI
        # path uses --gate-run, which always re-measures
        return []
    fails: List[str] = []
    rc = cd.get("recompiles")
    if not isinstance(rc, int) or rc != 0:
        fails.append(
            f"REGRESSION dispatch: recompiles={rc!r} on the "
            f"mixed-shape stream (the zero-recompile routing "
            f"invariant broke — some engine re-traced its program)")
    if cd.get("accounting_ok") is False:
        fails.append("REGRESSION dispatch: completed + shed != "
                     "offered requests (lost or duplicated work "
                     "across the pool)")
    if cd.get("engines_reconcile") is False:
        fails.append("REGRESSION dispatch: per-engine completed/shed "
                     "counts do not sum to the pool ledger")
    nk = cd.get("n_engine_keys")
    if not isinstance(nk, int) or nk < 3:
        fails.append(
            f"REGRESSION dispatch: only {nk!r} distinct engine keys "
            f"driven (the acceptance floor is 3 — the workload "
            f"stopped being heterogeneous)")
    sp, sp_ref = cd.get("turns_speedup_vs_serialized"), rd.get(
        "turns_speedup_vs_serialized")
    if not isinstance(sp, (int, float)):
        fails.append("dispatch proxy missing "
                     "turns_speedup_vs_serialized")
    else:
        if sp <= 1.0:
            fails.append(
                f"REGRESSION dispatch: work-conserving schedule no "
                f"longer beats the serialized one-engine-at-a-time "
                f"baseline (turn speedup {sp:.2f}x <= 1)")
        if isinstance(sp_ref, (int, float)) \
                and sp < sp_ref * (1.0 - GATE_DISPATCH_SPEEDUP_TOL):
            fails.append(
                f"REGRESSION dispatch: turn speedup {sp:.2f}x "
                f"dropped >{GATE_DISPATCH_SPEEDUP_TOL:.0%} below the "
                f"reference's {sp_ref:.2f}x; re-record with "
                f"--update-ref if intended")
    if cd.get("latency_beats_serialized") is False:
        fails.append("REGRESSION dispatch: mean retire latency "
                     "(turns) no longer beats the serialized "
                     "baseline")
    p99, p99_ref = cd.get("p99_latency_turns"), rd.get(
        "p99_latency_turns")
    if isinstance(p99, (int, float)) \
            and isinstance(p99_ref, (int, float)) \
            and p99 > p99_ref * (1.0 + GATE_DISPATCH_P99_TOL):
        fails.append(
            f"REGRESSION dispatch: pool p99 retire latency "
            f"{p99:.1f} turns grew >{GATE_DISPATCH_P99_TOL:.0%} "
            f"over the reference's {p99_ref:.1f}")
    # round 22: lease/overlap proxies. Only gated once the committed
    # reference carries them (the documented --update-ref flow); a
    # ref WITH them and a current record WITHOUT them means the lease
    # measurement silently fell out of the bench — fail loudly.
    if "lease_balanced" not in rd:
        return fails
    if "lease_balanced" not in cd:
        fails.append(
            "REGRESSION dispatch: the committed reference carries "
            "lease/overlap proxies but the current record has none "
            "(the round-22 lease measurement fell out of the bench)")
        return fails
    turns = cd.get("hetero_turns")
    if isinstance(turns, int) and turns * GATE_DISPATCH_LEASE_SPEEDUP \
            > R21_DISPATCH_TURNS:
        fails.append(
            f"REGRESSION dispatch: leased drain took {turns} turns — "
            f"not >= {GATE_DISPATCH_LEASE_SPEEDUP:.1f}x under the "
            f"round-21 schedule's {R21_DISPATCH_TURNS} (slot-credit "
            f"leasing stopped paying for itself)")
    ml = cd.get("mean_latency_turns")
    if isinstance(ml, (int, float)) \
            and ml * GATE_DISPATCH_LEASE_SPEEDUP \
            > R21_DISPATCH_MEAN_LAT + 1e-9:
        fails.append(
            f"REGRESSION dispatch: leased mean retire latency "
            f"{ml:.3f} turns — not >= "
            f"{GATE_DISPATCH_LEASE_SPEEDUP:.1f}x under the round-21 "
            f"schedule's {R21_DISPATCH_MEAN_LAT}")
    if cd.get("lease_balanced") is False:
        fails.append(
            "REGRESSION dispatch: lease ledger does not balance "
            "(donated credits != received credits — grants are "
            "being lost or double-counted)")
    ofr = cd.get("overlap_fraction")
    if not isinstance(ofr, (int, float)) or ofr <= 0.0:
        fails.append(
            f"REGRESSION dispatch: overlap_fraction={ofr!r} — no "
            f"phase boundary overlapped another engine's in-flight "
            f"cycle (the overlapped turn loop is not engaging)")
    return fails


def gate_multihost_record(cur: dict, ref: dict) -> List[str]:
    """Round-18 multi-host gate: the zero-lost-acks accounting and
    the per-request bit-identity invariants must hold, spillover must
    stay ENGAGED (share > 0, within +-GATE_SPILL_ABS_TOL of the
    reference), the host-loss recovery must have fired, and the
    redeal must finish inside the absolute wall budget. A reference
    WITHOUT a multihost block skips the gate (pre-round-18 refs)."""
    rm = (ref or {}).get("multihost")
    if not isinstance(rm, dict):
        return []
    cm = (cur or {}).get("multihost")
    if not isinstance(cm, dict):
        # an offline --gate FILE record without the block; the CI
        # path uses --gate-run, which always re-measures
        return []
    fails: List[str] = []
    if cm.get("accounting_ok") is False:
        fails.append("REGRESSION multihost: completed + shed != "
                     "offered requests (lost or duplicated work "
                     "across the host loss)")
    if cm.get("areas_bit_identical") is False:
        fails.append("REGRESSION multihost: per-request areas "
                     "diverged from the undisturbed run on the "
                     "dyadic workload (the redeal/spillover "
                     "determinism contract broke)")
    if not any(r.get("kind") == "host_loss"
               for r in cm.get("recoveries", [])):
        fails.append("REGRESSION multihost: the injected host loss "
                     "was not recovered through the host_loss arm")
    sf, sf_ref = cm.get("spillover_fraction"), rm.get(
        "spillover_fraction")
    if not isinstance(sf, (int, float)) or sf <= 0.0:
        fails.append("REGRESSION multihost: spillover did not "
                     "engage (share <= 0) under overload + host "
                     "loss")
    elif isinstance(sf_ref, (int, float)) \
            and abs(sf - sf_ref) > GATE_SPILL_ABS_TOL:
        fails.append(
            f"REGRESSION multihost: spillover_fraction {sf:.3f} "
            f"drifted >{GATE_SPILL_ABS_TOL} from the reference's "
            f"{sf_ref:.3f}; re-record with --update-ref if intended")
    rw = cm.get("redeal_wall_s")
    if not isinstance(rw, (int, float)) or rw < 0:
        fails.append("multihost proxy missing redeal_wall_s (no "
                     "redeal happened?)")
    elif rw > GATE_REDEAL_WALL_BUDGET_S:
        fails.append(
            f"REGRESSION multihost: redeal wall {rw:.2f}s over the "
            f"{GATE_REDEAL_WALL_BUDGET_S:.0f}s budget (the "
            f"surviving-host redeal is host-side bookkeeping — "
            f"seconds mean something regressed structurally)")
    return fails


def gate_stream_record(cur: dict, ref: dict) -> List[str]:
    """Round-16 multi-tenant SLO gate: the accounting invariant must
    hold, the shed fraction at offered load ~8 must stay within
    +-GATE_SHED_ABS_TOL (absolute) of the committed reference, and no
    priority class's p99 retire latency (phases) may grow more than
    GATE_STREAM_P99_TOL over it. A reference WITHOUT a stream block
    skips the gate (pre-round-16 refs)."""
    rs = (ref or {}).get("stream")
    if not isinstance(rs, dict):
        return []
    cs = (cur or {}).get("stream")
    if not isinstance(cs, dict):
        # e.g. an offline --gate FILE record without the block; the CI
        # path uses --gate-run, which always re-measures
        return []
    fails: List[str] = []
    if cs.get("accounting_ok") is False:
        fails.append("REGRESSION stream: completed + shed != offered "
                     "requests (lost or duplicated work)")
    sf, sf_ref = cs.get("shed_fraction"), rs.get("shed_fraction")
    if not isinstance(sf, (int, float)):
        fails.append("stream proxy missing shed_fraction")
    elif isinstance(sf_ref, (int, float)) \
            and abs(sf - sf_ref) > GATE_SHED_ABS_TOL:
        fails.append(
            f"REGRESSION stream: shed_fraction {sf:.3f} drifted "
            f">{GATE_SHED_ABS_TOL} from the reference's "
            f"{sf_ref:.3f}; re-record with --update-ref if intended")
    cl, rl = cs.get("latency_by_class"), rs.get("latency_by_class")
    if isinstance(cl, dict) and isinstance(rl, dict):
        for klass, rrow in rl.items():
            crow = cl.get(klass)
            if not isinstance(crow, dict):
                fails.append(f"stream proxy: priority class {klass} "
                             f"vanished from latency_by_class")
                continue
            p99, p99_ref = crow.get("p99_phases"), rrow.get(
                "p99_phases")
            if isinstance(p99, (int, float)) \
                    and isinstance(p99_ref, (int, float)) \
                    and p99 > p99_ref * (1.0 + GATE_STREAM_P99_TOL):
                fails.append(
                    f"REGRESSION stream: class {klass} p99 "
                    f"{p99:.1f} phases grew "
                    f">{GATE_STREAM_P99_TOL:.0%} over the "
                    f"reference's {p99_ref:.1f}")
    return fails


def gate_theta_record(cur: dict, ref: dict) -> List[str]:
    """Round-13 theta-proxy gate: the T=256 bookkeeping-per-theta
    reduction must hold the acceptance floor (>= 4x) and stay within
    GATE_THETA_TOL of the committed reference; the reconciliation
    invariant (theta_overwalk included) must be green. Returns
    regression messages (empty = pass). A reference WITHOUT a theta
    block skips the gate (pre-round-13 refs)."""
    rt = (ref or {}).get("theta")
    if not isinstance(rt, dict):
        return []
    ct = (cur or {}).get("theta")
    if not isinstance(ct, dict):
        # a quick-proxy record without a theta block (bench.py quick
        # output fed to --gate FILE) simply skips the theta gate; the
        # CI path uses --gate-run, which always re-measures theta
        return []
    fails: List[str] = []
    for key in ("256",):
        c, rv = ct.get(key), rt.get(key)
        if not isinstance(c, dict) or not isinstance(rv, dict):
            fails.append(f"theta proxy T={key} missing")
            continue
        red, red_ref = c.get("reduction_vs_t1"), rv.get(
            "reduction_vs_t1")
        if not isinstance(red, (int, float)):
            fails.append(f"theta T={key}: missing reduction_vs_t1")
            continue
        if red < GATE_THETA_MIN_REDUCTION:
            fails.append(
                f"REGRESSION theta T={key}: reduction_vs_t1 "
                f"{red:.2f}x below the {GATE_THETA_MIN_REDUCTION}x "
                f"acceptance floor")
        if isinstance(red_ref, (int, float)) \
                and red < red_ref * (1.0 - GATE_THETA_TOL):
            fails.append(
                f"REGRESSION theta T={key}: reduction_vs_t1 "
                f"{red:.2f}x dropped >{GATE_THETA_TOL:.0%} below the "
                f"reference's {red_ref:.2f}x; re-record with "
                f"--update-ref if intended")
        if c.get("reconciles") is False:
            fails.append(f"theta T={key}: lane-waste attribution "
                         f"(theta_overwalk included) does not "
                         f"reconcile")
        if c.get("quality_vs_solo_ok") is False:
            fails.append(f"theta T={key}: per-theta quality fell "
                         f"below the solo sweep + eps bound")
    return fails


def gate_record(cur: dict, ref: dict,
                tolerance: float = GATE_STEP_TOL,
                eff_tolerance: float = GATE_EFF_TOL) -> List[str]:
    """Compare a quick-proxy record against the reference; returns the
    list of regression messages (empty = gate passes)."""
    fails: List[str] = []
    cw, rw = cur.get("walker") or {}, ref.get("walker") or {}
    if not cw or not rw:
        return ["record/reference missing the 'walker' proxy block"]

    def _num(d, k):
        v = d.get(k)
        return float(v) if isinstance(v, (int, float)) \
            and not isinstance(v, bool) else None

    ct, rt = _num(cw, "tasks"), _num(rw, "tasks")
    if not ct or not rt:
        return ["record/reference missing device-counted 'tasks'"]
    if abs(ct / rt - 1.0) > GATE_TASK_TOL:
        fails.append(
            f"workload drifted: tasks {int(ct)} vs reference "
            f"{int(rt)} (>{GATE_TASK_TOL:.0%}); re-record the "
            f"reference (--update-ref) if the change is intended")
        return fails
    for key in ("kernel_steps", "boundaries_rounds_plus_segs"):
        c, rv = _num(cw, key), _num(rw, key)
        if c is None or rv is None:
            fails.append(f"missing proxy {key!r}")
        elif c > rv * (1.0 + tolerance):
            fails.append(
                f"REGRESSION {key}: {int(c)} vs reference {int(rv)} "
                f"(> {1.0 + tolerance:.2f}x)")
    ce, re_ = _num(cw, "lane_efficiency"), _num(rw, "lane_efficiency")
    if ce is None or re_ is None:
        fails.append("missing proxy 'lane_efficiency'")
    else:
        # ONE binding bound: the round-12 FLOOR (drop > 10% below the
        # committed scout-mode reference trips — the tentpole's
        # standing guarantee) tightened further by --eff-tolerance
        # when the caller passes something stricter. The old separate
        # 15% relative check was fully subsumed by the floor.
        tol = min(eff_tolerance, GATE_EFF_FLOOR_TOL)
        floor = re_ * (1.0 - tol)
        if ce < floor:
            fails.append(
                f"REGRESSION lane_efficiency: {ce:.4f} below the "
                f"{floor:.4f} floor ({re_:.4f} reference - {tol:.0%}; "
                f"round-12 floor {GATE_EFF_FLOOR_TOL:.0%}, "
                f"--eff-tolerance {eff_tolerance:.0%})")
    # round-12 scout-rot guard: a reference recorded in scout mode
    # demands a scout-mode measurement — zero scout evals against a
    # scouting reference means the f32 path silently stopped running
    rs, cs = _num(rw, "scout_evals"), _num(cw, "scout_evals")
    if rs and not cs:
        fails.append(
            "scout path rotted: reference counts scout_evals but the "
            "fresh run reports none (scouting silently off?)")
    attr = cw.get("attribution")
    if isinstance(attr, dict) and attr.get("reconciles") is False:
        fails.append("lane-waste attribution does not reconcile "
                     "(buckets != lanes x kernel steps)")
    return fails


def gate_tuning_record(table) -> List[str]:
    """Round-20 tuned-vs-default floor over the COMMITTED tuning table
    (tools/tuning_table.json, written by ``bench.py tune``): at least
    two workload families must carry entries whose tuned configuration
    Pareto-beats the hand default on the quick device-counted proxies
    (lane_efficiency no worse AND kernel_steps no worse, one strictly
    better — the same ``tune.pareto_improves`` definition the sweep's
    acceptance uses), every entry's attribution must reconcile, and
    every committed cadence value must sit inside the declared safe
    bands (a committed table that the resolution tier would discard
    as insane is a broken commit, not a tuning choice). Returns []
    when no table is committed (pre-round-20 refs)."""
    if table is None:
        return []
    from ppls_tpu.runtime.tune import (CADENCE_SAFE_BANDS,
                                       pareto_improves)
    entries = table.get("entries") if isinstance(table, dict) else None
    if not isinstance(entries, dict) or not entries:
        return ["tuning table committed but carries no entries"]
    fails: List[str] = []
    improved_families = set()
    for key in sorted(entries):
        e = entries[key]
        base = e.get("baseline") or {}
        tuned = e.get("tuned") or {}
        prov = e.get("provenance") or {}
        knobs = e.get("knobs") or {}
        fam = (e.get("signature") or {}).get("family", key)
        for blk, name in ((base, "baseline"), (tuned, "tuned")):
            for k in ("tasks", "kernel_steps", "lane_efficiency"):
                v = blk.get(k)
                if not isinstance(v, (int, float)) \
                        or isinstance(v, bool) or v < 0:
                    fails.append(f"tuning {key}: {name}.{k} missing "
                                 f"or non-numeric")
        if prov.get("reconciles") is not True:
            fails.append(f"tuning {key}: lane-waste attribution did "
                         f"not reconcile during the sweep")
        if int(prov.get("trials", 0)) < 1:
            fails.append(f"tuning {key}: no trials recorded")
        for k, (lo, hi) in CADENCE_SAFE_BANDS.items():
            v = knobs.get(k)
            if not isinstance(v, (int, float)) or isinstance(v, bool) \
                    or not lo <= v <= hi:
                fails.append(f"tuning {key}: knob {k}={v!r} outside "
                             f"the safe band [{lo}, {hi}]")
        if isinstance(knobs.get("exit_frac"), float) \
                and isinstance(knobs.get("suspend_frac"), float) \
                and knobs["suspend_frac"] >= knobs["exit_frac"]:
            fails.append(f"tuning {key}: suspend_frac >= exit_frac")
        cand = dict(tuned, reconciles=prov.get("reconciles") is True)
        try:
            beats = pareto_improves(cand, base)
        except (KeyError, TypeError, ValueError):
            beats = False
        if bool(prov.get("improved")) != beats:
            fails.append(
                f"tuning {key}: provenance says improved="
                f"{prov.get('improved')} but the recorded proxies say "
                f"{beats} — stale or hand-edited entry")
        if beats:
            improved_families.add(fam)
    if len(improved_families) < 2:
        fails.append(
            f"tuning table: tuned beats the hand default on only "
            f"{len(improved_families)} famil"
            f"{'y' if len(improved_families) == 1 else 'ies'} "
            f"({sorted(improved_families)}); the round-20 floor is 2 "
            f"— re-run `python bench.py tune` and commit the table")
    return fails


def load_tuning_table_for_gate():
    """The committed tuning table for ``--gate-run`` (None when no
    table is committed — the gate skips, pre-round-20 pattern)."""
    from ppls_tpu.runtime.tune import DEFAULT_TABLE_PATH
    try:
        with open(DEFAULT_TABLE_PATH, encoding="utf-8") as fh:
            return json.load(fh)
    except (OSError, json.JSONDecodeError):
        return None


def main(argv: List[str]) -> int:
    args = list(argv[1:])

    def flag_value(name, default=None):
        if name in args:
            i = args.index(name)
            if i + 1 >= len(args):
                print(f"bench_history: {name} requires a value",
                      file=sys.stderr)
                raise SystemExit(2)
            v = args[i + 1]
            del args[i:i + 2]
            return v
        return default

    tolerance = float(flag_value("--tolerance", GATE_STEP_TOL))
    eff_tol = float(flag_value("--eff-tolerance", GATE_EFF_TOL))
    ref_path = flag_value("--ref", DEFAULT_REF)
    gate_path = flag_value("--gate")
    do_check = "--check" in args
    if do_check:
        args.remove("--check")
    do_gate_run = "--gate-run" in args
    if do_gate_run:
        args.remove("--gate-run")
    do_update = "--update-ref" in args
    if do_update:
        args.remove("--update-ref")
    paths = [a for a in args if not a.startswith("-")]

    if do_update:
        rec = run_quick_proxies()
        th = run_theta_proxies()
        rec["theta"] = th["theta"]
        rec["theta_meta"] = {k: th[k] for k in (
            "family", "eps", "bounds", "lanes",
            "t1_bookkeeping_per_theta", "t1_solo_samples",
            "solo_max_abs_err")}
        rec["stream"] = run_stream_slo_proxies()
        rec["multihost"] = run_multihost_proxies()
        rec["dispatch"] = run_hetero_dispatch_proxies()
        with open(ref_path, "w", encoding="utf-8") as fh:
            json.dump(rec, fh, indent=1, sort_keys=True)
            fh.write("\n")
        print(f"bench_history: reference recorded -> {ref_path}")
        print(json.dumps(rec["walker"]))
        print(json.dumps(rec["theta"]))
        print(json.dumps(rec["stream"]))
        print(json.dumps(rec["multihost"]))
        print(json.dumps(rec["dispatch"]))
        return 0

    if gate_path or do_gate_run:
        try:
            with open(ref_path, encoding="utf-8") as fh:
                ref = json.load(fh)
        except (OSError, json.JSONDecodeError) as e:
            print(f"bench_history: cannot read reference "
                  f"{ref_path}: {e}", file=sys.stderr)
            return 1
        if gate_path:
            with open(gate_path, encoding="utf-8") as fh:
                cur = json.load(fh)
        else:
            cur = run_quick_proxies()
            if isinstance(ref.get("theta"), dict):
                # round 13: the committed ref carries the theta proxy
                # — re-measure it so the amortization claim is gated
                th = run_theta_proxies()
                cur["theta"] = th["theta"]
            if isinstance(ref.get("stream"), dict):
                # round 16: the ref carries the multi-tenant SLO
                # proxies — re-measure so the overload numbers are
                # regression-guarded like lane efficiency
                cur["stream"] = run_stream_slo_proxies()
            if isinstance(ref.get("multihost"), dict):
                # round 18: the ref carries the multi-host proxies —
                # re-measure so the redeal/spillover/zero-lost-acks
                # invariants stay regression-guarded
                cur["multihost"] = run_multihost_proxies()
            if isinstance(ref.get("dispatch"), dict):
                # round 21: the ref carries the heterogeneous-
                # dispatch proxies — re-measure so the zero-recompile
                # and work-conserving-beats-serialized invariants
                # stay regression-guarded
                cur["dispatch"] = run_hetero_dispatch_proxies()
        fails = gate_record(cur, ref, tolerance=tolerance,
                            eff_tolerance=eff_tol) \
            + gate_theta_record(cur, ref) \
            + gate_stream_record(cur, ref) \
            + gate_multihost_record(cur, ref) \
            + gate_dispatch_record(cur, ref) \
            + gate_tuning_record(load_tuning_table_for_gate())
        for msg in fails:
            print(f"bench_history: GATE {msg}", file=sys.stderr)
        verdict = "TRIPPED" if fails else "passed"
        print(f"bench_history: quick-proxy regression gate {verdict} "
              f"({len(fails)} finding(s); tolerance {tolerance}, "
              f"eff {eff_tol})")
        return 1 if fails else 0

    traj = load_trajectory(paths or None)
    if do_check:
        problems = check_trajectory(traj)
        for msg in problems:
            print(f"bench_history: {msg}", file=sys.stderr)
        for g in gaps(traj):
            print(f"bench_history: gap: {g}")
        n = len([r for r in traj["rounds"] if r["kind"] == "bench"])
        print(f"bench_history: {n} bench round(s), "
              f"{len(problems)} problem(s), {len(gaps(traj))} gap(s)")
        return 1 if problems else 0
    print(json.dumps(traj, indent=1, sort_keys=True))
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
