"""VERDICT r4 #3: measure the demand-driven walker's overhead at mesh=1
on the real chip vs the single-chip walker, same flagship workload, and
compare shipped vs flagship-matched dd sizing.

The dd engine's collective breed costs all_gather/psum traffic plus
lockstep breed rounds; at mesh=1 those collectives are degenerate, so
this bounds the ENGINE-STRUCTURE overhead (collective-breed code path,
per-leg host sync) separately from real ICI costs (unmeasurable on a
1-chip rig).

Run on the real TPU: ``python tools/characterize_dd.py``
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from ppls_tpu.utils.compile_cache import enable_compile_cache
enable_compile_cache()

import jax
import jax.numpy as jnp
import numpy as np

M = 1024
EPS = 1e-10
BOUNDS = (1e-4, 1.0)


def median_wall(fn, n=3):
    walls = []
    for _ in range(n):
        t0 = time.perf_counter()
        r = fn()
        walls.append(time.perf_counter() - t0)
    return float(np.median(walls)), r


def main():
    from ppls_tpu.models.integrands import get_family, get_family_ds
    from ppls_tpu.parallel.mesh import make_mesh
    from ppls_tpu.parallel.sharded_walker import integrate_family_walker_dd
    from ppls_tpu.parallel.walker import integrate_family_walker

    theta = 1.0 + np.arange(M) / M
    f, fds = get_family("sin_recip_scaled"), get_family_ds(
        "sin_recip_scaled")
    mesh1 = make_mesh(1)

    # RTT estimate to subtract from solo walls
    jax.device_get(jnp.zeros(8))
    rtts = []
    for _ in range(5):
        t0 = time.perf_counter()
        jax.device_get(jnp.zeros(8) + 1.0)
        rtts.append(time.perf_counter() - t0)
    rtt = float(np.median(rtts))
    print(f"RTT ~{rtt*1e3:.0f} ms", flush=True)

    def run_single():
        return integrate_family_walker(f, fds, theta, BOUNDS, EPS,
                                       capacity=1 << 23)

    def run_dd_matched():
        return integrate_family_walker_dd(
            "sin_recip_scaled", theta, BOUNDS, EPS,
            chunk=1 << 15, capacity=1 << 22, lanes=1 << 14,
            roots_per_lane=12, mesh=mesh1)

    def run_dd_shipped():
        return integrate_family_walker_dd(
            "sin_recip_scaled", theta, BOUNDS, EPS,
            capacity=1 << 22, mesh=mesh1)   # shipped lanes=2^12 etc.

    rows = []
    for name, fn in (("single-chip walker", run_single),
                     ("dd mesh=1 matched (lanes=2^14)", run_dd_matched),
                     ("dd mesh=1 shipped (lanes=2^12)", run_dd_shipped)):
        t0 = time.perf_counter()
        r = fn()                      # compile + first run
        print(f"{name}: compile+run {time.perf_counter()-t0:.0f}s",
              flush=True)
        wall, r = median_wall(fn, 3)
        net = max(wall - rtt, 1e-9)
        rate = r.metrics.tasks / net
        rows.append((name, r.metrics.tasks, wall, rate,
                     r.walker_fraction, r.lane_efficiency))
        print(f"{name}: median wall {wall:.3f}s (-RTT {net:.3f}s) "
              f"-> {rate/1e6:.0f} M subint/s, tasks={r.metrics.tasks}, "
              f"wfrac={r.walker_fraction:.3f}, "
              f"laneeff={r.lane_efficiency:.3f}", flush=True)

    base = rows[0][3]
    print("\nsummary (rate vs single-chip):")
    for name, tasks, wall, rate, wf, le in rows:
        print(f"  {name}: {rate/1e6:7.0f} M/s  ({rate/base*100:5.1f}%)")


if __name__ == "__main__":
    main()
