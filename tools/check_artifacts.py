#!/usr/bin/env python
"""Validate the round artifact JSONs (BENCH_r*.json, MULTICHIP_r*.json)
against the bench record envelope (ppls_tpu.utils.artifact_schema), so
malformed blocks fail CI loudly instead of silently dropping from the
round-over-round trajectory.

Usage:
    python tools/check_artifacts.py [FILE ...]   # default: repo-root
                                                 # BENCH_r*/MULTICHIP_r*
    some-bench | python tools/check_artifacts.py -   # validate stdin
"""

from __future__ import annotations

import glob
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from ppls_tpu.utils.artifact_schema import validate_artifact_text  # noqa: E402


def main(argv) -> int:
    paths = argv[1:]
    if not paths:
        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        paths = sorted(glob.glob(os.path.join(root, "BENCH_r*.json"))
                       + glob.glob(os.path.join(root,
                                                "MULTICHIP_r*.json")))
        if not paths:
            print("check_artifacts: no artifact files found", flush=True)
            return 0
    problems = []
    for p in paths:
        if p == "-":
            problems += validate_artifact_text(sys.stdin.read(),
                                               where="<stdin>")
            continue
        base = os.path.basename(p)
        with open(p) as fh:
            # the MULTICHIP dryrun log legitimately carries no bench
            # records (DD_OCCUPANCY blocks are not metric records)
            problems += validate_artifact_text(
                fh.read(), where=base,
                require_records=base.startswith("BENCH"))
    for msg in problems:
        print(f"check_artifacts: {msg}", file=sys.stderr)
    print(f"check_artifacts: {len(paths)} file(s), "
          f"{len(problems)} problem(s)")
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
