#!/usr/bin/env python
"""Validate the round artifact JSONs (BENCH_r*.json, MULTICHIP_r*.json)
against the bench record envelope (ppls_tpu.utils.artifact_schema), so
malformed blocks fail CI loudly instead of silently dropping from the
round-over-round trajectory.

Round 10: also validates telemetry event logs (the second artifact
document type — ``ppls-tpu serve --events`` span timelines) via
``--events FILE``; CI runs a short seeded synthetic serve and gates
its timeline through this path.

Usage:
    python tools/check_artifacts.py [FILE ...]   # default: repo-root
                                                 # BENCH_r*/MULTICHIP_r*
    some-bench | python tools/check_artifacts.py -   # validate stdin
    python tools/check_artifacts.py --events EVENTS.jsonl [...]
        # validate event logs (--unbalanced-ok tolerates the unclosed
        # spans a killed run leaves behind; --rid-linkage additionally
        # enforces the round-19 request-trace contract — every
        # rid-bearing trace event linked to an open request span,
        # terminal events closing their span, zero orphans)
    python tools/check_artifacts.py --serve SERVE_STDOUT.jsonl [...]
        # round 16: validate a serve stdout ledger — every line a
        # retire/shed/rejection/summary record, with the rid-deduped
        # accounting invariants (completed/shed/failed counts match
        # the summary, no rid both retired and shed)
    python tools/check_artifacts.py --graftlint LINT.json [...]
        # round 17: validate a `python -m tools.graftlint --format
        # json` ledger (one record per violation, counts reconciled,
        # grandfathered records carry reasons) — the machine-readable
        # lint output ci.sh's deep-lint step emits for annotations
    python tools/check_artifacts.py --tuning TABLE.json [...]
        # round 20: validate a `bench.py tune` tuning table (entry
        # keys round-trip from their signatures; knobs, baseline/
        # tuned proxies, and sweep provenance all present) — the
        # performance floor itself lives in bench_history's
        # gate_tuning_record
"""

from __future__ import annotations

import glob
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from ppls_tpu.utils.artifact_schema import (  # noqa: E402
    validate_artifact_text,
    validate_events_text,
    validate_graftlint_text,
    validate_serve_output_text,
    validate_tuning_table_text,
)


def main(argv) -> int:
    args = list(argv[1:])
    balanced = True
    if "--unbalanced-ok" in args:
        args.remove("--unbalanced-ok")
        balanced = False
    # round 19: --rid-linkage arms the request-trace contract on
    # --events files (every rid-bearing trace event links to an open
    # request span; terminal events close their span — zero orphans)
    rid_linkage = False
    if "--rid-linkage" in args:
        args.remove("--rid-linkage")
        rid_linkage = True
    event_paths = []
    while "--events" in args:
        i = args.index("--events")
        if i + 1 >= len(args):
            print("check_artifacts: --events requires a FILE",
                  file=sys.stderr)
            return 2
        event_paths.append(args[i + 1])
        del args[i:i + 2]
    serve_paths = []
    while "--serve" in args:
        i = args.index("--serve")
        if i + 1 >= len(args):
            print("check_artifacts: --serve requires a FILE",
                  file=sys.stderr)
            return 2
        serve_paths.append(args[i + 1])
        del args[i:i + 2]
    lint_paths = []
    while "--graftlint" in args:
        i = args.index("--graftlint")
        if i + 1 >= len(args):
            print("check_artifacts: --graftlint requires a FILE",
                  file=sys.stderr)
            return 2
        lint_paths.append(args[i + 1])
        del args[i:i + 2]
    # round 20: tuning tables (bench.py tune) — signature/provenance
    # shape checks; the performance floor lives in bench_history's
    # gate_tuning_record
    tuning_paths = []
    while "--tuning" in args:
        i = args.index("--tuning")
        if i + 1 >= len(args):
            print("check_artifacts: --tuning requires a FILE",
                  file=sys.stderr)
            return 2
        tuning_paths.append(args[i + 1])
        del args[i:i + 2]
    paths = args
    problems = []
    for p in event_paths:
        with open(p) as fh:
            problems += validate_events_text(
                fh.read(), where=os.path.basename(p),
                require_balanced=balanced,
                check_rid_linkage=rid_linkage)
    # round 16: serve stdout ledgers (retire/shed/rejection/summary
    # accounting invariants) — the chaos-under-load CI step's third
    # artifact document type
    for p in serve_paths:
        with open(p) as fh:
            problems += validate_serve_output_text(
                fh.read(), where=os.path.basename(p))
    # round 17: graftlint --format json ledgers (deep-lint CI step)
    for p in lint_paths:
        with open(p) as fh:
            problems += validate_graftlint_text(
                fh.read(), where=os.path.basename(p))
    for p in tuning_paths:
        with open(p) as fh:
            problems += validate_tuning_table_text(
                fh.read(), where=os.path.basename(p))
    event_paths = event_paths + serve_paths + lint_paths + tuning_paths
    if event_paths and not paths:
        for msg in problems:
            print(f"check_artifacts: {msg}", file=sys.stderr)
        print(f"check_artifacts: {len(event_paths)} event log(s), "
              f"{len(problems)} problem(s)")
        return 1 if problems else 0
    if not paths:
        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        paths = sorted(glob.glob(os.path.join(root, "BENCH_r*.json"))
                       + glob.glob(os.path.join(root,
                                                "MULTICHIP_r*.json")))
        if not paths:
            print("check_artifacts: no artifact files found", flush=True)
            return 0
    for p in paths:
        if p == "-":
            problems += validate_artifact_text(sys.stdin.read(),
                                               where="<stdin>")
            continue
        base = os.path.basename(p)
        with open(p) as fh:
            # the MULTICHIP dryrun log legitimately carries no bench
            # records (DD_OCCUPANCY blocks are not metric records)
            problems += validate_artifact_text(
                fh.read(), where=base,
                require_records=base.startswith("BENCH"))
    for msg in problems:
        print(f"check_artifacts: {msg}", file=sys.stderr)
    print(f"check_artifacts: {len(paths) + len(event_paths)} file(s), "
          f"{len(problems)} problem(s)")
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
